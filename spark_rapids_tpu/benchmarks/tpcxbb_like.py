"""TPCxBB-like benchmark: clickstream + multi-channel retail schema and
the machine-generated-analytics query shapes of the reference's
TpcxbbLikeSpark (integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala,
tpcxbb_test.py) — the reference's second query family next to TPC-DS.

Queries follow the reference's *supported* subset (its own q1-q4/q8 etc.
throw UnsupportedOperationException for UDTF/python): the ML feature
build (q5), premium-item geography (q7), multi-dimension filter sum
(q9), before/after price-change pivot (q16), promotion ratio (q17),
return-segmentation ratios (q20), cross-channel re-purchase (q21) and
inventory stability (q22).  Adapted to the engine dialect: explicit
JOINs, LEFT SEMI JOIN instead of IN-subqueries, date_dim surrogate-key
windows instead of unix_timestamp string math, and post-aggregate
arithmetic expressed through nested subqueries.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from spark_rapids_tpu import types as T

CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
              "Shoes", "Sports", "Toys"]
STATES = ["CA", "GA", "IL", "NY", "TX", "WA", None]
EDU = ["Advanced Degree", "College", "4 yr Degree", "2 yr Degree",
       "Secondary", "Primary"]


def _n(sf: float, base: int, floor: int = 20) -> int:
    return max(floor, int(sf * base))


def gen_item(sf: float, seed: int = 41) -> Dict:
    n = _n(sf, 2_000)
    r = np.random.RandomState(seed)
    return {
        "i_item_sk": (T.LONG, np.arange(1, n + 1)),
        "i_item_id": (T.STRING,
                      np.array([f"ITEM{i:06d}" for i in range(1, n + 1)],
                               dtype=object)),
        "i_item_desc": (T.STRING,
                        np.array([f"desc {i % 97}" for i in range(n)],
                                 dtype=object)),
        "i_category": (T.STRING, r.choice(CATEGORIES, n)),
        "i_category_id": (T.INT,
                          r.randint(1, 9, n).astype(np.int32)),
        "i_current_price": (T.DOUBLE, (r.rand(n) * 99 + 1).round(2)),
    }


def gen_customer(sf: float, seed: int = 42) -> Dict:
    n = _n(sf, 1_000)
    r = np.random.RandomState(seed)
    return {
        "c_customer_sk": (T.LONG, np.arange(1, n + 1)),
        "c_current_cdemo_sk": (T.LONG, r.randint(1, 101, n)),
        "c_current_addr_sk": (T.LONG, r.randint(1, 201, n)),
    }


def gen_customer_demographics(seed: int = 43) -> Dict:
    n = 100
    r = np.random.RandomState(seed)
    return {
        "cd_demo_sk": (T.LONG, np.arange(1, n + 1)),
        "cd_gender": (T.STRING, r.choice(["M", "F"], n)),
        "cd_education_status": (T.STRING, r.choice(EDU, n)),
    }


def gen_customer_address(seed: int = 44) -> Dict:
    n = 200
    r = np.random.RandomState(seed)
    state = r.choice(np.array(STATES, dtype=object), n)
    return {
        "ca_address_sk": (T.LONG, np.arange(1, n + 1)),
        "ca_state": (T.STRING, state),
        "ca_gmt_offset": (T.INT,
                          r.choice([-8, -6, -5], n).astype(np.int32)),
    }


def gen_store(seed: int = 45) -> Dict:
    n = 12
    r = np.random.RandomState(seed)
    return {
        "s_store_sk": (T.LONG, np.arange(1, n + 1)),
        "s_store_id": (T.STRING,
                       np.array([f"S{i:03d}" for i in range(1, n + 1)],
                                dtype=object)),
        "s_store_name": (T.STRING,
                         np.array([f"store {i}" for i in range(1, n + 1)],
                                  dtype=object)),
        "s_gmt_offset": (T.INT, r.choice([-8, -5], n).astype(np.int32)),
    }


def gen_warehouse(seed: int = 46) -> Dict:
    n = 6
    r = np.random.RandomState(seed)
    return {
        "w_warehouse_sk": (T.LONG, np.arange(1, n + 1)),
        "w_state": (T.STRING,
                    r.choice([s for s in STATES if s], n)),
    }


def gen_date_dim() -> Dict:
    n = 730
    sk = np.arange(1, n + 1)
    year = np.where(sk <= 365, 2001, 2004)
    doy = np.where(sk <= 365, sk, sk - 365)
    return {
        "d_date_sk": (T.LONG, sk),
        "d_year": (T.INT, year.astype(np.int32)),
        "d_moy": (T.INT,
                  np.minimum((doy - 1) // 30 + 1, 12).astype(np.int32)),
    }


def gen_promotion(seed: int = 47) -> Dict:
    n = 30
    r = np.random.RandomState(seed)
    return {
        "p_promo_sk": (T.LONG, np.arange(1, n + 1)),
        "p_channel_email": (T.STRING, r.choice(["Y", "N"], n)),
        "p_channel_dmail": (T.STRING, r.choice(["Y", "N"], n)),
        "p_channel_tv": (T.STRING, r.choice(["Y", "N"], n)),
    }


def gen_web_clickstreams(sf: float, seed: int = 48) -> Dict:
    n = _n(sf, 100_000, floor=200)
    r = np.random.RandomState(seed)
    n_item, n_cust = _n(sf, 2_000), _n(sf, 1_000)
    user = r.randint(1, n_cust + 1, n)
    null_mask = r.rand(n) < 0.1  # anonymous clicks -> NULL user
    users = [None if m else int(u) for u, m in zip(user, null_mask)]
    return {
        "wcs_user_sk": (T.LONG, users),
        "wcs_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
    }


def gen_store_sales(sf: float, seed: int = 49) -> Dict:
    n = _n(sf, 100_000, floor=200)
    r = np.random.RandomState(seed)
    n_item, n_cust = _n(sf, 2_000), _n(sf, 1_000)
    qty = r.randint(1, 101, n)
    price = (r.rand(n) * 200 + 1).round(2)
    return {
        "ss_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "ss_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "ss_customer_sk": (T.LONG, r.randint(1, n_cust + 1, n)),
        "ss_cdemo_sk": (T.LONG, r.randint(1, 101, n)),
        "ss_addr_sk": (T.LONG, r.randint(1, 201, n)),
        "ss_store_sk": (T.LONG, r.randint(1, 13, n)),
        "ss_promo_sk": (T.LONG, r.randint(1, 31, n)),
        "ss_ticket_number": (T.LONG, r.randint(1, n // 3 + 2, n)),
        "ss_quantity": (T.INT, qty.astype(np.int32)),
        "ss_net_paid": (T.DOUBLE, (price * qty).round(2)),
        "ss_ext_sales_price": (T.DOUBLE, (price * qty).round(2)),
    }


def gen_store_returns(sf: float, seed: int = 50) -> Dict:
    n = _n(sf, 10_000, floor=40)
    r = np.random.RandomState(seed)
    n_item, n_cust = _n(sf, 2_000), _n(sf, 1_000)
    return {
        "sr_returned_date_sk": (T.LONG, r.randint(1, 731, n)),
        "sr_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "sr_customer_sk": (T.LONG, r.randint(1, n_cust + 1, n)),
        "sr_ticket_number": (T.LONG, r.randint(1, n // 2 + 2, n)),
        "sr_return_quantity": (T.INT,
                               r.randint(1, 30, n).astype(np.int32)),
        "sr_return_amt": (T.DOUBLE, (r.rand(n) * 300).round(2)),
    }


def gen_web_sales(sf: float, seed: int = 51) -> Dict:
    n = _n(sf, 50_000, floor=100)
    r = np.random.RandomState(seed)
    n_item, n_cust = _n(sf, 2_000), _n(sf, 1_000)
    return {
        "ws_sold_date_sk": (T.LONG, r.randint(1, 731, n)),
        "ws_order_number": (T.LONG, r.randint(1, n // 2 + 2, n)),
        "ws_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "ws_warehouse_sk": (T.LONG, r.randint(1, 7, n)),
        "ws_bill_customer_sk": (T.LONG, r.randint(1, n_cust + 1, n)),
        "ws_quantity": (T.INT, r.randint(1, 50, n).astype(np.int32)),
        "ws_sales_price": (T.DOUBLE, (r.rand(n) * 150 + 1).round(2)),
    }


def gen_web_returns(sf: float, seed: int = 52) -> Dict:
    n = _n(sf, 5_000, floor=20)
    r = np.random.RandomState(seed)
    n_item = _n(sf, 2_000)
    return {
        "wr_returned_date_sk": (T.LONG, r.randint(1, 731, n)),
        "wr_order_number": (T.LONG, r.randint(1, n + 2, n)),
        "wr_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "wr_refunded_cash": (T.DOUBLE, (r.rand(n) * 100).round(2)),
    }


def gen_inventory(sf: float, seed: int = 53) -> Dict:
    n = _n(sf, 40_000, floor=100)
    r = np.random.RandomState(seed)
    n_item = _n(sf, 2_000)
    return {
        "inv_date_sk": (T.LONG, r.randint(1, 731, n)),
        "inv_item_sk": (T.LONG, r.randint(1, n_item + 1, n)),
        "inv_warehouse_sk": (T.LONG, r.randint(1, 7, n)),
        "inv_quantity_on_hand": (T.INT,
                                 r.randint(0, 500, n).astype(np.int32)),
    }


def register_tpcxbb(session, sf: float = 0.1, num_partitions: int = 3):
    tables = {
        "item": gen_item(sf),
        "customer": gen_customer(sf),
        "customer_demographics": gen_customer_demographics(),
        "customer_address": gen_customer_address(),
        "store": gen_store(),
        "warehouse": gen_warehouse(),
        "date_dim": gen_date_dim(),
        "promotion": gen_promotion(),
        "web_clickstreams": gen_web_clickstreams(sf),
        "store_sales": gen_store_sales(sf),
        "store_returns": gen_store_returns(sf),
        "web_sales": gen_web_sales(sf),
        "web_returns": gen_web_returns(sf),
        "inventory": gen_inventory(sf),
    }
    for name, data in tables.items():
        df = session.create_dataframe(data, num_partitions=num_partitions)
        session.register_view(name, df)


# -- queries (TpcxbbLikeSpark adaptation) ------------------------------------

Q5 = """
SELECT wcs_user_sk, clicks_in_category,
       CASE WHEN cd_education_status IN ('Advanced Degree', 'College',
                                         '4 yr Degree', '2 yr Degree')
            THEN 1 ELSE 0 END AS college_education,
       CASE WHEN cd_gender = 'M' THEN 1 ELSE 0 END AS male,
       clicks_in_1, clicks_in_2, clicks_in_3
FROM (
  SELECT wcs_user_sk,
         sum(CASE WHEN i_category = 'Books' THEN 1 ELSE 0 END)
           AS clicks_in_category,
         sum(CASE WHEN i_category_id = 1 THEN 1 ELSE 0 END) AS clicks_in_1,
         sum(CASE WHEN i_category_id = 2 THEN 1 ELSE 0 END) AS clicks_in_2,
         sum(CASE WHEN i_category_id = 3 THEN 1 ELSE 0 END) AS clicks_in_3
  FROM web_clickstreams
  JOIN item ON wcs_item_sk = i_item_sk AND wcs_user_sk IS NOT NULL
  GROUP BY wcs_user_sk
)
JOIN customer ON wcs_user_sk = c_customer_sk
JOIN customer_demographics ON c_current_cdemo_sk = cd_demo_sk
ORDER BY wcs_user_sk
"""

Q7 = """
SELECT ca_state, count(*) AS cnt
FROM store_sales
JOIN item ON ss_item_sk = i_item_sk
JOIN (
  SELECT i_category AS cat, avg(i_current_price) AS avg_price
  FROM item
  GROUP BY i_category
) ap ON i_category = cat
JOIN customer ON c_customer_sk = ss_customer_sk
JOIN customer_address ON ca_address_sk = c_current_addr_sk
LEFT SEMI JOIN (
  SELECT d_date_sk FROM date_dim WHERE d_year = 2004 AND d_moy = 7
) dd ON ss_sold_date_sk = d_date_sk
WHERE i_current_price > avg_price * 1.2 AND ca_state IS NOT NULL
GROUP BY ca_state
HAVING count(*) >= 2
ORDER BY cnt DESC, ca_state
LIMIT 10
"""

Q9 = """
SELECT sum(ss_quantity) AS total_quantity
FROM store_sales
JOIN date_dim ON ss_sold_date_sk = d_date_sk AND d_year = 2001
JOIN customer_demographics ON cd_demo_sk = ss_cdemo_sk
JOIN customer_address ON ca_address_sk = ss_addr_sk
WHERE ((cd_education_status = 'College'
          AND ss_quantity BETWEEN 1 AND 60)
    OR (cd_education_status = 'Advanced Degree'
          AND ss_quantity BETWEEN 40 AND 100))
  AND ((ca_state IN ('CA', 'TX') AND ss_net_paid BETWEEN 50 AND 12000)
    OR (ca_state IN ('NY', 'WA') AND ss_net_paid BETWEEN 150 AND 20000))
"""

Q16 = """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date_sk < 400
                THEN ws_sales_price - wr_cash ELSE 0.0 END)
         AS sales_before,
       sum(CASE WHEN d_date_sk >= 400
                THEN ws_sales_price - wr_cash ELSE 0.0 END)
         AS sales_after
FROM (
  SELECT ws_item_sk, ws_warehouse_sk, ws_sold_date_sk, ws_sales_price,
         coalesce(wr_refunded_cash, 0.0) AS wr_cash
  FROM web_sales
  LEFT JOIN web_returns ON ws_order_number = wr_order_number
    AND ws_item_sk = wr_item_sk
)
JOIN item ON ws_item_sk = i_item_sk
JOIN warehouse ON ws_warehouse_sk = w_warehouse_sk
JOIN date_dim ON ws_sold_date_sk = d_date_sk
  AND d_date_sk BETWEEN 370 AND 430
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
"""

Q17 = """
SELECT promotional, total,
       CASE WHEN total > 0 THEN 100.0 * promotional / total
            ELSE 0.0 END AS promo_percent
FROM (
  SELECT sum(promotional) AS promotional, sum(total) AS total
  FROM (
    SELECT CASE WHEN p_channel_dmail = 'Y' OR p_channel_email = 'Y'
                     OR p_channel_tv = 'Y'
                THEN sales ELSE 0.0 END AS promotional,
           sales AS total
    FROM (
      SELECT p_channel_email, p_channel_dmail, p_channel_tv,
             sum(ss_ext_sales_price) AS sales
      FROM store_sales
      LEFT SEMI JOIN (
        SELECT d_date_sk FROM date_dim WHERE d_year = 2001 AND d_moy = 12
      ) dd ON ss_sold_date_sk = d_date_sk
      LEFT SEMI JOIN (
        SELECT i_item_sk FROM item
        WHERE i_category IN ('Books', 'Music')
      ) it ON ss_item_sk = i_item_sk
      LEFT SEMI JOIN (
        SELECT s_store_sk FROM store WHERE s_gmt_offset = -5
      ) st ON ss_store_sk = s_store_sk
      JOIN promotion ON ss_promo_sk = p_promo_sk
      GROUP BY p_channel_email, p_channel_dmail, p_channel_tv
    )
  )
)
ORDER BY promotional, total
"""

# The reference wraps each ratio in round(x, 7); rounding to a fixed
# decimal place puts exact decimal-tie values one f64-emulation ULP from
# flipping, so the "like" adaptation compares the raw ratios instead
# (Round itself is covered by the expression suites).
Q20 = """
SELECT user_sk,
       CASE WHEN returns_count IS NULL OR orders_count IS NULL
            THEN 0.0
            ELSE returns_count / orders_count END AS orderratio,
       CASE WHEN returns_items IS NULL OR orders_items IS NULL
            THEN 0.0
            ELSE returns_items / orders_items END AS itemsratio,
       CASE WHEN returns_money IS NULL OR orders_money IS NULL
            THEN 0.0
            ELSE returns_money / orders_money END AS monetaryratio,
       round(CASE WHEN returns_count IS NULL THEN 0.0
                  ELSE returns_count END, 0) AS frequency
FROM (
  SELECT ss_customer_sk AS user_sk,
         orders_count, orders_items, orders_money,
         returns_count, returns_items, returns_money
  FROM (
    SELECT ss_customer_sk,
           count(DISTINCT ss_ticket_number) AS orders_count,
           count(ss_item_sk) AS orders_items,
           sum(ss_net_paid) AS orders_money
    FROM store_sales
    GROUP BY ss_customer_sk
  ) orders
  LEFT JOIN (
    SELECT sr_customer_sk,
           count(DISTINCT sr_ticket_number) AS returns_count,
           count(sr_item_sk) AS returns_items,
           sum(sr_return_amt) AS returns_money
    FROM store_returns
    GROUP BY sr_customer_sk
  ) returned ON ss_customer_sk = sr_customer_sk
)
ORDER BY user_sk
"""

Q21 = """
SELECT i_item_id, s_store_id,
       sum(ss_quantity) AS store_sales_quantity,
       sum(sr_return_quantity) AS store_returns_quantity
FROM store_sales
JOIN store_returns ON sr_customer_sk = ss_customer_sk
  AND sr_item_sk = ss_item_sk
  AND sr_returned_date_sk >= ss_sold_date_sk
JOIN item ON i_item_sk = ss_item_sk
JOIN store ON s_store_sk = ss_store_sk
LEFT SEMI JOIN (
  SELECT d_date_sk FROM date_dim WHERE d_year = 2001
) dd ON ss_sold_date_sk = d_date_sk
GROUP BY i_item_id, s_store_id
ORDER BY i_item_id, s_store_id
LIMIT 100
"""

Q22 = """
SELECT w_state, i_item_id, inv_before, inv_after
FROM (
  SELECT w_state, i_item_id,
         sum(CASE WHEN inv_date_sk < 400 THEN inv_quantity_on_hand
                  ELSE 0 END) AS inv_before,
         sum(CASE WHEN inv_date_sk >= 400 THEN inv_quantity_on_hand
                  ELSE 0 END) AS inv_after
  FROM inventory
  JOIN warehouse ON inv_warehouse_sk = w_warehouse_sk
  JOIN item ON inv_item_sk = i_item_sk
  WHERE i_current_price BETWEEN 10 AND 90
    AND inv_date_sk BETWEEN 370 AND 430
  GROUP BY w_state, i_item_id
)
WHERE inv_before > 0 AND inv_after >= inv_before * 0.666
  AND inv_after <= inv_before * 1.5
ORDER BY w_state, i_item_id
LIMIT 100
"""

QUERIES = {"q5": Q5, "q7": Q7, "q9": Q9, "q16": Q16, "q17": Q17,
           "q20": Q20, "q21": Q21, "q22": Q22}
