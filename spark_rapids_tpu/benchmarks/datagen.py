"""Synthetic TPC-H-shaped data generator (the data_gen.py / TpchLikeSpark
setup analogue).  Scale: rows = int(SF * base_rows); deterministic per seed.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from spark_rapids_tpu import types as T

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
FLAGS = ["A", "N", "R"]
STATUSES = ["F", "O", "P"]
MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
NATIONS = ["ALGERIA", "BRAZIL", "CANADA", "EGYPT", "FRANCE", "GERMANY",
           "INDIA", "JAPAN", "KENYA", "PERU", "CHINA", "ROMANIA"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
TYPES = [f"{a} {b} {c}"
         for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                   "PROMO")
         for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED",
                   "BRUSHED")
         for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
CONTAINERS = [f"{a} {b}"
              for a in ("SM", "MED", "LG", "JUMBO", "WRAP")
              for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK",
                        "CAN", "DRUM")]
PART_NOUNS = ["forest", "green", "lemon", "navy", "slate", "rose",
              "royal", "steel", "midnight", "linen"]
PHONE_CODES = ["13", "17", "18", "23", "29", "30", "31", "32", "33"]

_EPOCH_1992 = 8035   # days 1970->1992-01-01
_EPOCH_1999 = 10592  # days 1970->1998-12-31


def gen_lineitem(sf: float, seed: int = 11) -> Dict:
    n = max(1, int(sf * 60_000))
    r = np.random.RandomState(seed)
    qty = r.randint(1, 51, n)
    price = (r.rand(n) * 90000 + 900).round(2)
    disc = (r.randint(0, 11, n) / 100.0)
    tax = (r.randint(0, 9, n) / 100.0)
    return {
        "l_orderkey": (T.LONG, r.randint(1, int(sf * 15_000) + 2, n)),
        "l_partkey": (T.LONG, r.randint(1, int(sf * 2_000) + 2, n)),
        "l_suppkey": (T.LONG, r.randint(1, int(sf * 100) + 2, n)),
        "l_quantity": (T.DOUBLE, qty.astype(np.float64)),
        "l_extendedprice": (T.DOUBLE, price),
        "l_discount": (T.DOUBLE, disc),
        "l_tax": (T.DOUBLE, tax),
        "l_returnflag": (T.STRING, r.choice(FLAGS, n)),
        "l_linestatus": (T.STRING, r.choice(STATUSES, n)),
        "l_shipdate": (T.DATE,
                       r.randint(_EPOCH_1992, _EPOCH_1999, n)),
        "l_commitdate": (T.DATE,
                         r.randint(_EPOCH_1992, _EPOCH_1999, n)),
        "l_receiptdate": (T.DATE,
                          r.randint(_EPOCH_1992, _EPOCH_1999, n)),
        "l_shipmode": (T.STRING, r.choice(MODES, n)),
    }


def gen_orders(sf: float, seed: int = 12) -> Dict:
    n = max(1, int(sf * 15_000))
    r = np.random.RandomState(seed)
    return {
        "o_orderkey": (T.LONG, np.arange(1, n + 1)),
        "o_custkey": (T.LONG, r.randint(1, int(sf * 1_500) + 2, n)),
        "o_orderstatus": (T.STRING, r.choice(STATUSES, n)),
        "o_totalprice": (T.DOUBLE, (r.rand(n) * 500000).round(2)),
        "o_orderdate": (T.DATE, r.randint(_EPOCH_1992, _EPOCH_1999, n)),
        "o_orderpriority": (T.STRING, r.choice(PRIORITIES, n)),
        "o_shippriority": (T.INT, np.zeros(n, dtype=np.int32)),
    }


def gen_customer(sf: float, seed: int = 13) -> Dict:
    n = max(1, int(sf * 1_500))
    r = np.random.RandomState(seed)
    return {
        "c_custkey": (T.LONG, np.arange(1, n + 1)),
        "c_name": (T.STRING,
                   [f"Customer#{i:09d}" for i in range(1, n + 1)]),
        "c_nationkey": (T.INT, r.randint(0, len(NATIONS), n)),
        "c_mktsegment": (T.STRING, r.choice(SEGMENTS, n)),
        "c_acctbal": (T.DOUBLE, (r.rand(n) * 10000 - 1000).round(2)),
        "c_phone": (T.STRING, _gen_phones(r, n)),
    }


def _gen_phones(r, n):
    code = r.randint(0, len(PHONE_CODES), n)
    a, b, c = (r.randint(100, 999, n), r.randint(100, 999, n),
               r.randint(1000, 9999, n))
    return [f"{PHONE_CODES[code[i]]}-{a[i]}-{b[i]}-{c[i]}"
            for i in range(n)]


def gen_supplier(sf: float, seed: int = 14) -> Dict:
    n = max(1, int(sf * 100))
    r = np.random.RandomState(seed)
    return {
        "s_suppkey": (T.LONG, np.arange(1, n + 1)),
        "s_name": (T.STRING, [f"Supplier#{i:09d}" for i in range(1, n + 1)]),
        "s_nationkey": (T.INT, r.randint(0, len(NATIONS), n)),
        "s_acctbal": (T.DOUBLE, (r.rand(n) * 11000 - 1000).round(2)),
    }


def gen_part(sf: float, seed: int = 15) -> Dict:
    n = max(1, int(sf * 2_000))
    r = np.random.RandomState(seed)
    idx = r.randint(0, len(PART_NOUNS), (n, 3))
    names = [f"{PART_NOUNS[i]} {PART_NOUNS[j]} {PART_NOUNS[k]}"
             for i, j, k in idx]
    return {
        "p_partkey": (T.LONG, np.arange(1, n + 1)),
        "p_name": (T.STRING, names),
        "p_mfgr": (T.STRING,
                   [f"Manufacturer#{i % 5 + 1}" for i in range(n)]),
        "p_brand": (T.STRING, r.choice(BRANDS, n)),
        "p_type": (T.STRING, r.choice(TYPES, n)),
        "p_size": (T.INT, r.randint(1, 51, n).astype(np.int32)),
        "p_container": (T.STRING, r.choice(CONTAINERS, n)),
        "p_retailprice": (T.DOUBLE, (r.rand(n) * 2000 + 900).round(2)),
    }


def gen_partsupp(sf: float, seed: int = 16) -> Dict:
    n_part = max(1, int(sf * 2_000))
    n_supp = max(1, int(sf * 100))
    # 4 DISTINCT suppliers per part, the TPC-H shape ((partkey, suppkey)
    # is the table's primary key)
    pk = np.repeat(np.arange(1, n_part + 1), 4)
    offset = np.tile(np.arange(4), n_part)
    sk = (pk * 7 + offset * max(1, n_supp // 4)) % n_supp + 1
    r = np.random.RandomState(seed)
    n = len(pk)
    return {
        "ps_partkey": (T.LONG, pk),
        "ps_suppkey": (T.LONG, sk),
        "ps_availqty": (T.INT, r.randint(1, 10_000, n).astype(np.int32)),
        "ps_supplycost": (T.DOUBLE, (r.rand(n) * 1000 + 1).round(2)),
    }


def gen_region() -> Dict:
    n = len(REGIONS)
    return {
        "r_regionkey": (T.INT, np.arange(n, dtype=np.int32)),
        "r_name": (T.STRING, list(REGIONS)),
    }


def gen_nation() -> Dict:
    n = len(NATIONS)
    return {
        "n_nationkey": (T.INT, np.arange(n, dtype=np.int32)),
        "n_name": (T.STRING, list(NATIONS)),
        "n_regionkey": (T.INT, (np.arange(n) % 5).astype(np.int32)),
    }


def register_tpch(session, sf: float = 0.01, num_partitions: int = 4):
    """Create and register all TPC-H-like tables as temp views."""
    for name, data in [
        ("lineitem", gen_lineitem(sf)),
        ("orders", gen_orders(sf)),
        ("customer", gen_customer(sf)),
        ("supplier", gen_supplier(sf)),
        ("nation", gen_nation()),
        ("part", gen_part(sf)),
        ("partsupp", gen_partsupp(sf)),
        ("region", gen_region()),
    ]:
        df = session.create_dataframe(data, num_partitions=num_partitions)
        df.create_or_replace_temp_view(name)
