"""Benchmark runner (BenchUtils.runBench analogue,
integration_tests/BenchUtils.scala:109-240): runs queries with warmup +
timed iterations, captures environment + conf, writes a JSON report."""

from __future__ import annotations

import json
import platform
import time
from typing import Callable, Dict, List, Optional

import jax


def run_bench(session, name: str, query_fn: Callable[[], object],
              iterations: int = 3, warmups: int = 1,
              report_path: Optional[str] = None,
              keep_rows: bool = False) -> Dict:
    """query_fn() -> DataFrame; collects it warmups+iterations times.
    ``keep_rows`` includes the last iteration's collected rows in the
    report (for callers that checksum results)."""
    times: List[float] = []
    rows: List = []
    for _ in range(warmups):
        rows = query_fn().collect()
    for _ in range(iterations):
        t0 = time.monotonic()
        rows = query_fn().collect()
        times.append(time.monotonic() - t0)
    report = {
        "benchmark": name,
        "iterations": iterations,
        "times_s": [round(t, 4) for t in times],
        "best_s": round(min(times), 4),
        "mean_s": round(sum(times) / len(times), 4),
        "result_rows": len(rows),
        "env": {
            "platform": platform.platform(),
            "devices": [str(d) for d in jax.devices()],
        },
        "conf": {k: v for k, v in getattr(
            session.conf, "_settings", {}).items()},
    }
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2)
    if keep_rows:
        report["rows"] = rows
    return report
