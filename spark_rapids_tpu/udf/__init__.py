"""User-defined functions.

Two paths, mirroring the reference:

* :mod:`udf.compiler` — the udf-compiler analogue (SURVEY.md section 2.8):
  decompiles simple *Python* row-UDF bytecode into engine expression trees so
  they run columnar on the TPU (the reference decompiles Scala/JVM bytecode
  to Catalyst, udf-compiler/CatalystExpressionBuilder.scala:45).
* :mod:`udf.pandas_exec` — GpuArrowEvalPythonExec analogue
  (GpuArrowEvalPythonExec.scala:484): batches leave the device as Arrow,
  a pandas function runs on host (semaphore released while it runs), and
  results are staged back to HBM.
"""
