"""Python row-UDF bytecode -> expression-tree compiler.

Reference analogue: the udf-compiler module (SURVEY.md section 2.8) walks JVM
bytecode of Scala lambdas (CFG.scala basic blocks + an opcode interpreter)
and rebuilds Catalyst expressions.  Here the same idea over CPython bytecode:
a tiny abstract interpreter executes the UDF's code object symbolically,
mapping stack operations to engine expressions.  Anything it cannot model
raises :class:`CannotCompile` and the caller silently falls back to the
pandas path (the reference's silent-fallback behavior,
udf-compiler/Plugin.scala:36-94).

Supported: arithmetic (+,-,*,/,%,**), comparisons, and/or/not chains built
from conditional jumps, if/else expressions, abs/min/max/len over strings,
str methods (upper/lower/strip/startswith/endswith), math.sqrt/log/exp,
constants, multiple arguments.  No loops, no stores, no external state.
"""

from __future__ import annotations

import dis
import math
import types
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import Expression, Literal


class CannotCompile(Exception):
    pass


_BINOPS = {
    "+": "Add", "-": "Subtract", "*": "Multiply", "/": "Divide",
    "%": "Remainder", "**": "Pow",
}
# Python <= 3.10 emits one opcode per operator instead of 3.11's single
# parameterized BINARY_OP; both spellings compile to the same engine
# expressions.
_BINOP_OPCODES = {
    "BINARY_ADD": "+", "BINARY_SUBTRACT": "-", "BINARY_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "BINARY_MODULO": "%", "BINARY_POWER": "**",
}
_CMPOPS = {
    "==": "Equals", "!=": "NotEquals", "<": "LessThan",
    "<=": "LessThanOrEqual", ">": "GreaterThan", ">=": "GreaterThanOrEqual",
}


def _binop(opname: str, a: Expression, b: Expression) -> Expression:
    from spark_rapids_tpu.exprs import arithmetic as AR
    from spark_rapids_tpu.exprs import mathexprs as M
    if opname == "**":
        return M.Pow(a, b)
    cls = getattr(AR, _BINOPS[opname])
    return cls(a, b)


def _cmpop(opname: str, a: Expression, b: Expression) -> Expression:
    from spark_rapids_tpu.exprs import predicates as P
    return getattr(P, _CMPOPS[opname])(a, b)


_GLOBAL_FUNCS = {
    abs: lambda args: _abs(args[0]),
    len: lambda args: _len(args[0]),
    math.sqrt: lambda args: _math1("Sqrt", args[0]),
    math.log: lambda args: _math1("Log", args[0]),
    math.exp: lambda args: _math1("Exp", args[0]),
    math.floor: lambda args: _math1("Floor", args[0]),
    math.ceil: lambda args: _math1("Ceil", args[0]),
}

_STR_METHODS = {
    "upper": "Upper", "lower": "Lower", "strip": "StringTrim",
    "lstrip": "StringTrimLeft", "rstrip": "StringTrimRight",
}


def _abs(e):
    from spark_rapids_tpu.exprs.arithmetic import Abs
    return Abs(e)


def _len(e):
    from spark_rapids_tpu.exprs.strings import Length
    return Length(e)


def _math1(name, e):
    from spark_rapids_tpu.exprs import mathexprs as M
    return getattr(M, name)(e)


class _Method:
    def __init__(self, obj: Expression, name: str):
        self.obj = obj
        self.name = name


class _Compiler:
    """Symbolic evaluator over a code object's bytecode (single pass with
    branch forking for conditionals — the CFG/State analogue)."""

    def __init__(self, code: types.CodeType, arg_exprs: List[Expression],
                 globals_: Dict[str, Any]):
        self.code = code
        self.instrs = list(dis.get_instructions(code))
        self.by_offset = {ins.offset: i for i, ins in enumerate(self.instrs)}
        self.args = {code.co_varnames[i]: e
                     for i, e in enumerate(arg_exprs)}
        self.globals = globals_

    def run(self) -> Expression:
        return self._exec(0, [])

    def _exec(self, idx: int, stack: List[Any]) -> Expression:
        """Interpret from instruction idx until RETURN; returns result."""
        from spark_rapids_tpu.exprs import predicates as P
        from spark_rapids_tpu.exprs.conditional import If
        stack = list(stack)
        i = idx
        guard = 0
        while i < len(self.instrs):
            guard += 1
            if guard > 10000:
                raise CannotCompile("bytecode too long")
            ins = self.instrs[i]
            op = ins.opname
            if op in ("RESUME", "PRECALL", "CACHE", "NOP", "PUSH_NULL",
                      "COPY_FREE_VARS", "MAKE_CELL", "NOT_TAKEN"):
                i += 1
                continue
            if op in ("LOAD_FAST", "LOAD_FAST_CHECK", "LOAD_FAST_BORROW"):
                if ins.argval not in self.args:
                    raise CannotCompile(f"unknown local {ins.argval}")
                stack.append(self.args[ins.argval])
                i += 1
                continue
            if op in ("LOAD_FAST_LOAD_FAST", "LOAD_FAST_BORROW_LOAD_FAST_BORROW"):
                a, b = ins.argval
                for nm in (a, b):
                    if nm not in self.args:
                        raise CannotCompile(f"unknown local {nm}")
                    stack.append(self.args[nm])
                i += 1
                continue
            if op == "LOAD_CONST":
                stack.append(Literal(ins.argval)
                             if ins.argval is not None or True
                             else ins.argval)
                i += 1
                continue
            if op in ("LOAD_GLOBAL", "LOAD_DEREF", "LOAD_NAME"):
                name = ins.argval
                if name in self.globals:
                    stack.append(self.globals[name])
                elif name in __builtins__ if isinstance(__builtins__, dict) \
                        else hasattr(__builtins__, name):
                    b = __builtins__[name] if isinstance(__builtins__, dict) \
                        else getattr(__builtins__, name)
                    stack.append(b)
                else:
                    raise CannotCompile(f"unknown global {name}")
                i += 1
                continue
            if op in ("LOAD_ATTR", "LOAD_METHOD"):
                obj = stack.pop()
                name = ins.argval
                if isinstance(obj, Expression):
                    if name not in _STR_METHODS:
                        raise CannotCompile(f"method {name}")
                    stack.append(_Method(obj, name))
                elif isinstance(obj, types.ModuleType):
                    stack.append(getattr(obj, name))
                else:
                    raise CannotCompile(f"attr on {obj!r}")
                i += 1
                continue
            if op == "BINARY_OP" or op in _BINOP_OPCODES:
                b, a = stack.pop(), stack.pop()
                sym = _BINOP_OPCODES.get(op) or ins.argrepr.strip().rstrip("=")
                if sym not in _BINOPS:
                    raise CannotCompile(f"binop {ins.argrepr}")
                stack.append(_binop(sym, _as_expr(a), _as_expr(b)))
                i += 1
                continue
            if op == "COMPARE_OP":
                b, a = stack.pop(), stack.pop()
                sym = ins.argrepr.split()[0]
                if sym not in _CMPOPS:
                    raise CannotCompile(f"cmp {ins.argrepr}")
                stack.append(_cmpop(sym, _as_expr(a), _as_expr(b)))
                i += 1
                continue
            if op == "UNARY_NEGATIVE":
                from spark_rapids_tpu.exprs.arithmetic import UnaryMinus
                stack.append(UnaryMinus(_as_expr(stack.pop())))
                i += 1
                continue
            if op == "UNARY_NOT":
                stack.append(P.Not(_as_expr(stack.pop())))
                i += 1
                continue
            if op in ("CALL", "CALL_FUNCTION", "CALL_METHOD"):
                argc = ins.arg or 0
                args = [stack.pop() for _ in range(argc)][::-1]
                fn = stack.pop()
                if isinstance(fn, Literal):
                    raise CannotCompile("calling a literal")
                if isinstance(fn, _Method):
                    stack.append(self._call_method(fn, args))
                elif fn in _GLOBAL_FUNCS:
                    stack.append(_GLOBAL_FUNCS[fn](
                        [_as_expr(a) for a in args]))
                elif fn in (min, max) if callable(fn) else False:
                    stack.append(self._minmax(fn, args))
                else:
                    raise CannotCompile(f"call {fn!r}")
                i += 1
                continue
            if op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                      "POP_JUMP_FORWARD_IF_FALSE", "POP_JUMP_FORWARD_IF_TRUE"):
                cond = _as_expr(stack.pop())
                target = self.by_offset[ins.argval]
                take_true_first = "IF_FALSE" in op
                # fork: fallthrough vs jump
                ft = self._exec(i + 1, stack)
                jp = self._exec(target, stack)
                from spark_rapids_tpu.exprs.conditional import If
                if take_true_first:
                    return If(cond, ft, jp)
                return If(cond, jp, ft)
            if op in ("JUMP_IF_FALSE_OR_POP", "JUMP_IF_TRUE_OR_POP"):
                cond = _as_expr(stack.pop())
                target = self.by_offset[ins.argval]
                ft = self._exec(i + 1, stack)
                jp_stack = stack + [cond]
                jp = self._exec(target, jp_stack)
                from spark_rapids_tpu.exprs.conditional import If
                if "IF_FALSE" in op:
                    return If(cond, ft, jp)
                return If(cond, jp, ft)
            if op in ("JUMP_FORWARD", "JUMP_BACKWARD", "JUMP_ABSOLUTE"):
                if op == "JUMP_BACKWARD":
                    raise CannotCompile("loop")
                i = self.by_offset[ins.argval]
                continue
            if op in ("TO_BOOL",):
                i += 1
                continue
            if op in ("RETURN_VALUE",):
                return _as_expr(stack.pop())
            if op == "RETURN_CONST":
                return Literal(ins.argval)
            import sys
            pyver = ".".join(map(str, sys.version_info[:2]))
            raise CannotCompile(
                f"unsupported opcode {op} (python {pyver}); the UDF "
                "falls back to row-at-a-time CPU execution")
        raise CannotCompile("fell off end of bytecode")

    def _call_method(self, m: _Method, args) -> Expression:
        from spark_rapids_tpu.exprs import strings as S
        if m.name in _STR_METHODS and not args:
            return getattr(S, _STR_METHODS[m.name])(m.obj)
        if m.name == "startswith" and len(args) == 1:
            return S.StringStartsWith(m.obj, _as_expr(args[0]))
        if m.name == "endswith" and len(args) == 1:
            return S.StringEndsWith(m.obj, _as_expr(args[0]))
        raise CannotCompile(f"method {m.name}/{len(args)}")

    def _minmax(self, fn, args) -> Expression:
        from spark_rapids_tpu.exprs.conditional import If
        from spark_rapids_tpu.exprs import predicates as P
        if len(args) != 2:
            raise CannotCompile("min/max arity")
        a, b = _as_expr(args[0]), _as_expr(args[1])
        if fn is min:
            return If(P.LessThanOrEqual(a, b), a, b)
        return If(P.GreaterThanOrEqual(a, b), a, b)


def _as_expr(v) -> Expression:
    if isinstance(v, Expression):
        return v
    raise CannotCompile(f"non-expression value {v!r} on stack")


def compile_udf(fn, arg_exprs: List[Expression]) -> Expression:
    """Compile a python function of N scalars into an expression over the
    given argument expressions.  Raises CannotCompile on anything fancy."""
    if not isinstance(fn, types.FunctionType):
        raise CannotCompile("not a plain python function")
    if fn.__code__.co_argcount != len(arg_exprs):
        raise CannotCompile("arity mismatch")
    if fn.__closure__:
        # allow closures over plain constants
        free = {}
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            v = cell.cell_contents
            if isinstance(v, (int, float, str, bool)) or v is None:
                free[name] = Literal(v)
            elif isinstance(v, types.ModuleType) or callable(v):
                free[name] = v
            else:
                raise CannotCompile(f"closure over {type(v)}")
        g = dict(fn.__globals__)
        g.update(free)
    else:
        g = fn.__globals__
    comp = _Compiler(fn.__code__, arg_exprs, g)
    return comp.run()
