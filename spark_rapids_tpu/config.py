"""Typed configuration registry.

TPU-native analogue of the reference's RapidsConf (RapidsConf.scala:116-256):
a registry of typed ConfEntry objects with defaults and doc strings, plus
markdown doc generation (RapidsConf.scala:717,814 generates docs/configs.md).
Per-operator enable keys (``spark.rapids.sql.exec.<Name>`` etc.,
GpuOverrides.scala:129-137) are registered dynamically by the planner rules.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

_REGISTRY: "Dict[str, ConfEntry]" = {}
_REGISTRY_LOCK = threading.Lock()


class ConfEntry(Generic[T]):
    def __init__(self, key: str, default: T, doc: str, converter: Callable[[str], T],
                 internal: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.converter = converter
        self.internal = internal

    def get(self, conf: "RapidsConf") -> T:
        return conf.get(self.key)

    def __repr__(self):
        return f"ConfEntry({self.key}={self.default!r})"


def _to_bool(s: str) -> bool:
    return str(s).strip().lower() in ("true", "1", "yes", "on")


def _register(entry: ConfEntry) -> ConfEntry:
    with _REGISTRY_LOCK:
        if entry.key in _REGISTRY:
            return _REGISTRY[entry.key]
        _REGISTRY[entry.key] = entry
    return entry


def conf_bool(key: str, default: bool, doc: str, internal: bool = False) -> ConfEntry:
    return _register(ConfEntry(key, default, doc, _to_bool, internal))


def conf_int(key: str, default: int, doc: str, internal: bool = False) -> ConfEntry:
    return _register(ConfEntry(key, default, doc, int, internal))


def conf_float(key: str, default: float, doc: str, internal: bool = False) -> ConfEntry:
    return _register(ConfEntry(key, default, doc, float, internal))


def conf_str(key: str, default: str, doc: str, internal: bool = False) -> ConfEntry:
    return _register(ConfEntry(key, default, doc, str, internal))


def conf_bytes(key: str, default: int, doc: str, internal: bool = False) -> ConfEntry:
    def parse(s: str) -> int:
        s = str(s).strip().lower()
        mult = 1
        for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30), ("t", 1 << 40)):
            if s.endswith(suffix + "b"):
                s, mult = s[:-2], m
                break
            if s.endswith(suffix):
                s, mult = s[:-1], m
                break
        return int(float(s) * mult)
    return _register(ConfEntry(key, default, doc, parse, internal))


class RapidsConf:
    """An immutable-ish snapshot of configuration values.

    Values resolve in order: explicit settings > environment variables
    (``SPARK_RAPIDS_TPU_<KEY_WITH_UNDERSCORES>``) > registered default.
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings: Dict[str, Any] = dict(settings or {})

    def set(self, key: str, value: Any) -> "RapidsConf":
        self._settings[key] = value
        return self

    def get(self, key: str, default: Any = None) -> Any:
        entry = _REGISTRY.get(key)
        if key in self._settings:
            raw = self._settings[key]
            if entry is not None and isinstance(raw, str):
                return entry.converter(raw)
            return raw
        env_key = "SPARK_RAPIDS_TPU_" + key.replace(".", "_").upper()
        if env_key in os.environ:
            raw = os.environ[env_key]
            return entry.converter(raw) if entry is not None else raw
        if entry is not None:
            return entry.default
        return default

    def copy(self, **overrides: Any) -> "RapidsConf":
        c = RapidsConf(dict(self._settings))
        for k, v in overrides.items():
            c.set(k, v)
        return c

    def explicitly_set(self, key: str) -> bool:
        """True when the user pinned ``key`` — an explicit session
        setting or an environment override.  Adaptive controllers use
        this to honor pinned values instead of tuning over them (e.g.
        scan.readAhead.depth set explicitly disables the adaptive
        read-ahead controller)."""
        if key in self._settings:
            return True
        env_key = "SPARK_RAPIDS_TPU_" + key.replace(".", "_").upper()
        return env_key in os.environ

    def is_operator_enabled(self, key: str, default: bool = True) -> bool:
        v = self.get(key)
        if v is None:
            return default
        return v if isinstance(v, bool) else _to_bool(v)

    # ---- core entries (mirroring RapidsConf.scala:271-700) ----

    @property
    def sql_enabled(self) -> bool:
        return SQL_ENABLED.get(self)

    @property
    def explain(self) -> str:
        return EXPLAIN.get(self)

    @property
    def batch_size_bytes(self) -> int:
        return BATCH_SIZE_BYTES.get(self)

    @property
    def max_readers_batch_size_rows(self) -> int:
        return READER_BATCH_SIZE_ROWS.get(self)

    @property
    def concurrent_tpu_tasks(self) -> int:
        return CONCURRENT_TPU_TASKS.get(self)

    @property
    def test_enforce_tpu(self) -> bool:
        return TEST_ENFORCE_TPU.get(self)

    @property
    def allow_incompat(self) -> bool:
        return INCOMPATIBLE_OPS.get(self)

    @property
    def has_nans(self) -> bool:
        return HAS_NANS.get(self)

    @property
    def variable_float_agg(self) -> bool:
        return VARIABLE_FLOAT_AGG.get(self)

    @property
    def host_spill_storage_size(self) -> int:
        return HOST_SPILL_STORAGE_SIZE.get(self)

    @property
    def replace_sort_merge_join(self) -> bool:
        return REPLACE_SORT_MERGE_JOIN.get(self)

    @property
    def explain_enabled(self) -> bool:
        return str(self.explain).upper() not in ("NONE", "FALSE", "")

    @property
    def shuffle_partitions(self) -> int:
        return SHUFFLE_PARTITIONS.get(self)

    @property
    def coalesce_target_rows(self) -> int:
        return COALESCE_TARGET_ROWS.get(self)


SQL_ENABLED = conf_bool(
    "spark.rapids.sql.enabled", True,
    "Enable (true) or disable (false) TPU acceleration of SQL operators.")
EXPLAIN = conf_str(
    "spark.rapids.sql.explain", "NONE",
    "Explain why parts of a query were or were not placed on the TPU. "
    "Options: NONE, ALL, NOT_ON_TPU.")
BATCH_SIZE_BYTES = conf_bytes(
    "spark.rapids.sql.batchSizeBytes", 512 * 1024 * 1024,
    "The target size in bytes of columnar batches processed on the TPU. "
    "The coalesce layer concatenates smaller batches up to this goal.")
READER_BATCH_SIZE_ROWS = conf_int(
    "spark.rapids.sql.reader.batchSizeRows", 1 << 20,
    "Soft cap on the number of rows the file readers put in one batch.")
CONCURRENT_TPU_TASKS = conf_int(
    "spark.rapids.sql.concurrentTpuTasks", 1,
    "Number of tasks that can execute concurrently on a single TPU chip. "
    "Tasks above the limit block in the TpuSemaphore.")
TEST_ENFORCE_TPU = conf_bool(
    "spark.rapids.sql.test.enabled", False,
    "Testing only: fail query planning if any supported operator would "
    "fall back to the CPU.", internal=True)
INCOMPATIBLE_OPS = conf_bool(
    "spark.rapids.sql.incompatibleOps.enabled", False,
    "Enable operators that produce results that differ in corner cases "
    "from Spark CPU semantics.")
HAS_NANS = conf_bool(
    "spark.rapids.sql.hasNans", True,
    "Whether float/double data is assumed to possibly contain NaNs; when "
    "true some float aggregations and joins stay on CPU for exactness.")
VARIABLE_FLOAT_AGG = conf_bool(
    "spark.rapids.sql.variableFloatAgg.enabled", False,
    "Allow float/double aggregations whose result can vary run-to-run "
    "because of non-deterministic reduction order.")
HOST_SPILL_STORAGE_SIZE = conf_bytes(
    "spark.rapids.memory.host.spillStorageSize", 1 << 30,
    "Bytes of host memory used to cache spilled device data before "
    "overflowing to disk.")
DEVICE_POOL_FRACTION = conf_float(
    "spark.rapids.memory.tpu.allocFraction", 0.9,
    "Fraction of usable HBM to reserve for the device buffer pool at startup.")
REPLACE_SORT_MERGE_JOIN = conf_bool(
    "spark.rapids.sql.replaceSortMergeJoin.enabled", True,
    "Replace sort-merge joins with TPU hash joins and drop the now "
    "unneeded sorts (reference: RapidsConf.scala:423).")
AUTO_BROADCAST_THRESHOLD = conf_bytes(
    "spark.sql.autoBroadcastJoinThreshold", 10 << 20,
    "Max estimated build-side bytes for choosing a broadcast hash join "
    "over a shuffled hash join; -1 disables broadcast.")
SHUFFLE_PARTITIONS = conf_int(
    "spark.sql.shuffle.partitions", 8,
    "Number of partitions used for shuffle exchanges.")
SHUFFLE_COMPRESSION_CODEC = conf_str(
    "spark.rapids.shuffle.compression.codec", "copy",
    "Codec for compressing shuffled table buffers (copy = passthrough). "
    "`nativelz` is the project-specific C++ LZ-family block codec — its "
    "wire format is NOT standard LZ4; there is deliberately no `lz4` "
    "alias.")
STRING_HASH_JOIN = conf_bool(
    "spark.rapids.sql.stringHashGroupJoin.enabled", True,
    "Group by / join on string keys via 64-bit hashes computed on device; "
    "collisions are astronomically unlikely but theoretically possible.")
ENABLE_ICI_SHUFFLE = conf_bool(
    "spark.rapids.shuffle.ici.enabled", False,
    "Route shuffle exchanges through the ICI lax.all_to_all collective "
    "over the device mesh when >1 device is available.  Opt-in, like the "
    "reference's RapidsShuffleManager (docs/get-started.md); off means the "
    "single-host exchange path.")
MESH_SPMD_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.mesh.spmd.enabled", True,
    "Fuse contiguous plan segments on either side of a mesh shuffle into "
    "ONE shard_map program: exchanges (hash, round-robin AND range — "
    "range bounds are sampled/sorted/picked in-program) lower to "
    "in-program lax.all_to_all collectives, joins run per-shard with "
    "capacity-bucketed static output sizing, broadcast-join build sides "
    "replicate (PartitionSpec ()) and the whole stage runs with zero "
    "host syncs (host-driven mesh shuffle pays 1 sync + a restage per "
    "exchange).  Requires shuffle.ici.enabled and >1 device; "
    "single-partition collapses are the only remaining host-driven "
    "fallback (see mesh.spmd.autoFallback).  Bit-identical either way.")
MESH_SPMD_AUTO_FALLBACK = conf_bool(
    "spark.rapids.sql.tpu.mesh.spmd.autoFallback", True,
    "With mesh.spmd.enabled, silently route mesh-incompatible exchanges "
    "(single-partition collapses) through the host-driven mesh shuffle, "
    "and rerun a fused stage host-driven when a join's bucketed output "
    "capacity overflows, instead of failing.  false raises on the first "
    "incompatible exchange — a debugging aid to catch segments dropping "
    "out of whole-stage SPMD fusion.")
MESH_SPMD_JOIN_GROWTH = conf_float(
    "spark.rapids.sql.tpu.mesh.spmd.join.growthFactor", 2.0,
    "Pair-capacity growth factor for joins fused into a mesh-SPMD "
    "program: the per-shard static pair capacity is the bucket-quantized "
    "probe capacity times this factor (the host-driven path instead "
    "host-syncs the exact total).  Joins whose true pair count exceeds "
    "the bucket set an in-program overflow flag and the stage reruns "
    "host-driven (mesh.spmd.autoFallback).")
PINNED_POOL_SIZE = conf_bytes(
    "spark.rapids.memory.pinnedPool.size", 0,
    "Size of the pinned host staging pool used by the native runtime for "
    "host<->HBM transfers (0 = disabled).")
CPU_RANGE_PARTITIONING_SAMPLE = conf_int(
    "spark.rapids.sql.rangePartitioning.sampleSize", 1 << 16,
    "Rows sampled per partition when computing range-partitioning bounds.")
MULTITHREADED_READ_THREADS = conf_int(
    "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads", 8,
    "Threads used to read+decode file footers and column chunks in "
    "parallel ahead of device staging.")
STAGE_READAHEAD_BATCHES = conf_int(
    "spark.rapids.sql.tpu.stage.readAheadBatches", 2,
    "Host batches decoded AND staged into HBM ahead of the consumer by a "
    "background thread, so scan decode + host->device transfer overlap "
    "downstream device compute (the reference's read-ahead + semaphore "
    "pattern, GpuParquetScan.scala:647-700).  0 = synchronous staging.")
PARQUET_ENABLED = conf_bool(
    "spark.rapids.sql.format.parquet.enabled", True,
    "Enable the accelerated parquet scan path: multi-threaded read-ahead "
    "decode plus row-group predicate pushdown.  Disabled falls back to "
    "single-threaded plain decode.")
SCAN_PUSHDOWN_ENABLED = conf_bool(
    "spark.rapids.sql.scan.pushdown.enabled", True,
    "Push filter conjuncts into file scans: parquet row groups are "
    "skipped on min/max statistics and Hive key=value partition "
    "directories are pruned before any decode.")
SCAN_V2_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.scan.v2.enabled", True,
    "Parallel scan pipeline (io/scan_v2): sub-file decode parallelism "
    "(parquet row groups / ORC stripes as independent tasks on a "
    "process-shared decode pool), streaming chunk emission so decode "
    "overlaps host->device staging, plus the dictEncoding and "
    "lateMaterialization features below.  Off restores the v1 "
    "file-at-a-time scan (bit-identical results either way).")
SCAN_READAHEAD_DEPTH = conf_int(
    "spark.rapids.sql.tpu.scan.readAhead.depth", 4,
    "Decode tasks kept in flight ahead of the scan consumer by the v2 "
    "pipeline (bounded sliding window over the shared decode pool). "
    "Chunks are still yielded in deterministic file/chunk order.  "
    "<=1 decodes one chunk at a time (no read-ahead).")
SCAN_READAHEAD_ADAPTIVE = conf_bool(
    "spark.rapids.sql.tpu.scan.readAhead.adaptive.enabled", True,
    "Close the read-ahead control loop: the v2 scan adjusts its in-flight "
    "decode-task depth between chunk drains from its own blocked-drain "
    "ratio and the decode pool's utilization gauge — deepening while the "
    "consumer starves and the pool has headroom, shallowing when chunks "
    "are always ready (less host memory pinned in decoded-but-unconsumed "
    "chunks).  Clamped to [1, scan.readAhead.maxDepth].  Ignored (static "
    "depth honored) when scan.readAhead.depth is set explicitly.")
SCAN_READAHEAD_MAX_DEPTH = conf_int(
    "spark.rapids.sql.tpu.scan.readAhead.maxDepth", 16,
    "Upper clamp for the adaptive read-ahead controller "
    "(scan.readAhead.adaptive.enabled) — at most this many decode tasks "
    "in flight ahead of the scan consumer, bounding decoded-chunk host "
    "memory no matter how starved the consumer looks.")
SCAN_PAGE_CHUNK_MIN_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.scan.pageChunk.minBytes", 64 << 20,
    "Sub-row-group decode granularity (v2 parquet): a row group whose "
    "compressed footprint exceeds this is decoded as several column-slab "
    "subtasks on the pool (the projected columns split into balanced "
    "subsets) and reassembled column-wise on the consumer thread, so one "
    "fat row group cannot serialize the decode pool.  <=0 disables "
    "(always one task per row group).")
SCAN_FILE_HANDLE_CACHE_SIZE = conf_int(
    "spark.rapids.sql.tpu.scan.fileHandleCache.size", 8,
    "Per-thread pyarrow file-handle cache capacity (io.decode_pool): "
    "scan chunk tasks reuse the thread's open ParquetFile/ORC reader for "
    "the same path instead of paying open()+footer-parse per row group; "
    "least-recently-used handles past the bound are closed.  <=0 "
    "disables caching (open per chunk, the v1 behavior).")
SCAN_DICT_ENCODING_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.scan.dictEncoding.enabled", True,
    "Keep parquet dictionary-encoded string columns encoded through "
    "host->device staging: the device carries int32 codes plus the "
    "(small) dictionary buffers, so H2D moves indices instead of string "
    "bytes and encode-aware kernels (filter eq, hash/group keys) work "
    "on codes; other kernels materialize on demand.  Only active under "
    "scan v2 when the scan feeds the device directly.")
SCAN_LATE_MAT_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.scan.lateMaterialization.enabled", True,
    "Late materialization for pushed-predicate scans (v2): decode the "
    "predicate columns of a row-group chunk first, evaluate the pushed "
    "conjuncts, and decode the remaining projected columns only when "
    "the chunk has surviving rows — chunks with zero survivors are "
    "skipped entirely (the Filter above re-applies the predicate, so "
    "this only ever drops whole all-false chunks).")
AQE_COALESCE_ENABLED = conf_bool(
    "spark.rapids.sql.adaptive.coalescePartitions.enabled", True,
    "Group small post-shuffle partitions so each downstream task covers "
    "a worthwhile row count (GpuCustomShuffleReaderExec role); join pairs "
    "coalesce by combined size to stay co-partitioned.")
AQE_TARGET_ROWS = conf_int(
    "spark.rapids.sql.adaptive.targetPartitionRows", 1 << 16,
    "Row-count target per coalesced post-shuffle partition (used only "
    "when the exchange did not record byte sizes).")
AQE_TARGET_BYTES = conf_bytes(
    "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes", 64 << 20,
    "Byte-size target per coalesced post-shuffle partition; preferred "
    "over the row target whenever the exchange recorded per-piece bytes "
    "(the reference coalesces by map-status bytes, GpuCoalesceBatches "
    "goals).")
AQE_REPLAN_JOINS = conf_bool(
    "spark.rapids.sql.adaptive.replanJoins.enabled", True,
    "At execution time, convert a shuffled hash join whose build side "
    "came in under spark.sql.autoBroadcastJoinThreshold (by shuffle-known "
    "bytes) into the broadcast path (GpuCustomShuffleReaderExec / AQE "
    "OptimizeShuffledHashJoin role).")
AQE_SKEW_FACTOR = conf_float(
    "spark.rapids.sql.adaptive.skewJoin.skewedPartitionFactor", 5.0,
    "A coalesced join partition is considered skewed when its size "
    "exceeds this multiple of the median partition size (and the "
    "advisory target); the stream side is then joined in bounded chunks "
    "against the full build side.")
TPU_ADAPTIVE_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.adaptive.enabled", True,
    "Master gate for the runtime-stats replanning layer (plan/adaptive): "
    "post-shuffle partition coalescing, the dynamic shuffled->broadcast "
    "join switch and skew splitting all read ONLY statistics the shuffle "
    "split already fetched (piece_rows/piece_bytes), so turning this on "
    "adds zero host syncs.  Off forces the statically planned shapes.")
ADAPTIVE_COALESCE_TARGET_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.adaptive.coalesce.targetBytes", 0,
    "Byte target per coalesced post-shuffle partition for the adaptive "
    "layer.  0 (default) inherits "
    "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes (64MB), so "
    "the two knobs cannot fight; set nonzero to tune the adaptive layer "
    "independently of the legacy advisory target.")
ADAPTIVE_SKEW_THRESHOLD_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.adaptive.skew.thresholdBytes", 0,
    "Absolute floor a partition must also exceed (besides "
    "skewedPartitionFactor x median) to be treated as skewed and split "
    "back into its per-source pieces.  0 (default) inherits the adaptive "
    "coalesce byte target, i.e. a partition under one coalesce target is "
    "never worth splitting.")
HASH_AGG_MXU_ENABLED = conf_bool(
    "spark.rapids.sql.agg.mxuHash.enabled", True,
    "Aggregate update batches on the MXU via slot one-hot contractions "
    "when the agg list is sum/count/avg/min/max/first/last and the group "
    "keys are integral/date/bool columns (multi-key via mixed-radix slot "
    "packing): one matmul (plus a scatter pass for min/max-class aggs) "
    "replaces the sort-based groupby's argsort + gathers + scatters.  "
    "Batches whose packed key space exceeds the slot table (or float "
    "sums over NaN/Inf) transparently re-run the exact sort path.")
HASH_AGG_MXU_SLOTS = conf_int(
    "spark.rapids.sql.agg.mxuHash.tableSlots", 8192,
    "Slot-table capacity of the MXU hash aggregate: the product of the "
    "per-key value ranges (plus one per nullable key) must fit here or "
    "the batch falls back to the sort path.  Larger tables admit wider "
    "key spaces at the cost of one-hot contraction FLOPs/memory.")
NLJ_PAIR_CAPACITY = conf_int(
    "spark.rapids.sql.nestedLoopJoin.pairCapacity", 1 << 22,
    "Max cross-pair slots a single nested-loop-join step may allocate; "
    "a stream side whose pair space exceeds this is joined in row chunks "
    "(the reference streams broadcast NLJ per stream batch).")
CSV_ENABLED = conf_bool(
    "spark.rapids.sql.format.csv.enabled", True,
    "Enable the accelerated CSV scan path (multi-threaded read-ahead "
    "decode).  Disabled falls back to single-threaded decode.")
COALESCE_TARGET_ROWS = conf_int(
    "spark.rapids.sql.coalesce.targetRows", 1 << 20,
    "Row goal for the batch-coalesce layer (TargetSize analogue).")
UDF_COMPILER_ENABLED = conf_bool(
    "spark.rapids.sql.udfCompiler.enabled", False,
    "Compile python row UDFs into columnar expressions when possible.")
PIPELINE_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.pipeline.enabled", True,
    "Run all-TPU plan subtrees as whole-pipeline XLA programs (the "
    "whole-stage-codegen analogue): O(1) dispatched programs per query "
    "stage instead of one per operator per batch.")
FUSION_ENABLED = conf_bool(
    "spark.rapids.sql.fusion.enabled", True,
    "Collapse chains of per-batch map operators (project/filter) into one "
    "compiled program and absorb them into aggregate/sort/exchange "
    "consumers (dispatch-count optimizer).")
EXCHANGE_COLLAPSE_LOCAL = conf_bool(
    "spark.rapids.sql.tpu.exchange.collapseLocal", True,
    "Collapse shuffle exchanges to a single logical partition in "
    "single-process execution: partitioning only constrains placement, "
    "which one partition trivially satisfies, so the per-batch pid "
    "compute + split is pure overhead on one device.")
SHUFFLE_SPLIT_V2 = conf_bool(
    "spark.rapids.sql.tpu.exchange.splitV2.enabled", True,
    "Use the one-sync coalescing shuffle split: every input batch's "
    "pid-sort program is dispatched before ONE bulk count/byte-total "
    "fetch, then each target partition is assembled from all sorted "
    "batches by a single k-way segment-gather dispatch (<=N pieces, "
    "~B+N dispatches).  false restores the legacy per-batch split "
    "(B host syncs, one gather per batch x partition pair).")
SHUFFLE_COALESCE_MAX_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.exchange.splitCoalesceMaxBytes", 256 << 20,
    "Spill-budget cap for the coalescing shuffle split: a target "
    "partition whose combined size exceeds this stays as per-batch "
    "pieces so the catalog can spill early pieces while later input "
    "batches still materialize.  <=0 coalesces unconditionally.")
SHUFFLE_DICT_AWARE = conf_bool(
    "spark.rapids.sql.tpu.exchange.dictAware.enabled", True,
    "Dict-aware shuffle split (v2 split only): when input columns are "
    "dictionary-encoded, the pid-sort permutes 4-byte codes and each "
    "coalesced piece carries codes plus ONE merged dictionary instead of "
    "materialized string bytes — the encoded corridor survives the "
    "exchange, and shuffleEncodedBytesSaved records the bytes not moved. "
    "Bit-identical results; piece sizing/AQE statistics still report "
    "materialized bytes so plan decisions match encoded-off exactly.")
JOIN_DICT_KEYS_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.join.dictKeys.enabled", True,
    "Encoded equi-join string keys: when both sides of a hash join key "
    "are dictionary-encoded, probe on int32 codes — directly when the "
    "sides share one dictionary object, else after rendezvous-translating "
    "the smaller side's codes into the larger dictionary's space via a "
    "device entry-matching table (docs/io.md, encoded corridor v2).  "
    "Divergent dictionaries whose entry-pair table would exceed ~4M "
    "cells skip translation and hash entry content through the codes "
    "instead (still encoded, no materialization).")
PIPELINE_FUSE_TAIL = conf_bool(
    "spark.rapids.sql.tpu.pipeline.fuseTail.enabled", True,
    "Fuse the stage-break re-bucketing gather into the consuming (tail) "
    "stage program: the final merge-aggregate/sort/limit tail then runs "
    "in one jitted dispatch instead of shrink + tail (lower dispatchCount "
    "per query; the tail program is cached per shrunk-bucket signature).")
PIPELINE_ASYNC_PARTITIONS = conf_bool(
    "spark.rapids.sql.tpu.pipeline.asyncPartitions.enabled", True,
    "Dispatch every pipeline source's stage program (and every collected "
    "partition's work) before taking any blocking host sync, then batch "
    "the stage-break size syncs and the final device->host copy into one "
    "round trip each.  Off restores the sequential "
    "dispatch/sync-per-source order.")
DONATION_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.donation.enabled", True,
    "Donate consumed input buffers to the stage programs and stage-break "
    "shrink gathers (jax donate_argnums): XLA reuses the input HBM for "
    "outputs instead of holding input + output live across the dispatch. "
    "Only buffers the engine provably never touches again are donated "
    "(fresh host->device stagings and stage-break intermediates — never "
    "cached or spill-catalog batches); a donated dispatch that hits a "
    "device OOM fails fast instead of spill-retrying, since its inputs "
    "are already consumed.")
PIPELINE_SHRINK_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.pipeline.shrinkBytes", 4 << 20,
    "Padded stage outputs at or below this byte total skip the sizes "
    "round-trip + re-bucketing gather at pipeline stage breaks.")
COMPILE_CACHE_DIR = conf_str(
    "spark.rapids.sql.tpu.compileCacheDir", "",
    "Directory for JAX's persistent XLA compilation cache.  When set, "
    "compiled executables survive the process so re-runs (and "
    "session.prewarm()) skip recompilation; empty disables persistence.")
RETRY_MAX_ATTEMPTS = conf_int(
    "spark.rapids.sql.tpu.retry.maxAttempts", 3,
    "Total attempts (first try included) the unified RetryPolicy allows "
    "a retryable operation: OOM spill-retries, device-lost partition "
    "replays and whole-pipeline recoveries all share this bound.  "
    "Exhausted device-class errors degrade to the per-partition CPU "
    "fallback (fallback.onDeviceError).")
RETRY_BACKOFF_MS = conf_float(
    "spark.rapids.sql.tpu.retry.backoffMs", 50.0,
    "Base backoff milliseconds between retry attempts.  Delays are "
    "deterministic — backoffMs * 2^(attempt-1), a pure function of the "
    "attempt index with no jitter — so faulted runs replay identically.")
PARTITION_TIMEOUT_SEC = conf_float(
    "spark.rapids.sql.tpu.partition.timeoutSec", 0.0,
    "Deadline in seconds for driving one partition (and for one "
    "whole-pipeline stage).  On expiry a watchdog thread raises a "
    "classified PartitionTimeout into the driving thread — the wedged "
    "partition then enters device-lost recovery instead of hanging the "
    "process.  0 disables (the test-tier default; the bench driver "
    "arms it).")
FALLBACK_ON_DEVICE_ERROR = conf_bool(
    "spark.rapids.sql.tpu.fallback.onDeviceError", True,
    "After retry.maxAttempts device replays of a failed partition "
    "(device lost, wedged, or OOM that spilling cannot fix), re-run "
    "just that partition through the CPU operator path so the query "
    "completes with Spark-CPU-identical results.  false surfaces the "
    "raw device error instead.")
FAULTS_SPEC = conf_str(
    "spark.rapids.sql.tpu.faults.spec", "",
    "Deterministic fault injection spec, e.g. "
    "\"dispatch:oom@3;d2h:device_lost@1;spill:slow=200ms@2\": at each "
    "named site (dispatch, h2d, d2h, spill, unspill, exchange, scan, "
    "mesh) the Nth "
    "call raises the named error class (or stalls, for slow=<dur>); @N+ "
    "fires from the Nth call onward.  Call counters reset per query.  "
    "Empty disables injection.")
SPILL_ASYNC_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.spill.async.enabled", True,
    "Run budget-triggered spills on a bounded background writer pool: "
    "reserve() transitions victims to the SPILLING tier under the "
    "catalog lock and returns immediately; the D2H copy and any "
    "compress+disk write overlap compute.  A get() racing an unstarted "
    "spill cancels it cheaply; one racing a started spill joins just "
    "that handle's completion.  false restores the v1 synchronous "
    "spill (every tier move completes before the triggering call "
    "returns).  OOM-triggered spills (run_with_oom_retry) are always "
    "synchronous — eager, but off the catalog lock.")
SPILL_WRITER_THREADS = conf_int(
    "spark.rapids.sql.tpu.spill.writer.threads", 2,
    "Background writer threads draining the async spill queue "
    "(spill.async.enabled).  Each thread performs the D2H copy and the "
    "host-budget compress+write for one victim at a time.")
SPILL_CHUNK_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.spill.chunkBytes", 8 << 20,
    "Frame size for disk spill files: the serialized batch streams "
    "through the compression codec in chunks of this many bytes, so "
    "compression overlaps the file write and unspill starts "
    "decompressing before the whole file is read.  <=0 writes one "
    "whole-batch frame.")
TASK_MAX_FAILURES = conf_int(
    "spark.rapids.task.maxFailures", 0,
    "Legacy cap on partition replay attempts, honored only when set "
    "explicitly on the session; otherwise "
    "spark.rapids.sql.tpu.retry.maxAttempts governs (fault.recovery)."
    "  0 defers to the retry ladder.")
SORT_STRING_PREFIX_BYTES = conf_int(
    "spark.rapids.sql.tpu.sort.stringPrefixBytes", 64,
    "Bytes of each string sort key encoded into u32 comparison words "
    "(kernels.sortkeys): order beyond the prefix is approximate "
    "(documented incompat), larger values cost sort bandwidth.")
METRICS_DETAIL = conf_bool(
    "spark.rapids.sql.tpu.metrics.detailEnabled", False,
    "Accurate device-time metrics: block on dispatched outputs so "
    "deviceTimeNs/shuffleWallNs measure real device execution instead of "
    "async-dispatch lower bounds.  Costs a host sync per dispatch (kills "
    "async overlap) — leave off outside measurement runs.")
OBS_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.obs.enabled", True,
    "Observability event bus (obs.events): instrumentation chokepoints "
    "emit span/instant events into a bounded per-query ring, folded into "
    "session.query_history() profiles.  Disabled cost is one branch per "
    "site; enabled cost is one lock-protected append per event.")
OBS_RING_MAX_EVENTS = conf_int(
    "spark.rapids.sql.tpu.obs.ring.maxEvents", 65536,
    "Event-ring capacity per query; once full, further events increment "
    "last_metrics['obsEventsDropped'] instead of growing memory.")
OBS_HISTORY_MAX = conf_int(
    "spark.rapids.sql.tpu.obs.history.maxQueries", 16,
    "Queries session.query_history() retains (oldest profiles — events "
    "included — are evicted past the bound).")
OBS_EVENT_LOG_DIR = conf_str(
    "spark.rapids.sql.tpu.obs.eventLogDir", "",
    "When set, each query appends its profile header + events as JSONL "
    "to <dir>/events-<pid>.jsonl (the Spark event-log analogue), the "
    "input to tools/rapidsprof.py.  Empty disables the log.")
OBS_TELEMETRY_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.obs.telemetry.enabled", True,
    "Continuous time-series telemetry (obs.timeseries): every obs span "
    "also folds into a process-wide fixed-interval aggregation ring "
    "(per-site count/wall/bytes plus sampled gauges), exported as "
    "Prometheus-style text and JSONL flushes to obs.eventLogDir "
    "(telemetry-<pid>.jsonl, the tools/rapidstop.py input).  Disabled "
    "cost is one branch per emit.")
OBS_TELEMETRY_INTERVAL_MS = conf_int(
    "spark.rapids.sql.tpu.obs.telemetry.intervalMs", 1000,
    "Width of one telemetry aggregation interval: spans landing in the "
    "same wall-clock bucket fold into one ring entry.  Smaller values "
    "give rapidstop finer live resolution at more ring turnover.")
OBS_TELEMETRY_MAX_INTERVALS = conf_int(
    "spark.rapids.sql.tpu.obs.telemetry.maxIntervals", 512,
    "Completed intervals the telemetry ring retains (drop-OLDEST past "
    "the bound — unlike the per-query event ring, the live view must "
    "keep the newest data; drops are counted and exported as a gauge).")
SERVE_MAX_CONCURRENCY = conf_int(
    "spark.rapids.sql.tpu.serve.maxConcurrency", 2,
    "Runner threads the serving scheduler (serve.scheduler) drives "
    "queries with — the number of session.execute calls in flight at "
    "once.  Device admission is still governed per dispatch by "
    "spark.rapids.sql.concurrentTpuTasks; this bounds host-side query "
    "parallelism (planning, staging, result assembly).")
SERVE_BATCH_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.serve.batch.enabled", True,
    "Micro-query batching (serve.batching): queued template queries "
    "that resolve to the same (plan fingerprint, schema, bucket) are "
    "coalesced into one dispatch — rows concatenated, one execute, "
    "results split back per caller bit-identically.  false executes "
    "every submission individually.")
SERVE_BATCH_MAX_DELAY_MS = conf_float(
    "spark.rapids.sql.tpu.serve.batch.maxDelayMs", 2.0,
    "How long a poppable micro-query may wait for coalescing partners "
    "before it dispatches alone — the latency ceiling batching is "
    "allowed to add.  0 batches only queries already queued together.")
SERVE_BATCH_MAX_QUERIES = conf_int(
    "spark.rapids.sql.tpu.serve.batch.maxQueries", 16,
    "Cap on queries coalesced into one micro-batch dispatch (bounds "
    "result-splitting latency and keeps the combined rows inside one "
    "bucket step).")
SERVE_DEADLINE_SEC = conf_float(
    "spark.rapids.sql.tpu.serve.deadlineSec", 0.0,
    "Default per-query deadline, measured from submit: on expiry the "
    "watchdog raises a NON_RETRYABLE DeadlineExceeded into the running "
    "query (no recovery replay — fail fast, neighbors unaffected).  "
    "Per-submission deadlines override; 0 disables.")
SERVE_PLAN_CACHE_MAX = conf_int(
    "spark.rapids.sql.tpu.serve.planCache.maxPlans", 256,
    "LRU bound on the process-wide shared plan/executable cache "
    "(serve.excache) — entries pin their physical plans and compiled "
    "stage programs; past the bound the least-recently-hit plan is "
    "dropped (its executables fall out with it).")
SERVE_BATCH_ADAPTIVE = conf_bool(
    "spark.rapids.sql.tpu.serve.batch.adaptive.enabled", False,
    "Adaptive micro-batch linger (serve.scheduler): instead of the "
    "static serve.batch.maxDelayMs window, size each linger from the "
    "telemetry ring's observed arrival rate — roughly two expected "
    "inter-arrival gaps, clamped to [0, maxDelayMs] — so an idle server "
    "dispatches immediately and a busy one waits just long enough for "
    "the stragglers that are statistically coming.  Falls back to the "
    "static window while telemetry is disabled.")
SERVE_FRONTEND_HOST = conf_str(
    "spark.rapids.sql.tpu.serve.frontend.host", "127.0.0.1",
    "Interface the serve front door (serve.frontend) binds.  The "
    "loopback default keeps the server private to the machine; bind a "
    "routable address only behind real network controls — the NDJSON "
    "protocol itself is unauthenticated.")
SERVE_FRONTEND_PORT = conf_int(
    "spark.rapids.sql.tpu.serve.frontend.port", 0,
    "TCP port of the serve front door.  0 (default) binds an ephemeral "
    "port; read it back from FrontDoorServer.port (tools/rapidsserve.py "
    "--server prints it on its banner line).")
SERVE_FRONTEND_MAX_LINE = conf_bytes(
    "spark.rapids.sql.tpu.serve.frontend.maxLineBytes", 64 << 20,
    "Largest single protocol line (one NDJSON request or response) the "
    "front door will read or a client will accept — bounds per-request "
    "buffering against a runaway or malicious peer.  Submissions "
    "carrying inline columnar data must fit under it.")
SERVE_RESULT_CACHE_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.serve.resultCache.enabled", True,
    "Front-door query result cache (serve.resultcache): final result "
    "sets keyed by (plan fingerprint, conf signature, input identity) "
    "kept as catalog-registered spillable batches, so a repeat query "
    "over unchanged inputs answers with zero compiles and zero "
    "dispatches.  Invalidation follows the fragment-cache rules: input "
    "mtime/size change, plan-relevant conf change, device-generation "
    "bump.  Per-request opt-out via the protocol's cache flag.")
SERVE_RESULT_CACHE_MAX_ENTRIES = conf_int(
    "spark.rapids.sql.tpu.serve.resultCache.maxEntries", 64,
    "LRU entry bound on the front-door result cache.")
SERVE_RESULT_CACHE_MAX_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.serve.resultCache.maxBytes", 128 << 20,
    "Payload-byte bound on the front-door result cache (device bytes of "
    "the cached result batches, LRU-evicted past the bound).  <= 0 "
    "disables insertion while still serving existing entries' "
    "invalidation semantics.")
SERVE_RESULT_CACHE_MIN_NS_PER_BYTE = conf_float(
    "spark.rapids.sql.tpu.serve.resultCache.minNsPerByte", 10.0,
    "Cost-weighted admission floor for the result cache: a result is "
    "cached only when its recorded compute wall (ns) >= this many ns "
    "per payload byte — cheap-to-recompute bulky results (e.g. a "
    "projection of the whole input) are not worth the HBM/spill "
    "footprint they would occupy.  0 admits everything.")
SERVE_ADMISSION_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.serve.admission.enabled", True,
    "Sentinel-driven admission control at the front door: before "
    "executing a deadlined query, consult the history store's "
    "median/MAD wall-time aggregate for its plan fingerprint and shed "
    "it (fail fast with DeadlineExceeded, counted per tenant as "
    "admissionShed) when the prediction already misses the deadline.  "
    "Inactive without a history dir; queries with no deadline or no "
    "baseline are never shed.")
SERVE_ADMISSION_MIN_RUNS = conf_int(
    "spark.rapids.sql.tpu.serve.admission.minRuns", 3,
    "Minimum history-store runs of a fingerprint before admission "
    "control trusts its wall-time prediction — below this an unknown "
    "query always executes (same thin-baseline rule as the regression "
    "sentinel).")
SERVE_ADMISSION_MAD_K = conf_float(
    "spark.rapids.sql.tpu.serve.admission.madK", 3.0,
    "Admission prediction = median + K * MAD of the fingerprint's "
    "recorded wall_ns: K widens the band so run-to-run noise does not "
    "shed queries that usually make their deadline.")
HISTORY_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.history.enabled", True,
    "Master switch for the query-intelligence layer (history/): the "
    "persistent plan-fingerprint statistics store, history-seeded "
    "planning and the cross-query fragment cache.  Takes effect only "
    "when spark.rapids.sql.tpu.history.dir is also set; false pins "
    "byte-for-byte the history-free plans and behavior.")
HISTORY_DIR = conf_str(
    "spark.rapids.sql.tpu.history.dir", "",
    "Directory of the persistent statistics store (history.store): each "
    "query appends one JSONL record of runtime facts keyed by plan "
    "fingerprint (per-exchange rows/bytes, skew, spill pressure, "
    "compile wall), read back lazily to seed later plans.  Empty "
    "disables the whole history subsystem.")
HISTORY_SEED_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.history.seed.enabled", True,
    "History-seeded planning (history.seeding): before first execution "
    "consult the store to right-size shuffle partition counts, hint the "
    "broadcast build side and pre-mark skewed partitions — AQE v1's "
    "runtime decisions applied up front.  A stats-absent or stats-stale "
    "store degrades to exactly the unseeded plan.")
HISTORY_FRAGMENTS_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.history.fragments.enabled", True,
    "Cross-query fragment cache (history.fragcache): materialized "
    "root-subtree outputs are kept as catalog-registered spillable "
    "batches keyed by (plan fingerprint, conf signature, input "
    "identity); a repeat query re-executes zero dispatches for the "
    "cached subtree.  Entries ride the device->host->disk spill tiers "
    "and are never pinned.")
HISTORY_MAX_AGE_SEC = conf_float(
    "spark.rapids.sql.tpu.history.maxAgeSec", 604800.0,
    "Staleness horizon for store records consulted by seeding: records "
    "older than this many seconds (or written under a different "
    "plan-relevant conf signature) are ignored, degrading to the "
    "unseeded plan.  <=0 disables the age check.")
HISTORY_STORE_MAX_RECORDS = conf_int(
    "spark.rapids.sql.tpu.history.store.maxRecords", 1024,
    "Per-store record bound honored by tools/rapidshist.py prune and "
    "the in-process loader: when the JSONL holds more records, only the "
    "newest per fingerprint (newest-first overall) are kept.")
HISTORY_FRAGMENTS_MAX_ENTRIES = conf_int(
    "spark.rapids.sql.tpu.history.fragments.maxEntries", 64,
    "LRU entry bound on the process-wide fragment cache; past it the "
    "least-recently-hit fragment's batches are closed and its catalog "
    "bytes released.")
HISTORY_FRAGMENTS_MAX_BYTES = conf_bytes(
    "spark.rapids.sql.tpu.history.fragments.maxBytes", 256 << 20,
    "Byte bound on fragment-cache residency (sum of cached batch "
    "payloads across tiers); inserting past it evicts least-recently-"
    "hit fragments first.  0 disables insertion.")
HISTORY_AGGREGATE_RUNS = conf_int(
    "spark.rapids.sql.tpu.history.aggregateRuns", 8,
    "Runs per plan fingerprint the statistics store folds into its "
    "robust aggregate (median/MAD of wall, dispatches, compiles, "
    "spill/shuffle bytes) — the regression sentinel's baseline and the "
    "ROADMAP 'aggregated over N runs instead of newest-wins' record "
    "shape.  Seeding still reads the newest record.")
SENTINEL_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.sentinel.enabled", True,
    "Cross-run regression sentinel (obs.sentinel): each query's fresh "
    "metrics are compared against the history store's median/MAD "
    "aggregate for its plan fingerprint; a guarded key outside its band "
    "emits a 'regression' obs instant and bumps "
    "last_metrics['regressionAlerts'].  Active only with "
    "spark.rapids.sql.tpu.history.dir set.")
SENTINEL_MIN_RUNS = conf_int(
    "spark.rapids.sql.tpu.sentinel.minRuns", 3,
    "Aggregated runs a fingerprint needs before the sentinel compares "
    "against it — below this the baseline is too thin to call a "
    "regression (cold caches and first-run compiles would all flag).")
SENTINEL_MAD_THRESHOLD = conf_float(
    "spark.rapids.sql.tpu.sentinel.madThreshold", 4.0,
    "Half-width of the sentinel's acceptance band in robust deviations: "
    "a guarded key regresses when value > median + threshold * "
    "max(MAD, 25% of median, key floor).  Larger values tolerate more "
    "run-to-run noise before alerting.")
PALLAS_STRINGS_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.pallas.strings.enabled", True,
    "Kernel-tier gate for the Pallas string contains/LIKE scan "
    "(kernels.pallas_strings): one fused pass over the byte buffer "
    "replacing the shifted-gather + searchsorted XLA formulation.  "
    "Engages on a real TPU backend only (or under pallas.interpret); "
    "anywhere else the bit-identical XLA fallback runs and "
    "pallasFallbackCount increments.  The deprecated "
    "SPARK_RAPIDS_PALLAS_STRINGS env var (0/false=off, interp=interpret) "
    "is honored for one release when this conf is not explicitly set.")
PALLAS_GATHER_SCATTER_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.pallas.gatherScatter.enabled", True,
    "Kernel-tier gate for the segmented k-way gather/scatter Pallas "
    "kernel: one pass per output block walking the per-input segment "
    "table replaces the k drop-mode scatter chain inside concat_kway / "
    "gather_segments_kway (rows and bytes, honoring the live-bytes "
    "window so take_head-truncated inputs cannot leak stale tail "
    "bytes).  TPU-only with automatic bit-identical XLA fallback; "
    "unsupported element dtypes always take the fallback silently.")
PALLAS_JOIN_PROBE_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.pallas.joinProbe.enabled", True,
    "Kernel-tier gate for the hash-join probe Pallas kernel: when the "
    "sorted build-side arrays fit pallas.vmemBudgetBytes, one fused "
    "kernel performs both searchsorted passes, candidate expansion and "
    "the exact-match word verify of join_pairs_static, emitting the "
    "same capacity-bucketed pair buffers (hash_join_static and the "
    "mesh-fused pipeline consume it unchanged).  TPU-only with "
    "automatic bit-identical XLA fallback.")
PALLAS_STRING_HASH_ENABLED = conf_bool(
    "spark.rapids.sql.tpu.pallas.stringHash.enabled", True,
    "Kernel-tier gate for the string key-hash Pallas kernel: a "
    "row-blocked Horner pass over the byte buffer with segment "
    "boundaries from the offsets replaces the pow-table + segment-sum "
    "XLA formulation of string_hash2 (sort/join key hashing).  "
    "TPU-only with automatic bit-identical XLA fallback.")
PALLAS_INTERPRET = conf_bool(
    "spark.rapids.sql.tpu.pallas.interpret", False,
    "Debug: run every engaged kernel-tier Pallas kernel in interpret "
    "mode (pure XLA emulation of the kernel program) so CPU-backend "
    "tests can pin bit-identity against the XLA fallbacks.  Orders of "
    "magnitude slower than compiled kernels — never enable in "
    "production.")
PALLAS_VMEM_BUDGET = conf_bytes(
    "spark.rapids.sql.tpu.pallas.vmemBudgetBytes", 8 << 20,
    "VMEM residency budget shared by the kernel tier: a kernel whose "
    "resident working set (e.g. the join probe's sorted build arrays) "
    "exceeds this many bytes falls back to the XLA formulation and "
    "counts into pallasFallbackCount.  Sized well under a TPU core's "
    "~16 MB VMEM to leave room for per-block buffers.")


def registry() -> List[ConfEntry]:
    return sorted(_REGISTRY.values(), key=lambda e: e.key)


def generate_docs() -> str:
    """Markdown doc generation (analogue of RapidsConf.main -> docs/configs.md)."""
    lines = [
        "# spark_rapids_tpu configuration",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for e in registry():
        if not e.internal:
            lines.append(f"| `{e.key}` | {e.default} | {e.doc} |")
    return "\n".join(lines) + "\n"


#: Process-wide active configuration (sessions may carry their own copies).
conf = RapidsConf()
