"""Python UDF expressions.

``PythonUDF`` (row-at-a-time) and ``PandasUDF`` (vectorized over numpy/
pandas) evaluate host-side only; on a TPU plan the projection containing one
falls back to CPU, which — given the automatic device<->host transitions —
reproduces the reference's GpuArrowEvalPythonExec data flow
(GpuArrowEvalPythonExec.scala:484): device batch -> host columnar -> python
-> staged back to the device, with the semaphore released while python runs.

When ``spark.rapids.sql.udfCompiler.enabled`` is set, the planner first
tries :func:`spark_rapids_tpu.udf.compiler.compile_udf` to decompile the
function's bytecode into engine expressions so the whole projection stays on
the TPU (udf-compiler analogue).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import CpuVal, Expression


class PythonUDF(Expression):
    def __init__(self, fn: Callable, return_type: T.DataType,
                 *children: Expression, name: Optional[str] = None):
        self.fn = fn
        self.children = tuple(children)
        self.dtype = return_type
        self.nullable = True
        self.udf_name = name or getattr(fn, "__name__", "udf")

    def with_children(self, children):
        return type(self)(self.fn, self.dtype, *children,
                          name=self.udf_name)

    @property
    def name(self):
        return f"PythonUDF({self.udf_name})"

    def tpu_supported(self, conf):
        return ("python row UDF runs via the host Arrow path; enable "
                "spark.rapids.sql.udfCompiler.enabled to attempt columnar "
                "compilation")

    def cpu_eval(self, ctx) -> CpuVal:
        args = [c.cpu_eval(ctx) for c in self.children]
        n = ctx.num_rows
        out = np.empty(n, dtype=object)
        validity = np.zeros(n, dtype=np.bool_)
        arg_lists = [a.to_column().to_list() for a in args]
        for i in range(n):
            r = self.fn(*[al[i] for al in arg_lists])
            if r is not None:
                out[i] = r
                validity[i] = True
        if self.dtype.is_string:
            values = np.array(["" if not v else str(o)
                               for o, v in zip(out, validity)], dtype=object)
        else:
            values = np.array([o if v else 0
                               for o, v in zip(out, validity)],
                              dtype=self.dtype.np_dtype)
        return CpuVal(self.dtype, values, validity)


class PandasUDF(PythonUDF):
    """Vectorized UDF: fn(pandas.Series...) -> pandas.Series."""

    def cpu_eval(self, ctx) -> CpuVal:
        import pandas as pd
        args = [c.cpu_eval(ctx) for c in self.children]
        series = [pd.Series(a.to_column().to_list()) for a in args]
        res = self.fn(*series)
        if not isinstance(res, pd.Series):
            res = pd.Series(res)
        validity = ~res.isna().to_numpy()
        if self.dtype.is_string:
            values = np.array([
                "" if not v else str(x)
                for x, v in zip(res.tolist(), validity)], dtype=object)
        else:
            filled = res.fillna(0)
            values = filled.to_numpy().astype(self.dtype.np_dtype)
        return CpuVal(self.dtype, values, validity)
