"""NULL-handling expressions (reference: nullExpressions.scala, 297 LoC)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    CpuVal, DevVal, Expression, UnaryExpression, cast_cpu, cast_dev,
)


class IsNull(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = False

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.BOOLEAN, ~v.validity, jnp.ones_like(v.validity))

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.BOOLEAN, ~v.validity, np.ones(len(v.validity), np.bool_))


class IsNotNull(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = False

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.BOOLEAN, v.validity, jnp.ones_like(v.validity))

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.BOOLEAN, v.validity.copy(),
                      np.ones(len(v.validity), np.bool_))


class IsNan(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = False

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        data = jnp.isnan(v.data) & v.validity
        return DevVal(T.BOOLEAN, data, jnp.ones_like(v.validity))

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        data = np.isnan(v.values.astype(np.float64)) & v.validity
        return CpuVal(T.BOOLEAN, data, np.ones(len(v.validity), np.bool_))


class Coalesce(Expression):
    def __init__(self, *children: Expression):
        assert children
        self.children = tuple(children)
        self.dtype = children[0].dtype
        for c in children[1:]:
            self.dtype = T.promote(self.dtype, c.dtype)
        self.nullable = all(c.nullable for c in children)

    def with_children(self, children):
        return Coalesce(*children)

    def tpu_supported(self, conf):
        if self.dtype.is_string:
            return "coalesce over strings not yet supported on TPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        acc = cast_dev(self.children[0].tpu_eval(ctx), self.dtype)
        data, validity = acc.data, acc.validity
        for c in self.children[1:]:
            v = cast_dev(c.tpu_eval(ctx), self.dtype)
            data = jnp.where(validity, data, v.data)
            validity = validity | v.validity
        return DevVal(self.dtype, data, validity)

    def cpu_eval(self, ctx) -> CpuVal:
        acc = self.children[0].cpu_eval(ctx)
        if self.dtype.is_string:
            values = acc.values.copy()
            validity = acc.validity.copy()
            for c in self.children[1:]:
                v = c.cpu_eval(ctx)
                take = ~validity & v.validity
                values[take] = v.values[take]
                validity |= v.validity
            return CpuVal(self.dtype, values, validity)
        acc = cast_cpu(acc, self.dtype)
        data, validity = acc.values.copy(), acc.validity.copy()
        for c in self.children[1:]:
            v = cast_cpu(c.cpu_eval(ctx), self.dtype)
            data = np.where(validity, data, v.values)
            validity = validity | v.validity
        return CpuVal(self.dtype, data.astype(self.dtype.np_dtype), validity)


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN else a."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)
        self.dtype = T.DOUBLE
        self.nullable = left.nullable or right.nullable

    def with_children(self, children):
        return NaNvl(*children)

    def tpu_eval(self, ctx) -> DevVal:
        a = cast_dev(self.children[0].tpu_eval(ctx), T.DOUBLE)
        b = cast_dev(self.children[1].tpu_eval(ctx), T.DOUBLE)
        nan = jnp.isnan(a.data)
        data = jnp.where(nan, b.data, a.data)
        validity = jnp.where(nan, b.validity, a.validity)
        return DevVal(T.DOUBLE, data, validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a = cast_cpu(self.children[0].cpu_eval(ctx), T.DOUBLE)
        b = cast_cpu(self.children[1].cpu_eval(ctx), T.DOUBLE)
        nan = np.isnan(a.values)
        data = np.where(nan, b.values, a.values)
        validity = np.where(nan, b.validity, a.validity)
        return CpuVal(T.DOUBLE, data, validity.astype(np.bool_))


class AtLeastNNonNulls(Expression):
    """True when >= n of the children are non-null (and non-NaN for
    floats) — the predicate behind DataFrame.dropna (Spark
    AtLeastNNonNulls)."""

    def __init__(self, n: int, *children: Expression):
        self.n = int(n)
        self.children = tuple(children)
        self._resolve_type()

    def with_children(self, children):
        return AtLeastNNonNulls(self.n, *children)

    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = False

    def tpu_eval(self, ctx) -> DevVal:
        total = None
        for c in self.children:
            v = c.tpu_eval(ctx)
            valid = v.validity
            if c.dtype in (T.FLOAT, T.DOUBLE):
                safe = jnp.where(valid, v.data, 0)
                valid = valid & ~jnp.isnan(safe)
            cnt = valid.astype(jnp.int32)
            total = cnt if total is None else total + cnt
        return DevVal(T.BOOLEAN, total >= self.n,
                      jnp.ones_like(total, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        total = None
        for c in self.children:
            v = c.cpu_eval(ctx)
            valid = v.validity
            if c.dtype in (T.FLOAT, T.DOUBLE):
                safe = np.where(valid, v.values, 0.0)
                valid = valid & ~np.isnan(safe)
            cnt = valid.astype(np.int32)
            total = cnt if total is None else total + cnt
        return CpuVal(T.BOOLEAN, total >= self.n,
                      np.ones_like(total, dtype=np.bool_))
