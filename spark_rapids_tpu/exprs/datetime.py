"""Date/time expressions (reference: datetimeExpressions.scala, 560 LoC).

Calendar decomposition uses the branch-free civil-from-days algorithm
(integer-only, fully vectorizable), identical code shape for jnp and numpy —
no data-dependent control flow, so it lowers cleanly to XLA.
Timestamps are UTC microseconds (the reference requires UTC too).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, CpuVal, DevVal, Expression, UnaryExpression,
)

MICROS_PER_DAY = 86_400_000_000


def civil_from_days(days, xp):
    """days-since-epoch -> (year, month, day); xp is jnp or np."""
    days = days.astype(xp.int64)
    z = days + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = xp.floor_divide(
        doe - xp.floor_divide(doe, 1460) + xp.floor_divide(doe, 36524)
        - xp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + xp.floor_divide(yoe, 4) - xp.floor_divide(yoe, 100))
    mp = xp.floor_divide(5 * doy + 2, 153)
    d = doy - xp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y, m, d, xp):
    y = y.astype(xp.int64) - (m <= 2)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + xp.where(m > 2, -3, 9)
    doy = xp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + xp.floor_divide(yoe, 4) - xp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _days_of(v, xp):
    if v.dtype == T.TIMESTAMP:
        data = v.values if xp is np else v.data
        return xp.floor_divide(data, MICROS_PER_DAY)
    return (v.values if xp is np else v.data)


class _DatePart(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def _part(self, days, xp):
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        out = self._part(_days_of(v, jnp), jnp)
        return DevVal(T.INT, out.astype(jnp.int32), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = self._part(_days_of(v, np), np)
        return CpuVal(T.INT, out.astype(np.int32), v.validity)


class Year(_DatePart):
    def _part(self, days, xp):
        y, _, _ = civil_from_days(days, xp)
        return y


class Month(_DatePart):
    def _part(self, days, xp):
        _, m, _ = civil_from_days(days, xp)
        return m


class DayOfMonth(_DatePart):
    def _part(self, days, xp):
        _, _, d = civil_from_days(days, xp)
        return d


class DayOfWeek(_DatePart):
    """1 = Sunday ... 7 = Saturday (Spark semantics)."""

    def _part(self, days, xp):
        # 1970-01-01 was a Thursday (dow=5 in Spark numbering).
        return xp.mod(days.astype(xp.int64) + 4, 7) + 1


class DayOfYear(_DatePart):
    def _part(self, days, xp):
        y, _, _ = civil_from_days(days, xp)
        jan1 = days_from_civil(y, xp.full_like(y, 1), xp.full_like(y, 1), xp)
        return days.astype(xp.int64) - jan1 + 1


class Quarter(_DatePart):
    def _part(self, days, xp):
        _, m, _ = civil_from_days(days, xp)
        return xp.floor_divide(m - 1, 3) + 1


class _TimePart(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def _part(self, micros, xp):
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        tod = jnp.mod(v.data, MICROS_PER_DAY)
        return DevVal(T.INT, self._part(tod, jnp).astype(jnp.int32), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        tod = np.mod(v.values, MICROS_PER_DAY)
        return CpuVal(T.INT, self._part(tod, np).astype(np.int32), v.validity)


class Hour(_TimePart):
    def _part(self, tod, xp):
        return xp.floor_divide(tod, 3_600_000_000)


class Minute(_TimePart):
    def _part(self, tod, xp):
        return xp.mod(xp.floor_divide(tod, 60_000_000), 60)


class Second(_TimePart):
    def _part(self, tod, xp):
        return xp.mod(xp.floor_divide(tod, 1_000_000), 60)


class DateAdd(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        return DevVal(T.DATE, (a.data + b.data.astype(jnp.int32)).astype(jnp.int32),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        return CpuVal(T.DATE,
                      (a.values + b.values.astype(np.int32)).astype(np.int32),
                      a.validity & b.validity)


class DateSub(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        return DevVal(T.DATE, (a.data - b.data.astype(jnp.int32)).astype(jnp.int32),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        return CpuVal(T.DATE,
                      (a.values - b.values.astype(np.int32)).astype(np.int32),
                      a.validity & b.validity)


class DateDiff(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        return DevVal(T.INT, (a.data - b.data).astype(jnp.int32),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        return CpuVal(T.INT, (a.values - b.values).astype(np.int32),
                      a.validity & b.validity)


class LastDay(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = self.child.nullable

    @staticmethod
    def _last_day(days, xp):
        y, m, _ = civil_from_days(days, xp)
        ny = y + (m == 12)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, xp.full_like(ny, 1), xp)
        return first_next - 1

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.DATE, self._last_day(v.data, jnp).astype(jnp.int32),
                      v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.DATE, self._last_day(v.values, np).astype(np.int32),
                      v.validity)


class UnixTimestamp(UnaryExpression):
    """unix_timestamp(ts|date) -> LONG seconds since epoch
    (GpuUnixTimestamp, datetimeExpressions.scala).  String parsing runs on
    CPU (default 'yyyy-MM-dd HH:mm:ss' format only)."""

    def _resolve_type(self):
        self.dtype = T.LONG
        self.nullable = True

    def tpu_supported(self, conf):
        if self.child.dtype == T.STRING:
            return "unix_timestamp string parsing runs on CPU"
        if self.child.dtype not in (T.DATE, T.TIMESTAMP):
            return f"unix_timestamp needs date/timestamp/string, " \
                f"got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        if self.child.dtype == T.DATE:
            data = v.data.astype(jnp.int64) * 86_400
        else:
            # floor division keeps pre-epoch instants correct
            data = jnp.floor_divide(v.data.astype(jnp.int64), 1_000_000)
        return DevVal(T.LONG, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        if self.child.dtype == T.DATE:
            return CpuVal(T.LONG, v.values.astype(np.int64) * 86_400,
                          v.validity)
        if self.child.dtype == T.TIMESTAMP:
            return CpuVal(T.LONG,
                          np.floor_divide(v.values.astype(np.int64),
                                          1_000_000), v.validity)
        # string: default Spark format
        import datetime as _dt
        out = np.zeros(len(v.values), dtype=np.int64)
        valid = np.array(v.validity, copy=True)
        for i, (s, ok) in enumerate(zip(v.values, v.validity)):
            if not ok:
                continue
            try:
                t = _dt.datetime.strptime(str(s), "%Y-%m-%d %H:%M:%S")
                out[i] = int(t.replace(tzinfo=_dt.timezone.utc).timestamp())
            except ValueError:
                valid[i] = False
        return CpuVal(T.LONG, out, valid)


class FromUnixTime(UnaryExpression):
    """from_unixtime(seconds) -> 'yyyy-MM-dd HH:mm:ss' string
    (GpuFromUnixTime).  Only the default format runs on TPU; the output is
    fixed-width so the byte buffer is a [cap, 19] digit computation."""

    FMT = "yyyy-MM-dd HH:mm:ss"

    def __init__(self, child, fmt: str = FMT):
        self.fmt = str(fmt)
        super().__init__(child)

    def with_children(self, children):
        return FromUnixTime(children[0], self.fmt)

    def _resolve_type(self):
        self.dtype = T.STRING
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        if self.fmt != self.FMT:
            return f"from_unixtime format {self.fmt!r} runs on CPU"
        if not self.child.dtype.is_integral:
            return f"from_unixtime needs integral seconds, " \
                f"got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        cap = ctx.capacity
        secs = v.data.astype(jnp.int64)
        days = jnp.floor_divide(secs, 86_400)
        tod = secs - days * 86_400
        y, m, d = civil_from_days(days, jnp)
        hh = tod // 3_600
        mi = (tod // 60) % 60
        ss = tod % 60
        # fixed-width 19-byte rows: columns of digits, flattened
        def dig(x, p):
            return ((x // p) % 10 + 48).astype(jnp.uint8)
        cols = [
            dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1),
            jnp.full(cap, 45, jnp.uint8),
            dig(m, 10), dig(m, 1),
            jnp.full(cap, 45, jnp.uint8),
            dig(d, 10), dig(d, 1),
            jnp.full(cap, 32, jnp.uint8),
            dig(hh, 10), dig(hh, 1),
            jnp.full(cap, 58, jnp.uint8),
            dig(mi, 10), dig(mi, 1),
            jnp.full(cap, 58, jnp.uint8),
            dig(ss, 10), dig(ss, 1),
        ]
        mat = jnp.stack(cols, axis=1)  # [cap, 19]
        live = v.validity & ctx.row_mask
        lens = jnp.where(live, 19, 0).astype(jnp.int32)
        offsets = jnp.concatenate([
            jnp.zeros(1, jnp.int32), jnp.cumsum(lens).astype(jnp.int32)])
        nbytes = cap * 19
        pos = jnp.arange(nbytes, dtype=jnp.int32)
        row = jnp.clip(jnp.searchsorted(offsets[1:], pos, side="right"),
                       0, cap - 1).astype(jnp.int32)
        within = jnp.clip(pos - offsets[row], 0, 18)
        data = jnp.where(pos < offsets[-1], mat[row, within], 0)
        return DevVal(T.STRING, data.astype(jnp.uint8), v.validity, offsets)

    def cpu_eval(self, ctx) -> CpuVal:
        import datetime as _dt
        v = self.child.cpu_eval(ctx)
        fmt = (self.fmt.replace("yyyy", "%Y").replace("MM", "%m")
               .replace("dd", "%d").replace("HH", "%H")
               .replace("mm", "%M").replace("ss", "%S"))
        out = np.empty(len(v.values), dtype=object)
        for i, (s, ok) in enumerate(zip(v.values, v.validity)):
            if not ok:
                out[i] = ""
                continue
            t = _dt.datetime.fromtimestamp(int(s), tz=_dt.timezone.utc)
            out[i] = t.strftime(fmt)
        return CpuVal(T.STRING, out, v.validity)


class WeekDay(_DatePart):
    """weekday(date): 0 = Monday .. 6 = Sunday (Spark WeekDay;
    DayOfWeek is the 1=Sunday variant)."""

    def _part(self, days, xp):
        return xp.mod(days + 3, 7)


class ToUnixTimestamp(UnixTimestamp):
    """to_unix_timestamp: same semantics as unix_timestamp
    (datetimeExpressions' ToUnixTimestamp vs UnixTimestamp)."""


class TimeAdd(UnaryExpression):
    """timestamp + a literal interval (Spark TimeAdd with a
    CalendarInterval of micros; month intervals are not representable as
    a fixed duration and stay unsupported, as in the reference's
    GpuTimeAdd which rejects months)."""

    def __init__(self, child: Expression, interval_micros: int):
        self.interval_micros = int(interval_micros)
        super().__init__(child)

    def with_children(self, children):
        return TimeAdd(children[0], self.interval_micros)

    def _resolve_type(self):
        if self.child.dtype not in (T.TIMESTAMP, T.NULL):
            raise TypeError(f"TimeAdd needs a timestamp, "
                            f"got {self.child.dtype}")
        self.dtype = T.TIMESTAMP
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.TIMESTAMP, v.data + self.interval_micros,
                      v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.TIMESTAMP, v.values + self.interval_micros,
                      v.validity)
