"""Date/time expressions (reference: datetimeExpressions.scala, 560 LoC).

Calendar decomposition uses the branch-free civil-from-days algorithm
(integer-only, fully vectorizable), identical code shape for jnp and numpy —
no data-dependent control flow, so it lowers cleanly to XLA.
Timestamps are UTC microseconds (the reference requires UTC too).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, CpuVal, DevVal, Expression, UnaryExpression,
)

MICROS_PER_DAY = 86_400_000_000


def civil_from_days(days, xp):
    """days-since-epoch -> (year, month, day); xp is jnp or np."""
    days = days.astype(xp.int64)
    z = days + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = xp.floor_divide(
        doe - xp.floor_divide(doe, 1460) + xp.floor_divide(doe, 36524)
        - xp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + xp.floor_divide(yoe, 4) - xp.floor_divide(yoe, 100))
    mp = xp.floor_divide(5 * doy + 2, 153)
    d = doy - xp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y, m, d, xp):
    y = y.astype(xp.int64) - (m <= 2)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + xp.where(m > 2, -3, 9)
    doy = xp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + xp.floor_divide(yoe, 4) - xp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _days_of(v, xp):
    if v.dtype == T.TIMESTAMP:
        data = v.values if xp is np else v.data
        return xp.floor_divide(data, MICROS_PER_DAY)
    return (v.values if xp is np else v.data)


class _DatePart(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def _part(self, days, xp):
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        out = self._part(_days_of(v, jnp), jnp)
        return DevVal(T.INT, out.astype(jnp.int32), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = self._part(_days_of(v, np), np)
        return CpuVal(T.INT, out.astype(np.int32), v.validity)


class Year(_DatePart):
    def _part(self, days, xp):
        y, _, _ = civil_from_days(days, xp)
        return y


class Month(_DatePart):
    def _part(self, days, xp):
        _, m, _ = civil_from_days(days, xp)
        return m


class DayOfMonth(_DatePart):
    def _part(self, days, xp):
        _, _, d = civil_from_days(days, xp)
        return d


class DayOfWeek(_DatePart):
    """1 = Sunday ... 7 = Saturday (Spark semantics)."""

    def _part(self, days, xp):
        # 1970-01-01 was a Thursday (dow=5 in Spark numbering).
        return xp.mod(days.astype(xp.int64) + 4, 7) + 1


class DayOfYear(_DatePart):
    def _part(self, days, xp):
        y, _, _ = civil_from_days(days, xp)
        jan1 = days_from_civil(y, xp.full_like(y, 1), xp.full_like(y, 1), xp)
        return days.astype(xp.int64) - jan1 + 1


class Quarter(_DatePart):
    def _part(self, days, xp):
        _, m, _ = civil_from_days(days, xp)
        return xp.floor_divide(m - 1, 3) + 1


class _TimePart(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def _part(self, micros, xp):
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        tod = jnp.mod(v.data, MICROS_PER_DAY)
        return DevVal(T.INT, self._part(tod, jnp).astype(jnp.int32), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        tod = np.mod(v.values, MICROS_PER_DAY)
        return CpuVal(T.INT, self._part(tod, np).astype(np.int32), v.validity)


class Hour(_TimePart):
    def _part(self, tod, xp):
        return xp.floor_divide(tod, 3_600_000_000)


class Minute(_TimePart):
    def _part(self, tod, xp):
        return xp.mod(xp.floor_divide(tod, 60_000_000), 60)


class Second(_TimePart):
    def _part(self, tod, xp):
        return xp.mod(xp.floor_divide(tod, 1_000_000), 60)


class DateAdd(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        return DevVal(T.DATE, (a.data + b.data.astype(jnp.int32)).astype(jnp.int32),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        return CpuVal(T.DATE,
                      (a.values + b.values.astype(np.int32)).astype(np.int32),
                      a.validity & b.validity)


class DateSub(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        return DevVal(T.DATE, (a.data - b.data.astype(jnp.int32)).astype(jnp.int32),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        return CpuVal(T.DATE,
                      (a.values - b.values.astype(np.int32)).astype(np.int32),
                      a.validity & b.validity)


class DateDiff(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        return DevVal(T.INT, (a.data - b.data).astype(jnp.int32),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        return CpuVal(T.INT, (a.values - b.values).astype(np.int32),
                      a.validity & b.validity)


class LastDay(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = self.child.nullable

    @staticmethod
    def _last_day(days, xp):
        y, m, _ = civil_from_days(days, xp)
        ny = y + (m == 12)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, xp.full_like(ny, 1), xp)
        return first_next - 1

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.DATE, self._last_day(v.data, jnp).astype(jnp.int32),
                      v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.DATE, self._last_day(v.values, np).astype(np.int32),
                      v.validity)


class UnixTimestamp(UnaryExpression):
    """unix_timestamp(ts|date) -> LONG seconds since epoch
    (GpuUnixTimestamp, datetimeExpressions.scala).  String parsing runs on
    CPU (default 'yyyy-MM-dd HH:mm:ss' format only)."""

    def _resolve_type(self):
        self.dtype = T.LONG
        self.nullable = True

    def tpu_supported(self, conf):
        if self.child.dtype == T.STRING:
            return "unix_timestamp string parsing runs on CPU"
        if self.child.dtype not in (T.DATE, T.TIMESTAMP):
            return f"unix_timestamp needs date/timestamp/string, " \
                f"got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        if self.child.dtype == T.DATE:
            data = v.data.astype(jnp.int64) * 86_400
        else:
            # floor division keeps pre-epoch instants correct
            data = jnp.floor_divide(v.data.astype(jnp.int64), 1_000_000)
        return DevVal(T.LONG, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        if self.child.dtype == T.DATE:
            return CpuVal(T.LONG, v.values.astype(np.int64) * 86_400,
                          v.validity)
        if self.child.dtype == T.TIMESTAMP:
            return CpuVal(T.LONG,
                          np.floor_divide(v.values.astype(np.int64),
                                          1_000_000), v.validity)
        # string: default Spark format
        import datetime as _dt
        out = np.zeros(len(v.values), dtype=np.int64)
        valid = np.array(v.validity, copy=True)
        for i, (s, ok) in enumerate(zip(v.values, v.validity)):
            if not ok:
                continue
            try:
                t = _dt.datetime.strptime(str(s), "%Y-%m-%d %H:%M:%S")
                out[i] = int(t.replace(tzinfo=_dt.timezone.utc).timestamp())
            except ValueError:
                valid[i] = False
        return CpuVal(T.LONG, out, valid)


class FromUnixTime(UnaryExpression):
    """from_unixtime(seconds) -> 'yyyy-MM-dd HH:mm:ss' string
    (GpuFromUnixTime).  Only the default format runs on TPU; the output is
    fixed-width so the byte buffer is a [cap, 19] digit computation."""

    FMT = "yyyy-MM-dd HH:mm:ss"

    def __init__(self, child, fmt: str = FMT):
        self.fmt = str(fmt)
        super().__init__(child)

    def with_children(self, children):
        return FromUnixTime(children[0], self.fmt)

    def _resolve_type(self):
        self.dtype = T.STRING
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        if self.fmt != self.FMT:
            return f"from_unixtime format {self.fmt!r} runs on CPU"
        if not self.child.dtype.is_integral:
            return f"from_unixtime needs integral seconds, " \
                f"got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        cap = ctx.capacity
        secs = v.data.astype(jnp.int64)
        days = jnp.floor_divide(secs, 86_400)
        tod = secs - days * 86_400
        y, m, d = civil_from_days(days, jnp)
        hh = tod // 3_600
        mi = (tod // 60) % 60
        ss = tod % 60
        # fixed-width 19-byte rows: columns of digits, flattened
        def dig(x, p):
            return ((x // p) % 10 + 48).astype(jnp.uint8)
        cols = [
            dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1),
            jnp.full(cap, 45, jnp.uint8),
            dig(m, 10), dig(m, 1),
            jnp.full(cap, 45, jnp.uint8),
            dig(d, 10), dig(d, 1),
            jnp.full(cap, 32, jnp.uint8),
            dig(hh, 10), dig(hh, 1),
            jnp.full(cap, 58, jnp.uint8),
            dig(mi, 10), dig(mi, 1),
            jnp.full(cap, 58, jnp.uint8),
            dig(ss, 10), dig(ss, 1),
        ]
        return _emit_fixed_width(cols, v.validity, ctx)

    def cpu_eval(self, ctx) -> CpuVal:
        import datetime as _dt
        v = self.child.cpu_eval(ctx)
        fmt = (self.fmt.replace("yyyy", "%Y").replace("MM", "%m")
               .replace("dd", "%d").replace("HH", "%H")
               .replace("mm", "%M").replace("ss", "%S"))
        out = np.empty(len(v.values), dtype=object)
        for i, (s, ok) in enumerate(zip(v.values, v.validity)):
            if not ok:
                out[i] = ""
                continue
            t = _dt.datetime.fromtimestamp(int(s), tz=_dt.timezone.utc)
            out[i] = t.strftime(fmt)
        return CpuVal(T.STRING, out, v.validity)


class WeekDay(_DatePart):
    """weekday(date): 0 = Monday .. 6 = Sunday (Spark WeekDay;
    DayOfWeek is the 1=Sunday variant)."""

    def _part(self, days, xp):
        return xp.mod(days + 3, 7)


class ToUnixTimestamp(UnixTimestamp):
    """to_unix_timestamp: same semantics as unix_timestamp
    (datetimeExpressions' ToUnixTimestamp vs UnixTimestamp)."""


class TimeAdd(UnaryExpression):
    """timestamp + a literal interval (Spark TimeAdd with a
    CalendarInterval of micros; month intervals are not representable as
    a fixed duration and stay unsupported, as in the reference's
    GpuTimeAdd which rejects months)."""

    def __init__(self, child: Expression, interval_micros: int):
        self.interval_micros = int(interval_micros)
        super().__init__(child)

    def with_children(self, children):
        return TimeAdd(children[0], self.interval_micros)

    def _resolve_type(self):
        if self.child.dtype not in (T.TIMESTAMP, T.NULL):
            raise TypeError(f"TimeAdd needs a timestamp, "
                            f"got {self.child.dtype}")
        self.dtype = T.TIMESTAMP
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.TIMESTAMP, v.data + self.interval_micros,
                      v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.TIMESTAMP, v.values + self.interval_micros,
                      v.validity)


def _emit_fixed_width(cols, validity, ctx) -> DevVal:
    """Materialize a fixed-width-per-row string column from byte columns
    (shared by the device date/time renderers)."""
    cap = ctx.capacity
    width = len(cols)
    mat = jnp.stack(cols, axis=1)  # [cap, width]
    live = validity & ctx.row_mask
    lens = jnp.where(live, width, 0).astype(jnp.int32)
    offsets = jnp.concatenate([
        jnp.zeros(1, jnp.int32), jnp.cumsum(lens).astype(jnp.int32)])
    pos = jnp.arange(cap * width, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(offsets[1:], pos, side="right"),
                   0, cap - 1).astype(jnp.int32)
    within = jnp.clip(pos - offsets[row], 0, width - 1)
    data = jnp.where(pos < offsets[-1], mat[row, within], 0)
    return DevVal(T.STRING, data.astype(jnp.uint8), validity, offsets)


_JAVA_TO_STRPTIME = [("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
                     ("HH", "%H"), ("mm", "%M"), ("ss", "%S")]
_JAVA_TOKENS = {j for j, _ in _JAVA_TO_STRPTIME}


def _java_fmt_to_strptime(fmt: str) -> str:
    """Translate the supported java-format subset; reject anything with
    letter tokens outside it (a blind replace would mangle e.g. MMM
    into %mM and silently NULL every row)."""
    import re
    for tok in re.findall(r"[A-Za-z]+", fmt):
        if tok not in _JAVA_TOKENS:
            raise ValueError(
                f"unsupported date format token {tok!r} in {fmt!r}; "
                f"supported: {sorted(_JAVA_TOKENS)}")
    out = fmt
    for j, p in _JAVA_TO_STRPTIME:
        out = out.replace(j, p)
    return out


def _render_strptime(dt, pat: str) -> str:
    """Zero-padded rendering of the supported strptime subset (glibc
    strftime does not pad years < 1000, so it cannot be the canonical
    form)."""
    return (pat.replace("%Y", f"{dt.year:04d}")
            .replace("%m", f"{dt.month:02d}")
            .replace("%d", f"{dt.day:02d}")
            .replace("%H", f"{dt.hour:02d}")
            .replace("%M", f"{dt.minute:02d}")
            .replace("%S", f"{dt.second:02d}"))


class ToDate(UnaryExpression):
    """to_date(str[, fmt]) -> DATE; unparseable strings become NULL
    (Spark GetDate/ParseToDate).  The default 'yyyy-MM-dd' format parses
    on device (fixed-position digit extraction over the byte buffer);
    other formats run on CPU via strptime."""

    FMT = "yyyy-MM-dd"

    def __init__(self, child: Expression, fmt: str = FMT):
        self.fmt = str(fmt)
        super().__init__(child)

    def with_children(self, children):
        return ToDate(children[0], self.fmt)

    def _resolve_type(self):
        self.dtype = T.DATE
        self.nullable = True

    def tpu_supported(self, conf):
        if self.child.dtype is T.DATE:
            return None
        if not (self.child.dtype.is_string or
                self.child.dtype is T.NULL):
            return f"to_date over {self.child.dtype} runs on CPU"
        if self.fmt != self.FMT:
            return f"to_date format {self.fmt!r} runs on CPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        if v.dtype is T.DATE:
            return v
        if v.offsets is None:  # NULL-typed literal input
            zeros = jnp.zeros(ctx.capacity, dtype=jnp.int32)
            return DevVal(T.DATE, zeros,
                          jnp.zeros(ctx.capacity, dtype=jnp.bool_))
        nbytes = int(v.data.shape[0])
        starts = v.offsets[:-1].astype(jnp.int32)
        lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        idx = jnp.clip(starts[:, None] +
                       jnp.arange(10, dtype=jnp.int32)[None, :],
                       0, max(nbytes - 1, 0))
        ch = v.data[idx].astype(jnp.int32)          # [cap, 10]
        digit = (ch >= 48) & (ch <= 57)
        ok = (lens == 10)
        for p in (0, 1, 2, 3, 5, 6, 8, 9):
            ok = ok & digit[:, p]
        ok = ok & (ch[:, 4] == 45) & (ch[:, 7] == 45)
        d10 = ch - 48
        y = (d10[:, 0] * 1000 + d10[:, 1] * 100 + d10[:, 2] * 10
             + d10[:, 3])
        m = d10[:, 5] * 10 + d10[:, 6]
        d = d10[:, 8] * 10 + d10[:, 9]
        ok = ok & (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31)
        days = days_from_civil(y, jnp.maximum(m, 1), jnp.maximum(d, 1),
                               jnp)
        # exact calendar check: Feb 30 etc. roll over in days_from_civil,
        # so require the round trip to reproduce (y, m, d)
        y2, m2, d2 = civil_from_days(days, jnp)
        ok = ok & (y2 == y) & (m2 == m) & (d2 == d)
        return DevVal(T.DATE, days.astype(jnp.int32), v.validity & ok)

    def cpu_eval(self, ctx) -> CpuVal:
        import datetime as _dt
        v = self.child.cpu_eval(ctx)
        if v.dtype is T.DATE:
            return v
        pat = _java_fmt_to_strptime(self.fmt)
        n = len(v.values)
        out = np.zeros(n, dtype=np.int32)
        valid = np.array(v.validity, dtype=np.bool_).copy()
        epoch = _dt.date(1970, 1, 1)
        for i, s in enumerate(v.values):
            if not valid[i]:
                continue
            try:
                dt = _dt.datetime.strptime(str(s), pat)
                if _render_strptime(dt, pat) != str(s):
                    # strict parse: python strptime accepts unpadded
                    # fields ('2001-3-16'); the device kernel (and this
                    # oracle) require the canonical padded form
                    valid[i] = False
                    continue
                out[i] = (dt.date() - epoch).days
            except ValueError:
                valid[i] = False
        return CpuVal(T.DATE, out, valid)


class DateFormat(UnaryExpression):
    """date_format(date|timestamp, fmt) -> STRING (Spark DateFormatClass).
    'yyyy-MM-dd' renders on device (digit synthesis, the FromUnixTime
    machinery); other formats run on CPU via strftime."""

    FMT = "yyyy-MM-dd"

    def __init__(self, child: Expression, fmt: str = FMT):
        self.fmt = str(fmt)
        super().__init__(child)

    def with_children(self, children):
        return DateFormat(children[0], self.fmt)

    def _resolve_type(self):
        if self.child.dtype not in (T.DATE, T.TIMESTAMP, T.NULL):
            raise TypeError(
                f"date_format needs a date/timestamp input, "
                f"got {self.child.dtype}")
        self.dtype = T.STRING
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        if self.fmt != self.FMT:
            return f"date_format {self.fmt!r} runs on CPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        cap = ctx.capacity
        days = _days_of(v, jnp)
        y, m, d = civil_from_days(days, jnp)

        def dig(x, p):
            return ((x // p) % 10 + 48).astype(jnp.uint8)

        dash = jnp.full(cap, 45, jnp.uint8)
        cols = [dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1), dash,
                dig(m, 10), dig(m, 1), dash, dig(d, 10), dig(d, 1)]
        return _emit_fixed_width(cols, v.validity, ctx)

    def cpu_eval(self, ctx) -> CpuVal:
        import datetime as _dt
        v = self.child.cpu_eval(ctx)
        pat = _java_fmt_to_strptime(self.fmt)
        out = np.empty(len(v.values), dtype=object)
        for i, (x, ok) in enumerate(zip(v.values, v.validity)):
            if not ok:
                out[i] = ""
                continue
            if v.dtype is T.TIMESTAMP:
                dt = _dt.datetime(1970, 1, 1) + \
                    _dt.timedelta(microseconds=int(x))
            else:
                dt = _dt.datetime(1970, 1, 1) + \
                    _dt.timedelta(days=int(x))
            out[i] = _render_strptime(dt, pat)
        return CpuVal(T.STRING, out, v.validity)
