"""Conditional expressions: IF and CASE WHEN (reference:
conditionalExpressions.scala, 251 LoC)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    CpuVal, DevVal, Expression, cast_cpu, cast_dev,
)


def _common_type(exprs: Sequence[Expression]) -> T.DataType:
    out = exprs[0].dtype
    for e in exprs[1:]:
        out = T.promote(out, e.dtype)
    return out


class If(Expression):
    def __init__(self, predicate: Expression, if_true: Expression,
                 if_false: Expression):
        self.children = (predicate, if_true, if_false)
        self.dtype = _common_type([if_true, if_false])
        self.nullable = if_true.nullable or if_false.nullable or predicate.nullable

    def with_children(self, children):
        return If(*children)

    def tpu_supported(self, conf):
        if self.dtype.is_string:
            return "IF over string branches not yet supported on TPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        p = self.children[0].tpu_eval(ctx)
        a = cast_dev(self.children[1].tpu_eval(ctx), self.dtype)
        b = cast_dev(self.children[2].tpu_eval(ctx), self.dtype)
        # NULL predicate selects the else branch (Spark semantics).
        cond = p.data.astype(jnp.bool_) & p.validity
        data = jnp.where(cond, a.data, b.data)
        validity = jnp.where(cond, a.validity, b.validity)
        return DevVal(self.dtype, data, validity)

    def cpu_eval(self, ctx) -> CpuVal:
        p = self.children[0].cpu_eval(ctx)
        av = self.children[1].cpu_eval(ctx)
        bv = self.children[2].cpu_eval(ctx)
        cond = p.values.astype(np.bool_) & p.validity
        if self.dtype.is_string:
            values = np.where(cond, av.values, bv.values)
            validity = np.where(cond, av.validity, bv.validity)
            return CpuVal(self.dtype, values.astype(object),
                          validity.astype(np.bool_))
        a = cast_cpu(av, self.dtype)
        b = cast_cpu(bv, self.dtype)
        data = np.where(cond, a.values, b.values)
        validity = np.where(cond, a.validity, b.validity)
        return CpuVal(self.dtype, data.astype(self.dtype.np_dtype),
                      validity.astype(np.bool_))


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 [WHEN p2 THEN v2 ...] [ELSE e] END."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        self.branches = [tuple(b) for b in branches]
        self.else_value = else_value
        flat: List[Expression] = []
        for p, v in self.branches:
            flat.extend((p, v))
        if else_value is not None:
            flat.append(else_value)
        self.children = tuple(flat)
        values = [v for _, v in self.branches]
        if else_value is not None:
            values.append(else_value)
        self.dtype = _common_type(values)
        self.nullable = (else_value is None or else_value.nullable
                         or any(v.nullable for v in values))

    def with_children(self, children):
        n = len(self.branches)
        branches = [(children[2 * i], children[2 * i + 1]) for i in range(n)]
        else_value = children[2 * n] if len(children) > 2 * n else None
        return CaseWhen(branches, else_value)

    def tpu_supported(self, conf):
        if self.dtype.is_string:
            return "CASE WHEN over string branches not yet supported on TPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        cap = ctx.capacity
        data = jnp.zeros(cap, dtype=self.dtype.jnp_dtype)
        validity = jnp.zeros(cap, dtype=jnp.bool_)
        if self.else_value is not None:
            ev = cast_dev(self.else_value.tpu_eval(ctx), self.dtype)
            data, validity = ev.data, ev.validity
        # Walk branches in reverse so earlier branches win.
        for pred, value in reversed(self.branches):
            p = pred.tpu_eval(ctx)
            v = cast_dev(value.tpu_eval(ctx), self.dtype)
            cond = p.data.astype(jnp.bool_) & p.validity
            data = jnp.where(cond, v.data, data)
            validity = jnp.where(cond, v.validity, validity)
        return DevVal(self.dtype, data, validity)

    def cpu_eval(self, ctx) -> CpuVal:
        n = ctx.num_rows
        if self.dtype.is_string:
            values = np.array([""] * n, dtype=object)
        else:
            values = np.zeros(n, dtype=self.dtype.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        if self.else_value is not None:
            ev = self.else_value.cpu_eval(ctx)
            if not self.dtype.is_string:
                ev = cast_cpu(ev, self.dtype)
            values, validity = ev.values.copy(), ev.validity.copy()
        decided = np.zeros(n, dtype=np.bool_)
        for pred, value in self.branches:
            p = pred.cpu_eval(ctx)
            v = value.cpu_eval(ctx)
            if not self.dtype.is_string:
                v = cast_cpu(v, self.dtype)
            cond = p.values.astype(np.bool_) & p.validity & ~decided
            values = np.where(cond, v.values, values)
            validity = np.where(cond, v.validity, validity)
            decided |= cond
        if self.dtype.is_string:
            values = values.astype(object)
        else:
            values = values.astype(self.dtype.np_dtype)
        return CpuVal(self.dtype, values, validity.astype(np.bool_))
