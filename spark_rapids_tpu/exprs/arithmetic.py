"""Arithmetic expressions (reference: arithmetic.scala, 227 LoC).

Spark non-ANSI semantics: division/modulo by zero yields NULL; integral
overflow wraps (java semantics), which matches jnp/numpy fixed-width ints.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, CpuVal, DevVal, Expression, UnaryExpression,
    cast_cpu, cast_dev, promote_cpu, promote_dev,
)


class _BinaryArithmetic(BinaryExpression):
    def _compute(self, x, y):
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        a, b, out = promote_dev(self.left.tpu_eval(ctx), self.right.tpu_eval(ctx))
        data = self._compute(a.data, b.data)
        return DevVal(out, data.astype(out.jnp_dtype), a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b, out = promote_cpu(self.left.cpu_eval(ctx), self.right.cpu_eval(ctx))
        with np.errstate(all="ignore"):
            data = self._compute(a.values, b.values)
        return CpuVal(out, data.astype(out.np_dtype), a.validity & b.validity)


class Add(_BinaryArithmetic):
    def _compute(self, x, y):
        return x + y


class Subtract(_BinaryArithmetic):
    def _compute(self, x, y):
        return x - y


class Multiply(_BinaryArithmetic):
    def _compute(self, x, y):
        return x * y


class Divide(BinaryExpression):
    """Spark '/' : always double result; x/0 -> NULL (non-ANSI)."""

    def _resolve_type(self):
        self.dtype = T.DOUBLE
        self.nullable = True

    def tpu_eval(self, ctx) -> DevVal:
        a = cast_dev(self.left.tpu_eval(ctx), T.DOUBLE)
        b = cast_dev(self.right.tpu_eval(ctx), T.DOUBLE)
        zero = b.data == 0.0
        data = a.data / jnp.where(zero, 1.0, b.data)
        return DevVal(T.DOUBLE, data, a.validity & b.validity & ~zero)

    def cpu_eval(self, ctx) -> CpuVal:
        a = cast_cpu(self.left.cpu_eval(ctx), T.DOUBLE)
        b = cast_cpu(self.right.cpu_eval(ctx), T.DOUBLE)
        zero = b.values == 0.0
        with np.errstate(all="ignore"):
            data = a.values / np.where(zero, 1.0, b.values)
        return CpuVal(T.DOUBLE, data, a.validity & b.validity & ~zero)


class IntegralDivide(BinaryExpression):
    """Spark 'div': long result; x div 0 -> NULL."""

    def _resolve_type(self):
        self.dtype = T.LONG
        self.nullable = True

    def tpu_eval(self, ctx) -> DevVal:
        a = cast_dev(self.left.tpu_eval(ctx), T.LONG)
        b = cast_dev(self.right.tpu_eval(ctx), T.LONG)
        zero = b.data == 0
        den = jnp.where(zero, 1, b.data)
        # Java integer division truncates toward zero; jnp // floors.
        q = jnp.sign(a.data) * jnp.sign(den) * (jnp.abs(a.data) // jnp.abs(den))
        return DevVal(T.LONG, q.astype(jnp.int64), a.validity & b.validity & ~zero)

    def cpu_eval(self, ctx) -> CpuVal:
        a = cast_cpu(self.left.cpu_eval(ctx), T.LONG)
        b = cast_cpu(self.right.cpu_eval(ctx), T.LONG)
        zero = b.values == 0
        den = np.where(zero, 1, b.values)
        with np.errstate(all="ignore"):
            q = (np.sign(a.values) * np.sign(den)
                 * (np.abs(a.values) // np.abs(den)))
        return CpuVal(T.LONG, q.astype(np.int64), a.validity & b.validity & ~zero)


class Remainder(BinaryExpression):
    """Spark '%': java semantics (sign of dividend); x % 0 -> NULL."""

    def tpu_eval(self, ctx) -> DevVal:
        a, b, out = promote_dev(self.left.tpu_eval(ctx), self.right.tpu_eval(ctx))
        zero = b.data == 0
        den = jnp.where(zero, 1, b.data)
        # java remainder: a - trunc(a/den)*den
        if out.is_fractional:
            r = jnp.fmod(a.data, den)
        else:
            q = jnp.sign(a.data) * jnp.sign(den) * (jnp.abs(a.data) // jnp.abs(den))
            r = a.data - q * den
        return DevVal(out, r.astype(out.jnp_dtype), a.validity & b.validity & ~zero)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b, out = promote_cpu(self.left.cpu_eval(ctx), self.right.cpu_eval(ctx))
        zero = b.values == 0
        den = np.where(zero, 1, b.values)
        with np.errstate(all="ignore"):
            if out.is_fractional:
                r = np.fmod(a.values, den)
            else:
                q = (np.sign(a.values) * np.sign(den)
                     * (np.abs(a.values) // np.abs(den)))
                r = a.values - q * den
        return CpuVal(out, r.astype(out.np_dtype), a.validity & b.validity & ~zero)


class Pmod(BinaryExpression):
    """Spark pmod: r = a % n (java remainder, sign of dividend); if r < 0
    then (r + n) % n — note the result takes the divisor's sign for negative
    divisors, it is NOT forced non-negative."""

    @staticmethod
    def _java_rem(a, den, xp):
        q = xp.sign(a) * xp.sign(den) * (xp.abs(a) // xp.abs(den))
        return a - q * den

    def tpu_eval(self, ctx) -> DevVal:
        a, b, out = promote_dev(self.left.tpu_eval(ctx), self.right.tpu_eval(ctx))
        zero = b.data == 0
        den = jnp.where(zero, 1, b.data)
        if out.is_fractional:
            r = jnp.fmod(a.data, den)
        else:
            r = self._java_rem(a.data, den, jnp)
        r2 = self._java_rem(r + den, den, jnp)
        r = jnp.where(r < 0, r2, r)
        return DevVal(out, r.astype(out.jnp_dtype), a.validity & b.validity & ~zero)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b, out = promote_cpu(self.left.cpu_eval(ctx), self.right.cpu_eval(ctx))
        zero = b.values == 0
        den = np.where(zero, 1, b.values)
        with np.errstate(all="ignore"):
            if out.is_fractional:
                r = np.fmod(a.values, den)
            else:
                r = self._java_rem(a.values, den, np)
            r2 = self._java_rem(r + den, den, np)
            r = np.where(r < 0, r2, r)
        return CpuVal(out, r.astype(out.np_dtype), a.validity & b.validity & ~zero)


class UnaryMinus(UnaryExpression):
    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(v.dtype, -v.data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(v.dtype, (-v.values).astype(v.dtype.np_dtype), v.validity)


class Abs(UnaryExpression):
    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(v.dtype, jnp.abs(v.data), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(v.dtype, np.abs(v.values), v.validity)


class UnaryPositive(UnaryExpression):
    """+x: identity on the value, kept as a node for plan parity
    (Spark UnaryPositive)."""

    def tpu_eval(self, ctx) -> DevVal:
        return self.child.tpu_eval(ctx)

    def cpu_eval(self, ctx) -> CpuVal:
        return self.child.cpu_eval(ctx)
