"""Expression library: declarative AST + TPU (jax) and CPU (numpy) evaluation.

Analogue of the reference expression library (~35 files under
sql-plugin/.../rapids, SURVEY.md section 2.5).  Every expression implements
``tpu_eval`` (traced under jit, static shapes, validity-mask null semantics)
and ``cpu_eval`` (numpy; Spark-CPU-semantics oracle used for fallback and
tests, mirroring the reference's CPU-vs-GPU compare strategy in
SparkQueryCompareTestSuite.scala:153-161).
"""

from spark_rapids_tpu.exprs.base import (
    Expression, DevVal, CpuVal, ColumnRef, BoundRef, Literal, Alias, SortOrder,
    bind_references, resolve,
)
from spark_rapids_tpu.exprs.arithmetic import (
    Add, Subtract, Multiply, Divide, IntegralDivide, Remainder, UnaryMinus, Abs, Pmod,
)
from spark_rapids_tpu.exprs.predicates import (
    Equals, NotEquals, LessThan, LessThanOrEqual, GreaterThan, GreaterThanOrEqual,
    EqualNullSafe, And, Or, Not, In,
)
from spark_rapids_tpu.exprs.nullexprs import (
    IsNull, IsNotNull, IsNan, Coalesce, NaNvl,
)
from spark_rapids_tpu.exprs.conditional import If, CaseWhen
from spark_rapids_tpu.exprs.cast import Cast
from spark_rapids_tpu.exprs.mathexprs import (
    Sqrt, Exp, Log, Pow, Floor, Ceil, Round, Sin, Cos, Tan, Asin, Acos, Atan,
    Signum, Cbrt, Log2, Log10, Log1p, Expm1, Rint, ToDegrees, ToRadians,
)
from spark_rapids_tpu.exprs.datetime import (
    Year, Month, DayOfMonth, DayOfWeek, DayOfYear, Quarter, Hour, Minute, Second,
    DateAdd, DateSub, DateDiff, LastDay, UnixTimestamp, FromUnixTime,
)
from spark_rapids_tpu.exprs.strings import (
    Length, Upper, Lower, Substring, StringStartsWith, StringEndsWith,
    StringContains, ConcatStrings, Like, StringTrim, StringTrimLeft, StringTrimRight,
    StringReplace, StringLocate, StringRPad, StringLPad, RegExpReplace,
    SplitPart, ConcatWs,
)
from spark_rapids_tpu.exprs.bitwise import (
    BitwiseAnd, BitwiseOr, BitwiseXor, BitwiseNot, ShiftLeft, ShiftRight,
    ShiftRightUnsigned,
)
from spark_rapids_tpu.exprs.aggregates import (
    AggregateExpression, Sum, Count, Min, Max, Average, First, Last,
)
from spark_rapids_tpu.exprs.hashing import Murmur3Hash
from spark_rapids_tpu.exprs.misc import (
    MonotonicallyIncreasingID, SparkPartitionID, Rand, KnownFloatingPointNormalized,
)
