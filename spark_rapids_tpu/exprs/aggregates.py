"""Declarative aggregate functions (reference: AggregateFunctions.scala, 513
LoC: min/max/sum/count/avg/first/last as declarative cudf agg pairs).

Here each aggregate declares segment-reduce kernels instead of cudf agg pairs:
``segment_update`` folds raw input rows into per-group buffers and
``segment_merge`` folds partial buffers; both are plain
``jax.ops.segment_*`` calls with ``num_segments = capacity`` so shapes stay
static (worst case: every live row its own group).  ``finalize`` computes the
result projection (e.g. avg = sum / count).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import CpuVal, DevVal, Expression, Literal

# Trace-time flag: the sort-groupby path feeds segment kernels seg_ids in
# ascending order; the MXU hash-agg slot path feeds them UNSORTED.
# ``indices_are_sorted`` is a correctness contract for TPU scatter
# lowering, not just a speed hint, so the hash path must trace with it
# off (kernels/hashagg.py wraps its segment_update calls).
_SEG_IDS_SORTED = [True]


def _seg_sorted() -> bool:
    return _SEG_IDS_SORTED[-1]


@contextlib.contextmanager
def unsorted_segment_ids():
    _SEG_IDS_SORTED.append(False)
    try:
        yield
    finally:
        _SEG_IDS_SORTED.pop()


def _sum_result_type(dt: T.DataType) -> T.DataType:
    if dt.is_integral:
        return T.LONG
    return T.DOUBLE


@dataclasses.dataclass
class AggBufferSpec:
    dtype: T.DataType


class AggregateFunction(Expression):
    """Base: declares buffers + segment kernels.  Not columnar-evaluable."""

    def __init__(self, child: Expression):
        self.children = (child,)
        self._resolve_type()

    @property
    def child(self):
        return self.children[0]

    def _resolve_type(self):
        raise NotImplementedError

    # number and types of intermediate buffers
    def buffers(self) -> List[AggBufferSpec]:
        raise NotImplementedError

    def segment_update(self, v: DevVal, seg_ids, num_segments: int,
                      live_mask) -> List[DevVal]:
        """Fold input rows into per-group buffers (partial aggregation)."""
        raise NotImplementedError

    def segment_merge(self, buffers: List[DevVal], seg_ids,
                      num_segments: int, live_mask) -> List[DevVal]:
        """Fold partial buffers (final aggregation after shuffle)."""
        raise NotImplementedError

    def finalize(self, buffers: List[DevVal]) -> DevVal:
        raise NotImplementedError

    # CPU oracle: reduce a python/numpy group
    def cpu_reduce(self, values: np.ndarray, validity: np.ndarray):
        raise NotImplementedError

    def tpu_supported(self, conf):
        if self.child.dtype.is_string:
            return f"{self.name} over strings not supported on TPU"
        if self.child.dtype.is_fractional and not conf.variable_float_agg \
                and type(self) in (Sum, Average):
            return (f"{self.name} over floats can produce non-deterministic "
                    "results; set spark.rapids.sql.variableFloatAgg.enabled")
        return None


def _seg_any_valid(valid, seg_ids, num_segments, live_mask):
    # scatter-ADD (not max): adds combine in-lane on TPU scatters
    return jax.ops.segment_sum((valid & live_mask).astype(jnp.int32), seg_ids,
                               num_segments=num_segments, indices_are_sorted=_seg_sorted()) > 0


class Sum(AggregateFunction):
    def _resolve_type(self):
        self.dtype = _sum_result_type(self.child.dtype)
        self.nullable = True

    def buffers(self):
        return [AggBufferSpec(self.dtype), AggBufferSpec(T.BOOLEAN)]

    def segment_update(self, v, seg_ids, num_segments, live_mask):
        x = v.data.astype(self.dtype.jnp_dtype)
        use = v.validity & live_mask
        s = jax.ops.segment_sum(jnp.where(use, x, 0), seg_ids,
                                num_segments=num_segments, indices_are_sorted=_seg_sorted())
        any_v = _seg_any_valid(v.validity, seg_ids, num_segments, live_mask)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(self.dtype, s, ones), DevVal(T.BOOLEAN, any_v, ones)]

    def segment_merge(self, buffers, seg_ids, num_segments, live_mask):
        s, has = buffers
        total = jax.ops.segment_sum(
            jnp.where(live_mask, s.data, 0), seg_ids, num_segments=num_segments, indices_are_sorted=_seg_sorted())
        any_v = _seg_any_valid(has.data.astype(jnp.bool_), seg_ids,
                               num_segments, live_mask)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(self.dtype, total, ones), DevVal(T.BOOLEAN, any_v, ones)]

    def finalize(self, buffers):
        s, has = buffers
        return DevVal(self.dtype, s.data, has.data.astype(jnp.bool_))

    def cpu_reduce(self, values, validity):
        if not validity.any():
            return None
        vals = values[validity]
        if self.dtype == T.LONG:
            return int(np.sum(vals.astype(np.int64)))
        return float(np.sum(vals.astype(np.float64)))


class Count(AggregateFunction):
    def _resolve_type(self):
        self.dtype = T.LONG
        self.nullable = False

    def tpu_supported(self, conf):
        return None

    def buffers(self):
        return [AggBufferSpec(T.LONG)]

    def segment_update(self, v, seg_ids, num_segments, live_mask):
        use = v.validity & live_mask
        # scatter-add in i32 (native TPU lanes; a 64-bit scatter lowers to
        # an emulated sort-based path), widen after: one batch holds
        # < 2^31 rows so the per-batch count cannot overflow
        c32 = jax.ops.segment_sum(use.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments,
                                  indices_are_sorted=_seg_sorted())
        c = c32.astype(jnp.int64)
        return [DevVal(T.LONG, c, jnp.ones(num_segments, dtype=jnp.bool_))]

    def segment_merge(self, buffers, seg_ids, num_segments, live_mask):
        c = jax.ops.segment_sum(
            jnp.where(live_mask, buffers[0].data, 0), seg_ids,
            num_segments=num_segments, indices_are_sorted=_seg_sorted())
        return [DevVal(T.LONG, c, jnp.ones(num_segments, dtype=jnp.bool_))]

    def finalize(self, buffers):
        return DevVal(T.LONG, buffers[0].data,
                      jnp.ones_like(buffers[0].data, dtype=jnp.bool_))

    def cpu_reduce(self, values, validity):
        return int(validity.sum())


class _MinMax(AggregateFunction):
    _is_min = True

    def _resolve_type(self):
        self.dtype = self.child.dtype
        self.nullable = True

    def tpu_supported(self, conf):
        if self.child.dtype.is_string:
            return f"{self.name} over strings not supported on TPU"
        return None

    def buffers(self):
        return [AggBufferSpec(self.dtype), AggBufferSpec(T.BOOLEAN)]

    def _ident(self):
        jdt = self.dtype.jnp_dtype
        if self.dtype.is_fractional:
            return jnp.asarray(jnp.inf if self._is_min else -jnp.inf, dtype=jdt)
        info = jnp.iinfo(jdt) if self.dtype != T.BOOLEAN else None
        if self.dtype == T.BOOLEAN:
            return jnp.asarray(True if self._is_min else False)
        return jnp.asarray(info.max if self._is_min else info.min, dtype=jdt)

    def _seg_reduce(self, x, seg_ids, num_segments):
        if self._is_min:
            return jax.ops.segment_min(x, seg_ids, num_segments=num_segments, indices_are_sorted=_seg_sorted())
        return jax.ops.segment_max(x, seg_ids, num_segments=num_segments, indices_are_sorted=_seg_sorted())

    def segment_update(self, v, seg_ids, num_segments, live_mask):
        use = v.validity & live_mask
        x = jnp.where(use, v.data.astype(self.dtype.jnp_dtype), self._ident())
        m = self._seg_reduce(x, seg_ids, num_segments)
        any_v = _seg_any_valid(v.validity, seg_ids, num_segments, live_mask)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(self.dtype, m, ones), DevVal(T.BOOLEAN, any_v, ones)]

    def segment_merge(self, buffers, seg_ids, num_segments, live_mask):
        m, has = buffers
        use = has.data.astype(jnp.bool_) & live_mask
        x = jnp.where(use, m.data, self._ident())
        total = self._seg_reduce(x, seg_ids, num_segments)
        any_v = _seg_any_valid(has.data.astype(jnp.bool_), seg_ids,
                               num_segments, live_mask)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(self.dtype, total, ones), DevVal(T.BOOLEAN, any_v, ones)]

    def finalize(self, buffers):
        m, has = buffers
        return DevVal(self.dtype, m.data, has.data.astype(jnp.bool_))

    def cpu_reduce(self, values, validity):
        if not validity.any():
            return None
        vals = values[validity]
        if self.dtype.is_string:
            vals = [str(v) for v in vals]
        r = min(vals) if self._is_min else max(vals)
        return r


class Min(_MinMax):
    _is_min = True


class Max(_MinMax):
    _is_min = False


class Average(AggregateFunction):
    def _resolve_type(self):
        self.dtype = T.DOUBLE
        self.nullable = True

    def buffers(self):
        return [AggBufferSpec(T.DOUBLE), AggBufferSpec(T.LONG)]

    def segment_update(self, v, seg_ids, num_segments, live_mask):
        use = v.validity & live_mask
        x = v.data.astype(jnp.float64)
        s = jax.ops.segment_sum(jnp.where(use, x, 0.0), seg_ids,
                                num_segments=num_segments, indices_are_sorted=_seg_sorted())
        # count in i32 (native scatter lanes), widened after — see Count
        c = jax.ops.segment_sum(use.astype(jnp.int32), seg_ids,
                                num_segments=num_segments,
                                indices_are_sorted=_seg_sorted()).astype(jnp.int64)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(T.DOUBLE, s, ones), DevVal(T.LONG, c, ones)]

    def segment_merge(self, buffers, seg_ids, num_segments, live_mask):
        s, c = buffers
        st = jax.ops.segment_sum(jnp.where(live_mask, s.data, 0.0), seg_ids,
                                 num_segments=num_segments, indices_are_sorted=_seg_sorted())
        ct = jax.ops.segment_sum(jnp.where(live_mask, c.data, 0), seg_ids,
                                 num_segments=num_segments, indices_are_sorted=_seg_sorted())
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(T.DOUBLE, st, ones), DevVal(T.LONG, ct, ones)]

    def finalize(self, buffers):
        s, c = buffers
        nonzero = c.data > 0
        data = s.data / jnp.where(nonzero, c.data, 1).astype(jnp.float64)
        return DevVal(T.DOUBLE, data, nonzero)

    def cpu_reduce(self, values, validity):
        if not validity.any():
            return None
        vals = values[validity].astype(np.float64)
        return float(np.sum(vals) / len(vals))


class _FirstLast(AggregateFunction):
    _is_first = True

    def __init__(self, child: Expression, ignore_nulls: bool = False):
        self.ignore_nulls = ignore_nulls
        super().__init__(child)

    def with_children(self, children):
        return type(self)(children[0], self.ignore_nulls)

    def _resolve_type(self):
        self.dtype = self.child.dtype
        self.nullable = True

    def buffers(self):
        # value + validity + the row index it came from (for merge ordering)
        return [AggBufferSpec(self.dtype), AggBufferSpec(T.BOOLEAN),
                AggBufferSpec(T.LONG)]

    def _pick(self, v_data, v_valid, idx, seg_ids, num_segments, live_mask):
        cap = int(idx.shape[0])
        candidate = live_mask & (v_valid if self.ignore_nulls
                                 else jnp.ones_like(v_valid))
        big = jnp.int64(jnp.iinfo(jnp.int64).max // 2)
        key = jnp.where(candidate, idx, big if self._is_first else -big)
        if self._is_first:
            best = jax.ops.segment_min(key, seg_ids, num_segments=num_segments, indices_are_sorted=_seg_sorted())
        else:
            best = jax.ops.segment_max(key, seg_ids, num_segments=num_segments, indices_are_sorted=_seg_sorted())
        # Scatter values of winners into group slots.
        winner = candidate & (best[seg_ids] == key)
        out_val = jnp.zeros(num_segments, dtype=v_data.dtype)
        out_val = out_val.at[jnp.where(winner, seg_ids, num_segments)].set(
            v_data, mode="drop")
        out_ok = jnp.zeros(num_segments, dtype=jnp.bool_)
        out_ok = out_ok.at[jnp.where(winner, seg_ids, num_segments)].set(
            v_valid, mode="drop")
        has = jax.ops.segment_max(candidate.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments, indices_are_sorted=_seg_sorted()) > 0
        best_idx = jnp.where(has, best, 0)
        return out_val, out_ok & has, best_idx

    def segment_update(self, v, seg_ids, num_segments, live_mask):
        cap = int(v.data.shape[0])
        idx = jnp.arange(cap, dtype=jnp.int64)
        val, ok, bidx = self._pick(v.data.astype(self.dtype.jnp_dtype),
                                   v.validity, idx, seg_ids, num_segments,
                                   live_mask)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(self.dtype, val, ones), DevVal(T.BOOLEAN, ok, ones),
                DevVal(T.LONG, bidx, ones)]

    def segment_merge(self, buffers, seg_ids, num_segments, live_mask):
        val, ok, idx = buffers
        nv, nok, nidx = self._pick(val.data, ok.data.astype(jnp.bool_),
                                   idx.data, seg_ids, num_segments, live_mask)
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(self.dtype, nv, ones), DevVal(T.BOOLEAN, nok, ones),
                DevVal(T.LONG, nidx, ones)]

    def finalize(self, buffers):
        val, ok, _ = buffers
        return DevVal(self.dtype, val.data, ok.data.astype(jnp.bool_))

    def cpu_reduce(self, values, validity):
        order = range(len(values)) if self._is_first else \
            range(len(values) - 1, -1, -1)
        for i in order:
            if self.ignore_nulls and not validity[i]:
                continue
            return values[i] if validity[i] else None
        return None


class First(_FirstLast):
    _is_first = True


class Last(_FirstLast):
    _is_first = False


class Percentile(AggregateFunction):
    """percentile(x, p): Spark's exact percentile with linear
    interpolation between closest ranks (used by the reference's mortgage
    AggregatesWithPercentiles benchmark, MortgageSpark.scala:368-390).

    Never executed directly: the dataframe layer rewrites it into a
    rank-and-interpolate pipeline over existing machinery — row_number +
    count windows produce each row's interpolation weight, a plain SUM
    collapses them (see GroupedData._agg_with_percentile).  A buffered
    two-phase implementation would need unbounded per-group state, which
    the fixed-slot aggregate model deliberately excludes."""

    def __init__(self, child: Expression, percentage: float):
        if not (0.0 <= float(percentage) <= 1.0):
            raise ValueError(
                f"percentile percentage must be in [0, 1]: {percentage}")
        self.percentage = float(percentage)
        super().__init__(child)

    def with_children(self, children):
        return Percentile(children[0], self.percentage)

    def _resolve_type(self):
        dt = self.child.dtype
        if dt is not T.NULL and not dt.is_numeric:  # NULL = unresolved yet
            raise TypeError(f"percentile needs a numeric input, got {dt}")
        self.dtype = T.DOUBLE
        self.nullable = True

    def tpu_supported(self, conf):
        return None

    def buffers(self):
        raise AssertionError(
            "Percentile must be rewritten before execution")


class CountDistinct(AggregateFunction):
    """count(DISTINCT x).

    Never executed directly: the dataframe layer rewrites any aggregation
    containing it into two stacked Aggregates (group by keys+value, then by
    keys), the distinct-aggregate rewrite Spark's planner applies
    (cf. RewriteDistinctAggregates; the reference rides the rewritten plan's
    Partial/PartialMerge modes, aggregate.scala).  See
    GroupedData._agg_with_distinct."""

    def _resolve_type(self):
        self.dtype = T.LONG
        self.nullable = False

    def tpu_supported(self, conf):
        return None

    def buffers(self):
        raise AssertionError(
            "CountDistinct must be rewritten before execution")


@dataclasses.dataclass
class AggregateExpression:
    """An aggregate call in an output position: fn + output name."""

    fn: AggregateFunction
    output_name: str

    @property
    def dtype(self):
        return self.fn.dtype


def count_star() -> Count:
    return Count(Literal(1, T.INT))


class GroupingID(AggregateFunction):
    """grouping_id(): the bitmask of masked-out grouping keys under
    ROLLUP/CUBE/GROUPING SETS (Spark GroupingID).  A marker the
    grouping-sets rewrite replaces with min(__grouping_id) — reaching
    execution unreplaced means it was used outside grouping sets."""

    def __init__(self):
        super().__init__(Literal(0, T.INT))

    def with_children(self, children):
        return GroupingID()

    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = False

    def tpu_supported(self, conf):
        return None

    def buffers(self):
        raise AssertionError(
            "grouping_id() is only valid under rollup/cube/grouping sets")


class _CentralMoment(AggregateFunction):
    """stddev/variance family over (n, n*mean, m2-contribution) buffers.

    Partials merge with Chan's k-way formula expressed as three segment
    sums: S0 = Σnᵢ, S1 = Σnᵢ·meanᵢ, S2 = Σ(m2ᵢ + nᵢ·meanᵢ²); then
    mean = S1/S0 and m2 = S2 − S1²/S0 — numerically safer than raw
    sum-of-squares across shuffled partials.  Spark semantics: NULL for
    zero rows; sample variants give NaN for a single row (0/0)."""

    _sample = True   # ddof=1
    _sqrt = False    # stddev vs variance

    def _resolve_type(self):
        self.dtype = T.DOUBLE
        self.nullable = True

    def buffers(self):
        return [AggBufferSpec(T.DOUBLE), AggBufferSpec(T.DOUBLE),
                AggBufferSpec(T.DOUBLE)]

    def segment_update(self, v, seg_ids, num_segments, live_mask):
        use = v.validity & live_mask
        x = jnp.where(use, v.data.astype(jnp.float64), 0.0)
        n = jax.ops.segment_sum(use.astype(jnp.float64), seg_ids,
                                num_segments=num_segments,
                                indices_are_sorted=_seg_sorted())
        s1 = jax.ops.segment_sum(x, seg_ids, num_segments=num_segments,
                                 indices_are_sorted=_seg_sorted())
        # two-pass m2: deviations from the per-group mean, NOT the
        # cancellation-prone Σx² − (Σx)²/n (large-mean data — e.g. epoch
        # timestamps — loses every significant digit under that form)
        mean = s1 / jnp.maximum(n, 1.0)
        d = jnp.where(use, x - mean[seg_ids], 0.0)
        m2 = jax.ops.segment_sum(d * d, seg_ids,
                                 num_segments=num_segments,
                                 indices_are_sorted=_seg_sorted())
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(T.DOUBLE, n, ones),
                DevVal(T.DOUBLE, s1, ones),   # n*mean = Σx
                DevVal(T.DOUBLE, m2, ones)]

    def segment_merge(self, buffers, seg_ids, num_segments, live_mask):
        n_i, nm_i, m2_i = (b.data for b in buffers)
        live = live_mask.astype(jnp.float64)
        s0 = jax.ops.segment_sum(n_i * live, seg_ids,
                                 num_segments=num_segments,
                                 indices_are_sorted=_seg_sorted())
        s1 = jax.ops.segment_sum(nm_i * live, seg_ids,
                                 num_segments=num_segments,
                                 indices_are_sorted=_seg_sorted())
        # deviation form of Chan's combine: m2 = Σm2ᵢ + Σnᵢ·(meanᵢ−mean)²
        # — the Σnᵢ·meanᵢ² − n·mean² form cancels catastrophically for
        # large means (epoch-scale data), this one never does
        mean = s1 / jnp.maximum(s0, 1.0)
        mean_i = nm_i / jnp.maximum(n_i, 1.0)
        dev = mean_i - mean[seg_ids]
        m2 = jax.ops.segment_sum((m2_i + n_i * dev * dev) * live, seg_ids,
                                 num_segments=num_segments,
                                 indices_are_sorted=_seg_sorted())
        ones = jnp.ones(num_segments, dtype=jnp.bool_)
        return [DevVal(T.DOUBLE, s0, ones), DevVal(T.DOUBLE, s1, ones),
                DevVal(T.DOUBLE, m2, ones)]

    def finalize(self, buffers):
        n, _, m2 = (b.data for b in buffers)
        m2 = jnp.maximum(m2, 0.0)  # clamp negative rounding residue
        denom = n - 1.0 if self._sample else n
        out = m2 / denom  # n==1 sample: 0/0 -> NaN (Spark)
        if self._sqrt:
            out = jnp.sqrt(out)
        return DevVal(T.DOUBLE, out, n > 0)

    def cpu_reduce(self, values, validity):
        vals = np.asarray(values[validity], dtype=np.float64)
        if len(vals) == 0:
            return None
        ddof = 1 if self._sample else 0
        if self._sample and len(vals) == 1:
            return float("nan")
        with np.errstate(all="ignore"):
            var = float(np.var(vals, ddof=ddof))
            return float(np.sqrt(var)) if self._sqrt else var


class StddevSamp(_CentralMoment):
    _sample, _sqrt = True, True


class StddevPop(_CentralMoment):
    _sample, _sqrt = False, True


class VarianceSamp(_CentralMoment):
    _sample, _sqrt = True, False


class VariancePop(_CentralMoment):
    _sample, _sqrt = False, False


class _BinaryStatMarker(AggregateFunction):
    """corr/covar family marker: two children, never executed directly —
    the dataframe layer rewrites it onto windows + arithmetic + SUM
    (GroupedData._agg_with_binary_stats), since every aggregation path
    assumes single-child aggregates."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)
        self._resolve_type()

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def _resolve_type(self):
        for c in self.children:
            if c.dtype is not T.NULL and not c.dtype.is_numeric:
                raise TypeError(
                    f"{type(self).__name__} needs numeric inputs, "
                    f"got {c.dtype}")
        self.dtype = T.DOUBLE
        self.nullable = True

    def tpu_supported(self, conf):
        return None

    def buffers(self):
        raise AssertionError(
            f"{type(self).__name__} must be rewritten before execution")


class CovarPop(_BinaryStatMarker):
    pass


class CovarSamp(_BinaryStatMarker):
    pass


class Corr(_BinaryStatMarker):
    pass
