"""String expressions on the TPU (reference: stringFunctions.scala, 862 LoC).

Device layout is cudf-style: ``offsets`` int32[cap+1] into a flat uint8 byte
buffer.  Every kernel below is built from three vectorizable primitives that
XLA lowers well:

* ``rows_of_positions`` — map each byte position to its owning row
  (one ``searchsorted`` over the offsets), turning per-row varlen work into
  flat elementwise work over the byte buffer;
* prefix sums (``cumsum``) to build output offsets from per-row lengths;
* gathers with clamped indices to materialize output bytes.

Row equality/grouping uses dual 64-bit polynomial hashes computed with a
weighted segment-sum over the byte buffer — O(byte_cap) work, no per-row
loops, no dynamic shapes.

Case mapping is ASCII-only (flagged incompat, like the reference's
string incompatibilities).  Patterns (needles) must be literals for device
execution; anything else falls back to CPU via the planner.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    CpuVal, DevVal, Expression, Literal, UnaryExpression,
)

# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


def string_lengths(v: DevVal):
    if v.codes is not None:
        # Dictionary-encoded: offsets describe the ENTRIES; gather per-row
        # lengths through the codes (invalid rows are length-0, matching the
        # materialized layout).
        ent_lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        nd = int(v.offsets.shape[0]) - 1
        codes_c = jnp.clip(v.codes, 0, max(nd - 1, 0))
        return jnp.where(v.validity, ent_lens[codes_c], 0).astype(jnp.int32)
    return (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)


def rows_of_positions(offsets, nbytes: int):
    """int32[nbytes]: owning row of each byte position (cap for padding)."""
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    return jnp.searchsorted(offsets[1:], pos, side="right").astype(jnp.int32)


_HASH_BASES = (31, 131)


def _pow_table(base: int, n: int):
    """uint32 modular polynomial powers base^k (mod 2^32) for k in [0, n].

    Closed form via binary exponentiation: 32 elementwise multiplies
    selected by k's bits, with base^(2^j) precomputed in python.  A
    ``cumprod`` scan here compiles pathologically on TPU at byte-buffer
    sizes (the scan lowering, same family as the f64 cumsum blowup);
    the bit form is pure elementwise work.
    """
    k = jnp.arange(n + 1, dtype=jnp.uint32)
    out = jnp.ones(n + 1, dtype=jnp.uint32)
    sq = base % (1 << 32)
    for j in range(max(n, 1).bit_length()):
        bit = (k >> jnp.uint32(j)) & jnp.uint32(1)
        out = out * jnp.where(bit == 1, jnp.uint32(sq), jnp.uint32(1))
        sq = (sq * sq) % (1 << 32)
    return out


def string_hash2(v: DevVal) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dual 32-bit polynomial row hashes: h = sum byte[i] * base^(end-1-i)
    (mod 2^32).  Equality tests combine both hashes + length (+ the 64-byte
    sort prefix where exactness matters)."""
    if v.codes is not None:
        # Dictionary-encoded: hash each ENTRY once (O(dict bytes), not
        # O(row bytes)) and gather per-row hashes through the codes.
        # Invalid rows take the empty-string hash (0), exactly as the
        # materialized layout hashes its length-0 rows.
        nd_cap = int(v.offsets.shape[0]) - 1
        ent = DevVal(v.dtype, v.data,
                     jnp.ones(nd_cap, dtype=jnp.bool_), v.offsets)
        e1, e2 = string_hash2(ent)
        codes_c = jnp.clip(v.codes, 0, max(nd_cap - 1, 0))
        h1 = jnp.where(v.validity, e1[codes_c], jnp.uint32(0))
        h2 = jnp.where(v.validity, e2[codes_c], jnp.uint32(0))
        return h1, h2
    cap = v.capacity
    nbytes = int(v.data.shape[0])

    def xla():
        rows = rows_of_positions(v.offsets, nbytes)
        rows_c = jnp.clip(rows, 0, cap - 1)
        ends = v.offsets[rows_c + 1].astype(jnp.int32)
        pos = jnp.arange(nbytes, dtype=jnp.int32)
        in_data = pos < v.offsets[-1].astype(jnp.int32)
        exp = jnp.clip(ends - 1 - pos, 0, nbytes).astype(jnp.int32)
        byte = jnp.where(in_data, v.data, 0).astype(jnp.uint32)
        out = []
        for base in _HASH_BASES:
            pows = _pow_table(base, nbytes)
            contrib = byte * pows[exp]
            h = jax.ops.segment_sum(jnp.where(in_data, contrib, 0), rows_c,
                                    num_segments=cap,
                                    indices_are_sorted=True)
            # Mix in length so "" vs padding rows differ and lengths
            # disambiguate.
            h = h + string_lengths(v).astype(jnp.uint32) * \
                jnp.uint32(0x9E3779B9)
            out.append(h.astype(jnp.uint32))
        return out[0], out[1]

    if nbytes < 1 or cap < 1:
        return xla()
    # kernel tier: Horner over each row's byte window (bit-identical —
    # uint32 arithmetic is exact mod 2^32 in any association)
    from spark_rapids_tpu.kernels import pallas_tier as PT
    return PT.run(
        "stringHash",
        lambda interpret: PT.string_hash_rows(
            v.data, v.offsets, cap, _HASH_BASES, interpret=interpret),
        xla, resident_bytes=nbytes + 4 * (cap + 1))


def hash_literal2(s: str) -> Tuple[int, int]:
    raw = s.encode("utf-8")
    out = []
    for base in _HASH_BASES:
        h = 0
        for b in raw:
            h = (h * base + b) % (1 << 32)
        h = (h + len(raw) * 0x9E3779B9) % (1 << 32)
        out.append(h)
    return out[0], out[1]


def build_string(dtype, new_lens, src_index_fn, out_byte_cap: int,
                 validity) -> DevVal:
    """Materialize a string column from per-row output lengths.

    ``src_index_fn(row, pos_in_row)`` returns the source byte index for each
    output byte (vectorized over flat arrays).
    """
    cap = int(new_lens.shape[0])
    new_lens = new_lens.astype(jnp.int32)
    offsets = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(new_lens).astype(jnp.int32)
    ])
    rows = rows_of_positions(offsets, out_byte_cap)
    rows_c = jnp.clip(rows, 0, cap - 1)
    pos_in_row = jnp.arange(out_byte_cap, dtype=jnp.int32) - offsets[rows_c]
    live = jnp.arange(out_byte_cap, dtype=jnp.int32) < offsets[-1]
    data = src_index_fn(rows_c, pos_in_row)
    data = jnp.where(live, data, 0).astype(jnp.uint8)
    return DevVal(dtype, data, validity, offsets)


def _gather_substring(v: DevVal, starts, new_lens, out_byte_cap: int,
                      validity) -> DevVal:
    """Common shape: every output row is a contiguous slice of its input row."""
    src_base = v.offsets[:-1] + starts.astype(jnp.int32)
    nbytes = int(v.data.shape[0])

    def src(rows, pos):
        idx = jnp.clip(src_base[rows] + pos, 0, nbytes - 1)
        return v.data[idx]

    return build_string(T.STRING, new_lens, src, out_byte_cap, validity)


def _find_matches(v: DevVal, needle: bytes):
    """bool[nbytes]: needle match beginning at each byte position, fully
    inside the owning row."""
    nbytes = int(v.data.shape[0])
    L = len(needle)
    if L == 0:
        return jnp.ones(nbytes, dtype=jnp.bool_)
    cap = v.capacity
    rows = rows_of_positions(v.offsets, nbytes)
    rows_c = jnp.clip(rows, 0, cap - 1)
    ends = v.offsets[rows_c + 1]
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    ok = (pos + L) <= ends
    match = ok
    for k, b in enumerate(needle):
        idx = jnp.clip(pos + k, 0, nbytes - 1)
        match = match & (v.data[idx] == np.uint8(b))
    return match


def _rows_with_match(v: DevVal, needle: bytes):
    cap = v.capacity

    def xla():
        match = _find_matches(v, needle)
        nbytes = int(v.data.shape[0])
        rows = jnp.clip(rows_of_positions(v.offsets, nbytes), 0, cap - 1)
        counts = jax.ops.segment_sum(match.astype(jnp.int32), rows,
                                     num_segments=cap,
                                     indices_are_sorted=True)
        return counts > 0

    if len(needle) == 0:
        return jnp.ones(cap, dtype=jnp.bool_)
    # Pallas one-pass scan through the kernel tier (the reference's
    # dedicated contains kernel role): conf-gated, TPU-or-interpret
    # backend predicate, XLA formulation as the automatic fallback.
    from spark_rapids_tpu.kernels import pallas_strings as PS
    from spark_rapids_tpu.kernels import pallas_tier as PT
    return PT.run(
        "strings",
        lambda interpret: PS.rows_with_match(
            v.data, v.offsets, v.validity, cap, needle,
            interpret=interpret),
        xla)


def _literal_needle(expr: Expression) -> Optional[str]:
    if isinstance(expr, Literal) and expr.value is not None:
        return str(expr.value)
    return None


# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------


class Length(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        # NOTE: byte length == char length only for ASCII; Spark counts chars.
        return DevVal(T.INT, string_lengths(v), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        data = np.fromiter((len(str(s)) for s in v.values), dtype=np.int32,
                           count=len(v.values))
        return CpuVal(T.INT, data, v.validity)


class _CaseMap(UnaryExpression):
    _delta = 0

    def _resolve_type(self):
        self.dtype = T.STRING
        self.nullable = self.child.nullable

    def _map_dev(self, data):
        raise NotImplementedError

    def _map_cpu(self, s: str) -> str:
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.STRING, self._map_dev(v.data), v.validity, v.offsets)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = np.array([self._map_cpu(str(s)) for s in v.values], dtype=object)
        return CpuVal(T.STRING, out, v.validity)


class Upper(_CaseMap):
    def _map_dev(self, data):
        is_lower = (data >= 97) & (data <= 122)
        return jnp.where(is_lower, data - 32, data).astype(jnp.uint8)

    def _map_cpu(self, s):
        return "".join(c.upper() if "a" <= c <= "z" else c for c in s)


class Lower(_CaseMap):
    def _map_dev(self, data):
        is_upper = (data >= 65) & (data <= 90)
        return jnp.where(is_upper, data + 32, data).astype(jnp.uint8)

    def _map_cpu(self, s):
        return "".join(c.lower() if "A" <= c <= "Z" else c for c in s)


def _substr_bounds(length, pos: int, sublen: Optional[int], xp):
    """Spark substring semantics (UTF8String.substringSQL): 1-based pos,
    negative counts from end; the length window is measured from the raw
    (possibly negative) start before clamping."""
    if pos > 0:
        start_raw = xp.full_like(length, pos - 1)
    elif pos == 0:
        start_raw = xp.zeros_like(length)
    else:
        start_raw = length + pos
    end_raw = length if sublen is None else start_raw + max(sublen, 0)
    start = xp.clip(start_raw, 0, length)
    end = xp.clip(end_raw, 0, length)
    n = xp.maximum(end - start, 0)
    return start.astype(xp.int32), n.astype(xp.int32)


class Substring(UnaryExpression):
    def __init__(self, child: Expression, pos: int, length: Optional[int] = None):
        self.pos = int(pos)
        self.sublen = None if length is None else int(length)
        super().__init__(child)

    def with_children(self, children):
        return Substring(children[0], self.pos, self.sublen)

    def _resolve_type(self):
        self.dtype = T.STRING
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        lens = string_lengths(v)
        start, n = _substr_bounds(lens, self.pos, self.sublen, jnp)
        n = jnp.where(v.validity & ctx.row_mask, n, 0)
        return _gather_substring(v, start, n, int(v.data.shape[0]), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = np.empty(len(v.values), dtype=object)
        for i, s in enumerate(v.values):
            s = str(s)
            L = len(s)
            if self.pos > 0:
                start_raw = self.pos - 1
            elif self.pos == 0:
                start_raw = 0
            else:
                start_raw = L + self.pos
            end_raw = L if self.sublen is None else start_raw + max(self.sublen, 0)
            start = min(max(start_raw, 0), L)
            end = min(max(end_raw, 0), L)
            out[i] = s[start:end]
        return CpuVal(T.STRING, out, v.validity)


class _Trim(UnaryExpression):
    _left = True
    _right = True

    def _resolve_type(self):
        self.dtype = T.STRING
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        cap = v.capacity
        nbytes = int(v.data.shape[0])
        lens = string_lengths(v)
        rows = jnp.clip(rows_of_positions(v.offsets, nbytes), 0, cap - 1)
        pos_in_row = jnp.arange(nbytes, dtype=jnp.int32) - v.offsets[rows]
        in_data = jnp.arange(nbytes, dtype=jnp.int32) < v.offsets[-1]
        is_space = (v.data == 32) & in_data
        big = jnp.int32(nbytes + 1)
        if self._left:
            first_ns = jax.ops.segment_min(
                jnp.where(~is_space & in_data, pos_in_row, big), rows,
                num_segments=cap, indices_are_sorted=True)
            lead = jnp.where(first_ns > lens, lens, first_ns.astype(jnp.int32))
        else:
            lead = jnp.zeros(cap, dtype=jnp.int32)
        if self._right:
            last_ns = jax.ops.segment_max(
                jnp.where(~is_space & in_data, pos_in_row, -1), rows,
                num_segments=cap, indices_are_sorted=True)
            trail = lens - 1 - last_ns.astype(jnp.int32)
            trail = jnp.clip(trail, 0, lens)
        else:
            trail = jnp.zeros(cap, dtype=jnp.int32)
        new_lens = jnp.maximum(lens - lead - trail, 0)
        new_lens = jnp.where(v.validity & ctx.row_mask, new_lens, 0)
        return _gather_substring(v, lead, new_lens, nbytes, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = np.empty(len(v.values), dtype=object)
        for i, s in enumerate(v.values):
            s = str(s)
            if self._left and self._right:
                out[i] = s.strip(" ")
            elif self._left:
                out[i] = s.lstrip(" ")
            else:
                out[i] = s.rstrip(" ")
        return CpuVal(T.STRING, out, v.validity)


class StringTrim(_Trim):
    _left = True
    _right = True


class StringTrimLeft(_Trim):
    _left = True
    _right = False


class StringTrimRight(_Trim):
    _left = False
    _right = True


class ConcatStrings(Expression):
    """concat(a, b, ...) over strings; NULL if any input is NULL (Spark)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)
        self.dtype = T.STRING
        self.nullable = any(c.nullable for c in children)

    def with_children(self, children):
        return ConcatStrings(*children)

    def tpu_eval(self, ctx) -> DevVal:
        vals = [c.tpu_eval(ctx) for c in self.children]
        acc = vals[0]
        for v in vals[1:]:
            acc = _concat2(acc, v, ctx)
        return acc

    def cpu_eval(self, ctx) -> CpuVal:
        vals = [c.cpu_eval(ctx) for c in self.children]
        n = ctx.num_rows
        out = np.empty(n, dtype=object)
        validity = np.ones(n, dtype=np.bool_)
        for v in vals:
            validity &= v.validity
        for i in range(n):
            out[i] = "".join(str(v.values[i]) for v in vals) if validity[i] else ""
        return CpuVal(T.STRING, out, validity)


def _concat2(a: DevVal, b: DevVal, ctx) -> DevVal:
    la, lb = string_lengths(a), string_lengths(b)
    validity = a.validity & b.validity
    new_lens = jnp.where(validity & ctx.row_mask, la + lb, 0)
    na, nb = int(a.data.shape[0]), int(b.data.shape[0])
    a_base, b_base = a.offsets[:-1], b.offsets[:-1]

    def src(rows, pos):
        from_a = pos < la[rows]
        ia = jnp.clip(a_base[rows] + pos, 0, na - 1)
        ib = jnp.clip(b_base[rows] + pos - la[rows], 0, nb - 1)
        return jnp.where(from_a, a.data[ia], b.data[ib])

    return build_string(T.STRING, new_lens, src, na + nb, validity)


class _NeedlePredicate(Expression):
    """startswith/endswith/contains with a literal needle."""

    def __init__(self, child: Expression, needle: Expression):
        if not isinstance(needle, Expression):
            needle = Literal(str(needle), T.STRING)
        self.children = (child, needle)
        self.dtype = T.BOOLEAN
        self.nullable = child.nullable or needle.nullable

    def with_children(self, children):
        return type(self)(children[0], children[1])

    @property
    def needle(self) -> Optional[str]:
        return _literal_needle(self.children[1])

    def tpu_supported(self, conf):
        if self.needle is None:
            return "search pattern must be a literal for TPU execution"
        return None

    def _match_dev(self, v: DevVal, needle: bytes):
        raise NotImplementedError

    def _match_cpu(self, s: str, needle: str) -> bool:
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        v = self.children[0].tpu_eval(ctx)
        data = self._match_dev(v, self.needle.encode("utf-8"))
        return DevVal(T.BOOLEAN, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        nv = self.children[1].cpu_eval(ctx)
        data = np.fromiter(
            (self._match_cpu(str(s), str(n))
             for s, n in zip(v.values, nv.values)),
            dtype=np.bool_, count=len(v.values))
        return CpuVal(T.BOOLEAN, data, v.validity & nv.validity)


def _match_prefix(v: DevVal, needle: bytes):
    L = len(needle)
    if L == 0:
        return jnp.ones(v.capacity, dtype=jnp.bool_)
    nbytes = int(v.data.shape[0])
    ok = string_lengths(v) >= L
    starts = v.offsets[:-1]
    for k, bch in enumerate(needle):
        idx = jnp.clip(starts + k, 0, nbytes - 1)
        ok = ok & (v.data[idx] == np.uint8(bch))
    return ok


def _match_suffix(v: DevVal, needle: bytes):
    L = len(needle)
    if L == 0:
        return jnp.ones(v.capacity, dtype=jnp.bool_)
    nbytes = int(v.data.shape[0])
    ok = string_lengths(v) >= L
    ends = v.offsets[1:]
    for k, bch in enumerate(needle):
        idx = jnp.clip(ends - L + k, 0, nbytes - 1)
        ok = ok & (v.data[idx] == np.uint8(bch))
    return ok


class StringStartsWith(_NeedlePredicate):
    def _match_dev(self, v, needle):
        return _match_prefix(v, needle)

    def _match_cpu(self, s, needle):
        return s.startswith(needle)


class StringEndsWith(_NeedlePredicate):
    def _match_dev(self, v, needle):
        return _match_suffix(v, needle)

    def _match_cpu(self, s, needle):
        return s.endswith(needle)


class StringContains(_NeedlePredicate):
    def _match_dev(self, v, needle):
        return _rows_with_match(v, needle)

    def _match_cpu(self, s, needle):
        return needle in s


class Like(Expression):
    """SQL LIKE restricted to patterns translatable to prefix/suffix/contains
    tests: 'abc', 'abc%', '%abc', '%abc%', 'a%b'.  Other patterns (including
    '_' wildcards and escapes) fall back to CPU."""

    def __init__(self, child: Expression, pattern: str):
        self.children = (child,)
        self.pattern = pattern
        self.dtype = T.BOOLEAN
        self.nullable = child.nullable

    def with_children(self, children):
        return Like(children[0], self.pattern)

    def _plan(self):
        p = self.pattern
        if "_" in p or "\\" in p:
            return None
        parts = p.split("%")
        if len(parts) == 1:
            return ("exact", parts[0])
        if len(parts) == 2:
            if parts[0] == "" and parts[1] == "":
                return ("any",)
            if parts[1] == "":
                return ("prefix", parts[0])
            if parts[0] == "":
                return ("suffix", parts[1])
            return ("prefix_suffix", parts[0], parts[1])
        if len(parts) == 3 and parts[0] == "" and parts[2] == "":
            return ("contains", parts[1])
        return None

    def tpu_supported(self, conf):
        if self._plan() is None:
            return f"LIKE pattern {self.pattern!r} not supported on TPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        plan = self._plan()
        kind = plan[0]
        if kind in ("any", "exact"):
            # Hash/length-only tests work on dictionary-encoded input.
            from spark_rapids_tpu.exprs.base import eval_maybe_encoded
            v = eval_maybe_encoded(self.children[0], ctx)
        else:
            v = self.children[0].tpu_eval(ctx)
        lens = string_lengths(v)
        if kind == "any":
            data = jnp.ones(v.capacity, dtype=jnp.bool_)
        elif kind == "exact":
            h1, h2 = string_hash2(v)
            e1, e2 = hash_literal2(plan[1])
            data = (h1 == jnp.uint32(e1)) & (h2 == jnp.uint32(e2))
        elif kind == "prefix":
            data = _match_prefix(v, plan[1].encode())
        elif kind == "suffix":
            data = _match_suffix(v, plan[1].encode())
        elif kind == "contains":
            data = _rows_with_match(v, plan[1].encode())
        else:  # prefix_suffix
            pre, suf = plan[1], plan[2]
            data = (_match_prefix(v, pre.encode())
                    & _match_suffix(v, suf.encode())
                    & (lens >= len(pre) + len(suf)))
        return DevVal(T.BOOLEAN, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        import re
        v = self.children[0].cpu_eval(ctx)
        regex = "^" + "".join(
            ".*" if c == "%" else "." if c == "_" else re.escape(c)
            for c in self.pattern) + "$"
        rx = re.compile(regex, re.DOTALL)
        data = np.fromiter((rx.match(str(s)) is not None for s in v.values),
                           dtype=np.bool_, count=len(v.values))
        return CpuVal(T.BOOLEAN, data, v.validity)


class StringLocate(Expression):
    """locate(needle, str): 1-based position of first match, 0 if absent."""

    def __init__(self, needle: Expression, child: Expression):
        if not isinstance(needle, Expression):
            needle = Literal(str(needle), T.STRING)
        self.children = (needle, child)
        self.dtype = T.INT
        self.nullable = child.nullable

    def with_children(self, children):
        return StringLocate(children[0], children[1])

    def tpu_supported(self, conf):
        if _literal_needle(self.children[0]) is None:
            return "locate needle must be a literal for TPU execution"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.children[1].tpu_eval(ctx)
        needle = _literal_needle(self.children[0]).encode("utf-8")
        cap = v.capacity
        if len(needle) == 0:
            return DevVal(T.INT, jnp.ones(cap, dtype=jnp.int32), v.validity)
        nbytes = int(v.data.shape[0])
        match = _find_matches(v, needle)
        rows = jnp.clip(rows_of_positions(v.offsets, nbytes), 0, cap - 1)
        pos_in_row = jnp.arange(nbytes, dtype=jnp.int32) - v.offsets[rows]
        big = jnp.int32(nbytes + 1)
        first = jax.ops.segment_min(jnp.where(match, pos_in_row, big), rows,
                                    num_segments=cap, indices_are_sorted=True)
        data = jnp.where(first >= big, 0, first + 1).astype(jnp.int32)
        return DevVal(T.INT, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[1].cpu_eval(ctx)
        needle = str(_literal_needle(self.children[0]) or "")
        data = np.fromiter((str(s).find(needle) + 1 for s in v.values),
                           dtype=np.int32, count=len(v.values))
        return CpuVal(T.INT, data, v.validity)


def _has_self_overlap(needle: bytes) -> bool:
    """True if the pattern can match at two positions closer than len(needle)."""
    L = len(needle)
    for k in range(1, L):
        if needle[k:] == needle[:-k]:
            return True
    return False


def _replace_match_starts(v: DevVal, match, Ls: int, repl: bytes,
                          ctx) -> DevVal:
    """Replace every Ls-byte run beginning at a True position of ``match``
    (bool[nbytes], match starts fully inside their row, non-overlapping)
    with ``repl``.  Scatter-formulated: copied bytes and replacement bytes
    land at positions shifted by (Lr-Ls) per preceding in-row match."""
    cap = v.capacity
    nbytes = int(v.data.shape[0])
    Lr = len(repl)
    rows = jnp.clip(rows_of_positions(v.offsets, nbytes), 0, cap - 1)
    n_matches = jax.ops.segment_sum(match.astype(jnp.int32), rows,
                                    num_segments=cap, indices_are_sorted=True)
    lens = string_lengths(v)
    new_lens = lens + n_matches * (Lr - Ls)
    new_lens = jnp.where(v.validity & ctx.row_mask, new_lens, 0)
    out_cap = nbytes if Lr <= Ls else nbytes + (nbytes // Ls) * (Lr - Ls)
    row_first_byte = v.offsets[rows]
    pos_in_row = jnp.arange(nbytes, dtype=jnp.int32) - row_first_byte
    starts_i = match.astype(jnp.int32)
    # covered[i] = any match start in (i-Ls, i] -> byte i is replaced.
    csum = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                            jnp.cumsum(starts_i)])
    lo = jnp.maximum(jnp.arange(nbytes) - Ls + 1, 0)
    covered = (csum[jnp.arange(nbytes) + 1] - csum[lo]) > 0
    # Matches before byte i in the same row:
    m_before = csum[jnp.arange(nbytes)]  # global matches strictly before i
    m_before_row_start = csum[jnp.clip(row_first_byte, 0, nbytes)]
    m_in_row_before = m_before - m_before_row_start
    # Output position of each *copied* byte and each *match start*:
    out_pos_copy = pos_in_row + m_in_row_before * (Lr - Ls)
    # Build output via scatter of copied bytes, then scatter replacement
    # bytes at match starts.
    out_offsets = jnp.concatenate([
        jnp.zeros(1, dtype=jnp.int32),
        jnp.cumsum(new_lens).astype(jnp.int32)])
    out_base = out_offsets[rows]
    out_idx_copy = out_base + out_pos_copy
    in_data_mask = jnp.arange(nbytes, dtype=jnp.int32) < v.offsets[-1]
    valid_copy = in_data_mask & ~covered
    out = jnp.zeros(out_cap, dtype=jnp.uint8)
    out = out.at[jnp.where(valid_copy, out_idx_copy, out_cap)].set(
        v.data, mode="drop")
    # match starts: the match at input pos i (m_in_row_before matches
    # before it) maps to output position pos_in_row + m_in_row_before*(Lr-Ls)
    out_idx_match = out_base + pos_in_row + m_in_row_before * (Lr - Ls)
    for k, bch in enumerate(repl):
        out = out.at[jnp.where(match & in_data_mask, out_idx_match + k,
                               out_cap)].set(
            jnp.full(nbytes, bch, dtype=jnp.uint8), mode="drop")
    return DevVal(T.STRING, out, v.validity, out_offsets)


class StringReplace(Expression):
    """replace(str, search, replacement) with literal search/replacement."""

    def __init__(self, child: Expression, search: Expression, replacement: Expression):
        if not isinstance(search, Expression):
            search = Literal(str(search), T.STRING)
        if not isinstance(replacement, Expression):
            replacement = Literal(str(replacement), T.STRING)
        self.children = (child, search, replacement)
        self.dtype = T.STRING
        self.nullable = child.nullable

    def with_children(self, children):
        return StringReplace(*children)

    def tpu_supported(self, conf):
        s = _literal_needle(self.children[1])
        if s is None or _literal_needle(self.children[2]) is None:
            return "replace search/replacement must be literals for TPU"
        if s == "":
            return "replace with empty search is a no-op handled on CPU"
        if _has_self_overlap(s.encode("utf-8")):
            return ("replace search pattern can self-overlap; sequential "
                    "matching required (CPU only)")
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.children[0].tpu_eval(ctx)
        search = _literal_needle(self.children[1]).encode("utf-8")
        repl = _literal_needle(self.children[2]).encode("utf-8")
        match = _find_matches(v, search)
        return _replace_match_starts(v, match, len(search), repl, ctx)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        search = str(_literal_needle(self.children[1]) or "")
        repl = str(_literal_needle(self.children[2]) or "")
        if search == "":
            out = np.array([str(s) for s in v.values], dtype=object)
        else:
            out = np.array([str(s).replace(search, repl) for s in v.values],
                           dtype=object)
        return CpuVal(T.STRING, out, v.validity)


class _Pad(Expression):
    _left = True

    def __init__(self, child: Expression, length: int, pad: str = " "):
        self.children = (child,)
        self.target = int(length)
        self.pad = str(pad)
        self.dtype = T.STRING
        self.nullable = child.nullable

    def with_children(self, children):
        return type(self)(children[0], self.target, self.pad)

    def tpu_supported(self, conf):
        if len(self.pad) != 1:
            return "multi-char pad strings not supported on TPU yet"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.children[0].tpu_eval(ctx)
        cap = v.capacity
        nbytes = int(v.data.shape[0])
        lens = string_lengths(v)
        tgt = jnp.int32(self.target)
        new_lens = jnp.where(v.validity & ctx.row_mask,
                             jnp.full(cap, tgt, dtype=jnp.int32), 0)
        pad_b = np.uint8(ord(self.pad))
        npad = jnp.maximum(tgt - lens, 0)
        base = v.offsets[:-1]

        def src_index(rows, pos):
            if self._left:
                is_pad = pos < npad[rows]
                src = base[rows] + pos - npad[rows]
            else:
                is_pad = pos >= lens[rows]
                src = base[rows] + pos
            byte = v.data[jnp.clip(src, 0, nbytes - 1)]
            return jnp.where(is_pad, pad_b, byte)

        out_cap = max(cap * max(self.target, 1), 16)
        return build_string(T.STRING, new_lens, src_index, out_cap, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        out = np.empty(len(v.values), dtype=object)
        for i, s in enumerate(v.values):
            s = str(s)
            if len(s) >= self.target:
                out[i] = s[: self.target]
            elif self._left:
                out[i] = (self.pad * self.target + s)[-self.target:] \
                    if self.pad else s
            else:
                out[i] = (s + self.pad * self.target)[: self.target] \
                    if self.pad else s
        return CpuVal(T.STRING, out, v.validity)


class StringLPad(_Pad):
    _left = True


class StringRPad(_Pad):
    _left = False


# ---------------------------------------------------------------------------
# regexp_replace / split_part / concat_ws
# (reference: stringFunctions.scala GpuRegExpReplace/GpuStringSplit/
#  GpuConcatWs; the reference likewise transpiles or rejects regex patterns —
#  RegexParser in RegexParser.scala)
# ---------------------------------------------------------------------------

_REGEX_META = set(".^$*+?()[]{}|\\")


def _regex_as_literal(pattern: str) -> Optional[str]:
    """The literal string a regex matches exactly, or None if it uses any
    unescaped metacharacter (conservative transpile, like the reference's
    RegexParser rejecting what cudf can't run)."""
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\":
            if i + 1 >= len(pattern):
                return None
            nxt = pattern[i + 1]
            if nxt in _REGEX_META:
                out.append(nxt)
                i += 2
                continue
            return None  # \d, \s ... not a literal
        if ch in _REGEX_META:
            return None
        out.append(ch)
        i += 1
    return "".join(out)


def _regex_as_byte_class(pattern: str) -> Optional[bytes]:
    """The set of single bytes a regex char-class matches, or None.

    Supports ``[abc]`` and ``[a-z0-9]`` style classes over ASCII (no
    negation); escaped members and range ENDPOINTS (``[\\.-0]``) parse as
    one item each, so ranges with escaped endpoints are exact.
    """
    if len(pattern) < 3 or pattern[0] != "[" or pattern[-1] != "]":
        return None
    inner = pattern[1:-1]
    if inner.startswith("^") or not inner:
        return None

    def parse_item(i):
        """(char, next_i) for one literal-or-escaped class member."""
        ch = inner[i]
        if ch == "\\":
            if i + 1 >= len(inner):
                return None
            nxt = inner[i + 1]
            if nxt not in _REGEX_META and nxt != "-":
                return None  # \d, \s ... not a single char
            return nxt, i + 2
        return ch, i + 1

    members = set()
    i = 0
    while i < len(inner):
        item = parse_item(i)
        if item is None:
            return None
        lo_ch, i = item
        if i < len(inner) and inner[i] == "-" and i + 1 < len(inner):
            hi_item = parse_item(i + 1)
            if hi_item is None:
                return None
            hi_ch, i = hi_item
            lo, hi = ord(lo_ch), ord(hi_ch)
            if lo > hi or hi > 127:
                return None
            members.update(chr(c) for c in range(lo, hi + 1))
            continue
        if ord(lo_ch) > 127:
            return None
        members.add(lo_ch)
    if not members:
        return None
    return bytes(sorted(ord(c) for c in members))


class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement).

    TPU path covers the subset the engine can transpile: patterns that are
    plain literals (after unescaping) reuse the StringReplace kernel, and
    single-char classes like ``[0-9]`` map each member byte.  Everything
    else (real regex) falls back to the CPU engine's ``re`` evaluation —
    the same accept/reject shape as the reference's RegexParser
    (stringFunctions.scala:862 + RegexParser).
    """

    def __init__(self, child: Expression, pattern: Expression,
                 replacement: Expression):
        if not isinstance(pattern, Expression):
            pattern = Literal(str(pattern), T.STRING)
        if not isinstance(replacement, Expression):
            replacement = Literal(str(replacement), T.STRING)
        self.children = (child, pattern, replacement)
        self.dtype = T.STRING
        self.nullable = child.nullable

    def with_children(self, children):
        return RegExpReplace(*children)

    def _plan(self):
        """("literal", s) | ("class", bytes) | None."""
        pat = _literal_needle(self.children[1])
        if pat is None or _literal_needle(self.children[2]) is None:
            return None
        lit = _regex_as_literal(pat)
        if lit is not None and lit != "":
            return ("literal", lit)
        cls = _regex_as_byte_class(pat)
        if cls is not None:
            return ("class", cls)
        return None

    def tpu_supported(self, conf):
        plan = self._plan()
        if plan is None:
            return ("regexp pattern is not in the transpilable subset "
                    "(literal or single-char class); CPU fallback")
        if plan[0] == "literal" and \
                _has_self_overlap(plan[1].encode("utf-8")):
            return ("regexp literal can self-overlap; sequential matching "
                    "required (CPU only)")
        return None

    def tpu_eval(self, ctx) -> DevVal:
        kind, what = self._plan()
        v = self.children[0].tpu_eval(ctx)
        repl = _literal_needle(self.children[2]).encode("utf-8")
        if kind == "literal":
            match = _find_matches(v, what.encode("utf-8"))
            return _replace_match_starts(v, match,
                                         len(what.encode("utf-8")),
                                         repl, ctx)
        # char class: every member byte is a length-1 match
        nbytes = int(v.data.shape[0])
        match = jnp.zeros(nbytes, dtype=jnp.bool_)
        for b in what:
            match = match | (v.data == np.uint8(b))
        in_data = jnp.arange(nbytes, dtype=jnp.int32) < v.offsets[-1]
        return _replace_match_starts(v, match & in_data, 1, repl, ctx)

    def cpu_eval(self, ctx) -> CpuVal:
        import re
        v = self.children[0].cpu_eval(ctx)
        pat = _literal_needle(self.children[1])
        repl = _literal_needle(self.children[2])
        if pat is None or repl is None:
            raise NotImplementedError(
                "regexp_replace pattern/replacement must be literals")
        rx = re.compile(pat)
        # LITERAL replacement (lambda sidesteps python's \\-template
        # expansion, which crashes on '\\U...' and renders '$1' literally
        # anyway) — matches the TPU path; Java $-group references are a
        # documented non-feature (docs/compatibility.md).
        out = np.array([rx.sub(lambda _m: repl, str(s)) for s in v.values],
                       dtype=object)
        return CpuVal(T.STRING, out, v.validity)


class SplitPart(Expression):
    """split_part(str, delimiter, partNum): 1-based field extraction on a
    literal delimiter; out-of-range -> empty string (Spark split_part /
    the getItem(i) shape of GpuStringSplit, stringFunctions.scala)."""

    def __init__(self, child: Expression, delimiter, part):
        if not isinstance(delimiter, Expression):
            delimiter = Literal(str(delimiter), T.STRING)
        self.children = (child, delimiter)
        self.part = int(part)
        if self.part == 0:
            # Spark raises for partNum 0 (ANSI and non-ANSI alike)
            raise ValueError("split_part: partNum must not be 0")
        self.dtype = T.STRING
        self.nullable = child.nullable

    def with_children(self, children):
        return SplitPart(children[0], children[1], self.part)

    def tpu_supported(self, conf):
        d = _literal_needle(self.children[1])
        if d is None or d == "":
            return "split delimiter must be a non-empty literal"
        if self.part < 0:
            return "negative part numbers run on CPU"
        if _has_self_overlap(d.encode("utf-8")):
            return "split delimiter can self-overlap (CPU only)"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.children[0].tpu_eval(ctx)
        delim = _literal_needle(self.children[1]).encode("utf-8")
        Ld = len(delim)
        j = self.part - 1  # 0-based part index
        cap = v.capacity
        nbytes = int(v.data.shape[0])
        match = _find_matches(v, delim)
        rows = jnp.clip(rows_of_positions(v.offsets, nbytes), 0, cap - 1)
        pos = jnp.arange(nbytes, dtype=jnp.int32)
        starts_i = match.astype(jnp.int32)
        csum = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                                jnp.cumsum(starts_i)])
        rank = csum[pos] - csum[jnp.clip(v.offsets[rows], 0, nbytes)]
        big = jnp.int32(1 << 30)
        # in-row byte position of the (j-1)-th and j-th delimiter match
        def match_pos(k):
            sel = match & (rank == k)
            return jax.ops.segment_min(
                jnp.where(sel, pos, big), rows, num_segments=cap, indices_are_sorted=True)

        n_matches = jax.ops.segment_sum(starts_i, rows, num_segments=cap, indices_are_sorted=True)
        row_start = v.offsets[:-1]
        row_end = v.offsets[1:]
        start = row_start if j == 0 else \
            jnp.minimum(match_pos(j - 1) + Ld, row_end)
        end = jnp.where(n_matches > j, match_pos(j), row_end)
        exists = n_matches >= j  # part j exists when >= j delimiters... 
        # parts = n_matches + 1, so part index j valid iff j <= n_matches
        new_lens = jnp.where(exists, jnp.maximum(end - start, 0), 0)
        new_lens = jnp.where(v.validity & ctx.row_mask, new_lens, 0)
        rel_start = (start - row_start).astype(jnp.int32)
        return _gather_substring(v, rel_start, new_lens, nbytes, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        d = _literal_needle(self.children[1])
        if d is None:
            raise NotImplementedError(
                "split_part delimiter must be a literal")
        out = np.empty(len(v.values), dtype=object)
        for i, s in enumerate(v.values):
            parts = str(s).split(d) if d else [str(s)]
            k = self.part
            if k < 0:
                k = len(parts) + k + 1
            out[i] = parts[k - 1] if 1 <= k <= len(parts) else ""
        return CpuVal(T.STRING, out, v.validity)


class ConcatWs(Expression):
    """concat_ws(sep, cols...): join non-NULL values with a literal
    separator; NULL inputs are skipped (never nullify the result)."""

    def __init__(self, sep, *children: Expression):
        self.sep = str(sep)
        self.children = tuple(children)
        self.dtype = T.STRING
        self.nullable = False

    def with_children(self, children):
        return ConcatWs(self.sep, *children)

    def tpu_supported(self, conf):
        for c in self.children:
            if not c.dtype.is_string:
                return f"concat_ws child must be string, got {c.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        cap = ctx.capacity
        if not self.children:
            # Spark: concat_ws(sep) with no columns is '' per row
            return DevVal(T.STRING, jnp.zeros(16, dtype=jnp.uint8),
                          jnp.ones(cap, dtype=jnp.bool_),
                          jnp.zeros(cap + 1, dtype=jnp.int32))
        sep = self.sep.encode("utf-8")
        Lsep = len(sep)
        sep_arr = jnp.asarray(np.frombuffer(sep, dtype=np.uint8)) \
            if Lsep else jnp.zeros(1, dtype=jnp.uint8)
        vals = [c.tpu_eval(ctx) for c in self.children]
        # normalize the accumulator: NULL rows contribute zero bytes
        l0 = jnp.where(vals[0].validity, string_lengths(vals[0]), 0)
        acc = _gather_substring(
            vals[0],
            jnp.zeros(cap, dtype=jnp.int32),
            jnp.where(vals[0].validity & ctx.row_mask, l0, 0),
            int(vals[0].data.shape[0]),
            jnp.ones(cap, dtype=jnp.bool_))
        has_any = vals[0].validity
        for v in vals[1:]:
            la = string_lengths(acc)
            lv = jnp.where(v.validity, string_lengths(v), 0)
            add_sep = has_any & v.validity
            new_lens = la + jnp.where(v.validity,
                                      lv + jnp.where(add_sep, Lsep, 0), 0)
            new_lens = jnp.where(ctx.row_mask, new_lens, 0)
            na, nv = int(acc.data.shape[0]), int(v.data.shape[0])
            a_base, v_base = acc.offsets[:-1], v.offsets[:-1]
            sep_start = la  # in-row position where separator begins
            v_start = la + jnp.where(add_sep, Lsep, 0)

            def src(rows, pos, acc=acc, v=v, la=la, sep_start=sep_start,
                    v_start=v_start, na=na, nv=nv, a_base=a_base,
                    v_base=v_base):
                from_a = pos < la[rows]
                in_sep = (~from_a) & (pos < v_start[rows])
                ia = jnp.clip(a_base[rows] + pos, 0, na - 1)
                iv = jnp.clip(v_base[rows] + pos - v_start[rows], 0, nv - 1)
                isep = jnp.clip(pos - sep_start[rows], 0,
                                max(Lsep - 1, 0))
                return jnp.where(
                    from_a, acc.data[ia],
                    jnp.where(in_sep, sep_arr[isep], v.data[iv]))

            out_cap = na + nv + (cap * Lsep if Lsep else 0)
            acc = build_string(T.STRING, new_lens, src, out_cap,
                               jnp.ones(cap, dtype=jnp.bool_))
            has_any = has_any | v.validity
        return acc

    def cpu_eval(self, ctx) -> CpuVal:
        vals = [c.cpu_eval(ctx) for c in self.children]
        n = ctx.num_rows
        out = np.empty(n, dtype=object)
        for i in range(n):
            pieces = [str(v.values[i]) for v in vals if v.validity[i]]
            out[i] = self.sep.join(pieces)
        return CpuVal(T.STRING, out, np.ones(n, dtype=np.bool_))


class InitCap(_CaseMap):
    """initcap: lowercase everything, uppercase the first letter of each
    whitespace-separated word (Spark InitCap / GpuInitCap)."""

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        data = v.data
        nbytes = int(data.shape[0])
        # word starts: first byte of each row (scatter of row offsets)
        # or a byte following a space
        starts = jnp.zeros(nbytes + 1, dtype=jnp.bool_) \
            .at[jnp.clip(v.offsets, 0, nbytes)].set(True)[:nbytes]
        after_space = jnp.concatenate(
            [jnp.ones(1, dtype=jnp.bool_), data[:-1] == 32])
        head = starts | after_space
        is_upper = (data >= 65) & (data <= 90)
        is_lower = (data >= 97) & (data <= 122)
        lowered = jnp.where(is_upper, data + 32, data)
        out = jnp.where(head & is_lower, data - 32,
                        jnp.where(~head & is_upper, lowered, data))
        return DevVal(T.STRING, out.astype(jnp.uint8), v.validity,
                      v.offsets)

    def _map_cpu(self, s):
        # ASCII-only, matching the device byte mapping (same convention
        # as Upper/Lower above)
        out = []
        head = True
        for ch in s:
            if head and "a" <= ch <= "z":
                out.append(chr(ord(ch) - 32))
            elif not head and "A" <= ch <= "Z":
                out.append(chr(ord(ch) + 32))
            else:
                out.append(ch)
            head = ch == " "
        return "".join(out)


class SubstringIndex(Expression):
    """substring_index(str, delim, count): prefix before the count-th
    delimiter (count > 0) or suffix after the |count|-th-from-the-right
    delimiter (count < 0); whole string when not enough delimiters
    (Spark SubstringIndex / GpuSubstringIndex)."""

    def __init__(self, child: Expression, delimiter, count: int):
        if not isinstance(delimiter, Expression):
            delimiter = Literal(str(delimiter), T.STRING)
        self.children = (child, delimiter)
        self.count = int(count)
        self.dtype = T.STRING
        self.nullable = child.nullable

    def with_children(self, children):
        return SubstringIndex(children[0], children[1], self.count)

    def tpu_supported(self, conf):
        d = _literal_needle(self.children[1])
        if d is None or d == "":
            return "substring_index delimiter must be a non-empty literal"
        if _has_self_overlap(d.encode("utf-8")):
            return "substring_index delimiter can self-overlap (CPU only)"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.children[0].tpu_eval(ctx)
        delim = _literal_needle(self.children[1]).encode("utf-8")
        Ld = len(delim)
        cap = v.capacity
        nbytes = int(v.data.shape[0])
        row_start, row_end = v.offsets[:-1], v.offsets[1:]
        if self.count == 0:
            zero = jnp.zeros(cap, dtype=jnp.int32)
            return _gather_substring(v, zero, zero, nbytes, v.validity)
        match = _find_matches(v, delim)
        rows = jnp.clip(rows_of_positions(v.offsets, nbytes), 0, cap - 1)
        pos = jnp.arange(nbytes, dtype=jnp.int32)
        starts_i = match.astype(jnp.int32)
        csum = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                                jnp.cumsum(starts_i)])
        rank = csum[pos] - csum[jnp.clip(v.offsets[rows], 0, nbytes)]
        n_matches = jax.ops.segment_sum(starts_i, rows, num_segments=cap,
                                        indices_are_sorted=True)
        big = jnp.int32(1 << 30)
        if self.count > 0:
            # byte position of the (count-1)-th match per row
            sel = match & (rank == self.count - 1)
            kpos = jax.ops.segment_min(jnp.where(sel, pos, big), rows,
                                       num_segments=cap,
                                       indices_are_sorted=True)
            start = row_start
            end = jnp.where(n_matches >= self.count, kpos, row_end)
        else:
            # match index n_matches + count (0-based from the left)
            k = n_matches + self.count  # per-row target rank
            sel = match & (rank == k[rows])
            kpos = jax.ops.segment_min(jnp.where(sel, pos, big), rows,
                                       num_segments=cap,
                                       indices_are_sorted=True)
            start = jnp.where(n_matches >= -self.count, kpos + Ld,
                              row_start)
            end = row_end
        new_lens = jnp.maximum(end - start, 0)
        new_lens = jnp.where(v.validity & ctx.row_mask, new_lens, 0)
        rel_start = (start - row_start).astype(jnp.int32)
        return _gather_substring(v, rel_start, new_lens, nbytes,
                                 v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        d = _literal_needle(self.children[1])
        if d is None:
            raise NotImplementedError(
                "substring_index delimiter must be a literal")
        out = np.empty(len(v.values), dtype=object)
        for i, s in enumerate(v.values):
            s = str(s)
            c = self.count
            if c == 0 or not d:
                out[i] = ""
            elif c > 0:
                parts = s.split(d)
                out[i] = d.join(parts[:c]) if len(parts) > c else s
            else:
                parts = s.split(d)
                out[i] = d.join(parts[c:]) if len(parts) > -c else s
        return CpuVal(T.STRING, out, v.validity)


class StringSplit(Expression):
    """split(str, delim) -> array<string> (Spark StringSplit).  The
    engine's array columns hold fixed-width elements, so an array of
    variable-length strings cannot live on the device — this expression
    always runs on the CPU engine (planner fallback), like any
    type-unsupported expression in the reference.  The delimiter is a
    regex, matching Spark's split()."""

    def __init__(self, child: Expression, delimiter):
        if not isinstance(delimiter, Expression):
            delimiter = Literal(str(delimiter), T.STRING)
        self.children = (child, delimiter)
        self.dtype = T.ArrayType(T.STRING)
        self.nullable = child.nullable

    def with_children(self, children):
        return StringSplit(children[0], children[1])

    def tpu_supported(self, conf):
        return ("split produces array<string>; variable-length array "
                "elements are CPU-only")

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        d = _literal_needle(self.children[1])
        if d is None:
            raise NotImplementedError("split delimiter must be a literal")
        import re
        pat = re.compile(d) if d else None
        out = np.empty(len(v.values), dtype=object)
        for i, s in enumerate(v.values):
            out[i] = pat.split(str(s)) if pat else [str(s)]
        return CpuVal(self.dtype, out, v.validity)


class Hex(Expression):
    """hex(integral) -> uppercase hex string (Spark Hex / GpuOverrides'
    hex; negative longs render as 16-digit two's complement).  Device
    path computes nibbles with arithmetic shifts — no 64-bit bitcast,
    which the chip's f64/i64 emulation cannot do."""

    def __init__(self, child: Expression):
        self.children = (child,)
        self.dtype = T.STRING
        self.nullable = child.nullable

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return Hex(children[0])

    def tpu_supported(self, conf):
        if self.child.dtype is not T.NULL and \
                not self.child.dtype.is_integral:
            return "hex over non-integral inputs runs on CPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        cap = v.capacity
        x = v.data.astype(jnp.int64)
        # nibble k (0 = most significant); arithmetic >> keeps two's
        # complement bits, & 15 extracts the nibble
        nibbles = jnp.stack(
            [(x >> (4 * (15 - k))) & 15 for k in range(16)],
            axis=1).astype(jnp.int32)                       # [cap, 16]
        digits = jnp.where(nibbles < 10, nibbles + 48,
                           nibbles + 55).astype(jnp.uint8)
        # length = 16 - leading zero nibbles (min 1 so 0 -> "0")
        nz = nibbles != 0
        first_nz = jnp.argmax(nz, axis=1)                   # 0 if none
        any_nz = jnp.any(nz, axis=1)
        lens = jnp.where(any_nz, 16 - first_nz, 1).astype(jnp.int32)
        live = v.validity & ctx.row_mask
        lens = jnp.where(live, lens, 0)
        flat = digits.reshape(-1)
        offsets16 = (jnp.arange(cap + 1, dtype=jnp.int32) * 16)
        v16 = DevVal(T.STRING, flat, v.validity, offsets16)
        rel_start = jnp.where(any_nz, first_nz, 15).astype(jnp.int32)
        # cap is a power-of-two bucket, so cap*16 is too (stable compile
        # cache keys)
        return _gather_substring(v16, rel_start, lens, cap * 16,
                                 v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = np.empty(len(v.values), dtype=object)
        is_str = self.child.dtype.is_string
        for i, x in enumerate(v.values):
            if is_str:
                # Spark hex(string) = hex of the UTF-8 bytes
                out[i] = str(x).encode("utf-8").hex().upper()
                continue
            if self.child.dtype.is_fractional:
                # Spark's implicit double->bigint cast: truncate toward
                # zero, NaN -> 0, +-inf/out-of-range saturate at the
                # long bounds (same rules as Cast.cpu_eval)
                xf = float(x)
                if xf != xf:
                    xi = 0
                elif xf >= 2.0 ** 63:
                    xi = (1 << 63) - 1
                elif xf < -(2.0 ** 63):
                    xi = -(1 << 63)
                else:
                    xi = int(xf)
            else:
                xi = int(x)  # int64-exact: no float round trip
            out[i] = format(xi if xi >= 0 else xi + (1 << 64), "X")
        return CpuVal(T.STRING, out, v.validity)

