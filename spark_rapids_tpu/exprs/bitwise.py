"""Bitwise expressions (reference: bitwise.scala, 145 LoC — GpuBitwiseAnd/
Or/Xor/Not, GpuShiftLeft/Right/RightUnsigned).

Java shift semantics: the shift amount is masked to the operand width
(``x << (s & 31)`` for int, ``& 63`` for long); ``>>>`` is a logical shift
implemented via an unsigned view.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, CpuVal, DevVal, UnaryExpression, promote_cpu,
    promote_dev,
)


class _BitwiseBinary(BinaryExpression):
    def _compute(self, x, y):
        raise NotImplementedError

    def tpu_supported(self, conf):
        for c in (self.left, self.right):
            if not c.dtype.is_integral:
                return f"bitwise op needs integral inputs, got {c.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        a, b, out = promote_dev(self.left.tpu_eval(ctx),
                                self.right.tpu_eval(ctx))
        data = self._compute(a.data, b.data)
        return DevVal(out, data.astype(out.jnp_dtype),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b, out = promote_cpu(self.left.cpu_eval(ctx),
                                self.right.cpu_eval(ctx))
        data = self._compute(a.values, b.values)
        return CpuVal(out, data.astype(out.np_dtype),
                      a.validity & b.validity)


class BitwiseAnd(_BitwiseBinary):
    def _compute(self, x, y):
        return x & y


class BitwiseOr(_BitwiseBinary):
    def _compute(self, x, y):
        return x | y


class BitwiseXor(_BitwiseBinary):
    def _compute(self, x, y):
        return x ^ y


class BitwiseNot(UnaryExpression):
    def _resolve_type(self):
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        if not self.child.dtype.is_integral:
            return f"bitwise not needs an integral input, got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(v.dtype, ~v.data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(v.dtype, ~v.values, v.validity)


class _Shift(BinaryExpression):
    """Base: value {int,long} shifted by an int amount (java-masked)."""

    def _resolve_type(self):
        self.dtype = self.left.dtype if self.left.dtype == T.LONG else T.INT
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_supported(self, conf):
        if self.left.dtype not in (T.BYTE, T.SHORT, T.INT, T.LONG):
            return f"shift needs an integral value, got {self.left.dtype}"
        if not self.right.dtype.is_integral:
            return f"shift amount must be integral, got {self.right.dtype}"
        return None

    def _mask(self):
        return 63 if self.dtype == T.LONG else 31

    def _compute(self, x, s, xp):
        raise NotImplementedError

    def tpu_eval(self, ctx) -> DevVal:
        a = self.left.tpu_eval(ctx)
        b = self.right.tpu_eval(ctx)
        x = a.data.astype(self.dtype.jnp_dtype)
        s = (b.data.astype(jnp.int32) & self._mask()).astype(x.dtype)
        data = self._compute(x, s, jnp)
        return DevVal(self.dtype, data.astype(self.dtype.jnp_dtype),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a = self.left.cpu_eval(ctx)
        b = self.right.cpu_eval(ctx)
        x = a.values.astype(self.dtype.np_dtype)
        s = (b.values.astype(np.int64) & self._mask()).astype(x.dtype)
        with np.errstate(all="ignore"):
            data = self._compute(x, s, np)
        return CpuVal(self.dtype, data.astype(self.dtype.np_dtype),
                      a.validity & b.validity)


class ShiftLeft(_Shift):
    def _compute(self, x, s, xp):
        return x << s


class ShiftRight(_Shift):
    """Arithmetic shift (java >>): sign-extending."""

    def _compute(self, x, s, xp):
        return x >> s


class ShiftRightUnsigned(_Shift):
    """Logical shift (java >>>): shift the unsigned bit pattern."""

    def _compute(self, x, s, xp):
        udt = xp.uint64 if self.dtype == T.LONG else xp.uint32
        ux = x.view(udt) if xp is np else x.astype(udt)
        us = s.view(udt) if xp is np else s.astype(udt)
        shifted = ux >> us
        return shifted.view(x.dtype) if xp is np else shifted.astype(x.dtype)
