"""Expression base classes and evaluation contexts.

Reference analogue: GpuExpressions.scala (Unary/Binary/Ternary columnarEval
traits) + GpuBoundAttribute.scala.  ``tpu_eval`` runs inside a traced (jit)
stage over a :class:`~spark_rapids_tpu.batch.ColumnBatch`; ``cpu_eval`` is the
numpy oracle with Spark CPU semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import ColumnBatch, DeviceColumn, HostBatch, HostColumn


@dataclasses.dataclass
class DevVal:
    """An evaluated expression on device: dense buffers + validity mask.

    For strings ``data`` is the flat uint8 byte buffer and ``offsets`` the
    int32[cap+1] row offsets; otherwise ``data`` is [cap] of the jnp dtype.

    A dictionary-encoded string value (scan v2) additionally carries
    ``codes`` (int32[cap] row -> entry indices; data/offsets then describe
    the dictionary ENTRIES) and the static ``mat_byte_cap`` it would
    materialize into.  Only :func:`eval_maybe_encoded` produces these —
    ``from_column`` always materializes, so no kernel sees an encoded
    value it did not ask for.
    """

    dtype: T.DataType
    data: Any
    validity: Any
    offsets: Any = None
    codes: Any = None
    mat_byte_cap: int = 0

    @property
    def capacity(self) -> int:
        if self.codes is not None:
            return int(self.codes.shape[0])
        if self.offsets is not None:
            return int(self.offsets.shape[0]) - 1
        return int(self.data.shape[0])

    def to_column(self) -> DeviceColumn:
        return DeviceColumn(self.dtype, self.data, self.validity,
                            self.offsets, self.codes, self.mat_byte_cap)

    @staticmethod
    def from_column(col: DeviceColumn) -> "DevVal":
        if col.codes is not None:
            from spark_rapids_tpu.kernels.layout import dict_decode_column
            col = dict_decode_column(col)
        return DevVal(col.dtype, col.data, col.validity, col.offsets)

    @staticmethod
    def from_column_encoded(col: DeviceColumn) -> "DevVal":
        """Wrap a column verbatim, KEEPING dictionary encoding — only for
        callers that handle encoded values (hash/eq/group-key paths)."""
        return DevVal(col.dtype, col.data, col.validity, col.offsets,
                      col.codes, col.mat_byte_cap)

    def tree_flatten(self):
        if self.codes is not None:
            return ((self.data, self.validity, self.offsets, self.codes),
                    (self.dtype, True, True, self.mat_byte_cap))
        if self.offsets is None:
            return (self.data, self.validity), (self.dtype, False, False, 0)
        return ((self.data, self.validity, self.offsets),
                (self.dtype, True, False, 0))

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, has_offsets, has_codes, mat_byte_cap = aux
        if has_codes:
            data, validity, offsets, codes = children
            return cls(dtype, data, validity, offsets, codes, mat_byte_cap)
        if has_offsets:
            data, validity, offsets = children
            return cls(dtype, data, validity, offsets)
        data, validity = children
        return cls(dtype, data, validity, None)


jax.tree_util.register_pytree_node(
    DevVal, DevVal.tree_flatten, DevVal.tree_unflatten
)


@dataclasses.dataclass
class CpuVal:
    """Numpy evaluation result (strings: object array of str)."""

    dtype: T.DataType
    values: np.ndarray
    validity: np.ndarray

    def to_column(self) -> HostColumn:
        return HostColumn(self.dtype, self.values, self.validity)

    @staticmethod
    def from_column(col: HostColumn) -> "CpuVal":
        return CpuVal(col.dtype, col.values, col.validity)


class TpuEvalCtx:
    """Evaluation context for one device batch inside a traced stage."""

    def __init__(self, batch: ColumnBatch):
        self.batch = batch
        self.capacity = batch.capacity
        self.row_mask = batch.row_mask
        self.num_rows = batch.num_rows
        # partition_index is used by nondeterministic exprs (SparkPartitionID).
        self.partition_index = 0
        self.base_row_id = jnp.asarray(0, dtype=jnp.int64)


class CpuEvalCtx:
    def __init__(self, batch: HostBatch):
        self.batch = batch
        self.num_rows = batch.num_rows
        self.partition_index = 0
        self.base_row_id = 0


def _fp(v) -> str:
    """Encode a value for Expression.fingerprint (mirrors the
    plan_fingerprint encoder in plan/logical.py)."""
    if isinstance(v, Expression):
        return v.fingerprint()
    if isinstance(v, SortOrder):
        return f"SO({_fp(v.child)},{v.ascending},{v.nulls_first})"
    if isinstance(v, (str, int, float, bool, type(None))):
        return repr(v)
    if isinstance(v, T.DataType):
        return str(v)
    if isinstance(v, T.Schema):
        return str(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_fp(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(f"{_fp(k)}:{_fp(x)}" for k, x in sorted(
            v.items(), key=lambda kv: str(kv[0]))) + "}"
    return f"id:{id(v):x}"


class Expression:
    """Declarative expression tree node.

    Subclasses define ``children``, resolve ``dtype``/``nullable`` in
    ``__init__``, and implement ``tpu_eval``/``cpu_eval``.
    """

    children: Tuple["Expression", ...] = ()
    dtype: T.DataType = T.NULL
    nullable: bool = True

    # -- construction sugar used by the DataFrame frontend ------------------

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    @property
    def name(self) -> str:
        return type(self).__name__

    def __repr__(self) -> str:
        args = ", ".join(repr(c) for c in self.children)
        return f"{self.name}({args})"

    def fingerprint(self) -> str:
        """Structural identity INCLUDING non-child attributes (Lag.offset,
        Percentile.percentage, window frames...) — repr() prints only
        class + children, so two semantically different expressions can
        share a repr.  Use this for any dedup/reuse keying."""
        parts = [type(self).__name__]
        for k, a in sorted(vars(self).items()):
            if k == "children":
                continue
            parts.append(f"{k}={_fp(a)}")
        kids = ",".join(_fp(c) for c in self.children)
        return f"{'|'.join(parts)}({kids})"

    # -- resolution ---------------------------------------------------------

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        """Rebuild this node with new children (default: positional ctor)."""
        return type(self)(*children)

    def transform_up(self, fn) -> "Expression":
        new_children = [c.transform_up(fn) for c in self.children]
        node = self if all(a is b for a, b in zip(new_children, self.children)) \
            else self.with_children(new_children)
        return fn(node)

    def collect(self, pred) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    @property
    def references(self) -> List[str]:
        return [e.column for e in self.collect(lambda e: isinstance(e, ColumnRef))]

    # -- evaluation ---------------------------------------------------------

    def tpu_eval(self, ctx: TpuEvalCtx) -> DevVal:
        raise NotImplementedError(f"{self.name}.tpu_eval")

    def cpu_eval(self, ctx: CpuEvalCtx) -> CpuVal:
        raise NotImplementedError(f"{self.name}.cpu_eval")

    # -- planner hooks ------------------------------------------------------

    def tpu_supported(self, conf) -> Optional[str]:
        """Return None if supported on TPU, else a willNotWorkOnTpu reason."""
        if isinstance(self.dtype, T.ArrayType):
            # fixed-width-element arrays ride the varlen (offsets) layout;
            # consumers beyond project/filter/explode are gated at plan level
            return None
        if self.dtype not in T.ALL_TYPES and not isinstance(self.dtype, T.NullType):
            return f"unsupported result type {self.dtype}"
        return None


class ColumnRef(Expression):
    """Unresolved attribute: refers to an input column by name."""

    def __init__(self, column: str, dtype: T.DataType = T.NULL,
                 nullable: bool = True):
        self.column = column
        self.dtype = dtype
        self.nullable = nullable
        self.children = ()

    def with_children(self, children):
        return self

    @property
    def name(self):
        return f"col({self.column})"

    def __repr__(self):
        return f"`{self.column}`"

    def tpu_eval(self, ctx: TpuEvalCtx) -> DevVal:
        return DevVal.from_column(ctx.batch.column(self.column))

    def cpu_eval(self, ctx: CpuEvalCtx) -> CpuVal:
        return CpuVal.from_column(ctx.batch.column(self.column))


class BoundRef(Expression):
    """Reference bound to an input ordinal (GpuBoundAttribute.scala analogue)."""

    def __init__(self, ordinal: int, dtype: T.DataType, nullable: bool = True):
        self.ordinal = ordinal
        self.dtype = dtype
        self.nullable = nullable
        self.children = ()

    def with_children(self, children):
        return self

    def __repr__(self):
        return f"input[{self.ordinal}]"

    def tpu_eval(self, ctx: TpuEvalCtx) -> DevVal:
        return DevVal.from_column(ctx.batch.columns[self.ordinal])

    def cpu_eval(self, ctx: CpuEvalCtx) -> CpuVal:
        return CpuVal.from_column(ctx.batch.columns[self.ordinal])


def eval_maybe_encoded(expr: "Expression", ctx: TpuEvalCtx) -> DevVal:
    """Evaluate ``expr``, keeping dictionary encoding when it is a bare
    column reference.  Only hash/eq-based consumers (string equality
    predicates, group keys) may call this — every other path goes through
    ``tpu_eval`` → ``from_column`` which materializes."""
    while isinstance(expr, Alias):
        expr = expr.children[0]
    if isinstance(expr, ColumnRef):
        return DevVal.from_column_encoded(ctx.batch.column(expr.column))
    if isinstance(expr, BoundRef):
        return DevVal.from_column_encoded(ctx.batch.columns[expr.ordinal])
    return expr.tpu_eval(ctx)


class Literal(Expression):
    def __init__(self, value: Any, dtype: Optional[T.DataType] = None):
        if dtype is None:
            dtype = infer_literal_type(value)
        self.value = value
        self.dtype = dtype
        self.nullable = value is None
        self.children = ()

    def with_children(self, children):
        return self

    def __repr__(self):
        return f"lit({self.value!r})"

    def tpu_eval(self, ctx: TpuEvalCtx) -> DevVal:
        cap = ctx.capacity
        if self.value is None:
            validity = jnp.zeros(cap, dtype=jnp.bool_)
            if self.dtype.is_string:
                return DevVal(self.dtype, jnp.zeros(16, dtype=jnp.uint8), validity,
                              jnp.zeros(cap + 1, dtype=jnp.int32))
            return DevVal(self.dtype, jnp.zeros(cap, dtype=self.dtype.jnp_dtype),
                          validity)
        validity = jnp.ones(cap, dtype=jnp.bool_)
        if self.dtype.is_string:
            raw = np.frombuffer(str(self.value).encode("utf-8"), dtype=np.uint8)
            nbytes = max(len(raw), 1)
            data = jnp.zeros(cap * nbytes, dtype=jnp.uint8)
            tiled = jnp.tile(jnp.asarray(raw, dtype=jnp.uint8), cap) if len(raw) \
                else jnp.zeros(0, dtype=jnp.uint8)
            data = data.at[: tiled.shape[0]].set(tiled) if len(raw) else data
            offsets = jnp.arange(cap + 1, dtype=jnp.int32) * len(raw)
            return DevVal(self.dtype, data, validity, offsets)
        val = jnp.asarray(self.value, dtype=self.dtype.jnp_dtype)
        return DevVal(self.dtype, jnp.full(cap, val, dtype=self.dtype.jnp_dtype),
                      validity)

    def cpu_eval(self, ctx: CpuEvalCtx) -> CpuVal:
        n = ctx.num_rows
        if self.value is None:
            validity = np.zeros(n, dtype=np.bool_)
            if self.dtype.is_string:
                return CpuVal(self.dtype, np.array([""] * n, dtype=object), validity)
            return CpuVal(self.dtype, np.zeros(n, dtype=self.dtype.np_dtype), validity)
        validity = np.ones(n, dtype=np.bool_)
        if self.dtype.is_string:
            return CpuVal(self.dtype, np.array([str(self.value)] * n, dtype=object),
                          validity)
        return CpuVal(self.dtype,
                      np.full(n, self.value, dtype=self.dtype.np_dtype), validity)


def infer_literal_type(value: Any) -> T.DataType:
    if value is None:
        return T.NULL
    if isinstance(value, bool):
        return T.BOOLEAN
    if isinstance(value, int):
        return T.INT if -(2 ** 31) <= value < 2 ** 31 else T.LONG
    if isinstance(value, float):
        return T.DOUBLE
    if isinstance(value, (str, bytes)):
        return T.STRING
    raise TypeError(f"cannot infer literal type for {value!r}")


class Alias(Expression):
    def __init__(self, child: Expression, alias_name: str):
        self.children = (child,)
        self.alias_name = alias_name
        self.dtype = child.dtype
        self.nullable = child.nullable

    def with_children(self, children):
        return Alias(children[0], self.alias_name)

    def __repr__(self):
        return f"{self.children[0]!r} AS {self.alias_name}"

    def tpu_eval(self, ctx):
        return self.children[0].tpu_eval(ctx)

    def cpu_eval(self, ctx):
        return self.children[0].cpu_eval(ctx)

    def tpu_supported(self, conf):
        return self.children[0].tpu_supported(conf)


@dataclasses.dataclass
class SortOrder:
    """Sort key spec (GpuSortOrder analogue)."""

    child: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: Spark = nulls first iff asc

    def __post_init__(self):
        if self.nulls_first is None:
            self.nulls_first = self.ascending


def output_name(expr: Expression, ordinal: int) -> str:
    if isinstance(expr, Alias):
        return expr.alias_name
    if isinstance(expr, ColumnRef):
        return expr.column
    return f"_c{ordinal}"


def resolve(expr: Expression, schema: T.Schema) -> Expression:
    """Resolve ColumnRefs against a schema, filling in dtype/nullable, and
    re-deriving result types bottom-up."""

    def fix(e: Expression) -> Expression:
        if isinstance(e, ColumnRef):
            f = schema.field(e.column)
            return ColumnRef(e.column, f.dtype, f.nullable)
        return e

    def rebuild(e: Expression) -> Expression:
        new_children = [rebuild(c) for c in e.children]
        e2 = fix(e)
        if new_children and not all(
                a is b for a, b in zip(new_children, e2.children)):
            e2 = e2.with_children(new_children)
        elif e2 is e and not e.children:
            pass
        return e2

    return rebuild(expr)


def bind_references(expr: Expression, schema: T.Schema) -> Expression:
    """Replace resolved ColumnRefs with ordinal BoundRefs."""

    def fn(e: Expression) -> Expression:
        if isinstance(e, ColumnRef):
            f = schema.field(e.column)
            return BoundRef(schema.index_of(e.column), f.dtype, f.nullable)
        return e

    return expr.transform_up(fn)


# ---------------------------------------------------------------------------
# Shared kernel helpers
# ---------------------------------------------------------------------------


def promote_dev(a: DevVal, b: DevVal) -> Tuple[DevVal, DevVal, T.DataType]:
    out = T.promote(a.dtype, b.dtype)
    return cast_dev(a, out), cast_dev(b, out), out


def cast_dev(v: DevVal, to: T.DataType) -> DevVal:
    if v.dtype == to:
        return v
    assert not v.dtype.is_string and not to.is_string
    return DevVal(to, v.data.astype(to.jnp_dtype), v.validity)


def promote_cpu(a: CpuVal, b: CpuVal) -> Tuple[CpuVal, CpuVal, T.DataType]:
    out = T.promote(a.dtype, b.dtype)
    return cast_cpu(a, out), cast_cpu(b, out), out


def cast_cpu(v: CpuVal, to: T.DataType) -> CpuVal:
    if v.dtype == to:
        return v
    return CpuVal(to, v.values.astype(to.np_dtype), v.validity)


class UnaryExpression(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)
        self._resolve_type()

    @property
    def child(self) -> Expression:
        return self.children[0]

    def _resolve_type(self):
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable


class BinaryExpression(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)
        self._resolve_type()

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    def _resolve_type(self):
        self.dtype = T.promote(self.left.dtype, self.right.dtype)
        self.nullable = self.left.nullable or self.right.nullable
