"""Math expressions (reference: mathExpressions.scala, 378 LoC).

All unary math returns double (Spark semantics); domain errors produce NaN,
matching Spark CPU (java.lang.Math) behavior.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, CpuVal, DevVal, UnaryExpression, cast_cpu, cast_dev,
)


class _UnaryMathExpression(UnaryExpression):
    _jnp = None  # staticmethod set by _make_unary
    _np = None

    def _resolve_type(self):
        self.dtype = T.DOUBLE
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = cast_dev(self.child.tpu_eval(ctx), T.DOUBLE)
        return DevVal(T.DOUBLE, self._jnp(v.data), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = cast_cpu(self.child.cpu_eval(ctx), T.DOUBLE)
        with np.errstate(all="ignore"):
            data = self._np(v.values)
        return CpuVal(T.DOUBLE, np.asarray(data, dtype=np.float64), v.validity)


def _make_unary(name, jnp_fn, np_fn):
    return type(name, (_UnaryMathExpression,), {
        "_jnp": staticmethod(jnp_fn),
        "_np": staticmethod(np_fn),
    })


Sqrt = _make_unary("Sqrt", jnp.sqrt, np.sqrt)
Exp = _make_unary("Exp", jnp.exp, np.exp)
Log = _make_unary("Log", jnp.log, np.log)
Log2 = _make_unary("Log2", jnp.log2, np.log2)
Log10 = _make_unary("Log10", jnp.log10, np.log10)
Log1p = _make_unary("Log1p", jnp.log1p, np.log1p)
Expm1 = _make_unary("Expm1", jnp.expm1, np.expm1)
Floor = _make_unary("Floor", jnp.floor, np.floor)
Ceil = _make_unary("Ceil", jnp.ceil, np.ceil)
Sin = _make_unary("Sin", jnp.sin, np.sin)
Cos = _make_unary("Cos", jnp.cos, np.cos)
Tan = _make_unary("Tan", jnp.tan, np.tan)
Asin = _make_unary("Asin", jnp.arcsin, np.arcsin)
Acos = _make_unary("Acos", jnp.arccos, np.arccos)
Atan = _make_unary("Atan", jnp.arctan, np.arctan)
Cbrt = _make_unary("Cbrt", jnp.cbrt, np.cbrt)
Signum = _make_unary("Signum", jnp.sign, np.sign)
Rint = _make_unary("Rint", jnp.rint, np.rint)
ToDegrees = _make_unary("ToDegrees", jnp.degrees, np.degrees)
ToRadians = _make_unary("ToRadians", jnp.radians, np.radians)
Sinh = _make_unary("Sinh", jnp.sinh, np.sinh)
Cosh = _make_unary("Cosh", jnp.cosh, np.cosh)
Tanh = _make_unary("Tanh", jnp.tanh, np.tanh)
Asinh = _make_unary("Asinh", jnp.arcsinh, np.arcsinh)
Acosh = _make_unary("Acosh", jnp.arccosh, np.arccosh)
Atanh = _make_unary("Atanh", jnp.arctanh, np.arctanh)
Cot = _make_unary("Cot", lambda x: 1.0 / jnp.tan(x),
                  lambda x: 1.0 / np.tan(x))


class Pow(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.DOUBLE
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a = cast_dev(self.left.tpu_eval(ctx), T.DOUBLE)
        b = cast_dev(self.right.tpu_eval(ctx), T.DOUBLE)
        return DevVal(T.DOUBLE, jnp.power(a.data, b.data), a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a = cast_cpu(self.left.cpu_eval(ctx), T.DOUBLE)
        b = cast_cpu(self.right.cpu_eval(ctx), T.DOUBLE)
        with np.errstate(all="ignore"):
            data = np.power(a.values, b.values)
        return CpuVal(T.DOUBLE, data, a.validity & b.validity)


class Round(UnaryExpression):
    """round(x, scale) with HALF_UP semantics (Spark default)."""

    def __init__(self, child, scale: int = 0):
        self.scale = int(scale)
        super().__init__(child)

    def with_children(self, children):
        return Round(children[0], self.scale)

    def _resolve_type(self):
        self.dtype = self.child.dtype if self.child.dtype.is_numeric else T.DOUBLE
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        if v.dtype.is_integral and self.scale >= 0:
            return v
        x = v.data.astype(jnp.float64)
        m = 10.0 ** self.scale
        # HALF_UP: round(|x|*m + 0.5) with sign restored (numpy rounds half-even).
        r = jnp.sign(x) * jnp.floor(jnp.abs(x) * m + 0.5) / m
        return DevVal(self.dtype, r.astype(self.dtype.jnp_dtype), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        if v.dtype.is_integral and self.scale >= 0:
            return v
        x = v.values.astype(np.float64)
        m = 10.0 ** self.scale
        with np.errstate(all="ignore"):
            r = np.sign(x) * np.floor(np.abs(x) * m + 0.5) / m
        return CpuVal(self.dtype, r.astype(self.dtype.np_dtype), v.validity)


class Logarithm(BinaryExpression):
    """log(base, x) (Spark Logarithm, mathExpressions.scala)."""

    def _resolve_type(self):
        self.dtype = T.DOUBLE
        self.nullable = True

    def tpu_eval(self, ctx):
        b = cast_dev(self.left.tpu_eval(ctx), T.DOUBLE)
        x = cast_dev(self.right.tpu_eval(ctx), T.DOUBLE)
        return DevVal(T.DOUBLE, jnp.log(x.data) / jnp.log(b.data),
                      b.validity & x.validity)

    def cpu_eval(self, ctx):
        b = cast_cpu(self.left.cpu_eval(ctx), T.DOUBLE)
        x = cast_cpu(self.right.cpu_eval(ctx), T.DOUBLE)
        with np.errstate(all="ignore"):
            data = np.log(x.values) / np.log(b.values)
        return CpuVal(T.DOUBLE, data, b.validity & x.validity)
