"""Window expressions (reference: GpuWindowExec.scala:99,
GpuWindowExpression.scala:93 — row-frame windowing via cudf rolling windows).

TPU-first design: instead of per-row rolling kernels, the window exec sorts
the whole partition by (partition keys, order keys) once, derives partition
*segments*, and computes every supported function with prefix-sum /
segmented-scan primitives — O(n log n) sort + O(n) scans, ideal XLA shapes.

Supported frames: ROWS/RANGE with UNBOUNDED PRECEDING..CURRENT ROW (running,
RANGE extends to peers), UNBOUNDED..UNBOUNDED (whole partition), bounded
value-based RANGE BETWEEN x PRECEDING AND y FOLLOWING over a single
numeric/date/timestamp order key (binary search on the sorted span;
NULL/NaN keys frame over their peer blocks), and bounded
ROWS frames for sum/count/avg/min/max via prefix sums (min/max bounded uses a
log-steps sliding reduction).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.aggregates import AggregateFunction
from spark_rapids_tpu.exprs.base import Expression, Literal, SortOrder

UNBOUNDED = None
CURRENT_ROW = 0


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """kind: "rows" or "range".  start/end: None = unbounded, ints are
    offsets relative to the current row (negative = preceding)."""

    kind: str = "range"
    start: Optional[int] = UNBOUNDED
    end: Optional[int] = CURRENT_ROW

    @property
    def is_unbounded_whole(self) -> bool:
        return self.start is None and self.end is None

    @property
    def is_running(self) -> bool:
        return self.start is None and self.end == 0


class WindowFunction(Expression):
    """Marker base for ranking/offset window functions."""

    needs_order = True


class RowNumber(WindowFunction):
    def __init__(self):
        self.children = ()
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return self


class Rank(WindowFunction):
    def __init__(self):
        self.children = ()
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return self


class DenseRank(WindowFunction):
    def __init__(self):
        self.children = ()
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return self


class Lag(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        self.children = (child,) if default is None else (child, default)
        self.offset = int(offset)
        self.dtype = child.dtype
        self.nullable = True

    def with_children(self, children):
        d = children[1] if len(children) > 1 else None
        return type(self)(children[0], self.offset, d)


class Lead(Lag):
    pass


class WindowExpression(Expression):
    """function OVER (PARTITION BY ... ORDER BY ... frame)."""

    def __init__(self, function: Expression,
                 partition_by: List[Expression],
                 order_by: List[SortOrder],
                 frame: Optional[WindowFrame] = None):
        self.function = function
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        if frame is None:
            # Spark defaults: with ORDER BY -> RANGE UNBOUNDED..CURRENT;
            # without -> whole partition.
            frame = WindowFrame("range", UNBOUNDED, CURRENT_ROW) \
                if order_by else WindowFrame("rows", UNBOUNDED, UNBOUNDED)
        self.frame = frame
        self.children = (function,) + tuple(partition_by) + \
            tuple(o.child for o in order_by)
        self.dtype = function.dtype
        self.nullable = True

    def with_children(self, children):
        nf = children[0]
        np_ = children[1:1 + len(self.partition_by)]
        no = children[1 + len(self.partition_by):]
        orders = [SortOrder(c, o.ascending, o.nulls_first)
                  for c, o in zip(no, self.order_by)]
        return WindowExpression(nf, list(np_), orders, self.frame)

    @property
    def name(self):
        return f"WindowExpression({self.function.name})"

    def tpu_supported(self, conf):
        fn = self.function
        if isinstance(fn, (RowNumber, Rank, DenseRank)):
            if not self.order_by:
                return f"{fn.name} requires ORDER BY"
            return None
        if isinstance(fn, Lag):
            if len(fn.children) > 1 and fn.children[0].dtype.is_string:
                # ops/window.py has no string default-fill yet; route to
                # CPU instead of silently returning NULL for the default.
                return (f"{fn.name} with a default value on a string "
                        f"column not supported on TPU")
            return None
        if isinstance(fn, AggregateFunction):
            from spark_rapids_tpu.exprs.aggregates import (
                Average, Count, Max, Min, Sum,
            )
            if not isinstance(fn, (Sum, Count, Min, Max, Average)):
                return f"window aggregate {fn.name} not supported"
            r = fn.tpu_supported(conf)
            if r:
                return r
            if self.frame.kind == "range" and not (
                    self.frame.is_running or
                    self.frame.is_unbounded_whole):
                # bounded value-range frame: Spark requires exactly one
                # numeric/date/timestamp order key; anything else routes
                # to the CPU exec, which raises the analysis error
                if len(self.order_by) != 1:
                    return ("bounded RANGE frame needs exactly one "
                            "ORDER BY expression")
                kd = self.order_by[0].child.dtype
                if not kd.is_numeric and kd not in (T.DATE, T.TIMESTAMP):
                    return ("bounded RANGE frame needs a numeric "
                            "order key")
            return None
        return f"window function {fn.name} not supported"
