"""Nondeterministic and internal expressions (reference:
GpuRandomExpressions.scala, GpuMonotonicallyIncreasingID.scala,
GpuSparkPartitionID.scala, NormalizeFloatingNumbers.scala)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import CpuVal, DevVal, Expression, UnaryExpression


class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row offset within partition."""

    def __init__(self):
        self.children = ()
        self.dtype = T.LONG
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        base = (jnp.int64(ctx.partition_index) << 33) + ctx.base_row_id
        data = base + jnp.arange(ctx.capacity, dtype=jnp.int64)
        return DevVal(T.LONG, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        base = (np.int64(ctx.partition_index) << np.int64(33)) + ctx.base_row_id
        data = base + np.arange(ctx.num_rows, dtype=np.int64)
        return CpuVal(T.LONG, data, np.ones(ctx.num_rows, dtype=np.bool_))


class SparkPartitionID(Expression):
    def __init__(self):
        self.children = ()
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        data = jnp.full(ctx.capacity, ctx.partition_index, dtype=jnp.int32)
        return DevVal(T.INT, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        data = np.full(ctx.num_rows, ctx.partition_index, dtype=np.int32)
        return CpuVal(T.INT, data, np.ones(ctx.num_rows, dtype=np.bool_))


class Rand(Expression):
    """Uniform [0,1) per row.  Nondeterministic: TPU uses jax PRNG keyed by
    (seed, partition, base row id) — results differ from Spark CPU's XORShift
    but are deterministic per plan execution (the reference flags GpuRand as
    'retries are not idempotent')."""

    def __init__(self, seed: int = 0):
        self.children = ()
        self.seed = int(seed)
        self.dtype = T.DOUBLE
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        key = jax.random.PRNGKey(self.seed + 1000003 * (ctx.partition_index + 1))
        key = jax.random.fold_in(key, ctx.base_row_id.astype(jnp.uint32))
        data = jax.random.uniform(key, (ctx.capacity,), dtype=jnp.float64)
        return DevVal(T.DOUBLE, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        rng = np.random.RandomState(
            (self.seed + 1000003 * (ctx.partition_index + 1)
             + 31 * int(ctx.base_row_id)) % (2 ** 31))
        data = rng.uniform(size=ctx.num_rows)
        return CpuVal(T.DOUBLE, data, np.ones(ctx.num_rows, dtype=np.bool_))


class KnownFloatingPointNormalized(UnaryExpression):
    """Normalize -0.0 -> 0.0 and NaN -> canonical NaN for float grouping keys
    (reference: NormalizeFloatingNumbers.scala)."""

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        data = jnp.where(v.data == 0, jnp.zeros_like(v.data), v.data)
        data = jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)
        return DevVal(v.dtype, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        data = np.where(v.values == 0, np.zeros_like(v.values), v.values)
        data = np.where(np.isnan(data), np.full_like(data, np.nan), data)
        return CpuVal(v.dtype, data, v.validity)


class CreateArray(Expression):
    """array(e1, e2, ...) -> array<common element type>
    (GpuCreateArray, complexTypeCreator analogue).  TPU path requires
    non-nullable inputs (element-level NULLs are host-only in the v1
    nested envelope); nullable inputs fall back to CPU."""

    def __init__(self, *children: Expression):
        assert children, "array() needs at least one element"
        elem = children[0].dtype
        for c in children[1:]:
            elem = T.promote(elem, c.dtype)
        self.children = tuple(children)
        self.dtype = T.ArrayType(elem)
        self.nullable = False

    def with_children(self, children):
        return CreateArray(*children)

    def tpu_supported(self, conf):
        if self.dtype.element.is_string:
            return ("array<string> has variable-length elements "
                    "(host-only in the v1 nested envelope)")
        if any(c.nullable for c in self.children):
            return ("array() with nullable inputs can produce NULL "
                    "elements (host-only in the v1 nested envelope)")
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        elem = self.dtype.element
        vals = [c.tpu_eval(ctx) for c in self.children]
        k = len(vals)
        cap = ctx.capacity
        data = jnp.stack([v.data.astype(elem.jnp_dtype) for v in vals],
                         axis=1).reshape(-1)  # row-major [cap*k]
        offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
        # live rows only: clamp offsets past num_rows to the live total
        total = ctx.num_rows * k
        offsets = jnp.minimum(offsets, total.astype(jnp.int32))
        return DevVal(self.dtype, data,
                      jnp.ones(cap, dtype=jnp.bool_), offsets)

    def cpu_eval(self, ctx) -> CpuVal:
        vals = [c.cpu_eval(ctx) for c in self.children]
        n = ctx.num_rows
        out = np.empty(n, dtype=object)
        elem = self.dtype.element
        for i in range(n):
            out[i] = [
                (None if not v.validity[i] else
                 T.np_scalar(elem, v.values[i]))
                for v in vals]
        return CpuVal(self.dtype, out, np.ones(n, dtype=np.bool_))


class GetArrayItem(Expression):
    """arr[i] with a literal 0-based ordinal (GpuGetArrayItem,
    complexTypeExtractors.scala): NULL when out of range or the array row
    is NULL."""

    def __init__(self, child: Expression, ordinal: int):
        self.children = (child,)
        self.ordinal = int(ordinal)
        # pre-resolution the child is an untyped ColumnRef; the planner
        # rebuilds this node with resolved children (with_children)
        self.dtype = child.dtype.element \
            if isinstance(child.dtype, T.ArrayType) else T.NULL
        self.nullable = True

    def with_children(self, children):
        return GetArrayItem(children[0], self.ordinal)

    def tpu_supported(self, conf):
        if not isinstance(self.children[0].dtype, T.ArrayType):
            return f"getItem needs an array, got {self.children[0].dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        v = self.children[0].tpu_eval(ctx)
        if self.ordinal < 0:
            # Spark: negative ordinals are out of range -> NULL
            return DevVal(self.dtype,
                          jnp.zeros(ctx.capacity,
                                    dtype=self.dtype.jnp_dtype),
                          jnp.zeros(ctx.capacity, dtype=jnp.bool_))
        lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        in_range = self.ordinal < lens
        idx = jnp.clip(v.offsets[:-1] + self.ordinal, 0,
                       int(v.data.shape[0]) - 1)
        data = jnp.where(in_range, v.data[idx], 0)
        return DevVal(self.dtype, data.astype(self.dtype.jnp_dtype),
                      v.validity & in_range & ctx.row_mask)

    def cpu_eval(self, ctx) -> CpuVal:
        # Spark semantics: negative / out-of-range ordinals yield NULL
        # (non-ANSI), never python-style tail indexing.
        v = self.children[0].cpu_eval(ctx)
        n = len(v.values)
        out = np.zeros(n, dtype=self.dtype.np_dtype)
        ok = np.zeros(n, dtype=np.bool_)
        k = self.ordinal
        for i, (arr, valid) in enumerate(zip(v.values, v.validity)):
            if valid and arr is not None and 0 <= k < len(arr) and \
                    arr[k] is not None:
                out[i] = arr[k]
                ok[i] = True
        return CpuVal(self.dtype, out, ok)


class ArraySize(UnaryExpression):
    """size(arr) -> INT element count; size(NULL) -> NULL.

    This matches Spark with ``spark.sql.legacy.sizeOfNull=false`` (the
    ANSI-aligned behavior; Spark's historical default returns -1 for NULL
    input).  Documented divergence from the legacy default."""

    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        if not isinstance(self.child.dtype, T.ArrayType):
            return f"size needs an array, got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        v = self.child.tpu_eval(ctx)
        lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        return DevVal(T.INT, lens, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        n = len(v.values)
        out = np.zeros(n, dtype=np.int32)
        for i, (arr, ok) in enumerate(zip(v.values, v.validity)):
            out[i] = len(arr) if ok and arr is not None else 0
        return CpuVal(T.INT, out, v.validity)
