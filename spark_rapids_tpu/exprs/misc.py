"""Nondeterministic and internal expressions (reference:
GpuRandomExpressions.scala, GpuMonotonicallyIncreasingID.scala,
GpuSparkPartitionID.scala, NormalizeFloatingNumbers.scala)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import CpuVal, DevVal, Expression, UnaryExpression


class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row offset within partition."""

    def __init__(self):
        self.children = ()
        self.dtype = T.LONG
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        base = (jnp.int64(ctx.partition_index) << 33) + ctx.base_row_id
        data = base + jnp.arange(ctx.capacity, dtype=jnp.int64)
        return DevVal(T.LONG, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        base = (np.int64(ctx.partition_index) << np.int64(33)) + ctx.base_row_id
        data = base + np.arange(ctx.num_rows, dtype=np.int64)
        return CpuVal(T.LONG, data, np.ones(ctx.num_rows, dtype=np.bool_))


class SparkPartitionID(Expression):
    def __init__(self):
        self.children = ()
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        data = jnp.full(ctx.capacity, ctx.partition_index, dtype=jnp.int32)
        return DevVal(T.INT, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        data = np.full(ctx.num_rows, ctx.partition_index, dtype=np.int32)
        return CpuVal(T.INT, data, np.ones(ctx.num_rows, dtype=np.bool_))


class Rand(Expression):
    """Uniform [0,1) per row.  Nondeterministic: TPU uses jax PRNG keyed by
    (seed, partition, base row id) — results differ from Spark CPU's XORShift
    but are deterministic per plan execution (the reference flags GpuRand as
    'retries are not idempotent')."""

    def __init__(self, seed: int = 0):
        self.children = ()
        self.seed = int(seed)
        self.dtype = T.DOUBLE
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        key = jax.random.PRNGKey(self.seed + 1000003 * (ctx.partition_index + 1))
        key = jax.random.fold_in(key, ctx.base_row_id.astype(jnp.uint32))
        data = jax.random.uniform(key, (ctx.capacity,), dtype=jnp.float64)
        return DevVal(T.DOUBLE, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        rng = np.random.RandomState(
            (self.seed + 1000003 * (ctx.partition_index + 1)
             + 31 * int(ctx.base_row_id)) % (2 ** 31))
        data = rng.uniform(size=ctx.num_rows)
        return CpuVal(T.DOUBLE, data, np.ones(ctx.num_rows, dtype=np.bool_))


class KnownFloatingPointNormalized(UnaryExpression):
    """Normalize -0.0 -> 0.0 and NaN -> canonical NaN for float grouping keys
    (reference: NormalizeFloatingNumbers.scala)."""

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        data = jnp.where(v.data == 0, jnp.zeros_like(v.data), v.data)
        data = jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)
        return DevVal(v.dtype, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        data = np.where(v.values == 0, np.zeros_like(v.values), v.values)
        data = np.where(np.isnan(data), np.full_like(data, np.nan), data)
        return CpuVal(v.dtype, data, v.validity)


class CreateArray(Expression):
    """array(e1, e2, ...) -> array<common element type>
    (GpuCreateArray, complexTypeCreator analogue).  TPU path requires
    non-nullable inputs (element-level NULLs are host-only in the v1
    nested envelope); nullable inputs fall back to CPU."""

    def __init__(self, *children: Expression):
        assert children, "array() needs at least one element"
        elem = children[0].dtype
        for c in children[1:]:
            elem = T.promote(elem, c.dtype)
        self.children = tuple(children)
        self.dtype = T.ArrayType(elem)
        self.nullable = False

    def with_children(self, children):
        return CreateArray(*children)

    def tpu_supported(self, conf):
        if any(c.nullable for c in self.children):
            return ("array() with nullable inputs can produce NULL "
                    "elements (host-only in the v1 nested envelope)")
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        elem = self.dtype.element
        vals = [c.tpu_eval(ctx) for c in self.children]
        k = len(vals)
        cap = ctx.capacity
        data = jnp.stack([v.data.astype(elem.jnp_dtype) for v in vals],
                         axis=1).reshape(-1)  # row-major [cap*k]
        offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
        # live rows only: clamp offsets past num_rows to the live total
        total = ctx.num_rows * k
        offsets = jnp.minimum(offsets, total.astype(jnp.int32))
        return DevVal(self.dtype, data,
                      jnp.ones(cap, dtype=jnp.bool_), offsets)

    def cpu_eval(self, ctx) -> CpuVal:
        vals = [c.cpu_eval(ctx) for c in self.children]
        n = ctx.num_rows
        out = np.empty(n, dtype=object)
        elem = self.dtype.element
        for i in range(n):
            out[i] = [
                (None if not v.validity[i] else
                 T.np_scalar(elem, v.values[i]))
                for v in vals]
        return CpuVal(self.dtype, out, np.ones(n, dtype=np.bool_))
