"""Nondeterministic and internal expressions (reference:
GpuRandomExpressions.scala, GpuMonotonicallyIncreasingID.scala,
GpuSparkPartitionID.scala, NormalizeFloatingNumbers.scala)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    CpuVal, DevVal, Expression, Literal, UnaryExpression,
)


class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row offset within partition."""

    def __init__(self):
        self.children = ()
        self.dtype = T.LONG
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        base = (jnp.int64(ctx.partition_index) << 33) + ctx.base_row_id
        data = base + jnp.arange(ctx.capacity, dtype=jnp.int64)
        return DevVal(T.LONG, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        base = (np.int64(ctx.partition_index) << np.int64(33)) + ctx.base_row_id
        data = base + np.arange(ctx.num_rows, dtype=np.int64)
        return CpuVal(T.LONG, data, np.ones(ctx.num_rows, dtype=np.bool_))


class SparkPartitionID(Expression):
    def __init__(self):
        self.children = ()
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        data = jnp.full(ctx.capacity, ctx.partition_index, dtype=jnp.int32)
        return DevVal(T.INT, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        data = np.full(ctx.num_rows, ctx.partition_index, dtype=np.int32)
        return CpuVal(T.INT, data, np.ones(ctx.num_rows, dtype=np.bool_))


class Rand(Expression):
    """Uniform [0,1) per row.  Nondeterministic: TPU uses jax PRNG keyed by
    (seed, partition, base row id) — results differ from Spark CPU's XORShift
    but are deterministic per plan execution (the reference flags GpuRand as
    'retries are not idempotent')."""

    def __init__(self, seed: int = 0):
        self.children = ()
        self.seed = int(seed)
        self.dtype = T.DOUBLE
        self.nullable = False

    def with_children(self, children):
        return self

    def tpu_eval(self, ctx) -> DevVal:
        key = jax.random.PRNGKey(self.seed + 1000003 * (ctx.partition_index + 1))
        key = jax.random.fold_in(key, ctx.base_row_id.astype(jnp.uint32))
        data = jax.random.uniform(key, (ctx.capacity,), dtype=jnp.float64)
        return DevVal(T.DOUBLE, data, jnp.ones(ctx.capacity, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        rng = np.random.RandomState(
            (self.seed + 1000003 * (ctx.partition_index + 1)
             + 31 * int(ctx.base_row_id)) % (2 ** 31))
        data = rng.uniform(size=ctx.num_rows)
        return CpuVal(T.DOUBLE, data, np.ones(ctx.num_rows, dtype=np.bool_))


class KnownFloatingPointNormalized(UnaryExpression):
    """Normalize -0.0 -> 0.0 and NaN -> canonical NaN for float grouping keys
    (reference: NormalizeFloatingNumbers.scala)."""

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        data = jnp.where(v.data == 0, jnp.zeros_like(v.data), v.data)
        data = jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)
        return DevVal(v.dtype, data, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        data = np.where(v.values == 0, np.zeros_like(v.values), v.values)
        data = np.where(np.isnan(data), np.full_like(data, np.nan), data)
        return CpuVal(v.dtype, data, v.validity)


class CreateArray(Expression):
    """array(e1, e2, ...) -> array<common element type>
    (GpuCreateArray, complexTypeCreator analogue).  TPU path requires
    non-nullable inputs (element-level NULLs are host-only in the v1
    nested envelope); nullable inputs fall back to CPU."""

    def __init__(self, *children: Expression):
        assert children, "array() needs at least one element"
        elem = children[0].dtype
        for c in children[1:]:
            elem = T.promote(elem, c.dtype)
        self.children = tuple(children)
        self.dtype = T.ArrayType(elem)
        self.nullable = False

    def with_children(self, children):
        return CreateArray(*children)

    def tpu_supported(self, conf):
        if self.dtype.element.is_string:
            return ("array<string> has variable-length elements "
                    "(host-only in the v1 nested envelope)")
        if any(c.nullable for c in self.children):
            return ("array() with nullable inputs can produce NULL "
                    "elements (host-only in the v1 nested envelope)")
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        elem = self.dtype.element
        vals = [c.tpu_eval(ctx) for c in self.children]
        k = len(vals)
        cap = ctx.capacity
        data = jnp.stack([v.data.astype(elem.jnp_dtype) for v in vals],
                         axis=1).reshape(-1)  # row-major [cap*k]
        offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
        # live rows only: clamp offsets past num_rows to the live total
        total = ctx.num_rows * k
        offsets = jnp.minimum(offsets, total.astype(jnp.int32))
        return DevVal(self.dtype, data,
                      jnp.ones(cap, dtype=jnp.bool_), offsets)

    def cpu_eval(self, ctx) -> CpuVal:
        vals = [c.cpu_eval(ctx) for c in self.children]
        n = ctx.num_rows
        out = np.empty(n, dtype=object)
        elem = self.dtype.element
        for i in range(n):
            out[i] = [
                (None if not v.validity[i] else
                 T.np_scalar(elem, v.values[i]))
                for v in vals]
        return CpuVal(self.dtype, out, np.ones(n, dtype=np.bool_))


class GetArrayItem(Expression):
    """arr[i] with a literal 0-based ordinal (GpuGetArrayItem,
    complexTypeExtractors.scala): NULL when out of range or the array row
    is NULL."""

    def __init__(self, child: Expression, ordinal: int):
        self.children = (child,)
        self.ordinal = int(ordinal)
        # pre-resolution the child is an untyped ColumnRef; the planner
        # rebuilds this node with resolved children (with_children)
        self.dtype = child.dtype.element \
            if isinstance(child.dtype, T.ArrayType) else T.NULL
        self.nullable = True

    def with_children(self, children):
        return GetArrayItem(children[0], self.ordinal)

    def tpu_supported(self, conf):
        if not isinstance(self.children[0].dtype, T.ArrayType):
            return f"getItem needs an array, got {self.children[0].dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        v = self.children[0].tpu_eval(ctx)
        if self.ordinal < 0:
            # Spark: negative ordinals are out of range -> NULL
            return DevVal(self.dtype,
                          jnp.zeros(ctx.capacity,
                                    dtype=self.dtype.jnp_dtype),
                          jnp.zeros(ctx.capacity, dtype=jnp.bool_))
        lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        in_range = self.ordinal < lens
        idx = jnp.clip(v.offsets[:-1] + self.ordinal, 0,
                       int(v.data.shape[0]) - 1)
        data = jnp.where(in_range, v.data[idx], 0)
        return DevVal(self.dtype, data.astype(self.dtype.jnp_dtype),
                      v.validity & in_range & ctx.row_mask)

    def cpu_eval(self, ctx) -> CpuVal:
        # Spark semantics: negative / out-of-range ordinals yield NULL
        # (non-ANSI), never python-style tail indexing.
        v = self.children[0].cpu_eval(ctx)
        n = len(v.values)
        out = np.zeros(n, dtype=self.dtype.np_dtype)
        ok = np.zeros(n, dtype=np.bool_)
        k = self.ordinal
        for i, (arr, valid) in enumerate(zip(v.values, v.validity)):
            if valid and arr is not None and 0 <= k < len(arr) and \
                    arr[k] is not None:
                out[i] = arr[k]
                ok[i] = True
        return CpuVal(self.dtype, out, ok)


class ArraySize(UnaryExpression):
    """size(arr) -> INT element count; size(NULL) -> NULL.

    This matches Spark with ``spark.sql.legacy.sizeOfNull=false`` (the
    ANSI-aligned behavior; Spark's historical default returns -1 for NULL
    input).  Documented divergence from the legacy default."""

    def _resolve_type(self):
        self.dtype = T.INT
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        if not isinstance(self.child.dtype, T.ArrayType):
            return f"size needs an array, got {self.child.dtype}"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        v = self.child.tpu_eval(ctx)
        lens = (v.offsets[1:] - v.offsets[:-1]).astype(jnp.int32)
        return DevVal(T.INT, lens, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        n = len(v.values)
        out = np.zeros(n, dtype=np.int32)
        for i, (arr, ok) in enumerate(zip(v.values, v.validity)):
            out[i] = len(arr) if ok and arr is not None else 0
        return CpuVal(T.INT, out, v.validity)


def _array_rows(v):
    """int32[n_elements]: owning row of each flat element slot (the
    strings module's byte->row mapping, reused for array elements)."""
    from spark_rapids_tpu.exprs.strings import rows_of_positions
    return rows_of_positions(v.offsets, int(v.data.shape[0]))


def _element_slots(v, cap):
    """(rows, in_range) for the flat element buffer: owning row per slot
    (clipped into [0, cap)) and the live-slot mask."""
    nelem = int(v.data.shape[0])
    rows = jnp.clip(_array_rows(v), 0, cap - 1)
    in_range = jnp.arange(nelem, dtype=jnp.int32) < v.offsets[-1]
    return rows, in_range


def _host_isnan(value) -> bool:
    return isinstance(value, float) and value != value


def _needle_eq(e, needle) -> bool:
    """Ordering equivalence for array membership (Spark ArrayContains /
    ArrayPosition): NaN equals NaN, unlike IEEE ==."""
    if _host_isnan(needle):
        return isinstance(e, float) and e != e
    return e == needle


def _check_array_needle(elem_dt, value):
    """Reject needles whose python type does not match the element type
    (a silent narrowing cast would diverge between backends)."""
    if elem_dt.is_string:
        ok = isinstance(value, str)
    elif elem_dt == T.BOOLEAN:
        ok = isinstance(value, bool)
    elif elem_dt.is_integral:
        ok = isinstance(value, int) and not isinstance(value, bool)
    else:
        ok = isinstance(value, (int, float)) and             not isinstance(value, bool)
    if not ok:
        raise TypeError(
            f"needle {value!r} does not match array element type "
            f"{elem_dt} (no implicit narrowing)")


class ArrayContains(Expression):
    """array_contains(arr, literal) -> BOOLEAN (GpuArrayContains role,
    collectionOperations).  NULL array -> NULL; literal must be a
    non-null scalar (Spark requires a foldable non-null value)."""

    def __init__(self, child: Expression, value):
        if isinstance(value, Expression) and not isinstance(value,
                                                            Literal):
            raise NotImplementedError(
                "array_contains needs a literal needle (column-valued "
                "needles are not supported, like the reference's GPU "
                "plugin)")
        if not isinstance(value, Literal):
            value = Literal(value)
        if value.value is None:
            raise ValueError("array_contains value must not be NULL")
        self.children = (child, value)
        self.dtype = T.BOOLEAN
        # NULL when the array row is NULL, or when it has NULL elements
        # and no match (Spark three-valued IN semantics)
        self.nullable = True

    def with_children(self, children):
        return ArrayContains(children[0], children[1])

    def _check_needle(self, elem_dt):
        _check_array_needle(elem_dt, self.children[1].value)

    def tpu_supported(self, conf):
        dt = self.children[0].dtype
        if not isinstance(dt, T.ArrayType):
            return f"array_contains needs an array, got {dt}"
        if dt.element.is_string:
            return "array<string> is host-only"
        self._check_needle(dt.element)
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax
        import jax.numpy as jnp
        v = self.children[0].tpu_eval(ctx)
        cap = ctx.capacity
        elem_dt = self.children[0].dtype.element
        self._check_needle(elem_dt)
        needle = jnp.asarray(self.children[1].value,
                             dtype=elem_dt.jnp_dtype)
        rows, in_range = _element_slots(v, cap)
        # Spark's ArrayContains uses ordering equivalence: NaN == NaN
        if elem_dt.is_fractional and _host_isnan(self.children[1].value):
            hit = in_range & jnp.isnan(v.data)
        else:
            hit = in_range & (v.data == needle)
        n_hits = jax.ops.segment_sum(hit.astype(jnp.int32), rows,
                                     num_segments=cap,
                                     indices_are_sorted=True)
        return DevVal(T.BOOLEAN, n_hits > 0, v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        dt = self.children[0].dtype
        if isinstance(dt, T.ArrayType):
            self._check_needle(dt.element)
        needle = self.children[1].value
        n = len(v.values)
        out = np.zeros(n, dtype=np.bool_)
        valid = np.array(v.validity, dtype=np.bool_).copy()
        for i, (arr, ok) in enumerate(zip(v.values, v.validity)):
            if not (ok and arr is not None):
                continue
            hit = any(e is not None and _needle_eq(e, needle)
                      for e in arr)
            out[i] = hit
            if not hit and any(e is None for e in arr):
                valid[i] = False  # Spark: NULL element + no match -> NULL
        return CpuVal(T.BOOLEAN, out, valid)


class _ArrayMinMax(UnaryExpression):
    """array_min / array_max: reduce each row's elements (NULL for an
    empty or NULL array, Spark semantics)."""

    _is_min = True

    def _resolve_type(self):
        dt = self.child.dtype
        self.dtype = dt.element if isinstance(dt, T.ArrayType) else T.NULL
        self.nullable = True

    def tpu_supported(self, conf):
        dt = self.child.dtype
        if not isinstance(dt, T.ArrayType):
            return f"{self.name} needs an array, got {dt}"
        if dt.element.is_string:
            return "array<string> is host-only"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax
        import jax.numpy as jnp
        v = self.child.tpu_eval(ctx)
        cap = ctx.capacity
        jdt = self.dtype.jnp_dtype
        if self.dtype.is_fractional:
            ident = jnp.asarray(jnp.inf if self._is_min else -jnp.inf,
                                jdt)
        elif self.dtype == T.BOOLEAN:
            ident = jnp.asarray(True if self._is_min else False)
        else:
            info = jnp.iinfo(jdt)
            ident = jnp.asarray(info.max if self._is_min else info.min,
                                jdt)
        rows, in_range = _element_slots(v, cap)
        x = jnp.where(in_range, v.data.astype(jdt), ident)
        if self.dtype.is_fractional:
            # Spark orders NaN as the LARGEST value: min skips NaNs
            # (unless every element is NaN), max is NaN if any present
            is_nan = in_range & jnp.isnan(x)
            x = jnp.where(is_nan, ident, x)
            nan_cnt = jax.ops.segment_sum(
                is_nan.astype(jnp.int32), rows, num_segments=cap,
                indices_are_sorted=True)
            notnan_cnt = jax.ops.segment_sum(
                (in_range & ~is_nan).astype(jnp.int32), rows,
                num_segments=cap, indices_are_sorted=True)
        red = jax.ops.segment_min if self._is_min else \
            jax.ops.segment_max
        out = red(x, rows, num_segments=cap, indices_are_sorted=True)
        if self.dtype.is_fractional:
            nan = jnp.asarray(jnp.nan, jdt)
            if self._is_min:
                out = jnp.where((notnan_cnt == 0) & (nan_cnt > 0), nan,
                                out)
            else:
                out = jnp.where(nan_cnt > 0, nan, out)
        lens = (v.offsets[1:] - v.offsets[:-1]) > 0
        return DevVal(self.dtype, out, v.validity & lens)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        n = len(v.values)
        out = np.zeros(n, dtype=self.dtype.np_dtype)
        valid = np.zeros(n, dtype=np.bool_)
        frac = self.dtype.is_fractional
        for i, (arr, ok) in enumerate(zip(v.values, v.validity)):
            if not (ok and arr):
                continue
            vals = [e for e in arr if e is not None]
            if not vals:
                continue
            valid[i] = True
            if frac:
                nn = [e for e in vals if e == e]
                if self._is_min:
                    out[i] = min(nn) if nn else float("nan")
                else:
                    out[i] = float("nan") if len(nn) < len(vals) \
                        else max(vals)
            else:
                out[i] = min(vals) if self._is_min else max(vals)
        return CpuVal(self.dtype, out, valid)


class ArrayMin(_ArrayMinMax):
    _is_min = True


class ArrayMax(_ArrayMinMax):
    _is_min = False


class SortArray(UnaryExpression):
    """sort_array(arr[, asc]) — per-row element sort (Spark SortArray).
    Device path: one lexsort over (owning row, element value) reorders
    the flat element buffer; offsets/validity are untouched."""

    def __init__(self, child: Expression, ascending: bool = True):
        self.ascending = bool(ascending)
        super().__init__(child)

    def with_children(self, children):
        return SortArray(children[0], self.ascending)

    def _resolve_type(self):
        self.dtype = self.child.dtype
        self.nullable = self.child.nullable

    def tpu_supported(self, conf):
        dt = self.child.dtype
        if not isinstance(dt, T.ArrayType):
            return f"sort_array needs an array, got {dt}"
        if dt.element.is_string:
            return "array<string> is host-only"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax.numpy as jnp
        v = self.child.tpu_eval(ctx)
        cap = ctx.capacity
        rows, in_range = _element_slots(v, cap)
        elem_dt = self.child.dtype.element
        jdt = elem_dt.jnp_dtype
        x = v.data.astype(jdt)
        if elem_dt.is_fractional:
            is_nan = jnp.isnan(x)
            rk = jnp.where(is_nan, jnp.inf, x.astype(jnp.float64))
            if not self.ascending:
                rk = -rk
            # rank separates NaN from real infinities on key ties, and
            # padding from everything: NaN sorts last ascending / first
            # descending (Spark: NaN is the largest value)
            nan_rank = jnp.where(is_nan,
                                 1 if self.ascending else -1, 0)
        else:
            rk = x.astype(jnp.int64)  # exact for the full int64 range
            if not self.ascending:
                rk = ~rk  # complement: monotone flip, no INT64_MIN wrap
            nan_rank = jnp.zeros_like(rows)
        rk = jnp.where(in_range, rk, 0)
        nan_rank = jnp.where(in_range, nan_rank, 2)  # padding dead last
        order = jnp.lexsort((nan_rank, rk, rows.astype(jnp.int32)))
        data = v.data[order]
        return DevVal(self.dtype, data, v.validity, v.offsets)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        out = np.empty(len(v.values), dtype=object)
        for i, (arr, ok) in enumerate(zip(v.values, v.validity)):
            if not ok or arr is None:
                out[i] = None
                continue
            nn = [e for e in arr if e is not None]
            nulls = [None] * (len(arr) - len(nn))
            key = (lambda e: (e != e, e)) if any(
                isinstance(e, float) for e in nn) else (lambda e: e)
            s = sorted(nn, key=key, reverse=not self.ascending)
            # Spark: NULL elements first ascending, last descending
            out[i] = nulls + s if self.ascending else s + nulls
        return CpuVal(self.dtype, out, v.validity)


class ArrayPosition(Expression):
    """array_position(arr, literal): 1-based index of the first match,
    0 when absent, NULL for a NULL array (Spark ArrayPosition)."""

    def __init__(self, child: Expression, value):
        if isinstance(value, Expression) and not isinstance(value,
                                                            Literal):
            raise NotImplementedError(
                "array_position needs a literal needle")
        if not isinstance(value, Literal):
            value = Literal(value)
        if value.value is None:
            raise ValueError("array_position value must not be NULL")
        self.children = (child, value)
        self.dtype = T.LONG
        self.nullable = child.nullable

    def with_children(self, children):
        return ArrayPosition(children[0], children[1])

    def tpu_supported(self, conf):
        dt = self.children[0].dtype
        if not isinstance(dt, T.ArrayType):
            return f"array_position needs an array, got {dt}"
        if dt.element.is_string:
            return "array<string> is host-only"
        _check_array_needle(dt.element, self.children[1].value)
        return None

    def tpu_eval(self, ctx) -> DevVal:
        import jax
        import jax.numpy as jnp
        v = self.children[0].tpu_eval(ctx)
        cap = ctx.capacity
        elem_dt = self.children[0].dtype.element
        _check_array_needle(elem_dt, self.children[1].value)
        needle = jnp.asarray(self.children[1].value,
                             dtype=elem_dt.jnp_dtype)
        rows, in_range = _element_slots(v, cap)
        pos = jnp.arange(int(v.data.shape[0]), dtype=jnp.int32)
        if elem_dt.is_fractional and _host_isnan(self.children[1].value):
            hit = in_range & jnp.isnan(v.data)
        else:
            hit = in_range & (v.data == needle)
        big = jnp.int32(1 << 30)
        first = jax.ops.segment_min(jnp.where(hit, pos, big), rows,
                                    num_segments=cap,
                                    indices_are_sorted=True)
        found = first < big
        idx = jnp.where(found,
                        first - v.offsets[:-1].astype(jnp.int32) + 1, 0)
        return DevVal(T.LONG, idx.astype(jnp.int64), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.children[0].cpu_eval(ctx)
        dt = self.children[0].dtype
        if isinstance(dt, T.ArrayType):
            _check_array_needle(dt.element, self.children[1].value)
        needle = self.children[1].value
        n = len(v.values)
        out = np.zeros(n, dtype=np.int64)
        for i, (arr, ok) in enumerate(zip(v.values, v.validity)):
            if ok and arr is not None:
                for j, e in enumerate(arr):
                    if e is not None and _needle_eq(e, needle):
                        out[i] = j + 1
                        break
        return CpuVal(T.LONG, out, v.validity)
