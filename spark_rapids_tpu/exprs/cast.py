"""CAST (reference: GpuCast.scala, 884 LoC).

Device-supported casts: between numeric types (Java narrowing semantics:
NaN->0, saturation at int bounds, truncation toward zero), boolean<->numeric,
date<->timestamp, numeric<->timestamp (seconds).  Casts involving strings run
on CPU only (the reference likewise special-cases string casts heavily,
GpuCast.scala:262-337).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import CpuVal, DevVal, Expression, UnaryExpression

_INT_BOUNDS = {
    T.BYTE: (-(2 ** 7), 2 ** 7 - 1),
    T.SHORT: (-(2 ** 15), 2 ** 15 - 1),
    T.INT: (-(2 ** 31), 2 ** 31 - 1),
    T.LONG: (-(2 ** 63), 2 ** 63 - 1),
}


class Cast(UnaryExpression):
    def __init__(self, child: Expression, to: T.DataType):
        self.to = to
        super().__init__(child)

    def with_children(self, children):
        return Cast(children[0], self.to)

    def _resolve_type(self):
        self.dtype = self.to
        self.nullable = self.child.nullable or (
            self.child.dtype.is_string and not self.to.is_string)

    @property
    def name(self):
        return f"Cast(->{self.to})"

    def tpu_supported(self, conf):
        src, dst = self.child.dtype, self.to
        if src.is_string != dst.is_string and (src.is_string or dst.is_string):
            return f"cast {src} -> {dst} involves string conversion (CPU only)"
        return None

    # -- device ------------------------------------------------------------

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        src, dst = v.dtype, self.to
        if src == dst:
            return v
        data, validity = v.data, v.validity
        if src == T.BOOLEAN:
            data = data.astype(dst.jnp_dtype)
        elif src.is_fractional and dst.is_integral:
            lo, hi = _INT_BOUNDS[dst]
            x = jnp.nan_to_num(data, nan=0.0, posinf=float(hi), neginf=float(lo))
            # saturate the boundaries in INTEGER domain: the f64-emulated
            # clip value (e.g. 2147483647.0 at ~48-bit mantissa) converts
            # off-by-one on TPU
            over = x >= float(hi)
            under = x <= float(lo)
            conv = jnp.trunc(jnp.clip(x, float(lo), float(hi))) \
                .astype(dst.jnp_dtype)
            data = jnp.where(over, jnp.asarray(hi, dst.jnp_dtype),
                             jnp.where(under,
                                       jnp.asarray(lo, dst.jnp_dtype),
                                       conv))
        elif dst == T.BOOLEAN:
            data = data != 0
        elif src == T.DATE and dst == T.TIMESTAMP:
            data = data.astype(jnp.int64) * 86_400_000_000
        elif src == T.TIMESTAMP and dst == T.DATE:
            data = jnp.floor_divide(data, 86_400_000_000).astype(jnp.int32)
        elif src == T.TIMESTAMP and dst.is_numeric:
            data = jnp.floor_divide(data, 1_000_000).astype(dst.jnp_dtype)
        elif src.is_numeric and dst == T.TIMESTAMP:
            data = (data.astype(jnp.float64) * 1e6).astype(jnp.int64) \
                if src.is_fractional else data.astype(jnp.int64) * 1_000_000
        else:
            data = data.astype(dst.jnp_dtype)
        return DevVal(dst, data, validity)

    # -- cpu ---------------------------------------------------------------

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        src, dst = v.dtype, self.to
        if src == dst:
            return v
        validity = v.validity.copy()
        with np.errstate(all="ignore"):
            if src.is_string:
                values, validity = _cast_from_string(v, dst)
            elif dst.is_string:
                values = np.array(
                    [_to_string(x, src) for x in v.values], dtype=object)
            elif src == T.BOOLEAN:
                values = v.values.astype(dst.np_dtype)
            elif src.is_fractional and dst.is_integral:
                lo, hi = _INT_BOUNDS[dst]
                x = np.nan_to_num(v.values.astype(np.float64), nan=0.0,
                                  posinf=float(hi), neginf=float(lo))
                values = np.trunc(np.clip(x, float(lo), float(hi))).astype(
                    dst.np_dtype)
            elif dst == T.BOOLEAN:
                values = v.values != 0
            elif src == T.DATE and dst == T.TIMESTAMP:
                values = v.values.astype(np.int64) * 86_400_000_000
            elif src == T.TIMESTAMP and dst == T.DATE:
                values = np.floor_divide(v.values, 86_400_000_000).astype(np.int32)
            elif src == T.TIMESTAMP and dst.is_numeric:
                values = np.floor_divide(v.values, 1_000_000).astype(dst.np_dtype)
            elif src.is_numeric and dst == T.TIMESTAMP:
                values = ((v.values.astype(np.float64) * 1e6).astype(np.int64)
                          if src.is_fractional
                          else v.values.astype(np.int64) * 1_000_000)
            else:
                values = v.values.astype(dst.np_dtype)
        return CpuVal(dst, values, validity)


def _to_string(x, src: T.DataType) -> str:
    if src == T.BOOLEAN:
        return "true" if x else "false"
    if src.is_integral:
        return str(int(x))
    if src.is_fractional:
        f = float(x)
        if f != f:
            return "NaN"
        if f == int(f) and abs(f) < 1e16:
            return f"{f:.1f}"
        return repr(f)
    if src == T.DATE:
        days = int(x)
        import datetime
        return (datetime.date(1970, 1, 1)
                + datetime.timedelta(days=days)).isoformat()
    if src == T.TIMESTAMP:
        import datetime
        dt = datetime.datetime(1970, 1, 1) + datetime.timedelta(
            microseconds=int(x))
        return dt.strftime("%Y-%m-%d %H:%M:%S")
    return str(x)


def _cast_from_string(v: CpuVal, dst: T.DataType):
    out_validity = v.validity.copy()
    values = np.zeros(len(v.values), dtype=dst.np_dtype if not dst.is_string
                      else object)
    for i, (s, ok) in enumerate(zip(v.values, v.validity)):
        if not ok:
            continue
        s = str(s).strip()
        try:
            if dst == T.BOOLEAN:
                low = s.lower()
                if low in ("true", "t", "yes", "y", "1"):
                    values[i] = True
                elif low in ("false", "f", "no", "n", "0"):
                    values[i] = False
                else:
                    out_validity[i] = False
            elif dst.is_integral:
                values[i] = dst.np_dtype(int(float(s)) if "." in s else int(s))
            elif dst.is_fractional:
                values[i] = dst.np_dtype(float(s))
            elif dst == T.DATE:
                import datetime
                d = datetime.date.fromisoformat(s[:10])
                values[i] = (d - datetime.date(1970, 1, 1)).days
            elif dst == T.TIMESTAMP:
                import datetime
                dt = datetime.datetime.fromisoformat(s)
                values[i] = int(
                    (dt - datetime.datetime(1970, 1, 1)).total_seconds() * 1e6)
            else:
                values[i] = s
        except (ValueError, OverflowError):
            out_validity[i] = False
    return values, out_validity
