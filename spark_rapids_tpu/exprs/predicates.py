"""Comparison and boolean predicates (reference: predicates.scala, 631 LoC).

And/Or implement Kleene three-valued logic exactly as Spark does.  String
equality is evaluated on device via dual 64-bit polynomial hashes plus length
(config spark.rapids.sql.stringHashGroupJoin.enabled); ordering comparisons on
strings fall back to CPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, CpuVal, DevVal, Expression, Literal, UnaryExpression,
    promote_cpu, promote_dev,
)


def _string_eq_dev(a: DevVal, b: DevVal):
    from spark_rapids_tpu.exprs.strings import string_hash2, string_lengths
    ha1, ha2 = string_hash2(a)
    hb1, hb2 = string_hash2(b)
    la, lb = string_lengths(a), string_lengths(b)
    return (ha1 == hb1) & (ha2 == hb2) & (la == lb)


class _Comparison(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = self.left.nullable or self.right.nullable

    def _compute(self, x, y):
        raise NotImplementedError

    def _supports_string(self) -> bool:
        return False

    def tpu_supported(self, conf):
        if self.left.dtype.is_string or self.right.dtype.is_string:
            if not self._supports_string():
                return "string ordering comparisons not supported on TPU"
        return None

    def tpu_eval(self, ctx) -> DevVal:
        if self.left.dtype.is_string and self._supports_string():
            # Hash-based equality works directly on dictionary-encoded
            # columns — keep the encoding so the dictionary is hashed once.
            from spark_rapids_tpu.exprs.base import eval_maybe_encoded
            lv = eval_maybe_encoded(self.left, ctx)
            rv = eval_maybe_encoded(self.right, ctx)
        else:
            lv, rv = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        if lv.dtype.is_string:
            data = self._compute_string_dev(lv, rv)
            return DevVal(T.BOOLEAN, data, lv.validity & rv.validity)
        a, b, _ = promote_dev(lv, rv)
        return DevVal(T.BOOLEAN, self._compute(a.data, b.data),
                      a.validity & b.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        lv, rv = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        if lv.dtype.is_string:
            x = np.array([str(v) for v in lv.values], dtype=object)
            y = np.array([str(v) for v in rv.values], dtype=object)
            data = self._compute(x, y)
            return CpuVal(T.BOOLEAN, np.asarray(data, dtype=np.bool_),
                          lv.validity & rv.validity)
        a, b, _ = promote_cpu(lv, rv)
        return CpuVal(T.BOOLEAN, np.asarray(self._compute(a.values, b.values),
                                            dtype=np.bool_),
                      a.validity & b.validity)

    def _compute_string_dev(self, a: DevVal, b: DevVal):
        raise NotImplementedError


class Equals(_Comparison):
    def _supports_string(self):
        return True

    def _compute(self, x, y):
        return x == y

    def _compute_string_dev(self, a, b):
        return _string_eq_dev(a, b)


class NotEquals(_Comparison):
    def _supports_string(self):
        return True

    def _compute(self, x, y):
        return x != y

    def _compute_string_dev(self, a, b):
        return ~_string_eq_dev(a, b)


class LessThan(_Comparison):
    def _compute(self, x, y):
        return x < y


class LessThanOrEqual(_Comparison):
    def _compute(self, x, y):
        return x <= y


class GreaterThan(_Comparison):
    def _compute(self, x, y):
        return x > y


class GreaterThanOrEqual(_Comparison):
    def _compute(self, x, y):
        return x >= y


class EqualNullSafe(BinaryExpression):
    """<=> : never NULL; NULL <=> NULL is true."""

    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = False

    def tpu_supported(self, conf):
        return None

    def tpu_eval(self, ctx) -> DevVal:
        if self.left.dtype.is_string:
            from spark_rapids_tpu.exprs.base import eval_maybe_encoded
            lv = eval_maybe_encoded(self.left, ctx)
            rv = eval_maybe_encoded(self.right, ctx)
        else:
            lv, rv = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        if lv.dtype.is_string:
            eq = _string_eq_dev(lv, rv)
        else:
            a, b, _ = promote_dev(lv, rv)
            eq = a.data == b.data
            lv, rv = a, b
        both_null = ~lv.validity & ~rv.validity
        data = jnp.where(both_null, True, eq & lv.validity & rv.validity)
        return DevVal(T.BOOLEAN, data, jnp.ones_like(data, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        lv, rv = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        if lv.dtype.is_string:
            eq = np.array([str(a) == str(b) for a, b in zip(lv.values, rv.values)],
                          dtype=np.bool_)
        else:
            a, b, _ = promote_cpu(lv, rv)
            eq = a.values == b.values
        both_null = ~lv.validity & ~rv.validity
        data = np.where(both_null, True, eq & lv.validity & rv.validity)
        return CpuVal(T.BOOLEAN, data.astype(np.bool_),
                      np.ones(len(data), dtype=np.bool_))


class And(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        x = a.data & a.validity  # treat NULL as "not definitely true"
        y = b.data & b.validity
        false_a = a.validity & ~a.data
        false_b = b.validity & ~b.data
        validity = (a.validity & b.validity) | false_a | false_b
        return DevVal(T.BOOLEAN, x & y, validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        x = a.values.astype(np.bool_) & a.validity
        y = b.values.astype(np.bool_) & b.validity
        false_a = a.validity & ~a.values.astype(np.bool_)
        false_b = b.validity & ~b.values.astype(np.bool_)
        validity = (a.validity & b.validity) | false_a | false_b
        return CpuVal(T.BOOLEAN, x & y, validity)


class Or(BinaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = self.left.nullable or self.right.nullable

    def tpu_eval(self, ctx) -> DevVal:
        a, b = self.left.tpu_eval(ctx), self.right.tpu_eval(ctx)
        true_a = a.validity & a.data
        true_b = b.validity & b.data
        validity = (a.validity & b.validity) | true_a | true_b
        return DevVal(T.BOOLEAN, true_a | true_b, validity)

    def cpu_eval(self, ctx) -> CpuVal:
        a, b = self.left.cpu_eval(ctx), self.right.cpu_eval(ctx)
        true_a = a.validity & a.values.astype(np.bool_)
        true_b = b.validity & b.values.astype(np.bool_)
        validity = (a.validity & b.validity) | true_a | true_b
        return CpuVal(T.BOOLEAN, true_a | true_b, validity)


class Not(UnaryExpression):
    def _resolve_type(self):
        self.dtype = T.BOOLEAN
        self.nullable = self.child.nullable

    def tpu_eval(self, ctx) -> DevVal:
        v = self.child.tpu_eval(ctx)
        return DevVal(T.BOOLEAN, ~v.data.astype(jnp.bool_), v.validity)

    def cpu_eval(self, ctx) -> CpuVal:
        v = self.child.cpu_eval(ctx)
        return CpuVal(T.BOOLEAN, ~v.values.astype(np.bool_), v.validity)


class In(Expression):
    """value IN (literals...) — OR of equality tests (GpuInSet analogue)."""

    def __init__(self, value: Expression, options):
        opts = tuple(o if isinstance(o, Expression) else Literal(o) for o in options)
        self.children = (value,) + opts
        self.dtype = T.BOOLEAN
        self.nullable = value.nullable

    def with_children(self, children):
        return In(children[0], children[1:])

    def _as_or(self) -> Expression:
        value = self.children[0]
        expr: Expression = Equals(value, self.children[1])
        for opt in self.children[2:]:
            expr = Or(expr, Equals(value, opt))
        return expr

    def tpu_eval(self, ctx):
        return self._as_or().tpu_eval(ctx)

    def cpu_eval(self, ctx):
        return self._as_or().cpu_eval(ctx)
