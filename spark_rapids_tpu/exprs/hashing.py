"""Hash expressions: Spark-compatible murmur3_x86_32 (seed 42) for fixed-width
types, used by hash partitioning and hash joins (reference:
GpuHashPartitioning.scala — "cudf murmur3-compatible hash").

Everything is uint32 modular arithmetic, fully elementwise -> lowers to pure
VPU work on TPU.  Strings use the polynomial row hashes from
exprs.strings (engine-internal determinism is all partitioning needs).
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.base import CpuVal, DevVal, Expression

_C1 = 0xCC9E2D51
_C2 = 0x1B873593


def _rotl32(x, r, xp):
    return ((x << xp.uint32(r)) | (x >> xp.uint32(32 - r))).astype(xp.uint32)


def _mix_k1(k1, xp):
    k1 = (k1 * xp.uint32(_C1)).astype(xp.uint32)
    k1 = _rotl32(k1, 15, xp)
    return (k1 * xp.uint32(_C2)).astype(xp.uint32)


def _mix_h1(h1, k1, xp):
    h1 = (h1 ^ k1).astype(xp.uint32)
    h1 = _rotl32(h1, 13, xp)
    return (h1 * xp.uint32(5) + xp.uint32(0xE6546B64)).astype(xp.uint32)


def _fmix(h, length, xp):
    h = (h ^ xp.uint32(length)).astype(xp.uint32)
    h = h ^ (h >> xp.uint32(16))
    h = (h * xp.uint32(0x85EBCA6B)).astype(xp.uint32)
    h = h ^ (h >> xp.uint32(13))
    h = (h * xp.uint32(0xC2B2AE35)).astype(xp.uint32)
    return h ^ (h >> xp.uint32(16))


def _words_of(v: DevVal, xp):
    """Decompose a fixed-width column into 32-bit words (Spark layout:
    int-like promoted to int; long/double as two words low,high)."""
    dt = v.dtype
    data = v.data
    if dt in (T.BOOLEAN, T.BYTE, T.SHORT, T.INT, T.DATE):
        return [data.astype(xp.int32).astype(xp.uint32)], 4
    if dt in (T.LONG, T.TIMESTAMP):
        x = data.astype(xp.int64).astype(xp.uint64)
        lo = (x & xp.uint64(0xFFFFFFFF)).astype(xp.uint32)
        hi = (x >> xp.uint64(32)).astype(xp.uint32)
        return [lo, hi], 8
    if dt == T.FLOAT:
        # normalize -0.0 to 0.0 like Spark
        x = xp.where(data == 0, xp.zeros_like(data), data)
        bits = x.astype(xp.float32)
        u = np.frombuffer(np.asarray(bits).tobytes(), dtype=np.uint32) \
            if xp is np else None
        if xp is np:
            return [u.copy()], 4
        import jax
        return [jax.lax.bitcast_convert_type(bits, jnp.uint32)], 4
    if dt == T.DOUBLE:
        # Two-float (hi, lo) encoding hashed as two f32 words: the TPU's
        # f64 emulation stores doubles as f32 pairs and cannot bitcast raw
        # IEEE-64 bits at all, so BOTH engines hash this encoding — ~48
        # effective mantissa bits, matching the emulation's own precision.
        # Diverges from Spark's raw-bit double hash (partition placement
        # only; docs/compatibility.md).
        # Magnitudes past float32 range are ONE equality class per sign on
        # the TPU engine (the f32-pair emulation saturates them at ingest),
        # so both engines canonicalize lo to 0 when hi is non-finite —
        # keys that compare equal on device must hash equal.
        x = xp.where(data == 0, xp.zeros_like(data), data)
        if xp is np:
            x64 = np.asarray(x, dtype=np.float64)
            with np.errstate(invalid="ignore", over="ignore"):
                hi32 = x64.astype(np.float32)
                lo32 = (x64 - hi32.astype(np.float64)).astype(np.float32)
            lo32 = np.where(np.isfinite(hi32), lo32, np.float32(0.0))

            def norm_np(f):
                f = np.where(np.isnan(f), np.float32(np.nan), f)
                return np.where(f == 0, np.float32(0.0), f)

            hi_b = np.frombuffer(norm_np(hi32).tobytes(),
                                 np.uint32).copy()
            lo_b = np.frombuffer(norm_np(lo32).tobytes(),
                                 np.uint32).copy()
        else:
            import jax
            hi32 = x.astype(jnp.float32)
            lo32 = (x - hi32.astype(jnp.float64)).astype(jnp.float32)
            lo32 = jnp.where(jnp.isfinite(hi32), lo32, jnp.float32(0.0))

            def norm_j(f):
                f = jnp.where(jnp.isnan(f), jnp.float32(jnp.nan), f)
                return jnp.where(f == 0, jnp.float32(0.0), f)

            hi_b = jax.lax.bitcast_convert_type(norm_j(hi32), jnp.uint32)
            lo_b = jax.lax.bitcast_convert_type(norm_j(lo32), jnp.uint32)
        return [lo_b, hi_b], 8
    raise TypeError(f"murmur3 on {dt}")


def murmur3_cols(vals: Sequence[DevVal], seed: int = 42):
    """Combined row hash over several device columns (Spark semantics: each
    column's hash feeds the next as seed; NULL columns are skipped)."""
    cap = None
    for v in vals:
        cap = int(v.validity.shape[0])
        break
    h = jnp.full(cap, np.uint32(seed), dtype=jnp.uint32)
    for v in vals:
        if v.dtype.is_string:
            from spark_rapids_tpu.exprs.strings import string_hash2
            h1, h2 = string_hash2(v)
            words, length = [h1, h2], 8
        else:
            words, length = _words_of(v, jnp)
        hv = h
        for w in words:
            hv = _mix_h1(hv, _mix_k1(w, jnp), jnp)
        hv = _fmix(hv, length, jnp)
        # NULL input leaves the running hash unchanged (Spark semantics).
        h = jnp.where(v.validity, hv, h)
    return h.astype(jnp.int32)


def murmur3_cols_cpu(vals: Sequence[CpuVal], seed: int = 42):
    n = len(vals[0].validity)
    h = np.full(n, np.uint32(seed), dtype=np.uint32)
    with np.errstate(over="ignore"):
        for v in vals:
            if v.dtype.is_string:
                from spark_rapids_tpu.exprs.strings import hash_literal2
                pairs = [hash_literal2(str(s)) for s in v.values]
                lo = np.array([p[0] for p in pairs], dtype=np.uint32)
                hi = np.array([p[1] for p in pairs], dtype=np.uint32)
                words, length = [lo, hi], 8
            else:
                words, length = _words_of(
                    DevVal(v.dtype, v.values, v.validity), np)
            hv = h
            for w in words:
                hv = _mix_h1(hv, _mix_k1(w, np), np)
            hv = _fmix(hv, length, np)
            h = np.where(v.validity, hv, h)
    return h.astype(np.int32)


class Murmur3Hash(Expression):
    def __init__(self, *children: Expression, seed: int = 42):
        self.children = tuple(children)
        self.seed = seed
        self.dtype = T.INT
        self.nullable = False

    def with_children(self, children):
        return Murmur3Hash(*children, seed=self.seed)

    def tpu_eval(self, ctx) -> DevVal:
        vals = [c.tpu_eval(ctx) for c in self.children]
        data = murmur3_cols(vals, self.seed)
        return DevVal(T.INT, data, jnp.ones_like(data, dtype=jnp.bool_))

    def cpu_eval(self, ctx) -> CpuVal:
        vals = [c.cpu_eval(ctx) for c in self.children]
        data = murmur3_cols_cpu(vals, self.seed)
        return CpuVal(T.INT, data, np.ones(len(data), dtype=np.bool_))
