"""SQL lexer."""

from __future__ import annotations

import dataclasses
import re
from typing import Iterator, List

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "is", "null", "like", "between",
    "join", "inner", "left", "right", "full", "outer", "semi", "anti",
    "cross", "on", "using", "union", "all", "distinct", "intersect",
    "except", "case", "when",
    "then", "else", "end", "asc", "desc", "nulls", "first", "last", "cast",
    "true", "false", "exists", "interval", "over", "partition", "rows",
    "range", "unbounded", "preceding", "following", "current", "row",
}

TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+\.\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|\d+([eE][+-]?\d+)?)
  | (?P<string>'([^']|'')*')
  | (?P<qident>`[^`]+`|"[^"]+")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|<>|!=|==|\|\||[-+*/%(),.<>=])
""", re.VERBOSE)


@dataclasses.dataclass
class Token:
    kind: str  # keyword | ident | number | string | op | eof
    value: str
    pos: int

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    while i < len(sql):
        m = TOKEN_RE.match(sql, i)
        if not m:
            raise SyntaxError(f"cannot tokenize SQL at {sql[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind in ("ws", "comment"):
            continue
        if kind == "ident":
            low = text.lower()
            if low in KEYWORDS:
                out.append(Token("keyword", low, m.start()))
            else:
                out.append(Token("ident", text, m.start()))
        elif kind == "qident":
            out.append(Token("ident", text[1:-1], m.start()))
        elif kind == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"),
                             m.start()))
        else:
            out.append(Token(kind, text, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out
