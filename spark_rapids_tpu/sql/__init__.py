"""SQL frontend: lexer + recursive-descent parser producing logical plans
(Catalyst's parser role; the reference relies on Spark SQL for this layer,
so the TPU build provides its own to be standalone)."""
