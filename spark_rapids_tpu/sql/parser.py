"""Recursive-descent SQL parser -> DataFrame/logical plan.

Grammar (enough for the TPC-H/TPC-DS-style workloads the reference
benchmarks with, SURVEY.md section 4.5):

  query     := select [UNION ALL select]* [ORDER BY ...] [LIMIT n]
  select    := SELECT [DISTINCT] proj (, proj)* FROM source (join)*
               [WHERE expr] [GROUP BY expr*] [HAVING expr]
  source    := ident [[AS] alias] | ( query ) [AS] alias
  join      := [INNER|LEFT [OUTER]|RIGHT [OUTER]|FULL [OUTER]|LEFT SEMI|
               LEFT ANTI|CROSS] JOIN source (ON expr | USING (cols))
  expr      := standard precedence: OR > AND > NOT > cmp > add > mul > unary
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs import aggregates as A
from spark_rapids_tpu.exprs.windows import (
    WindowExpression as _WindowExpression,
)
from spark_rapids_tpu.exprs.base import (
    Alias, ColumnRef, Expression, Literal, SortOrder,
)
from spark_rapids_tpu.sql.lexer import Token, tokenize


class _GeneratorCall(Expression):
    """Marker for explode()/posexplode() in a SELECT list: build_select
    rewrites the source through DataFrame.explode before projecting
    (Spark's single-generator-per-select rule)."""

    def __init__(self, column: str, pos: bool, outer: bool):
        self.column = column
        self.pos = pos
        self.outer = outer
        self.children = ()
        self.dtype = T.NULL
        self.nullable = True

    def with_children(self, children):
        return self


class Parser:
    def __init__(self, tokens: List[Token], session):
        self.toks = tokens
        self.i = 0
        self.session = session
        # WITH-clause bindings, name -> DataFrame; consulted before the
        # session catalog so a CTE shadows a view of the same name
        self.ctes = {}

    # -- token helpers ------------------------------------------------------

    def peek(self, offset=0) -> Token:
        return self.toks[min(self.i + offset, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            got = self.peek()
            raise SyntaxError(
                f"expected {value or kind}, got {got.value!r} at {got.pos}")
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "keyword" and t.value in words

    def _at_ident(self, *words: str) -> bool:
        """Context-sensitive soft keyword: an identifier matching one of
        ``words`` (ROLLUP/CUBE/GROUPING SETS are not reserved — a column
        may be named rollup)."""
        t = self.peek()
        return t.kind == "ident" and t.value.lower() in words

    # -- entry --------------------------------------------------------------

    def _setop_qualifier(self, op: str) -> bool:
        """Parse [ALL | DISTINCT] after a set-op keyword; True = ALL."""
        has_all = bool(self.accept("keyword", "all"))
        has_distinct = bool(self.accept("keyword", "distinct"))
        if has_all and has_distinct:
            raise SyntaxError(f"{op.upper()} ALL DISTINCT is contradictory")
        return has_all

    def parse_set_term(self):
        """select [INTERSECT select]* — INTERSECT binds tighter than
        UNION/EXCEPT (SQL precedence)."""
        df = self.parse_select()
        while self.at_kw("intersect"):
            self.next()
            if self._setop_qualifier("intersect"):
                raise NotImplementedError(
                    "INTERSECT ALL (multiset semantics) is not "
                    "supported; use INTERSECT [DISTINCT]")
            df = df.intersect(self.parse_select())
        return df

    def parse_statement(self):
        """[WITH name AS (query) [, ...]] query — CTEs are lazy
        DataFrames bound into a parser-local namespace (Spark expands
        CTE references the same way: each reference re-plans the
        subtree; the plan-fingerprint memo de-duplicates compilation)."""
        if self._at_ident("with"):
            self.next()
            while True:
                name = self.expect("ident").value
                self.expect("keyword", "as")
                self.expect("op", "(")
                self.ctes[name.lower()] = self.parse_query()
                self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        return self.parse_query()

    def parse_query(self):
        df = self.parse_set_term()
        while self.at_kw("union", "except"):
            op = self.next().value
            has_all = self._setop_qualifier(op)
            if op == "union":
                df = df.union(self.parse_set_term())
                if not has_all:
                    df = df.distinct()
            elif has_all:
                raise NotImplementedError(
                    "EXCEPT ALL (multiset semantics) is not supported; "
                    "use EXCEPT [DISTINCT]")
            else:
                df = df.subtract(self.parse_set_term())
        if self.at_kw("order"):
            self.next()
            self.expect("keyword", "by")
            orders = [self.parse_sort_item(df) for _ in [0]]
            while self.accept("op", ","):
                orders.append(self.parse_sort_item(df))
            df = df.order_by(*orders)
        if self.at_kw("limit"):
            self.next()
            n = int(self.expect("number").value)
            df = df.limit(n)
        return df

    def parse_sort_item(self, df) -> SortOrder:
        e = self.parse_expr()
        asc = True
        if self.accept("keyword", "asc"):
            asc = True
        elif self.accept("keyword", "desc"):
            asc = False
        nulls_first = None
        if self.accept("keyword", "nulls"):
            w = self.next()
            nulls_first = w.value == "first"
        return SortOrder(e, asc, nulls_first)

    # -- SELECT -------------------------------------------------------------

    def parse_select(self):
        self.expect("keyword", "select")
        distinct = bool(self.accept("keyword", "distinct"))
        projections: List[Tuple[Expression, Optional[str]]] = []
        star = False
        while True:
            if self.accept("op", "*"):
                star = True
            else:
                e = self.parse_expr()
                name = None
                if self.accept("keyword", "as"):
                    name = self.next().value
                elif self.peek().kind == "ident" and not self.at_kw():
                    name = self.next().value
                projections.append((e, name))
            if not self.accept("op", ","):
                break
        self.expect("keyword", "from")
        df = self.parse_source()
        df = self.parse_joins(df)
        where = None
        if self.accept("keyword", "where"):
            where = self.parse_expr()
        group_by: Optional[List[Expression]] = None
        group_sets = None  # None = plain GROUP BY; else list of index sets
        if self.accept("keyword", "group"):
            self.expect("keyword", "by")
            if self._at_ident("rollup", "cube") and \
                    self.peek(1).value == "(":
                kind = self.next().value.lower()
                self.expect("op", "(")
                group_by = [self.parse_expr()]
                while self.accept("op", ","):
                    group_by.append(self.parse_expr())
                self.expect("op", ")")
                from spark_rapids_tpu.dataframe import (
                    cube_sets, rollup_sets,
                )
                n = len(group_by)
                group_sets = rollup_sets(n) if kind == "rollup" \
                    else cube_sets(n)
            elif self._at_ident("grouping") and \
                    self.peek(1).kind == "ident" and \
                    self.peek(1).value.lower() == "sets":
                self.next()
                self.next()
                self.expect("op", "(")
                raw_sets = []
                keys: List[Expression] = []
                while True:
                    one = []
                    if self.accept("op", "("):
                        if not (self.peek().kind == "op"
                                and self.peek().value == ")"):
                            one.append(self.parse_expr())
                            while self.accept("op", ","):
                                one.append(self.parse_expr())
                        self.expect("op", ")")
                    else:
                        # bare expression = one-element set (Spark
                        # shorthand: GROUPING SETS (a, (b, c), ()))
                        one.append(self.parse_expr())
                    idxs = []
                    for e in one:
                        key = next((i for i, k in enumerate(keys)
                                    if repr(k) == repr(e)), None)
                        if key is None:
                            key = len(keys)
                            keys.append(e)
                        idxs.append(key)
                    raw_sets.append(tuple(idxs))
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
                group_by, group_sets = keys, raw_sets
            else:
                group_by = [self.parse_expr()]
                while self.accept("op", ","):
                    group_by.append(self.parse_expr())
        having = None
        if self.accept("keyword", "having"):
            having = self.parse_expr()

        return self.build_select(df, star, projections, where, group_by,
                                 having, distinct, group_sets)

    def build_select(self, df, star, projections, where, group_by, having,
                     distinct, group_sets=None):
        from spark_rapids_tpu.dataframe import Column
        from spark_rapids_tpu.exprs.base import output_name, resolve
        def _has_gen(e):
            if isinstance(e, _GeneratorCall):
                return True
            return any(_has_gen(c) for c in e.children)

        for clause in ([where] if where is not None else []) \
                + (group_by or []) \
                + ([having] if having is not None else []):
            if _has_gen(clause):
                raise SyntaxError(
                    "explode/posexplode is only allowed as a top-level "
                    "SELECT expression")
        gens = [(i, e, nm) for i, (e, nm) in enumerate(projections)
                if isinstance(e, _GeneratorCall)]
        for e, _nm in projections:
            if not isinstance(e, _GeneratorCall) and _has_gen(e):
                raise SyntaxError(
                    "explode/posexplode cannot be nested inside another "
                    "expression")
        if len(gens) > 1:
            raise SyntaxError(
                "only one generator (explode/posexplode) per SELECT")
        if gens and star:
            raise SyntaxError(
                "SELECT * with a generator is not supported; list the "
                "columns explicitly (the engine's explode replaces the "
                "source array column)")
        # WHERE runs pre-projection, so filter BEFORE exploding (the
        # predicate may reference the array column Generate drops)
        if where is not None:
            df = df.filter(Column(where))
            where = None
        if gens:
            i, g, nm = gens[0]
            alias = nm or "col"
            if g.pos and "pos" in df.schema:
                raise SyntaxError(
                    "posexplode output column 'pos' collides with an "
                    "existing column; rename it first")
            df = df.explode(g.column, alias=alias, pos=g.pos,
                            outer=g.outer)
            if g.pos:
                # posexplode emits (pos, col); surface both columns
                projections = (projections[:i]
                               + [(ColumnRef("pos"), "pos"),
                                  (ColumnRef(alias), alias)]
                               + projections[i + 1:])
            else:
                projections = (projections[:i]
                               + [(ColumnRef(alias), alias)]
                               + projections[i + 1:])
        has_agg = group_by is not None or any(
            _contains_agg(e) for e, _ in projections) or \
            (having is not None and _contains_agg(having))
        if has_agg:
            keys = [resolve(k, df.schema) for k in (group_by or [])]
            key_names = [output_name(k, i) for i, k in enumerate(keys)]
            key_map = {k.fingerprint(): nm for k, nm in zip(keys, key_names)}
            if group_sets is not None:
                gd = df._grouping_sets([Column(k) for k in keys],
                                       group_sets)
            else:
                gd = df.group_by(*[Column(k) for k in keys])
            aggs, post = [], []  # post: (output_name, expr-or-None)
            agg_map = {}  # repr(agg) -> output column name
            for idx, (e, name) in enumerate(projections):
                nm = name or _default_name(e, idx)
                if _contains_agg(e):
                    er = resolve(e, df.schema)
                    if isinstance(er, A.AggregateFunction):
                        aggs.append(Column(Alias(er, nm)))
                        agg_map[er.fingerprint()] = nm
                        post.append((nm, None))
                    else:
                        # post-agg arithmetic (avg(x) * 1.2, sum(a)/sum(b)):
                        # aggregate the embedded calls under hidden names,
                        # then project the expression over the agg output
                        for a in _collect_aggs(er):
                            if a.fingerprint() not in agg_map:
                                hn = f"__agg_{len(agg_map)}"
                                aggs.append(Column(Alias(a, hn)))
                                agg_map[a.fingerprint()] = hn
                        post.append((nm, ("postagg", er)))
                else:
                    post.append((nm, resolve(e, df.schema)))
            # HAVING may reference aggregates not in the projection list
            hidden = []
            if having is not None:
                having = resolve(having, df.schema)
                for a in _collect_aggs(having):
                    if a.fingerprint() not in agg_map:
                        hn = f"__having_{len(hidden)}"
                        aggs.append(Column(Alias(a, hn)))
                        agg_map[a.fingerprint()] = hn
                        hidden.append(hn)
            out = gd.agg(*aggs)
            if having is not None:
                hexpr = _replace_aggs(having, agg_map, key_map)
                out = out.filter(Column(hexpr))
            sel = []
            for nm, e in post:
                if e is None:
                    sel.append(Column(ColumnRef(nm)).alias(nm))
                elif isinstance(e, tuple) and e[0] == "postagg":
                    e2 = _replace_aggs(e[1], agg_map, key_map)
                    sel.append(Column(e2).alias(nm))
                else:
                    e2 = _replace_keys(e, key_map)
                    sel.append(Column(e2).alias(nm))
            df = out.select(*sel)
        elif star and not projections:
            pass
        else:
            sel = []
            if star:
                sel.append("*")
            for idx, (e, name) in enumerate(projections):
                sel.append(Column(Alias(e, name or _default_name(e, idx))))
            df = df.select(*sel)
        if distinct:
            df = df.distinct()
        return df

    # -- FROM / JOIN --------------------------------------------------------

    def parse_source(self):
        if self.accept("op", "("):
            sub = self.parse_query()
            self.expect("op", ")")
            self.accept("keyword", "as")
            if self.peek().kind == "ident":
                self.next()  # alias (single-namespace: names already unique)
            return sub
        name = self.expect("ident").value
        df = self.ctes.get(name.lower())
        if df is None:
            df = self.session.table(name)
        self.accept("keyword", "as")
        if self.peek().kind == "ident" and not self.at_kw():
            self.next()
        return df

    def parse_joins(self, df):
        while True:
            how = None
            if self.at_kw("inner") or self.at_kw("join"):
                self.accept("keyword", "inner")
                how = "inner"
            elif self.at_kw("left"):
                self.next()
                if self.accept("keyword", "semi"):
                    how = "left_semi"
                elif self.accept("keyword", "anti"):
                    how = "left_anti"
                else:
                    self.accept("keyword", "outer")
                    how = "left"
            elif self.at_kw("right"):
                self.next()
                self.accept("keyword", "outer")
                how = "right"
            elif self.at_kw("full"):
                self.next()
                self.accept("keyword", "outer")
                how = "full"
            elif self.at_kw("cross"):
                self.next()
                how = "cross"
            else:
                return df
            self.expect("keyword", "join")
            right = self.parse_source()
            if how == "cross":
                df = df.cross_join(right)
                continue
            if self.accept("keyword", "using"):
                self.expect("op", "(")
                cols = [self.expect("ident").value]
                while self.accept("op", ","):
                    cols.append(self.expect("ident").value)
                self.expect("op", ")")
                df = df.join(right, on=cols, how=how)
            else:
                self.expect("keyword", "on")
                cond = self.parse_expr()
                from spark_rapids_tpu.dataframe import Column
                df = df.join(right, on=Column(cond), how=how)
        return df

    # -- expressions --------------------------------------------------------

    def parse_expr(self) -> Expression:
        return self.parse_or()

    def parse_or(self) -> Expression:
        from spark_rapids_tpu.exprs.predicates import Or
        e = self.parse_and()
        while self.accept("keyword", "or"):
            e = Or(e, self.parse_and())
        return e

    def parse_and(self) -> Expression:
        from spark_rapids_tpu.exprs.predicates import And
        e = self.parse_not()
        while self.accept("keyword", "and"):
            e = And(e, self.parse_not())
        return e

    def parse_not(self) -> Expression:
        from spark_rapids_tpu.exprs.predicates import Not
        if self.accept("keyword", "not"):
            return Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expression:
        from spark_rapids_tpu.exprs import predicates as P
        from spark_rapids_tpu.exprs.nullexprs import IsNotNull, IsNull
        from spark_rapids_tpu.exprs.strings import Like
        e = self.parse_additive()
        while True:
            if self.accept("keyword", "is"):
                neg = bool(self.accept("keyword", "not"))
                self.expect("keyword", "null")
                e = IsNotNull(e) if neg else IsNull(e)
                continue
            neg = False
            save = self.i
            if self.accept("keyword", "not"):
                if self.at_kw("in", "like", "between"):
                    neg = True
                else:
                    self.i = save
                    return e
            if self.accept("keyword", "in"):
                self.expect("op", "(")
                opts = [self.parse_expr()]
                while self.accept("op", ","):
                    opts.append(self.parse_expr())
                self.expect("op", ")")
                e = P.In(e, opts)
                if neg:
                    e = P.Not(e)
                continue
            if self.accept("keyword", "like"):
                pat = self.expect("string").value
                e = Like(e, pat)
                if neg:
                    e = P.Not(e)
                continue
            if self.accept("keyword", "between"):
                lo = self.parse_additive()
                self.expect("keyword", "and")
                hi = self.parse_additive()
                e = P.And(P.GreaterThanOrEqual(e, lo),
                          P.LessThanOrEqual(e, hi))
                if neg:
                    e = P.Not(e)
                continue
            op = self.peek()
            if op.kind == "op" and op.value in ("=", "==", "<>", "!=", "<",
                                               "<=", ">", ">="):
                self.next()
                rhs = self.parse_additive()
                cls = {"=": P.Equals, "==": P.Equals, "<>": P.NotEquals,
                       "!=": P.NotEquals, "<": P.LessThan,
                       "<=": P.LessThanOrEqual, ">": P.GreaterThan,
                       ">=": P.GreaterThanOrEqual}[op.value]
                e = cls(e, rhs)
                continue
            return e

    def parse_additive(self) -> Expression:
        from spark_rapids_tpu.exprs.arithmetic import Add, Subtract
        from spark_rapids_tpu.exprs.strings import ConcatStrings
        e = self.parse_multiplicative()
        while True:
            if self.accept("op", "+"):
                e = Add(e, self.parse_multiplicative())
            elif self.accept("op", "-"):
                e = Subtract(e, self.parse_multiplicative())
            elif self.accept("op", "||"):
                e = ConcatStrings(e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self) -> Expression:
        from spark_rapids_tpu.exprs.arithmetic import (
            Divide, Multiply, Remainder,
        )
        e = self.parse_unary()
        while True:
            if self.accept("op", "*"):
                e = Multiply(e, self.parse_unary())
            elif self.accept("op", "/"):
                e = Divide(e, self.parse_unary())
            elif self.accept("op", "%"):
                e = Remainder(e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> Expression:
        from spark_rapids_tpu.exprs.arithmetic import UnaryMinus
        if self.accept("op", "-"):
            return UnaryMinus(self.parse_unary())
        if self.accept("op", "+"):
            from spark_rapids_tpu.exprs.arithmetic import UnaryPositive
            return UnaryPositive(self.parse_unary())
        return self.parse_primary()

    def parse_primary(self) -> Expression:
        t = self.peek()
        if self.accept("op", "("):
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if t.kind == "number":
            self.next()
            txt = t.value
            if "." in txt or "e" in txt.lower():
                return Literal(float(txt))
            v = int(txt)
            return Literal(v)
        if t.kind == "string":
            self.next()
            return Literal(t.value)
        if self.accept("keyword", "true"):
            return Literal(True)
        if self.accept("keyword", "false"):
            return Literal(False)
        if self.accept("keyword", "null"):
            return Literal(None)
        if self.accept("keyword", "case"):
            return self.parse_case()
        if self.accept("keyword", "cast"):
            self.expect("op", "(")
            e = self.parse_expr()
            self.expect("keyword", "as")
            tname = self.next().value
            self.expect("op", ")")
            from spark_rapids_tpu.exprs.cast import Cast
            return Cast(e, T.type_from_name(tname))
        if t.kind == "ident":
            self.next()
            if self.peek().kind == "op" and self.peek().value == "(":
                return self.parse_function(t.value)
            # qualified name a.b -> column b (single namespace)
            if self.accept("op", "."):
                col = self.next().value
                return ColumnRef(col)
            return ColumnRef(t.value)
        raise SyntaxError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_case(self) -> Expression:
        from spark_rapids_tpu.exprs.conditional import CaseWhen
        from spark_rapids_tpu.exprs.predicates import Equals
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        branches = []
        while self.accept("keyword", "when"):
            cond = self.parse_expr()
            if operand is not None:
                cond = Equals(operand, cond)
            self.expect("keyword", "then")
            val = self.parse_expr()
            branches.append((cond, val))
        default = None
        if self.accept("keyword", "else"):
            default = self.parse_expr()
        self.expect("keyword", "end")
        return CaseWhen(branches, default)

    def parse_function(self, name: str) -> Expression:
        self.expect("op", "(")
        name_l = name.lower()
        distinct = bool(self.accept("keyword", "distinct"))
        args: List[Expression] = []
        star = False
        if self.accept("op", "*"):
            star = True
        elif not (self.peek().kind == "op" and self.peek().value == ")"):
            args.append(self.parse_expr())
            while self.accept("op", ","):
                args.append(self.parse_expr())
        self.expect("op", ")")
        e = _build_function(name_l, args, star, distinct)
        if self.accept("keyword", "over"):
            e = self.parse_over(e)
        return e

    def parse_over(self, fn: Expression) -> Expression:
        from spark_rapids_tpu.exprs.windows import WindowFrame
        self.expect("op", "(")
        part = []
        orders = []
        frame = None
        if self.accept("keyword", "partition"):
            self.expect("keyword", "by")
            part.append(self.parse_expr())
            while self.accept("op", ","):
                part.append(self.parse_expr())
        if self.accept("keyword", "order"):
            self.expect("keyword", "by")
            orders.append(self.parse_sort_item(None))
            while self.accept("op", ","):
                orders.append(self.parse_sort_item(None))
        if self.at_kw("rows", "range"):
            kind = self.next().value
            self.expect("keyword", "between")
            lo = self._frame_bound()
            self.expect("keyword", "and")
            hi = self._frame_bound()
            frame = WindowFrame(kind, lo, hi)
        self.expect("op", ")")
        return _WindowExpression(fn, part, orders, frame)

    def _frame_bound(self):
        if self.accept("keyword", "unbounded"):
            self.next()  # preceding/following
            return None
        if self.accept("keyword", "current"):
            self.expect("keyword", "row")
            return 0
        n = int(self.expect("number").value)
        w = self.next().value
        return -n if w == "preceding" else n


def _build_function(name: str, args: List[Expression], star: bool,
                    distinct: bool) -> Expression:
    from spark_rapids_tpu.exprs import mathexprs as M
    from spark_rapids_tpu.exprs import datetime as D
    from spark_rapids_tpu.exprs import strings as S
    from spark_rapids_tpu.exprs import nullexprs as N
    from spark_rapids_tpu.exprs.windows import (
        DenseRank, Lag, Lead, Rank, RowNumber,
    )
    if name == "count":
        if star or not args:
            return A.count_star()
        if distinct:
            if len(args) != 1:
                raise NotImplementedError(
                    "COUNT(DISTINCT a, b, ...) over multiple columns is "
                    "not supported")
            return A.CountDistinct(args[0])
        return A.Count(args[0])
    if distinct:
        raise NotImplementedError(
            f"{name.upper()}(DISTINCT ...) is not supported; only "
            f"COUNT(DISTINCT x)")
    simple = {
        "sum": A.Sum, "avg": A.Average, "mean": A.Average, "min": A.Min,
        "max": A.Max, "first": A.First, "last": A.Last,
        "stddev": A.StddevSamp, "stddev_samp": A.StddevSamp,
        "std": A.StddevSamp, "stddev_pop": A.StddevPop,
        "variance": A.VarianceSamp, "var_samp": A.VarianceSamp,
        "var_pop": A.VariancePop,
        "abs": None, "sqrt": M.Sqrt, "exp": M.Exp, "ln": M.Log,
        "log": M.Log, "log2": M.Log2, "log10": M.Log10, "floor": M.Floor,
        "ceil": M.Ceil, "ceiling": M.Ceil, "sin": M.Sin, "cos": M.Cos,
        "tan": M.Tan, "asin": M.Asin, "acos": M.Acos, "atan": M.Atan,
        "signum": M.Signum, "sign": M.Signum, "sinh": M.Sinh,
        "cosh": M.Cosh, "tanh": M.Tanh, "asinh": M.Asinh,
        "acosh": M.Acosh, "atanh": M.Atanh, "cot": M.Cot,
        "upper": S.Upper, "ucase": S.Upper, "lower": S.Lower,
        "initcap": S.InitCap, "hex": S.Hex,
        "lcase": S.Lower, "length": S.Length, "char_length": S.Length,
        "trim": S.StringTrim, "ltrim": S.StringTrimLeft,
        "rtrim": S.StringTrimRight,
        "year": D.Year, "month": D.Month, "day": D.DayOfMonth,
        "dayofmonth": D.DayOfMonth, "dayofweek": D.DayOfWeek,
        "dayofyear": D.DayOfYear, "quarter": D.Quarter, "hour": D.Hour,
        "weekday": D.WeekDay,
        "minute": D.Minute, "second": D.Second,
        "isnull": N.IsNull, "isnan": N.IsNan,
    }
    if name == "abs":
        from spark_rapids_tpu.exprs.arithmetic import Abs
        return Abs(args[0])
    if name == "log" and len(args) == 2:
        return M.Logarithm(args[0], args[1])
    if name == "substring_index":
        from spark_rapids_tpu.exprs.arithmetic import UnaryMinus
        from spark_rapids_tpu.exprs.base import Literal as _Lit
        cnt = None
        if len(args) == 3:
            if isinstance(args[2], _Lit):
                cnt = int(args[2].value)
            elif isinstance(args[2], UnaryMinus) and \
                    isinstance(args[2].child, _Lit):
                cnt = -int(args[2].child.value)
        if cnt is None:
            raise SyntaxError(
                "substring_index(str, delim, count) needs a literal count")
        return S.SubstringIndex(args[0], args[1], cnt)
    if name == "split":
        if len(args) != 2:
            raise SyntaxError("split(str, delimiter) takes two arguments")
        return S.StringSplit(args[0], args[1])
    if name == "grouping_id":
        return A.GroupingID()
    if name in ("corr", "covar_pop", "covar_samp"):
        cls = {"corr": A.Corr, "covar_pop": A.CovarPop,
               "covar_samp": A.CovarSamp}[name]
        if len(args) != 2:
            raise SyntaxError(f"{name}(x, y) takes two arguments")
        return cls(args[0], args[1])
    if name == "percentile":
        from spark_rapids_tpu.exprs.base import Literal
        if len(args) != 2 or not isinstance(args[1], Literal) \
                or isinstance(args[1].value, bool) \
                or not isinstance(args[1].value, (int, float)):
            raise SyntaxError(
                "percentile(expr, p) needs a numeric literal percentage")
        return A.Percentile(args[0], float(args[1].value))
    if name in simple and simple[name] is not None:
        return simple[name](*args)
    if name == "coalesce":
        return N.Coalesce(*args)
    if name == "nvl":
        return N.Coalesce(args[0], args[1])
    if name in ("substr", "substring"):
        pos = args[1].value
        ln = args[2].value if len(args) > 2 else None
        return S.Substring(args[0], pos, ln)
    if name == "concat":
        return S.ConcatStrings(*args)
    if name in ("pow", "power"):
        return M.Pow(args[0], args[1])
    if name == "round":
        scale = args[1].value if len(args) > 1 else 0
        return M.Round(args[0], scale)
    if name == "hash":
        from spark_rapids_tpu.exprs.hashing import Murmur3Hash
        return Murmur3Hash(*args)
    if name == "row_number":
        return RowNumber()
    if name == "rank":
        return Rank()
    if name == "dense_rank":
        return DenseRank()
    if name == "lag":
        off = args[1].value if len(args) > 1 else 1
        d = args[2] if len(args) > 2 else None
        return Lag(args[0], off, d)
    if name == "lead":
        off = args[1].value if len(args) > 1 else 1
        d = args[2] if len(args) > 2 else None
        return Lead(args[0], off, d)
    if name in ("date_add",):
        return D.DateAdd(args[0], args[1])
    if name in ("date_sub",):
        return D.DateSub(args[0], args[1])
    if name == "datediff":
        return D.DateDiff(args[0], args[1])
    if name == "if":
        from spark_rapids_tpu.exprs.conditional import If
        return If(args[0], args[1], args[2])
    if name == "replace":
        return S.StringReplace(args[0], args[1], args[2])
    if name == "regexp_replace":
        return S.RegExpReplace(args[0], args[1], args[2])
    if name == "split_part":
        return S.SplitPart(args[0], args[1], args[2].value)
    if name == "concat_ws":
        sep = args[0].value if hasattr(args[0], "value") else str(args[0])
        return S.ConcatWs(sep, *args[1:])
    if name in ("lpad", "rpad"):
        cls = S.StringLPad if name == "lpad" else S.StringRPad
        pad = args[2].value if len(args) > 2 else " "
        return cls(args[0], args[1].value, pad)
    if name == "unix_timestamp":
        return D.UnixTimestamp(args[0])
    if name == "to_unix_timestamp":
        return D.ToUnixTimestamp(args[0])
    if name == "to_date":
        from spark_rapids_tpu.exprs.base import Literal as _L
        if len(args) == 1:
            return D.ToDate(args[0])
        if len(args) == 2 and isinstance(args[1], _L):
            return D.ToDate(args[0], str(args[1].value))
        raise SyntaxError("to_date(expr[, fmt]) needs a literal format")
    if name == "date_format":
        from spark_rapids_tpu.exprs.base import Literal as _L
        if len(args) != 2 or not isinstance(args[1], _L):
            raise SyntaxError(
                "date_format(expr, fmt) needs a literal format")
        return D.DateFormat(args[0], str(args[1].value))
    if name == "from_unixtime":
        if len(args) > 1:
            return D.FromUnixTime(args[0], args[1].value)
        return D.FromUnixTime(args[0])
    if name in ("shiftleft", "shiftright", "shiftrightunsigned"):
        from spark_rapids_tpu.exprs.bitwise import (
            ShiftLeft, ShiftRight, ShiftRightUnsigned,
        )
        cls = {"shiftleft": ShiftLeft, "shiftright": ShiftRight,
               "shiftrightunsigned": ShiftRightUnsigned}[name]
        return cls(args[0], args[1])
    if name == "size":
        from spark_rapids_tpu.exprs.misc import ArraySize
        return ArraySize(args[0])
    if name == "array_contains":
        from spark_rapids_tpu.exprs.misc import ArrayContains
        return ArrayContains(args[0], args[1])
    if name == "array_min":
        from spark_rapids_tpu.exprs.misc import ArrayMin
        return ArrayMin(args[0])
    if name == "array_max":
        from spark_rapids_tpu.exprs.misc import ArrayMax
        return ArrayMax(args[0])
    if name == "sort_array":
        from spark_rapids_tpu.exprs.base import Literal as _L
        from spark_rapids_tpu.exprs.misc import SortArray
        asc = True
        if len(args) == 2:
            if not isinstance(args[1], _L):
                raise SyntaxError(
                    "sort_array(arr, asc) needs a literal boolean")
            asc = bool(args[1].value)
        return SortArray(args[0], asc)
    if name == "array_position":
        from spark_rapids_tpu.exprs.misc import ArrayPosition
        return ArrayPosition(args[0], args[1])
    if name in ("explode", "explode_outer", "posexplode"):
        if len(args) != 1 or not isinstance(args[0], ColumnRef):
            raise SyntaxError(
                f"{name}() takes exactly one plain column argument")
        return _GeneratorCall(args[0].column, name == "posexplode",
                              name == "explode_outer")
    if name == "array":
        from spark_rapids_tpu.exprs.misc import CreateArray
        return CreateArray(*args)
    if name == "element_at":
        # SQL element_at is 1-based; engine ordinals are 0-based
        from spark_rapids_tpu.exprs.misc import GetArrayItem
        return GetArrayItem(args[0], int(args[1].value) - 1)
    raise SyntaxError(f"unknown function {name}")


def _contains_agg(e: Expression) -> bool:
    """True if ``e`` contains a GROUPING aggregate.  A window expression
    is opaque here: avg(x) OVER (...) is a window computation over plain
    rows (Spark classifies windowed aggregates as windows, not group
    aggs), so it must not flip the select into aggregate mode."""
    if isinstance(e, _WindowExpression):
        return False
    if isinstance(e, A.AggregateFunction):
        return True
    return any(_contains_agg(c) for c in e.children)


def _collect_aggs(e: Expression):
    if isinstance(e, A.AggregateFunction):
        return [e]
    out = []
    for c in e.children:
        out.extend(_collect_aggs(c))
    return out


def _replace_aggs(e: Expression, agg_map, key_map) -> Expression:
    if isinstance(e, A.AggregateFunction):
        return ColumnRef(agg_map[e.fingerprint()])
    if e.fingerprint() in key_map:
        return ColumnRef(key_map[e.fingerprint()])
    new_children = [_replace_aggs(c, agg_map, key_map) for c in e.children]
    if new_children and any(a is not b for a, b in
                            zip(new_children, e.children)):
        return e.with_children(new_children)
    return e


def _replace_keys(e: Expression, key_map) -> Expression:
    if e.fingerprint() in key_map:
        return ColumnRef(key_map[e.fingerprint()])
    new_children = [_replace_keys(c, key_map) for c in e.children]
    if new_children and any(a is not b for a, b in
                            zip(new_children, e.children)):
        return e.with_children(new_children)
    return e


def _default_name(e: Expression, idx: int) -> str:
    if isinstance(e, ColumnRef):
        return e.column
    if isinstance(e, Alias):
        return e.alias_name
    return f"_c{idx}"


def parse_sql(sql: str, session):
    return Parser(tokenize(sql), session).parse_statement()


def parse_expression(text: str) -> Expression:
    p = Parser(tokenize(text), None)
    return p.parse_expr()
