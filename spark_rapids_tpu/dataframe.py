"""Lazy DataFrame frontend over the logical plan.

The reference accelerates Spark's DataFrame/SQL API transparently; this
engine owns the frontend, exposing a pyspark-flavored API that builds
:mod:`spark_rapids_tpu.plan.logical` trees.  ``collect()`` runs the
TpuOverrides planner and executes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.aggregates import (
    AggregateExpression, AggregateFunction, Average, Count, First, Last, Max,
    Min, Sum, count_star,
)
from spark_rapids_tpu.exprs.base import (
    Alias, ColumnRef, Expression, Literal, SortOrder, output_name, resolve,
)
from spark_rapids_tpu.plan import logical as L


class Column:
    """Expression wrapper with operator sugar (pyspark Column analogue)."""

    def __init__(self, expr: Expression):
        self.expr = expr

    # comparison / arithmetic build expression trees lazily
    def _bin(self, other, cls):
        from spark_rapids_tpu.exprs import arithmetic as A
        from spark_rapids_tpu.exprs import predicates as P
        o = _to_expr(other)
        return Column(cls(self.expr, o))

    def __add__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Add
        return self._bin(other, Add)

    def __radd__(self, other):
        return Column(_to_expr(other)) + self

    def __sub__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Subtract
        return self._bin(other, Subtract)

    def __rsub__(self, other):
        return Column(_to_expr(other)) - self

    def __mul__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Multiply
        return self._bin(other, Multiply)

    def __rmul__(self, other):
        return Column(_to_expr(other)) * self

    def __truediv__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Divide
        return self._bin(other, Divide)

    def __mod__(self, other):
        from spark_rapids_tpu.exprs.arithmetic import Remainder
        return self._bin(other, Remainder)

    def __neg__(self):
        from spark_rapids_tpu.exprs.arithmetic import UnaryMinus
        return Column(UnaryMinus(self.expr))

    def __eq__(self, other):  # type: ignore[override]
        from spark_rapids_tpu.exprs.predicates import Equals
        return self._bin(other, Equals)

    def __ne__(self, other):  # type: ignore[override]
        from spark_rapids_tpu.exprs.predicates import NotEquals
        return self._bin(other, NotEquals)

    def __lt__(self, other):
        from spark_rapids_tpu.exprs.predicates import LessThan
        return self._bin(other, LessThan)

    def __le__(self, other):
        from spark_rapids_tpu.exprs.predicates import LessThanOrEqual
        return self._bin(other, LessThanOrEqual)

    def __gt__(self, other):
        from spark_rapids_tpu.exprs.predicates import GreaterThan
        return self._bin(other, GreaterThan)

    def __ge__(self, other):
        from spark_rapids_tpu.exprs.predicates import GreaterThanOrEqual
        return self._bin(other, GreaterThanOrEqual)

    def __and__(self, other):
        from spark_rapids_tpu.exprs.predicates import And
        return self._bin(other, And)

    def __or__(self, other):
        from spark_rapids_tpu.exprs.predicates import Or
        return self._bin(other, Or)

    def __invert__(self):
        from spark_rapids_tpu.exprs.predicates import Not
        return Column(Not(self.expr))

    def is_null(self):
        from spark_rapids_tpu.exprs.nullexprs import IsNull
        return Column(IsNull(self.expr))

    def is_not_null(self):
        from spark_rapids_tpu.exprs.nullexprs import IsNotNull
        return Column(IsNotNull(self.expr))

    def isin(self, *values):
        from spark_rapids_tpu.exprs.predicates import In
        vals = values[0] if len(values) == 1 and \
            isinstance(values[0], (list, tuple, set)) else values
        return Column(In(self.expr, list(vals)))

    def cast(self, dtype: Union[str, T.DataType]):
        from spark_rapids_tpu.exprs.cast import Cast
        dt = T.type_from_name(dtype) if isinstance(dtype, str) else dtype
        return Column(Cast(self.expr, dt))

    def alias(self, name: str) -> "Column":
        return Column(Alias(self.expr, name))

    def asc(self, nulls_first: Optional[bool] = None) -> SortOrder:
        return SortOrder(self.expr, True, nulls_first)

    def desc(self, nulls_first: Optional[bool] = None) -> SortOrder:
        return SortOrder(self.expr, False, nulls_first)

    def getItem(self, ordinal: int) -> "Column":
        from spark_rapids_tpu.exprs.misc import GetArrayItem
        return Column(GetArrayItem(self.expr, ordinal))

    def substr(self, start: int, length: int):
        from spark_rapids_tpu.exprs.strings import Substring
        return Column(Substring(self.expr, start, length))

    def startswith(self, prefix: str):
        from spark_rapids_tpu.exprs.strings import StringStartsWith
        return Column(StringStartsWith(self.expr, Literal(prefix)))

    def endswith(self, suffix: str):
        from spark_rapids_tpu.exprs.strings import StringEndsWith
        return Column(StringEndsWith(self.expr, Literal(suffix)))

    def contains(self, needle: str):
        from spark_rapids_tpu.exprs.strings import StringContains
        return Column(StringContains(self.expr, Literal(needle)))

    def like(self, pattern: str):
        from spark_rapids_tpu.exprs.strings import Like
        return Column(Like(self.expr, pattern))

    def between(self, low, high):
        return (self >= low) & (self <= high)

    def __repr__(self):
        return f"Column({self.expr!r})"

    def __hash__(self):
        return id(self)


def _to_expr(v) -> Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, Expression):
        return v
    return Literal(v)


def _to_order(v) -> SortOrder:
    if isinstance(v, SortOrder):
        return v
    if isinstance(v, str):
        return SortOrder(ColumnRef(v), True)
    if isinstance(v, Column):
        return SortOrder(v.expr, True)
    raise TypeError(f"cannot order by {v!r}")


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session):
        self.plan = plan
        self.session = session

    # -- schema -------------------------------------------------------------

    @property
    def schema(self) -> T.Schema:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return self.plan.schema.names

    def __getitem__(self, name: str) -> Column:
        f = self.schema.field(name)
        return Column(ColumnRef(name, f.dtype, f.nullable))

    def col(self, name: str) -> Column:
        return self[name]

    # -- transformations ----------------------------------------------------

    def _resolve(self, e: Expression) -> Expression:
        return resolve(e, self.schema)

    def select(self, *cols) -> "DataFrame":
        exprs, names = [], []
        for i, c in enumerate(cols):
            if isinstance(c, str):
                if c == "*":
                    for f in self.schema.fields:
                        exprs.append(ColumnRef(f.name, f.dtype, f.nullable))
                        names.append(f.name)
                    continue
                c = self[c]
            e = self._resolve(_to_expr(c))
            exprs.append(e)
            names.append(output_name(e, i))
        return DataFrame(self._project_node(exprs, names), self.session)

    def _project_node(self, exprs: List[Expression], names: List[str]):
        """Build a Project, hoisting window expressions ANYWHERE in the
        projection trees into Window nodes below it (Spark's
        ExtractWindowExpressions analogue — round 5 generalized from
        top-level-only so e.g. ``x * 100 / sum(x) OVER (...)`` works)."""
        from spark_rapids_tpu.exprs.windows import WindowExpression

        found: Dict[str, Tuple[str, Any]] = {}  # repr(w) -> (hidden, w)

        def hoist(e):
            if isinstance(e, WindowExpression):
                # fingerprint, NOT repr: repr omits frames/offsets/order
                # flags and would merge semantically different windows
                key = e.fingerprint()
                if key not in found:
                    found[key] = (f"__w{len(found)}", e)
                hn, _ = found[key]
                return ColumnRef(hn, e.dtype, True)
            kids = getattr(e, "children", ())
            if not kids:
                return e
            new_kids = [hoist(c) for c in kids]
            if all(a is b for a, b in zip(new_kids, kids)):
                return e
            return e.with_children(new_kids)

        new_exprs = [hoist(e) for e in exprs]
        if not found:
            return L.Project(exprs, names, self.plan)
        # group by (partition, order) spec; one Window node per group
        groups: Dict[str, List[Tuple[str, Any]]] = {}
        for hn, w in found.values():
            key = f"{[repr(p) for p in w.partition_by]}|" \
                  f"{[(repr(o.child), o.ascending, o.nulls_first) for o in w.order_by]}"
            groups.setdefault(key, []).append((hn, w))
        child = self.plan
        for key, items in groups.items():
            child = L.Window([w for _, w in items], [hn for hn, _ in items],
                             child)
        resolved = [resolve(e, child.schema) for e in new_exprs]
        return L.Project(resolved, names, child)

    def with_column(self, name: str, col) -> "DataFrame":
        exprs, names = [], []
        replaced = False
        for f in self.schema.fields:
            if f.name == name:
                exprs.append(self._resolve(_to_expr(col)))
                replaced = True
            else:
                exprs.append(ColumnRef(f.name, f.dtype, f.nullable))
            names.append(f.name)
        if not replaced:
            exprs.append(self._resolve(_to_expr(col)))
            names.append(name)
        return DataFrame(self._project_node(exprs, names), self.session)

    withColumn = with_column

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        exprs = [ColumnRef(f.name, f.dtype, f.nullable)
                 for f in self.schema.fields]
        names = [new if f.name == old else f.name
                 for f in self.schema.fields]
        return DataFrame(L.Project(exprs, names, self.plan), self.session)

    def drop(self, *names: str) -> "DataFrame":
        keep = [f for f in self.schema.fields if f.name not in names]
        exprs = [ColumnRef(f.name, f.dtype, f.nullable) for f in keep]
        return DataFrame(L.Project(exprs, [f.name for f in keep], self.plan),
                         self.session)

    def filter(self, condition) -> "DataFrame":
        if isinstance(condition, str):
            from spark_rapids_tpu.sql.parser import parse_expression
            condition = parse_expression(condition)
        e = self._resolve(_to_expr(condition))
        return DataFrame(L.Filter(e, self.plan), self.session)

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        keys, names = [], []
        for i, c in enumerate(cols):
            if isinstance(c, str):
                c = self[c]
            e = self._resolve(_to_expr(c))
            keys.append(e)
            names.append(output_name(e, i))
        return GroupedData(self, keys, names)

    groupBy = group_by

    def _grouping_sets(self, cols, sets) -> "GroupingSetsData":
        gd = self.group_by(*cols)
        n = len(gd.keys)
        for s in sets:
            bad = [i for i in s if not (0 <= i < n)]
            if bad:
                raise ValueError(
                    f"grouping set {s} references key positions {bad}; "
                    f"only {n} grouping keys exist")
        return GroupingSetsData(self, gd.keys, gd.names,
                                [tuple(s) for s in sets])

    def rollup(self, *cols) -> "GroupingSetsData":
        """GROUP BY ROLLUP: hierarchical subtotal grouping sets
        ((k1..kn), (k1..kn-1), ..., ()) over the Expand exec
        (GpuExpandExec's grouping-sets role)."""
        return self._grouping_sets(cols, rollup_sets(len(cols)))

    def cube(self, *cols) -> "GroupingSetsData":
        """GROUP BY CUBE: every subset of the grouping keys."""
        return self._grouping_sets(cols, cube_sets(len(cols)))

    def grouping_sets(self, cols, sets) -> "GroupingSetsData":
        """Explicit GROUPING SETS: ``sets`` is a list of tuples of key
        positions (indices into ``cols``)."""
        return self._grouping_sets(cols, [tuple(s) for s in sets])

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, [], []).agg(*aggs)

    def explode(self, column: str, alias: Optional[str] = None,
                pos: bool = False, outer: bool = False) -> "DataFrame":
        """Explode an array column: one output row per element, other
        columns repeated (GpuGenerateExec analogue).  ``pos=True`` adds the
        element position column; ``outer=True`` keeps empty/NULL arrays as
        a NULL-element row (CPU path)."""
        node = L.Generate(column, alias or "col", pos, outer, self.plan)
        return DataFrame(node, self.session)

    def window_in_pandas(self, partition_by, specs) -> "DataFrame":
        """Whole-partition pandas window columns: specs is
        {out_name: (fn, dtype, col)} with fn(pd.Series) -> scalar,
        broadcast over each partition (GpuWindowInPandasExec analogue).

        ``partition_by`` must name existing columns (project expression
        keys first); ``out_name``s must not collide with input columns.
        """
        names = []
        for c in partition_by:
            if not isinstance(c, str) or c not in self.schema:
                raise TypeError(
                    f"window_in_pandas partition key must be an existing "
                    f"column name, got {c!r}; project expressions first")
            names.append(c)
        for n in specs:
            if n in self.schema:
                raise ValueError(
                    f"window_in_pandas output {n!r} collides with an "
                    "input column")
        keys = [self._resolve(ColumnRef(n)) for n in names]
        win_specs = [(n, fn, dt, col)
                     for n, (fn, dt, col) in specs.items()]
        return DataFrame(
            L.WindowInPandas(keys, names, win_specs, self.plan),
            self.session)

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(Iterator[pd.DataFrame]) -> Iterator[pd.DataFrame] per
        partition (GpuMapInPandasExec analogue)."""
        return DataFrame(
            L.MapInPandas(fn, _to_schema(schema), self.plan), self.session)

    mapInPandas = map_in_pandas

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"leftouter": "left", "left_outer": "left",
               "rightouter": "right", "right_outer": "right",
               "outer": "full", "fullouter": "full", "full_outer": "full",
               "leftsemi": "left_semi", "semi": "left_semi",
               "leftanti": "left_anti", "anti": "left_anti"}.get(how, how)
        lkeys: List[Expression] = []
        rkeys: List[Expression] = []
        condition = None
        if on is None:
            how = "cross" if how == "inner" else how
        elif isinstance(on, str):
            on = [on]
        if isinstance(on, (list, tuple)):
            return self._join_using(other, list(on), how)
        if isinstance(on, Column):
            # equi-join extraction from a boolean expression
            lkeys, rkeys, condition = _extract_join_keys(
                on.expr, self.schema, other.schema)
        right, mapping = _dedupe_right(
            self, other, how in ("left_semi", "left_anti"))
        if mapping:
            def remap(e: Expression) -> Expression:
                if isinstance(e, ColumnRef) and e.column in mapping:
                    return ColumnRef(mapping[e.column], e.dtype, e.nullable)
                return e
            rkeys = [k.transform_up(remap) for k in rkeys]
            if condition is not None:
                # condition may reference either side; remap only names that
                # exist solely on the right
                lnames = set(self.schema.names)
                def remap_cond(e: Expression) -> Expression:
                    if isinstance(e, ColumnRef) and e.column in mapping and \
                            e.column not in lnames:
                        return ColumnRef(mapping[e.column], e.dtype,
                                         e.nullable)
                    return e
                condition = condition.transform_up(remap_cond)
        node = L.Join(self.plan, right.plan, lkeys, rkeys, how, condition)
        return DataFrame(node, self.session)

    def _join_using(self, other: "DataFrame", names: List[str], how: str
                    ) -> "DataFrame":
        """USING-join semantics: one output column per key name (left value;
        right value for right-outer; coalesce for full-outer), then the
        remaining left columns, then the remaining right columns."""
        lkeys = [self._resolve(ColumnRef(n)) for n in names]
        # rename the right key columns so the raw join output has no dups
        ren = {n: f"__rkey_{i}" for i, n in enumerate(names)}
        rexprs, rnames = [], []
        for f in other.schema.fields:
            rexprs.append(ColumnRef(f.name, f.dtype, f.nullable))
            rnames.append(ren.get(f.name, f.name))
        right = DataFrame(L.Project(rexprs, rnames, other.plan),
                          other.session)
        right, _mapping = _dedupe_right(
            self, right, how in ("left_semi", "left_anti"))
        rkeys = [right._resolve(ColumnRef(ren[n])) for n in names]
        node = L.Join(self.plan, right.plan, lkeys, rkeys, how, None)
        joined = DataFrame(node, self.session)
        if how in ("left_semi", "left_anti"):
            return joined
        # final projection: dedupe key columns
        sch = joined.schema
        exprs, out_names = [], []
        for n in names:
            lref = ColumnRef(n)
            rref = ColumnRef(ren[n])
            if how == "right":
                e = resolve(rref, sch)
            elif how == "full":
                from spark_rapids_tpu.exprs.nullexprs import Coalesce
                e = Coalesce(resolve(lref, sch), resolve(rref, sch))
            else:
                e = resolve(lref, sch)
            exprs.append(e)
            out_names.append(n)
        for f in sch.fields:
            if f.name in names or f.name in ren.values():
                continue
            exprs.append(ColumnRef(f.name, f.dtype, f.nullable))
            out_names.append(f.name)
        return DataFrame(L.Project(exprs, out_names, joined.plan),
                         self.session)

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        node = L.Join(self.plan, other.plan, [], [], "cross", None)
        return DataFrame(node, self.session)

    crossJoin = cross_join

    def union(self, other: "DataFrame") -> "DataFrame":
        left, right = self._union_coerce(other)
        return DataFrame(L.Union([left, right]), self.session)

    def _union_coerce(self, other: "DataFrame"):
        """Widen numeric columns to the common type before UNION (Spark's
        WidenSetOperationTypes): `SELECT 0 AS id` against a LONG column
        must not fail the union schema check.  Output names come from the
        left side, per Spark."""
        lf = list(self.plan.schema.fields)
        rf = list(other.plan.schema.fields)
        if len(lf) != len(rf) or \
                all(a.dtype == b.dtype for a, b in zip(lf, rf)):
            return self.plan, other.plan
        try:
            common = [T.promote(a.dtype, b.dtype)
                      for a, b in zip(lf, rf)]
        except TypeError:
            return self.plan, other.plan  # let L.Union raise its check

        def recast(plan, fields):
            from spark_rapids_tpu.exprs.cast import Cast
            exprs = []
            for f, lt, dt in zip(fields, lf, common):
                ref = ColumnRef(f.name, f.dtype, f.nullable)
                exprs.append(ref if f.dtype == dt else Cast(ref, dt))
            return L.Project(exprs, [f.name for f in lf], plan)

        return recast(self.plan, lf), recast(other.plan, rf)

    unionAll = union

    def _set_op(self, other: "DataFrame", keep) -> "DataFrame":
        """INTERSECT/EXCEPT (distinct set semantics) via union + group-by:
        grouping keys already treat NULLs (and NaNs) as equal, which is
        exactly the SQL set-operation equality — and the plan rides the
        hash-aggregate path instead of a null-safe join (Spark plans
        these as left-semi/anti joins; the aggregate form is the
        TPU-friendly equivalent)."""
        from spark_rapids_tpu import functions as F
        if len(self.columns) != len(other.columns):
            raise ValueError(
                f"set operation needs equal column counts: "
                f"{len(self.columns)} vs {len(other.columns)}")
        side = "__setop_side"
        right = other.select(*[
            other[c2].alias(c1)
            for c1, c2 in zip(self.columns, other.columns)])
        # No per-side distinct: the min/max group-by is insensitive to
        # row multiplicity, so one aggregation collapses everything.
        u = (self.with_column(side, F.lit(0))
             .union(right.with_column(side, F.lit(1))))
        g = (u.group_by(*self.columns)
             .agg(F.min(side).alias("__mn"), F.max(side).alias("__mx")))
        mn, mx = g["__mn"], g["__mx"]
        cond = (mn == 0) & (mx == 1) if keep == "both" else \
            (mn == 0) & (mx == 0)
        return g.filter(cond).select(*self.columns)

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows present in BOTH frames (SQL INTERSECT)."""
        return self._set_op(other, "both")

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows of this frame absent from ``other`` (SQL
        EXCEPT; pyspark subtract/exceptAll's distinct sibling)."""
        return self._set_op(other, "left")

    exceptDistinct = subtract

    def describe(self, *cols) -> "DataFrame":
        """count/mean/stddev/min/max summary of numeric columns (pyspark
        DataFrame.describe): a small string-typed frame with a 'summary'
        column.  Computed eagerly (one aggregation pass)."""
        from spark_rapids_tpu import functions as F
        targets = list(cols) or [f.name for f in self.schema.fields
                                 if f.dtype.is_numeric or
                                 f.dtype.is_string]
        data = {"summary": (T.STRING,
                            ["count", "mean", "stddev", "min", "max"])}
        if targets:
            aggs = []
            for c in targets:
                numeric = self.schema.field(c).dtype.is_numeric
                aggs.append(F.count(c).alias(f"c_{c}"))
                if numeric:
                    aggs += [F.avg(c).alias(f"m_{c}"),
                             F.stddev(c).alias(f"s_{c}")]
                aggs += [F.min(c).alias(f"mn_{c}"),
                         F.max(c).alias(f"mx_{c}")]
            row = list(self.agg(*aggs).collect()[0])

            def s(v):
                return None if v is None else str(v)

            i = 0
            for c in targets:
                numeric = self.schema.field(c).dtype.is_numeric
                cnt = row[i]; i += 1
                mean = std = None
                if numeric:
                    mean, std = row[i], row[i + 1]; i += 2
                mn, mx = row[i], row[i + 1]; i += 2
                data[c] = (T.STRING,
                           [str(cnt), s(mean), s(std), s(mn), s(mx)])
        return self.session.create_dataframe(data, num_partitions=1)

    def fillna(self, value, subset: Optional[List[str]] = None
               ) -> "DataFrame":
        """Replace nulls — and NaNs in float columns — with ``value``
        (pyspark DataFrame.na.fill): scalar applied to type-compatible
        columns, or a {col: value} dict.  Fill values cast to the column
        type (2.5 fills an INT column as 2, like pyspark); incompatible
        columns are left untouched."""
        from spark_rapids_tpu import functions as F

        def check(v):
            if isinstance(v, bool) or                     isinstance(v, (int, float, str)):
                return v
            raise TypeError(
                "value should be a float, int, string, bool or dict, "
                f"got {type(v).__name__}")

        if isinstance(value, dict):
            mapping = {c: check(v) for c, v in value.items()}
            for c in mapping:
                self.schema.field(c)  # raises on unknown columns
        else:
            check(value)
            cols = subset or [f.name for f in self.schema.fields]
            for c in cols:
                self.schema.field(c)
            mapping = {c: value for c in cols}
        sel = []
        for f in self.schema.fields:
            v = mapping.get(f.name)
            if v is not None and _fill_compatible(f.dtype, v):
                if f.dtype.is_integral and isinstance(v, float) \
                        and not isinstance(v, bool):
                    v = int(v)  # pyspark casts the value to the column
                filled = F.coalesce(self[f.name], F.lit(v))
                if f.dtype in (T.FLOAT, T.DOUBLE):
                    # pyspark na.fill replaces NaN too
                    from spark_rapids_tpu.exprs.nullexprs import NaNvl
                    filled = F.coalesce(
                        Column(NaNvl(self[f.name].expr,
                                     Literal(float(v), T.DOUBLE))),
                        F.lit(v))
                sel.append(filled.cast(f.dtype).alias(f.name))
            else:
                sel.append(self[f.name].alias(f.name))
        return self.select(*sel)

    def dropna(self, how: str = "any", thresh: Optional[int] = None,
               subset: Optional[List[str]] = None) -> "DataFrame":
        """Drop rows with null/NaN values (pyspark DataFrame.na.drop;
        Spark plans it as a Filter over AtLeastNNonNulls)."""
        from spark_rapids_tpu.exprs.nullexprs import AtLeastNNonNulls
        cols = subset or [f.name for f in self.schema.fields]
        if thresh is None:
            if how not in ("any", "all"):
                raise ValueError(
                    f"how ({how!r}) should be 'any' or 'all'")
            thresh = len(cols) if how == "any" else 1
        e = AtLeastNNonNulls(thresh, *[
            resolve(ColumnRef(c), self.schema) for c in cols])
        return self.filter(Column(e))

    def distinct(self) -> "DataFrame":
        return DataFrame(L.Distinct(self.plan), self.session)

    def drop_duplicates(self, subset: Optional[List[str]] = None):
        if subset is None:
            return self.distinct()
        keys = [self._resolve(ColumnRef(n)) for n in subset]
        aggs = [AggregateExpression(First(
            self._resolve(ColumnRef(f.name))), f.name)
            for f in self.schema.fields if f.name not in subset]
        node = L.Aggregate(keys, list(subset), aggs, self.plan)
        return DataFrame(node, self.session)

    dropDuplicates = drop_duplicates

    def order_by(self, *cols) -> "DataFrame":
        orders = [self._resolve_order(_to_order(c)) for c in cols]
        return DataFrame(L.Sort(orders, True, self.plan), self.session)

    orderBy = order_by
    sort = order_by

    def sort_within_partitions(self, *cols) -> "DataFrame":
        orders = [self._resolve_order(_to_order(c)) for c in cols]
        return DataFrame(L.Sort(orders, False, self.plan), self.session)

    def _resolve_order(self, o: SortOrder) -> SortOrder:
        """Resolve a sort expression against this DataFrame's schema.  A
        bare column name the select list renamed away falls back to its
        alias's output column (SQL allows ORDER BY on the pre-alias
        input name — Spark resolves sort ordering against both the
        projection's output and its input; sorting by the alias output
        is equivalent because the alias is a pure rename)."""
        try:
            return SortOrder(self._resolve(o.child), o.ascending,
                             o.nulls_first)
        except KeyError:
            alias = self._order_alias_for(o.child)
            if alias is None:
                raise
            return SortOrder(self._resolve(ColumnRef(alias)),
                             o.ascending, o.nulls_first)

    def _order_alias_for(self, e: Expression) -> Optional[str]:
        """Output name of a select-list entry that is a pure rename of
        the input column ``e`` references, when this plan is a
        projection (possibly under distinct/limit); None otherwise."""
        if not isinstance(e, ColumnRef):
            return None
        node = self.plan
        while isinstance(node, (L.Distinct, L.Limit)):
            node = node.children[0]
        if not isinstance(node, L.Project):
            return None
        for name, pe in zip(node.names, node.exprs):
            inner = pe
            while isinstance(inner, Alias):
                inner = inner.children[0]
            if isinstance(inner, ColumnRef) and inner.column == e.column:
                return name
        return None

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self.plan), self.session)

    def repartition(self, n: int, *cols) -> "DataFrame":
        if cols:
            keys = [self._resolve(_to_expr(self[c] if isinstance(c, str)
                                           else c)) for c in cols]
            node = L.Repartition("hash", n, keys, self.plan)
        else:
            node = L.Repartition("roundrobin", n, [], self.plan)
        return DataFrame(node, self.session)

    def coalesce(self, n: int) -> "DataFrame":
        return DataFrame(L.Repartition("roundrobin", n, [], self.plan),
                         self.session)

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        return DataFrame(L.Sample(fraction, seed, self.plan), self.session)

    # -- stat functions (pyspark DataFrameStatFunctions surface) ------------

    def crosstab(self, col1: str, col2: str) -> "DataFrame":
        """Pairwise frequency table (pyspark crosstab): one row per col1
        value, one column per col2 value, cells = pair counts (0 when
        absent).  NULL keys render as the string "null" on both axes and
        MERGE with a literal "null" value (one column/row, summed counts
        — pyspark emits a duplicate column name there)."""
        from spark_rapids_tpu import functions as F
        tmp = "__ct_p"
        normalized = self.with_column(
            tmp, F.coalesce(self[col2].cast(T.STRING), F.lit("null")))
        out = (normalized.group_by(col1)
               .pivot(tmp)
               .agg(F.count("*").alias("n")))
        first = out.columns[0]
        sel = [F.coalesce(out[first].cast(T.STRING), F.lit("null"))
               .alias(f"{col1}_{col2}")]
        for c in out.columns[1:]:
            sel.append(F.coalesce(out[c], F.lit(0)).alias(c))
        return out.select(*sel)

    def approx_quantile(self, col_name: str, probabilities, rel_err=0.0
                        ) -> List[float]:
        """Quantiles of a numeric column (pyspark approxQuantile).  The
        engine computes EXACT percentiles (rel_err accepted for API
        compatibility, ignored — exact satisfies any error bound)."""
        from spark_rapids_tpu import functions as F
        if not probabilities:
            return []
        aggs = [F.percentile(col_name, float(p)).alias(f"q{i}")
                for i, p in enumerate(probabilities)]
        row = self.agg(*aggs).collect()[0]
        if all(v is None for v in row):
            return []  # no non-null values (pyspark returns [])
        return list(row)

    approxQuantile = approx_quantile

    def freq_items(self, cols: List[str], support: float = 0.01
                   ) -> "DataFrame":
        """Values occurring in more than ``support`` of rows, one
        array-typed column per input (pyspark freqItems; this engine
        computes exact heavy hitters, a superset guarantee of pyspark's
        sketch)."""
        from spark_rapids_tpu import functions as F
        out_data = {}
        thresh = support * self.count()
        for c in cols:
            # threshold applied engine-side: the driver only receives
            # frequent values, never the full distinct set
            g = (self.group_by(c).agg(F.count("*").alias("__n")))
            vals = [k for k, _ in
                    g.filter(g["__n"] > float(thresh)).collect()]
            f = self.schema.field(c)
            out_data[f"{c}_freqItems"] = (T.ArrayType(f.dtype), [vals])
        return self.session.create_dataframe(out_data, num_partitions=1)

    freqItems = freq_items

    def sample_by(self, col_name: str, fractions: Dict, seed: int = 42
                  ) -> "DataFrame":
        """Stratified sample without replacement (pyspark sampleBy):
        each row kept with its key's fraction; keys absent from
        ``fractions`` are dropped."""
        from spark_rapids_tpu import functions as F
        for k, f in fractions.items():
            if not (0.0 <= float(f) <= 1.0):
                raise ValueError(f"fraction for {k!r} must be in [0, 1]")
        if not fractions:  # pyspark: empty strata -> empty sample
            return self.filter(F.lit(False))
        key = self[col_name]
        frac = None
        for k, f in fractions.items():
            branch = (key.is_null() if k is None else (key == k))
            frac = F.when(branch, float(f)) if frac is None \
                else frac.when(branch, float(f))
        frac_col = frac.otherwise(0.0)
        return self.filter(F.rand(seed) < frac_col)

    sampleBy = sample_by

    # -- actions ------------------------------------------------------------

    def collect(self) -> List[tuple]:
        hb = self.session.execute(self.plan)
        cols = [c.to_list() for c in hb.columns]
        return [tuple(c[i] for c in cols) for i in range(hb.num_rows)]

    def to_pydict(self) -> Dict[str, List[Any]]:
        return self.session.execute(self.plan).to_pydict()

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.to_pydict())

    def count(self) -> int:
        node = L.Aggregate([], [], [AggregateExpression(count_star(),
                                                        "count")], self.plan)
        hb = self.session.execute(node)
        return int(hb.columns[0].values[0])

    def show(self, n: int = 20):
        rows = self.limit(n).collect()
        names = self.columns
        widths = [max(len(str(x)) for x in [nm] + [r[i] for r in rows])
                  for i, nm in enumerate(names)]
        line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(line)
        print("|" + "|".join(f" {nm:<{w}} "
                             for nm, w in zip(names, widths)) + "|")
        print(line)
        for r in rows:
            print("|" + "|".join(f" {str(x):<{w}} "
                                 for x, w in zip(r, widths)) + "|")
        print(line)

    def cache(self) -> "DataFrame":
        """Mark for caching: first execution materializes device batches
        into the spillable-buffer catalog (df.cache() analogue; spills
        device->host->disk under pressure instead of recompute)."""
        if isinstance(self.plan, L.CachedRelation):
            return self
        return DataFrame(L.CachedRelation(self.plan, L.CacheHolder()),
                         self.session)

    persist = cache

    def unpersist(self) -> "DataFrame":
        if isinstance(self.plan, L.CachedRelation):
            self.plan.holder.unpersist()
        return self

    def create_or_replace_temp_view(self, name: str):
        self.session.register_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def explain(self) -> str:
        s = self.session.explain_plan(self.plan)
        print(s)
        return s

    def write_parquet(self, path: str, mode: str = "error",
                      partition_by=None):
        from spark_rapids_tpu.io.writer import write_dataframe
        return write_dataframe(self, "parquet", path, mode,
                               partition_by=partition_by)

    def write_csv(self, path: str, mode: str = "error"):
        from spark_rapids_tpu.io.writer import write_dataframe
        return write_dataframe(self, "csv", path, mode)

    def write_orc(self, path: str, mode: str = "error"):
        from spark_rapids_tpu.io.writer import write_dataframe
        return write_dataframe(self, "orc", path, mode)

    # -- conveniences -------------------------------------------------------

    def head(self, n: int = 1):
        rows = self.limit(n).collect()
        return rows[0] if n == 1 and rows else rows

    def first(self):
        return self.head(1)

    def take(self, n: int):
        return self.limit(n).collect()

    def is_empty(self) -> bool:
        return not self.limit(1).collect()

    @property
    def dtypes(self):
        return [(f.name, f.dtype.name) for f in self.schema.fields]

    def print_schema(self):
        print("root")
        for f in self.schema.fields:
            null = "true" if f.nullable else "false"
            print(f" |-- {f.name}: {f.dtype} (nullable = {null})")

    printSchema = print_schema


def _dedupe_right(left: "DataFrame", right: "DataFrame", is_semi: bool):
    """Rename right-side columns that collide with left-side names
    (suffix ``_r``) so the joined schema is unambiguous.  Semi/anti joins
    output only the left side, so no rename is needed.

    Returns (right_df, {old_name: new_name})."""
    if is_semi:
        return right, {}
    lnames = set(left.schema.names)
    if not (lnames & set(right.schema.names)):
        return right, {}
    exprs, names, mapping = [], [], {}
    for f in right.schema.fields:
        exprs.append(ColumnRef(f.name, f.dtype, f.nullable))
        nm = f.name
        while nm in lnames:
            nm = nm + "_r"
        if nm != f.name:
            mapping[f.name] = nm
        names.append(nm)
    return DataFrame(L.Project(exprs, names, right.plan),
                     right.session), mapping


def _extract_join_keys(expr: Expression, lschema: T.Schema,
                       rschema: T.Schema):
    """Split a join condition into equi-key pairs + residual condition."""
    from spark_rapids_tpu.exprs.predicates import And, Equals as EqualTo
    lkeys, rkeys, residual = [], [], []

    def visit(e: Expression):
        if isinstance(e, And):
            visit(e.children[0])
            visit(e.children[1])
            return
        if isinstance(e, EqualTo):
            a, b = e.children
            if isinstance(a, ColumnRef) and isinstance(b, ColumnRef):
                if a.column in lschema and b.column in rschema:
                    lkeys.append(resolve(a, lschema))
                    rkeys.append(resolve(b, rschema))
                    return
                if b.column in lschema and a.column in rschema:
                    lkeys.append(resolve(b, lschema))
                    rkeys.append(resolve(a, rschema))
                    return
        residual.append(e)

    visit(expr)
    cond = None
    if residual:
        from spark_rapids_tpu.exprs.predicates import And as AndE
        cond = residual[0]
        for r in residual[1:]:
            cond = AndE(cond, r)
    return lkeys, rkeys, cond


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Expression],
                 names: List[str]):
        self.df = df
        self.keys = keys
        self.names = names

    @staticmethod
    def _unwrap_agg(a) -> Tuple[AggregateFunction, Optional[str]]:
        """(aggregate fn, alias-or-None) from an agg() argument."""
        if isinstance(a, AggregateExpression):
            return a.fn, a.output_name
        if isinstance(a, Column):
            e, name = a.expr, None
            if isinstance(e, Alias):
                name, e = e.alias_name, e.children[0]
            if isinstance(e, AggregateFunction):
                return e, name
        raise TypeError(f"not an aggregate: {a!r}")

    def agg(self, *aggs) -> DataFrame:
        out: List[AggregateExpression] = []
        for i, a in enumerate(aggs):
            if isinstance(a, AggregateExpression):
                out.append(a)
                continue
            e, name = self._unwrap_agg(a)
            e = _resolve_agg(e, self.df.schema)
            out.append(AggregateExpression(
                e, name or f"{e.name.lower()}_{i}"))
        from spark_rapids_tpu.exprs import aggregates as A
        if any(isinstance(a.fn, A._BinaryStatMarker) for a in out):
            return self._agg_with_binary_stats(out)
        if any(isinstance(a.fn, A.Percentile) for a in out):
            return self._agg_with_percentile(out)
        if any(isinstance(a.fn, A.CountDistinct) for a in out):
            return self._agg_with_distinct(out)
        node = L.Aggregate(self.keys, self.names, out, self.df.plan)
        return DataFrame(node, self.df.session)

    def _agg_with_binary_stats(self, out: List[AggregateExpression]
                               ) -> DataFrame:
        """corr / covar_pop / covar_samp rewrite: no aggregation path
        takes two inputs, so each marker becomes window means over the
        pair-complete rows + a SUM of centered products, with the ratio
        computed in a post-projection (mean-shifted => no large-mean
        cancellation):

            gx  = x when both non-null; gy likewise
            mx  = avg(gx) OVER (keys); my = avg(gy) OVER (keys)
            sp  = SUM((gx-mx)*(gy-my)); n = COUNT(gx)
            covar_pop  = sp/n;  covar_samp = sp/(n-1) (NaN at n=1)
            corr       = sp / sqrt(SUM((gx-mx)^2) * SUM((gy-my)^2))
                         (NaN when a variance is 0); NULL for n=0.
        """
        from spark_rapids_tpu import functions as F
        from spark_rapids_tpu.exprs import aggregates as A

        df = self.df
        key_cols = [Column(k) for k in self.keys]
        wp = F.Window.partition_by(*key_cols)
        final: List = []
        post = {}  # output name -> builder(frame) -> Column
        for i, a in enumerate(out):
            fn = a.fn
            if not isinstance(fn, A._BinaryStatMarker):
                final.append(a)
                continue
            x, y = Column(fn.left), Column(fn.right)
            both = x.is_not_null() & y.is_not_null()
            gxn, gyn = f"__bs_x{i}", f"__bs_y{i}"
            mxn, myn = f"__bs_mx{i}", f"__bs_my{i}"
            df = (df.with_column(gxn, F.when(both, x.cast(T.DOUBLE))
                                 .otherwise(None))
                  .with_column(gyn, F.when(both, y.cast(T.DOUBLE))
                               .otherwise(None)))
            df = (df.with_column(mxn, F.avg(df[gxn]).over(wp))
                  .with_column(myn, F.avg(df[gyn]).over(wp)))
            dx = df[gxn] - df[mxn]
            dy = df[gyn] - df[myn]
            spn, nn = f"__bs_sp{i}", f"__bs_n{i}"
            final.append(AggregateExpression(
                _resolve_agg(A.Sum((dx * dy).expr), df.schema), spn))
            final.append(AggregateExpression(
                _resolve_agg(A.Count(ColumnRef(gxn)), df.schema), nn))
            if isinstance(fn, A.Corr):
                sxn, syn = f"__bs_sx{i}", f"__bs_sy{i}"
                final.append(AggregateExpression(
                    _resolve_agg(A.Sum((dx * dx).expr), df.schema), sxn))
                final.append(AggregateExpression(
                    _resolve_agg(A.Sum((dy * dy).expr), df.schema), syn))

                def mk_corr(g, spn=spn, nn=nn, sxn=sxn, syn=syn):
                    denom = g[sxn] * g[syn]
                    nan = F.lit(float("nan"))
                    return F.when(
                        (g[nn] >= 1) & (denom > 0),
                        g[spn] / F.sqrt(denom)).when(
                        g[nn] >= 1, nan).otherwise(None)
                post[a.output_name] = mk_corr
            elif isinstance(fn, A.CovarSamp):
                def mk_cs(g, spn=spn, nn=nn):
                    nan = F.lit(float("nan"))
                    samp = g[spn] / (g[nn] - 1).cast(T.DOUBLE)
                    return (F.when(g[nn] > 1, samp)
                            .when(g[nn] == 1, nan).otherwise(None))
                post[a.output_name] = mk_cs
            else:
                def mk_cp(g, spn=spn, nn=nn):
                    return (F.when(g[nn] >= 1,
                                   g[spn] / g[nn].cast(T.DOUBLE))
                            .otherwise(None))
                post[a.output_name] = mk_cp
        gd = GroupedData(df, self.keys, self.names)
        grouped = gd.agg(*final)
        sel = []
        for name in self.names:
            sel.append(grouped[name].alias(name))
        for a in out:
            if a.output_name in post:
                sel.append(post[a.output_name](grouped)
                           .alias(a.output_name))
            else:
                sel.append(grouped[a.output_name].alias(a.output_name))
        return grouped.select(*sel)

    def _agg_with_percentile(self, out: List[AggregateExpression]
                             ) -> DataFrame:
        """Exact-percentile rewrite: no fixed-size aggregation buffer can
        hold a percentile's state, so each percentile becomes a
        rank-and-interpolate pipeline (the positional equivalent of
        Spark's sort-based Percentile ImperativeAggregate):

            rn  = row_number() OVER (keys ORDER BY x nulls-last) - 1
            n   = count(x) OVER (keys)            -- non-null count
            pos = p * (n - 1); lo = floor(pos); frac = pos - lo
            w   = (rn == lo) * (1 - frac) + (rn == lo + 1) * frac
            percentile = SUM(x * w) GROUP BY keys

        Rows with NULL x sort after every valid row (rank >= n) and carry
        a NULL weight, so they vanish in the SUM; an all-NULL group sums
        to NULL, matching Spark.  Regular aggregates in the same list ride
        the final aggregation unchanged."""
        from spark_rapids_tpu import functions as F
        from spark_rapids_tpu.exprs import aggregates as A
        from spark_rapids_tpu.exprs.nullexprs import IsNull

        if any(isinstance(a.fn, A.CountDistinct) for a in out):
            raise NotImplementedError(
                "percentile and count_distinct in one aggregation; "
                "split into separate aggregations")
        df = self.df
        key_cols = [Column(k) for k in self.keys]
        contrib_names: dict = {}
        rank_cols: dict = {}  # repr(child) -> (rn, n): percentiles of the
        #                       same child share one sorted window pass
        for i, a in enumerate(out):
            if not isinstance(a.fn, A.Percentile):
                continue
            x = Column(a.fn.child)
            p = a.fn.percentage
            ckey = repr(a.fn.child)
            wp = F.Window.partition_by(*key_cols)
            if ckey not in rank_cols:
                nf, rn, n = (f"__pct_nf{i}", f"__pct_rn{i}", f"__pct_n{i}")
                df = (df.with_column(nf, Column(IsNull(a.fn.child)))
                      .with_column(rn, F.row_number().over(
                          wp.order_by(F.col(nf), x)))
                      .with_column(n, F.count(x).over(wp)))
                rank_cols[ckey] = (rn, n)
            rn, n = rank_cols[ckey]
            cb = f"__pct_c{i}"
            pos = (df[n] - 1).cast(T.DOUBLE) * F.lit(p)
            lo = F.floor(pos)
            frac = pos - lo
            rn0 = (df[rn] - 1).cast(T.DOUBLE)
            # Gate the VALUE, not a product: rows off the interpolation
            # ranks contribute NULL (which SUM skips), so an inf/NaN
            # elsewhere in the group cannot poison the sum via 0 * inf.
            # The lo+1 branch only exists when frac > 0 — Spark's exact
            # percentile never reads past rank lo on integer positions.
            df = df.with_column(
                cb,
                F.when(rn0 == lo, x.cast(T.DOUBLE) * (F.lit(1.0) - frac))
                .when((rn0 == lo + F.lit(1.0)) & (frac > F.lit(0.0)),
                      x.cast(T.DOUBLE) * frac)
                .otherwise(None))
            contrib_names[i] = cb
        gd = GroupedData(df, self.keys, self.names)
        final: List[AggregateExpression] = []
        for i, a in enumerate(out):
            if i in contrib_names:
                final.append(AggregateExpression(
                    _resolve_agg(A.Sum(ColumnRef(contrib_names[i])),
                                 df.schema), a.output_name))
            else:
                final.append(a)
        return gd.agg(*final)

    def _agg_with_distinct(self, out: List[AggregateExpression]
                           ) -> DataFrame:
        """The distinct-aggregate rewrite (Spark RewriteDistinctAggregates):

            Agg(k, [count(DISTINCT v), regular...])
              -> Agg(k, [count(v'), re-agg(regular)],
                     Agg(k + [v], [regular per (k, v)]))

        The inner aggregate dedups (k, v) pairs while computing the regular
        aggregates once per pair; the outer counts the now-unique non-null
        values and re-aggregates the regulars (sum of sums, sum of counts,
        min of mins, avg from sum/count).  Both levels ride the existing
        partial/merge exchange machinery unchanged."""
        from spark_rapids_tpu.exprs import aggregates as A
        from spark_rapids_tpu.exprs.arithmetic import Divide
        from spark_rapids_tpu.exprs.cast import Cast

        dvals = [a.fn.child for a in out if isinstance(a.fn, A.CountDistinct)]
        if any(repr(e) != repr(dvals[0]) for e in dvals[1:]):
            raise NotImplementedError(
                "count_distinct over different expressions in one "
                "aggregation needs the Expand-based rewrite (not yet "
                "implemented); split into separate aggregations")
        dname = "__cd_val"

        inner_aggs: List[AggregateExpression] = []
        plans = []  # one entry per output: how the outer level produces it
        for i, a in enumerate(out):
            fn = a.fn
            if isinstance(fn, A.CountDistinct):
                plans.append(("count_distinct",))
            elif isinstance(fn, A.Average):
                ns, nc = f"__cd_s{i}", f"__cd_c{i}"
                inner_aggs.append(A.AggregateExpression(A.Sum(fn.child), ns))
                inner_aggs.append(A.AggregateExpression(A.Count(fn.child),
                                                        nc))
                plans.append(("avg", ns, nc))
            elif isinstance(fn, (A.Sum, A.Count, A.Min, A.Max, A.First,
                                 A.Last)):
                nm = f"__cd_a{i}"
                inner_aggs.append(A.AggregateExpression(fn, nm))
                plans.append(("reagg", nm, fn))
            else:
                raise NotImplementedError(
                    f"{type(fn).__name__} cannot be combined with "
                    f"count_distinct (no re-aggregation rule)")

        inner = GroupedData(self.df, self.keys + [dvals[0]],
                            self.names + [dname]).agg(*inner_aggs)

        outer_gd = inner.group_by(*self.names)
        o_aggs: List[AggregateExpression] = []
        avg_slots = {}  # output index -> (sum_name, count_name)
        for i, (a, plan) in enumerate(zip(out, plans)):
            if plan[0] == "count_distinct":
                o_aggs.append(A.AggregateExpression(
                    A.Count(inner._resolve(ColumnRef(dname))),
                    a.output_name))
            elif plan[0] == "avg":
                _, ns, nc = plan
                os_, oc = f"__cd_os{i}", f"__cd_oc{i}"
                o_aggs.append(A.AggregateExpression(
                    A.Sum(inner._resolve(ColumnRef(ns))), os_))
                o_aggs.append(A.AggregateExpression(
                    A.Sum(inner._resolve(ColumnRef(nc))), oc))
                avg_slots[i] = (os_, oc)
            else:
                _, nm, fn = plan
                ref = inner._resolve(ColumnRef(nm))
                if isinstance(fn, A.Count):
                    o_fn = A.Sum(ref)  # sum of per-(k,v) counts
                elif isinstance(fn, (A.First, A.Last)):
                    o_fn = type(fn)(ref, fn.ignore_nulls)
                else:
                    o_fn = type(fn)(ref)
                o_aggs.append(A.AggregateExpression(o_fn, a.output_name))
        outer = outer_gd.agg(*o_aggs)

        if not avg_slots:
            return outer
        # Rebuild avg outputs as sum/count and restore column order/names.
        sel: List[Column] = [Column(ColumnRef(n)) for n in self.names]
        for i, a in enumerate(out):
            if i in avg_slots:
                os_, oc = avg_slots[i]
                e = Divide(Cast(ColumnRef(os_), T.DOUBLE),
                           Cast(ColumnRef(oc), T.DOUBLE))
                sel.append(Column(Alias(e, a.output_name)))
            else:
                sel.append(Column(ColumnRef(a.output_name)))
        return outer.select(*sel)

    def count(self) -> DataFrame:
        return self.agg(Column(Alias(count_star(), "count")))

    def pivot(self, col_name: str, values: Optional[List] = None
              ) -> "PivotedData":
        """Pivot a column's values into output columns (pyspark
        GroupedData.pivot).  Rewritten to per-value conditional
        aggregates — agg_fn(CASE WHEN pivot = v THEN child END) — so the
        plan is one ordinary hash aggregation.  Without an explicit
        ``values`` list the distinct values are computed eagerly (as
        pyspark does), capped at 10000."""
        if values is None:
            vals_df = (self.df.select(self.df[col_name].alias("__pv"))
                       .distinct().limit(10_001))
            raw = [r[0] for r in vals_df.collect()]
            if len(raw) > 10_000:
                raise ValueError(
                    "pivot column has more than 10000 distinct values; "
                    "pass an explicit values list")
            # ascending native sort, NULL first (Spark sort order)
            nonnull = sorted(v for v in raw if v is not None)
            values = ([None] if any(v is None for v in raw) else []) \
                + nonnull
        return PivotedData(self.df, self.keys, self.names, col_name,
                           list(values))

    def _simple(self, cls, cols) -> DataFrame:
        targets = cols or [f.name for f in self.df.schema.fields
                           if f.dtype.is_numeric]
        aggs = [Column(Alias(cls(self.df._resolve(ColumnRef(c))),
                             f"{cls.__name__.lower()}({c})"))
                for c in targets]
        return self.agg(*aggs)

    def sum(self, *cols):
        return self._simple(Sum, cols)

    def avg(self, *cols):
        return self._simple(Average, cols)

    mean = avg

    def min(self, *cols):
        return self._simple(Min, cols)

    def max(self, *cols):
        return self._simple(Max, cols)

    # -- pandas execs (GpuFlatMapGroupsInPandasExec family) -----------------

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(pd.DataFrame) -> pd.DataFrame per group
        (GpuFlatMapGroupsInPandasExec analogue)."""
        schema = _to_schema(schema)
        node = L.FlatMapGroupsInPandas(self.keys, self.names, fn, schema,
                                       self.df.plan)
        return DataFrame(node, self.df.session)

    applyInPandas = apply_in_pandas

    def agg_in_pandas(self, specs) -> DataFrame:
        """specs: {out_name: (fn, dtype, col)} with fn(pd.Series) -> scalar
        (GpuAggregateInPandasExec / GROUPED_AGG pandas_udf analogue)."""
        agg_specs = [(name, fn, dt, col)
                     for name, (fn, dt, col) in specs.items()]
        node = L.AggregateInPandas(self.keys, self.names, agg_specs,
                                   self.df.plan)
        return DataFrame(node, self.df.session)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)


class CoGroupedData:
    """a.group_by(k).cogroup(b.group_by(k)).apply_in_pandas(fn, schema)
    (GpuFlatMapCoGroupsInPandasExec analogue)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        schema = _to_schema(schema)
        node = L.FlatMapCoGroupsInPandas(
            self.left.keys, self.left.names, self.right.keys,
            self.right.names, fn, schema, self.left.df.plan,
            self.right.df.plan)
        return DataFrame(node, self.left.df.session)

    applyInPandas = apply_in_pandas


def _to_schema(schema) -> T.Schema:
    if isinstance(schema, T.Schema):
        return schema
    return T.Schema(schema)


def _resolve_agg(fn: AggregateFunction, schema: T.Schema
                 ) -> AggregateFunction:
    if len(fn.children) > 1:  # binary-stat markers (corr/covar)
        return fn.with_children(
            [resolve(c, schema) for c in fn.children])
    child = resolve(fn.fn_child if hasattr(fn, "fn_child") else fn.child,
                    schema)
    return fn.with_children([child])


GROUPING_ID_COL = "__grouping_id"
GROUPING_SET_COL = "__gset_idx"


def rollup_sets(n: int):
    """((0..n-1), (0..n-2), ..., ()) — the ROLLUP ladder.  Ordering is
    bit-layout-sensitive: grouping_id bit (n-1-i) marks key i masked."""
    return [tuple(range(k)) for k in range(n, -1, -1)]


def cube_sets(n: int):
    """Every subset of the n grouping keys (CUBE)."""
    import itertools
    return [s for k in range(n, -1, -1)
            for s in itertools.combinations(range(n), k)]


class GroupingSetsData(GroupedData):
    """GroupedData over ROLLUP / CUBE / GROUPING SETS: plans an Expand
    producing one copy of the input per grouping set — original columns
    passed through for the aggregates, key columns masked to NULL where
    grouped-out, plus a grouping_id — then a single hash aggregation
    over (masked keys, grouping_id).  The reference's GpuExpandExec
    exists for exactly this plan shape (GpuExpandExec.scala)."""

    def __init__(self, df: DataFrame, keys: List[Expression],
                 names: List[str], sets: List[tuple]):
        super().__init__(df, keys, names)
        self.sets = sets

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        raise NotImplementedError(
            "apply_in_pandas under rollup/cube/grouping sets is not "
            "supported (Spark has no pandas path for grouping sets "
            "either); aggregate with agg() instead")

    applyInPandas = apply_in_pandas

    def agg_in_pandas(self, specs) -> DataFrame:
        raise NotImplementedError(
            "agg_in_pandas under rollup/cube/grouping sets is not "
            "supported; aggregate with agg() instead")

    def cogroup(self, other) -> "CoGroupedData":
        raise NotImplementedError(
            "cogroup under rollup/cube/grouping sets is not supported")

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu import functions as F
        from spark_rapids_tpu.exprs.aggregates import GroupingID
        n = len(self.keys)
        child_fields = self.df.schema.fields
        masked = [f"__gset_k{i}" for i in range(n)]
        projections, names = [], None
        # The set INDEX (not the grouping_id) is the hidden group key:
        # duplicate grouping sets must stay separate groups and emit
        # duplicate result rows (Spark semantics, SPARK-33229) — their
        # gids are equal, their indices are not.
        for si, s in enumerate(self.sets):
            gid = sum(1 << (n - 1 - i) for i in range(n) if i not in s)
            proj = [ColumnRef(f.name, f.dtype, f.nullable)
                    for f in child_fields]
            for i, k in enumerate(self.keys):
                proj.append(k if i in s else Literal(None, k.dtype))
            proj.append(Literal(si, T.INT))
            proj.append(Literal(gid, T.INT))
            projections.append(proj)
        names = [f.name for f in child_fields] + masked \
            + [GROUPING_SET_COL, GROUPING_ID_COL]
        expanded = DataFrame(
            L.Expand(projections, names, self.df.plan), self.df.session)
        inner_keys = [ColumnRef(mn, k.dtype, True)
                      for mn, k in zip(masked, self.keys)]
        inner_keys.append(ColumnRef(GROUPING_SET_COL, T.INT, False))
        gd = GroupedData(expanded, inner_keys,
                         self.names + [GROUPING_SET_COL])
        fixed = []
        for a in aggs:
            e = a.expr if isinstance(a, Column) else None
            name = None
            if isinstance(e, Alias):
                name, e = e.alias_name, e.children[0]
            if isinstance(e, GroupingID):
                fixed.append(F.min(Column(
                    ColumnRef(GROUPING_ID_COL, T.INT, False)))
                    .alias(name or "grouping_id"))
            else:
                fixed.append(a)
        out = gd.agg(*fixed)
        return out.select(*[c for c in out.columns
                            if c not in (GROUPING_ID_COL,
                                         GROUPING_SET_COL)])


def _agg_label(e: Expression) -> str:
    """pyspark-style pivot column label for an unaliased aggregate:
    'sum(x)' — falls back to the expression repr for computed args."""
    child = e.children[0] if e.children else None
    if isinstance(child, ColumnRef):
        arg = child.column
    elif isinstance(child, Literal):
        arg = str(child.value)
    else:
        arg = repr(child) if child is not None else ""
    return f"{e.name.lower()}({arg})"


def _fill_compatible(dtype: T.DataType, value) -> bool:
    """pyspark fill rules: numeric fills numeric, string fills string,
    bool fills bool; mismatches leave the column untouched."""
    if isinstance(value, bool):
        return dtype == T.BOOLEAN
    if isinstance(value, (int, float)):
        return dtype.is_numeric
    if isinstance(value, str):
        return dtype.is_string
    return False


class PivotedData(GroupedData):
    """GroupedData after .pivot(): agg() plans Spark's two-phase pivot —
    an inner aggregation grouped by (keys, pivot column), then an outer
    aggregation picking each pivot value's result with
    first(CASE WHEN pivot = v THEN agg END, ignoreNulls=true) (the
    ResolvePivot/PivotFirst shape).  Group/value combinations with no
    rows come out NULL — including for count(), matching pyspark."""

    def __init__(self, df: DataFrame, keys: List[Expression],
                 names: List[str], pivot_col: str, values: List):
        super().__init__(df, keys, names)
        self.pivot_col = pivot_col
        self.values = values

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu import functions as F
        from spark_rapids_tpu.exprs.aggregates import First
        from spark_rapids_tpu.exprs.nullexprs import IsNull

        norm = [self._unwrap_agg(a) for a in aggs]

        pv_name = "__pivot_val"
        inner_aggs = [Column(Alias(e, f"__pv_a{j}"))
                      for j, (e, _) in enumerate(norm)]
        inner = GroupedData(
            self.df,
            self.keys + [resolve(ColumnRef(self.pivot_col),
                                 self.df.schema)],
            self.names + [pv_name]).agg(*inner_aggs)

        pcol = inner[pv_name]
        outer = []
        for v in self.values:
            cond = Column(IsNull(pcol.expr)) if v is None else (pcol == v)
            vlabel = "null" if v is None else str(v)
            for j, (e, name) in enumerate(norm):
                picked = First(
                    F.when(cond, inner[f"__pv_a{j}"]).otherwise(None)
                    .expr, ignore_nulls=True)
                # pyspark naming: the bare value for a single aggregate,
                # '{value}_{alias-or-fn(arg)}' otherwise
                if len(norm) == 1:
                    out_name = vlabel
                else:
                    label = name or _agg_label(e)
                    out_name = f"{vlabel}_{label}"
                outer.append(Column(Alias(picked, out_name)))
        return (GroupedData(inner, [
            inner._resolve(ColumnRef(n)) for n in self.names],
            list(self.names)).agg(*outer))
