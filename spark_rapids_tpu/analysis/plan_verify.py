"""Runtime-free plan-invariant verifier.

Checks structural invariants of an already-built physical plan — no
dispatch, no device work, no re-execution.  Five families:

* **Schema consistency** — every operator's output schema is well formed
  (unique names, concrete dtypes) and the planner-inserted transitions
  (`HostToDeviceExec` / `DeviceToHostExec`) are schema-transparent; any
  CPU<->TPU flip in the tree happens ONLY through those transitions
  (GpuTransitionOverrides invariant).
* **Donation-mask provenance** — every cached stage program
  (``op._stage_cache``, plan/pipeline.py) may donate a source's buffers
  only when that source is a stage-break intermediate or a fresh
  `HostToDeviceExec` staging.  Cached scans, spill-catalog handles and
  broadcast builds are re-referenced across partitions/queries: donating
  one hands live HBM to XLA and the next read returns garbage (or a
  deleted-buffer error on backends that check).
* **Mesh sharding** — fused mesh-SPMD stages declare a PartitionSpec for
  every program input/output (replicated or leading with the ``data``
  axis), flip sharding only at recorded reshard (exchange) nodes, and
  never donate under sharding (``check_mesh_sharding``).
* **Semaphore balance** — after a query completes, the task-wide
  re-entrant hold depth must be back to zero; a leaked permit silently
  halves device admission for every later query in the process.
* **Catalog accounting** — the spill catalog's incremental per-tier byte
  counters must match a full handle scan after in-flight spills drain; a
  mismatch means some tier transition skipped its counter update and the
  budget loop is steering on a stale number.
* **Encoded corridor** — dictionary-encoded string columns never cross
  the collection DeviceToHost unmaterialized (``ctx.encoded_d2h_leaks``,
  recorded by DeviceToHostExec), and encoded pieces the spill catalog
  holds on the host tier are structurally reconstructible (non-empty
  dictionary, codes inside it) so unspill rebuilds the same column.

The module imports no engine code at import time so `tools/rapidslint.py`
and other host-only tooling can load it without pulling in jax; the
isinstance probes import lazily inside the checks.

Used by ``tests/conftest.py`` behind ``RAPIDS_PLAN_VERIFY=1`` (on in CI)
to verify every plan the suite executes, and directly by
``tests/test_lint.py`` fixtures.
"""

from __future__ import annotations

from typing import Iterator, List, Optional


class PlanInvariantError(AssertionError):
    """A physical plan violated a structural invariant."""

    def __init__(self, problems: List[str]):
        self.problems = list(problems)
        super().__init__(
            "plan invariant violation(s):\n  - " + "\n  - ".join(problems))


def _walk(op) -> Iterator:
    seen = set()
    stack = [op]
    while stack:
        node = stack.pop()
        if id(node) in seen:   # joins may share a cached build subtree
            continue
        seen.add(id(node))
        yield node
        stack.extend(getattr(node, "children", ()) or ())


def _describe(op) -> str:
    return f"{type(op).__name__}[{getattr(op, 'op_id', '?')}]"


def check_schemas(root) -> List[str]:
    """Well-formed output schemas + schema-transparent transitions."""
    from spark_rapids_tpu.plan.physical import (
        DeviceToHostExec, HostToDeviceExec,
    )
    problems = []
    for op in _walk(root):
        schema = getattr(op, "output_schema", None)
        fields = getattr(schema, "fields", None)
        if fields is None:
            problems.append(f"{_describe(op)}: missing output schema")
            continue
        names = [f.name for f in fields]
        if len(set(names)) != len(names):
            problems.append(
                f"{_describe(op)}: duplicate output columns {names}")
        for f in fields:
            if f.dtype is None:
                problems.append(
                    f"{_describe(op)}: column {f.name!r} has no dtype")
        if isinstance(op, (HostToDeviceExec, DeviceToHostExec)):
            child = op.children[0]
            cs = child.output_schema
            if [(f.name, f.dtype) for f in cs.fields] != \
                    [(f.name, f.dtype) for f in fields]:
                problems.append(
                    f"{_describe(op)}: transition altered schema "
                    f"{cs.fields} -> {fields}")
    return problems


def check_boundaries(root) -> List[str]:
    """CPU<->TPU flips only through the planner's transition nodes."""
    from spark_rapids_tpu.plan.physical import (
        DeviceToHostExec, HostToDeviceExec,
    )
    problems = []
    for op in _walk(root):
        if isinstance(op, (HostToDeviceExec, DeviceToHostExec)):
            continue  # the sanctioned flips
        for child in getattr(op, "children", ()) or ():
            if bool(getattr(op, "is_tpu", False)) != \
                    bool(getattr(child, "is_tpu", False)):
                problems.append(
                    f"{_describe(op)} (is_tpu={op.is_tpu}) feeds from "
                    f"{_describe(child)} (is_tpu={child.is_tpu}) without "
                    "a HostToDevice/DeviceToHost transition")
    return problems


def check_donation_provenance(root) -> List[str]:
    """Every True bit in a cached stage's donation mask must point at a
    stage-break intermediate or a HostToDeviceExec staging — the only
    sources whose batches the stage provably consumes exactly once
    (plan/pipeline.py ``_materialize_sources`` contract)."""
    from spark_rapids_tpu.plan.physical import HostToDeviceExec
    problems = []
    for op in _walk(root):
        cache = getattr(op, "_stage_cache", None)
        builds = getattr(op, "_stage_builds", None)
        if not isinstance(cache, dict) or not isinstance(builds, dict):
            continue
        for key in cache:
            variant, _spec, dmask = key
            if variant not in builds:
                problems.append(
                    f"{_describe(op)}: stage program cached for variant "
                    f"{variant!r} with no recorded build")
                continue
            sources = builds[variant][0]
            if len(dmask) != len(sources):
                problems.append(
                    f"{_describe(op)}: donation mask arity {len(dmask)} != "
                    f"{len(sources)} sources (variant {variant!r})")
                continue
            for i, donated in enumerate(dmask):
                if not donated:
                    continue
                src = sources[i]
                if isinstance(src, HostToDeviceExec):
                    continue
                if getattr(src, "pipeline_stage_break", False):
                    continue
                problems.append(
                    f"{_describe(op)}: variant {variant!r} donates source "
                    f"{i} ({_describe(src)}), which is neither a "
                    "stage-break intermediate nor a HostToDevice staging")
    return problems


def check_mesh_sharding(root) -> List[str]:
    """Sharding invariants of fused mesh-SPMD stages
    (``op._mesh_partition_specs``, written by parallel.mesh_spmd after
    each fused dispatch):

    * every program input/output leaf carries a DECLARED PartitionSpec,
      and each is either fully replicated (no named axis — broadcast
      build sides) or leads with an axis some partitioning RULE declares
      (parallel.partitioning.MESH_PARTITION_RULES — the same pytree the
      lowering consults, so verifier and lowering cannot drift);
    * sharding boundaries flip only at explicit reshard nodes: the stage
      records the fused exchanges it resharded through, each of which
      must be a shuffle exchange inside the stage root's subtree.  A
      stage with NO reshard must have fused at least one join (a
      broadcast join fuses exchange-free: its build side replicates);
    * fused joins: each recorded join is a hash-join exec inside the
      subtree; every replicated input leaf (broadcast build side) is
      declared fully replicated (build side rides as ``P()``), and no
      OUTPUT leaf is replicated — join output sharding derives from the
      data-sharded probe side;
    * donation masks are all-False — a donated leaf of a mesh global
      would hand ONE shard's buffer to XLA while the other shards (and a
      device-lost replay) still reference the global."""
    from spark_rapids_tpu.parallel.partitioning import (
        MESH_PARTITION_RULES,
    )
    rule_axes = {spec[0] for _, spec in MESH_PARTITION_RULES
                 if spec is not None}
    problems = []
    for op in _walk(root):
        specs = getattr(op, "_mesh_partition_specs", None)
        if not isinstance(specs, dict):
            continue
        replicated = set(specs.get("replicated", ()))
        for role in ("in_specs", "out_specs"):
            for i, spec in enumerate(specs.get(role, ())):
                axes = tuple(spec) if spec is not None else None
                if axes is None:
                    problems.append(
                        f"{_describe(op)}: mesh {role}[{i}] has no "
                        "declared PartitionSpec")
                elif not all(a is None for a in axes) and \
                        (not axes or axes[0] not in rule_axes):
                    problems.append(
                        f"{_describe(op)}: mesh {role}[{i}] = {spec} is "
                        "neither replicated nor leading with a "
                        "rule-declared mesh axis")
                elif role == "out_specs" and specs.get("joins") and \
                        all(a is None for a in axes):
                    problems.append(
                        f"{_describe(op)}: mesh out_specs[{i}] is "
                        "replicated, but a fused join's output must be "
                        "data-sharded like its probe side")
        reshards = list(specs.get("reshards", ()))
        joins = list(specs.get("joins", ()))
        if not reshards and not joins:
            problems.append(
                f"{_describe(op)}: fused mesh stage records no reshard "
                "(exchange) boundary")
        subtree_ids = {getattr(o, "op_id", None): o for o in _walk(op)}
        for ex_id in reshards:
            ex = subtree_ids.get(ex_id)
            if ex is None:
                problems.append(
                    f"{_describe(op)}: mesh reshard {ex_id} is not in the "
                    "stage root's subtree")
                continue
            if "ShuffleExchange" not in type(ex).__name__:
                problems.append(
                    f"{_describe(op)}: mesh reshard {ex_id} is a "
                    f"{type(ex).__name__}, not a shuffle exchange — "
                    "sharding may only flip at explicit reshard nodes")
        for j_id in joins:
            j = subtree_ids.get(j_id)
            if j is None:
                problems.append(
                    f"{_describe(op)}: fused mesh join {j_id} is not in "
                    "the stage root's subtree")
                continue
            if "HashJoin" not in type(j).__name__:
                problems.append(
                    f"{_describe(op)}: fused mesh join {j_id} is a "
                    f"{type(j).__name__}, not a hash join exec")
        in_specs = list(specs.get("in_specs", ()))
        for i in replicated:
            if i < len(in_specs) and in_specs[i] is not None and \
                    not all(a is None for a in tuple(in_specs[i])):
                problems.append(
                    f"{_describe(op)}: broadcast build leaf {i} must be "
                    f"fully replicated (P()), got {in_specs[i]}")
        if any(specs.get("dmask", ())):
            problems.append(
                f"{_describe(op)}: donation under mesh sharding "
                f"(dmask={specs.get('dmask')})")
    return problems


def check_catalog_accounting(runtime) -> List[str]:
    """The spill catalog's incremental per-tier byte counters must equal a
    full handle scan (mem/catalog.py ``verify_accounting``): every tier
    transition updates tier and counter under the same lock, so any
    divergence means a transition path forgot its counter half.  In-flight
    async spills are drained first — the invariant is defined at
    lock-quiesced instants."""
    catalog = getattr(runtime, "catalog", None)
    if catalog is None or not hasattr(catalog, "verify_accounting"):
        return []
    catalog.drain_spills()
    return list(catalog.verify_accounting())


def check_adaptive_events(root, ctx) -> List[str]:
    """Every replan decision the adaptive layer logged
    (``ExecContext.adaptive_events``, written by plan/adaptive) must be
    structurally sound: the event's op is in the executed plan, the
    mechanism is a known one, a broadcast switch happened on a shuffled
    hash join whose join type permits SOME broadcast side (switching an
    illegal side would drop the outer side's unmatched rows), and a skew
    split never ran on a full outer join (chunking would double-count
    its build-side unmatched rows)."""
    events = getattr(ctx, "adaptive_events", None)
    if not events:
        return []
    problems = []
    by_id = {getattr(op, "op_id", None): op for op in _walk(root)}
    known = {"coalesce", "broadcast_switch", "skew"}
    for op_id, mechanism in events:
        op = by_id.get(op_id)
        if op is None:
            problems.append(
                f"adaptive event ({op_id}, {mechanism}) references an op "
                "absent from the executed plan")
            continue
        if mechanism not in known:
            problems.append(
                f"{_describe(op)}: unknown adaptive mechanism "
                f"{mechanism!r}")
            continue
        how = getattr(op, "how", None)
        if mechanism == "broadcast_switch":
            from spark_rapids_tpu.plan.adaptive import (
                broadcast_build_sides,
            )
            if how is None or not broadcast_build_sides(how):
                problems.append(
                    f"{_describe(op)}: broadcast switch on join type "
                    f"{how!r} with no legal broadcast side")
        if mechanism == "skew" and how == "full":
            problems.append(
                f"{_describe(op)}: skew split on a full outer join")
    return problems


def check_encoded_corridor(runtime, ctx) -> List[str]:
    """Encoded columns never cross the collection D2H unmaterialized, and
    host-tier encoded spill pieces are structurally consistent."""
    problems = []
    leaks = getattr(ctx, "encoded_d2h_leaks", 0) if ctx is not None else 0
    if leaks:
        problems.append(
            f"{leaks} collected host batch(es) carried dictionary-encoded "
            "columns across DeviceToHost — collection must materialize "
            "(only spill tier transitions keep the dictionary)")
    catalog = getattr(runtime, "catalog", None) if runtime is not None \
        else None
    if catalog is not None and \
            hasattr(catalog, "verify_encoded_host_batches"):
        problems += list(catalog.verify_encoded_host_batches())
    return problems


def check_semaphore_balance(runtime) -> List[str]:
    """Post-query the task-wide hold depth must be zero."""
    sem = getattr(runtime, "semaphore", None)
    if sem is None:
        return []
    depth = sem.held_depth()
    if depth != 0:
        return [f"semaphore hold depth {depth} != 0 after query "
                "completion (leaked device admission permit)"]
    return []


def verify_plan(root, runtime=None, ctx=None) -> None:
    """Run every check; raise :class:`PlanInvariantError` on violations."""
    problems = []
    problems += check_schemas(root)
    problems += check_boundaries(root)
    problems += check_donation_provenance(root)
    problems += check_mesh_sharding(root)
    if ctx is not None:
        problems += check_adaptive_events(root, ctx)
    problems += check_encoded_corridor(runtime, ctx)
    if runtime is not None:
        problems += check_semaphore_balance(runtime)
        problems += check_catalog_accounting(runtime)
    if problems:
        raise PlanInvariantError(problems)


def verify_session(session) -> None:
    """Verify the most recent query a :class:`TpuSparkSession` executed.

    Convenience entry point for the conftest hook: pulls the plan,
    runtime and execution context off the session, no-op when no query
    ran yet."""
    root = getattr(session, "last_physical_plan", None)
    if root is None:
        return
    verify_plan(root, runtime=getattr(session, "runtime", None),
                ctx=getattr(session, "last_exec_ctx", None))
