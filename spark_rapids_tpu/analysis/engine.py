"""rapidslint rule framework.

Deliberately runtime-free: the engine parses source with :mod:`ast` and
never imports the modules it checks (importing would initialize jax — the
lint gate must run in ~seconds and must be able to lint a module that
would crash at import).  Rules come in two shapes:

* :class:`Rule` — per-file: ``check(SourceFile) -> findings``.
* :class:`ProjectRule` — whole-tree: ``check_project(files) -> findings``
  (cross-file consistency like the config-registry and metrics-key sync).

Suppression model (mirrors the reference's opt-in conf kill-switches —
every override is explicit and auditable):

* ``# rapidslint: disable=R2`` on the offending line (or
  ``disable=R2,R3``) suppresses that line only.
* ``# rapidslint: disable-file=R3`` anywhere in a file suppresses the
  rule for the whole file.
* The checked-in baseline (``tools/rapidslint_baseline.json``) accepts
  specific findings with a one-line justification each.  Baseline
  entries are fingerprinted by (rule, path, normalized line text) so
  they survive line-number drift but die with the code they excused.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"


class Finding:
    """One rule violation at a source location."""

    def __init__(self, rule_id: str, path: str, line: int, message: str,
                 severity: str = Severity.ERROR):
        self.rule_id = rule_id
        self.path = path  # repo-relative, '/'-separated
        self.line = line  # 1-based; 0 = whole-file/project finding
        self.message = message
        self.severity = severity
        self.line_text = ""  # filled by the engine from the source

    def fingerprint(self) -> Tuple[str, str, str]:
        """Line-drift-tolerant identity: the line's normalized text stands
        in for its number, so a finding keeps matching its baseline entry
        when unrelated edits move it — and stops matching the moment the
        excused code itself changes."""
        return (self.rule_id, self.path, _norm(self.line_text))

    def __repr__(self):
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.rule_id}] {self.message}")


def _norm(text: str) -> str:
    return re.sub(r"\s+", " ", text.strip())


_DISABLE_RE = re.compile(r"#\s*rapidslint:\s*disable=([A-Za-z0-9_,\-]+)")
_DISABLE_FILE_RE = re.compile(
    r"#\s*rapidslint:\s*disable-file=([A-Za-z0-9_,\-]+)")


class SourceFile:
    """A parsed source file plus its suppression comments."""

    def __init__(self, abs_path: str, rel_path: str, text: str):
        self.abs_path = abs_path
        self.path = rel_path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel_path)
        self.line_disables: Dict[int, set] = {}
        self.file_disables: set = set()
        for i, ln in enumerate(self.lines, start=1):
            if "rapidslint" not in ln:
                continue
            m = _DISABLE_RE.search(ln)
            if m:
                self.line_disables.setdefault(i, set()).update(
                    m.group(1).split(","))
            m = _DISABLE_FILE_RE.search(ln)
            if m:
                self.file_disables.update(m.group(1).split(","))

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def suppressed(self, rule_id: str, line: int) -> bool:
        if rule_id in self.file_disables:
            return True
        return rule_id in self.line_disables.get(line, set())


class Rule:
    """Per-file rule: subclass and implement :meth:`check`."""

    id = "R0"
    name = "unnamed"
    severity = Severity.ERROR
    description = ""

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, sf: SourceFile, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(self.id, sf.path, int(line), message, self.severity)


class ProjectRule(Rule):
    """Whole-tree rule: sees every file (and the repo root for docs)."""

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        return iter(())

    def check_project(self, files: Sequence[SourceFile],
                      repo_root: str) -> Iterator[Finding]:
        raise NotImplementedError


class Baseline:
    """The checked-in accepted-findings file.

    JSON list of ``{"rule", "path", "line", "reason"}`` where ``line`` is
    the normalized source line text (see :meth:`Finding.fingerprint`).
    Each entry excuses exactly one matching finding; a second identical
    offense on another line needs its own entry.
    """

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = list(entries or [])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({
                "_comment": "rapidslint accepted findings; each entry "
                            "needs a one-line reason.  Regenerate with "
                            "tools/rapidslint.py --write-baseline (reasons "
                            "are preserved for surviving entries).",
                "findings": self.entries,
            }, f, indent=2)
            f.write("\n")

    def partition(self, findings: List[Finding]
                  ) -> Tuple[List[Finding], List[dict], List[dict]]:
        """-> (new findings, used entries, stale entries)."""
        pool: Dict[Tuple[str, str, str], List[dict]] = {}
        for e in self.entries:
            key = (e.get("rule", ""), e.get("path", ""),
                   _norm(e.get("line", "")))
            pool.setdefault(key, []).append(e)
        new: List[Finding] = []
        used: List[dict] = []
        for f in findings:
            hits = pool.get(f.fingerprint())
            if hits:
                used.append(hits.pop(0))
            else:
                new.append(f)
        stale = [e for bucket in pool.values() for e in bucket]
        return new, used, stale


#: Directories under the repo root whose .py files are linted.  tests/ is
#: deliberately excluded: R3's no-unbounded-wait invariant (and friends)
#: bind non-test code; tests may block/wait freely under the harness's
#: SIGALRM bound.
DEFAULT_LINT_DIRS = ("spark_rapids_tpu", "tools", "ci")
DEFAULT_LINT_FILES = ("bench.py", "profile_bench.py", "__graft_entry__.py")


def discover_files(repo_root: str,
                   extra_paths: Iterable[str] = ()) -> List[SourceFile]:
    paths: List[str] = []
    for d in DEFAULT_LINT_DIRS:
        base = os.path.join(repo_root, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    paths.append(os.path.join(dirpath, fn))
    for fn in DEFAULT_LINT_FILES:
        p = os.path.join(repo_root, fn)
        if os.path.exists(p):
            paths.append(p)
    paths.extend(extra_paths)
    out: List[SourceFile] = []
    for p in sorted(set(paths)):
        rel = os.path.relpath(p, repo_root)
        with open(p, encoding="utf-8") as f:
            text = f.read()
        try:
            out.append(SourceFile(p, rel, text))
        except SyntaxError as e:
            sf = SourceFile.__new__(SourceFile)
            sf.abs_path, sf.path, sf.text = p, rel.replace(os.sep, "/"), text
            sf.lines = text.splitlines()
            sf.tree = None
            sf.line_disables, sf.file_disables = {}, set()
            f0 = Finding("syntax", sf.path, e.lineno or 0,
                         f"file does not parse: {e.msg}")
            sf._syntax_finding = f0  # surfaced by LintEngine.run
            out.append(sf)
    return out


class LintEngine:
    def __init__(self, rules: Sequence[Rule]):
        self.rules = list(rules)

    def run(self, files: Sequence[SourceFile],
            repo_root: str) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files:
            syn = getattr(sf, "_syntax_finding", None)
            if syn is not None:
                findings.append(syn)
                continue
            for rule in self.rules:
                if isinstance(rule, ProjectRule):
                    continue
                for f in rule.check(sf):
                    if not sf.suppressed(f.rule_id, f.line):
                        f.line_text = sf.line_text(f.line)
                        findings.append(f)
        by_path = {sf.path: sf for sf in files}
        parsed = [sf for sf in files if sf.tree is not None]
        for rule in self.rules:
            if not isinstance(rule, ProjectRule):
                continue
            for f in rule.check_project(parsed, repo_root):
                sf = by_path.get(f.path)
                if sf is not None:
                    if sf.suppressed(f.rule_id, f.line):
                        continue
                    f.line_text = sf.line_text(f.line)
                findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
        return findings


# -- small AST helpers shared by the rules ------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_no_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s subtree but do not descend into nested function or
    lambda bodies (their control flow is separate)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
