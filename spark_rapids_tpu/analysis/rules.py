"""The project rule catalog (R1..R8).

Every rule is distilled from a real incident in this repo's history;
docs/static_analysis.md maps each id to the PR that motivated it and
shows the suppression syntax.  Matchers are deliberately narrow: a lint
that cries wolf gets disabled, so each rule targets the exact shape of
the bug class it retires and leaves neighboring idioms alone (the same
philosophy as the reference's per-op tagging: precise reasons, no
blanket bans).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import (
    Finding, ProjectRule, Rule, Severity, SourceFile, dotted_name, str_const,
    walk_no_nested_functions,
)


def _functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class ImportTimeJnpRule(Rule):
    """R1: no jnp/jax.numpy value construction at module import time.

    Module-level device values are created before tests/conftest pin the
    platform, can capture a tracer when the module first loads under a
    jit trace, and silently pin HBM for the process lifetime (the PR-2
    tracer-leak class).  Build device constants inside the function (XLA
    constant-folds them) or lazily.
    """

    id = "R1"
    name = "import-time-jnp"
    description = ("no jnp.*/jax.numpy value construction at module "
                   "import time")

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        # walk module scope, descending into classes/ifs/trys but never
        # into function or lambda bodies
        stack: List[ast.AST] = list(sf.tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name.startswith("jnp.") or name.startswith("jax.numpy."):
                    yield self.finding(
                        sf, node,
                        f"`{name}(...)` at module import time builds a "
                        "device value before the platform/test harness is "
                        "configured (tracer-leak class); construct it "
                        "inside the consuming function")
            stack.extend(ast.iter_child_nodes(node))


_SEM_SEG = re.compile(r"sem", re.IGNORECASE)


def _is_sem_call(node: ast.Call, method: Tuple[str, ...]) -> bool:
    name = dotted_name(node.func)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] not in method:
        return False
    return any(_SEM_SEG.search(seg) for seg in parts[:-1])


class SemaphoreReleaseRule(Rule):
    """R2: a function that acquires a semaphore must release it in a
    ``finally`` of the same function.

    Coarse, per-function: one sem-release inside any ``finally`` clears
    every sem-acquire in that function.  Deliberate cross-function
    pairings (the engine's H2D-acquire / D2H-release protocol) are
    baseline entries with the pairing spelled out — the rule exists so a
    NEW unpaired acquire can't land silently (the PR-3/4 leak class).
    """

    id = "R2"
    name = "semaphore-release-finally"
    description = ("semaphore.acquire without a release in a finally "
                   "reachable from the same function")

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        for fn in _functions(sf.tree):
            acquires = [
                n for n in walk_no_nested_functions(fn)
                if isinstance(n, ast.Call)
                and _is_sem_call(n, ("acquire",))]
            if not acquires:
                continue
            releases_in_finally = False
            for n in walk_no_nested_functions(fn):
                if isinstance(n, ast.Try) and n.finalbody:
                    for fin_stmt in n.finalbody:
                        for m in ast.walk(fin_stmt):
                            if isinstance(m, ast.Call) and _is_sem_call(
                                    m, ("release", "release_all")):
                                releases_in_finally = True
            if releases_in_finally:
                continue
            for acq in acquires:
                yield self.finding(
                    sf, acq,
                    "semaphore acquired with no release in a finally of "
                    "this function — an error between acquire and release "
                    "leaks the permit and wedges device admission")


class UnboundedWaitRule(Rule):
    """R3: no unbounded blocking primitive in non-test code.

    The PR-4 watchdog delivers ``PartitionTimeout`` via
    ``PyThreadState_SetAsyncExc``, which only lands when the target
    thread re-enters the interpreter — a thread parked in an unbounded
    C-level wait never does.  Every wait must carry a timeout (slice
    loops re-check in bounded steps).
    """

    id = "R3"
    name = "unbounded-wait"
    description = ("Condition/Event.wait(), thread.join() or queue.get() "
                   "without a timeout defeats the partition watchdog")

    _QUEUE_RE = re.compile(r"(queue$|^q$|_q$)", re.IGNORECASE)

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            has_args = bool(node.args) or bool(node.keywords)
            if attr in ("wait", "join") and not has_args:
                yield self.finding(
                    sf, node,
                    f"unbounded .{attr}() blocks in C and cannot receive "
                    "the watchdog's async PartitionTimeout; pass a timeout "
                    "(loop over bounded slices if needed)")
            elif attr == "get" and not has_args:
                recv = dotted_name(node.func.value) or ""
                last = recv.split(".")[-1]
                if self._QUEUE_RE.search(last):
                    yield self.finding(
                        sf, node,
                        "queue .get() without timeout parks the thread "
                        "beyond the watchdog's reach; use "
                        "get(timeout=...) in a bounded loop")


class SwallowBaseExceptionRule(Rule):
    """R4: no handler that can swallow KeyboardInterrupt/SystemExit.

    The fault taxonomy (fault/errors.py) promises KI/SE are never
    retried or absorbed by recovery; a ``except:`` or ``except
    BaseException:`` that neither re-raises nor exits the process breaks
    that promise.  (Plain ``except Exception`` cannot catch KI/SE and is
    not flagged.)
    """

    id = "R4"
    name = "swallow-base-exception"
    description = ("bare except / except BaseException that can absorb "
                   "KeyboardInterrupt/SystemExit")

    _BROAD = ("BaseException", "KeyboardInterrupt", "SystemExit")

    def _is_broad(self, type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True  # bare except:
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        name = dotted_name(type_node) or ""
        return name.split(".")[-1] in self._BROAD

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            propagates = False
            for m in walk_no_nested_functions(node):
                if isinstance(m, ast.Raise):
                    if m.exc is None:
                        propagates = True  # bare re-raise
                    elif node.name and isinstance(m.exc, ast.Name) \
                            and m.exc.id == node.name:
                        propagates = True  # raise e (same object)
                elif isinstance(m, ast.Call):
                    cname = dotted_name(m.func) or ""
                    if cname in ("os._exit", "sys.exit"):
                        propagates = True
            if not propagates:
                what = "bare except" if node.type is None else \
                    f"except {ast.unparse(node.type)}"
                yield self.finding(
                    sf, node,
                    f"{what} absorbs KeyboardInterrupt/SystemExit (no "
                    "bare re-raise / raise of the caught object / "
                    "process exit on any path); narrow to Exception or "
                    "re-raise non-Exception classes")


class DonationHygieneRule(Rule):
    """R5: donation and compilation go through ``instrumented_jit``.

    ``donate_argnums`` on a raw ``jax.jit`` bypasses the registry's
    donation audit (donatedBytes accounting, cache-bypass for donating
    programs, ``donation_supported()`` platform gate) — a donated buffer
    later re-read by a cached/spill-catalog path is silent corruption.
    Raw ``jax.jit`` anywhere also under-counts compileCount/
    dispatchCount, so the compile-economics metrics lie.
    """

    id = "R5"
    name = "donation-hygiene"
    description = ("donate_argnums outside instrumented_jit, or raw "
                   "jax.jit bypassing the compile registry")

    ALLOWED_FILE = "spark_rapids_tpu/utils/compile_registry.py"

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            donating = [k for k in node.keywords
                        if k.arg in ("donate_argnums", "donate_argnames")]
            if donating and not name.endswith("instrumented_jit"):
                yield self.finding(
                    sf, node,
                    f"`{name}(..., {donating[0].arg}=...)` donates outside "
                    "instrumented_jit: no donatedBytes accounting, no "
                    "donation_supported() gate, and the compile cache may "
                    "serve a donating executable to a non-donating call "
                    "site")
            elif name == "jax.jit" and sf.path != self.ALLOWED_FILE:
                yield self.finding(
                    sf, node,
                    "raw jax.jit bypasses the compile registry "
                    "(compileCount/dispatchCount metrics, shape-bucket "
                    "policy, persistent-cache wiring); use "
                    "utils.compile_registry.instrumented_jit")


class SyncUnderRuntimeLockRule(Rule):
    """R6: no blocking device sync while holding ``DeviceRuntime._lock``.

    Every thread in the process serializes on that lock via
    ``DeviceRuntime.get()/generation()``; a device sync inside it against
    a sick device turns one wedged transfer into a whole-process hang —
    the exact failure device-lost recovery exists to prevent (recover()
    deliberately rescues the catalog OUTSIDE the lock).
    """

    id = "R6"
    name = "sync-under-runtime-lock"
    description = ("blocking device sync (device_get/block_until_ready/"
                   "device_to_host) while holding DeviceRuntime._lock")

    _SYNC_ATTRS = ("block_until_ready", "device_get")
    _SYNC_NAMES = ("device_to_host",)

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        # map each With node to whether its context is DeviceRuntime._lock
        runtime_classes = {
            node for node in ast.walk(sf.tree)
            if isinstance(node, ast.ClassDef) and node.name == "DeviceRuntime"
        }
        in_runtime: Set[int] = set()
        for cls in runtime_classes:
            for n in ast.walk(cls):
                in_runtime.add(id(n))
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.With):
                continue
            holds = False
            for item in node.items:
                name = dotted_name(item.context_expr) or ""
                if name == "DeviceRuntime._lock":
                    holds = True
                elif name in ("cls._lock", "self._lock") \
                        and id(node) in in_runtime:
                    holds = True
            if not holds:
                continue
            for m in walk_no_nested_functions(node):
                if not isinstance(m, ast.Call):
                    continue
                cname = dotted_name(m.func) or ""
                last = cname.split(".")[-1]
                if last in self._SYNC_ATTRS or cname in self._SYNC_NAMES:
                    yield self.finding(
                        sf, m,
                        f"`{cname}` blocks on the device while holding "
                        "DeviceRuntime._lock — a sick device wedges every "
                        "thread in get()/generation(); move the sync "
                        "outside the lock (see DeviceRuntime.recover)")


_CONF_REGISTER_FNS = ("conf_bool", "conf_int", "conf_float", "conf_str",
                      "conf_bytes")
# a conf KEY, not prose that merely mentions one: dotted identifier
# segments only, optionally ending at a dangling "." (prefix literal)
_CONF_KEY_RE = re.compile(r"^spark\.(rapids|sql)\.[A-Za-z0-9_.]*$")


class ConfRegistrySyncRule(ProjectRule):
    """R7: every ``spark.rapids.*``/``spark.sql.*`` literal resolves to a
    registered ConfEntry, and every registered entry is referenced.

    Registration sites are calls to the ``conf_*`` constructors; dynamic
    per-op keys are recognized by their f-string prefixes
    (``f"spark.rapids.sql.exec.{name}"`` et al).  A registered entry
    counts as referenced when its holder variable is loaded anywhere or
    its key literal appears outside the registration call (docstrings
    never count).  Dead confs are docs that lie; unregistered literals
    are knobs that silently no-op.
    """

    id = "R7"
    name = "conf-registry-sync"
    description = ("spark.rapids.* literals out of sync with the "
                   "config.py registry (unregistered use / dead conf)")

    def check_project(self, files: Sequence[SourceFile],
                      repo_root: str) -> Iterator[Finding]:
        registered: Dict[str, Tuple[str, int]] = {}  # key -> (path, line)
        reg_vars: Dict[str, str] = {}  # key -> holder variable name
        reg_literal_nodes: Set[int] = set()
        dynamic_prefixes: Set[str] = set()
        docstrings: Set[int] = set()
        name_loads: Dict[str, int] = {}

        for sf in files:
            for scope in ast.walk(sf.tree):
                if isinstance(scope, (ast.Module, ast.ClassDef,
                                      ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and scope.body \
                        and isinstance(scope.body[0], ast.Expr) \
                        and str_const(scope.body[0].value) is not None:
                    docstrings.add(id(scope.body[0].value))
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    fname = (dotted_name(node.func) or "").split(".")[-1]
                    if fname in _CONF_REGISTER_FNS and node.args:
                        key = str_const(node.args[0])
                        if key is not None:
                            registered[key] = (sf.path, node.lineno)
                            reg_literal_nodes.add(id(node.args[0]))
                elif isinstance(node, ast.Assign):
                    if isinstance(node.value, ast.Call):
                        fname = (dotted_name(node.value.func) or ""
                                 ).split(".")[-1]
                        if fname in _CONF_REGISTER_FNS and node.value.args:
                            key = str_const(node.value.args[0])
                            if key is not None and node.targets and \
                                    isinstance(node.targets[0], ast.Name):
                                reg_vars[key] = node.targets[0].id
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load):
                    name_loads[node.id] = name_loads.get(node.id, 0) + 1
                elif isinstance(node, ast.JoinedStr) and node.values:
                    head = str_const(node.values[0])
                    if head and _CONF_KEY_RE.match(head):
                        dynamic_prefixes.add(head)

        # pass 2: literal usages outside registrations/docstrings
        literal_uses: Dict[str, List[Tuple[str, int]]] = {}
        for sf in files:
            for node in ast.walk(sf.tree):
                s = str_const(node)
                if s is None or not _CONF_KEY_RE.match(s):
                    continue
                if id(node) in reg_literal_nodes or id(node) in docstrings:
                    continue
                literal_uses.setdefault(s, []).append(
                    (sf.path, node.lineno))

        for key, sites in sorted(literal_uses.items()):
            if key.endswith("."):
                # prefix literal (startswith checks / f-string bases):
                # must cover at least one registered or dynamic key
                if any(k.startswith(key) for k in registered) or \
                        key in dynamic_prefixes:
                    continue
                for path, line in sites:
                    yield Finding(self.id, path, line,
                                  f"conf prefix `{key}` matches no "
                                  "registered key", self.severity)
            elif key not in registered and not any(
                    key.startswith(p) for p in dynamic_prefixes):
                for path, line in sites:
                    yield Finding(
                        self.id, path, line,
                        f"conf key `{key}` is not registered in the "
                        "config registry — setting it silently no-ops "
                        "and it never reaches docs/configs.md",
                        self.severity)

        for key, (path, line) in sorted(registered.items()):
            var = reg_vars.get(key)
            # the holder variable's own Store doesn't count; conf_* calls
            # register plenty of vars loaded exactly once (property
            # wrappers), so any Load at all marks the entry alive
            alive = bool(var and name_loads.get(var, 0) > 0)
            alive = alive or key in literal_uses
            if not alive:
                yield Finding(
                    self.id, path, line,
                    f"dead conf: `{key}` is registered (and documented in "
                    "docs/configs.md) but no code reads it — wire it or "
                    "remove it", self.severity)


_CAMEL_RE = re.compile(r"^[a-z][a-z0-9]*(?:[A-Z][a-zA-Z0-9]*)+$")
_DOC_TOKEN_RE = re.compile(r"^\|\s*`([A-Za-z_][A-Za-z0-9_.]*)`")


class MetricsKeySyncRule(ProjectRule):
    """R8: ``session.last_metrics`` keys, bench JSON fields and
    ``docs/metrics.md`` agree.

    Source of truth is the set of keys session.execute assigns into
    ``last_metrics``.  bench.py may only read camelCase keys from that
    set; docs/metrics.md must table every session key and every bench
    JSON field, and must not document keys that don't exist.
    """

    id = "R8"
    name = "metrics-key-sync"
    description = ("session.last_metrics keys / bench JSON fields / "
                   "docs/metrics.md out of sync")

    DOC = "docs/metrics.md"

    def check_project(self, files: Sequence[SourceFile],
                      repo_root: str) -> Iterator[Finding]:
        by_path = {sf.path: sf for sf in files}
        session = by_path.get("spark_rapids_tpu/session.py")
        bench = by_path.get("bench.py")
        if session is None:
            return

        session_keys: Dict[str, int] = {}
        for node in ast.walk(session.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Attribute) and \
                            t.value.attr == "last_metrics":
                        k = str_const(t.slice)
                        if k is not None:
                            session_keys[k] = node.lineno

        bench_reads: Dict[str, int] = {}
        bench_fields: Dict[str, int] = {}
        if bench is not None:
            for node in ast.walk(bench.tree):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "get" and node.args:
                    k = str_const(node.args[0])
                    if k and _CAMEL_RE.match(k):
                        bench_reads[k] = node.lineno
                elif isinstance(node, ast.Subscript):
                    k = str_const(node.slice)
                    if k and _CAMEL_RE.match(k):
                        bench_reads[k] = node.lineno
                elif isinstance(node, ast.Dict):
                    keys = [str_const(k) for k in node.keys
                            if k is not None]
                    keyset = {k for k in keys if k}
                    # the econ dict and the benchmark record dict are the
                    # two shipped JSON surfaces
                    if "compile_s" in keyset or "vs_baseline" in keyset:
                        for kn in node.keys:
                            k = str_const(kn) if kn is not None else None
                            if k:
                                bench_fields[k] = kn.lineno

        for k, line in sorted(bench_reads.items()):
            if k not in session_keys:
                yield Finding(
                    self.id, "bench.py", line,
                    f"bench reads session metric `{k}` which "
                    "session.execute never sets — it silently reads the "
                    "default forever", self.severity)

        doc_path = os.path.join(repo_root, self.DOC)
        if not os.path.exists(doc_path):
            yield Finding(
                self.id, self.DOC, 0,
                f"{self.DOC} is missing: the metrics contract "
                "(session.last_metrics keys + bench JSON fields) must "
                "be documented there", self.severity)
            return
        with open(doc_path, encoding="utf-8") as f:
            doc_lines = f.read().splitlines()
        doc_tokens: Dict[str, int] = {}
        for i, ln in enumerate(doc_lines, start=1):
            m = _DOC_TOKEN_RE.match(ln.strip())
            if m:
                doc_tokens[m.group(1)] = i

        for k, line in sorted(session_keys.items()):
            if k not in doc_tokens:
                yield Finding(
                    self.id, "spark_rapids_tpu/session.py", line,
                    f"session.last_metrics key `{k}` is undocumented in "
                    f"{self.DOC}", self.severity)
        for k, line in sorted(bench_fields.items()):
            if k not in doc_tokens:
                yield Finding(
                    self.id, "bench.py", line,
                    f"bench JSON field `{k}` is undocumented in "
                    f"{self.DOC}", self.severity)
        known = set(session_keys) | set(bench_fields)
        for k, line in sorted(doc_tokens.items()):
            if k not in known:
                yield Finding(
                    self.id, self.DOC, line,
                    f"{self.DOC} documents `{k}` but neither "
                    "session.last_metrics nor bench.py produces it",
                    self.severity)


class PallasKernelTierRule(Rule):
    """R9: every ``pl.pallas_call`` lives in the kernel tier.

    A bare ``pallas_call`` outside ``kernels/pallas_tier.py`` /
    ``kernels/pallas_strings.py`` bypasses the tier's contract: no conf
    gate, no TPU/interpret backend predicate, no automatic bit-identical
    XLA fallback, no ``pallas`` obs span for rapidsprof, and no
    ``pallasFallbackCount`` accounting — a kernel that fails to lower
    then kills the query instead of degrading.
    """

    id = "R9"
    name = "pallas-kernel-tier"
    description = ("pl.pallas_call outside the registered kernel tier "
                   "(kernels/pallas_tier.py, kernels/pallas_strings.py)")

    ALLOWED_FILES = (
        "spark_rapids_tpu/kernels/pallas_tier.py",
        "spark_rapids_tpu/kernels/pallas_strings.py",
    )

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        if sf.path in self.ALLOWED_FILES:
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name == "pallas_call" or name.endswith(".pallas_call"):
                yield self.finding(
                    sf, node,
                    f"`{name}` outside the kernel tier: route through "
                    "kernels.pallas_tier.run (conf gate, backend "
                    "predicate, bit-identical XLA fallback, `pallas` obs "
                    "span, pallasFallbackCount metric)")


ALL_RULES = (
    ImportTimeJnpRule,
    SemaphoreReleaseRule,
    UnboundedWaitRule,
    SwallowBaseExceptionRule,
    DonationHygieneRule,
    SyncUnderRuntimeLockRule,
    ConfRegistrySyncRule,
    MetricsKeySyncRule,
    PallasKernelTierRule,
)


def default_rules() -> List[Rule]:
    return [cls() for cls in ALL_RULES]
