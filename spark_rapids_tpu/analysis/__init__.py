"""Static analysis: project-specific lints + plan-invariant verification.

The reference spark-rapids design treats detection machinery as co-equal
with the kernels: unsupported or unsafe constructs are *tagged and
flagged*, never silently executed (SURVEY.md §1, the GpuOverrides
tagging/fallback model).  This package applies the same philosophy to the
engine's own source: every recurring bug class that past PRs burned
debugging time on — tracer-leaking module constants, orphaned semaphore
permits, watchdog-defeating unbounded waits, swallowed
KeyboardInterrupt, unaccounted donation, config/metrics drift — is
mechanically detectable from the AST, so ``rapidslint`` makes the class
extinct instead of re-fixed.

Layout:

* :mod:`~spark_rapids_tpu.analysis.engine` — rule framework: file
  loading, per-line ``# rapidslint: disable=<id>`` suppressions, the
  checked-in baseline (``tools/rapidslint_baseline.json``), finding
  fingerprints that survive line drift.
* :mod:`~spark_rapids_tpu.analysis.rules` — the project rule catalog
  (R1..R8), each distilled from a real incident (docs/static_analysis.md
  maps rule -> incident).
* :mod:`~spark_rapids_tpu.analysis.plan_verify` — runtime plan-invariant
  verifier: schema consistency across operator boundaries,
  donation-mask provenance, semaphore/catalog balance.  Wired into every
  tier-1 query via tests/conftest.py behind ``RAPIDS_PLAN_VERIFY=1``.

Entry point: ``tools/rapidslint.py --check`` (the CI lint gate).
"""

from .engine import (  # noqa: F401
    Finding, LintEngine, Rule, Severity,
)
