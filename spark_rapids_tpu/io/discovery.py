"""Path expansion + schema inference for file sources."""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List

import pyarrow as pa

from spark_rapids_tpu import types as T

_EXTS = {"parquet": (".parquet", ".parq"), "csv": (".csv",),
         "orc": (".orc",)}


def expand_paths(paths: List[str], fmt: str) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                for n in sorted(names):
                    if n.startswith(("_", ".")):
                        continue
                    if n.endswith(_EXTS.get(fmt, ())):
                        files.append(os.path.join(root, n))
        elif any(ch in p for ch in "*?["):
            files.extend(sorted(glob.glob(p)))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no {fmt} files found in {paths}")
    return files


def infer_schema(fmt: str, files: List[str],
                 options: Dict[str, Any]) -> T.Schema:
    from spark_rapids_tpu.io.arrow_convert import schema_from_arrow
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return schema_from_arrow(pq.read_schema(files[0]))
    if fmt == "orc":
        import pyarrow.orc as orc
        return schema_from_arrow(orc.ORCFile(files[0]).schema)
    if fmt == "csv":
        import pyarrow.csv as pacsv
        read_opts, parse_opts, conv_opts = csv_options(options)
        tb = pacsv.read_csv(files[0], read_options=read_opts,
                            parse_options=parse_opts,
                            convert_options=conv_opts)
        return schema_from_arrow(tb.schema)
    raise ValueError(f"unknown format {fmt}")


def csv_options(options: Dict[str, Any]):
    import pyarrow.csv as pacsv
    header = str(options.get("header", "true")).lower() == "true"
    sep = options.get("sep", options.get("delimiter", ","))
    read_opts = pacsv.ReadOptions(
        autogenerate_column_names=not header)
    parse_opts = pacsv.ParseOptions(delimiter=sep)
    conv_opts = pacsv.ConvertOptions(
        null_values=[options.get("nullValue", "")],
        strings_can_be_null=True)
    return read_opts, parse_opts, conv_opts
