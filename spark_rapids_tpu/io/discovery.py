"""Path expansion + schema inference for file sources."""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List

import pyarrow as pa

from spark_rapids_tpu import types as T

_EXTS = {"parquet": (".parquet", ".parq"), "csv": (".csv",),
         "orc": (".orc",)}


def expand_paths(paths: List[str], fmt: str) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                for n in sorted(names):
                    if n.startswith(("_", ".")):
                        continue
                    if n.endswith(_EXTS.get(fmt, ())):
                        files.append(os.path.join(root, n))
        elif any(ch in p for ch in "*?["):
            files.extend(sorted(glob.glob(p)))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no {fmt} files found in {paths}")
    return files


HIVE_DEFAULT_PARTITION = "__HIVE_DEFAULT_PARTITION__"


def discover_partitions(paths: List[str], files: List[str]):
    """Hive-layout partition discovery: ``key=value`` directory components
    under the scan roots become partition columns
    (ColumnarPartitionReaderWithPartitionValues.scala /
    PartitioningAwareFileIndex role).

    Returns (partition_schema, {file: [typed values...]}) or None when the
    layout is not a consistent key=value tree.
    """
    roots = [os.path.abspath(p) for p in paths if os.path.isdir(p)]
    if not roots:
        return None
    keys_order: List[str] = None
    raw: Dict[str, List[str]] = {}
    for f in files:
        af = os.path.abspath(f)
        comps = None
        for r in roots:
            if af.startswith(r + os.sep):
                rel = os.path.relpath(os.path.dirname(af), r)
                comps = [] if rel == "." else rel.split(os.sep)
                break
        if comps is None:
            return None
        kv = []
        for c in comps:
            if "=" not in c:
                return None
            k, _, v = c.partition("=")
            kv.append((k, v))
        ks = [k for k, _ in kv]
        if keys_order is None:
            keys_order = ks
        elif keys_order != ks:
            return None
        raw[f] = [v for _, v in kv]
    if not keys_order:
        return None

    # per-key type inference (Spark: numeric partition values -> numbers)
    def typed(values: List[str]):
        non_null = [v for v in values if v != HIVE_DEFAULT_PARTITION]
        try:
            [int(v) for v in non_null]
            return T.LONG, (lambda v: None if v == HIVE_DEFAULT_PARTITION
                            else int(v))
        except ValueError:
            pass
        try:
            [float(v) for v in non_null]
            return T.DOUBLE, (lambda v: None if v == HIVE_DEFAULT_PARTITION
                              else float(v))
        except ValueError:
            return T.STRING, (lambda v: None if v == HIVE_DEFAULT_PARTITION
                              else v)

    fields, convs = [], []
    for i, k in enumerate(keys_order):
        dt, conv = typed([raw[f][i] for f in files])
        fields.append(T.Field(k, dt))
        convs.append(conv)
    file_values = {
        f: [conv(v) for conv, v in zip(convs, raw[f])] for f in files}
    return T.Schema(fields), file_values


def infer_schema(fmt: str, files: List[str],
                 options: Dict[str, Any]) -> T.Schema:
    from spark_rapids_tpu.io.arrow_convert import schema_from_arrow
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return schema_from_arrow(pq.read_schema(files[0]))
    if fmt == "orc":
        import pyarrow.orc as orc
        return schema_from_arrow(orc.ORCFile(files[0]).schema)
    if fmt == "csv":
        import pyarrow.csv as pacsv
        read_opts, parse_opts, conv_opts = csv_options(options)
        tb = pacsv.read_csv(files[0], read_options=read_opts,
                            parse_options=parse_opts,
                            convert_options=conv_opts)
        return schema_from_arrow(tb.schema)
    raise ValueError(f"unknown format {fmt}")


def csv_options(options: Dict[str, Any]):
    import pyarrow.csv as pacsv
    header = str(options.get("header", "true")).lower() == "true"
    sep = options.get("sep", options.get("delimiter", ","))
    read_opts = pacsv.ReadOptions(
        autogenerate_column_names=not header)
    parse_opts = pacsv.ParseOptions(delimiter=sep)
    conv_opts = pacsv.ConvertOptions(
        null_values=[options.get("nullValue", "")],
        strings_can_be_null=True)
    return read_opts, parse_opts, conv_opts
