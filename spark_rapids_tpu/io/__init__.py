"""File-format IO: host-side decode (pyarrow) staged into HBM, and columnar
writers (reference: GpuParquetScan.scala, GpuOrcScan.scala,
GpuBatchScanExec.scala CSV, writers — SURVEY.md section 2.6).

TPU adaptation (SURVEY.md section 2.9): a TPU cannot decode parquet on
device the way cudf does on GPU, so decode runs on host threads
(multi-threaded read-ahead, the MultiFileParquetPartitionReader analogue)
and dense columns are staged asynchronously into device memory."""
