"""Process-shared file-decode thread pool.

The v1 scan created a ThreadPoolExecutor per ``partitions()`` call and
never shut it down — every query (and every serve tenant) leaked
``multiThreadedRead.numThreads`` threads for the process lifetime.  All
scans now share ONE pool, grown to the largest thread count any scan has
requested, with a deterministic bounded shutdown registered at exit (the
MultiFileReaderThreadPool role, GpuMultiFileReader.scala).
"""

from __future__ import annotations

import atexit
import collections
import concurrent.futures
import os
import threading
from typing import Callable, Optional

_lock = threading.Lock()
_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
_pool_size = 0

# -- per-thread reader handle cache ------------------------------------------
#
# Every chunk task used to open its own pyarrow reader: the footer /
# stripe-index parse repeats per row group of the same file, and the
# comment in scan_v2 ("ParquetFile is not safe for concurrent reads")
# only forbids CROSS-thread sharing.  Readers are therefore cached
# per (thread, kind, path) — each pool worker reuses its own handle and
# never shares it — with a bounded LRU that closes the evicted reader
# (scan.fileHandleCache.size handles per thread; 0 disables).

_tls = threading.local()
_cache_hits = 0
_cache_misses = 0


def cached_reader(kind: str, path: str, factory: Callable[[], object],
                  cache_size: int):
    """Open-or-reuse a file reader for this thread.  ``kind`` keys reader
    variants of the same path apart (e.g. parquet with/without
    ``read_dictionary``)."""
    global _cache_hits, _cache_misses
    if cache_size <= 0:
        return factory()
    cache = getattr(_tls, "readers", None)
    if cache is None:
        cache = _tls.readers = collections.OrderedDict()
    # mtime+size in the key: a rewritten file misses instead of serving a
    # stale footer; the dead handle ages out of the LRU
    try:
        st = os.stat(path)
        key = (kind, path, st.st_mtime_ns, st.st_size)
    except OSError:
        return factory()
    r = cache.get(key)
    if r is not None:
        cache.move_to_end(key)
        with _lock:
            _cache_hits += 1
        return r
    r = factory()
    cache[key] = r
    with _lock:
        _cache_misses += 1
    while len(cache) > cache_size:
        _k, old = cache.popitem(last=False)
        close = getattr(old, "close", None)
        if close is not None:
            try:
                close()
            except OSError:
                pass  # eviction is best-effort; the reader is unreferenced
    return r


def reader_cache_stats():
    """(hits, misses) across all threads — tests and telemetry."""
    with _lock:
        return _cache_hits, _cache_misses


def clear_reader_cache() -> None:
    """Drop THIS thread's cached readers (closing them) and zero the
    shared counters.  Tests call this for isolation; pool workers keep
    their caches for the thread lifetime."""
    global _cache_hits, _cache_misses
    cache = getattr(_tls, "readers", None)
    if cache:
        for old in cache.values():
            close = getattr(old, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass
        cache.clear()
    with _lock:
        _cache_hits = 0
        _cache_misses = 0


def get_decode_pool(nthreads: int) -> concurrent.futures.ThreadPoolExecutor:
    """The shared decode pool, grown (never shrunk) to ``nthreads``."""
    global _pool, _pool_size
    nthreads = max(1, int(nthreads))
    with _lock:
        if _pool is not None and _pool_size >= nthreads:
            return _pool
        old = _pool
        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(nthreads, _pool_size),
            thread_name_prefix="rapids-decode")
        _pool_size = max(nthreads, _pool_size)
    if old is not None:
        _shutdown(old)
    return _pool


def decode_pool_size() -> int:
    """Current worker count (0 when no pool has been created)."""
    with _lock:
        return _pool_size if _pool is not None else 0


def decode_pool_utilization() -> float:
    """Queued-work backlog as a fraction of the pool size — the
    ``io.decode_pool_utilization`` telemetry gauge (0.0 when no pool
    exists; executors without an inspectable work queue read as idle)."""
    with _lock:
        pool, size = _pool, _pool_size
    if pool is None or size <= 0:
        return 0.0
    try:
        return pool._work_queue.qsize() / float(size)
    except AttributeError:
        return 0.0


def _shutdown(pool: concurrent.futures.ThreadPoolExecutor,
              timeout: float = 5.0) -> None:
    # shutdown(wait=True) joins without a bound; reap each worker with a
    # per-thread timeout instead so a wedged decode can't hang exit.
    pool.shutdown(wait=False)
    for t in list(getattr(pool, "_threads", ())):
        t.join(timeout=timeout)


def shutdown_decode_pool(timeout: float = 5.0) -> None:
    """Deterministically stop the shared pool (idempotent; tests + atexit)."""
    global _pool, _pool_size
    with _lock:
        pool = _pool
        _pool = None
        _pool_size = 0
    if pool is not None:
        _shutdown(pool, timeout)


atexit.register(shutdown_decode_pool)
