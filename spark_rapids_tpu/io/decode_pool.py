"""Process-shared file-decode thread pool.

The v1 scan created a ThreadPoolExecutor per ``partitions()`` call and
never shut it down — every query (and every serve tenant) leaked
``multiThreadedRead.numThreads`` threads for the process lifetime.  All
scans now share ONE pool, grown to the largest thread count any scan has
requested, with a deterministic bounded shutdown registered at exit (the
MultiFileReaderThreadPool role, GpuMultiFileReader.scala).
"""

from __future__ import annotations

import atexit
import concurrent.futures
import threading
from typing import Optional

_lock = threading.Lock()
_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
_pool_size = 0


def get_decode_pool(nthreads: int) -> concurrent.futures.ThreadPoolExecutor:
    """The shared decode pool, grown (never shrunk) to ``nthreads``."""
    global _pool, _pool_size
    nthreads = max(1, int(nthreads))
    with _lock:
        if _pool is not None and _pool_size >= nthreads:
            return _pool
        old = _pool
        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(nthreads, _pool_size),
            thread_name_prefix="rapids-decode")
        _pool_size = max(nthreads, _pool_size)
    if old is not None:
        _shutdown(old)
    return _pool


def decode_pool_size() -> int:
    """Current worker count (0 when no pool has been created)."""
    with _lock:
        return _pool_size if _pool is not None else 0


def decode_pool_utilization() -> float:
    """Queued-work backlog as a fraction of the pool size — the
    ``io.decode_pool_utilization`` telemetry gauge (0.0 when no pool
    exists; executors without an inspectable work queue read as idle)."""
    with _lock:
        pool, size = _pool, _pool_size
    if pool is None or size <= 0:
        return 0.0
    try:
        return pool._work_queue.qsize() / float(size)
    except AttributeError:
        return 0.0


def _shutdown(pool: concurrent.futures.ThreadPoolExecutor,
              timeout: float = 5.0) -> None:
    # shutdown(wait=True) joins without a bound; reap each worker with a
    # per-thread timeout instead so a wedged decode can't hang exit.
    pool.shutdown(wait=False)
    for t in list(getattr(pool, "_threads", ())):
        t.join(timeout=timeout)


def shutdown_decode_pool(timeout: float = 5.0) -> None:
    """Deterministically stop the shared pool (idempotent; tests + atexit)."""
    global _pool, _pool_size
    with _lock:
        pool = _pool
        _pool = None
        _pool_size = 0
    if pool is not None:
        _shutdown(pool, timeout)


atexit.register(shutdown_decode_pool)
