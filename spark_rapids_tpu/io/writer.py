"""Columnar writers (GpuParquetFileFormat / GpuOrcFileFormat /
ColumnarOutputWriter analogues, SURVEY.md section 2.6): one output file per
partition, written host-side from staged batches via Arrow."""

from __future__ import annotations

import os
import shutil
from typing import List

from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow


def _prepare_dir(path: str, mode: str):
    if os.path.exists(path):
        if mode == "overwrite":
            shutil.rmtree(path)
        elif mode in ("error", "errorifexists"):
            raise FileExistsError(path)
        elif mode == "ignore":
            return False
    os.makedirs(path, exist_ok=True)
    return True


def write_dataframe(df, fmt: str, path: str, mode: str = "error"):
    """Execute the plan and write one file per partition."""
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    from spark_rapids_tpu.plan.physical import (
        DeviceToHostExec, ExecContext,
    )
    if not _prepare_dir(path, mode):
        return
    session = df.session
    overrides = TpuOverrides(session.conf)
    phys = overrides.apply(df.plan)
    if phys.is_tpu:
        phys = DeviceToHostExec(phys)
    ctx = ExecContext(
        session.conf,
        semaphore=session.runtime.semaphore if session.runtime else None,
        device=session.runtime.device if session.runtime else None)
    wrote = 0
    for pi, part in enumerate(phys.partitions(ctx)):
        batches: List[HostBatch] = [hb for hb in part if hb.num_rows]
        if not batches:
            continue
        hb = HostBatch.concat(batches)
        table = host_batch_to_arrow(hb)
        fname = os.path.join(path, f"part-{pi:05d}.{_ext(fmt)}")
        if fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, fname)
        elif fmt == "orc":
            import pyarrow.orc as paorc
            paorc.write_table(table, fname)
        elif fmt == "csv":
            import pyarrow.csv as pacsv
            pacsv.write_csv(table, fname)
        else:
            raise ValueError(fmt)
        wrote += 1
    if wrote == 0:
        # still write an empty marker file with the schema for parquet
        if fmt == "parquet":
            import pyarrow.parquet as pq
            empty = host_batch_to_arrow(HostBatch(df.plan.schema, [
                _empty_col(f) for f in df.plan.schema.fields]))
            pq.write_table(empty,
                           os.path.join(path, f"part-00000.parquet"))
    open(os.path.join(path, "_SUCCESS"), "w").close()


def _empty_col(f):
    import numpy as np
    from spark_rapids_tpu.batch import HostColumn
    vals = np.zeros(0, dtype=object if f.dtype.is_string else f.dtype.np_dtype)
    return HostColumn(f.dtype, vals, np.zeros(0, dtype=np.bool_))


def _ext(fmt: str) -> str:
    return {"parquet": "parquet", "orc": "orc", "csv": "csv"}[fmt]
