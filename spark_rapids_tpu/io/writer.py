"""Columnar writers (GpuParquetFileFormat / GpuOrcFileFormat /
ColumnarOutputWriter analogues, SURVEY.md section 2.6): one output file per
partition, written host-side from staged batches via Arrow."""

from __future__ import annotations

import os
import shutil
from typing import List

from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow


def _prepare_dir(path: str, mode: str):
    if os.path.exists(path):
        if mode == "overwrite":
            shutil.rmtree(path)
        elif mode in ("error", "errorifexists"):
            raise FileExistsError(path)
        elif mode == "ignore":
            return False
    os.makedirs(path, exist_ok=True)
    return True


def _write_table(table, fmt: str, fname: str):
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, fname)
    elif fmt == "orc":
        import pyarrow.orc as paorc
        paorc.write_table(table, fname)
    elif fmt == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(table, fname)
    else:
        raise ValueError(fmt)


def write_dataframe(df, fmt: str, path: str, mode: str = "error",
                    partition_by=None):
    """Execute the plan and write one file per partition.

    ``partition_by``: column names for dynamic-partition output
    (key=value subdirectories — the GpuDynamicPartitionDataWriter role,
    GpuFileFormatDataWriter.scala).  Returns write stats
    (BasicColumnarWriteStatsTracker analogue): {num_files, num_rows,
    num_bytes, partitions}.
    """
    from spark_rapids_tpu.plan.physical import (
        DeviceToHostExec, ExecContext,
    )
    if not _prepare_dir(path, mode):
        return {"num_files": 0, "num_rows": 0, "num_bytes": 0,
                "partitions": 0}
    session = df.session
    phys = session.plan_physical(df.plan)
    if phys.is_tpu:
        phys = DeviceToHostExec(phys)
    ctx = ExecContext(
        session.conf,
        semaphore=session.runtime.semaphore if session.runtime else None,
        device=session.runtime.device if session.runtime else None)
    stats = {"num_files": 0, "num_rows": 0, "num_bytes": 0, "partitions": 0}
    part_dirs = set()
    try:
        for pi, part in enumerate(phys.partitions(ctx)):
            batches: List[HostBatch] = [hb for hb in part if hb.num_rows]
            if not batches:
                continue
            hb = HostBatch.concat(batches)
            if partition_by:
                _write_partitioned(hb, fmt, path, pi, partition_by, stats,
                                   part_dirs)
                continue
            table = host_batch_to_arrow(hb)
            fname = os.path.join(path, f"part-{pi:05d}.{_ext(fmt)}")
            _write_table(table, fname=fname, fmt=fmt)
            stats["num_files"] += 1
            stats["num_rows"] += hb.num_rows
            stats["num_bytes"] += os.path.getsize(fname)
    finally:
        ctx.close_deferred()
    stats["partitions"] = len(part_dirs)
    if stats["num_files"] == 0 and fmt == "parquet" and not partition_by:
        # still write an empty file carrying the schema
        import pyarrow.parquet as pq
        empty = host_batch_to_arrow(HostBatch(df.plan.schema, [
            _empty_col(f) for f in df.plan.schema.fields]))
        fname = os.path.join(path, "part-00000.parquet")
        pq.write_table(empty, fname)
        stats["num_files"] = 1
    open(os.path.join(path, "_SUCCESS"), "w").close()
    return stats


def _write_partitioned(hb: HostBatch, fmt: str, path: str, pi: int,
                       partition_by, stats, part_dirs):
    """Dynamic-partition write: group rows by the partition-column values,
    one file per (task partition, value combination)."""
    import numpy as np

    from spark_rapids_tpu.batch import HostColumn
    key_idx = [hb.schema.index_of(c) for c in partition_by]
    data_fields = [f for f in hb.schema.fields
                   if f.name not in set(partition_by)]
    key_cols = [hb.columns[i].to_list() for i in key_idx]
    rows_by_key = {}
    for r in range(hb.num_rows):
        k = tuple(col[r] for col in key_cols)
        rows_by_key.setdefault(k, []).append(r)
    from spark_rapids_tpu import types as T
    for k, rows in rows_by_key.items():
        sub_dir = os.path.join(path, *[
            f"{name}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
            for name, v in zip(partition_by, k)])
        os.makedirs(sub_dir, exist_ok=True)
        part_dirs.add(sub_dir)
        idx = np.asarray(rows)
        cols = []
        for f in data_fields:
            c = hb.columns[hb.schema.index_of(f.name)]
            cols.append(HostColumn(f.dtype, c.values[idx], c.validity[idx]))
        sub = HostBatch(T.Schema(data_fields), cols)
        fname = os.path.join(sub_dir, f"part-{pi:05d}.{_ext(fmt)}")
        _write_table(host_batch_to_arrow(sub), fmt, fname)
        stats["num_files"] += 1
        stats["num_rows"] += sub.num_rows
        stats["num_bytes"] += os.path.getsize(fname)


def _empty_col(f):
    import numpy as np
    from spark_rapids_tpu.batch import HostColumn
    vals = np.zeros(0, dtype=object if f.dtype.is_string else f.dtype.np_dtype)
    return HostColumn(f.dtype, vals, np.zeros(0, dtype=np.bool_))


def _ext(fmt: str) -> str:
    return {"parquet": "parquet", "orc": "orc", "csv": "csv"}[fmt]
