"""Arrow <-> HostBatch conversion (the JCudfSerialization/host-buffer staging
analogue — Arrow is the interchange layer the TPU build standardizes on,
SURVEY.md section 7)."""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, HostColumn
from spark_rapids_tpu.obs import events as obs_events

_ARROW_TO_TYPE = {
    pa.bool_(): T.BOOLEAN,
    pa.int8(): T.BYTE,
    pa.int16(): T.SHORT,
    pa.int32(): T.INT,
    pa.int64(): T.LONG,
    pa.float32(): T.FLOAT,
    pa.float64(): T.DOUBLE,
    pa.date32(): T.DATE,
    pa.string(): T.STRING,
    pa.large_string(): T.STRING,
}


def arrow_type_to_sql(at: pa.DataType) -> T.DataType:
    if at in _ARROW_TO_TYPE:
        return _ARROW_TO_TYPE[at]
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_dictionary(at):
        return arrow_type_to_sql(at.value_type)
    raise TypeError(f"unsupported arrow type {at}")


def sql_type_to_arrow(dt: T.DataType) -> pa.DataType:
    for a, s in _ARROW_TO_TYPE.items():
        if s == dt and a != pa.large_string():
            return a
    if dt == T.TIMESTAMP:
        return pa.timestamp("us", tz="UTC")
    raise TypeError(f"unsupported sql type {dt}")


def schema_from_arrow(asch: pa.Schema) -> T.Schema:
    return T.Schema([
        T.Field(f.name, arrow_type_to_sql(f.type), f.nullable)
        for f in asch
    ])


def _dict_host_column(f: T.Field, arr: "pa.DictionaryArray") -> HostColumn:
    """Preserve an Arrow dictionary string array as (int64 codes, object
    dictionary): H2D then moves 4-byte indices per row instead of string
    bytes, and the dictionary's bytes move once."""
    validity = np.ones(len(arr), dtype=np.bool_) if arr.null_count == 0 \
        else np.asarray(arr.is_valid())
    codes = arr.indices.to_numpy(zero_copy_only=False)
    codes = np.where(validity, np.nan_to_num(codes), 0).astype(np.int64)
    entries = np.array(
        ["" if v is None else v for v in arr.dictionary.to_pylist()],
        dtype=object)
    if not len(entries):
        entries = np.array([""], dtype=object)
    return HostColumn(f.dtype, codes, validity, entries)


def arrow_to_host_batch(table_or_batch, schema: Optional[T.Schema] = None,
                        keep_dictionary: bool = False) -> HostBatch:
    t0 = time.monotonic_ns()
    tb = table_or_batch
    if isinstance(tb, pa.Table):
        tb = tb.combine_chunks()
    if schema is None:
        schema = schema_from_arrow(tb.schema)
    cols: List[HostColumn] = []
    for f, name in zip(schema.fields, tb.schema.names):
        arr = tb.column(name)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks() if arr.num_chunks != 1 else \
                arr.chunk(0)
        if pa.types.is_dictionary(arr.type):
            if keep_dictionary and f.dtype.is_string:
                cols.append(_dict_host_column(f, arr))
                continue
            arr = arr.dictionary_decode()
        null_free = arr.null_count == 0
        # null-free columns skip the bit-unpacking is_valid() pass
        validity = np.ones(len(arr), dtype=np.bool_) if null_free \
            else np.asarray(arr.is_valid())
        if f.dtype.is_string:
            values = np.array(
                ["" if v is None else v for v in arr.to_pylist()],
                dtype=object)
        elif f.dtype == T.TIMESTAMP:
            arr2 = arr.cast(pa.timestamp("us"))
            values = np.nan_to_num(
                arr2.to_numpy(zero_copy_only=False)).astype(
                "datetime64[us]").astype(np.int64)
            values = np.where(validity, values, 0).astype(np.int64)
        else:
            values = None
            if null_free:
                # zero-copy view over the arrow buffer for contiguous
                # null-free numerics: the scan's read-ahead then feeds H2D
                # staging without an intermediate host copy (bit-packed
                # bools and anything non-contiguous raise and fall through)
                try:
                    values = arr.to_numpy(zero_copy_only=True)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                    values = None
            if values is None:
                values = arr.to_numpy(zero_copy_only=False)
            if values.dtype.kind == "f" and not f.dtype.is_fractional:
                # arrow promotes nullable ints to float NaN; undo it
                values = np.where(validity, np.nan_to_num(values), 0)
            values = values.astype(f.dtype.np_dtype, copy=False)
        cols.append(HostColumn(f.dtype, values, validity))
    hb = HostBatch(schema, cols)
    obs_events.emit_span("io", "arrow_convert", t0=t0,
                         t1=time.monotonic_ns(), rows=tb.num_rows,
                         columns=len(cols))
    return hb


def host_batch_to_arrow(hb: HostBatch) -> pa.Table:
    arrays = []
    names = []
    for f, c in zip(hb.schema.fields, hb.columns):
        names.append(f.name)
        vals = c.to_list()
        at = sql_type_to_arrow(f.dtype)
        if f.dtype == T.TIMESTAMP:
            arrays.append(pa.array(
                [None if v is None else int(v) for v in vals],
                type=pa.int64()).cast(pa.timestamp("us", tz="UTC")))
        elif f.dtype == T.DATE:
            arrays.append(pa.array(
                [None if v is None else int(v) for v in vals],
                type=pa.int32()).cast(pa.date32()))
        else:
            arrays.append(pa.array(vals, type=at))
    return pa.table(dict(zip(names, arrays)))
