"""Scan v2: chunk-granular parallel decode with bounded read-ahead,
dictionary-preserving string decode and chunk-level late materialization
(docs/io.md; the MultiFileParquetPartitionReader shape,
GpuParquetScan.scala:647-700, rebuilt for the host-decode TPU pipeline).

v1 decodes whole files serially on one pool thread per file and
materializes every HostBatch before the first H2D transfer.  v2 splits the
decode at parquet row-group / ORC stripe granularity, runs chunks on the
process-shared decode pool (io.decode_pool) and yields them through an
ordered sliding window of ``scan.readAhead.depth`` in-flight futures — so
decode of chunks k+1..k+depth overlaps the consumer's H2D staging and
device compute of chunk k, while output order stays deterministic
(submission order, for bit parity with v1).

Late materialization (``scan.lateMaterialization.enabled``): when
conjuncts were pushed, each chunk first decodes ONLY the predicate
columns present in the file and evaluates the conjuncts exactly; chunks
with no surviving row skip the decode of every remaining projected
column.  The Filter above the scan re-applies the predicate, so the skip
is chunk-granular and bit-exact.

Dictionary encoding (``scan.dictEncoding.enabled``): when the consumer is
H2D staging (HostToDeviceExec's ``set_device_consumer`` handshake),
parquet string columns are decoded with Arrow dictionary preservation and
emitted as (codes, dictionary) HostColumns — the transfer moves integer
codes per row plus the dictionary's bytes once, and device kernels that
only need lengths/hashes/prefixes (string equality, group keys) never
touch the raw bytes (exprs.strings / kernels.sortkeys dict paths).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Iterable, List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, host_batch_bytes
from spark_rapids_tpu.config import (
    SCAN_DICT_ENCODING_ENABLED, SCAN_FILE_HANDLE_CACHE_SIZE,
    SCAN_LATE_MAT_ENABLED, SCAN_PAGE_CHUNK_MIN_BYTES,
    SCAN_READAHEAD_ADAPTIVE, SCAN_READAHEAD_DEPTH, SCAN_READAHEAD_MAX_DEPTH,
    RapidsConf,
)
from spark_rapids_tpu.fault import inject
from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch
from spark_rapids_tpu.io.decode_pool import (
    cached_reader, decode_pool_utilization, get_decode_pool,
)
from spark_rapids_tpu.io.discovery import csv_options
from spark_rapids_tpu.io.scan import CpuFileScanExec, _row_group_can_match
from spark_rapids_tpu.obs import events as obs_events
from spark_rapids_tpu.obs import timeseries as obs_ts
from spark_rapids_tpu.plan.physical import ExecContext

#: Decoded-and-ready chunks held beyond the one being consumed — chunk k
#: on device, k+1 staged on host, k+2..k+1+depth decoding: the classic
#: triple buffer, with the decode window as the third stage.
_READY_BUF = 2

#: Drains between adaptive read-ahead adjustments (smooths the
#: blocked-fraction signal over a few chunks).
_ADAPT_EVERY = 4


@dataclasses.dataclass
class _ChunkResult:
    """One decoded (or skipped) chunk, in submission order."""

    batches: List[HostBatch]
    decode_ns: int = 0
    bytes_decoded: int = 0
    skipped: bool = False       # late-mat: no row can survive the conjuncts
    rg_total: int = 0
    rg_read: int = 0
    dict_columns: int = 0
    label: str = ""
    t0: int = 0                 # worker-side decode window (monotonic ns)
    t1: int = 0


def _chunk_survivors(descriptors, table) -> bool:
    """Exact chunk-level survival: does ANY row satisfy every pushed
    conjunct?  Evaluated with plain numpy comparisons — the same IEEE
    semantics the device Filter applies — so a skipped chunk can never
    contain a row the Filter would have kept."""
    import pyarrow as pa
    mask: Optional[np.ndarray] = None
    for name, op, value in descriptors:
        if name not in table.schema.names:
            continue
        arr = table.column(name)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks() if arr.num_chunks != 1 else \
                arr.chunk(0)
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        valid = np.ones(len(arr), dtype=np.bool_) if arr.null_count == 0 \
            else np.asarray(arr.is_valid())
        if op == "notnull":
            m = valid
        else:
            try:
                if pa.types.is_string(arr.type) or \
                        pa.types.is_large_string(arr.type):
                    vals = np.array(
                        ["" if v is None else v for v in arr.to_pylist()],
                        dtype=object)
                else:
                    vals = arr.to_numpy(zero_copy_only=False)
                cmp = {"eq": np.equal, "lt": np.less, "le": np.less_equal,
                       "gt": np.greater, "ge": np.greater_equal}[op]
                with np.errstate(invalid="ignore"):
                    m = valid & np.asarray(cmp(vals, value), dtype=np.bool_)
            except (TypeError, ValueError):
                continue  # incomparable: conservatively keep the chunk
        mask = m if mask is None else (mask & m)
    return bool(mask.any()) if mask is not None else True


def _dict_candidate(t) -> bool:
    """String columns the encoded corridor can carry: plain strings (the
    scan requests read_dictionary) and columns whose restored arrow
    schema is ALREADY dictionary<string> (pyarrow round-trips the arrow
    schema through parquet metadata, so a file written from encoded
    arrays reads back dictionary-typed with no read_dictionary ask)."""
    import pyarrow as pa
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return True
    return pa.types.is_dictionary(t) and (
        pa.types.is_string(t.value_type) or
        pa.types.is_large_string(t.value_type))


class FileScanV2Exec(CpuFileScanExec):
    """Chunk-parallel scan with read-ahead, dictionary strings and late
    materialization; bit-parity with :class:`CpuFileScanExec`."""

    def __init__(self, node, conf: RapidsConf):
        super().__init__(node, conf)
        self._depth = max(1, SCAN_READAHEAD_DEPTH.get(conf))
        # the adaptive controller owns the depth UNLESS the user pinned
        # scan.readAhead.depth explicitly — static wins when set
        self._adaptive = (SCAN_READAHEAD_ADAPTIVE.get(conf) and
                          not conf.explicitly_set(SCAN_READAHEAD_DEPTH.key))
        self._max_depth = max(self._depth, SCAN_READAHEAD_MAX_DEPTH.get(conf))
        self._page_min_bytes = SCAN_PAGE_CHUNK_MIN_BYTES.get(conf)
        self._handle_cache = max(0, SCAN_FILE_HANDLE_CACHE_SIZE.get(conf))
        self._dict_enabled = SCAN_DICT_ENCODING_ENABLED.get(conf)
        self._late_mat = SCAN_LATE_MAT_ENABLED.get(conf)
        self._device_consumer = False

    def set_device_consumer(self) -> None:
        """Called by HostToDeviceExec: batches feed device staging, so
        dictionary-encoded string columns may be emitted."""
        self._device_consumer = True

    def _use_dict(self) -> bool:
        return self._device_consumer and self._dict_enabled

    def describe(self):
        flags = []
        if self.descriptors:
            flags.append(f"pushed={len(self.descriptors)}")
        if self._use_dict():
            flags.append("dict")
        if self._late_mat:
            flags.append("latemat")
        extra = (", " + ",".join(flags)) if flags else ""
        return (f"FileScanV2({self.fmt}, {len(self.paths)} files, "
                f"depth={self._depth}{extra})")

    # -- chunk planning ------------------------------------------------------

    def _file_columns(self) -> List[str]:
        part_fields = []
        if self.partitions_info is not None:
            part_fields = self.partitions_info[0].fields
        part_names = {f.name for f in part_fields}
        return [n for n in self.output_schema.names if n not in part_names]

    def _parquet_file(self, path: str, read_dict: Optional[List[str]] = None):
        import pyarrow.parquet as pq
        kind = "pq" if not read_dict else "pq+dict:" + ",".join(read_dict)
        if read_dict:
            return cached_reader(
                kind, path,
                lambda: pq.ParquetFile(path, read_dictionary=read_dict),
                self._handle_cache)
        return cached_reader(kind, path, lambda: pq.ParquetFile(path),
                             self._handle_cache)

    def _orc_file(self, path: str):
        import pyarrow.orc as orc
        return cached_reader("orc", path, lambda: orc.ORCFile(path),
                             self._handle_cache)

    def _plan_column_slabs(self, meta, rg: int, columns: List[str]
                           ) -> Optional[List[List[str]]]:
        """Page-level chunk granularity: split an OVERSIZED parquet row
        group into contiguous column slabs of >= scan.pageChunk.minBytes
        compressed bytes each, decoded as parallel pool tasks and zipped
        back column-wise by the consumer — one writer's giant row group
        stops serializing the whole pipeline behind a single decode
        thread.  Returns None (no split) for small row groups, single- or
        zero-column projections, and pushed-predicate scans (slabs would
        re-run the survival probe per slab)."""
        if self._page_min_bytes <= 0 or self.descriptors or \
                len(columns) < 2:
            return None
        rgm = meta.row_group(rg)
        sizes = {}
        for i in range(rgm.num_columns):
            c = rgm.column(i)
            name = c.path_in_schema.split(".")[0]
            sizes[name] = sizes.get(name, 0) + c.total_compressed_size
        total = sum(sizes.get(n, 0) for n in columns)
        if total < 2 * self._page_min_bytes:
            return None
        n_slabs = min(len(columns), total // self._page_min_bytes)
        target = total / n_slabs
        slabs: List[List[str]] = []
        cur: List[str] = []
        acc = 0
        for name in columns:
            cur.append(name)
            acc += sizes.get(name, 0)
            if acc >= target and len(slabs) < n_slabs - 1:
                slabs.append(cur)
                cur, acc = [], 0
        if cur:
            slabs.append(cur)
        return slabs if len(slabs) > 1 else None

    def _chunk_tasks(self, files: List[str]):
        """Lazily yield one decode task GROUP per chunk as ``(path,
        [callables])``, in deterministic order (file order, then chunk
        index) — the sliding window preserves it.  A group has one task
        per column slab (len 1 for everything but oversized parquet row
        groups); the consumer zips multi-slab results column-wise."""
        columns = self._file_columns()
        batch_rows = self.conf.max_readers_batch_size_rows
        for path in files:
            if self.fmt == "parquet":
                meta = self._parquet_file(path).metadata
                for rg in range(meta.num_row_groups):
                    slabs = self._plan_column_slabs(meta, rg, columns) \
                        if columns else None
                    if slabs is None:
                        yield path, [
                            lambda p=path, i=rg:
                            self._decode_parquet_chunk(p, i, columns,
                                                       batch_rows)]
                    else:
                        yield path, [
                            lambda p=path, i=rg, s=slab:
                            self._decode_parquet_slab(p, i, s, batch_rows)
                            for slab in slabs]
            elif self.fmt == "orc":
                n_stripes = self._orc_file(path).nstripes
                for st in range(n_stripes):
                    yield path, [
                        lambda p=path, i=st:
                        self._decode_orc_chunk(p, i, columns, batch_rows)]
            elif self.fmt == "csv":
                yield path, [
                    lambda p=path:
                    self._decode_csv_chunk(p, columns, batch_rows)]
            else:
                raise ValueError(self.fmt)

    # -- per-chunk decode (runs on pool worker threads) ----------------------

    def _finish_chunk(self, path: str, batches: List[HostBatch],
                      res: _ChunkResult) -> _ChunkResult:
        use_dict = self._use_dict()
        batches = self._with_partition_columns(path, batches,
                                               use_dict=use_dict)
        res.batches = batches
        res.bytes_decoded = sum(host_batch_bytes(hb) for hb in batches)
        if use_dict:
            res.dict_columns = sum(
                1 for hb in batches[:1] for c in hb.columns
                if c.dictionary is not None)
        return res

    def _decode_parquet_chunk(self, path: str, rg: int, columns: List[str],
                              batch_rows: int) -> _ChunkResult:
        import pyarrow as pa
        import pyarrow.parquet as pq
        res = _ChunkResult([], rg_total=1, label=f"parquet:{rg}",
                           t0=time.monotonic_ns())
        # readers are per-THREAD (decode_pool.cached_reader): ParquetFile
        # is not safe for concurrent reads from multiple pool threads,
        # but one worker reusing its own handle across row groups is
        f = self._parquet_file(path)
        file_schema = f.schema_arrow
        read_dict: List[str] = []
        if self._use_dict():
            read_dict = [n for n in file_schema.names
                         if _dict_candidate(file_schema.field(n).type)]
            if read_dict:
                f = self._parquet_file(path, read_dict)
        meta = f.metadata
        col_index = {meta.schema.column(i).name: i
                     for i in range(meta.num_columns)}
        if self.descriptors and not _row_group_can_match(
                meta.row_group(rg), col_index, self.descriptors):
            res.t1 = time.monotonic_ns()
            res.decode_ns = res.t1 - res.t0
            return res  # statistics skip (v1 parity): nothing decoded
        res.rg_read = 1
        probe = None
        if self._late_mat and self.descriptors:
            pred_cols = sorted({name for name, _op, _v in self.descriptors
                                if name in file_schema.names})
            if pred_cols:
                probe = f.read_row_group(rg, columns=pred_cols)
                if not _chunk_survivors(self.descriptors, probe):
                    res.skipped = True
                    res.bytes_decoded = probe.nbytes
                    res.t1 = time.monotonic_ns()
                    res.decode_ns = res.t1 - res.t0
                    return res
        if not columns:
            tb = f.read_row_group(rg)  # v1 parity: empty projection -> all
        elif probe is None:
            tb = f.read_row_group(rg, columns=columns)
        else:
            # survivors exist: decode only the columns the probe didn't
            rest = [c for c in columns if c not in probe.schema.names]
            tb_rest = f.read_row_group(rg, columns=rest) if rest else None
            arrays = {}
            for src in (probe, tb_rest):
                if src is not None:
                    for name in src.schema.names:
                        arrays[name] = src.column(name)
            tb = pa.table({n: arrays[n] for n in columns})
        hb = arrow_to_host_batch(tb, keep_dictionary=bool(read_dict))
        batches = [hb.slice(j, min(batch_rows, hb.num_rows - j))
                   for j in range(0, hb.num_rows, batch_rows)]
        self._finish_chunk(path, batches, res)
        res.t1 = time.monotonic_ns()
        res.decode_ns = res.t1 - res.t0
        return res

    def _decode_parquet_slab(self, path: str, rg: int, slab: List[str],
                             batch_rows: int) -> _ChunkResult:
        """Decode ONE column slab of a row group (page-level granularity;
        no predicate pushdown here — _plan_column_slabs guards).  Raw
        result: no partition columns, no byte accounting — the consumer
        merges slabs and runs _finish_chunk once."""
        import pyarrow as pa
        res = _ChunkResult([], label=f"parquet:{rg}:{slab[0]}",
                           t0=time.monotonic_ns())
        f = self._parquet_file(path)
        file_schema = f.schema_arrow
        read_dict: List[str] = []
        if self._use_dict():
            read_dict = [n for n in slab
                         if n in file_schema.names and
                         _dict_candidate(file_schema.field(n).type)]
            if read_dict:
                f = self._parquet_file(path, read_dict)
        tb = f.read_row_group(rg, columns=slab)
        hb = arrow_to_host_batch(tb, keep_dictionary=bool(read_dict))
        res.batches = [hb.slice(j, min(batch_rows, hb.num_rows - j))
                       for j in range(0, hb.num_rows, batch_rows)]
        res.t1 = time.monotonic_ns()
        res.decode_ns = res.t1 - res.t0
        return res

    def _merge_slab_results(self, path: str,
                            results: List[_ChunkResult]) -> _ChunkResult:
        """Zip column-slab results back into one whole-row chunk.  Slabs
        cover disjoint contiguous column ranges of the SAME rows with the
        same batch_rows splits, so batch j of every slab aligns."""
        res = _ChunkResult([], rg_total=1, rg_read=1,
                           label=results[0].label.rsplit(":", 1)[0],
                           t0=min(r.t0 for r in results),
                           t1=max(r.t1 for r in results))
        res.decode_ns = sum(r.decode_ns for r in results)
        merged = []
        for parts in zip(*(r.batches for r in results)):
            fields = [f for hb in parts for f in hb.schema.fields]
            cols = [c for hb in parts for c in hb.columns]
            merged.append(HostBatch(T.Schema(fields), cols))
        self._finish_chunk(path, merged, res)
        return res

    def _dict_encode_table(self, tb):
        """Host-side dictionary encoding for formats without a native
        dictionary read path (ORC stripes, CSV): string columns re-encode
        to (codes, entries) before staging, so H2D still moves 4-byte
        codes plus the dictionary once.  Returns (table, encoded_any)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        if not self._use_dict():
            return tb, False
        encoded = False
        for i, f in enumerate(tb.schema):
            if pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
                tb = tb.set_column(i, f.name,
                                   pc.dictionary_encode(tb.column(i)))
                encoded = True
        return tb, encoded

    def _decode_orc_chunk(self, path: str, stripe: int, columns: List[str],
                          batch_rows: int) -> _ChunkResult:
        res = _ChunkResult([], rg_total=1, label=f"orc:{stripe}",
                           t0=time.monotonic_ns())
        f = self._orc_file(path)
        avail = set(f.schema.names)
        pred_cols = sorted({name for name, _op, _v in self.descriptors
                            if name in avail})
        if pred_cols:
            probe = f.read_stripe(stripe, columns=pred_cols)
            if self._late_mat:
                if not _chunk_survivors(self.descriptors, probe):
                    res.skipped = True
                    res.bytes_decoded = probe.nbytes
                    res.t1 = time.monotonic_ns()
                    res.decode_ns = res.t1 - res.t0
                    return res
            elif not self._stripe_can_match(probe):
                res.t1 = time.monotonic_ns()
                res.decode_ns = res.t1 - res.t0
                return res  # v1-style min/max stripe skip
        res.rg_read = 1
        tb, enc = self._dict_encode_table(
            f.read_stripe(stripe, columns=columns or None))
        hb = arrow_to_host_batch(tb, keep_dictionary=enc)
        batches = [hb.slice(j, min(batch_rows, hb.num_rows - j))
                   for j in range(0, hb.num_rows, batch_rows)]
        self._finish_chunk(path, batches, res)
        res.t1 = time.monotonic_ns()
        res.decode_ns = res.t1 - res.t0
        return res

    def _stripe_can_match(self, probe) -> bool:
        """v1 ORC min/max stripe test over probe columns (same NaN
        conservatism as io.scan._read_orc_file)."""
        from spark_rapids_tpu.io.scan import _range_can_match
        for name, op, value in self.descriptors:
            if name not in probe.schema.names:
                continue
            arr = probe.column(name)
            nulls = arr.null_count
            if op == "notnull":
                if nulls == len(arr):
                    return False
                continue
            if nulls == len(arr):
                return False  # all NULL: no comparison can hold
            vals = arr.drop_null().to_numpy(zero_copy_only=False)
            if vals.dtype.kind == "f" and np.isnan(vals).any():
                continue  # NaN poisons min/max; never skip such stripes
            if not _range_can_match(op, value, vals.min(), vals.max()):
                return False
        return True

    def _decode_csv_chunk(self, path: str, columns: List[str],
                          batch_rows: int) -> _ChunkResult:
        import pyarrow.csv as pacsv
        res = _ChunkResult([], rg_total=1, rg_read=1, label="csv",
                           t0=time.monotonic_ns())
        read_opts, parse_opts, conv_opts = csv_options(self.options)
        if columns:
            conv_opts.include_columns = columns
        tb = pacsv.read_csv(path, read_options=read_opts,
                            parse_options=parse_opts,
                            convert_options=conv_opts)
        tb, enc = self._dict_encode_table(tb)
        hb = arrow_to_host_batch(tb, keep_dictionary=enc)
        batches = [hb.slice(j, min(batch_rows, hb.num_rows - j))
                   for j in range(0, hb.num_rows, batch_rows)] \
            if hb.num_rows else []
        self._finish_chunk(path, batches, res)
        res.t1 = time.monotonic_ns()
        res.decode_ns = res.t1 - res.t0
        return res

    # -- partition driver ----------------------------------------------------

    def partitions(self, ctx: ExecContext):
        n = self.num_partitions(ctx)
        groups: List[List[str]] = [[] for _ in range(n)]
        for i, p in enumerate(self.paths):
            groups[i % n].append(p)
        pool = get_decode_pool(self._nthreads)
        m_decode = ctx.metric(self.op_id, "scanDecodeWallNs")
        m_overlap = ctx.metric(self.op_id, "scanH2dOverlapNs")
        m_bytes = ctx.metric(self.op_id, "scanBytesDecoded")
        m_dict = ctx.metric(self.op_id, "scanDictColumns")
        m_skipped = ctx.metric(self.op_id, "scanChunksSkipped")
        m_depth = ctx.metric(self.op_id, "readaheadDepthEffective")
        rg_read = ctx.metric(self.op_id, "rowGroupsRead")
        rg_total = ctx.metric(self.op_id, "rowGroupsTotal")
        adaptive = self._adaptive
        max_depth = self._max_depth

        def gen(files: List[str]):
            # pending: (path, [futures]) decode window, submission order.
            # ready: decoded chunks harvested off the window head but not
            # yet yielded — the host-side stage of the triple buffer.
            pending: collections.deque = collections.deque()
            ready: collections.deque = collections.deque()
            stats = {"decode": 0, "bytes": 0, "skipped": 0, "dict": 0,
                     "rg_read": 0, "rg_total": 0, "blocked": 0,
                     "drains": 0, "win_blocked": 0,
                     "win_t0": time.monotonic_ns(),
                     "depth": self._depth, "depth_max": self._depth}

            def finish_entry(entry, blocked_ns: int) -> _ChunkResult:
                _path, futs = entry  # every future completed by now
                rs = [fu.result() for fu in futs]
                res = rs[0] if len(rs) == 1 else \
                    self._merge_slab_results(_path, rs)
                stats["blocked"] += blocked_ns
                stats["win_blocked"] += blocked_ns
                stats["decode"] += res.decode_ns
                stats["bytes"] += res.bytes_decoded
                stats["skipped"] += 1 if res.skipped else 0
                stats["dict"] += res.dict_columns
                stats["rg_read"] += res.rg_read
                stats["rg_total"] += res.rg_total
                obs_events.emit_span(
                    "scan", "chunk", op_id=self.op_id, t0=res.t0, t1=res.t1,
                    label=res.label, bytes=res.bytes_decoded,
                    skipped=res.skipped)
                return res

            def adapt() -> None:
                # telemetry-driven read-ahead: raise the depth while the
                # consumer blocks on decode AND the pool has headroom;
                # shed it when chunks pile up decoded-but-unconsumed
                stats["drains"] += 1
                if not adaptive or stats["drains"] % _ADAPT_EVERY:
                    return
                now = time.monotonic_ns()
                wall = max(now - stats["win_t0"], 1)
                blocked_frac = stats["win_blocked"] / wall
                d = stats["depth"]
                if blocked_frac > 0.05 and decode_pool_utilization() < 1.0:
                    d = min(d + 1, max_depth)
                elif blocked_frac < 0.005 and len(ready) >= _READY_BUF:
                    d = max(d - 1, 1)
                if d != stats["depth"]:
                    stats["depth"] = d
                    stats["depth_max"] = max(stats["depth_max"], d)
                obs_ts.record_value("io.scan.readahead_depth", float(d))
                stats["win_blocked"] = 0
                stats["win_t0"] = now

            def drain_blocking() -> _ChunkResult:
                entry = pending.popleft()
                w0 = time.monotonic_ns()
                for fu in entry[1]:
                    fu.result()
                res = finish_entry(entry, time.monotonic_ns() - w0)
                adapt()
                return res

            def harvest() -> None:
                # move COMPLETED head entries out of the decode window so
                # the submit loop starts the next decode immediately
                # instead of counting finished chunks against the depth
                while pending and len(ready) < _READY_BUF and \
                        all(fu.done() for fu in pending[0][1]):
                    ready.append(finish_entry(pending.popleft(), 0))

            def results():
                for path, tasks in self._chunk_tasks(files):
                    # fire on the consumer thread: deterministic per-query
                    # numbering AND the active query's scoped registry
                    # (pool workers carry no obs scope)
                    inject.maybe_fire("scan")
                    pending.append((path, [pool.submit(t) for t in tasks]))
                    harvest()
                    while len(pending) >= stats["depth"]:
                        if ready:
                            yield ready.popleft()
                        else:
                            yield drain_blocking()
                        harvest()
                while pending or ready:
                    if ready:
                        yield ready.popleft()
                    else:
                        yield drain_blocking()
                    harvest()

            try:
                for res in results():
                    for hb in res.batches:
                        if hb.num_rows:
                            yield hb
            finally:
                for _path, futs in pending:
                    for fu in futs:
                        fu.cancel()
                pending.clear()
                ready.clear()
                m_decode.add(stats["decode"])
                m_overlap.add(max(0, stats["decode"] - stats["blocked"]))
                m_bytes.add(stats["bytes"])
                m_dict.add(stats["dict"])
                m_skipped.add(stats["skipped"])
                # max, not sum: each partition generator reports the
                # deepest read-ahead it actually ran
                m_depth.value = max(m_depth.value, stats["depth_max"])
                rg_read.add(stats["rg_read"])
                rg_total.add(stats["rg_total"])

        return [gen(g) for g in groups]
