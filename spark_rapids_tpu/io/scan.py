"""File-scan exec: multi-threaded host decode of parquet/orc/csv into
HostBatches (GpuParquetScan.scala:68 structure: host-side footer/filter work,
then decode; here decode itself is host-side by design — SURVEY.md 2.9 row 2 —
with a read-ahead thread pool mirroring MultiFileParquetPartitionReader,
GpuParquetScan.scala:647-700)."""

from __future__ import annotations

import concurrent.futures
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.config import (
    MULTITHREADED_READ_THREADS, RapidsConf,
)
from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch
from spark_rapids_tpu.io.discovery import csv_options
from spark_rapids_tpu.plan.physical import CpuExec, ExecContext


def _read_parquet_file(path: str, columns: List[str], batch_rows: int,
                       filters=None) -> List[HostBatch]:
    import pyarrow.parquet as pq
    out = []
    f = pq.ParquetFile(path)
    for rb in f.iter_batches(batch_size=batch_rows,
                             columns=columns or None):
        out.append(arrow_to_host_batch(rb))
    return out


def _read_orc_file(path: str, columns: List[str], batch_rows: int
                   ) -> List[HostBatch]:
    import pyarrow.orc as orc
    f = orc.ORCFile(path)
    tb = f.read(columns=columns or None)
    hb = arrow_to_host_batch(tb)
    return [hb.slice(i, min(batch_rows, hb.num_rows - i))
            for i in range(0, max(hb.num_rows, 1), batch_rows)] \
        if hb.num_rows else []


def _read_csv_file(path: str, columns: List[str], batch_rows: int,
                   options: Dict[str, Any]) -> List[HostBatch]:
    import pyarrow.csv as pacsv
    read_opts, parse_opts, conv_opts = csv_options(options)
    if columns:
        conv_opts.include_columns = columns
    tb = pacsv.read_csv(path, read_options=read_opts,
                        parse_options=parse_opts, convert_options=conv_opts)
    hb = arrow_to_host_batch(tb)
    return [hb.slice(i, min(batch_rows, hb.num_rows - i))
            for i in range(0, max(hb.num_rows, 1), batch_rows)] \
        if hb.num_rows else []


class CpuFileScanExec(CpuExec):
    """Reads files with a shared thread pool, one partition per file group.

    Partitioning: files are assigned round-robin to
    ``spark.sql.shuffle.partitions`` partitions (or fewer when there are
    fewer files)."""

    def __init__(self, node, conf: RapidsConf):
        super().__init__([], node.schema)
        self.node = node
        self.conf = conf
        self.fmt = node.fmt
        self.paths = node.paths
        self.options = node.options
        self._nthreads = MULTITHREADED_READ_THREADS.get(conf)

    def describe(self):
        return f"CpuFileScan({self.fmt}, {len(self.paths)} files)"

    def num_partitions(self, ctx):
        return max(1, min(len(self.paths), self.conf.shuffle_partitions))

    def _read_file(self, path: str) -> List[HostBatch]:
        batch_rows = self.conf.max_readers_batch_size_rows
        columns = self.output_schema.names
        if self.fmt == "parquet":
            return _read_parquet_file(path, columns, batch_rows)
        if self.fmt == "orc":
            return _read_orc_file(path, columns, batch_rows)
        if self.fmt == "csv":
            return _read_csv_file(path, columns, batch_rows, self.options)
        raise ValueError(self.fmt)

    def partitions(self, ctx: ExecContext):
        n = self.num_partitions(ctx)
        groups: List[List[str]] = [[] for _ in range(n)]
        for i, p in enumerate(self.paths):
            groups[i % n].append(p)
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._nthreads)

        def gen(files: List[str]):
            # read-ahead: submit all files in this partition to the pool
            futures = [pool.submit(self._read_file, f) for f in files]
            for fu in futures:
                for hb in fu.result():
                    if hb.num_rows:
                        yield hb

        return [gen(g) for g in groups]
