"""File-scan exec: multi-threaded host decode of parquet/orc/csv into
HostBatches (GpuParquetScan.scala:68 structure: host-side footer/filter work,
then decode; here decode itself is host-side by design — SURVEY.md 2.9 row 2 —
with a read-ahead thread pool mirroring MultiFileParquetPartitionReader,
GpuParquetScan.scala:647-700).

Predicate pushdown: planner-pushed conjuncts become (column, op, literal)
descriptors; parquet row groups whose min/max statistics prove no row can
match are skipped before any decode (GpuParquetScan.scala:217-281
clipBlocksToSchema + filterBlocks role), and partition-column predicates
prune whole files (PartitioningAwareFileIndex pruning role).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, HostColumn
from spark_rapids_tpu.config import (
    CSV_ENABLED, MULTITHREADED_READ_THREADS, PARQUET_ENABLED, RapidsConf,
)
from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch
from spark_rapids_tpu.io.discovery import csv_options
from spark_rapids_tpu.plan.physical import CpuExec, ExecContext


# -- pushed-filter descriptors ----------------------------------------------


def extract_pushdown_descriptors(exprs) -> List[Tuple[str, str, Any]]:
    """(column, op, literal) descriptors from pushed filter conjuncts; ops:
    eq/lt/le/gt/ge/notnull.  Anything unconvertible is simply dropped —
    pushdown is advisory, the full Filter still runs above the scan."""
    from spark_rapids_tpu.exprs.base import ColumnRef, Literal
    from spark_rapids_tpu.exprs.nullexprs import IsNotNull
    from spark_rapids_tpu.exprs.predicates import (
        Equals, GreaterThan, GreaterThanOrEqual, LessThan, LessThanOrEqual,
    )
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    ops = {Equals: "eq", LessThan: "lt", LessThanOrEqual: "le",
           GreaterThan: "gt", GreaterThanOrEqual: "ge"}
    out = []
    for e in exprs:
        if isinstance(e, IsNotNull) and isinstance(e.child, ColumnRef):
            out.append((e.child.column, "notnull", None))
            continue
        op = ops.get(type(e))
        if op is None or len(e.children) != 2:
            continue
        lhs, rhs = e.children
        if isinstance(lhs, ColumnRef) and isinstance(rhs, Literal) and \
                rhs.value is not None:
            out.append((lhs.column, op, rhs.value))
        elif isinstance(rhs, ColumnRef) and isinstance(lhs, Literal) and \
                lhs.value is not None:
            out.append((rhs.column, flip[op], lhs.value))
    return out


def _range_can_match(op: str, value, vmin, vmax) -> bool:
    """Can any v in [vmin, vmax] satisfy `v <op> value`?"""
    try:
        if op == "eq":
            return not (value < vmin or value > vmax)
        if op == "lt":
            return vmin < value
        if op == "le":
            return vmin <= value
        if op == "gt":
            return vmax > value
        if op == "ge":
            return vmax >= value
    except TypeError:
        return True  # incomparable types: keep
    return True


def _row_group_can_match(meta_rg, col_index: Dict[str, int],
                         descriptors) -> bool:
    for name, op, value in descriptors:
        ci = col_index.get(name)
        if ci is None:
            continue
        col = meta_rg.column(ci)
        stats = col.statistics
        if stats is None:
            continue
        if op == "notnull":
            if stats.null_count is not None and \
                    stats.null_count == meta_rg.num_rows:
                return False
            continue
        if not stats.has_min_max:
            continue
        if not _range_can_match(op, value, stats.min, stats.max):
            return False
    return True


def _read_parquet_file(path: str, columns: List[str], batch_rows: int,
                       descriptors=None,
                       counters: Optional[Dict[str, int]] = None
                       ) -> List[HostBatch]:
    import pyarrow.parquet as pq
    f = pq.ParquetFile(path)
    meta = f.metadata
    n_rg = meta.num_row_groups
    keep: List[int] = []
    col_index = {meta.schema.column(i).name: i
                 for i in range(meta.num_columns)}
    for i in range(n_rg):
        if not descriptors or _row_group_can_match(
                meta.row_group(i), col_index, descriptors):
            keep.append(i)
    if counters is not None:
        counters["row_groups_total"] = counters.get("row_groups_total", 0) \
            + n_rg
        counters["row_groups_read"] = counters.get("row_groups_read", 0) \
            + len(keep)
    out = []
    if not keep:
        return out
    for rb in f.iter_batches(batch_size=batch_rows, row_groups=keep,
                             columns=columns or None):
        out.append(arrow_to_host_batch(rb))
    return out


def _read_orc_file(path: str, columns: List[str], batch_rows: int,
                   descriptors=None,
                   counters: Optional[Dict[str, int]] = None
                   ) -> List[HostBatch]:
    """ORC scan with stripe-level predicate skipping.

    pyarrow exposes per-stripe reads but not the file's stripe statistics,
    so the pushdown is two-pass (the OrcFilters/SearchArgument role,
    OrcFilters.scala): pass 1 decodes ONLY the predicate columns of each
    stripe and computes min/max/null-count on host; stripes that provably
    cannot match skip the full decode of pass 2.  For selective predicates
    over wide tables that removes most of the decode work.
    """
    import pyarrow.orc as orc
    f = orc.ORCFile(path)
    n_stripes = f.nstripes
    # Probe predicate columns independently of the projection: a filter on
    # a non-projected column must still drive stripe skipping (intersect
    # only with what the FILE actually has — partition-value predicates
    # have no file column to probe).
    avail = set(f.schema.names)
    pred_cols = sorted({name for name, _op, _v in (descriptors or [])
                        if name in avail})
    keep: List[int] = []
    for i in range(n_stripes):
        if not descriptors or not pred_cols:
            keep.append(i)
            continue
        probe = f.read_stripe(i, columns=pred_cols)
        ok = True
        for name, op, value in descriptors:
            if name not in pred_cols:
                continue
            arr = probe.column(name)
            nulls = arr.null_count
            if op == "notnull":
                if nulls == len(arr):
                    ok = False
                    break
                continue
            if nulls == len(arr):
                ok = False  # all NULL: no comparison can hold
                break
            vals = arr.drop_null().to_numpy(zero_copy_only=False)
            if vals.dtype.kind == "f" and np.isnan(vals).any():
                # Spark orders NaN greater than everything (so NaN rows CAN
                # match > / >= / = NaN predicates) and plain min/max would
                # propagate NaN into the bounds — never skip such stripes
                # (parquet writers likewise omit stats when NaN is present)
                continue
            if not _range_can_match(op, value, vals.min(), vals.max()):
                ok = False
                break
        if ok:
            keep.append(i)
    if counters is not None:
        counters["row_groups_total"] = counters.get("row_groups_total", 0) \
            + n_stripes
        counters["row_groups_read"] = counters.get("row_groups_read", 0) \
            + len(keep)
    out: List[HostBatch] = []
    for i in keep:
        hb = arrow_to_host_batch(f.read_stripe(i, columns=columns or None))
        for j in range(0, hb.num_rows, batch_rows):
            out.append(hb.slice(j, min(batch_rows, hb.num_rows - j)))
    return out


def partition_value_column(f: T.Field, v: Any, n: int,
                           use_dict: bool = False) -> HostColumn:
    """Constant partition-value column for one file's batches
    (ColumnarPartitionReaderWithPartitionValues role).  With ``use_dict``
    a string value becomes a 1-entry dictionary column — H2D then moves
    int32 codes instead of ``n`` copies of the same bytes."""
    if v is None:
        values = np.zeros(n, dtype=object if f.dtype.is_string
                          else f.dtype.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        if use_dict and f.dtype.is_string:
            return HostColumn(f.dtype, np.zeros(n, dtype=np.int64), validity,
                              np.array([""], dtype=object))
        return HostColumn(f.dtype, values, validity)
    validity = np.ones(n, dtype=np.bool_)
    if use_dict and f.dtype.is_string:
        return HostColumn(f.dtype, np.zeros(n, dtype=np.int64), validity,
                          np.array([str(v)], dtype=object))
    values = np.full(n, v, dtype=object if f.dtype.is_string
                     else f.dtype.np_dtype)
    return HostColumn(f.dtype, values, validity)


def _read_csv_file(path: str, columns: List[str], batch_rows: int,
                   options: Dict[str, Any]) -> List[HostBatch]:
    import pyarrow.csv as pacsv
    read_opts, parse_opts, conv_opts = csv_options(options)
    if columns:
        conv_opts.include_columns = columns
    tb = pacsv.read_csv(path, read_options=read_opts,
                        parse_options=parse_opts, convert_options=conv_opts)
    hb = arrow_to_host_batch(tb)
    return [hb.slice(i, min(batch_rows, hb.num_rows - i))
            for i in range(0, max(hb.num_rows, 1), batch_rows)] \
        if hb.num_rows else []


class CpuFileScanExec(CpuExec):
    """Reads files with a shared thread pool, one partition per file group.

    Partitioning: files are assigned round-robin to
    ``spark.sql.shuffle.partitions`` partitions (or fewer when there are
    fewer files)."""

    def __init__(self, node, conf: RapidsConf):
        super().__init__([], node.schema)
        self.node = node
        self.conf = conf
        self.fmt = node.fmt
        self.paths = node.paths
        self.options = node.options
        # per-format acceleration gate: disabled formats decode on one
        # thread with no row-group pushdown (plain fallback path)
        accel_entry = {"parquet": PARQUET_ENABLED,
                       "csv": CSV_ENABLED}.get(node.fmt)
        accel = accel_entry is None or accel_entry.get(conf)
        self._nthreads = MULTITHREADED_READ_THREADS.get(conf) if accel else 1
        self.partitions_info = getattr(node, "partitions", None)
        self.descriptors = extract_pushdown_descriptors(
            node.pushed_filters) if accel else []
        if self.partitions_info is not None:
            # partition pruning: drop whole files whose partition values
            # cannot satisfy the pushed predicates
            part_schema, file_values = self.partitions_info
            names = part_schema.names
            kept = []
            for p in self.paths:
                vals = dict(zip(names, file_values[p]))
                if self._file_can_match(vals):
                    kept.append(p)
            self.paths = kept

    def _file_can_match(self, part_vals: Dict[str, Any]) -> bool:
        for name, op, value in self.descriptors:
            if name not in part_vals:
                continue
            v = part_vals[name]
            if v is None:
                return False  # NULL partition value fails any comparison
            if op == "notnull":
                continue
            if not _range_can_match(op, value, v, v):
                return False
        return True

    def describe(self):
        extra = f", pushed={len(self.descriptors)}" if self.descriptors \
            else ""
        return f"CpuFileScan({self.fmt}, {len(self.paths)} files{extra})"

    def num_partitions(self, ctx):
        return max(1, min(max(len(self.paths), 1),
                          self.conf.shuffle_partitions))

    def _read_file(self, path: str,
                   counters: Optional[Dict[str, int]] = None
                   ) -> List[HostBatch]:
        batch_rows = self.conf.max_readers_batch_size_rows
        part_fields = []
        if self.partitions_info is not None:
            part_fields = self.partitions_info[0].fields
        part_names = {f.name for f in part_fields}
        columns = [n for n in self.output_schema.names
                   if n not in part_names]
        if self.fmt == "parquet":
            batches = _read_parquet_file(path, columns, batch_rows,
                                         self.descriptors, counters)
        elif self.fmt == "orc":
            batches = _read_orc_file(path, columns, batch_rows,
                                     self.descriptors, counters)
        elif self.fmt == "csv":
            batches = _read_csv_file(path, columns, batch_rows, self.options)
        else:
            raise ValueError(self.fmt)
        return self._with_partition_columns(path, batches)

    def _with_partition_columns(self, path: str, batches: List[HostBatch],
                                use_dict: bool = False) -> List[HostBatch]:
        """Append this file's constant partition-value columns and reorder
        to the output schema (ColumnarPartitionReaderWithPartitionValues
        role)."""
        if self.partitions_info is None or not batches:
            return batches
        _part_schema, file_values = self.partitions_info
        vals = dict(zip(_part_schema.names, file_values[path]))
        out = []
        for hb in batches:
            cols = {f.name: c for f, c in zip(hb.schema.fields, hb.columns)}
            ordered = []
            for f in self.output_schema.fields:
                if f.name in cols:
                    ordered.append(cols[f.name])
                else:
                    ordered.append(partition_value_column(
                        f, vals[f.name], hb.num_rows, use_dict))
            out.append(HostBatch(self.output_schema, ordered))
        return out

    def partitions(self, ctx: ExecContext):
        n = self.num_partitions(ctx)
        groups: List[List[str]] = [[] for _ in range(n)]
        for i, p in enumerate(self.paths):
            groups[i % n].append(p)
        from spark_rapids_tpu.io.decode_pool import get_decode_pool
        pool = get_decode_pool(self._nthreads)
        rg_read = ctx.metric(self.op_id, "rowGroupsRead")
        rg_total = ctx.metric(self.op_id, "rowGroupsTotal")

        def gen(files: List[str]):
            # read-ahead: submit all files in this partition to the pool
            # (one counter dict per file: no cross-thread read-modify-write)
            counter_list = [dict() for _ in files]
            futures = [pool.submit(self._read_file, f, c)
                       for f, c in zip(files, counter_list)]
            for fu in futures:
                for hb in fu.result():
                    if hb.num_rows:
                        yield hb
            rg_read.add(sum(c.get("row_groups_read", 0)
                            for c in counter_list))
            rg_total.add(sum(c.get("row_groups_total", 0)
                             for c in counter_list))

        return [gen(g) for g in groups]
