"""Profiler ranges fused with metrics.

Reference analogue: NvtxWithMetrics (NvtxWithMetrics.scala:27-36) — one
``with`` block feeds both the profiler timeline and a SQL metric.  On TPU the
profiler side is XProf via ``jax.profiler.TraceAnnotation`` (the XLA runtime
exports these through the PJRT profiler C API, SURVEY.md section 2.9 NVTX
row); the metric side is the ExecContext Metric objects.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax.profiler


@contextlib.contextmanager
def trace_range(name: str, metric=None):
    """Profiler range + optional elapsed-nanos metric accumulation."""
    t0 = time.monotonic_ns()
    with jax.profiler.TraceAnnotation(name):
        yield
    if metric is not None:
        metric.add(time.monotonic_ns() - t0)


def start_profile(logdir: str):
    """Begin an XProf capture (nsys-capture analogue,
    docs/dev/nvtx_profiling.md)."""
    jax.profiler.start_trace(logdir)


def stop_profile():
    jax.profiler.stop_trace()
