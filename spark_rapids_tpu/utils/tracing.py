"""Profiler ranges fused with metrics.

Reference analogue: NvtxWithMetrics (NvtxWithMetrics.scala:27-36) — one
``with`` block feeds both the profiler timeline and a SQL metric.  On TPU the
profiler side is XProf via ``jax.profiler.TraceAnnotation`` (the XLA runtime
exports these through the PJRT profiler C API, SURVEY.md section 2.9 NVTX
row); the metric side is the ExecContext Metric objects.

Device-time accounting: jax dispatch is asynchronous, so the wall time of a
dispatch call is only a *lower bound* on device execution.  The accurate
number needs a ``block_until_ready`` on the outputs — a host sync that
costs a tunnel round trip and kills async overlap, so it is gated behind
``spark.rapids.sql.tpu.metrics.detailEnabled`` (off by default).
:func:`device_dispatch` implements both modes for the dispatch sites in
``plan/pipeline.py`` / ``plan/physical.py``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax.profiler

from spark_rapids_tpu.config import METRICS_DETAIL
from spark_rapids_tpu.obs import events as obs_events


def metrics_detail(conf) -> bool:
    """True when the accurate-sync metrics path is enabled (the cheap
    lower-bound path is the default)."""
    return METRICS_DETAIL.get(conf)


@contextlib.contextmanager
def trace_range(name: str, metric=None):
    """Profiler range + optional elapsed-nanos metric accumulation."""
    t0 = time.monotonic_ns()
    with jax.profiler.TraceAnnotation(name):
        yield
    if metric is not None:
        metric.add(time.monotonic_ns() - t0)


@contextlib.contextmanager
def device_dispatch(ctx, op_id: str, name: str,
                    obs_op: Optional[str] = None):
    """Time one device program dispatch into ``ctx.metric(op_id,
    'deviceTimeNs')`` under a profiler range.

    The body sets ``holder['outputs']`` to the dispatched result.  With
    the metrics-detail conf on, the outputs are blocked on before the
    clock stops — on pre-staged (already device-resident) inputs that
    delta IS device execution time; ``deviceTimeSyncs`` counts how many
    accurate samples the total contains.  Detail off: the dispatch wall
    alone is recorded (a lower bound, async dispatch).

    The elapsed time is recorded in a ``finally`` so a dispatch that
    raises (an injected fault, an OOM about to be retried) still shows
    in the metric and the profile instead of vanishing; the failed
    attempt's obs span is tagged ``error``.  ``obs_op`` names the
    physical-plan node the span is attributed to when the metric op_id
    is a shared bucket (the pipeline dispatcher passes the stage root's
    op_id here while keeping the metric under ``"pipeline"``).
    """
    holder: dict = {}
    err = False
    t0 = time.monotonic_ns()
    try:
        with jax.profiler.TraceAnnotation(f"{op_id}:{name}"):
            yield holder
            if metrics_detail(ctx.conf) and \
                    holder.get("outputs") is not None:
                jax.block_until_ready(holder["outputs"])
                ctx.metric(op_id, "deviceTimeSyncs").add(1)
    except BaseException:
        err = True
        raise
    finally:
        elapsed = time.monotonic_ns() - t0
        ctx.metric(op_id, "deviceTimeNs").add(elapsed)
        if err:
            ctx.metric(op_id, "deviceTimeErrors").add(1)
            obs_events.emit_span("device", name, obs_op or op_id,
                                 t0, t0 + elapsed, error=True)
        else:
            obs_events.emit_span("device", name, obs_op or op_id,
                                 t0, t0 + elapsed)


def start_profile(logdir: str):
    """Begin an XProf capture (nsys-capture analogue,
    docs/dev/nvtx_profiling.md)."""
    jax.profiler.start_trace(logdir)


def stop_profile():
    jax.profiler.stop_trace()
