"""Compile/dispatch economics: the registry behind every ``jax.jit``
entry point the execs use.

The reference engine pays no per-query compile tax — cudf kernels ship
precompiled — so its metrics layer never needed to account for it.  On
TPU every new (program, shape-bucket) pair costs an XLA compile that can
dwarf the query itself, and every dispatched program costs a host->device
round trip.  This module makes both quantities *measured*:

* :func:`instrumented_jit` wraps ``jax.jit`` so each call is counted as a
  dispatch, and a growth of the jitted function's executable cache is
  counted as a compile (with the call's wall time attributed to
  ``compile_wall_ns`` — compile-inclusive first-call wall, the number a
  user actually waits for).
* The process-wide tallies are snapshotted around each query by
  ``session.execute`` into ``last_metrics`` (``compileCount``,
  ``compileWallNs``, ``dispatchCount``, ``compiledShapes``) and surfaced
  by ``bench.py`` as ``compile_s``.
* :func:`enable_persistent_cache` turns on JAX's persistent compilation
  cache (conf ``spark.rapids.sql.tpu.compileCacheDir``) so repeated
  processes skip recompilation entirely.

When available, ``jax.monitoring`` backend-compile duration events are
also accumulated (``backend_compile_ns``) — pure XLA compile seconds,
excluding the first-run execution that the wall number includes.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax

_LOCK = threading.Lock()
_STATS: Dict[str, int] = {
    # cumulative process-wide; per-query deltas come from snapshot() pairs
    "compiles": 0,          # executable-cache misses observed at call sites
    "compile_wall_ns": 0,   # wall ns of calls that triggered a compile
    "dispatches": 0,        # jitted program invocations
    "backend_compile_ns": 0,  # jax.monitoring backend compile durations
}
_LABEL_COMPILES: Dict[str, int] = {}


def snapshot() -> Dict[str, int]:
    """Copy of the cumulative counters (take two and subtract for a
    per-query delta)."""
    with _LOCK:
        return dict(_STATS)


def delta(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
    return {k: after[k] - before.get(k, 0) for k in after}


def compiled_shapes() -> int:
    """Cumulative executables compiled at registered call sites — an UPPER
    BOUND on distinct (program, shape-bucket) cardinality.  Exact within a
    session (plan/exec memoization means a shape compiles once); across
    sessions the same shape recompiles and is counted again, so suite-level
    trends, not absolute cardinality, are what this metric shows."""
    with _LOCK:
        return _STATS["compiles"]


def per_label_compiles() -> Dict[str, int]:
    with _LOCK:
        return dict(_LABEL_COMPILES)


def _record(label: str, compiled: bool, wall_ns: int) -> None:
    with _LOCK:
        _STATS["dispatches"] += 1
        if compiled:
            _STATS["compiles"] += 1
            _STATS["compile_wall_ns"] += wall_ns
            _LABEL_COMPILES[label] = _LABEL_COMPILES.get(label, 0) + 1


def _cache_size(jitted) -> int:
    try:
        return jitted._cache_size()
    except Exception:  # noqa: BLE001 — older/newer jax without the probe
        return -1


def _trace_state_clean() -> bool:
    """False while jax is tracing (a nested-jit call inlines, it doesn't
    dispatch)."""
    try:
        return jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001
        return True


def instrumented_jit(fn: Optional[Callable] = None, *, label: str = "",
                     **jit_kwargs) -> Callable:
    """``jax.jit`` with dispatch/compile accounting.

    Usable as ``instrumented_jit(f, label=...)`` or as a decorator
    ``@instrumented_jit(label=..., static_argnames=...)``.  The wrapper is
    call-compatible with the jitted function; the raw jitted callable is
    exposed as ``wrapper.jitted``.
    """
    if fn is None:
        return functools.partial(instrumented_jit, label=label, **jit_kwargs)
    name = label or getattr(fn, "__name__", "jit")
    jitted = jax.jit(fn, **jit_kwargs)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not _trace_state_clean():
            # nested call while an outer program is being traced: it
            # inlines into the outer jaxpr, so it is neither a device
            # dispatch nor a separate compile — don't count it
            return jitted(*args, **kwargs)
        before = _cache_size(jitted)
        t0 = time.monotonic_ns()
        out = jitted(*args, **kwargs)
        after = _cache_size(jitted)
        compiled = after >= 0 and after != before
        _record(name, compiled, time.monotonic_ns() - t0)
        return out

    wrapper.jitted = jitted
    wrapper.label = name
    return wrapper


# -- jax.monitoring hook (precise backend compile seconds) -------------------

_MONITORING_HOOKED = False


def _on_event_duration(event: str, duration_secs: float, **kw) -> None:
    if "compil" not in event:
        return
    with _LOCK:
        _STATS["backend_compile_ns"] += int(duration_secs * 1e9)


def _hook_monitoring() -> None:
    global _MONITORING_HOOKED
    if _MONITORING_HOOKED:
        return
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _MONITORING_HOOKED = True
    except Exception:  # noqa: BLE001 — monitoring API is best-effort
        _MONITORING_HOOKED = True  # don't retry every call


_hook_monitoring()


# -- persistent compilation cache --------------------------------------------

_PERSISTENT_DIR: Optional[str] = None


def enable_persistent_cache(cache_dir: str,
                            min_compile_secs: float = 1.0) -> None:
    """Point XLA's persistent compilation cache at ``cache_dir`` (conf
    ``spark.rapids.sql.tpu.compileCacheDir``): executables survive the
    process, so a re-run pre-warms from disk instead of recompiling."""
    global _PERSISTENT_DIR
    if not cache_dir or _PERSISTENT_DIR == cache_dir:
        return
    import os
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    _PERSISTENT_DIR = cache_dir
