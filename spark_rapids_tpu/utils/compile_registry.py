"""Compile/dispatch economics: the registry behind every ``jax.jit``
entry point the execs use.

The reference engine pays no per-query compile tax — cudf kernels ship
precompiled — so its metrics layer never needed to account for it.  On
TPU every new (program, shape-bucket) pair costs an XLA compile that can
dwarf the query itself, and every dispatched program costs a host->device
round trip.  This module makes both quantities *measured*:

* :func:`instrumented_jit` wraps ``jax.jit`` so each call is counted as a
  dispatch, and a growth of the jitted function's executable cache is
  counted as a compile (with the call's wall time attributed to
  ``compile_wall_ns`` — compile-inclusive first-call wall, the number a
  user actually waits for).
* The process-wide tallies are snapshotted around each query by
  ``session.execute`` into ``last_metrics`` (``compileCount``,
  ``compileWallNs``, ``dispatchCount``, ``compiledShapes``) and surfaced
  by ``bench.py`` as ``compile_s``.
* :func:`enable_persistent_cache` turns on JAX's persistent compilation
  cache (conf ``spark.rapids.sql.tpu.compileCacheDir``) so repeated
  processes skip recompilation entirely.

Data-plane accounting rides the same snapshot/delta machinery:

* ``donate_argnums`` passes through :func:`instrumented_jit` to ``jax.jit``
  and every donated call adds the donated arguments' buffer bytes to
  ``donated_bytes`` (surfaced as ``session.last_metrics['donatedBytes']``).
  The :func:`donation_guard` context manager arms a use-after-donate
  assertion for tests: once a buffer has been donated, presenting it to
  any later instrumented call (or sync site registered via
  :func:`guard_check`) raises.
* :func:`record_transfer` accumulates host<->device staging bytes and
  wall time (``h2d_bytes``/``h2d_ns``/``d2h_bytes``/``d2h_ns``) from the
  batch staging layer, feeding bench.py's ``h2d_gb_per_sec`` /
  ``d2h_gb_per_sec``.

When available, ``jax.monitoring`` backend-compile duration events are
also accumulated (``backend_compile_ns``) — pure XLA compile seconds,
excluding the first-run execution that the wall number includes.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax

from spark_rapids_tpu.fault import inject as _fault_inject
from spark_rapids_tpu.obs import events as _obs_events

_LOCK = threading.Lock()
_STATS: Dict[str, int] = {
    # cumulative process-wide; per-query deltas come from snapshot() pairs
    "compiles": 0,          # executable-cache misses observed at call sites
    "compile_wall_ns": 0,   # wall ns of calls that triggered a compile
    "dispatches": 0,        # jitted program invocations
    "backend_compile_ns": 0,  # jax.monitoring backend compile durations
    "donated_bytes": 0,     # input buffer bytes donated to dispatches
    "h2d_bytes": 0,         # host->device staging bytes
    "h2d_ns": 0,            # host->device staging wall ns
    "d2h_bytes": 0,         # device->host bulk-copy bytes
    "d2h_ns": 0,            # device->host bulk-copy wall ns
}
_LABEL_COMPILES: Dict[str, int] = {}


def snapshot() -> Dict[str, int]:
    """Copy of the cumulative counters (take two and subtract for a
    per-query delta)."""
    with _LOCK:
        return dict(_STATS)


def delta(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
    return {k: after[k] - before.get(k, 0) for k in after}


def compiled_shapes() -> int:
    """Cumulative executables compiled at registered call sites — an UPPER
    BOUND on distinct (program, shape-bucket) cardinality.  Exact within a
    session (plan/exec memoization means a shape compiles once); across
    sessions the same shape recompiles and is counted again, so suite-level
    trends, not absolute cardinality, are what this metric shows."""
    with _LOCK:
        return _STATS["compiles"]


def per_label_compiles() -> Dict[str, int]:
    with _LOCK:
        return dict(_LABEL_COMPILES)


def _record(label: str, compiled: bool, wall_ns: int,
            donated_bytes: int = 0) -> None:
    with _LOCK:
        _STATS["dispatches"] += 1
        _STATS["donated_bytes"] += donated_bytes
        if compiled:
            _STATS["compiles"] += 1
            _STATS["compile_wall_ns"] += wall_ns
            _LABEL_COMPILES[label] = _LABEL_COMPILES.get(label, 0) + 1
    # credit the executing query's scope as well: under concurrent
    # serving the global delta mixes queries, so session.execute reads
    # these per-scope counters instead
    sc = _obs_events.current_scope()
    if sc is not None:
        sc.add("dispatches", 1)
        if donated_bytes:
            sc.add("donated_bytes", donated_bytes)
        if compiled:
            sc.add("compiles", 1)
            sc.add("compile_wall_ns", wall_ns)


def record_transfer(kind: str, nbytes: int, wall_ns: int) -> None:
    """Accumulate one host<->device staging pass (kind: "h2d" | "d2h")."""
    with _LOCK:
        _STATS[kind + "_bytes"] += int(nbytes)
        _STATS[kind + "_ns"] += int(wall_ns)
    sc = _obs_events.current_scope()
    if sc is not None:
        sc.add(kind + "_bytes", int(nbytes))
        sc.add(kind + "_ns", int(wall_ns))
    if _obs_events.active():
        now = time.monotonic_ns()
        _obs_events.emit_span(kind, "transfer", t0=now - int(wall_ns),
                              t1=now, bytes=int(nbytes))


# -- use-after-donate guard (tests) ------------------------------------------

# When armed, maps id(array) -> (donating label, strong ref).  The strong
# ref pins the array object so a GC'd id can never be reused by a fresh
# buffer and false-positive.
_DONATION_GUARD: Optional[Dict[int, tuple]] = None


class _guard_ctx:
    def __enter__(self):
        global _DONATION_GUARD
        self._prev = _DONATION_GUARD
        _DONATION_GUARD = {}
        return _DONATION_GUARD

    def __exit__(self, *exc):
        global _DONATION_GUARD
        _DONATION_GUARD = self._prev
        return False


def donation_guard() -> "_guard_ctx":
    """Context manager arming the use-after-donate assertion: every
    instrumented dispatch (and every sync site calling :func:`guard_check`)
    verifies none of its inputs were previously donated."""
    return _guard_ctx()


def guard_check(tree, site: str) -> None:
    """Assert no leaf of ``tree`` was donated to an earlier dispatch.
    No-op unless :func:`donation_guard` is armed (hot paths pay one
    ``is None`` test)."""
    guard = _DONATION_GUARD
    if guard is None:
        return
    for leaf in jax.tree_util.tree_leaves(tree):
        hit = guard.get(id(leaf))
        if hit is not None:
            raise AssertionError(
                f"use-after-donate: {site} received a buffer already "
                f"donated to {hit[0]}")


def _guard_mark(label: str, leaves) -> None:
    guard = _DONATION_GUARD
    if guard is None:
        return
    for leaf in leaves:
        guard[id(leaf)] = (label, leaf)


def _cache_size(jitted) -> int:
    try:
        return jitted._cache_size()
    except Exception:  # noqa: BLE001 — older/newer jax without the probe
        return -1


# -- persistent-cache bypass for donating executables -------------------------
#
# XLA:CPU (jax 0.4.37): an executable DESERIALIZED from the persistent
# compilation cache mishandles input-output aliasing — donated input
# buffers are freed while the deserialized program still reads them
# (wrong results and segfaults; reproduced 8/8 with a populated cache,
# 0/8 with the cache disabled, identical code).  Freshly *compiled*
# donating executables are sound, so donating programs simply never
# enter the persistent cache: while a donating dispatch is on the
# current thread, cache reads return a miss and writes are dropped.
# Non-donating programs (the vast majority of compile time) keep full
# persistence.

_NO_PERSIST = threading.local()
_CACHE_BYPASS_INSTALLED = False


class _no_persist_scope:
    def __enter__(self):
        _NO_PERSIST.depth = getattr(_NO_PERSIST, "depth", 0) + 1

    def __exit__(self, *exc):
        _NO_PERSIST.depth -= 1
        return False


def _install_cache_bypass() -> None:
    global _CACHE_BYPASS_INSTALLED
    with _LOCK:
        # under the lock, and the installed flag is only set AFTER the
        # hooks are swapped: a concurrent donation_supported() must not
        # see True while cache reads are still live (that window would
        # re-open the deserialized-donation use-after-free)
        if _CACHE_BYPASS_INSTALLED:
            return
        try:
            from jax._src import compilation_cache as _cc
            real_get = _cc.get_executable_and_time
            real_put = _cc.put_executable_and_time

            @functools.wraps(real_get)
            def get(*args, **kwargs):
                if getattr(_NO_PERSIST, "depth", 0):
                    return None, None
                return real_get(*args, **kwargs)

            @functools.wraps(real_put)
            def put(*args, **kwargs):
                if getattr(_NO_PERSIST, "depth", 0):
                    return None
                return real_put(*args, **kwargs)

            _cc.get_executable_and_time = get
            _cc.put_executable_and_time = put
        except Exception:  # noqa: BLE001 — private API moved: fall back
            # to disabling donation outright rather than risk the
            # use-after-free
            global _DONATION_FORCED_OFF
            _DONATION_FORCED_OFF = True
        _CACHE_BYPASS_INSTALLED = True


_DONATION_FORCED_OFF = False


def donation_supported() -> bool:
    """False when the persistent-cache bypass could not be installed (jax
    private API moved) — donation then stays off everywhere rather than
    risk cache-deserialized aliasing corruption."""
    _install_cache_bypass()
    return not _DONATION_FORCED_OFF


def _trace_state_clean() -> bool:
    """False while jax is tracing (a nested-jit call inlines, it doesn't
    dispatch)."""
    try:
        return jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001
        return True


_DONATION_WARNING_FILTERED = False


def _filter_donation_warning() -> None:
    """Once per process: a donated input whose shape matches no output
    can't be aliased in place; jax warns per lowering, but the buffer is
    still consumed (freed at dispatch) — exactly the intent, so the
    warning is noise at our opt-in call sites.  One global filter entry,
    not one per donating jit (every warning check scans the list)."""
    global _DONATION_WARNING_FILTERED
    if _DONATION_WARNING_FILTERED:
        return
    _DONATION_WARNING_FILTERED = True
    import warnings
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")


def instrumented_jit(fn: Optional[Callable] = None, *, label: str = "",
                     **jit_kwargs) -> Callable:
    """``jax.jit`` with dispatch/compile accounting.

    Usable as ``instrumented_jit(f, label=...)`` or as a decorator
    ``@instrumented_jit(label=..., static_argnames=...)``.  The wrapper is
    call-compatible with the jitted function; the raw jitted callable is
    exposed as ``wrapper.jitted``.  ``donate_argnums`` passes through to
    ``jax.jit``; donated argument bytes are accumulated per dispatch.
    """
    if fn is None:
        return functools.partial(instrumented_jit, label=label, **jit_kwargs)
    name = label or getattr(fn, "__name__", "jit")
    donate = tuple(jit_kwargs.get("donate_argnums") or ())
    if donate and not donation_supported():
        jit_kwargs = {k: v for k, v in jit_kwargs.items()
                      if k != "donate_argnums"}
        donate = ()
    if donate:
        _filter_donation_warning()
    jitted = jax.jit(fn, **jit_kwargs)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not _trace_state_clean():
            # nested call while an outer program is being traced: it
            # inlines into the outer jaxpr, so it is neither a device
            # dispatch nor a separate compile — don't count it (donation
            # of a traced value is likewise meaningless and ignored)
            return jitted(*args, **kwargs)
        # fault-injection site: every real dispatch (not nested traces)
        # counts; disarmed cost is one module-global None test
        _fault_inject.maybe_fire("dispatch")
        if _DONATION_GUARD is not None:
            guard_check((args, kwargs), name)
        donated_bytes = 0
        donated_leaves = ()
        if donate:
            donated_leaves = [
                leaf for i in donate if i < len(args)
                for leaf in jax.tree_util.tree_leaves(args[i])]
            donated_bytes = sum(
                getattr(leaf, "nbytes", 0) for leaf in donated_leaves)
        before = _cache_size(jitted)
        t0 = time.monotonic_ns()
        if donate:
            # a compile triggered by a donating dispatch must neither read
            # nor write the persistent cache (deserialized executables
            # mishandle the donation aliasing — see _install_cache_bypass)
            with _no_persist_scope():
                out = jitted(*args, **kwargs)
        else:
            out = jitted(*args, **kwargs)
        t1 = time.monotonic_ns()
        after = _cache_size(jitted)
        compiled = after >= 0 and after != before
        _record(name, compiled, t1 - t0, donated_bytes)
        if compiled:
            _obs_events.emit_span("dispatch", name, t0=t0, t1=t1,
                                  compiled=True)
        else:
            _obs_events.emit_span("dispatch", name, t0=t0, t1=t1)
        if donated_leaves:
            _guard_mark(name, donated_leaves)
        return out

    wrapper.jitted = jitted
    wrapper.label = name
    return wrapper


# -- jax.monitoring hook (precise backend compile seconds) -------------------

_MONITORING_HOOKED = False


def _on_event_duration(event: str, duration_secs: float, **kw) -> None:
    if "compil" not in event:
        return
    with _LOCK:
        _STATS["backend_compile_ns"] += int(duration_secs * 1e9)
    # the listener fires on the dispatching thread mid-jit, so the
    # current scope is the compiling query's
    sc = _obs_events.current_scope()
    if sc is not None:
        sc.add("backend_compile_ns", int(duration_secs * 1e9))


def _hook_monitoring() -> None:
    global _MONITORING_HOOKED
    if _MONITORING_HOOKED:
        return
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _MONITORING_HOOKED = True
    except Exception:  # noqa: BLE001 — monitoring API is best-effort
        _MONITORING_HOOKED = True  # don't retry every call


_hook_monitoring()


# -- persistent compilation cache --------------------------------------------

_PERSISTENT_DIR: Optional[str] = None


def enable_persistent_cache(cache_dir: str,
                            min_compile_secs: float = 1.0) -> None:
    """Point XLA's persistent compilation cache at ``cache_dir`` (conf
    ``spark.rapids.sql.tpu.compileCacheDir``): executables survive the
    process, so a re-run pre-warms from disk instead of recompiling."""
    global _PERSISTENT_DIR
    if not cache_dir or _PERSISTENT_DIR == cache_dir:
        return
    import os
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    _PERSISTENT_DIR = cache_dir
