"""Shared utilities: tracing/profiler ranges, codecs."""
