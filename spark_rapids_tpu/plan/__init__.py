"""Query planning: logical plan, physical operators, and the TPU-overrides
plan-rewrite machinery (reference: GpuOverrides.scala / RapidsMeta.scala /
GpuTransitionOverrides.scala, SURVEY.md section 2.2)."""
