"""Logical plan nodes built by the DataFrame frontend.

The reference accelerates Spark's physical plans; here the frontend owns the
whole stack, so this logical layer plays Catalyst's role: a typed operator
tree that the physical planner lowers to CPU/TPU execs.  Node set mirrors the
exec inventory of SURVEY.md section 2.5.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs.aggregates import AggregateExpression
from spark_rapids_tpu.exprs.base import Expression, SortOrder


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def schema(self) -> T.Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, depth: int = 0) -> str:
        out = "  " * depth + self.describe() + "\n"
        for c in self.children:
            out += c.tree_string(depth + 1)
        return out

    def describe(self) -> str:
        return self.name


class InMemoryScan(LogicalPlan):
    """Scan over host-resident batches (createDataFrame / test input)."""

    def __init__(self, batches: List, schema: T.Schema,
                 num_partitions: int = 1):
        self.batches = batches  # List[HostBatch]
        self._schema = schema
        self.num_partitions = num_partitions
        self.children = ()

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"InMemoryScan({self._schema})"


class FileScan(LogicalPlan):
    """File-source scan (parquet/csv/orc); decode happens host-side, staged
    to HBM by the physical scan exec (GpuParquetScan analogue)."""

    def __init__(self, fmt: str, paths: List[str], schema: T.Schema,
                 options: Optional[Dict[str, Any]] = None,
                 pushed_filters: Optional[List[Expression]] = None,
                 partitions=None):
        self.fmt = fmt
        self.paths = paths
        self._schema = schema
        self.options = options or {}
        self.pushed_filters = pushed_filters or []
        # Hive-layout partition columns: (partition_schema,
        # {file: [values...]}) — appended as constants per file by the scan
        self.partitions = partitions
        self.children = ()

    @property
    def schema(self):
        return self._schema

    def describe(self):
        extra = f", pushed={len(self.pushed_filters)}" \
            if self.pushed_filters else ""
        return f"FileScan({self.fmt}, {len(self.paths)} files{extra})"


class Range(LogicalPlan):
    """spark.range() analogue (GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1, name: str = "id"):
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self.col_name = name
        self.children = ()

    @property
    def schema(self):
        return T.Schema([(self.col_name, T.LONG)])

    def describe(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class Project(LogicalPlan):
    def __init__(self, exprs: List[Expression], names: List[str],
                 child: LogicalPlan):
        self.exprs = exprs
        self.names = names
        self.children = (child,)

    @property
    def schema(self):
        return T.Schema([
            T.Field(n, e.dtype, e.nullable)
            for n, e in zip(self.names, self.exprs)
        ])

    def describe(self):
        return f"Project({', '.join(self.names)})"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return f"Filter({self.condition!r})"


class Aggregate(LogicalPlan):
    """Groupby aggregation; empty ``keys`` = global reduction."""

    def __init__(self, keys: List[Expression], key_names: List[str],
                 aggs: List[AggregateExpression], child: LogicalPlan):
        self.keys = keys
        self.key_names = key_names
        self.aggs = aggs
        self.children = (child,)

    @property
    def schema(self):
        fields = [T.Field(n, e.dtype, e.nullable)
                  for n, e in zip(self.key_names, self.keys)]
        fields += [T.Field(a.output_name, a.dtype, True) for a in self.aggs]
        return T.Schema(fields)

    def describe(self):
        return (f"Aggregate(keys=[{', '.join(self.key_names)}], "
                f"aggs=[{', '.join(a.output_name for a in self.aggs)}])")


class Sort(LogicalPlan):
    def __init__(self, orders: List[SortOrder], is_global: bool,
                 child: LogicalPlan):
        self.orders = orders
        self.is_global = is_global
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        g = "global" if self.is_global else "local"
        return f"Sort({g}, {len(self.orders)} keys)"


class Join(LogicalPlan):
    JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti",
                  "cross")

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: List[Expression], right_keys: List[Expression],
                 how: str, condition: Optional[Expression] = None):
        assert how in self.JOIN_TYPES, how
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.how = how
        self.condition = condition
        self.children = (left, right)

    @property
    def schema(self):
        left, right = self.children
        if self.how in ("left_semi", "left_anti"):
            return left.schema
        lfields = list(left.schema.fields)
        rfields = list(right.schema.fields)
        if self.how in ("left", "full"):
            rfields = [T.Field(f.name, f.dtype, True) for f in rfields]
        if self.how in ("right", "full"):
            lfields = [T.Field(f.name, f.dtype, True) for f in lfields]
        return T.Schema(lfields + rfields)

    def describe(self):
        return f"Join({self.how})"


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        self.children = tuple(children)
        s0 = self.children[0].schema
        for c in self.children[1:]:
            assert [f.dtype for f in c.schema.fields] == \
                [f.dtype for f in s0.fields], "union schema mismatch"

    @property
    def schema(self):
        return self.children[0].schema


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return f"Limit({self.n})"


class Distinct(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema


class Expand(LogicalPlan):
    """Grouping-sets expansion: each projection list emits one output row set
    (GpuExpandExec analogue)."""

    def __init__(self, projections: List[List[Expression]], names: List[str],
                 child: LogicalPlan):
        self.projections = projections
        self.names = names
        self.children = (child,)

    @property
    def schema(self):
        p0 = self.projections[0]
        return T.Schema([
            T.Field(n, e.dtype, True) for n, e in zip(self.names, p0)
        ])


class Generate(LogicalPlan):
    """explode/posexplode over a per-row repetition (GpuGenerateExec
    analogue).  Round 1: explode of a literal-bounded sequence column model;
    array types land with nested-type support."""

    def __init__(self, generator, output_names: List[str], child: LogicalPlan):
        self.generator = generator
        self.output_names = output_names
        self.children = (child,)

    @property
    def schema(self):
        base = list(self.children[0].schema.fields)
        gen = [T.Field(n, t, True)
               for n, t in zip(self.output_names, self.generator.output_types)]
        return T.Schema(base + gen)


class Window(LogicalPlan):
    def __init__(self, window_exprs, output_names: List[str],
                 child: LogicalPlan):
        self.window_exprs = window_exprs
        self.output_names = output_names
        self.children = (child,)

    @property
    def schema(self):
        base = list(self.children[0].schema.fields)
        extra = [T.Field(n, w.dtype, True)
                 for n, w in zip(self.output_names, self.window_exprs)]
        return T.Schema(base + extra)


class Repartition(LogicalPlan):
    """Explicit exchange: mode in {hash, roundrobin, range, single}."""

    def __init__(self, mode: str, num_partitions: int,
                 keys: List[Expression], child: LogicalPlan,
                 orders: Optional[List[SortOrder]] = None):
        self.mode = mode
        self.num_partitions = num_partitions
        self.keys = keys
        self.orders = orders
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return f"Repartition({self.mode}, {self.num_partitions})"


class Sample(LogicalPlan):
    def __init__(self, fraction: float, seed: int, child: LogicalPlan):
        self.fraction = fraction
        self.seed = seed
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema


class WriteFile(LogicalPlan):
    """Data-writing command (GpuDataWritingCommandExec analogue)."""

    def __init__(self, fmt: str, path: str, mode: str, options: Dict[str, Any],
                 child: LogicalPlan):
        self.fmt = fmt
        self.path = path
        self.mode = mode
        self.options = options
        self.children = (child,)

    @property
    def schema(self):
        return T.Schema([])


class CacheHolder:
    """Materialized cache state shared by all DataFrames over a cached plan
    (the GPU df.cache() analogue; reference: ParquetCachedBatchSerializer,
    shims/spark310 — here cached batches live as catalog-registered
    spillable device batches, so they flow device->host->disk under
    memory pressure instead of being re-encoded as parquet blobs)."""

    def __init__(self):
        self.partitions = None  # List[List[SpillableBatch]] once filled

    @property
    def is_materialized(self) -> bool:
        return self.partitions is not None

    def unpersist(self):
        if self.partitions:
            for part in self.partitions:
                for h in part:
                    h.close()
        self.partitions = None


class CachedRelation(LogicalPlan):
    def __init__(self, child: LogicalPlan, holder: CacheHolder):
        self.children = (child,)
        self.holder = holder

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        state = "materialized" if self.holder.is_materialized else "lazy"
        return f"CachedRelation({state})"


class BroadcastHint(LogicalPlan):
    """Marks a subtree as broadcast-preferred (functions.broadcast(df))."""

    def __init__(self, child: LogicalPlan):
        self.children = (child,)

    @property
    def schema(self):
        return self.children[0].schema


class MapInPandas(LogicalPlan):
    """mapInPandas(fn, schema): fn(Iterator[pd.DataFrame]) ->
    Iterator[pd.DataFrame] per partition (GpuMapInPandasExec analogue)."""

    def __init__(self, fn, schema: T.Schema, child: LogicalPlan):
        self.fn = fn
        self._schema = schema
        self.children = (child,)

    @property
    def schema(self):
        return self._schema


class FlatMapGroupsInPandas(LogicalPlan):
    """groupBy(...).applyInPandas(fn, schema)
    (GpuFlatMapGroupsInPandasExec analogue)."""

    def __init__(self, keys: List[Expression], key_names: List[str], fn,
                 schema: T.Schema, child: LogicalPlan):
        self.keys = keys
        self.key_names = key_names
        self.fn = fn
        self._schema = schema
        self.children = (child,)

    @property
    def schema(self):
        return self._schema


class FlatMapCoGroupsInPandas(LogicalPlan):
    """a.groupBy(k).cogroup(b.groupBy(k)).applyInPandas(fn, schema)
    (GpuFlatMapCoGroupsInPandasExec analogue)."""

    def __init__(self, left_keys, left_names, right_keys, right_names, fn,
                 schema: T.Schema, left: LogicalPlan, right: LogicalPlan):
        self.left_keys = left_keys
        self.left_names = left_names
        self.right_keys = right_keys
        self.right_names = right_names
        self.fn = fn
        self._schema = schema
        self.children = (left, right)

    @property
    def schema(self):
        return self._schema


class AggregateInPandas(LogicalPlan):
    """groupBy(...).agg_in_pandas({out: (fn, dtype, col)}): one output row
    per group, values computed by python over each group's pandas Series
    (GpuAggregateInPandasExec analogue)."""

    def __init__(self, keys: List[Expression], key_names: List[str],
                 agg_specs, child: LogicalPlan):
        self.keys = keys
        self.key_names = key_names
        self.agg_specs = agg_specs  # list of (out_name, fn, dtype, col)
        self.children = (child,)

    @property
    def schema(self):
        fields = [T.Field(n, e.dtype, e.nullable)
                  for n, e in zip(self.key_names, self.keys)]
        fields += [T.Field(n, dt, True) for n, _fn, dt, _c in self.agg_specs]
        return T.Schema(fields)


def plan_fingerprint(plan: LogicalPlan) -> str:
    """Canonical identity of a logical plan for physical-plan reuse.

    Built from node types + their scalar/expression attributes; objects
    without stable reprs (user fns, batch lists) key by python identity —
    collisions are impossible (identity reprs are unique), only *misses*
    for structurally equal but distinct-object inputs, which is safe.
    """
    from spark_rapids_tpu.exprs.base import Expression, SortOrder

    def enc(v):
        if isinstance(v, AggregateExpression):
            return f"AE({v.output_name},{enc(v.fn)})"
        if isinstance(v, Expression):
            # NOT repr(): Expression.__repr__ prints only class + children,
            # omitting scalar attributes (ConcatWs.sep, Lag.offset,
            # window frames...) — encode every non-child attribute too so
            # structurally different expressions never collide.
            parts = [type(v).__name__]
            for k, a in sorted(vars(v).items()):
                if k == "children":
                    continue
                parts.append(f"{k}={enc(a)}")
            kids = ",".join(enc(c) for c in v.children)
            return f"{'|'.join(parts)}({kids})"
        if isinstance(v, SortOrder):
            return (f"SO({enc(v.child)},{v.ascending},{v.nulls_first})")
        if isinstance(v, (str, int, float, bool, type(None))):
            return repr(v)
        if isinstance(v, T.Schema):
            return str(v)
        if isinstance(v, T.DataType):
            return str(v)
        if isinstance(v, (list, tuple)):
            return "[" + ",".join(enc(x) for x in v) + "]"
        if isinstance(v, dict):
            return "{" + ",".join(
                f"{enc(k)}:{enc(x)}" for k, x in sorted(
                    v.items(), key=lambda kv: str(kv[0]))) + "}"
        return f"id:{id(v):x}"  # fns, batch lists, cache holders...

    attrs = []
    for k, v in sorted(vars(plan).items()):
        if k in ("children", "_schema"):
            continue
        attrs.append(f"{k}={enc(v)}")
    kids = ",".join(plan_fingerprint(c) for c in plan.children)
    return f"{plan.name}({';'.join(attrs)})[{kids}]"


class Generate(LogicalPlan):
    """Generator expansion: explode/posexplode of an array column
    (GpuGenerateExec analogue, GpuGenerateExec.scala).  Output = the
    child's other columns repeated per element (+ optional ``pos``) + the
    element column.  ``outer`` keeps empty/NULL-array rows with a NULL
    element (CPU path)."""

    def __init__(self, column: str, alias: str, pos: bool, outer: bool,
                 child: LogicalPlan):
        self.column = column
        self.alias = alias
        self.pos = pos
        self.outer = outer
        self.children = (child,)

    @property
    def schema(self):
        child = self.children[0].schema
        arr = child.field(self.column)
        assert arr.dtype.is_array, f"explode needs an array, got {arr.dtype}"
        fields = [f for f in child.fields if f.name != self.column]
        if self.pos:
            fields.append(T.Field("pos", T.INT, False))
        fields.append(T.Field(self.alias, arr.dtype.element, self.outer))
        return T.Schema(fields)

    def describe(self):
        kind = "posexplode" if self.pos else "explode"
        return f"Generate({kind}({self.column}) as {self.alias})"


class WindowInPandas(LogicalPlan):
    """Whole-partition-frame pandas window: each output row carries
    fn(partition pd.Series) broadcast over its partition
    (GpuWindowInPandasExec analogue — unbounded preceding/following frame,
    the shape pyspark's GROUPED_AGG pandas_udf over a Window takes)."""

    def __init__(self, keys: List[Expression], key_names: List[str],
                 win_specs, child: LogicalPlan):
        self.keys = keys
        self.key_names = key_names
        self.win_specs = win_specs  # list of (out_name, fn, dtype, col)
        self.children = (child,)

    @property
    def schema(self):
        child = self.children[0].schema
        fields = list(child.fields)
        fields += [T.Field(n, dt, True)
                   for n, _fn, dt, _c in self.win_specs]
        return T.Schema(fields)
