"""Whole-pipeline compilation: run an entire TPU query stage as ONE XLA
program (a few, at capacity-reduction boundaries).

The reference amortizes per-op JNI dispatch with batch-level cudf calls; on
TPU (especially a remotely-tunneled one) every dispatched program and every
blocking host transfer costs a round trip that dwarfs the compute, so the
engine's steady state must execute O(1) programs per query, not O(ops).
This module composes the per-batch functions of an all-TPU physical subtree
(map stages, collapsed exchanges, aggregate update/merge, sort, limit,
expand, union) into jitted stage functions over the source batches — the
TPU-native analogue of Spark whole-stage codegen, with XLA doing the
fusion.

Stage boundaries ("stage breaks") sit where live rows collapse far below
capacity (aggregate partials): the driver syncs the live sizes once (one
round trip), re-buckets with a compiled gather, and feeds the shrunk
batches to the next stage — otherwise padded capacities would snowball
through concats and every downstream sort would pay O(padded).

Ops that cannot be inlined (host transitions, joins needing host-visible
output sizing, samples with host RNG) become pipeline *sources*: their
iterator path materializes batches that feed the program as arguments.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, HostBatch, device_to_host_many, host_sizes,
    round_up_capacity,
)
from spark_rapids_tpu.plan.physical import ExecContext, PhysicalOp, TpuExec


def concat_static(batches: List[ColumnBatch], schema: T.Schema
                  ) -> ColumnBatch:
    """In-jit concatenation: output capacity = sum of input *capacities*
    (static — no host sync).  Stage breaks pay the padding back."""
    from spark_rapids_tpu.kernels.layout import concat_pair
    if len(batches) == 1:
        return batches[0]
    cap = round_up_capacity(sum(b.capacity for b in batches))
    byte_caps = []
    for i, f in enumerate(schema.fields):
        if f.dtype.is_string or f.dtype.is_array:
            byte_caps.append(round_up_capacity(
                sum(int(b.columns[i].data.shape[0]) for b in batches),
                minimum=16))
    acc = batches[0]
    for nxt in batches[1:]:
        acc = concat_pair(acc, nxt, cap, out_byte_caps=byte_caps or None)
    return acc


def build_pipeline(op: PhysicalOp, ctx: ExecContext,
                   sources: List[PhysicalOp], memo: dict,
                   root: PhysicalOp) -> Callable:
    """Recursively compose ``op`` into f(args) -> List[ColumnBatch].

    ``args`` is a tuple aligned with ``sources``: args[i] is the tuple of
    batches materialized from sources[i].  Ops whose ``pipeline_inline``
    returns None — and stage-break ops below the stage root — become
    sources.
    """
    if id(op) in memo:
        return memo[id(op)]
    f = None
    if isinstance(op, TpuExec) and not (
            op is not root and getattr(op, "pipeline_stage_break", False)):
        f = op.pipeline_inline(
            ctx,
            lambda child: build_pipeline(child, ctx, sources, memo, root))
    if f is None:
        idx = len(sources)
        sources.append(op)
        f = lambda args, _i=idx: list(args[_i])  # noqa: E731
    memo[id(op)] = f
    return f


# Padded outputs smaller than this skip the sizes round-trip + shrink.
_SHRINK_BYTES = 4 << 20


def _batch_padded_bytes(b: ColumnBatch) -> int:
    total = 0
    for c in b.columns:
        total += c.data.size * c.data.dtype.itemsize
        total += c.validity.size * c.validity.dtype.itemsize
        if c.offsets is not None:
            total += c.offsets.size * c.offsets.dtype.itemsize
    return total


@functools.partial(jax.jit, static_argnames=("caps", "bcapss"))
def _shrink_jit(bs: Tuple[ColumnBatch, ...], caps: Tuple[int, ...],
                bcapss: Tuple[Tuple[int, ...], ...]):
    from spark_rapids_tpu.kernels.layout import gather_rows
    out = []
    for b, cap, bcaps in zip(bs, caps, bcapss):
        idx = jnp.arange(cap, dtype=jnp.int32)
        out.append(gather_rows(b, idx, b.num_rows, out_capacity=cap,
                               out_byte_caps=list(bcaps) or None))
    return tuple(out)


def _shrink_outputs(outs: List[ColumnBatch], ctx: ExecContext
                    ) -> List[ColumnBatch]:
    """Sizes round trip + one compiled gather re-bucketing every batch."""
    if not outs or sum(_batch_padded_bytes(b) for b in outs) <= _SHRINK_BYTES:
        return outs
    sizes = host_sizes(outs)
    ctx.metric("pipeline", "shrinks").add(1)
    caps = tuple(round_up_capacity(max(n, 1)) for n, _ in sizes)
    bcapss = tuple(
        tuple(round_up_capacity(max(t, 16), minimum=16) for t in totals)
        for _, totals in sizes)
    return list(_shrink_jit(tuple(outs), caps, bcapss))


def _materialize_source(src: PhysicalOp, ctx: ExecContext
                        ) -> List[ColumnBatch]:
    from spark_rapids_tpu.plan.physical import HostToDeviceExec
    if getattr(src, "pipeline_stage_break", False):
        return _run_stage(src, ctx)
    batches = []
    for part in src.partitions(ctx):
        batches.extend(part)
    if isinstance(src, HostToDeviceExec):
        ctx._pipeline_h2d = getattr(ctx, "_pipeline_h2d", 0) + len(batches)
    return batches


def _stage_program(root: PhysicalOp, ctx: ExecContext, variant: str):
    """(sources, jitted) for one variant of ``root``'s stage (ops like the
    hash aggregate compile a fast path and an exact-fallback path)."""
    cache = getattr(root, "_stage_cache", None)
    if not isinstance(cache, dict):
        cache = {}
        root._stage_cache = cache
    if variant not in cache:
        sources: List[PhysicalOp] = []
        fn = build_pipeline(root, ctx, sources, {}, root)
        cache[variant] = (sources, jax.jit(lambda args: tuple(fn(args))))
    return cache[variant]


def _run_oom_guarded(ctx: ExecContext, thunk, args=()):
    """Dispatch a stage program under the OOM→spill→retry guard
    (DeviceMemoryEventHandler.scala:35 role; see mem.catalog).  ``args`` —
    the stage's input batches, still referenced by the retry — are pinned
    so the spill pass doesn't waste a pass "freeing" live buffers."""
    from spark_rapids_tpu.mem.catalog import run_with_oom_retry
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    pinned = [b for bs in args for b in bs]
    return run_with_oom_retry(
        DeviceRuntime.get(ctx.conf).catalog, thunk, pinned=pinned,
        on_retry=lambda _freed: ctx.metric("pipeline", "oom_retries").add(1))


def _run_stage(root: PhysicalOp, ctx: ExecContext) -> List[ColumnBatch]:
    """Execute ``root``'s stage as one program; shrunk device outputs."""
    variant_fn = getattr(root, "stage_variant", None)
    variant = variant_fn(ctx) if variant_fn is not None else "default"
    sources, jitted = _stage_program(root, ctx, variant)
    args = tuple(tuple(_materialize_source(s, ctx)) for s in sources)
    from spark_rapids_tpu.batch import colocate_batches
    args = tuple(tuple(bs) for bs in colocate_batches(args))
    ctx.metric("pipeline", "programs").add(1)
    outs = _run_oom_guarded(ctx, lambda: _shrink_outputs(list(jitted(args)),
                                                         ctx), args)
    post = getattr(root, "postprocess_stage_outputs", None)
    if post is not None:
        def rerun():
            # the op flipped its variant (e.g. hash -> exact sort);
            # re-execute on the SAME materialized source batches
            v2 = variant_fn(ctx) if variant_fn is not None else "default"
            s2, j2 = _stage_program(root, ctx, v2)
            assert len(s2) == len(sources), "stage variants disagree"
            ctx.metric("pipeline", "programs").add(1)
            return _run_oom_guarded(ctx, lambda: _shrink_outputs(
                list(j2(args)), ctx), args)

        outs = post(ctx, outs, rerun)
    return outs


def pipeline_collect(root: PhysicalOp, ctx: ExecContext
                     ) -> Optional[HostBatch]:
    """Try to run ``root`` as a whole-pipeline program; None if the plan
    doesn't inline anything (caller falls back to the iterator path)."""
    if not root.is_tpu:
        return None
    if ctx.conf.get("spark.rapids.sql.tpu.pipeline.enabled", True) \
            in (False, "false"):
        return None

    probe = getattr(root, "_pipeline_viable", None)
    if probe is None:
        sources: List[PhysicalOp] = []
        build_pipeline(root, ctx, sources, {}, root)
        probe = not (len(sources) == 1 and sources[0] is root)
        root._pipeline_viable = probe
    if not probe:
        return None

    ctx._pipeline_h2d = 0
    try:
        outs = _run_stage(root, ctx)
        hbs = [hb for hb in device_to_host_many(outs) if hb.num_rows]
    finally:
        if ctx.semaphore is not None:
            for _ in range(getattr(ctx, "_pipeline_h2d", 0)):
                ctx.semaphore.release()
    if not hbs:
        from spark_rapids_tpu.plan.physical import _empty_host_col
        return HostBatch(root.output_schema, [
            _empty_host_col(f) for f in root.output_schema.fields])
    return HostBatch.concat(hbs)
