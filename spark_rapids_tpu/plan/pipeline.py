"""Whole-pipeline compilation: run an entire TPU query stage as ONE XLA
program (a few, at capacity-reduction boundaries).

The reference amortizes per-op JNI dispatch with batch-level cudf calls; on
TPU (especially a remotely-tunneled one) every dispatched program and every
blocking host transfer costs a round trip that dwarfs the compute, so the
engine's steady state must execute O(1) programs per query, not O(ops).
This module composes the per-batch functions of an all-TPU physical subtree
(map stages, collapsed exchanges, aggregate update/merge, sort, limit,
expand, union) into jitted stage functions over the source batches — the
TPU-native analogue of Spark whole-stage codegen, with XLA doing the
fusion.

Stage boundaries ("stage breaks") sit where live rows collapse far below
capacity (aggregate partials): the driver syncs the live sizes once (one
round trip), re-buckets the shrunk batches and feeds them to the next stage
— otherwise padded capacities would snowball through concats and every
downstream sort would pay O(padded).  With
``spark.rapids.sql.tpu.pipeline.fuseTail.enabled`` (default) the
re-bucketing gather is not a separate dispatched program: it compiles INTO
the consuming tail stage (cached per shrunk-bucket signature), so the
final merge-aggregate/order-by/limit tail costs one dispatch, not two.

Ops that cannot be inlined (host transitions, joins needing host-visible
output sizing, samples with host RNG) become pipeline *sources*: their
iterator path materializes batches that feed the program as arguments.

Data-plane economics (docs/dataplane.md): consumed source batches —
stage-break intermediates and fresh host->device stagings — are DONATED
to the stage program (``donate_argnums``), so XLA reuses their HBM for
outputs instead of holding two full copies; with
``spark.rapids.sql.tpu.pipeline.asyncPartitions.enabled`` every source's
program is dispatched before any blocking sync and all stage-break size
fetches ride one batched round trip.

Every stage program dispatch is counted and device-timed
(utils/compile_registry + utils/tracing), feeding the per-query
``dispatchCount`` / ``compileCount`` / ``deviceTimeNs`` metrics.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    BUCKETS, ColumnBatch, HostBatch, device_to_host_many, host_sizes,
    round_up_capacity,
)
from spark_rapids_tpu.plan.physical import ExecContext, PhysicalOp, TpuExec
from spark_rapids_tpu.utils.compile_registry import instrumented_jit
from spark_rapids_tpu.utils.tracing import device_dispatch


def concat_static(batches: List[ColumnBatch], schema: T.Schema
                  ) -> ColumnBatch:
    """In-jit concatenation: output capacity = sum of input *capacities*
    (static — no host sync).  Stage breaks pay the padding back.  One
    single-allocation k-way kernel writes each input once at its offset;
    the pairwise chain this replaced materialized k-1 growing
    intermediates inside the program (O(k * out_capacity) HBM traffic)."""
    from spark_rapids_tpu.kernels.layout import concat_kway
    if len(batches) == 1:
        return batches[0]
    cap = round_up_capacity(sum(b.capacity for b in batches))

    def _col_elem_cap(c):
        # Dictionary-encoded inputs materialize inside concat_kway's
        # row-layout guard: size the output for the decoded bytes, not
        # the dictionary's.
        if c.codes is not None:
            return max(int(c.mat_byte_cap), 16)
        return int(c.data.shape[0])

    byte_caps = []
    for i, f in enumerate(schema.fields):
        if f.dtype.is_string or f.dtype.is_array:
            byte_caps.append(BUCKETS.elems(
                sum(_col_elem_cap(b.columns[i]) for b in batches)))
    return concat_kway(batches, cap, out_byte_caps=byte_caps or None)


def build_pipeline(op: PhysicalOp, ctx: ExecContext,
                   sources: List[PhysicalOp], memo: dict,
                   root: PhysicalOp) -> Callable:
    """Recursively compose ``op`` into f(args) -> List[ColumnBatch].

    ``args`` is a tuple aligned with ``sources``: args[i] is the tuple of
    batches materialized from sources[i].  Ops whose ``pipeline_inline``
    returns None — and stage-break ops below the stage root — become
    sources.
    """
    if id(op) in memo:
        return memo[id(op)]
    f = None
    if isinstance(op, TpuExec) and not (
            op is not root and getattr(op, "pipeline_stage_break", False)):
        f = op.pipeline_inline(
            ctx,
            lambda child: build_pipeline(child, ctx, sources, memo, root))
    if f is None:
        idx = len(sources)
        sources.append(op)
        f = lambda args, _i=idx: list(args[_i])  # noqa: E731
    memo[id(op)] = f
    return f


class MeshBuildScope:
    """Build-time channel between the stage builder and mesh-fusable ops,
    active only while ``ExecContext.mesh_spmd_active()``.

    ``TpuShuffleExchangeExec.pipeline_inline`` appends itself to
    ``exchanges`` when it fuses as an in-program all_to_all instead of
    becoming a host-driven stage source; join execs append themselves to
    ``joins`` when they lower per-shard with static bucketed output
    sizing, and ``TpuBroadcastHashJoinExec`` records in ``replicated``
    the source indices its build side added, so parallel.mesh_spmd feeds
    those sources as PartitionSpec-() replicated globals.  ``sources``
    aliases the stage's live source list, letting ops observe indices as
    ``build_pipeline`` appends."""

    def __init__(self, sources: List[PhysicalOp]):
        self.sources = sources
        self.exchanges: List[PhysicalOp] = []
        self.replicated: set = set()
        self.joins: List[PhysicalOp] = []


_MESH_BUILD = threading.local()


def mesh_build_scope() -> Optional[MeshBuildScope]:
    """The innermost active mesh-SPMD build scope; None outside a stage
    build or when SPMD fusion is off — ops treat None as 'do not
    mesh-fuse', which routes exchanges to the host-driven mesh path."""
    if getattr(_MESH_BUILD, "disabled", False):
        return None
    stack = getattr(_MESH_BUILD, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def mesh_fusion_disabled():
    """Scoped off-switch for mesh-SPMD fusion: while active,
    :func:`mesh_build_scope` reports no scope, so every exchange and
    join lowers host-driven.  The bucketed-join overflow fallback
    rebuilds an overflowed stage under this to get the classic
    host-synced plan (see :func:`run_stage_unfused`)."""
    prev = getattr(_MESH_BUILD, "disabled", False)
    _MESH_BUILD.disabled = True
    try:
        yield
    finally:
        _MESH_BUILD.disabled = prev


def _mesh_scoped_build(root: PhysicalOp, ctx: ExecContext,
                       sources: List[PhysicalOp]):
    """Run :func:`build_pipeline` under a :class:`MeshBuildScope` when
    SPMD fusion is active for ``ctx``; (fn, scope-or-None)."""
    if not ctx.mesh_spmd_active():
        return build_pipeline(root, ctx, sources, {}, root), None
    scope = MeshBuildScope(sources)
    stack = getattr(_MESH_BUILD, "stack", None)
    if stack is None:
        stack = _MESH_BUILD.stack = []
    stack.append(scope)
    try:
        fn = build_pipeline(root, ctx, sources, {}, root)
    finally:
        stack.pop()
    return fn, scope


def _shrink_threshold(ctx: ExecContext) -> int:
    """Padded outputs at or below this skip the sizes round-trip + shrink."""
    from spark_rapids_tpu.config import PIPELINE_SHRINK_BYTES
    return PIPELINE_SHRINK_BYTES.get(ctx.conf)


def _fuse_tail_enabled(ctx: ExecContext) -> bool:
    from spark_rapids_tpu.config import PIPELINE_FUSE_TAIL
    return PIPELINE_FUSE_TAIL.get(ctx.conf)


def _donation_enabled(ctx: ExecContext) -> bool:
    from spark_rapids_tpu.config import DONATION_ENABLED
    from spark_rapids_tpu.utils.compile_registry import donation_supported
    # donation_supported() guards the fallback where the persistent-cache
    # bypass could not install and instrumented_jit strips donate_argnums:
    # the "donating" jits then don't donate, and treating them as donating
    # here would needlessly disable the OOM spill-retry (retryable=False)
    return DONATION_ENABLED.get(ctx.conf) and donation_supported()


def _async_partitions(ctx: ExecContext) -> bool:
    from spark_rapids_tpu.config import PIPELINE_ASYNC_PARTITIONS
    return PIPELINE_ASYNC_PARTITIONS.get(ctx.conf)


def _stage_may_rerun(root: PhysicalOp, ctx: ExecContext) -> bool:
    """True when the stage's epilogue may re-dispatch on the SAME
    materialized inputs (hash-agg exact fallback): those inputs must then
    never be donated."""
    probe = getattr(root, "stage_may_rerun", None)
    return bool(probe(ctx)) if probe is not None else False


def _batch_padded_bytes(b: ColumnBatch) -> int:
    total = 0
    for c in b.columns:
        total += c.data.size * c.data.dtype.itemsize
        total += c.validity.size * c.validity.dtype.itemsize
        if c.offsets is not None:
            total += c.offsets.size * c.offsets.dtype.itemsize
        if c.codes is not None:
            total += c.codes.size * c.codes.dtype.itemsize
    return total


def _shrink_gather(b: ColumnBatch, cap: int, bcaps: Tuple[int, ...]
                   ) -> ColumnBatch:
    """One compiled gather re-bucketing ``b`` to (cap, bcaps) — traceable,
    used both by the standalone shrink program and inlined in fused tail
    stage prologues."""
    from spark_rapids_tpu.kernels.layout import gather_rows
    idx = jnp.arange(cap, dtype=jnp.int32)
    return gather_rows(b, idx, b.num_rows, out_capacity=cap,
                       out_byte_caps=list(bcaps) or None)


def _shrink_many(bs: Tuple[ColumnBatch, ...], caps: Tuple[int, ...],
                 bcapss: Tuple[Tuple[int, ...], ...]):
    return tuple(_shrink_gather(b, cap, bcaps)
                 for b, cap, bcaps in zip(bs, caps, bcapss))


# Two compiled variants of the stage-break re-bucketing gather: the
# donating one consumes its inputs (raw stage outputs — nothing else ever
# references them, and an OOM retry recomputes them from the stage
# program), so XLA reuses their HBM for the shrunk outputs.
_shrink_jit = instrumented_jit(
    _shrink_many, label="pipeline:shrink",
    static_argnames=("caps", "bcapss"))
_shrink_jit_donate = instrumented_jit(
    _shrink_many, label="pipeline:shrink",
    static_argnames=("caps", "bcapss"), donate_argnums=(0,))


def _spec_of(sizes) -> tuple:
    """(row cap, varlen byte caps) re-bucketing spec from host-fetched
    (num_rows, [varlen totals]) pairs."""
    return tuple(
        (BUCKETS.rows(n), tuple(BUCKETS.elems(t) for t in totals))
        for n, totals in sizes)


def _worth_shrinking(outs: List[ColumnBatch], ctx: ExecContext) -> bool:
    return bool(outs) and sum(_batch_padded_bytes(b) for b in outs) > \
        _shrink_threshold(ctx)


def _record_break_stats(ctx: ExecContext, sizes) -> None:
    """Stage-break live sizes feed the adaptive statistics pool
    (aqeStatsRows): the sizes round trip was paid for the re-bucketing
    anyway, so accounting the rows it revealed keeps the pipelined path
    inside plan/adaptive's zero-extra-sync contract."""
    ctx.metric("pipeline", "aqeStatsRows").add(
        sum(int(n) for n, _ in sizes))


def _shrink_spec(outs: List[ColumnBatch], ctx: ExecContext):
    """Per-batch re-bucketing spec for a stage break's raw outputs — ONE
    sizes round trip for all batches — or None when the padded total is
    too small to be worth a shrink."""
    if not _worth_shrinking(outs, ctx):
        return None
    sizes = host_sizes(outs)
    _record_break_stats(ctx, sizes)
    return _spec_of(sizes)


def _apply_shrink(outs: List[ColumnBatch], spec: tuple, ctx: ExecContext,
                  guard: bool = False) -> List[ColumnBatch]:
    """One compiled gather re-bucketing every batch to ``spec`` (inputs
    donated when enabled — they are consumed).  ``guard=True`` runs the
    dispatch under the OOM→spill→retry guard for call sites not already
    inside one (standalone stage-break shrinks); a donating shrink still
    fails fast on OOM — its inputs are consumed at dispatch."""
    caps = tuple(c for c, _ in spec)
    bcapss = tuple(bc for _, bc in spec)
    devs = set()
    for b in outs:
        for leaf in jax.tree_util.tree_leaves(b):
            get_devs = getattr(leaf, "devices", None)
            if callable(get_devs):
                devs.update(get_devs())
    if len(devs) > 1:
        # mesh-stage outputs land one batch per mesh device: the gather
        # must dispatch per batch (one jit over the tuple would be an
        # illegal cross-device program, and colocating would drag every
        # shard onto one device).  No donation — per-batch signatures
        # would fragment the donate cache
        per_batch = lambda: [  # noqa: E731
            _shrink_jit((b,), (cap,), (bcaps,))[0]
            for b, cap, bcaps in zip(outs, caps, bcapss)]
        if guard:
            return _run_oom_guarded(ctx, per_batch, (outs,),
                                    retryable=True)
        return per_batch()
    jit = _shrink_jit_donate if _donation_enabled(ctx) else _shrink_jit
    if jit is _shrink_jit_donate:
        leaves = jax.tree_util.tree_leaves(tuple(outs))
        if len({id(leaf) for leaf in leaves}) != len(leaves):
            # a duplicated leaf cannot be donated twice
            jit = _shrink_jit
    run = lambda: list(jit(tuple(outs), caps, bcapss))  # noqa: E731
    if guard:
        return _run_oom_guarded(ctx, run, (outs,),
                                retryable=jit is _shrink_jit)
    return run()


def _shrink_outputs(outs: List[ColumnBatch], ctx: ExecContext
                    ) -> List[ColumnBatch]:
    """Sizes round trip + one compiled gather re-bucketing every batch."""
    spec = _shrink_spec(outs, ctx)
    if spec is None:
        return outs
    ctx.metric("pipeline", "shrinks").add(1)
    return _apply_shrink(outs, spec, ctx)


def _shrink_outputs_sharded(outs: List[ColumnBatch], ctx: ExecContext
                            ) -> List[ColumnBatch]:
    """Mesh-stage variant of :func:`_shrink_outputs`: the unsharded
    outputs are committed one per mesh device, so the re-bucketing gather
    dispatches per batch (each on its own device — ONE jit over the whole
    tuple would be an illegal cross-device program).  Still exactly one
    sizes round trip for the lot.  No donation: per-batch signatures
    would fragment the donate cache, and mesh outputs are short-lived."""
    spec = _shrink_spec(outs, ctx)
    if spec is None:
        return outs
    ctx.metric("pipeline", "shrinks").add(1)
    return [
        _shrink_jit((b,), (cap,), (bcaps,))[0]
        for b, (cap, bcaps) in zip(outs, spec)]


def _materialize_sources(sources: List[PhysicalOp], ctx: ExecContext,
                         fuse: bool) -> List[list]:
    """Materialize every stage source -> [[batches, shrink_spec,
    donatable], ...].

    Dispatch-then-sync: every source's stage program (and iterator path)
    is driven FIRST; the stage-break sizes fetch — the only blocking host
    sync — is then taken for ALL sources in one batched ``host_sizes``
    round trip (asyncPartitions conf; off = one fetch per source, the old
    order).  With tail fusion on, stage-break sources return RAW outputs
    plus the re-bucketing spec the consumer compiles into its own program;
    with it off the shrink gather is dispatched standalone here.

    ``donatable`` marks sources whose batches this stage consumes
    outright: stage-break intermediates and fresh host->device stagings.
    Everything else (cached scans, spill-catalog handles, broadcast
    builds) may be referenced again and must never be donated.
    """
    from spark_rapids_tpu.plan.physical import HostToDeviceExec
    async_on = _async_partitions(ctx)
    mats: List[list] = []
    pending: List[Tuple[int, List[ColumnBatch]]] = []

    def resolve(i: int, spec: tuple) -> None:
        if fuse:
            ctx.metric("pipeline", "fusedShrinks").add(1)
            mats[i][1] = spec
        else:
            ctx.metric("pipeline", "shrinks").add(1)
            mats[i][0] = _apply_shrink(mats[i][0], spec, ctx, guard=True)

    for src in sources:
        if getattr(src, "pipeline_stage_break", False):
            outs = _run_stage(src, ctx, shrink=False)
            mats.append([outs, None, True])
            if _worth_shrinking(outs, ctx):
                if async_on:
                    pending.append((len(mats) - 1, outs))
                else:
                    # sync-per-source: sizes fetch (and shrink) taken
                    # right here, before the next source dispatches —
                    # the old sequential order the conf's off position
                    # promises to restore
                    src_sizes = host_sizes(outs)
                    _record_break_stats(ctx, src_sizes)
                    resolve(len(mats) - 1, _spec_of(src_sizes))
        else:
            batches = []
            for part in src.partitions(ctx):
                batches.extend(part)
            # H2D-side semaphore acquires are counted into
            # ctx._pipeline_h2d at acquire time (HostToDeviceExec), so
            # an abort mid-source releases exactly what was taken
            donatable = isinstance(src, HostToDeviceExec)
            mats.append([batches, None, donatable])
    if pending:
        # one sizes round trip across EVERY stage-break source, taken
        # only after all their programs are in flight
        flat = [b for _, outs in pending for b in outs]
        sizes = host_sizes(flat)
        _record_break_stats(ctx, sizes)
        pos = 0
        for i, outs in pending:
            resolve(i, _spec_of(sizes[pos:pos + len(outs)]))
            pos += len(outs)
    return mats


def _stage_build(root: PhysicalOp, ctx: ExecContext, variant: str):
    """(sources, composed fn) for one variant of ``root``'s stage (ops like
    the hash aggregate compose a fast path and an exact-fallback path)."""
    cache = getattr(root, "_stage_builds", None)
    if not isinstance(cache, dict):
        cache = {}
        root._stage_builds = cache
    if variant not in cache:
        sources: List[PhysicalOp] = []
        fn, scope = _mesh_scoped_build(root, ctx, sources)
        if scope is not None and (scope.exchanges or scope.joins):
            minfo = getattr(root, "_mesh_stage_info", None)
            if not isinstance(minfo, dict):
                minfo = {}
                root._mesh_stage_info = minfo
            minfo[variant] = (list(scope.exchanges),
                              frozenset(scope.replicated),
                              list(scope.joins))
        cache[variant] = (sources, fn)
    return cache[variant]


def _stage_program(root: PhysicalOp, ctx: ExecContext, variant: str,
                   spec: Optional[tuple], dmask: Tuple[bool, ...]):
    """(sources, jitted) for (variant, tail-fusion shrink spec, donation
    mask).

    ``spec`` (one entry per source; None = feed raw) bakes the stage-break
    re-bucketing gathers into the stage program's prologue, so shrink +
    tail ride ONE dispatch.  Power-of-two bucketing keeps the number of
    distinct specs — and therefore compiled tail variants — small.

    ``dmask`` (one bool per source) selects which sources' batches are
    DONATED: the program takes (donated, kept) argument tuples and
    ``donate_argnums`` hands the donated buffers' HBM to XLA for reuse —
    a consumed input batch then never holds a second full copy across the
    dispatch.
    """
    cache = getattr(root, "_stage_cache", None)
    if not isinstance(cache, dict):
        cache = {}
        root._stage_cache = cache
    key = (variant, spec, dmask)
    if key not in cache:
        sources, fn = _stage_build(root, ctx, variant)

        def assemble(dargs, kargs, _mask=dmask):
            di, ki, args = 0, 0, []
            for m in _mask:
                if m:
                    args.append(dargs[di])
                    di += 1
                else:
                    args.append(kargs[ki])
                    ki += 1
            return tuple(args)

        if spec is None or all(s is None for s in spec):
            def run(dargs, kargs):
                return tuple(fn(assemble(dargs, kargs)))
        else:
            def run(dargs, kargs, _spec=spec):
                shrunk = tuple(
                    tuple(bs) if sp is None else tuple(
                        _shrink_gather(b, cap, bcaps)
                        for b, (cap, bcaps) in zip(bs, sp))
                    for bs, sp in zip(assemble(dargs, kargs), _spec))
                return tuple(fn(shrunk))
        jit_kw = {"donate_argnums": (0,)} if any(dmask) else {}
        cache[key] = (sources,
                      instrumented_jit(run, label=f"stage:{root.name}",
                                       **jit_kw))
    return cache[key]


def _run_oom_guarded(ctx: ExecContext, thunk, args=(), retryable=True):
    """Dispatch a stage program under the OOM→spill→retry guard
    (DeviceMemoryEventHandler.scala:35 role; see mem.catalog).  ``args`` —
    the stage's input batches, still referenced by the retry — are pinned
    so the spill pass doesn't waste a pass "freeing" live buffers.
    ``retryable=False`` (donated inputs: consumed at dispatch, a retry
    cannot re-present them) fails fast with the original OOM, TAGGED
    NON_RETRYABLE (fault.errors taxonomy: donated-dispatch OOM) so no
    outer recovery level replays against consumed buffers either."""
    from spark_rapids_tpu.fault.errors import (
        ErrorClass, classify_error, mark_non_retryable,
    )
    from spark_rapids_tpu.mem.catalog import run_with_oom_retry
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    pinned = [b for bs in args for b in bs]
    try:
        return run_with_oom_retry(
            DeviceRuntime.get(ctx.conf).catalog, thunk,
            retries=None if retryable else 0, pinned=pinned,
            on_retry=lambda _freed: ctx.metric("pipeline",
                                               "oom_retries").add(1))
    except Exception as e:
        # only raw XLA OOMs get the donated tag: they come from the
        # dispatch itself, after the inputs were consumed.  An error
        # already carrying an explicit class (an injected fault fires at
        # the call site, BEFORE any buffer is consumed) keeps it — the
        # stage replay is sound there.
        if not retryable and \
                getattr(e, "rapids_error_class", None) is None and \
                classify_error(e) is ErrorClass.RETRYABLE_OOM:
            raise mark_non_retryable(e)
        raise


def _run_stage(root: PhysicalOp, ctx: ExecContext,
               shrink: bool = True) -> List[ColumnBatch]:
    """Execute ``root``'s stage as one program.  ``shrink=True`` (the
    default, for directly-collected stages) re-buckets the outputs;
    ``shrink=False`` hands raw outputs to a tail-fusing consumer."""
    variant_fn = getattr(root, "stage_variant", None)
    variant = variant_fn(ctx) if variant_fn is not None else "default"
    sources, _fn = _stage_build(root, ctx, variant)
    minfo = getattr(root, "_mesh_stage_info", None)
    if isinstance(minfo, dict) and variant in minfo:
        # the build fused at least one exchange as an in-program
        # all_to_all (or a join as a per-shard static kernel): this
        # stage MUST run as a mesh-sharded shard_map program — the
        # single-device path below would trace lax.axis_index with no
        # mesh axis bound
        from spark_rapids_tpu.parallel.mesh_spmd import run_mesh_stage

        def dispatch_mesh(v: str) -> List[ColumnBatch]:
            return run_mesh_stage(root, ctx, v, shrink=shrink)

        outs = dispatch_mesh(variant)
        post = getattr(root, "postprocess_stage_outputs", None)
        if post is not None:
            def rerun_mesh():
                v2 = variant_fn(ctx) if variant_fn is not None \
                    else "default"
                return dispatch_mesh(v2)

            outs = post(ctx, outs, rerun_mesh)
        return outs
    return _run_stage_host(root, ctx, variant, sources, shrink)


def run_stage_unfused(root: PhysicalOp, ctx: ExecContext, variant: str,
                      shrink: bool = True) -> List[ColumnBatch]:
    """Host-driven rerun of a fused mesh stage (the bucketed-join
    overflow fallback, parallel.mesh_spmd): rebuild the stage with mesh
    fusion disabled under a distinct ``nomesh:`` variant key — the
    unfused build/program caches never collide with the fused ones and
    the minfo lookup above misses — then dispatch through the normal
    host path (joins revert to the host-synced two-phase kernel)."""
    v = "nomesh:" + variant
    with mesh_fusion_disabled():
        sources, _fn = _stage_build(root, ctx, v)
    return _run_stage_host(root, ctx, v, sources, shrink, unfused=True)


def _run_stage_host(root: PhysicalOp, ctx: ExecContext, variant: str,
                    sources: List[PhysicalOp], shrink: bool,
                    unfused: bool = False) -> List[ColumnBatch]:
    variant_fn = getattr(root, "stage_variant", None)
    fuse = _fuse_tail_enabled(ctx)
    mats = _materialize_sources(sources, ctx, fuse)
    args = tuple(tuple(bs) for bs, _, _ in mats)
    spec = tuple(sp for _, sp, _ in mats) if fuse else None
    from spark_rapids_tpu.batch import colocate_batches
    args = tuple(tuple(bs) for bs in colocate_batches(args))
    donate = _donation_enabled(ctx) and not _stage_may_rerun(root, ctx)
    dmask = tuple(bool(donate and d) for _, _, d in mats)
    if any(dmask):
        leaves = jax.tree_util.tree_leaves(
            tuple(a for a, m in zip(args, dmask) if m))
        if len({id(leaf) for leaf in leaves}) != len(leaves):
            # a duplicated leaf cannot be donated twice — keep everything
            dmask = tuple(False for _ in dmask)

    def dispatch(v: str) -> List[ColumnBatch]:
        s2, jitted = _stage_program(root, ctx, v, spec, dmask)
        assert len(s2) == len(sources), "stage variants disagree"
        ctx.metric("pipeline", "programs").add(1)
        dargs = tuple(a for a, m in zip(args, dmask) if m)
        kargs = tuple(a for a, m in zip(args, dmask) if not m)
        with device_dispatch(ctx, "pipeline", root.name,
                             obs_op=root.op_id) as holder:
            outs = _run_oom_guarded(
                ctx,
                lambda: _shrink_outputs(list(jitted(dargs, kargs)), ctx)
                if shrink else list(jitted(dargs, kargs)),
                args, retryable=not any(dmask))
            holder["outputs"] = outs
        return outs

    outs = dispatch(variant)
    post = getattr(root, "postprocess_stage_outputs", None)
    if post is not None:
        def rerun():
            # the op flipped its variant (e.g. hash -> exact sort);
            # re-execute on the SAME materialized source batches
            v2 = variant_fn(ctx) if variant_fn is not None else "default"
            if unfused:
                v2 = "nomesh:" + v2
                with mesh_fusion_disabled():
                    _stage_build(root, ctx, v2)
            return dispatch(v2)

        outs = post(ctx, outs, rerun)
    return outs


def pipeline_collect(root: PhysicalOp, ctx: ExecContext
                     ) -> Optional[HostBatch]:
    """Try to run ``root`` as a whole-pipeline program; None if the plan
    doesn't inline anything (caller falls back to the iterator path)."""
    from spark_rapids_tpu.config import PIPELINE_ENABLED
    if not root.is_tpu:
        return None
    if not PIPELINE_ENABLED.get(ctx.conf):
        return None

    probe = getattr(root, "_pipeline_viable", None)
    if probe is None:
        sources: List[PhysicalOp] = []
        # probe under the mesh scope too: with SPMD fusion on, a plan
        # whose root consumes only a fused exchange (repartition/distinct
        # collected straight off the shuffle) is viable even though the
        # scope-less build would leave root as its own sole source
        _mesh_scoped_build(root, ctx, sources)
        probe = not (len(sources) == 1 and sources[0] is root)
        root._pipeline_viable = probe
    if not probe:
        return None

    ctx._pipeline_h2d = 0
    try:
        outs = _run_stage(root, ctx)
        hbs = [hb for hb in device_to_host_many(outs) if hb.num_rows]
    finally:
        from spark_rapids_tpu.plan.physical import _release_admission
        if ctx.semaphore is not None:
            _release_admission(ctx, getattr(ctx, "_pipeline_h2d", 0))
        else:
            ctx._pipeline_h2d = 0
    frag_key = getattr(ctx, "_history_frag_key", None)
    if frag_key is not None and getattr(ctx, "logical_plan", None) is not None:
        # adopt the outputs into the cross-query fragment cache
        # (history.fragcache) AFTER the D2H landed: registering first
        # would let budget pressure spill a batch mid-transfer.  Only
        # this path inserts — its outs are always fresh jitted-program
        # outputs, never aliases of cached source batches.
        from spark_rapids_tpu.history.fragcache import fragment_cache
        fragment_cache().insert(frag_key, ctx.logical_plan, outs, ctx)
    if not hbs:
        from spark_rapids_tpu.plan.physical import _empty_host_col
        return HostBatch(root.output_schema, [
            _empty_host_col(f) for f in root.output_schema.fields])
    return HostBatch.concat(hbs)
