"""TPU overrides: tag every logical operator for TPU support, lower supported
ones to TPU execs and the rest to CPU execs, insert exchanges and
host<->device transitions, and produce the explain output.

Reference analogue: GpuOverrides.scala (rule registry + wrap/tag/convert,
:1884-1902), RapidsMeta.scala (tagging tree, willNotWorkOnGpu reasons :127),
GpuTransitionOverrides.scala (transition insertion :38-221).  Differences are
deliberate: the engine owns the frontend, so tagging happens on the *logical*
plan and the physical planner (exchange insertion, two-phase agg split) runs
fused with conversion — one pass instead of Catalyst's two.

Per-operator conf gates mirror the reference's generated keys
(GpuOverrides.scala:129-137): ``spark.rapids.sql.exec.<Name>`` and
``spark.rapids.sql.expression.<Name>``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exprs.base import (
    ColumnRef, Expression, SortOrder, resolve,
)
from spark_rapids_tpu.exprs.aggregates import AggregateFunction
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.ops import cpu_exec as C
from spark_rapids_tpu.ops import tpu_exec as X
from spark_rapids_tpu.parallel.exchange import (
    CpuBroadcastExchangeExec, CpuShuffleExchangeExec, TpuShuffleExchangeExec,
)
from spark_rapids_tpu.parallel.partitioning import (
    HashPartitioning, Partitioning, RangePartitioning, RoundRobinPartitioning,
    SinglePartitioning,
)
from spark_rapids_tpu.plan.physical import (
    DeviceToHostExec, HostToDeviceExec, PhysicalOp,
)


class ExprMeta:
    """Tags one expression tree (BaseExprMeta analogue,
    RapidsMeta.scala:656)."""

    def __init__(self, expr: Expression, conf: RapidsConf):
        self.expr = expr
        self.conf = conf
        self.reasons: List[str] = []
        self._tag(expr)

    def _tag(self, e: Expression):
        cls = type(e)
        if cls.tpu_eval is Expression.tpu_eval and \
                not isinstance(e, AggregateFunction):
            self.reasons.append(
                f"expression {e.name} has no TPU implementation")
        else:
            reason = e.tpu_supported(self.conf)
            if reason:
                self.reasons.append(f"expression {e.name}: {reason}")
        key = f"spark.rapids.sql.expression.{e.name}"
        if self.conf.get(key, True) in (False, "false"):
            self.reasons.append(
                f"expression {e.name} disabled by {key}")
        for c in e.children:
            self._tag(c)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons


class PlanMeta:
    """Tags one logical operator (SparkPlanMeta analogue,
    RapidsMeta.scala:418)."""

    def __init__(self, node: L.LogicalPlan, conf: RapidsConf):
        self.node = node
        self.conf = conf
        self.reasons: List[str] = []
        self.children = [PlanMeta(c, conf) for c in node.children]

    def will_not_work(self, reason: str):
        self.reasons.append(reason)

    def check_exprs(self, *exprs: Expression):
        for e in exprs:
            m = ExprMeta(e, self.conf)
            self.reasons.extend(m.reasons)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def explain_lines(self, depth: int = 0) -> List[str]:
        ind = "  " * depth
        name = self.node.name
        if self.can_run_on_tpu:
            lines = [f"{ind}*{name} will run on TPU"]
        else:
            why = "; ".join(self.reasons)
            lines = [f"{ind}!{name} cannot run on TPU because {why}"]
        for c in self.children:
            lines.extend(c.explain_lines(depth + 1))
        return lines


class TpuOverrides:
    """The plan rewriter: logical plan -> physical plan with per-operator
    TPU/CPU placement, exchanges and transitions."""

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.last_explain: str = ""

    # ------------------------------------------------------------------ tag

    def tag(self, meta: PlanMeta):
        for c in meta.children:
            self.tag(c)
        node = meta.node
        conf = self.conf
        if not conf.sql_enabled:
            meta.will_not_work("spark.rapids.sql.enabled is false")
            return
        key = f"spark.rapids.sql.exec.{node.name}"
        if conf.get(key, True) in (False, "false"):
            meta.will_not_work(f"disabled by {key}")

        # nested-type gating: array columns ride the varlen device layout
        # but only project/filter/explode consume them on TPU (the reference
        # gates nested types per-op the same way, GpuOverrides.scala:397-409)
        if not isinstance(node, (L.Project, L.Filter, L.Generate,
                                 L.InMemoryScan, L.FileScan, L.Union,
                                 L.Limit, L.CachedRelation)):
            schemas = [c.schema for c in node.children]
            if any(f.dtype.is_array for s in schemas for f in s.fields):
                meta.will_not_work(
                    "array columns: only project/filter/explode run on TPU")

        if isinstance(node, (L.InMemoryScan, L.FileScan)):
            # Scans decode on host by design (SURVEY.md section 7: host Arrow
            # decode staged into HBM); they are CPU execs + HostToDevice.
            meta.will_not_work("scans decode host-side (by design)")
        elif isinstance(node, L.CachedRelation):
            pass  # cached device batches are always TPU-resident
        elif isinstance(node, L.Project):
            meta.check_exprs(*node.exprs)
        elif isinstance(node, L.Filter):
            meta.check_exprs(node.condition)
        elif isinstance(node, L.Aggregate):
            meta.check_exprs(*node.keys)
            self._tag_string_keys(meta, node.keys, "group by")
            for a in node.aggs:
                meta.check_exprs(a.fn.child)
                reason = a.fn.tpu_supported(conf)
                if reason:
                    meta.will_not_work(f"aggregate {a.fn.name}: {reason}")
                if any(k.dtype.is_fractional for k in node.keys) and \
                        conf.has_nans:
                    meta.will_not_work(
                        "grouping by floating point when NaNs possible; set "
                        "spark.rapids.sql.hasNans=false to enable")
        elif isinstance(node, L.Sort):
            for o in node.orders:
                meta.check_exprs(o.child)
        elif isinstance(node, L.Join):
            meta.check_exprs(*node.left_keys, *node.right_keys)
            self._tag_string_keys(
                meta, list(node.left_keys) + list(node.right_keys), "join")
            if node.condition is not None:
                # conditions gate matches inside the join kernel for every
                # join type (GpuHashJoin.scala:265-271 parity)
                meta.check_exprs(node.condition)
        elif isinstance(node, L.Expand):
            for proj in node.projections:
                meta.check_exprs(*proj)
        elif isinstance(node, L.Window):
            for w in node.window_exprs:
                reason = w.tpu_supported(conf)
                if reason:
                    meta.will_not_work(reason)
        elif isinstance(node, L.Repartition):
            for k in node.keys:
                meta.check_exprs(k)
        elif isinstance(node, L.Generate):
            if node.outer:
                meta.will_not_work(
                    "explode_outer emits NULL-element rows (CPU path)")
            arr = node.children[0].schema.field(node.column)
            if not arr.dtype.is_array:
                meta.will_not_work(f"explode needs an array, got {arr.dtype}")
        elif isinstance(node, (L.MapInPandas, L.FlatMapGroupsInPandas,
                               L.FlatMapCoGroupsInPandas,
                               L.AggregateInPandas, L.WindowInPandas)):
            meta.will_not_work(
                "pandas exec runs python via the host Arrow path "
                "(GpuArrowEvalPythonExec data flow)")

    def _tag_string_keys(self, meta: PlanMeta, keys, what: str):
        """String keys group/join through 64-bit device hashes (documented
        collision incompat); ``stringHashGroupJoin.enabled=false`` opts the
        op out to the exact CPU path."""
        from spark_rapids_tpu.config import STRING_HASH_JOIN
        if any(k.dtype.is_string for k in keys) and \
                not STRING_HASH_JOIN.get(self.conf):
            meta.will_not_work(
                f"string {what} keys use device 64-bit hashes; disabled "
                "by spark.rapids.sql.stringHashGroupJoin.enabled")

    # -------------------------------------------------------------- convert

    def apply(self, plan: L.LogicalPlan) -> PhysicalOp:
        if self.conf.get("spark.rapids.sql.udfCompiler.enabled", False):
            plan = _compile_plan_udfs(plan)
        if self.conf.get("spark.rapids.sql.scan.pushdown.enabled", True) \
                not in (False, "false"):
            plan = _pushdown_scan_filters(plan)
        meta = PlanMeta(plan, self.conf)
        self.tag(meta)
        self.last_explain = "\n".join(meta.explain_lines())
        if self.conf.explain_enabled:
            # routed through the obs sink (a logger by default) instead of
            # print(): library embedders and pytest capture aren't spammed,
            # and tools can install their own sink (obs.set_explain_sink)
            from spark_rapids_tpu.obs import explain_sink
            explain_sink(self.last_explain)
        phys = self._convert(meta)
        phys = _insert_transitions(phys)
        from spark_rapids_tpu.config import FUSION_ENABLED
        if FUSION_ENABLED.get(self.conf):
            phys = _fuse_map_chains(phys)
        return phys

    def _shuffle_parts(self) -> int:
        return self.conf.shuffle_partitions

    def _convert(self, meta: PlanMeta) -> PhysicalOp:
        node = meta.node
        on_tpu = meta.can_run_on_tpu
        conv = [self._convert(c) for c in meta.children]

        if isinstance(node, L.InMemoryScan):
            return C.CpuInMemoryScanExec(node.batches, node.schema,
                                         node.num_partitions)
        if isinstance(node, L.FileScan):
            from spark_rapids_tpu.config import SCAN_V2_ENABLED
            if SCAN_V2_ENABLED.get(self.conf):
                from spark_rapids_tpu.io.scan_v2 import FileScanV2Exec
                return FileScanV2Exec(node, self.conf)
            from spark_rapids_tpu.io.scan import CpuFileScanExec
            return CpuFileScanExec(node, self.conf)
        if isinstance(node, L.BroadcastHint):
            return conv[0]
        if isinstance(node, L.CachedRelation):
            if not self.conf.sql_enabled:
                return conv[0]  # CPU engine: no device cache
            return X.TpuCachedScanExec(
                node.holder,
                None if node.holder.is_materialized else
                _to_device(conv[0]), node.schema)
        if isinstance(node, L.Range):
            if on_tpu:
                return X.TpuRangeExec(node.start, node.end, node.step,
                                      node.num_partitions, node.schema)
            return C.CpuRangeExec(node.start, node.end, node.step,
                                  node.num_partitions, node.schema)
        if isinstance(node, L.Project):
            if on_tpu:
                return X.TpuProjectExec(node.exprs, conv[0], node.schema)
            return C.CpuProjectExec(node.exprs, conv[0], node.schema)
        if isinstance(node, L.Filter):
            if on_tpu:
                return X.TpuFilterExec(node.condition, conv[0])
            return C.CpuFilterExec(node.condition, conv[0])
        if isinstance(node, L.Aggregate):
            return self._convert_aggregate(node, conv[0], on_tpu)
        if isinstance(node, L.Distinct):
            child = meta.node.children[0]
            keys = [ColumnRef(f.name, f.dtype, f.nullable)
                    for f in child.schema.fields]
            agg = L.Aggregate(keys, [f.name for f in child.schema.fields],
                              [], child)
            return self._convert_aggregate(agg, conv[0], on_tpu)
        if isinstance(node, L.Sort):
            return self._convert_sort(node, conv[0], on_tpu)
        if isinstance(node, L.Join):
            return self._convert_join(node, conv, on_tpu)
        if isinstance(node, L.Union):
            if on_tpu and all(c.is_tpu for c in conv):
                return X.TpuUnionExec(conv, node.schema)
            return C.CpuUnionExec(
                [_to_host(c) for c in conv], node.schema)
        if isinstance(node, L.Limit):
            return self._convert_limit(node, conv[0], on_tpu)
        if isinstance(node, L.Expand):
            flat_projs = node.projections
            if on_tpu:
                return X.TpuExpandExec(flat_projs, conv[0], node.schema)
            return C.CpuExpandExec(flat_projs, conv[0], node.schema)
        if isinstance(node, L.Sample):
            if on_tpu:
                return X.TpuSampleExec(node.fraction, node.seed, conv[0])
            return C.CpuSampleExec(node.fraction, node.seed, conv[0])
        if isinstance(node, L.Repartition):
            part = self._make_partitioning(node)
            if on_tpu:
                return TpuShuffleExchangeExec(part, conv[0])
            return CpuShuffleExchangeExec(part, conv[0])
        if isinstance(node, L.Generate):
            if on_tpu:
                return X.TpuGenerateExec(node.column, node.alias, node.pos,
                                         _to_device(conv[0]), node.schema)
            return C.CpuGenerateExec(node.column, node.alias, node.pos,
                                     node.outer, _to_host(conv[0]),
                                     node.schema)
        if isinstance(node, L.MapInPandas):
            from spark_rapids_tpu.ops.pandas_exec import CpuMapInPandasExec
            return CpuMapInPandasExec(node.fn, _to_host(conv[0]),
                                      node.schema)
        if isinstance(node, L.FlatMapGroupsInPandas):
            from spark_rapids_tpu.ops.pandas_exec import (
                CpuFlatMapGroupsInPandasExec,
            )
            part = HashPartitioning(node.keys, self._shuffle_parts())
            ex = CpuShuffleExchangeExec(part, _to_host(conv[0]))
            return CpuFlatMapGroupsInPandasExec(node.key_names, node.fn, ex,
                                                node.schema)
        if isinstance(node, L.FlatMapCoGroupsInPandas):
            from spark_rapids_tpu.ops.pandas_exec import (
                CpuFlatMapCoGroupsInPandasExec,
            )
            n_parts = self._shuffle_parts()
            lex = CpuShuffleExchangeExec(
                HashPartitioning(node.left_keys, n_parts),
                _to_host(conv[0]))
            rex = CpuShuffleExchangeExec(
                HashPartitioning(node.right_keys, n_parts),
                _to_host(conv[1]))
            return CpuFlatMapCoGroupsInPandasExec(
                node.left_names, node.right_names, node.fn, lex, rex,
                node.schema)
        if isinstance(node, L.AggregateInPandas):
            from spark_rapids_tpu.ops.pandas_exec import (
                CpuAggregateInPandasExec,
            )
            part = HashPartitioning(node.keys, self._shuffle_parts())
            ex = CpuShuffleExchangeExec(part, _to_host(conv[0]))
            return CpuAggregateInPandasExec(node.key_names, node.agg_specs,
                                            ex, node.schema)
        if isinstance(node, L.WindowInPandas):
            from spark_rapids_tpu.ops.pandas_exec import (
                CpuWindowInPandasExec,
            )
            part = HashPartitioning(node.keys, self._shuffle_parts())
            ex = CpuShuffleExchangeExec(part, _to_host(conv[0]))
            return CpuWindowInPandasExec(node.key_names, node.win_specs,
                                         ex, node.schema)
        if isinstance(node, L.Window):
            from spark_rapids_tpu.ops.window import (
                CpuWindowExec, TpuWindowExec,
            )
            w0 = node.window_exprs[0]
            part = HashPartitioning(w0.partition_by,
                                    self._shuffle_parts()) \
                if w0.partition_by else SinglePartitioning()
            if on_tpu:
                ex = X.TpuCoalescedShuffleReaderExec(
                    TpuShuffleExchangeExec(part, _to_device(conv[0])))
                return TpuWindowExec(node.window_exprs, node.output_names,
                                     ex, node.schema)
            ex = CpuShuffleExchangeExec(part, _to_host(conv[0]))
            return CpuWindowExec(node.window_exprs, node.output_names,
                                 ex, node.schema)
        raise NotImplementedError(f"cannot convert {node.name}")

    def _make_partitioning(self, node: L.Repartition) -> Partitioning:
        if node.mode == "hash":
            return HashPartitioning(node.keys, node.num_partitions)
        if node.mode == "roundrobin":
            return RoundRobinPartitioning(node.num_partitions)
        if node.mode == "single":
            return SinglePartitioning()
        if node.mode == "range":
            child = node.children[0]
            ordinals = [child.schema.index_of(o.child.column)
                        for o in node.orders]
            return RangePartitioning(node.orders, ordinals,
                                     node.num_partitions)
        raise ValueError(node.mode)

    def _convert_aggregate(self, node: L.Aggregate, child: PhysicalOp,
                           on_tpu: bool) -> PhysicalOp:
        n_parts = self._shuffle_parts()
        if on_tpu:
            child = _to_device(child)
            buf_schema = X._buffer_schema(node.key_names, node.keys,
                                          node.aggs)
            partial = X.TpuHashAggregateExec(
                "update", node.keys, node.key_names, node.aggs, child,
                buf_schema)
            if node.keys:
                keys = [ColumnRef(n, k.dtype, k.nullable)
                        for n, k in zip(node.key_names, node.keys)]
                part = HashPartitioning(keys, n_parts)
            else:
                part = SinglePartitioning()
            exchange = TpuShuffleExchangeExec(part, partial)
            return X.TpuHashAggregateExec(
                "merge", [ColumnRef(n, k.dtype, k.nullable)
                          for n, k in zip(node.key_names, node.keys)],
                node.key_names, node.aggs, exchange, node.schema)
        # CPU: exchange raw rows by key, then full groupby per partition.
        child = _to_host(child)
        if node.keys:
            part = HashPartitioning(node.keys, n_parts)
        else:
            part = SinglePartitioning()
        exchange = CpuShuffleExchangeExec(part, child)
        return C.CpuAggregateExec(node.keys, [], node.aggs, exchange,
                                  node.schema)

    def _convert_sort(self, node: L.Sort, child: PhysicalOp,
                      on_tpu: bool) -> PhysicalOp:
        # Sort keys that are not plain column refs get projected into hidden
        # columns first (Spark does the same materialization for sort exprs).
        orders = node.orders
        schema = node.schema
        hidden = [o for o in orders
                  if not isinstance(o.child, ColumnRef)]
        if hidden:
            base = [ColumnRef(f.name, f.dtype, f.nullable)
                    for f in schema.fields]
            names = [f.name for f in schema.fields]
            extra, new_orders = [], []
            for i, o in enumerate(orders):
                if isinstance(o.child, ColumnRef):
                    new_orders.append(o)
                else:
                    nm = f"__sortkey_{i}"
                    extra.append(o.child)
                    names.append(nm)
                    new_orders.append(SortOrder(
                        ColumnRef(nm, o.child.dtype, o.child.nullable),
                        o.ascending, o.nulls_first))
            proj_schema = T.Schema(
                list(schema.fields) +
                [T.Field(n, e.dtype, e.nullable)
                 for n, e in zip(names[len(schema.fields):], extra)])
            child = (X.TpuProjectExec(base + extra, _to_device(child),
                                      proj_schema) if on_tpu else
                     C.CpuProjectExec(base + extra, _to_host(child),
                                      proj_schema))
            inner = self._convert_sort(
                L.Sort(new_orders, node.is_global, _FakeNode(proj_schema)),
                child, on_tpu)
            final = [ColumnRef(f.name, f.dtype, f.nullable)
                     for f in schema.fields]
            if on_tpu:
                return X.TpuProjectExec(final, inner, schema)
            return C.CpuProjectExec(final, inner, schema)

        key_ordinals = [schema.index_of(o.child.column) for o in orders]
        if node.is_global:
            part = RangePartitioning(orders, key_ordinals,
                                     self._shuffle_parts())
            child = X.TpuCoalescedShuffleReaderExec(
                TpuShuffleExchangeExec(part, _to_device(child))) \
                if on_tpu else CpuShuffleExchangeExec(part, _to_host(child))
        if on_tpu:
            from spark_rapids_tpu.config import SORT_STRING_PREFIX_BYTES
            return X.TpuSortExec(
                orders, [o.child for o in orders], _to_device(child),
                string_prefix_bytes=SORT_STRING_PREFIX_BYTES.get(self.conf))
        return C.CpuSortExec(orders, key_ordinals, _to_host(child))

    # Heuristic average payload per varlen cell (string bytes / array
    # elements x element width) when actual values are not visible.
    _VARLEN_CELL_BYTES = 24

    def _field_width(self, f: T.Field) -> int:
        """Estimated bytes per row for one output column, mirroring the
        device layout the shuffle split accounts (batch.fixed_row_bytes):
        data itemsize + one validity byte, varlen columns a 4-byte offset
        entry + validity + the heuristic payload."""
        import numpy as np
        if f.dtype.is_string or f.dtype.is_array:
            return 5 + self._VARLEN_CELL_BYTES
        return int(np.dtype(f.dtype.np_dtype).itemsize) + 1

    def _estimate_rows(self, node: L.LogicalPlan):
        """Plan-output row estimate (None = unknown: aggregates, joins
        and other cardinality-changing ops make no guess)."""
        if isinstance(node, L.InMemoryScan):
            return sum(hb.num_rows for hb in node.batches)
        if isinstance(node, L.Range):
            return max(0, -(-(node.end - node.start) // node.step))
        if isinstance(node, L.Limit):
            rows = self._estimate_rows(node.children[0])
            return node.n if rows is None else min(node.n, rows)
        if isinstance(node, L.Sample):
            rows = self._estimate_rows(node.children[0])
            return None if rows is None else int(rows * node.fraction)
        if isinstance(node, (L.Project, L.Filter, L.Distinct, L.Sort,
                             L.CachedRelation, L.BroadcastHint)):
            return self._estimate_rows(node.children[0])
        return None

    def _estimate_size(self, node: L.LogicalPlan):
        """Per-column-aware plan-output byte estimate for broadcast
        decisions (the role Spark statistics play for
        GpuBroadcastHashJoinExec planning).  Scans with visible values
        are measured exactly — string/array payloads counted per cell —
        and every other estimable node multiplies its row estimate by
        ITS OWN output schema's per-column widths, so a narrowing
        projection over a wide scan estimates the projected width, not
        the scan's.  The runtime compares these against actual shuffle
        bytes (aqeEstimateErrorPct, parallel/exchange)."""
        if isinstance(node, L.BroadcastHint):
            return 0
        if isinstance(node, L.InMemoryScan):
            import numpy as np
            total = 0
            for hb in node.batches:
                for f, c in zip(hb.schema.fields, hb.columns):
                    if f.dtype.is_string:
                        total += sum(len(str(x)) for x in c.values
                                     if x is not None) + 5 * len(c.values)
                    elif f.dtype.is_array:
                        ew = int(np.dtype(
                            f.dtype.element.np_dtype).itemsize)
                        total += ew * sum(len(x) for x in c.values
                                          if x is not None) + \
                            5 * len(c.values)
                    else:
                        total += c.values.nbytes + len(c.values)
            return total
        if isinstance(node, L.FileScan):
            import os
            try:
                return sum(os.path.getsize(p) for p in node.paths)
            except OSError:
                return None
        rows = self._estimate_rows(node)
        if rows is None:
            return None
        fields = getattr(node.schema, "fields", None)
        if not fields:
            return None
        return rows * sum(self._field_width(f) for f in fields)

    def _convert_join(self, node: L.Join, conv: List[PhysicalOp],
                      on_tpu: bool) -> PhysicalOp:
        left, right = conv
        if node.how == "cross" or not node.left_keys:
            if on_tpu:
                return X.TpuNestedLoopJoinExec(
                    _to_device(left), _to_device(right), node.how,
                    node.condition, node.schema)
            return C.CpuNestedLoopJoinExec(
                _to_host(left), _to_host(right), node.how, node.condition,
                node.schema)
        if on_tpu:
            threshold = int(self.conf.get(
                "spark.sql.autoBroadcastJoinThreshold", 10 * 1024 * 1024))
            l_est = self._estimate_size(node.children[0])
            r_est = self._estimate_size(node.children[1])
            bc_side = None
            if node.how in ("inner", "left", "left_semi", "left_anti") and \
                    r_est is not None and r_est <= threshold:
                bc_side = "right"
            if node.how in ("inner", "right") and l_est is not None and \
                    l_est <= threshold and (
                        bc_side is None or (r_est is None or l_est < r_est)):
                bc_side = "left"
            if bc_side == "right":
                return X.TpuBroadcastHashJoinExec(
                    _to_device(left), _to_device(right), node.left_keys,
                    node.right_keys, node.how, "right", node.condition,
                    node.schema)
            if bc_side == "left":
                return X.TpuBroadcastHashJoinExec(
                    _to_device(right), _to_device(left), node.left_keys,
                    node.right_keys, node.how, "left", node.condition,
                    node.schema)
        n_parts = self._shuffle_parts()
        lpart = HashPartitioning(node.left_keys, n_parts)
        rpart = HashPartitioning(node.right_keys, n_parts)
        if on_tpu:
            lex = TpuShuffleExchangeExec(lpart, _to_device(left))
            rex = TpuShuffleExchangeExec(rpart, _to_device(right))
            # stash the static estimates: the exchange compares them
            # against actual materialized bytes (aqeEstimateErrorPct) so
            # bench runs quantify planner error
            if l_est is not None:
                lex._aqe_est_bytes = l_est
            if r_est is not None:
                rex._aqe_est_bytes = r_est
            return X.TpuShuffledHashJoinExec(
                lex, rex, node.left_keys, node.right_keys, node.how,
                node.condition, node.schema)
        lex = CpuShuffleExchangeExec(lpart, _to_host(left))
        rex = CpuShuffleExchangeExec(rpart, _to_host(right))
        return C.CpuHashJoinExec(lex, rex, node.left_keys, node.right_keys,
                                 node.how, node.condition, node.schema)

    def _convert_limit(self, node: L.Limit, child: PhysicalOp,
                       on_tpu: bool) -> PhysicalOp:
        if on_tpu:
            local = X.TpuLocalLimitExec(node.n, _to_device(child))
            single = TpuShuffleExchangeExec(SinglePartitioning(), local)
            return X.TpuLocalLimitExec(node.n, single)
        local = C.CpuLocalLimitExec(node.n, _to_host(child))
        single = CpuShuffleExchangeExec(SinglePartitioning(), local)
        return C.CpuLocalLimitExec(node.n, single)


class _FakeNode:
    """Minimal logical-node stand-in for recursive planner helpers."""

    def __init__(self, schema: T.Schema):
        self._schema = schema
        self.children = ()

    @property
    def schema(self):
        return self._schema


def _split_conjuncts(e: Expression) -> List[Expression]:
    from spark_rapids_tpu.exprs.predicates import And
    if isinstance(e, And):
        return _split_conjuncts(e.children[0]) + \
            _split_conjuncts(e.children[1])
    return [e]


def _pushdown_scan_filters(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Push Filter conjuncts into a child FileScan so the parquet reader can
    skip row groups on statistics and prune partition directories
    (GpuParquetScan.scala:217-281 filterBlocks role).  Advisory: the Filter
    stays in place for exact row filtering.

    Non-mutating: untouched subtrees return the ORIGINAL nodes (the
    user-held plan object never changes, and the session's fingerprint
    cache — computed on the pre-rewrite tree — stays hittable)."""
    import copy

    from spark_rapids_tpu.io.scan import extract_pushdown_descriptors
    new_children = [_pushdown_scan_filters(c) for c in plan.children]
    changed = any(n is not o for n, o in zip(new_children, plan.children))
    if isinstance(plan, L.Filter) and \
            isinstance(new_children[0], L.FileScan):
        scan = new_children[0]
        conjuncts = _split_conjuncts(plan.condition)
        pushable = [c for c in conjuncts
                    if extract_pushdown_descriptors([c])]
        if pushable:
            new_scan = L.FileScan(scan.fmt, scan.paths, scan.schema,
                                  scan.options, pushed_filters=pushable,
                                  partitions=scan.partitions)
            return L.Filter(plan.condition, new_scan)
    if not changed:
        return plan
    clone = copy.copy(plan)
    clone.children = tuple(new_children)
    return clone


def _compile_plan_udfs(plan: L.LogicalPlan) -> L.LogicalPlan:
    """udf-compiler analogue (udf-compiler/Plugin.scala:36-94): rewrite
    PythonUDF calls into engine expressions where bytecode compilation
    succeeds; silently keep the UDF (and its CPU fallback) otherwise."""
    from spark_rapids_tpu.exprs.python_udf import PythonUDF
    from spark_rapids_tpu.udf.compiler import CannotCompile, compile_udf

    def fix_expr(e):
        def fn(node):
            if isinstance(node, PythonUDF) and type(node) is PythonUDF:
                try:
                    return compile_udf(node.fn, list(node.children))
                except CannotCompile:
                    return node
            return node
        return e.transform_up(fn)

    new_children = [_compile_plan_udfs(c) for c in plan.children]
    if isinstance(plan, L.Project):
        return L.Project([fix_expr(e) for e in plan.exprs], plan.names,
                         new_children[0])
    if isinstance(plan, L.Filter):
        return L.Filter(fix_expr(plan.condition), new_children[0])
    # other nodes: rebuild children in place
    plan.children = tuple(new_children)
    return plan


def _is_map_like(op: PhysicalOp) -> bool:
    return isinstance(op, (X.TpuProjectExec, X.TpuFilterExec,
                           X.TpuFusedMapExec)) and len(op.children) == 1


def _map_fns(op: PhysicalOp):
    if isinstance(op, X.TpuFusedMapExec):
        return op.fns, op.labels
    return [op.batch_fn], [op.name]


def _fuse_map_chains(op: PhysicalOp) -> PhysicalOp:
    """Dispatch-count optimizer: collapse chains of per-batch map ops into
    one compiled program, and absorb map chains into the per-batch programs
    of aggregation/sort/exchange consumers.  One XLA dispatch then covers
    e.g. filter+project+partial-aggregate — XLA fuses the elementwise work
    into the aggregation's sort pass, and host->device dispatch latency is
    paid once per batch instead of once per operator."""
    from spark_rapids_tpu.parallel.partitioning import (
        HashPartitioning, RoundRobinPartitioning,
    )
    op.children = [_fuse_map_chains(c) for c in op.children]

    if _is_map_like(op) and op.children and _is_map_like(op.children[0]):
        child = op.children[0]
        cf, cl = _map_fns(child)
        of, ol = _map_fns(op)
        return X.TpuFusedMapExec(child.children[0], cf + of,
                                 op.output_schema, cl + ol)

    absorb_ok = (
        (isinstance(op, X.TpuHashAggregateExec) and op.mode == "update") or
        isinstance(op, X.TpuSortExec) or
        (isinstance(op, TpuShuffleExchangeExec) and
         isinstance(op.partitioning,
                    (HashPartitioning, RoundRobinPartitioning)))
    )
    if absorb_ok and op.children and _is_map_like(op.children[0]):
        child = op.children[0]
        fns, _ = _map_fns(child)
        op.absorb_input(fns)
        op.children = [child.children[0]]
    return op


def _to_device(op: PhysicalOp) -> PhysicalOp:
    return op if op.is_tpu else HostToDeviceExec(op)


def _to_host(op: PhysicalOp) -> PhysicalOp:
    return DeviceToHostExec(op) if op.is_tpu else op


def _insert_transitions(op: PhysicalOp) -> PhysicalOp:
    """Final pass: make every edge type-correct (device vs host batches) —
    the GpuTransitionOverrides analogue."""
    new_children = []
    for c in op.children:
        c = _insert_transitions(c)
        if op.is_tpu and not c.is_tpu and \
                not isinstance(op, HostToDeviceExec):
            c = HostToDeviceExec(c)
        elif not op.is_tpu and c.is_tpu and \
                not isinstance(op, DeviceToHostExec):
            c = DeviceToHostExec(c)
        new_children.append(c)
    op.children = new_children
    return op
