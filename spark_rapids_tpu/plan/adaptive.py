"""Runtime-stats replanning (adaptive execution v1).

The static planner (plan/overrides) fixes partition counts and join
strategy from pre-execution size guesses; this module is the runtime
loop that revisits those decisions between stage materialization and
downstream consumption, the role Spark 3.0 AQE + spark-rapids 0.3.0's
GpuCustomShuffleReader / join replanning play for the reference.

Every decision here is driven by statistics the engine ALREADY holds on
the host — the shuffle split's one bulk size fetch records per-piece
``piece_rows``/``piece_bytes`` and per-partition ``_last_part_rows`` /
``_last_part_bytes`` on the exchange (parallel/exchange._split_v2) — so
adaptive planning adds ZERO host round trips.  Three mechanisms, one
conf family (``spark.rapids.sql.tpu.adaptive.enabled``):

* **Post-shuffle coalescing** (:func:`plan_groups`): adjacent small
  target partitions merge until each reaches the coalesce byte target,
  so a many-partition shuffle over a small intermediate collapses to a
  handful of downstream tasks.  Consumers chain the grouped pieces
  lazily — coalesced reads ride the existing k-way gather/concat
  kernels and catalog prefetch, and stay spill-friendly because pieces
  above ``splitCoalesceMaxBytes`` were never merged on device.
* **Dynamic broadcast switch** (ops/tpu_exec.TpuShuffledHashJoinExec
  ``_try_broadcast_switch``): a shuffled hash join whose build-side
  exchange materialized under ``spark.sql.autoBroadcastJoinThreshold``
  actual bytes replans to the broadcast shape, reusing the
  already-materialized pieces as the build and ELIDING the probe-side
  shuffle (the probe exchange's split never runs).  The switch decision
  and build handle are generation-checked so a device-lost replay
  recomputes from lineage.
* **Skew split** (:func:`skew_flags` + the join's per-piece path): a
  target partition far above the median is never merged with its
  neighbors, and the skewed join streams its per-source pieces in
  bounded chunks against the full build side instead of one giant
  concat+join.

The module imports no jax at import time; everything here is host-side
list arithmetic over already-known integers.
"""

from __future__ import annotations

import statistics
from typing import List, Optional, Tuple


# ---------------------------------------------------------------- gates

def enabled(ctx) -> bool:
    """Master gate for every adaptive mechanism."""
    from spark_rapids_tpu.config import TPU_ADAPTIVE_ENABLED
    return TPU_ADAPTIVE_ENABLED.get(ctx.conf)


def coalesce_enabled(ctx) -> bool:
    from spark_rapids_tpu.config import AQE_COALESCE_ENABLED
    return enabled(ctx) and AQE_COALESCE_ENABLED.get(ctx.conf)


def replan_joins_enabled(ctx) -> bool:
    from spark_rapids_tpu.config import AQE_REPLAN_JOINS
    return enabled(ctx) and AQE_REPLAN_JOINS.get(ctx.conf)


# -------------------------------------------------------------- targets

def target_rows(ctx) -> int:
    from spark_rapids_tpu.config import AQE_TARGET_ROWS
    return AQE_TARGET_ROWS.get(ctx.conf)


def target_bytes(ctx) -> int:
    """Coalesce byte target: the adaptive knob, inheriting the legacy
    advisory target when left at 0 so the two confs cannot fight."""
    from spark_rapids_tpu.config import (
        ADAPTIVE_COALESCE_TARGET_BYTES, AQE_TARGET_BYTES,
    )
    v = ADAPTIVE_COALESCE_TARGET_BYTES.get(ctx.conf)
    return v if v > 0 else AQE_TARGET_BYTES.get(ctx.conf)


def target_for(ctx, unit: str) -> int:
    return target_bytes(ctx) if unit == "bytes" else target_rows(ctx)


def skew_factor(ctx) -> float:
    from spark_rapids_tpu.config import AQE_SKEW_FACTOR
    return AQE_SKEW_FACTOR.get(ctx.conf)


def skew_floor(ctx, unit: str) -> int:
    """Absolute size a partition must also exceed to count as skewed
    (0-valued conf inherits the coalesce target: anything under one
    target is never worth splitting)."""
    from spark_rapids_tpu.config import ADAPTIVE_SKEW_THRESHOLD_BYTES
    if unit == "bytes":
        v = ADAPTIVE_SKEW_THRESHOLD_BYTES.get(ctx.conf)
        return v if v > 0 else target_bytes(ctx)
    return target_rows(ctx)


# ---------------------------------------------------------------- stats

def part_stats(child, n_parts: int
               ) -> Tuple[Optional[List[int]], Optional[str]]:
    """Shuffle-recorded per-partition sizes: (sizes, unit) preferring
    bytes over rows (the reference coalesces by map-status BYTES — row
    targets are an order of magnitude off for wide or string-heavy
    rows).  (None, None) when the child recorded nothing."""
    for attr, unit in (("_last_part_bytes", "bytes"),
                       ("_last_part_rows", "rows")):
        v = getattr(child, attr, None)
        if v is not None and len(v) == n_parts:
            return v, unit
    return None, None


def record_stats(ctx, op_id: str, sizes: List[int], unit: str) -> None:
    """Account the host-known statistics an adaptive decision consumed
    (aqeStatsRows/aqeStatsBytes).  These numbers were fetched by the
    shuffle split's own bulk sync — recording them costs nothing."""
    total = sum(sizes)
    name = "aqeStatsBytes" if unit == "bytes" else "aqeStatsRows"
    ctx.metric(op_id, name).add(total)


def note_event(ctx, op_id: str, mechanism: str) -> None:
    """Append a replan event to the context's adaptive log (consumed by
    analysis/plan_verify.check_adaptive_events)."""
    note = getattr(ctx, "note_adaptive", None)
    if note is not None:
        note(op_id, mechanism)
    from spark_rapids_tpu.obs import events as obs_events
    obs_events.emit_instant("adaptive", mechanism, op_id)


# ------------------------------------------------------------- grouping

def group_by_target(items: List, sizes: List[int], target: int
                    ) -> List[List]:
    """Group consecutive items until each group reaches the target — the
    ONE adaptive grouping rule, shared by the shuffle reader, the
    aggregate merge and the shuffled join (which groups (left, right)
    pairs)."""
    groups, cur, cur_sz = [], [], 0
    for it, sz in zip(items, sizes):
        cur.append(it)
        cur_sz += sz
        if cur_sz >= target:
            groups.append(cur)
            cur, cur_sz = [], 0
    if cur or not groups:
        groups.append(cur)
    return groups


def coalesce_partition_lists(parts: List[List], sizes: List[int],
                             target: int) -> List[List]:
    """Group consecutive partitions until each group reaches target."""
    return [[b for p in g for b in p]
            for g in group_by_target(parts, sizes, target)]


def skew_flags(ctx, sizes: List[int], unit: str) -> List[bool]:
    """Per-partition skew marks (AQE OptimizeSkewedJoin role): far above
    the MEDIAN raw size (median over raw partitions, not coalesced
    groups — with few groups the skewed group itself drags the median
    up; it may be 0 when most partitions are empty and one key is hot)
    AND above the absolute floor."""
    if not sizes:
        return []
    med = statistics.median(sizes)
    factor = skew_factor(ctx)
    floor = skew_floor(ctx, unit)
    return [s > factor * med and s > floor for s in sizes]


def plan_groups(ctx, op_id: str, items: List, sizes: List[int],
                unit: str, record: bool = True, detect_skew: bool = True,
                seed_flags: Optional[List[bool]] = None
                ) -> Tuple[List[List], List[bool]]:
    """The coalescing planner: group adjacent small partitions to the
    target while keeping skewed partitions ALONE (a hot partition merged
    into a group would re-serialize the stage the split is trying to
    parallelize).  Returns (groups, per-group skew flag) and accounts
    the aqeCoalescedPartitions / aqeSkewSplits / aqeStats* metrics.

    ``record=False`` skips the stats metrics for callers whose sizes
    came from a fallback host fetch rather than the shuffle's own sync
    (aqeStats* counts only zero-cost, already-known statistics).
    ``detect_skew=False`` disables isolation for consumers that cannot
    act on a skewed partition anyway (a full outer join must see the
    whole pair at once).  ``seed_flags`` are history-seeded skew marks
    (history.seeding, from a previous run's recorded sizes): OR-ed into
    the runtime detection so a known-hot partition is isolated up front
    even when this run's stats alone would not flag it."""
    target = target_for(ctx, unit)
    flags = skew_flags(ctx, sizes, unit) if detect_skew \
        else [False] * len(sizes)
    if seed_flags is not None and detect_skew \
            and len(seed_flags) == len(flags):
        flags = [a or b for a, b in zip(flags, seed_flags)]
    groups: List[List] = []
    gflags: List[bool] = []
    cur: List = []
    cur_sz = 0
    for it, sz, fl in zip(items, sizes, flags):
        if fl:
            if cur:
                groups.append(cur)
                gflags.append(False)
                cur, cur_sz = [], 0
            groups.append([it])
            gflags.append(True)
            continue
        cur.append(it)
        cur_sz += sz
        if cur_sz >= target:
            groups.append(cur)
            gflags.append(False)
            cur, cur_sz = [], 0
    if cur or not groups:
        groups.append(cur)
        gflags.append(False)
    if record:
        record_stats(ctx, op_id, sizes, unit)
    merged_away = len(items) - len(groups)
    if merged_away > 0:
        ctx.metric(op_id, "aqeCoalescedPartitions").add(merged_away)
        note_event(ctx, op_id, "coalesce")
    n_skew = sum(1 for f in gflags if f)
    if n_skew:
        ctx.metric(op_id, "aqeSkewSplits").add(n_skew)
        note_event(ctx, op_id, "skew")
    return groups, gflags


# ------------------------------------------------------ broadcast switch

def broadcast_build_sides(how: str) -> List[str]:
    """Legal build sides for a runtime shuffled->broadcast switch, in
    preference order (right first: the planner's own bias, and probe
    elision then skips the usually-larger left shuffle).  Broadcasting
    the outer side's opposite would drop its unmatched rows."""
    sides = []
    if how in ("inner", "left", "left_semi", "left_anti", "cross"):
        sides.append("right")
    if how in ("inner", "right", "cross"):
        sides.append("left")
    return sides


def broadcast_threshold(ctx) -> int:
    from spark_rapids_tpu.config import AUTO_BROADCAST_THRESHOLD
    return AUTO_BROADCAST_THRESHOLD.get(ctx.conf)
