"""Physical operator model.

Execution contract (the ``doExecuteColumnar(): RDD[ColumnarBatch]`` analogue,
GpuExec.scala:58): every physical op exposes
``partitions(ctx) -> List[Iterator[batch]]`` — a list of lazily-evaluated
per-partition batch iterators.  TPU execs yield device
:class:`~spark_rapids_tpu.batch.ColumnBatch`; CPU (fallback) execs yield host
:class:`~spark_rapids_tpu.batch.HostBatch`.  The planner inserts
:class:`HostToDeviceExec` / :class:`DeviceToHostExec` transitions at every
CPU<->TPU boundary (GpuTransitionOverrides analogue).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterator, List, Optional

import jax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, HostBatch, device_to_host, host_to_device,
)
from spark_rapids_tpu.config import RapidsConf


class Metric:
    """A named SQL-metric (GpuMetricNames analogue, GpuExec.scala:27-56)."""

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self.value = 0

    def add(self, v):
        self.value += v

    def __repr__(self):
        return f"{self.name}={self.value}{self.unit}"


class ExecContext:
    """Per-query execution context: conf, metrics, device admission."""

    def __init__(self, conf: RapidsConf, semaphore=None, device=None,
                 mesh=None):
        self.conf = conf
        self.semaphore = semaphore
        self.device = device
        # multi-device jax.sharding.Mesh when the ICI collective shuffle is
        # active (spark.rapids.shuffle.ici.enabled + >1 device); exchanges
        # then run lax.all_to_all instead of the single-host split
        self.mesh = mesh
        self.metrics: Dict[str, Dict[str, Metric]] = {}
        # spillable handles whose lifetime is the whole query (shuffle
        # outputs survive partition retries, like the reference's shuffle
        # files); collect_host closes them when the query ends
        self._deferred_handles: List = []

    def defer_close(self, handle) -> None:
        self._deferred_handles.append(handle)

    def close_deferred(self) -> None:
        for h in self._deferred_handles:
            h.close()
        self._deferred_handles.clear()

    def metric(self, op_id: str, name: str) -> Metric:
        ops = self.metrics.setdefault(op_id, {})
        if name not in ops:
            ops[name] = Metric(name)
        return ops[name]


class PhysicalOp:
    """Base physical operator."""

    is_tpu = False

    def __init__(self, children: List["PhysicalOp"], output_schema: T.Schema):
        self.children = children
        self.output_schema = output_schema
        self.op_id = f"{type(self).__name__}@{id(self):x}"

    @property
    def name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.name

    def tree_string(self, depth: int = 0) -> str:
        out = "  " * depth + ("*" if self.is_tpu else " ") + \
            self.describe() + "\n"
        for c in self.children:
            out += c.tree_string(depth + 1)
        return out

    def num_partitions(self, ctx: ExecContext) -> int:
        if self.children:
            return self.children[0].num_partitions(ctx)
        return 1

    def partitions(self, ctx: ExecContext) -> List[Iterator]:
        raise NotImplementedError(self.name)


class TpuExec(PhysicalOp):
    """Operator executing on device over ColumnBatch partitions."""

    is_tpu = True

    def pipeline_inline(self, ctx: "ExecContext", build):
        """Whole-pipeline hook (plan/pipeline.py): return
        f(args) -> List[ColumnBatch] composing this op into one jitted
        program (``build(child)`` composes a child), or None to act as a
        pipeline source fed through the iterator path."""
        return None


class CpuExec(PhysicalOp):
    """Host fallback operator over HostBatch partitions."""

    is_tpu = False


class HostToDeviceExec(TpuExec):
    """Stage host batches into HBM (GpuRowToColumnarExec /
    HostColumnarToGpu analogue: acquire semaphore, bulk-copy to device)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.output_schema)

    def describe(self):
        return "HostToDevice"

    def partitions(self, ctx: ExecContext) -> List[Iterator]:
        from spark_rapids_tpu.config import STAGE_READAHEAD_BATCHES
        child_parts = self.children[0].partitions(ctx)
        t_metric = ctx.metric(self.op_id, "stageTime")
        depth = STAGE_READAHEAD_BATCHES.get(ctx.conf)

        def stage(hb, catalog):
            from spark_rapids_tpu.mem.catalog import run_with_oom_retry
            t0 = time.monotonic()
            if ctx.semaphore is not None:
                ctx.semaphore.acquire()
            db = run_with_oom_retry(
                catalog, lambda: host_to_device(hb, device=ctx.device))
            t_metric.add(time.monotonic() - t0)
            return db

        def gen(part):
            from spark_rapids_tpu.runtime.device import DeviceRuntime
            catalog = DeviceRuntime.get(ctx.conf).catalog
            for hb in part:
                yield stage(hb, catalog)

        def stage_nosem(hb, catalog):
            # worker-thread variant: NO semaphore acquire here.  Admission
            # is taken by the CONSUMER below before the batch is yielded
            # downstream, pairing with the release when results leave the
            # device (TpuSemaphore depth is task-wide, so the thread the
            # acquire/release lands on no longer matters); the read-ahead
            # transfer itself rides the catalog's OOM-retry.
            from spark_rapids_tpu.mem.catalog import run_with_oom_retry
            t0 = time.monotonic()
            db = run_with_oom_retry(
                catalog, lambda: host_to_device(hb, device=ctx.device))
            t_metric.add(time.monotonic() - t0)
            return db

        def gen_pipelined(part):
            # Read-ahead staging: a background thread pulls host batches
            # (driving the scan's decode) and stages them into HBM up to
            # ``depth`` ahead, so decode + H2D transfer overlap the
            # consumer's device compute — the reference's read-ahead pool
            # + semaphore shape (GpuParquetScan.scala:647-700) without a
            # dedicated stream: jax dispatch is async, the thread only
            # pays the host-side copy/transfer-enqueue cost.
            import queue
            import threading
            from spark_rapids_tpu.runtime.device import DeviceRuntime
            catalog = DeviceRuntime.get(ctx.conf).catalog
            q: "queue.Queue" = queue.Queue(maxsize=depth)
            stop = threading.Event()
            DONE = object()

            def put_bounded(item):
                # never a blocking put: a consumer that already left its
                # finally-drain must not strand the worker (it would hold
                # generator/device state past the query)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.25)
                        return True
                    except queue.Full:
                        continue
                return False

            def worker():
                try:
                    for hb in part:
                        if stop.is_set():
                            return
                        if not put_bounded(("b", stage_nosem(hb, catalog))):
                            return
                    put_bounded(DONE)
                except BaseException as e:  # surfaced on the consumer side
                    put_bounded(("e", e))

            t = threading.Thread(target=worker, daemon=True,
                                 name="stage-readahead")
            t.start()
            try:
                while True:
                    item = q.get()
                    if item is DONE:
                        return
                    kind, v = item
                    if kind == "e":
                        raise v
                    # device admission on the CONSUMER (main) thread —
                    # re-entrant there, and paired with DeviceToHostExec's
                    # release on the same thread
                    if ctx.semaphore is not None:
                        ctx.semaphore.acquire()
                    yield v
            finally:
                stop.set()
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
                # Reap the worker (bounded): a worker wedged inside a
                # device transfer would otherwise outlive the query and
                # leak its generator state into the next test/query —
                # the cross-suite-state-leak shape.  The drain above
                # unblocks any q.put wait, so a healthy worker exits
                # within the put timeout.
                t.join(timeout=5.0)

        mk = gen_pipelined if depth > 0 else gen
        return [mk(p) for p in child_parts]


class DeviceToHostExec(CpuExec):
    """Copy device batches back to host (GpuColumnarToRowExec /
    GpuBringBackToHost analogue)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.output_schema)

    def describe(self):
        return "DeviceToHost"

    def partitions(self, ctx: ExecContext) -> List[Iterator]:
        child_parts = self.children[0].partitions(ctx)

        def gen(part):
            from spark_rapids_tpu.ops.tpu_exec import shrink_to_fit
            for db in part:
                # Shrink to the live-row bucket first (one scalar round
                # trip + a device-side gather) so the bulk transfer moves
                # live rows, not padded capacity.
                hb = device_to_host(shrink_to_fit(db))
                if ctx.semaphore is not None:
                    ctx.semaphore.release()
                if hb.num_rows:
                    yield hb

        return [gen(p) for p in child_parts]


def run_partition_with_retry(root: PhysicalOp, ctx: ExecContext,
                             index: int) -> List:
    """Materialize one partition with retries (Spark task-retry analogue —
    SURVEY.md section 5: failure detection is delegated to task retry +
    lineage; partitions are pure recomputations of their lineage here too).
    """
    max_failures = int(ctx.conf.get("spark.rapids.task.maxFailures", 2))
    last_err = None
    for attempt in range(max(1, max_failures)):
        try:
            return list(root.partitions(ctx)[index])
        except MemoryError:
            raise
        except Exception as e:  # noqa: BLE001 — retried, then re-raised
            last_err = e
            ctx.metric("task", "retries").add(1)
    raise last_err


def collect_host(op: PhysicalOp, ctx: ExecContext) -> HostBatch:
    """Drive a plan to completion and concatenate all partitions on host."""
    from spark_rapids_tpu.utils.tracing import trace_range
    try:
        if op.is_tpu:
            from spark_rapids_tpu.plan.pipeline import pipeline_collect
            with trace_range("pipeline_collect",
                             ctx.metric("collect", "wallTimeNs")):
                hb = pipeline_collect(op, ctx)
            if hb is not None:
                return hb
        root = op if not op.is_tpu else DeviceToHostExec(op)
        batches: List[HostBatch] = []
        t0 = time.monotonic()
        parts = root.partitions(ctx)
        for i, part in enumerate(parts):
            try:
                with trace_range(f"partition:{i}"):
                    got = list(part)
            except MemoryError:
                raise
            except Exception:
                got = run_partition_with_retry(root, ctx, i)
            batches.extend(got)
            ctx.metric("collect", "batches").add(len(got))
        ctx.metric("collect", "wallTimeNs").add(
            int((time.monotonic() - t0) * 1e9))
        if not batches:
            return HostBatch(op.output_schema, [
                _empty_host_col(f) for f in op.output_schema.fields
            ])
        return HostBatch.concat(batches)
    finally:
        ctx.close_deferred()


def _empty_host_col(f: T.Field):
    import numpy as np
    from spark_rapids_tpu.batch import HostColumn
    vals = np.zeros(0, dtype=object if f.dtype.is_string else f.dtype.np_dtype)
    return HostColumn(f.dtype, vals, np.zeros(0, dtype=np.bool_))
