"""Physical operator model.

Execution contract (the ``doExecuteColumnar(): RDD[ColumnarBatch]`` analogue,
GpuExec.scala:58): every physical op exposes
``partitions(ctx) -> List[Iterator[batch]]`` — a list of lazily-evaluated
per-partition batch iterators.  TPU execs yield device
:class:`~spark_rapids_tpu.batch.ColumnBatch`; CPU (fallback) execs yield host
:class:`~spark_rapids_tpu.batch.HostBatch`.  The planner inserts
:class:`HostToDeviceExec` / :class:`DeviceToHostExec` transitions at every
CPU<->TPU boundary (GpuTransitionOverrides analogue).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

import jax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    ColumnBatch, HostBatch, device_to_host, host_to_device,
)
from spark_rapids_tpu.config import RapidsConf


class Metric:
    """A named SQL-metric (GpuMetricNames analogue, GpuExec.scala:27-56)."""

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self.value = 0

    def add(self, v):
        self.value += v

    def __repr__(self):
        return f"{self.name}={self.value}{self.unit}"


class ExecContext:
    """Per-query execution context: conf, metrics, device admission."""

    def __init__(self, conf: RapidsConf, semaphore=None, device=None,
                 mesh=None):
        self.conf = conf
        self.semaphore = semaphore
        self.device = device
        # multi-device jax.sharding.Mesh when the ICI collective shuffle is
        # active (spark.rapids.shuffle.ici.enabled + >1 device); exchanges
        # then run lax.all_to_all instead of the single-host split
        self.mesh = mesh
        self.metrics: Dict[str, Dict[str, Metric]] = {}
        # Net outstanding H2D admission acquires for this query.
        # HostToDeviceExec counts every semaphore acquire at acquire time;
        # each per-batch release site decrements; collect_host's finally
        # releases the residue.  Pairing releases to OUTPUT batches alone
        # leaks the difference whenever a plan is not 1:1 (a semi join
        # dropping an empty pair, an n->1 concat on the fallback path),
        # and a leaked permit silently shrinks device admission for the
        # rest of the process.
        self._pipeline_h2d = 0
        # spillable handles whose lifetime is the whole query (shuffle
        # outputs survive partition retries, like the reference's shuffle
        # files); collect_host closes them when the query ends
        self._deferred_handles: List = []
        # (op_id, mechanism) replan decisions the adaptive layer took for
        # this query (plan/adaptive.note_event), checked post-query by
        # analysis/plan_verify.check_adaptive_events: every event must
        # point at a live plan op and respect join-type legality
        self.adaptive_events: List = []

    def note_adaptive(self, op_id: str, mechanism: str) -> None:
        self.adaptive_events.append((op_id, mechanism))

    def defer_close(self, handle) -> None:
        self._deferred_handles.append(handle)

    def close_deferred(self) -> None:
        for h in self._deferred_handles:
            h.close()
        self._deferred_handles.clear()

    def metric(self, op_id: str, name: str) -> Metric:
        ops = self.metrics.setdefault(op_id, {})
        if name not in ops:
            ops[name] = Metric(name)
        return ops[name]

    def mesh_spmd_active(self) -> bool:
        """True when whole-stage SPMD fusion may run for this query: a
        multi-device mesh is installed AND mesh.spmd.enabled.  Both the
        stage builder (plan/pipeline) and the fusable ops (shuffle
        exchange, broadcast join) consult this single gate, so a plan
        segment can never half-fuse."""
        if self.mesh is None:
            return False
        from spark_rapids_tpu.config import MESH_SPMD_ENABLED
        return MESH_SPMD_ENABLED.get(self.conf)


def _release_admission(ctx: ExecContext, n: int = 1) -> None:
    """Release ``n`` H2D-paired admission permits and keep the query's
    outstanding-acquire count in step (``ExecContext._pipeline_h2d``)."""
    for _ in range(n):
        ctx.semaphore.release()
    ctx._pipeline_h2d = max(0, getattr(ctx, "_pipeline_h2d", 0) - n)


def prefetch_spillables(handles, depth: int = 1):
    """Drive a list of SpillableBatch handles with overlapped unspill:
    batch i+1's rehydration (disk read + decompress + async H2D enqueue)
    is already in flight while the consumer computes on batch i
    (catalog.prefetch).  The shared drive loop for cached-scan partitions
    and shuffle piece reads.  Admission is NOT acquired here: the calling
    task's semaphore permit is task-wide re-entrant and the catalog's
    reserve() bounds device bytes, so read-ahead adds no leakable depth."""
    handles = list(handles)
    if not handles:
        return iter(())
    return handles[0]._catalog.prefetch(handles, depth=depth)


class PhysicalOp:
    """Base physical operator."""

    is_tpu = False

    def __init__(self, children: List["PhysicalOp"], output_schema: T.Schema):
        self.children = children
        self.output_schema = output_schema
        self.op_id = f"{type(self).__name__}@{id(self):x}"

    @property
    def name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.name

    def tree_string(self, depth: int = 0) -> str:
        out = "  " * depth + ("*" if self.is_tpu else " ") + \
            self.describe() + "\n"
        for c in self.children:
            out += c.tree_string(depth + 1)
        return out

    def num_partitions(self, ctx: ExecContext) -> int:
        if self.children:
            return self.children[0].num_partitions(ctx)
        return 1

    def partitions(self, ctx: ExecContext) -> List[Iterator]:
        raise NotImplementedError(self.name)


class TpuExec(PhysicalOp):
    """Operator executing on device over ColumnBatch partitions."""

    is_tpu = True

    def pipeline_inline(self, ctx: "ExecContext", build):
        """Whole-pipeline hook (plan/pipeline.py): return
        f(args) -> List[ColumnBatch] composing this op into one jitted
        program (``build(child)`` composes a child), or None to act as a
        pipeline source fed through the iterator path."""
        return None


class CpuExec(PhysicalOp):
    """Host fallback operator over HostBatch partitions."""

    is_tpu = False


class _ReadAheadChannel:
    """Bounded staging channel for the read-ahead worker: put/get wait on a
    condition variable AND wake immediately on :meth:`stop` — the
    queue.Full poll loop this replaced re-armed a 0.25 s timeout on every
    back-pressure wait, so worker shutdown and a full queue both paid a
    polling tail latency.

    ``put`` returns False once stopped (the consumer has left: the item is
    dropped, never stranded).  ``get`` returns the sentinel ``None`` when
    stopped-and-drained.

    Waits are BOUNDED (re-armed in a loop): ``notify`` still wakes them
    immediately — the bound never adds latency — but it caps how long
    the blocked thread sits inside one C-level wait, so an async
    exception (the fault watchdog's PartitionTimeout, delivered only
    between Python bytecodes) reaches a consumer parked here within the
    bound instead of after the producer's entire stall.
    """

    _WAIT_SLICE = 0.25

    def __init__(self, depth: int):
        self._items = collections.deque()
        self._depth = max(1, depth)
        self._cond = threading.Condition()
        self._stopped = False

    @property
    def stopped(self) -> bool:
        return self._stopped

    def put(self, item) -> bool:
        with self._cond:
            while not self._stopped and len(self._items) >= self._depth:
                self._cond.wait(self._WAIT_SLICE)
            if self._stopped:
                return False
            self._items.append(item)
            self._cond.notify_all()
            return True

    def get(self):
        with self._cond:
            while not self._stopped and not self._items:
                self._cond.wait(self._WAIT_SLICE)
            if self._items:
                item = self._items.popleft()
                self._cond.notify_all()
                return item
            return None

    def stop(self) -> None:
        """Drain + wake everyone: blocked producers return False from
        ``put`` immediately instead of after a poll interval."""
        with self._cond:
            self._stopped = True
            self._items.clear()
            self._cond.notify_all()


class HostToDeviceExec(TpuExec):
    """Stage host batches into HBM (GpuRowToColumnarExec /
    HostColumnarToGpu analogue: acquire semaphore, bulk-copy to device)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.output_schema)
        # Device-consumer handshake: a scan that can emit dictionary-encoded
        # string columns only does so when its batches are headed for H2D
        # staging (codes transfer instead of bytes); CPU-exec consumers
        # always get fully decoded host strings.
        probe = getattr(child, "set_device_consumer", None)
        if probe is not None:
            probe()

    def describe(self):
        return "HostToDevice"

    def partitions(self, ctx: ExecContext) -> List[Iterator]:
        from spark_rapids_tpu.config import STAGE_READAHEAD_BATCHES
        child_parts = self.children[0].partitions(ctx)
        t_metric = ctx.metric(self.op_id, "stageTime")
        depth = STAGE_READAHEAD_BATCHES.get(ctx.conf)

        def acquire_counted():
            # pipeline_collect counts H2D-side acquires via
            # ctx._pipeline_h2d and releases that many in its finally —
            # counting AT ACQUIRE TIME (not per materialized source)
            # keeps the books right when an abort (PartitionTimeout,
            # device loss) lands mid-source
            if ctx.semaphore is not None:
                ctx.semaphore.acquire()
                if hasattr(ctx, "_pipeline_h2d"):
                    ctx._pipeline_h2d += 1

        def stage(hb, catalog):
            from spark_rapids_tpu.mem.catalog import run_with_oom_retry
            t0 = time.monotonic()
            acquire_counted()
            db = run_with_oom_retry(
                catalog, lambda: host_to_device(hb, device=ctx.device))
            t_metric.add(time.monotonic() - t0)
            return db

        def gen(part):
            from spark_rapids_tpu.runtime.device import DeviceRuntime
            catalog = DeviceRuntime.get(ctx.conf).catalog
            for hb in part:
                yield stage(hb, catalog)

        def stage_nosem(hb, catalog):
            # worker-thread variant: NO semaphore acquire here.  Admission
            # is taken by the CONSUMER below before the batch is yielded
            # downstream, pairing with the release when results leave the
            # device (TpuSemaphore depth is task-wide, so the thread the
            # acquire/release lands on no longer matters); the read-ahead
            # transfer itself rides the catalog's OOM-retry.
            from spark_rapids_tpu.mem.catalog import run_with_oom_retry
            t0 = time.monotonic()
            db = run_with_oom_retry(
                catalog, lambda: host_to_device(hb, device=ctx.device))
            t_metric.add(time.monotonic() - t0)
            return db

        def gen_pipelined(part):
            # Read-ahead staging: a background thread pulls host batches
            # (driving the scan's decode) and stages them into HBM up to
            # ``depth`` ahead, so decode + H2D transfer overlap the
            # consumer's device compute — the reference's read-ahead pool
            # + semaphore shape (GpuParquetScan.scala:647-700) without a
            # dedicated stream: jax dispatch is async, the thread only
            # pays the host-side copy/transfer-enqueue cost.  Producer
            # back-pressure and shutdown ride the channel's condition
            # variable, so neither pays a poll interval.
            from spark_rapids_tpu.obs import events as obs_events
            from spark_rapids_tpu.runtime.device import DeviceRuntime
            catalog = DeviceRuntime.get(ctx.conf).catalog
            chan = _ReadAheadChannel(depth)
            DONE = object()
            # adopt the spawning query's scope on the worker so its
            # transfers/events attribute to THIS query even when several
            # queries are in flight (serve runtime)
            scope = obs_events.current_scope()

            def worker():
                try:
                    with obs_events.adopt(scope):
                        for hb in part:
                            if chan.stopped:
                                return
                            if not chan.put(("b", stage_nosem(hb, catalog))):
                                return
                        chan.put((DONE, None))
                except BaseException as e:  # surfaced on the consumer side
                    chan.put(("e", e))

            t = threading.Thread(target=worker, daemon=True,
                                 name="stage-readahead")
            t.start()
            try:
                while True:
                    item = chan.get()
                    if item is None or item[0] is DONE:
                        return
                    kind, v = item
                    if kind == "e":
                        raise v
                    # device admission on the CONSUMER (main) thread —
                    # re-entrant there, and paired with DeviceToHostExec's
                    # release on the same thread
                    acquire_counted()
                    yield v
            finally:
                # Wake + reap the worker (bounded): stop() drains the
                # channel and releases any blocked put immediately; a
                # worker wedged inside a device transfer would otherwise
                # outlive the query and leak its generator state into the
                # next test/query — the cross-suite-state-leak shape.
                chan.stop()
                t.join(timeout=5.0)

        mk = gen_pipelined if depth > 0 else gen
        return [mk(p) for p in child_parts]


class DeviceToHostExec(CpuExec):
    """Copy device batches back to host (GpuColumnarToRowExec /
    GpuBringBackToHost analogue)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.output_schema)

    def describe(self):
        return "DeviceToHost"

    def partitions(self, ctx: ExecContext) -> List[Iterator]:
        child_parts = self.children[0].partitions(ctx)

        def gen(part):
            from spark_rapids_tpu.ops.tpu_exec import shrink_to_fit
            for db in part:
                # Shrink to the live-row bucket first (one scalar round
                # trip + a device-side gather) so the bulk transfer moves
                # live rows, not padded capacity.
                hb = device_to_host(shrink_to_fit(db))
                if any(c.dictionary is not None for c in hb.columns):
                    # encoded-corridor invariant (analysis/plan_verify):
                    # collection D2H must materialize dictionary columns
                    ctx.encoded_d2h_leaks = \
                        getattr(ctx, "encoded_d2h_leaks", 0) + 1
                if ctx.semaphore is not None:
                    _release_admission(ctx)
                if hb.num_rows:
                    yield hb

        return [gen(p) for p in child_parts]


def run_partition_with_retry(root: PhysicalOp, ctx: ExecContext,
                             index: int, error=None) -> List:
    """Materialize one partition with retries (Spark task-retry analogue —
    SURVEY.md section 5: failure detection is delegated to task retry +
    lineage; partitions are pure recomputations of their lineage here too).

    Thin wrapper: the loop itself lives in fault.recovery, which
    classifies the failure (fault.errors), applies the unified
    RetryPolicy (spill on OOM, runtime reset + device-tier invalidation
    on device loss) and, once device attempts are exhausted, completes
    just this partition through the CPU operator path
    (``spark.rapids.sql.tpu.fallback.onDeviceError``).  ``error`` is the
    failure that already consumed attempt 1.
    """
    from spark_rapids_tpu.fault import recovery
    return recovery.run_partition_with_retry(root, ctx, index, error=error)


def _drive_partitions(root: PhysicalOp, ctx: ExecContext,
                      release_partial: bool) -> List:
    """Drive every partition of ``root`` (trace range, MemoryError
    pass-through, per-partition deadline + retry, collect/batches
    metric) into one flat batch list — shared by the bulk and iterator
    collect paths.

    ``release_partial=True`` (bulk path, where the semaphore release for
    a batch happens only after the final D2H): a partition attempt that
    fails after yielding k batches must release those k H2D-side acquires
    before the retry re-acquires for its own batches, or the depth leaks
    for the process lifetime.  The iterator path releases incrementally
    per converted batch (DeviceToHostExec), so it must NOT double-release
    here.
    """
    from spark_rapids_tpu.fault.watchdog import partition_deadline
    from spark_rapids_tpu.utils.tracing import trace_range
    with partition_deadline(ctx.conf, "plan-partitions"):
        # eager per-op work (e.g. the exchange split) happens here, under
        # its own deadline — a wedge before the first partition must
        # trip the watchdog too
        parts = root.partitions(ctx)
    flat: List = []
    for i, part in enumerate(parts):
        got: List = []
        try:
            with trace_range(f"partition:{i}"), \
                    partition_deadline(ctx.conf, f"partition:{i}"):
                for b in part:
                    got.append(b)
        except BaseException as e:
            if release_partial and ctx.semaphore is not None:
                _release_admission(ctx, len(got))
            if isinstance(e, MemoryError) or \
                    not isinstance(e, Exception):
                # MemoryError passes to the caller's handler;
                # KeyboardInterrupt/SystemExit must never be swallowed
                # by a successful retry
                raise
            got = run_partition_with_retry(root, ctx, i, error=e)
        flat.extend(got)
        ctx.metric("collect", "batches").add(len(got))
    return flat


def _collect_device_bulk(root: PhysicalOp, ctx: ExecContext
                         ) -> List[HostBatch]:
    """Async-overlapped collect of a TPU root: EVERY partition's device
    work is dispatched first (jax dispatch is async — the device pipelines
    across partitions instead of idling at each partition's D2H), then one
    batched sizes sync right-sizes all batches and ONE bulk transfer
    brings them home (the DeviceToHostExec iterator paid a sizes sync + a
    blocking copy per batch, serializing dispatch behind each round trip).
    """
    from spark_rapids_tpu.batch import device_to_host_many, host_sizes
    from spark_rapids_tpu.ops.tpu_exec import shrink_to_fit
    flat = _drive_partitions(root, ctx, release_partial=True)
    try:
        if not flat:
            return []
        # A partition completed via the CPU fallback path yields
        # HostBatch directly: pass those through in place and run the
        # sizes-sync + bulk D2H over the device batches only.
        out: List = list(flat)
        dev = [(j, b) for j, b in enumerate(flat)
               if isinstance(b, ColumnBatch)]
        if dev:
            dbs = [b for _, b in dev]
            sizes = host_sizes(dbs)
            shrunk = [shrink_to_fit(b, sizes=s)
                      for b, s in zip(dbs, sizes)]
            for (j, _), hb in zip(dev, device_to_host_many(shrunk)):
                out[j] = hb
        return [hb for hb in out if hb.num_rows]
    finally:
        # results left the device (or the sizes/D2H step failed — either
        # way this collect is done with them): release once per collected
        # DEVICE batch, pairing with the H2D-side acquires
        # (DeviceToHostExec's role in the iterator path); CPU-fallback
        # host batches never took device admission
        if ctx.semaphore is not None:
            _release_admission(
                ctx, sum(1 for b in flat if isinstance(b, ColumnBatch)))


def _async_collect_enabled(ctx: ExecContext) -> bool:
    from spark_rapids_tpu.config import PIPELINE_ASYNC_PARTITIONS
    return PIPELINE_ASYNC_PARTITIONS.get(ctx.conf)


def _history_cached_collect(op: PhysicalOp, ctx: ExecContext
                            ) -> Optional[HostBatch]:
    """Serve the whole collect from the cross-query fragment cache
    (history.fragcache) when the session armed a fragment key and the
    cache holds this (fingerprint, conf, input-identity): the cached
    device batches ARE a previous run's outputs, so D2H + concat here
    reproduces that run bit-identically with zero dispatches.  None on
    a miss (caller executes normally)."""
    key = getattr(ctx, "_history_frag_key", None)
    if key is None:
        return None
    from spark_rapids_tpu.history.fragcache import fragment_cache
    devs = fragment_cache().fetch(key, ctx)
    if devs is None:
        return None
    from spark_rapids_tpu.batch import device_to_host_many
    hbs = [hb for hb in device_to_host_many(devs) if hb.num_rows]
    if not hbs:
        return HostBatch(op.output_schema, [
            _empty_host_col(f) for f in op.output_schema.fields])
    return HostBatch.concat(hbs)


def collect_host(op: PhysicalOp, ctx: ExecContext) -> HostBatch:
    """Drive a plan to completion and concatenate all partitions on host."""
    from spark_rapids_tpu.utils.tracing import trace_range
    try:
        if op.is_tpu:
            hb = _history_cached_collect(op, ctx)
            if hb is not None:
                return hb
            from spark_rapids_tpu.fault.recovery import (
                run_pipeline_with_recovery,
            )
            with trace_range("pipeline_collect",
                             ctx.metric("collect", "wallTimeNs")):
                hb = run_pipeline_with_recovery(op, ctx)
            if hb is not None:
                return hb
            if _async_collect_enabled(ctx):
                t0 = time.monotonic()
                batches = _collect_device_bulk(op, ctx)
                ctx.metric("collect", "wallTimeNs").add(
                    int((time.monotonic() - t0) * 1e9))
                if not batches:
                    return HostBatch(op.output_schema, [
                        _empty_host_col(f) for f in op.output_schema.fields
                    ])
                return HostBatch.concat(batches)
        root = op if not op.is_tpu else DeviceToHostExec(op)
        t0 = time.monotonic()
        batches: List[HostBatch] = _drive_partitions(
            root, ctx, release_partial=False)
        ctx.metric("collect", "wallTimeNs").add(
            int((time.monotonic() - t0) * 1e9))
        if not batches:
            return HostBatch(op.output_schema, [
                _empty_host_col(f) for f in op.output_schema.fields
            ])
        return HostBatch.concat(batches)
    finally:
        ctx.close_deferred()
        # Give back any staging acquires whose batches never reached a
        # per-batch release (dropped-empty join pairs, n->1 concats):
        # the query is over, so the outstanding count must drain to zero
        # or the permit leaks for the process lifetime.  The plan
        # verifier (analysis/plan_verify.py) asserts the resulting
        # held_depth() == 0 after every suite query.
        if ctx.semaphore is not None:
            _release_admission(ctx, getattr(ctx, "_pipeline_h2d", 0))


def _empty_host_col(f: T.Field):
    import numpy as np
    from spark_rapids_tpu.batch import HostColumn
    vals = np.zeros(0, dtype=object if f.dtype.is_string else f.dtype.np_dtype)
    return HostColumn(f.dtype, vals, np.zeros(0, dtype=np.bool_))
