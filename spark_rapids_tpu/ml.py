"""ML integration: zero-copy handoff of device-resident query results to JAX
ML pipelines.

Reference analogue: ColumnarRdd (ColumnarRdd.scala:41-49) exposes
``RDD[cudf.Table]`` so XGBoost trains directly on GPU batches without a
host round trip.  Here the query result stays as ``jax.Array`` columns in
HBM, ready to feed jitted training steps (the dlpack story of SURVEY.md
section 7 is unnecessary — both sides are already JAX).
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp

from spark_rapids_tpu.batch import ColumnBatch
from spark_rapids_tpu.plan.overrides import TpuOverrides
from spark_rapids_tpu.plan.physical import (
    ExecContext, HostToDeviceExec, _release_admission)


def to_device_batches(df) -> List[ColumnBatch]:
    """Execute the plan and return the per-partition device batches WITHOUT
    copying to host (ColumnarRdd.convert analogue)."""
    session = df.session
    overrides = TpuOverrides(session.conf)
    phys = overrides.apply(df.plan)
    if not phys.is_tpu:
        phys = HostToDeviceExec(phys)
    ctx = ExecContext(
        session.conf,
        semaphore=session.runtime.semaphore if session.runtime else None,
        device=session.runtime.device if session.runtime else None)
    out: List[ColumnBatch] = []
    try:
        for part in phys.partitions(ctx):
            out.extend(part)
    finally:
        ctx.close_deferred()
        # This drive loop never routes through DeviceToHostExec (the
        # batches stay in HBM by design), so the per-batch staging
        # releases never fire.  The handoff is complete once the loop
        # ends — drain the outstanding acquires or this task's permit
        # stays held for the process lifetime and starves every later
        # query's admission.
        if ctx.semaphore is not None:
            _release_admission(ctx, getattr(ctx, "_pipeline_h2d", 0))
    return out


def to_jax(df, dense_only: bool = True) -> Dict[str, jnp.ndarray]:
    """Execute and return {column: jnp.ndarray} of the LIVE rows, compacted
    into one array per column — the feature-matrix handoff for training.

    Strings are excluded when dense_only (encode them in the query with
    hash()/cast first, the way the reference's XGBoost flow pre-encodes).
    """
    from spark_rapids_tpu.kernels.layout import gather_rows
    from spark_rapids_tpu.ops.tpu_exec import _concat_all, shrink_to_fit
    batches = to_device_batches(df)
    if not batches:
        return {f.name: jnp.zeros(0, dtype=f.dtype.jnp_dtype)
                for f in df.schema.fields if not f.dtype.is_string}
    merged = shrink_to_fit(_concat_all(batches, df.plan.schema))
    n = merged.host_num_rows()
    out: Dict[str, jnp.ndarray] = {}
    for f, c in zip(merged.schema.fields, merged.columns):
        if f.dtype.is_string:
            if dense_only:
                continue
            raise ValueError("string columns need dense_only=False handling")
        out[f.name] = c.data[:n]
        out[f.name + "__valid"] = c.validity[:n]
    return out
