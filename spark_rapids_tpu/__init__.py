"""spark_rapids_tpu: a TPU-native columnar SQL/DataFrame acceleration framework.

Re-designed from scratch for TPU (JAX/XLA/Pallas/pjit) with the capability
envelope of the spark-rapids GPU accelerator (reference: firestarman/spark-rapids
v0.3.0-SNAPSHOT): a columnar batch data model resident in HBM, a plan-rewrite
planner with per-operator CPU fallback and explain/tagging machinery, an
operator+expression library lowered to XLA, device-side partitioning and an
ICI all-to-all shuffle, a tiered device->host->disk spill subsystem, and
host-side Parquet/CSV decode staged asynchronously into device memory.

Architectural mapping (reference -> TPU build):
  cudf Table in GPU memory   -> ColumnBatch: struct of padded, static-shape
                                jax.Arrays (data + validity + string offsets)
  libcudf kernels (JNI)      -> jitted XLA computations, fused per pipeline
                                stage; Pallas for hot ops
  GpuOverrides plan rewrite  -> plan.overrides tagging/replacement over a
                                logical plan built by the DataFrame frontend
  RMM pool + spill tiers     -> mem.catalog device->host->disk spill chain
  UCX shuffle transport      -> parallel.shuffle all-to-all over an ICI mesh
                                (shard_map + XLA collectives)
"""

import jax as _jax

# A SQL engine needs real 64-bit longs/doubles; XLA on TPU emulates int64
# where needed.  Must run before any jnp array is materialized.
_jax.config.update("jax_enable_x64", True)

from spark_rapids_tpu.version import __version__
from spark_rapids_tpu.config import RapidsConf, conf
from spark_rapids_tpu import types

__all__ = [
    "__version__",
    "RapidsConf",
    "conf",
    "types",
]


def __getattr__(name):
    # Lazy to avoid importing the full planner stack on package import.
    if name == "TpuSparkSession":
        from spark_rapids_tpu.session import TpuSparkSession
        return TpuSparkSession
    raise AttributeError(name)
