// Native host runtime for spark_rapids_tpu.
//
// TPU-native replacements for the reference's JNI-backed host runtime
// (SURVEY.md section 2.9):
//   * columnar batch wire serializer   <- JCudfSerialization
//     (GpuColumnarBatchSerializer.scala:84-95 wire role): one contiguous
//     framed buffer holding all column buffers, used by the host shuffle
//     fallback, broadcast and disk spill.
//   * aligned host staging arena       <- PinnedMemoryPool
//     (GpuDeviceManager.scala:244-250): recycling aligned allocator for
//     host<->HBM staging buffers.
//   * murmur3_x86_32 row hasher        <- spark-compatible hash partitioning
//     on the host path (GpuHashPartitioning.scala murmur3 contract).
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Wire format:
//   u32 magic 'TPUB'  u32 version  u32 n_cols  u64 n_rows
//   per column: u8 type_code  u8 has_offsets  u64 data_len  u64 validity_len
//               u64 offsets_len, then the three buffers back to back,
//               each 8-byte aligned.
// ---------------------------------------------------------------------------

static const uint32_t kMagic = 0x54505542;  // "TPUB"
static const uint32_t kVersion = 1;

static inline uint64_t align8(uint64_t x) { return (x + 7) & ~uint64_t(7); }

// Returns required buffer size for serialization.
uint64_t batch_serialized_size(int32_t n_cols, const uint64_t* data_lens,
                               const uint64_t* validity_lens,
                               const uint64_t* offsets_lens) {
  uint64_t total = 4 + 4 + 4 + 8;
  for (int32_t i = 0; i < n_cols; i++) {
    total += 1 + 1 + 8 + 8 + 8;
    total = align8(total);
    total += align8(data_lens[i]) + align8(validity_lens[i]) +
             align8(offsets_lens[i]);
  }
  return total;
}

// Serialize column buffers into out (must be >= batch_serialized_size).
// Returns bytes written, or 0 on error.
uint64_t batch_serialize(int32_t n_cols, uint64_t n_rows,
                         const uint8_t* type_codes,
                         const uint8_t** data_bufs, const uint64_t* data_lens,
                         const uint8_t** validity_bufs,
                         const uint64_t* validity_lens,
                         const uint8_t** offsets_bufs,
                         const uint64_t* offsets_lens, uint8_t* out,
                         uint64_t out_cap) {
  uint64_t need = batch_serialized_size(n_cols, data_lens, validity_lens,
                                        offsets_lens);
  if (out_cap < need) return 0;
  uint64_t p = 0;
  auto put32 = [&](uint32_t v) { std::memcpy(out + p, &v, 4); p += 4; };
  auto put64 = [&](uint64_t v) { std::memcpy(out + p, &v, 8); p += 8; };
  put32(kMagic);
  put32(kVersion);
  put32((uint32_t)n_cols);
  put64(n_rows);
  for (int32_t i = 0; i < n_cols; i++) {
    out[p++] = type_codes[i];
    out[p++] = offsets_lens[i] ? 1 : 0;
    put64(data_lens[i]);
    put64(validity_lens[i]);
    put64(offsets_lens[i]);
    p = align8(p);
    std::memcpy(out + p, data_bufs[i], data_lens[i]);
    p += align8(data_lens[i]);
    std::memcpy(out + p, validity_bufs[i], validity_lens[i]);
    p += align8(validity_lens[i]);
    if (offsets_lens[i]) {
      std::memcpy(out + p, offsets_bufs[i], offsets_lens[i]);
      p += align8(offsets_lens[i]);
    }
  }
  return p;
}

// Parse header: fills n_cols/n_rows; returns 0 on bad magic.
int32_t batch_read_header(const uint8_t* buf, uint64_t len, int32_t* n_cols,
                          uint64_t* n_rows) {
  if (len < 20) return 0;
  uint32_t magic, version;
  std::memcpy(&magic, buf, 4);
  std::memcpy(&version, buf + 4, 4);
  if (magic != kMagic || version != kVersion) return 0;
  uint32_t nc;
  std::memcpy(&nc, buf + 8, 4);
  *n_cols = (int32_t)nc;
  std::memcpy(n_rows, buf + 12, 8);
  return 1;
}

// Per-column metadata+pointer extraction. Arrays must hold n_cols entries.
int32_t batch_deserialize_index(const uint8_t* buf, uint64_t len,
                                uint8_t* type_codes, uint64_t* data_offs,
                                uint64_t* data_lens, uint64_t* validity_offs,
                                uint64_t* validity_lens,
                                uint64_t* offsets_offs,
                                uint64_t* offsets_lens) {
  int32_t n_cols;
  uint64_t n_rows;
  if (!batch_read_header(buf, len, &n_cols, &n_rows)) return 0;
  uint64_t p = 20;
  for (int32_t i = 0; i < n_cols; i++) {
    if (p + 26 > len) return 0;
    type_codes[i] = buf[p++];
    p++;  // has_offsets implied by offsets_lens
    std::memcpy(&data_lens[i], buf + p, 8); p += 8;
    std::memcpy(&validity_lens[i], buf + p, 8); p += 8;
    std::memcpy(&offsets_lens[i], buf + p, 8); p += 8;
    p = align8(p);
    data_offs[i] = p;
    p += align8(data_lens[i]);
    validity_offs[i] = p;
    p += align8(validity_lens[i]);
    offsets_offs[i] = offsets_lens[i] ? p : 0;
    p += align8(offsets_lens[i]);
    if (p > len) return 0;
  }
  return 1;
}

// ---------------------------------------------------------------------------
// Aligned host arena: power-of-two size-class recycling allocator.
// ---------------------------------------------------------------------------

struct Arena {
  std::mutex mu;
  std::map<uint64_t, std::vector<void*>> free_lists;  // size -> buffers
  uint64_t allocated = 0;   // live bytes handed out
  uint64_t pooled = 0;      // bytes sitting in free lists
  uint64_t high_water = 0;
  uint64_t pool_limit;
  explicit Arena(uint64_t limit) : pool_limit(limit) {}
};

static uint64_t next_pow2(uint64_t v) {
  if (v < 64) return 64;
  v--;
  v |= v >> 1; v |= v >> 2; v |= v >> 4;
  v |= v >> 8; v |= v >> 16; v |= v >> 32;
  return v + 1;
}

void* arena_create(uint64_t pool_limit_bytes) {
  return new Arena(pool_limit_bytes);
}

void arena_destroy(void* arena) {
  Arena* a = (Arena*)arena;
  for (auto& kv : a->free_lists)
    for (void* p : kv.second) std::free(p);
  delete a;
}

void* arena_alloc(void* arena, uint64_t size) {
  Arena* a = (Arena*)arena;
  uint64_t cls = next_pow2(size);
  {
    std::lock_guard<std::mutex> g(a->mu);
    auto it = a->free_lists.find(cls);
    if (it != a->free_lists.end() && !it->second.empty()) {
      void* p = it->second.back();
      it->second.pop_back();
      a->pooled -= cls;
      a->allocated += cls;
      if (a->allocated > a->high_water) a->high_water = a->allocated;
      return p;
    }
  }
  void* p = nullptr;
  if (posix_memalign(&p, 64, cls) != 0) return nullptr;
  std::lock_guard<std::mutex> g(a->mu);
  a->allocated += cls;
  if (a->allocated > a->high_water) a->high_water = a->allocated;
  return p;
}

void arena_free(void* arena, void* ptr, uint64_t size) {
  Arena* a = (Arena*)arena;
  uint64_t cls = next_pow2(size);
  std::lock_guard<std::mutex> g(a->mu);
  a->allocated -= cls;
  if (a->pooled + cls <= a->pool_limit) {
    a->free_lists[cls].push_back(ptr);
    a->pooled += cls;
  } else {
    std::free(ptr);
  }
}

void arena_stats(void* arena, uint64_t* allocated, uint64_t* pooled,
                 uint64_t* high_water) {
  Arena* a = (Arena*)arena;
  std::lock_guard<std::mutex> g(a->mu);
  *allocated = a->allocated;
  *pooled = a->pooled;
  *high_water = a->high_water;
}

// ---------------------------------------------------------------------------
// murmur3_x86_32, Spark layout (seed chains across columns; NULLs skipped).
// Matches exprs/hashing.py word decomposition.
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_k1(uint32_t k1) {
  k1 *= 0xCC9E2D51u;
  k1 = rotl32(k1, 15);
  return k1 * 0x1B873593u;
}

static inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5 + 0xE6546B64u;
}

static inline uint32_t fmix(uint32_t h, uint32_t length) {
  h ^= length;
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  return h ^ (h >> 16);
}

// words: int64 per row per word column (pre-decomposed by python);
// this hashes one column's words into running hashes h[n].
// word_count in {1, 2}; length = 4 or 8; validity may be null (all valid).
void murmur3_column(const uint32_t* words0, const uint32_t* words1,
                    int32_t word_count, uint32_t byte_length,
                    const uint8_t* validity, int64_t n, uint32_t* h) {
  for (int64_t i = 0; i < n; i++) {
    if (validity && !validity[i]) continue;
    uint32_t hv = h[i];
    hv = mix_h1(hv, mix_k1(words0[i]));
    if (word_count > 1) hv = mix_h1(hv, mix_k1(words1[i]));
    h[i] = fmix(hv, byte_length);
  }
}

// pmod partition ids from final hashes.
void pmod_partition(const uint32_t* h, int64_t n, int32_t n_parts,
                    int32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    int32_t v = (int32_t)h[i] % n_parts;
    out[i] = v < 0 ? v + n_parts : v;
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Block compression: LZ4-style byte-oriented LZ77 (token = 4-bit literal
// run + 4-bit match run, 2-byte offsets, 255-run length extensions).
// The TableCompressionCodec native path (the reference links nvcomp for
// this role; here a dependency-free host codec for spill/shuffle bytes).
// ---------------------------------------------------------------------------

extern "C" uint64_t lz_compress_bound(uint64_t n) {
  return n + n / 255 + 16;
}

static inline uint32_t lz_hash4(uint32_t v) {
  return (v * 2654435761u) >> 18;  // 14-bit bucket
}

extern "C" uint64_t lz_compress(const uint8_t* src, uint64_t n,
                                uint8_t* dst, uint64_t cap) {
  // Returns bytes written, 0 when dst cannot hold the output.
  const uint32_t HT = 1u << 14;
  static thread_local uint32_t table[1u << 14];
  memset(table, 0, sizeof(table));

  uint64_t si = 0, di = 0, anchor = 0;

  auto emit_run = [&](uint64_t r) {  // 255-run extension bytes
    while (r >= 255) {
      if (di >= cap) return false;
      dst[di++] = 255; r -= 255;
    }
    if (di >= cap) return false;
    dst[di++] = (uint8_t)r;
    return true;
  };

  if (n >= 13) {
    uint64_t limit = n - 12;
    while (si < limit) {
      uint32_t seq;
      memcpy(&seq, src + si, 4);
      uint32_t h = lz_hash4(seq) & (HT - 1);
      uint64_t cand = table[h] ? (uint64_t)(table[h] - 1) : UINT64_MAX;
      if (si + 1 <= 0xFFFFFFFFull) table[h] = (uint32_t)(si + 1);
      uint32_t cseq = 0;
      bool hit = cand != UINT64_MAX && si - cand <= 65535 &&
                 (memcpy(&cseq, src + cand, 4), cseq == seq);
      if (!hit) { si++; continue; }
      uint64_t m = si + 4, c = cand + 4;
      while (m < n && src[m] == src[c]) { m++; c++; }
      uint64_t lit = si - anchor;
      uint64_t mlen = (m - si) - 4;
      uint8_t tl = lit >= 15 ? 15 : (uint8_t)lit;
      uint8_t tm = mlen >= 15 ? 15 : (uint8_t)mlen;
      if (di + 1 + lit + 2 + 8 + lit / 255 + mlen / 255 > cap) return 0;
      dst[di++] = (uint8_t)((tl << 4) | tm);
      if (lit >= 15 && !emit_run(lit - 15)) return 0;
      memcpy(dst + di, src + anchor, lit);
      di += lit;
      uint16_t off = (uint16_t)(si - cand);
      dst[di++] = (uint8_t)(off & 0xFF);
      dst[di++] = (uint8_t)(off >> 8);
      if (mlen >= 15 && !emit_run(mlen - 15)) return 0;
      si = m;
      anchor = m;
    }
  }
  // trailing literals-only block (no offset follows)
  uint64_t lit = n - anchor;
  uint8_t tl = lit >= 15 ? 15 : (uint8_t)lit;
  if (di + 1 + lit + lit / 255 + 1 > cap) return 0;
  dst[di++] = (uint8_t)(tl << 4);
  if (lit >= 15 && !emit_run(lit - 15)) return 0;
  memcpy(dst + di, src + anchor, lit);
  di += lit;
  return di;
}

extern "C" int32_t lz_decompress(const uint8_t* src, uint64_t n,
                                 uint8_t* dst, uint64_t out_n) {
  // 0 on success (exactly out_n bytes produced), -1 on malformed input.
  uint64_t si = 0, di = 0;
  while (si < n) {
    uint8_t tok = src[si++];
    uint64_t lit = tok >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (si >= n) return -1;
        b = src[si++];
        lit += b;
      } while (b == 255);
    }
    if (si + lit > n || di + lit > out_n) return -1;
    memcpy(dst + di, src + si, lit);
    si += lit;
    di += lit;
    if (si >= n) break;  // trailing literals-only block
    if (si + 2 > n) return -1;
    uint64_t off = (uint64_t)src[si] | ((uint64_t)src[si + 1] << 8);
    si += 2;
    uint64_t mlen = tok & 15;
    if (mlen == 15) {
      uint8_t b;
      do {
        if (si >= n) return -1;
        b = src[si++];
        mlen += b;
      } while (b == 255);
    }
    mlen += 4;
    if (off == 0 || off > di || di + mlen > out_n) return -1;
    for (uint64_t k = 0; k < mlen; k++) {  // overlap-safe byte copy
      dst[di] = dst[di - off];
      di++;
    }
  }
  return di == out_n ? 0 : -1;
}
