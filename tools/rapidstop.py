#!/usr/bin/env python
"""rapidstop — "top" for a running (or finished) rapids engine process.

Usage:
    python tools/rapidstop.py <telemetry.jsonl> [more.jsonl ...]
        [--once] [--follow] [--last N] [--prom]

Reads the telemetry JSONL a session flushes under
``spark.rapids.sql.tpu.obs.eventLogDir`` (``telemetry-<pid>.jsonl``,
written by obs.timeseries) and renders the newest interval's per-site
activity table — events, wall, bytes, derived GB/s — plus the gauge
samples (catalog tier bytes, spill-writer/decode-pool utilization,
serve queue depth) and a window rollup.  ``--follow`` re-renders as the
live process appends intervals; ``--prom`` emits Prometheus exposition
text summed over the window instead (pipe it to a textfile collector).

Runtime-free by construction (the same loading discipline as
``rapidslint``/``rapidsprof``): the ``obs`` package is loaded standalone
without executing the engine's root ``__init__``, so no jax import and
no device runtime — watch a TPU host's flushes from any laptop.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: --follow re-render cadence; also the bounded sleep slice (R3).
_POLL_SLICE_S = 0.25


def _load_obs():
    """Load spark_rapids_tpu.obs WITHOUT executing the engine's package
    __init__ (which imports jax) — obs is stdlib-only and relative-
    imported precisely so this tool stays runtime-free."""
    pkg_dir = os.path.join(REPO_ROOT, "spark_rapids_tpu", "obs")
    spec = importlib.util.spec_from_file_location(
        "rapidstop_obs", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["rapidstop_obs"] = mod
    spec.loader.exec_module(mod)
    return mod


_obs = _load_obs()
from rapidstop_obs import timeseries as ts  # noqa: E402


def load_intervals(paths):
    """Concatenate the telemetry logs, oldest interval first (multiple
    files = multiple processes; idx orders within one ring).  A
    directory stands for every ``telemetry-*.jsonl`` inside it, so
    pointing at ``obs.eventLogDir`` itself works."""
    out = []
    for path in paths:
        if os.path.isdir(path):
            names = sorted(n for n in os.listdir(path)
                           if n.startswith("telemetry-")
                           and n.endswith(".jsonl"))
            files = [os.path.join(path, n) for n in names]
        else:
            files = [path]
        for f in files:
            try:
                out.extend(ts.read_telemetry_log(f))
            except OSError:
                continue  # not flushed yet (or gone) — render what exists
    return out


def _gauges_latest(intervals):
    for iv in reversed(intervals):
        g = iv.get("gauges")
        if g:
            return g
    return {}


def render_prom(intervals) -> str:
    totals = {}
    for iv in intervals:
        for site, st in (iv.get("sites") or {}).items():
            t = totals.setdefault(site, [0, 0, 0])
            t[0] += int(st[0])
            t[1] += int(st[1])
            t[2] += int(st[2])
    return ts.render_prometheus(totals, _gauges_latest(intervals),
                                len(intervals))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live per-site telemetry view over rapids "
                    "telemetry JSONL flushes")
    ap.add_argument("logs", nargs="+", help="telemetry JSONL path(s) "
                    "(telemetry-<pid>.jsonl under obs.eventLogDir)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (default)")
    ap.add_argument("--follow", action="store_true",
                    help="keep re-rendering as intervals land (^C to "
                    "stop)")
    ap.add_argument("--last", type=int, default=0, metavar="N",
                    help="window rollup over only the last N intervals")
    ap.add_argument("--prom", action="store_true",
                    help="emit Prometheus exposition text instead of "
                    "the table")
    args = ap.parse_args(argv)

    def frame() -> str:
        intervals = load_intervals(args.logs)
        if args.prom:
            return render_prom(intervals)
        return ts.render_intervals(intervals, last=args.last)

    if not args.follow:
        out = frame()
        print(out)
        return 0 if "(no telemetry intervals)" not in out else 2
    try:
        while True:
            print("\x1b[2J\x1b[H" + frame(), flush=True)
            time.sleep(_POLL_SLICE_S)
    except KeyboardInterrupt:
        sys.exit(0)  # clean ^C out of --follow


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe — normal for a CLI
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
