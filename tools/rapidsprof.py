#!/usr/bin/env python
"""rapidsprof — offline analysis of obs event logs.

Usage:
    python tools/rapidsprof.py <events.jsonl> [more.jsonl ...]
        [--top N] [--query ID] [--chrome out.json] [--critpath]

Reads the JSONL event log(s) a session wrote under
``spark.rapids.sql.tpu.obs.eventLogDir`` and prints, per query and in
aggregate: top operators by device time, transfer/spill pressure, the
retry/fault summary, and a per-query comparison table.  ``--chrome``
additionally exports a Chrome ``trace_event`` JSON (load it in Perfetto
or chrome://tracing).

Runtime-free by construction (the RAPIDS profiling-tool role, and the
same loading discipline as ``rapidslint``): the ``obs`` package is
loaded standalone without executing the engine's root ``__init__``, so
no jax import and no device runtime — a log from a TPU host analyzes on
any laptop.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs():
    """Load spark_rapids_tpu.obs WITHOUT executing the engine's package
    __init__ (which imports jax) — obs is stdlib-only and relative-
    imported precisely so this tool stays runtime-free."""
    pkg_dir = os.path.join(REPO_ROOT, "spark_rapids_tpu", "obs")
    spec = importlib.util.spec_from_file_location(
        "rapidsprof_obs", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["rapidsprof_obs"] = mod
    spec.loader.exec_module(mod)
    return mod


_obs = _load_obs()
from rapidsprof_obs import critpath as obs_critpath  # noqa: E402
from rapidsprof_obs import export as obs_export  # noqa: E402
from rapidsprof_obs.profile import QueryProfile  # noqa: E402


def load_profiles(paths):
    profiles = []
    for path in paths:
        for i, q in enumerate(obs_export.read_event_log(path)):
            profiles.append(QueryProfile(
                q.get("id", i + 1), q.get("events", []),
                dropped=q.get("dropped", 0), wall_ns=q.get("wall_ns", 0),
                metrics=q.get("metrics") or {},
                dropped_by_site=q.get("dropped_by_site") or {},
                session_id=q.get("session", 0),
                qt0_ns=q.get("t0_ns", 0), qt1_ns=q.get("t1_ns", 0)))
    return profiles


def _gbps(nbytes: int, ns: int) -> str:
    if not ns:
        return "-"
    return f"{nbytes / max(ns, 1):.3f} GB/s"


def _mb(nbytes: int) -> str:
    return f"{nbytes / (1 << 20):.2f} MB"


def report(profiles, top_n: int = 10, critpath: bool = False) -> str:
    lines = []
    # group per-query blocks by the session that ran them (one shared
    # log accumulates every session in the process)
    sessions = sorted({p.session_id for p in profiles})
    grouped = len(sessions) > 1
    for sid in sessions:
        if grouped:
            lines.append(f"== session {sid} ==")
        for p in profiles:
            if p.session_id != sid:
                continue
            lines.append(p.summary())
            if critpath:
                cp = obs_critpath.from_profile(p)
                lines.append(cp.summary() if cp is not None
                             else "critical path: (no query window "
                                  "recorded)")
            lines.append("")

    # aggregate top operators by device time
    merged = {}
    for p in profiles:
        for r in p.top_operators(10 ** 9):
            m = merged.setdefault(
                r["op_id"] or r["name"],
                {"name": r["name"], "device_ns": 0, "dispatches": 0,
                 "errors": 0, "shuffle_bytes": 0})
            m["name"] = m["name"] or r["name"]
            m["device_ns"] += r["device_ns"]
            m["dispatches"] += r["dispatches"]
            m["errors"] += r["errors"]
            m["shuffle_bytes"] += r["shuffle_bytes"]
    lines.append("== top operators by device time ==")
    ops = sorted(merged.values(), key=lambda m: m["device_ns"],
                 reverse=True)[:top_n]
    if not ops:
        lines.append("  (no operator events)")
    for m in ops:
        extra = f", {m['errors']} errored" if m["errors"] else ""
        sh = f", shuffle {_mb(m['shuffle_bytes'])}" \
            if m["shuffle_bytes"] else ""
        lines.append(f"  {m['name'] or '?'}: {m['device_ns'] / 1e6:.2f} ms "
                     f"across {m['dispatches']} dispatches{extra}{sh}")

    # transfer/spill pressure
    lines.append("")
    lines.append("== transfer/spill pressure ==")
    for site, label in (("h2d", "host->device"), ("d2h", "device->host"),
                        ("spill", "spill"), ("unspill", "unspill"),
                        ("io", "arrow decode")):
        tot = {"count": 0, "wall_ns": 0, "bytes": 0}
        for p in profiles:
            s = p.site(site)
            for k in tot:
                tot[k] += s[k]
        if not tot["count"]:
            continue
        lines.append(f"  {label}: {tot['count']} events, "
                     f"{_mb(tot['bytes'])}, {tot['wall_ns'] / 1e6:.2f} ms "
                     f"({_gbps(tot['bytes'], tot['wall_ns'])})")

    # retry/fault summary
    lines.append("")
    lines.append("== retry/fault summary ==")
    retry = sum(p.site("retry")["count"] for p in profiles)
    fault = sum(p.site("fault")["count"] for p in profiles)
    adaptive = sum(p.site("adaptive")["count"] for p in profiles)
    rmetrics = {"retryCount": 0, "faultsInjected": 0, "deviceLostCount": 0,
                "partitionFallbackCount": 0}
    for p in profiles:
        for k in rmetrics:
            rmetrics[k] += int(p.metrics.get(k, 0) or 0)
    lines.append(f"  retry events {retry}, fault events {fault}, "
                 f"adaptive decisions {adaptive}")
    lines.append("  metrics: " + ", ".join(
        f"{k}={v}" for k, v in rmetrics.items()))

    # query-intelligence summary (history/): seeded decisions and
    # fragment-cache reuse recorded by the sessions that wrote these logs
    hist_events = sum(p.site("history")["count"] for p in profiles)
    hmetrics = {"historySeededDecisions": 0, "fragmentCacheHits": 0,
                "fragmentCacheBytes": 0, "statsStoreQueries": 0}
    for p in profiles:
        for k in hmetrics:
            hmetrics[k] += int(p.metrics.get(k, 0) or 0)
    if hist_events or any(hmetrics.values()):
        lines.append("")
        lines.append("== query intelligence (history) ==")
        lines.append(f"  history events {hist_events}")
        lines.append("  metrics: " + ", ".join(
            f"{k}={v}" for k, v in hmetrics.items()))

    # per-query comparison
    if len(profiles) > 1:
        lines.append("")
        lines.append("== per-query comparison ==")
        lines.append("  query | sess | wall ms | device ms | events | "
                     "dropped | dispatches | shuffle MB")
        for p in profiles:
            sh = sum(r["shuffle_bytes"] for r in p.op_rollups.values())
            lines.append(
                f"  {p.query_id:>5} | {p.session_id:>4} | "
                f"{p.wall_ns / 1e6:>7.1f} | "
                f"{p.attributed_device_ns / 1e6:>9.2f} | "
                f"{p.event_count:>6} | {p.dropped:>7} | "
                f"{p.site('dispatch')['count']:>10} | "
                f"{sh / (1 << 20):>10.2f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="analyze spark_rapids_tpu obs event logs")
    ap.add_argument("logs", nargs="+", help="JSONL event log path(s)")
    ap.add_argument("--top", type=int, default=10,
                    help="operators to list (default 10)")
    ap.add_argument("--query", type=int, default=None,
                    help="restrict to one query id")
    ap.add_argument("--chrome", default=None, metavar="OUT",
                    help="also write a Chrome trace_event JSON")
    ap.add_argument("--critpath", action="store_true",
                    help="print each query's exact critical-path "
                         "decomposition")
    args = ap.parse_args(argv)

    profiles = load_profiles(args.logs)
    if args.query is not None:
        profiles = [p for p in profiles if p.query_id == args.query]
    if not profiles:
        print("no queries found in", ", ".join(args.logs))
        return 2
    print(report(profiles, args.top, critpath=args.critpath))
    if args.chrome:
        events = [ev for p in profiles for ev in p.events]
        obs_export.write_chrome_trace(args.chrome, events)
        doc = obs_export.events_to_chrome(events)
        print(f"\nwrote {args.chrome}: {len(doc['traceEvents'])} trace "
              "events")
    return 0


if __name__ == "__main__":
    sys.exit(main())
