#!/usr/bin/env python
"""rapidsserve — drive the serving runtime and report its economics.

Usage:
    python tools/rapidsserve.py [--tenants a:2,b:1] [--queries N]
        [--rows N] [--concurrency N] [--fault SPEC] [--deadline SEC]
    python tools/rapidsserve.py --server [--host H] [--port P]
        [--tenants a:2,b:1] [--concurrency N] [--history-dir DIR]
    python tools/rapidsserve.py --client HOST:PORT --sql "SELECT ..."
        [--tenant NAME] [--deadline SEC] [--no-cache] [--stats]
        [--drain]

Default mode runs the deterministic serving workload from
``spark_rapids_tpu.serve.bench`` — template micro-queries round-robined
across weighted tenants, served concurrently with micro-batching — and
prints ONE JSON line with the ``serve_*`` metrics: queries/sec, p50/p99
latency, coalesced-query count, served-vs-serial wall ratio, bit-parity
vs one-at-a-time execution, the shared executable cache's
second-session compile count, and per-tenant SLO rollups.

``--fault`` installs a per-query deterministic fault spec (e.g.
``dispatch:oom@2``) on the serving session: every served query injects
it and must still return correct rows through the recovery ladder —
the CI serve smoke drives exactly that.  ``--deadline`` arms a
per-query deadline (seconds; queries that miss it fail fast with
DeadlineExceeded and count in ``serve_deadline_exceeded``).

``--server`` starts the network front door (serve/frontend) over the
demo view (``bench_events(k BIGINT, v BIGINT)``) plus the bench
template, prints ONE JSON banner line ``{"host", "port", "view",
"sqls"}`` on stdout, and serves until SIGINT/SIGTERM.  ``--client``
speaks the newline-delimited JSON protocol (docs/serving.md) to any
front door: submit one ``--sql`` (rows printed as JSON), or fetch
``--stats`` / issue ``--drain``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WAIT_SLICE_S = 0.25


def _parse_tenants(spec: str):
    """``a:2,b:1`` -> {"a": 2.0, "b": 1.0} (weight defaults to 1)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        out[name.strip()] = float(weight) if weight else 1.0
    return out


def _run_server(args) -> int:
    from spark_rapids_tpu.serve.bench import (
        FRONTEND_SQLS, FRONTEND_VIEW, _template, frontend_demo_session,
    )
    from spark_rapids_tpu.serve.frontend import FrontDoorServer
    from spark_rapids_tpu.serve.scheduler import ServeScheduler
    session = frontend_demo_session(
        _parse_tenants(args.tenants) or {"default": 1.0},
        history_dir=args.history_dir, rows=max(64, args.rows))
    session.conf.set("spark.rapids.sql.tpu.serve.frontend.host", args.host)
    session.conf.set("spark.rapids.sql.tpu.serve.frontend.port",
                     str(args.port))
    server = FrontDoorServer(session, scheduler=ServeScheduler(
        session, max_concurrency=max(1, args.concurrency)))
    server.register_template(_template())
    server.start()
    # ONE machine-readable banner so a parent process (CI smoke) can
    # discover the ephemeral port, then serve until signalled
    print(json.dumps({"host": args.host, "port": server.port,
                      "view": FRONTEND_VIEW, "sqls": FRONTEND_SQLS}),
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_a: stop.set())
    while not stop.is_set():
        stop.wait(_WAIT_SLICE_S)
    server.close()
    return 0


def _run_client(args) -> int:
    from spark_rapids_tpu.serve.protocol import FrontDoorClient
    host, _, port = args.client.rpartition(":")
    with FrontDoorClient(host or "127.0.0.1", int(port)) as c:
        if args.stats:
            print(json.dumps(c.stats()))
            return 0
        if args.drain:
            print(json.dumps(c.drain()))
            return 0
        if not args.sql:
            print("rapidsserve --client needs --sql, --stats or --drain",
                  file=sys.stderr)
            return 2
        rows, metrics = c.submit_sql(
            args.sql, tenant=args.tenant, deadline_sec=args.deadline,
            cache=not args.no_cache)
        print(json.dumps({"rows": rows.to_pydict(), "metrics": metrics}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="rapidsserve", description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", default="a:2,b:1",
                    help="comma list of name:weight (default a:2,b:1)")
    ap.add_argument("--queries", type=int, default=32,
                    help="queries to serve (default 32)")
    ap.add_argument("--rows", type=int, default=512,
                    help="rows per query batch (default 512)")
    ap.add_argument("--concurrency", type=int, default=2,
                    help="scheduler runner threads (default 2)")
    ap.add_argument("--fault", default="",
                    help="faults.spec to inject per served query")
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="per-query deadline seconds (0 = off)")
    ap.add_argument("--server", action="store_true",
                    help="start the network front door (demo view)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="--server bind host (default 127.0.0.1)")
    ap.add_argument("--port", type=int, default=0,
                    help="--server bind port (default 0 = ephemeral)")
    ap.add_argument("--history-dir", default="",
                    help="--server: history store dir (enables the "
                         "admission predictor's baseline)")
    ap.add_argument("--client", default="",
                    help="HOST:PORT of a front door to talk to")
    ap.add_argument("--sql", default="",
                    help="--client: SQL text to submit")
    ap.add_argument("--tenant", default="default",
                    help="--client: tenant to submit as")
    ap.add_argument("--no-cache", action="store_true",
                    help="--client: bypass the server result cache")
    ap.add_argument("--stats", action="store_true",
                    help="--client: print scheduler+frontend stats")
    ap.add_argument("--drain", action="store_true",
                    help="--client: drain the server and report "
                         "held_depth")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO_ROOT)
    if args.server:
        return _run_server(args)
    if args.client:
        return _run_client(args)
    from spark_rapids_tpu.serve.bench import run_serve_bench
    result = run_serve_bench(
        queries=max(1, args.queries), rows=max(1, args.rows),
        tenants=_parse_tenants(args.tenants) or {"default": 1.0},
        fault=args.fault, deadline_sec=args.deadline,
        max_concurrency=max(1, args.concurrency))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
