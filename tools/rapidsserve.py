#!/usr/bin/env python
"""rapidsserve — drive the serving runtime and report its economics.

Usage:
    python tools/rapidsserve.py [--tenants a:2,b:1] [--queries N]
        [--rows N] [--concurrency N] [--fault SPEC] [--deadline SEC]

Runs the deterministic serving workload from
``spark_rapids_tpu.serve.bench`` — template micro-queries round-robined
across weighted tenants, served concurrently with micro-batching — and
prints ONE JSON line with the ``serve_*`` metrics: queries/sec, p50/p99
latency, coalesced-query count, served-vs-serial wall ratio, bit-parity
vs one-at-a-time execution, the shared executable cache's
second-session compile count, and per-tenant SLO rollups.

``--fault`` installs a per-query deterministic fault spec (e.g.
``dispatch:oom@2``) on the serving session: every served query injects
it and must still return correct rows through the recovery ladder —
the CI serve smoke drives exactly that.  ``--deadline`` arms a
per-query deadline (seconds; queries that miss it fail fast with
DeadlineExceeded and count in ``serve_deadline_exceeded``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse_tenants(spec: str):
    """``a:2,b:1`` -> {"a": 2.0, "b": 1.0} (weight defaults to 1)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        out[name.strip()] = float(weight) if weight else 1.0
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="rapidsserve", description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", default="a:2,b:1",
                    help="comma list of name:weight (default a:2,b:1)")
    ap.add_argument("--queries", type=int, default=32,
                    help="queries to serve (default 32)")
    ap.add_argument("--rows", type=int, default=512,
                    help="rows per query batch (default 512)")
    ap.add_argument("--concurrency", type=int, default=2,
                    help="scheduler runner threads (default 2)")
    ap.add_argument("--fault", default="",
                    help="faults.spec to inject per served query")
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="per-query deadline seconds (0 = off)")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO_ROOT)
    from spark_rapids_tpu.serve.bench import run_serve_bench
    result = run_serve_bench(
        queries=max(1, args.queries), rows=max(1, args.rows),
        tenants=_parse_tenants(args.tenants) or {"default": 1.0},
        fault=args.fault, deadline_sec=args.deadline,
        max_concurrency=max(1, args.concurrency))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
