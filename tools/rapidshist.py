#!/usr/bin/env python
"""rapidshist — inspect and prune the query-intelligence statistics store.

Usage:
    python tools/rapidshist.py <history-dir> [--fingerprint FP]
        [--prune N] [--json] [--regressions]

Reads the JSONL statistics store a session wrote under
``spark.rapids.sql.tpu.history.dir`` (history/store.py schema) and
prints, per plan fingerprint: record age, query wall, compile economics,
spill pressure, the median/MAD aggregate over retained runs, and the
per-exchange partition layout that seeds the next run's plan.
``--prune N`` rewrites the store keeping the newest record per
fingerprint, bounded to the N newest overall.  ``--regressions`` runs
the sentinel offline: each fingerprint's newest run is compared against
the aggregate of the runs before it, exit code 1 when anything alerts.

Runtime-free by construction (the same loading discipline as
``rapidslint``/``rapidsprof``): ``history/store.py`` is stdlib-only and
loaded standalone without executing the engine's root ``__init__``, so
a store written on a TPU host inspects and prunes on any laptop.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_store():
    """Load spark_rapids_tpu.history.store WITHOUT the engine package
    __init__ (which imports jax) — the store module is stdlib-only with
    no package-relative imports precisely for this."""
    path = os.path.join(REPO_ROOT, "spark_rapids_tpu", "history",
                        "store.py")
    spec = importlib.util.spec_from_file_location("rapidshist_store", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["rapidshist_store"] = mod
    spec.loader.exec_module(mod)
    return mod


store = _load_store()


def _load_sentinel():
    """Load spark_rapids_tpu.obs.sentinel standalone (stdlib-only, no
    relative imports) for the offline ``--regressions`` check."""
    path = os.path.join(REPO_ROOT, "spark_rapids_tpu", "obs",
                        "sentinel.py")
    spec = importlib.util.spec_from_file_location(
        "rapidshist_sentinel", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["rapidshist_sentinel"] = mod
    spec.loader.exec_module(mod)
    return mod


def _age(ts: float) -> str:
    d = max(0.0, time.time() - ts)
    if d < 120:
        return f"{d:.0f}s"
    if d < 7200:
        return f"{d / 60:.0f}m"
    if d < 172800:
        return f"{d / 3600:.1f}h"
    return f"{d / 86400:.1f}d"


def _mb(n: int) -> str:
    return f"{n / (1 << 20):.2f} MB"


def describe(rec: dict, agg: dict = None) -> str:
    lines = [
        f"fingerprint {rec.get('fp')}  (conf {rec.get('conf_sig')}, "
        f"age {_age(float(rec.get('ts', 0) or 0))})",
        f"  wall {float(rec.get('wall_ns', 0)) / 1e6:.2f} ms, "
        f"{rec.get('out_rows', 0)} rows out, "
        f"compiles {rec.get('compile_count', 0)} "
        f"({float(rec.get('compile_wall_ns', 0)) / 1e6:.1f} ms)",
    ]
    if agg and int(agg.get("n", 0) or 0) > 1:
        w = (agg.get("keys") or {}).get("wall_ns") or {}
        lines.append(
            f"  aggregate over {agg['n']} run(s): wall median "
            f"{float(w.get('median', 0)) / 1e6:.2f} ms "
            f"(MAD {float(w.get('mad', 0)) / 1e6:.2f} ms)")
    sp_h = int(rec.get("spill_host_bytes", 0) or 0)
    sp_d = int(rec.get("spill_disk_bytes", 0) or 0)
    if sp_h or sp_d:
        lines.append(f"  spill pressure: {_mb(sp_h)} to host, "
                     f"{_mb(sp_d)} to disk")
    for ex in rec.get("exchanges", ()):
        sizes = ex.get("bytes") or ex.get("rows") or []
        unit = "B" if ex.get("bytes") else "rows"
        total = sum(sizes)
        mx = max(sizes) if sizes else 0
        lines.append(
            f"  exchange {ex.get('path')}: {ex.get('parts')} partitions, "
            f"total {total} {unit}, max {mx} {unit}")
    for jn in rec.get("joins", ()):
        lines.append(f"  join {jn.get('path')}: broadcast build side = "
                     f"{jn.get('bc_side')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="inspect/prune the spark_rapids_tpu statistics store")
    ap.add_argument("dir", help="history dir "
                    "(spark.rapids.sql.tpu.history.dir)")
    ap.add_argument("--fingerprint", default=None,
                    help="restrict to one plan fingerprint hash")
    ap.add_argument("--prune", type=int, default=None, metavar="N",
                    help="rewrite the store keeping the N newest records "
                    "(newest per fingerprint always wins)")
    ap.add_argument("--json", action="store_true",
                    help="emit the folded records (with their run "
                    "aggregates under 'agg') as JSON")
    ap.add_argument("--regressions", action="store_true",
                    help="compare each fingerprint's newest run against "
                    "the aggregate of the runs before it; exit 1 when "
                    "anything alerts")
    ap.add_argument("--threshold", type=float, default=4.0,
                    help="sentinel MAD threshold (default 4.0)")
    ap.add_argument("--min-runs", type=int, default=3,
                    help="minimum baseline runs before alerting "
                    "(default 3)")
    args = ap.parse_args(argv)

    if args.prune is not None:
        before, after = store.prune(args.dir, args.prune)
        print(f"pruned {store.store_path(args.dir)}: "
              f"{before} -> {after} records")
        return 0

    records = store.load(args.dir)
    if args.fingerprint is not None:
        records = {fp: r for fp, r in records.items()
                   if fp == args.fingerprint}
    if not records:
        print("no records found in", store.store_path(args.dir))
        return 2
    aggs = {fp: store.aggregate(args.dir, fp, r.get("conf_sig") or "",
                                runs=store.AGG_MAX_RUNS)
            for fp, r in records.items()}
    if args.regressions:
        sentinel = _load_sentinel()
        alerted = 0
        for fp, rec in sorted(records.items()):
            runs = store.runs_for(args.dir, fp, rec.get("conf_sig") or "")
            baseline = store.aggregate_records(runs[:-1])
            alerts = sentinel.check(rec, baseline, args.threshold,
                                    args.min_runs)
            for a in alerts:
                alerted += 1
                print(f"REGRESSION fingerprint {fp}: {a['key']} = "
                      f"{a['value']:g} (median {a['median']:g}, band "
                      f"{a['band']:g} over {a['runs']} run(s))")
        if not alerted:
            print(f"no regressions across {len(records)} "
                  "fingerprint(s)")
        return 1 if alerted else 0
    if args.json:
        out = {fp: dict(r, agg=aggs.get(fp))
               for fp, r in records.items()}
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    recs = sorted(records.values(),
                  key=lambda r: float(r.get("ts", 0) or 0), reverse=True)
    print(f"{len(recs)} plan fingerprint(s) in "
          f"{store.store_path(args.dir)}\n")
    for rec in recs:
        print(describe(rec, aggs.get(str(rec.get("fp")))))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
