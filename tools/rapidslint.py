#!/usr/bin/env python
"""rapidslint — the project lint gate.

Usage:
    python tools/rapidslint.py --check            # CI gate: fail on new
                                                  # findings or stale
                                                  # baseline entries
    python tools/rapidslint.py --write-baseline   # accept current findings
                                                  # (reasons preserved for
                                                  # surviving entries, new
                                                  # entries get TODO reasons
                                                  # you must fill in)
    python tools/rapidslint.py --rules            # print the rule catalog

Runtime-free by construction: the linter parses source with ``ast`` and
never imports the query engine (or jax), so the whole tree checks in
well under a second (the CI budget is 15s).  See docs/static_analysis.md for the rule
catalog and suppression syntax.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Load spark_rapids_tpu.analysis WITHOUT executing the engine's
    package __init__ (which imports jax and flips global config) — the
    analysis package uses relative imports precisely so the lint gate
    stays a plain-ast tool with no runtime footprint."""
    pkg_dir = os.path.join(REPO_ROOT, "spark_rapids_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "rapidslint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["rapidslint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


_analysis = _load_analysis()
from rapidslint_analysis.engine import (  # noqa: E402
    Baseline, LintEngine, discover_files,
)
from rapidslint_analysis.rules import default_rules  # noqa: E402

BASELINE_PATH = os.path.join(REPO_ROOT, "tools", "rapidslint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on new findings / stale baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the baseline")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.rules:
        for r in rules:
            print(f"{r.id}  {r.name}: {r.description}")
        return 0

    t0 = time.monotonic()
    files = discover_files(args.root)
    engine = LintEngine(rules)
    findings = engine.run(files, args.root)
    baseline = Baseline.load(args.baseline)
    new, used, stale = baseline.partition(findings)
    dt = time.monotonic() - t0

    if args.write_baseline:
        entries = []
        for f in findings:
            reason = None
            for e in used:
                if (e.get("rule"), e.get("path")) == (f.rule_id, f.path) \
                        and e.get("line", "").split() == \
                        f.line_text.split():
                    reason = e.get("reason")
                    break
            entries.append({
                "rule": f.rule_id,
                "path": f.path,
                "line": " ".join(f.line_text.split()),
                "reason": reason or "TODO: justify this suppression",
            })
        Baseline(entries).save(args.baseline)
        print(f"wrote {len(entries)} baseline entries to {args.baseline}")
        return 0

    for f in new:
        print(f"{f.path}:{f.line}: {f.severity} [{f.rule_id}] {f.message}")
    for e in stale:
        print(f"{e.get('path')}: stale baseline entry "
              f"[{e.get('rule')}] for line `{e.get('line')}` — the code "
              "it excused is gone; remove the entry")
    status = "clean" if not new and not stale else \
        f"{len(new)} new finding(s), {len(stale)} stale entr(y/ies)"
    print(f"rapidslint: {len(files)} files, {len(findings)} finding(s) "
          f"({len(used)} baselined), {status} [{dt:.2f}s]")
    if args.check and (new or stale):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
