"""Runtime AQE tests: byte-based coalescing targets, shuffled->broadcast
join replan, skew split (GpuCustomShuffleReaderExec +
AQE OptimizeShuffledHashJoin / OptimizeSkewedJoin roles,
GpuOverrides.scala:1873-1881)."""

from spark_rapids_tpu import types as T
from spark_rapids_tpu.dataframe import Column
from spark_rapids_tpu.exprs.aggregates import Count, Sum
from spark_rapids_tpu.exprs.base import Alias, ColumnRef

from compare import _canon, cpu_session, tpu_session

NO_COLLAPSE = {"spark.rapids.sql.tpu.exchange.collapseLocal": False}


def _assert_equal_rows(cpu_rows, tpu_rows):
    a = _canon(cpu_rows, True, True)
    b = _canon(tpu_rows, True, True)
    assert len(a) == len(b), f"cpu={len(a)} tpu={len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra == rb, f"row {i}: cpu={ra} tpu={rb}"


def _metric_ops(sess, name):
    return [op for op, ms in sess.last_metrics.items()
            if isinstance(ms, dict) and name in ms]


BIG = {
    "a": (T.INT, [i % 7 for i in range(200)]),
    "v": (T.LONG, list(range(200))),
}
SMALL = {
    "a": (T.INT, [0, 1, 2, 3, 4, 5, 6, 0, 1, 2]),
    "w": (T.LONG, [10, 20, 30, 40, 50, 60, 70, 80, 90, 100]),
}


def _replan_query(s, how="inner", small_data=None):
    # BOTH join inputs are aggregate outputs: plan-time size estimates are
    # None -> shuffled hash join at plan time; runtime shuffle stats show
    # a tiny build side -> AQE replans to the broadcast shape
    big = s.create_dataframe(BIG, num_partitions=3) \
        .group_by("a", "v").agg(
            Column(Alias(Count(ColumnRef("v")), "c")))
    small = s.create_dataframe(small_data or SMALL, num_partitions=2) \
        .group_by("a").agg(Column(Alias(Sum(ColumnRef("w")), "sw")))
    return big.join(small, on="a", how=how)


def test_aqe_replan_shuffled_to_broadcast():
    cpu = cpu_session(**NO_COLLAPSE)
    tpu = tpu_session(**NO_COLLAPSE)
    cpu_rows = _replan_query(cpu).collect()
    tpu_rows = _replan_query(tpu).collect()
    _assert_equal_rows(cpu_rows, tpu_rows)
    assert "TpuShuffledHashJoin" in tpu.last_physical_plan.tree_string()
    assert _metric_ops(tpu, "replannedBroadcast"), \
        f"replan did not fire: {tpu.last_metrics}"


def test_aqe_replan_respects_disable_conf():
    tpu = tpu_session(**dict(
        NO_COLLAPSE,
        **{"spark.rapids.sql.adaptive.replanJoins.enabled": False}))
    rows = _replan_query(tpu).collect()
    cpu_rows = _replan_query(cpu_session(**NO_COLLAPSE)).collect()
    _assert_equal_rows(cpu_rows, rows)
    assert not _metric_ops(tpu, "replannedBroadcast")


def test_aqe_replan_left_join_keeps_unmatched():
    small = {"a": (T.INT, [0, 1]), "w": (T.LONG, [5, 6])}
    cpu = cpu_session(**NO_COLLAPSE)
    tpu = tpu_session(**NO_COLLAPSE)
    _assert_equal_rows(
        _replan_query(cpu, how="left", small_data=small).collect(),
        _replan_query(tpu, how="left", small_data=small).collect())
    assert _metric_ops(tpu, "replannedBroadcast"), tpu.last_metrics


def _skew_data():
    # one dominant key: hash partitioning lands ~all rows in one shuffle
    # partition, far above the median partition size
    keys = [42] * 600 + [i for i in range(20)]
    return {
        "k": (T.INT, keys),
        "v": (T.LONG, list(range(len(keys)))),
    }


def test_aqe_skew_split_inner_join():
    confs = dict(NO_COLLAPSE, **{
        # tiny byte target so the dominant partition splits into chunks
        "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes": 512,
        "spark.rapids.sql.adaptive.skewJoin.skewedPartitionFactor": 2.0,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })

    def q(s):
        left = s.create_dataframe(_skew_data(), num_partitions=3)
        right = s.create_dataframe(
            {"k": (T.INT, [42, 1, 2, 3]),
             "w": (T.LONG, [7, 8, 9, 10])},
            num_partitions=2)
        return left.join(right, on="k", how="inner")

    cpu = cpu_session(**confs)
    tpu = tpu_session(**confs)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())
    ops = _metric_ops(tpu, "skewSplitChunks")
    assert ops, f"skew split did not fire: {tpu.last_metrics}"
    chunks = sum(tpu.last_metrics[op]["skewSplitChunks"] for op in ops)
    assert chunks >= 2, tpu.last_metrics


def test_aqe_skew_split_left_join_null_padding():
    confs = dict(NO_COLLAPSE, **{
        "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes": 512,
        "spark.rapids.sql.adaptive.skewJoin.skewedPartitionFactor": 2.0,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })

    def q(s):
        left = s.create_dataframe(_skew_data(), num_partitions=3)
        right = s.create_dataframe(
            {"k": (T.INT, [42, 99]), "w": (T.LONG, [7, 8])},
            num_partitions=2)
        return left.join(right, on="k", how="left")

    cpu = cpu_session(**confs)
    tpu = tpu_session(**confs)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())
    assert _metric_ops(tpu, "skewSplitChunks"), tpu.last_metrics


def test_aqe_skew_split_single_piece():
    """A skewed partition that arrives as ONE piece still splits — the
    chunking is row-granularity, not piece-granularity."""
    confs = dict(NO_COLLAPSE, **{
        "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes": 512,
        "spark.rapids.sql.adaptive.skewJoin.skewedPartitionFactor": 2.0,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })

    def q(s):
        left = s.create_dataframe(_skew_data(), num_partitions=1)
        right = s.create_dataframe(
            {"k": (T.INT, [42, 1, 2, 3]),
             "w": (T.LONG, [7, 8, 9, 10])},
            num_partitions=1)
        return left.join(right, on="k", how="inner")

    cpu = cpu_session(**confs)
    tpu = tpu_session(**confs)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())
    ops = _metric_ops(tpu, "skewSplitChunks")
    assert ops, f"skew split did not fire: {tpu.last_metrics}"
    chunks = sum(tpu.last_metrics[op]["skewSplitChunks"] for op in ops)
    assert chunks >= 2, tpu.last_metrics


def test_aqe_skew_split_median_zero():
    """Extreme skew: ONE hot key, most shuffle partitions empty, median
    pair size 0 — the hot partition must still be flagged and split."""
    confs = dict(NO_COLLAPSE, **{
        "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes": 512,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })

    def q(s):
        left = s.create_dataframe(
            {"k": (T.INT, [42] * 500),
             "v": (T.LONG, list(range(500)))}, num_partitions=2)
        right = s.create_dataframe(
            {"k": (T.INT, [42]), "w": (T.LONG, [7])}, num_partitions=1)
        return left.join(right, on="k", how="inner")

    cpu = cpu_session(**confs)
    tpu = tpu_session(**confs)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())
    ops = _metric_ops(tpu, "skewSplitChunks")
    assert ops, f"skew split did not fire: {tpu.last_metrics}"
    chunks = sum(tpu.last_metrics[op]["skewSplitChunks"] for op in ops)
    assert chunks >= 2, tpu.last_metrics


def test_non_collapsed_exchange_array_and_string_columns():
    """Array + string columns through the device partition split: the
    split's varlen buffer caps align positionally with gather_rows'
    varlen columns (a string-only caps list would mis-size the array
    buffer)."""
    arr = T.ArrayType(T.LONG)
    data = {
        "k": (T.INT, [1, 2, 3, 1, 2, 3, 1, 2]),
        "arr": (arr, [[1, 2, 3], [], [4], None, [5, 6], [7], [8, 9], []]),
        "s": (T.STRING, ["aa", "b", None, "dddd", "e", "ff", "g", "hh"]),
    }

    def q(s):
        return s.create_dataframe(data, num_partitions=3).order_by("k")

    cpu = cpu_session(**NO_COLLAPSE)
    tpu = tpu_session(**NO_COLLAPSE)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())


def test_aqe_part_stats_prefer_bytes():
    """Byte stats win over row stats when the exchange recorded both (the
    reference coalesces by map-status bytes — row targets are an order of
    magnitude off for wide rows)."""
    from spark_rapids_tpu.ops.tpu_exec import (
        _aqe_part_stats, _group_by_target,
    )

    class FakeExchange:
        _last_part_rows = [10, 10, 10]
        _last_part_bytes = [100, 90_000_000, 100]

    sizes, unit = _aqe_part_stats(FakeExchange(), 3)
    assert unit == "bytes" and sizes == [100, 90_000_000, 100]
    # a 64MB byte target keeps the fat partition alone; a row target of
    # 64K would have merged all three
    groups = _group_by_target(["p0", "p1", "p2"], sizes, 64 << 20)
    assert ["p0", "p1"] in groups and ["p2"] in groups

    class RowsOnly:
        _last_part_rows = [10, 10, 10]

    sizes, unit = _aqe_part_stats(RowsOnly(), 3)
    assert unit == "rows" and sizes == [10, 10, 10]
    assert _aqe_part_stats(object(), 3) == (None, None)


def test_exchange_records_piece_bytes():
    tpu = tpu_session(**NO_COLLAPSE)
    df = tpu.create_dataframe(BIG, num_partitions=2)
    df.group_by("a").agg(
        Column(Alias(Count(ColumnRef("v")), "c"))).collect()
    plan = tpu.last_physical_plan
    found = []

    def walk(node):
        if hasattr(node, "_last_part_bytes"):
            found.append(node._last_part_bytes)
        for c in getattr(node, "children", []):
            walk(c)

    walk(plan)
    assert found and all(
        all(b >= 0 for b in bl) and sum(bl) > 0 for bl in found), found
