"""Kernel tests: gather / compact / concat / sort / groupby vs numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    HostBatch, device_to_host, host_to_device, round_up_capacity,
)
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels import (
    compact, concat_pair, gather_rows, sort_batch, take_head,
)
from spark_rapids_tpu.kernels.groupby import group_segments

from conftest import assert_batches_equal


def make_batch(pydict):
    return host_to_device(HostBatch.from_pydict(pydict))


MIXED = {
    "i": (T.INT, [3, None, 7, 1, 7, None, 0]),
    "d": (T.DOUBLE, [1.5, -2.0, None, 0.0, float("nan"), 3.25, -0.0]),
    "s": (T.STRING, ["bb", "", None, "apple", "bb", "zed", "aa"]),
    "b": (T.BOOLEAN, [True, False, None, True, False, True, None]),
}


def test_gather_rows_permutation():
    b = make_batch(MIXED)
    perm = np.array([6, 5, 4, 3, 2, 1, 0], dtype=np.int32)
    idx = jnp.zeros(b.capacity, dtype=jnp.int32).at[:7].set(jnp.asarray(perm))
    out = gather_rows(b, idx, jnp.asarray(7, jnp.int32))
    got = device_to_host(out).to_pydict()
    exp = {k: [v[i] for i in perm] for k, (dt, v) in MIXED.items()}
    assert_batches_equal(exp, got, approx=True)


def test_gather_rows_with_repeats():
    b = make_batch(MIXED)
    sel = np.array([0, 0, 3, 3, 3], dtype=np.int32)
    idx = jnp.zeros(b.capacity, dtype=jnp.int32).at[:5].set(jnp.asarray(sel))
    # Repeats can grow total string bytes past the input byte capacity, so
    # the caller sizes the output (the join two-phase pattern does this).
    out = gather_rows(b, idx, jnp.asarray(5, jnp.int32), out_byte_caps=[32])
    got = device_to_host(out).to_pydict()
    exp = {k: [v[i] for i in sel] for k, (dt, v) in MIXED.items()}
    assert_batches_equal(exp, got, approx=True)


def test_compact():
    b = make_batch(MIXED)
    mask_host = np.array([True, False, True, True, False, False, True])
    mask = jnp.zeros(b.capacity, dtype=jnp.bool_).at[:7].set(
        jnp.asarray(mask_host))
    out = compact(b, mask)
    assert int(jax.device_get(out.num_rows)) == 4
    got = device_to_host(out).to_pydict()
    keep = [i for i, m in enumerate(mask_host) if m]
    exp = {k: [v[i] for i in keep] for k, (dt, v) in MIXED.items()}
    assert_batches_equal(exp, got, approx=True)


def test_take_head():
    b = make_batch(MIXED)
    out = take_head(b, 3)
    got = device_to_host(out).to_pydict()
    exp = {k: v[:3] for k, (dt, v) in MIXED.items()}
    assert_batches_equal(exp, got, approx=True)


def test_concat_pair():
    d1 = {"i": (T.INT, [1, None, 3]), "s": (T.STRING, ["xx", None, "y"])}
    d2 = {"i": (T.INT, [9, 8]), "s": (T.STRING, ["hello world", ""])}
    a, b = make_batch(d1), make_batch(d2)
    cap = round_up_capacity(5)
    out = concat_pair(a, b, cap)
    assert int(jax.device_get(out.num_rows)) == 5
    got = device_to_host(out).to_pydict()
    exp = {"i": [1, None, 3, 9, 8], "s": ["xx", None, "y", "hello world", ""]}
    assert_batches_equal(exp, got)


def _spark_sort_key(row, ascendings, nulls_firsts):
    key = []
    for (v, asc, nf) in zip(row, ascendings, nulls_firsts):
        if v is None:
            null_rank = 0 if nf else 1
            val = 0
        else:
            null_rank = 1 if nf else 0
            if isinstance(v, float) and v != v:
                val = (1, 0)  # NaN greatest
            elif isinstance(v, bool):
                val = (0, int(v))
            elif isinstance(v, str):
                val = (0, v.encode())
            else:
                val = (0, v)
            if not asc:
                val = _Neg(val)
        key.append((null_rank, val if v is not None else 0))
    return tuple(key)


class _Neg:
    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


def sort_oracle(pydict, keys, ascendings, nulls_firsts):
    names = list(pydict.keys())
    cols = {k: v for k, (dt, v) in pydict.items()}
    n = len(next(iter(cols.values())))
    rows = list(range(n))
    key_vals = [[cols[k][i] for k in keys] for i in range(n)]
    order = sorted(rows, key=lambda i: _spark_sort_key(
        key_vals[i], ascendings, nulls_firsts))
    return {k: [cols[k][i] for i in order] for k in names}


@pytest.mark.parametrize("keys,asc,nf", [
    (["i"], [True], [True]),
    (["i"], [False], [False]),
    (["s"], [True], [True]),
    (["s", "i"], [False, True], [True, True]),
    (["d"], [True], [True]),
    (["d", "s"], [False, False], [False, False]),
    (["b", "i"], [True, False], [True, False]),
])
def test_sort_batch(keys, asc, nf):
    b = make_batch(MIXED)
    vals = [DevVal.from_column(b.column(k)) for k in keys]
    out = sort_batch(b, vals, asc, nf)
    got = device_to_host(out).to_pydict()
    exp = sort_oracle(MIXED, keys, asc, nf)
    assert_batches_equal(exp, got, approx=True)


def test_sort_larger_random(rng):
    n = 1000
    ints = [None if rng.rand() < 0.1 else int(rng.randint(-50, 50))
            for _ in range(n)]
    strs = [None if rng.rand() < 0.1 else
            "".join(rng.choice(list("abcd"), size=rng.randint(0, 6)))
            for _ in range(n)]
    pyd = {"i": (T.INT, ints), "s": (T.STRING, strs)}
    b = make_batch(pyd)
    vals = [DevVal.from_column(b.column(k)) for k in ("s", "i")]
    out = sort_batch(b, vals, [True, False], [False, True])
    got = device_to_host(out).to_pydict()
    exp = sort_oracle(pyd, ["s", "i"], [True, False], [False, True])
    assert_batches_equal(exp, got)


def test_group_segments_exact():
    pyd = {
        "k": (T.STRING, ["a", "b", "a", None, "b", "a", None, "c"]),
        "j": (T.INT, [1, 1, 1, 2, 2, 1, 2, None]),
    }
    b = make_batch(pyd)
    vals = [DevVal.from_column(b.column(k)) for k in ("k", "j")]
    segs = group_segments(vals, b.num_rows)
    # distinct (k, j) pairs: (a,1), (b,1), (None,2), (b,2), (None... wait
    # pairs: (a,1)x3, (b,1), (None,2)x2, (b,2), (c,None) -> 5 groups
    assert int(jax.device_get(segs.num_groups)) == 5
