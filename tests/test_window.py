"""Window function tests (WindowFunctionSuite analogue): TPU vs CPU."""

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import Window

from compare import assert_tpu_cpu_equal

DATA = {
    "g": (T.STRING, ["a", "a", "a", "b", "b", None, "c", "c", "c", "c"]),
    "x": (T.INT, [3, 1, 2, 5, 5, 7, None, 2, 9, 2]),
    "v": (T.LONG, [10, 20, 30, 40, None, 60, 70, 80, 90, 100]),
}


def make_df(s):
    return s.create_dataframe(DATA, num_partitions=3)


def test_row_number():
    def q(s):
        w = Window.partition_by("g").order_by("x", "v")
        return make_df(s).with_column("rn", F.row_number().over(w))
    assert_tpu_cpu_equal(q)


def test_rank_dense_rank():
    def q(s):
        w = Window.partition_by("g").order_by("x")
        df = make_df(s)
        return df.with_column("rk", F.rank().over(w)) \
                 .with_column("drk", F.dense_rank().over(w))
    assert_tpu_cpu_equal(q)


def test_running_sum_and_count():
    def q(s):
        w = Window.partition_by("g").order_by("x")
        df = make_df(s)
        return df.with_column("rs", F.sum("v").over(w)) \
                 .with_column("rc", F.count("v").over(w))
    assert_tpu_cpu_equal(q)


def test_whole_partition_agg():
    def q(s):
        w = Window.partition_by("g")
        df = make_df(s)
        return df.with_column("tot", F.sum("v").over(w)) \
                 .with_column("mx", F.max("v").over(w))
    assert_tpu_cpu_equal(q)


def test_bounded_rows_frame():
    def q(s):
        w = Window.partition_by("g").order_by("x", "v") \
            .rows_between(-1, 1)
        df = make_df(s)
        return df.with_column("s3", F.sum("v").over(w)) \
                 .with_column("m3", F.min("v").over(w)) \
                 .with_column("a3", F.avg("v").over(w))
    assert_tpu_cpu_equal(q, approx=True)


def test_lag_lead():
    def q(s):
        w = Window.partition_by("g").order_by("x", "v")
        df = make_df(s)
        return df.with_column("lg", F.lag("v", 1).over(w)) \
                 .with_column("ld", F.lead("v", 2).over(w))
    assert_tpu_cpu_equal(q)


def test_window_no_partition():
    def q(s):
        w = Window.order_by("x", "v")
        return make_df(s).with_column("rn", F.row_number().over(w))
    assert_tpu_cpu_equal(q)
