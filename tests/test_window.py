"""Window function tests (WindowFunctionSuite analogue): TPU vs CPU."""

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import Window

from compare import assert_tpu_cpu_equal

DATA = {
    "g": (T.STRING, ["a", "a", "a", "b", "b", None, "c", "c", "c", "c"]),
    "x": (T.INT, [3, 1, 2, 5, 5, 7, None, 2, 9, 2]),
    "v": (T.LONG, [10, 20, 30, 40, None, 60, 70, 80, 90, 100]),
}


def make_df(s):
    return s.create_dataframe(DATA, num_partitions=3)


def test_row_number():
    def q(s):
        w = Window.partition_by("g").order_by("x", "v")
        return make_df(s).with_column("rn", F.row_number().over(w))
    assert_tpu_cpu_equal(q)


def test_rank_dense_rank():
    def q(s):
        w = Window.partition_by("g").order_by("x")
        df = make_df(s)
        return df.with_column("rk", F.rank().over(w)) \
                 .with_column("drk", F.dense_rank().over(w))
    assert_tpu_cpu_equal(q)


def test_running_sum_and_count():
    def q(s):
        w = Window.partition_by("g").order_by("x")
        df = make_df(s)
        return df.with_column("rs", F.sum("v").over(w)) \
                 .with_column("rc", F.count("v").over(w))
    assert_tpu_cpu_equal(q)


def test_whole_partition_agg():
    def q(s):
        w = Window.partition_by("g")
        df = make_df(s)
        return df.with_column("tot", F.sum("v").over(w)) \
                 .with_column("mx", F.max("v").over(w))
    assert_tpu_cpu_equal(q)


def test_bounded_rows_frame():
    def q(s):
        w = Window.partition_by("g").order_by("x", "v") \
            .rows_between(-1, 1)
        df = make_df(s)
        return df.with_column("s3", F.sum("v").over(w)) \
                 .with_column("m3", F.min("v").over(w)) \
                 .with_column("a3", F.avg("v").over(w))
    assert_tpu_cpu_equal(q, approx=True)


def test_lag_lead():
    def q(s):
        w = Window.partition_by("g").order_by("x", "v")
        df = make_df(s)
        return df.with_column("lg", F.lag("v", 1).over(w)) \
                 .with_column("ld", F.lead("v", 2).over(w))
    assert_tpu_cpu_equal(q)


def test_window_no_partition():
    def q(s):
        w = Window.order_by("x", "v")
        return make_df(s).with_column("rn", F.row_number().over(w))
    assert_tpu_cpu_equal(q)


class TestBoundedRangeFrames:
    """Value-based RANGE BETWEEN x PRECEDING AND y FOLLOWING frames."""

    DATA = {"g": (T.STRING, ["a"] * 6 + ["b"] * 3),
            "k": (T.INT, [1, 2, 4, 7, 7, 12, 5, None, 9]),
            "v": (T.DOUBLE, [1.0, 2.0, 4.0, 7.0, 7.5, 12.0, 5.0, 100.0,
                             9.0])}

    def test_bounded_range_sum_ground_truth(self):
        from compare import tpu_session
        s = tpu_session()
        df = s.create_dataframe(self.DATA, num_partitions=2)
        w = F.Window.partition_by("g").order_by("k") \
            .range_between(-2, 2)
        rows = (df.with_column("rs", F.sum("v").over(w))
                .order_by("g", "k", "v").collect())
        by = {(r[0], r[1], r[2]): r[3] for r in rows}
        # g=a, k=1: values with k in [-1, 3] -> v(1) + v(2) = 3.0
        assert by[("a", 1, 1.0)] == 3.0
        # k=4: [2, 6] -> 2.0 + 4.0 = 6.0
        assert by[("a", 4, 4.0)] == 6.0
        # k=7 rows: [5, 9] -> 7.0 + 7.5 (peers both included)
        assert by[("a", 7, 7.0)] == 14.5
        # k=12: [10, 14] -> only itself
        assert by[("a", 12, 12.0)] == 12.0
        # NULL key frames over the null peer block only
        assert by[("b", None, 100.0)] == 100.0
        assert by[("b", 5, 5.0)] == 5.0   # [3,7]: only k=5
        assert by[("b", 9, 9.0)] == 9.0

    def test_bounded_range_engines_agree(self):
        def build(s):
            df = s.create_dataframe(self.DATA, num_partitions=3)
            w = F.Window.partition_by("g").order_by("k") \
                .range_between(-3, 1)
            return (df.with_column("rs", F.sum("v").over(w))
                    .with_column("rc", F.count("v").over(w))
                    .with_column("rm", F.max("v").over(w))
                    .order_by("g", "k", "v"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    def test_bounded_range_desc(self):
        def build(s):
            df = s.create_dataframe(self.DATA, num_partitions=2)
            w = F.Window.partition_by("g") \
                .order_by(F.col("k").desc()).range_between(-2, 0)
            return (df.with_column("rs", F.sum("v").over(w))
                    .order_by("g", "k", "v"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    def test_bounded_range_sql(self):
        def build(s):
            s.register_view("t", s.create_dataframe(self.DATA,
                                                    num_partitions=2))
            return s.sql(
                "SELECT g, k, v, sum(v) OVER (PARTITION BY g ORDER BY k "
                "RANGE BETWEEN 2 PRECEDING AND 2 FOLLOWING) AS rs "
                "FROM t ORDER BY g, k, v")

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    def test_bounded_range_unbounded_start_includes_null_block(self):
        from compare import tpu_session
        s = tpu_session()
        df = s.create_dataframe(
            {"k": (T.INT, [None, 1, 2]),
             "v": (T.DOUBLE, [10.0, 1.0, 2.0])}, num_partitions=1)
        w = F.Window.order_by("k").range_between(
            F.Window.unboundedPreceding, 1)
        rows = (df.with_column("rs", F.sum("v").over(w))
                .order_by("k", "v").collect())
        by = {r[0]: r[2] for r in rows}
        # UNBOUNDED PRECEDING reaches the partition start: the null row
        # is inside the k=1 row's frame (Spark partition-edge semantics)
        assert by[1] == 13.0
        assert by[2] == 13.0

        def build(s2):
            d = s2.create_dataframe(
                {"k": (T.INT, [None, 1, 2]),
                 "v": (T.DOUBLE, [10.0, 1.0, 2.0])}, num_partitions=2)
            w2 = F.Window.order_by("k").range_between(
                F.Window.unboundedPreceding, 1)
            return (d.with_column("rs", F.sum("v").over(w2))
                    .order_by("k", "v"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    def test_bounded_range_nan_peer_block(self):
        def build(s):
            d = s.create_dataframe(
                {"k": (T.DOUBLE, [1.0, 2.0, float("nan"), float("nan"),
                                  None]),
                 "v": (T.DOUBLE, [1.0, 2.0, 30.0, 40.0, 500.0])},
                num_partitions=2)
            w = F.Window.order_by("k").range_between(-1, 1)
            return (d.with_column("rs", F.sum("v").over(w))
                    .order_by("k", "v"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        d = s.create_dataframe(
            {"k": (T.DOUBLE, [1.0, 2.0, float("nan"), float("nan")]),
             "v": (T.DOUBLE, [1.0, 2.0, 30.0, 40.0])}, num_partitions=1)
        w = F.Window.order_by("k").range_between(-1, 1)
        rows = d.with_column("rs", F.sum("v").over(w)).collect()
        by = {r[0]: r[2] for r in rows
              if r[0] is not None and r[0] == r[0]}
        assert by[1.0] == 3.0 and by[2.0] == 3.0
        nan_sums = [r[2] for r in rows
                    if r[0] is not None and r[0] != r[0]]
        assert nan_sums == [70.0, 70.0]  # NaN rows frame over NaN peers

    def test_bounded_range_narrow_key_no_overflow(self):
        def build(s):
            d = s.create_dataframe(
                {"k": (T.INT, [2147483640, 2147483645, 2147483646]),
                 "v": (T.DOUBLE, [1.0, 2.0, 4.0])}, num_partitions=1)
            w = F.Window.order_by("k").range_between(0, 10)
            return (d.with_column("rs", F.sum("v").over(w))
                    .order_by("k"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        d = s.create_dataframe(
            {"k": (T.INT, [2147483640, 2147483645, 2147483646]),
             "v": (T.DOUBLE, [1.0, 2.0, 4.0])}, num_partitions=1)
        w = F.Window.order_by("k").range_between(0, 10)
        rows = d.with_column("rs", F.sum("v").over(w)).order_by(
            "k").collect()
        # k + 10 exceeds int32 max: must widen, not wrap to an empty frame
        assert [r[2] for r in rows] == [7.0, 6.0, 4.0]

    def test_range_between_rejects_float_bounds(self):
        import pytest as _pt
        with _pt.raises(TypeError):
            F.Window.order_by("k").range_between(-0.5, 0.5)
