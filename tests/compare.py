"""CPU-vs-TPU query compare harness — the SparkQueryCompareTestSuite
analogue (reference tests/: every test body runs under a CPU session and a
TPU session and the collected results must match)."""

import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

from conftest import FLOAT_ABS, FLOAT_REL, TEST_PLATFORM


def cpu_session(**confs) -> TpuSparkSession:
    conf = RapidsConf({"spark.rapids.sql.enabled": False,
                       "spark.sql.shuffle.partitions": 4})
    for k, v in confs.items():
        conf.set(k, v)
    return TpuSparkSession(conf)


def tpu_session(**confs) -> TpuSparkSession:
    conf = RapidsConf({"spark.rapids.sql.enabled": True,
                       "spark.sql.shuffle.partitions": 4})
    for k, v in confs.items():
        conf.set(k, v)
    return TpuSparkSession(conf)


def _canon(rows, approx, ignore_order):
    approx = approx or TEST_PLATFORM == "tpu"

    def enc(v):
        if v is None:
            return (0, "")
        if isinstance(v, float):
            if v != v:
                return (1, "NaN")
            # No absolute-decimal rounding: _row_approx_eq compares with
            # RELATIVE tolerance, so large magnitudes (where 3 decimals is
            # far tighter than f64-emulation error) and tiny ones (where
            # it is uselessly loose) are both judged proportionally.
            return (1, v)
        if isinstance(v, bool):
            return (2, v)
        return (3, str(v)) if not isinstance(v, (int, float)) else (1, v)

    def sort_key(r):
        # floats keyed by a relative (significant-digit) canonicalization
        # so near-equal CPU/TPU values land in the same sort position
        return str(tuple(
            (t, float(f"{v:.6g}")) if isinstance(v, float) else (t, v)
            for t, v in r))

    out = [tuple(enc(v) for v in r) for r in rows]
    if ignore_order:
        out = sorted(out, key=sort_key)
    return out


def assert_tpu_cpu_equal(build_fn, approx=False, ignore_order=True,
                         confs=None, expect_fallback=None,
                         forbid_fallback=None):
    """build_fn(session) -> DataFrame; runs on both engines and compares.

    expect_fallback: optional operator-name substring expected in the explain
    output's cannot-run list (assert_gpu_fallback_collect analogue).
    forbid_fallback: operator-name substring that must NOT appear in the
    cannot-run list — guards against a regression test silently comparing
    CPU against CPU.
    """
    confs = confs or {}
    cpu = cpu_session(**confs)
    tpu = tpu_session(**confs)
    cpu_rows = build_fn(cpu).collect()
    df = build_fn(tpu)
    tpu_rows = df.collect()
    if expect_fallback:
        explain = tpu.last_explain
        assert expect_fallback in explain and "cannot run on TPU" in explain, \
            f"expected fallback of {expect_fallback}; explain:\n{explain}"
    if forbid_fallback:
        explain = tpu.last_explain
        assert not any(forbid_fallback in ln for ln in
                       explain.splitlines() if "cannot run on TPU" in ln), \
            f"unexpected fallback of {forbid_fallback}; explain:\n{explain}"
    a = _canon(cpu_rows, approx, ignore_order)
    b = _canon(tpu_rows, approx, ignore_order)
    assert len(a) == len(b), \
        f"row count: cpu={len(a)} tpu={len(b)}\ncpu={a[:10]}\ntpu={b[:10]}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if approx or TEST_PLATFORM == "tpu":
            _row_approx_eq(ra, rb, i)
        else:
            assert ra == rb, f"row {i}: cpu={ra} tpu={rb}"


def _row_approx_eq(ra, rb, i):
    assert len(ra) == len(rb), f"row {i} width"
    for (ta, va), (tb, vb) in zip(ra, rb):
        assert ta == tb, f"row {i}: {va!r} vs {vb!r}"
        if isinstance(va, float) and isinstance(vb, float):
            # rel dominates for large magnitudes; the abs floor covers
            # near-zero values (where the old 6-decimal rounding was
            # effectively a ~5e-7 absolute tolerance)
            assert vb == pytest.approx(va, rel=max(FLOAT_REL, 1e-5),
                                       abs=max(FLOAT_ABS, 1e-6)), f"row {i}"
        else:
            assert va == vb, f"row {i}: {va!r} vs {vb!r}"
