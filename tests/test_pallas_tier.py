"""Pallas kernel tier: interpret-mode parity matrix vs the XLA fallbacks.

Every registered kernel must be BIT-identical to the XLA formulation it
replaces (docs/kernels.md).  On the CPU test backend the kernels engage
through the Pallas interpreter (`spark.rapids.sql.tpu.pallas.interpret`),
which executes the kernel's own program — so these tests pin the kernel
logic, not just the fallback.  Each family is exercised across empty,
single-row, NULL-heavy, capacity-boundary and string/varlen inputs, plus
the take_head-truncated live-bytes case for the pack kernel.
"""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    HostBatch, device_to_host, host_to_device, round_up_capacity,
)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exprs import strings as S
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels import pallas_tier as PT
from spark_rapids_tpu.kernels.join import join_pairs_static
from spark_rapids_tpu.kernels.layout import concat_kway, gather_segments_kway
from spark_rapids_tpu.kernels import take_head

INTERPRET_KEY = "spark.rapids.sql.tpu.pallas.interpret"


@contextlib.contextmanager
def tier(extra=None):
    PT.configure(RapidsConf(dict(extra or {})))
    try:
        yield
    finally:
        PT.configure(None)


def interp_conf():
    return {INTERPRET_KEY: True}


def off_conf():
    return {spec.entry.key: False for spec in PT.registered()}


def make_batch(pydict):
    return host_to_device(HostBatch.from_pydict(pydict))


def assert_batch_bits(a, b):
    """Raw-buffer equality: same bytes, same dtypes, dead lanes included."""
    assert int(jax.device_get(a.num_rows)) == int(jax.device_get(b.num_rows))
    assert len(a.columns) == len(b.columns)
    for ca, cb in zip(a.columns, b.columns):
        for field in ("data", "validity", "offsets", "codes", "lengths"):
            va, vb = getattr(ca, field, None), getattr(cb, field, None)
            assert (va is None) == (vb is None), field
            if va is not None:
                ga, gb = jax.device_get(va), jax.device_get(vb)
                assert ga.dtype == gb.dtype, field
                np.testing.assert_array_equal(ga, gb, err_msg=field)


def run_both(fn):
    """fn() under the interpreted tier and under kernel-off; no fallbacks
    may fire while the tier is engaged (the kernel really ran)."""
    with tier(interp_conf()):
        before = PT.fallback_count()
        got = jax.block_until_ready(fn())
        assert PT.fallback_count() == before, "kernel fell back under interpret"
    with tier(off_conf()):
        want = jax.block_until_ready(fn())
    return got, want


MIXED = {
    "i": (T.INT, [3, None, 7, 1, 7, None, 0]),
    "f": (T.FLOAT, [1.5, -2.0, None, 0.0, float("nan"), 3.25, -0.0]),
    "s": (T.STRING, ["bb", "", None, "apple", "bb", "zed", "aa"]),
    "b": (T.BOOLEAN, [True, False, None, True, False, True, None]),
}
NULLY = {
    "i": (T.INT, [None, None, 5, None]),
    "f": (T.FLOAT, [None, 1.0, 2.0, None]),
    "s": (T.STRING, [None, "x", None, None]),
    "b": (T.BOOLEAN, [True, None, False, True]),
}
SINGLE = {
    "i": (T.INT, [42]),
    "f": (T.FLOAT, [0.5]),
    "s": (T.STRING, ["one"]),
    "b": (T.BOOLEAN, [None]),
}


@pytest.mark.parametrize("dicts,cap", [
    ([MIXED, NULLY], round_up_capacity(11)),
    ([SINGLE, SINGLE], 2),                      # capacity-boundary: cap == rows
    ([{"i": (T.INT, []), "s": (T.STRING, [])},
      {"i": (T.INT, [1]), "s": (T.STRING, ["z"])}], 8),   # empty input
], ids=["mixed-nully", "single-boundary", "empty"])
def test_concat_kway_parity(dicts, cap):
    batches = [make_batch(d) for d in dicts]
    got, want = run_both(lambda: concat_kway(batches, cap))
    assert_batch_bits(got, want)


def test_concat_kway_take_head_live_bytes():
    """A take_head-truncated input contributes offsets[num_rows] bytes —
    the kernel must not leak the stale tail bytes past the truncation."""
    b1 = take_head(make_batch(MIXED), 2)
    b2 = make_batch(SINGLE)
    got, want = run_both(
        lambda: concat_kway([b1, b2], round_up_capacity(3)))
    assert_batch_bits(got, want)
    out = device_to_host(got).to_pydict()
    assert out["s"] == ["bb", "", "one"]


@pytest.mark.parametrize("starts,counts", [
    ([1, 0], [3, 2]),          # interior + prefix segments
    ([0, 3], [0, 1]),          # empty segment from input 0
    ([0, 0], [7, 4]),          # whole-batch segments, boundary cap
], ids=["interior", "empty-seg", "whole"])
def test_gather_segments_kway_parity(starts, counts):
    batches = [make_batch(MIXED), make_batch(NULLY)]
    cap = max(sum(counts), 1)
    got, want = run_both(lambda: gather_segments_kway(
        batches,
        [jnp.asarray(s, jnp.int32) for s in starts],
        [jnp.asarray(c, jnp.int32) for c in counts], cap))
    assert_batch_bits(got, want)


def _devvals(batch, idxs):
    return [DevVal(c.dtype, c.data, c.validity, c.offsets)
            for i, c in enumerate(batch.columns) if i in idxs]


@pytest.mark.parametrize("left,right,key_idx,pair_cap", [
    # int keys, duplicates both sides
    ({"k": (T.INT, [1, 2, 2, None, 3, 1, 2])},
     {"k": (T.INT, [2, 2, 1, None])}, [0], 64),
    # string keys incl. empties and NULLs
    ({"k": (T.STRING, ["ab", "", None, "zzz", "ab", "q"])},
     {"k": (T.STRING, ["", "ab", None, "q", "nope"])}, [0], 64),
    # composite int+string key
    ({"k": (T.INT, [1, 1, 2, 2]), "s": (T.STRING, ["a", "b", "a", None])},
     {"k": (T.INT, [1, 2, 2]), "s": (T.STRING, ["a", "a", None])},
     [0, 1], 32),
    # empty probe side
    ({"k": (T.INT, [])}, {"k": (T.INT, [5, 6])}, [0], 8),
    # overflow boundary: true pair total exceeds pair_cap; the truncated
    # buffers and the overflow flag must still match bit-for-bit
    ({"k": (T.INT, [7] * 6)}, {"k": (T.INT, [7] * 6)}, [0], 16),
], ids=["int", "string", "composite", "empty", "overflow"])
def test_join_pairs_static_parity(left, right, key_idx, pair_cap):
    lb, rb = make_batch(left), make_batch(right)
    lk, rk = _devvals(lb, key_idx), _devvals(rb, key_idx)
    got, want = run_both(lambda: join_pairs_static(
        lk, lb.num_rows, rk, rb.num_rows, pair_cap))
    for g, w in zip(got, want):
        ga, wa = jax.device_get(g), jax.device_get(w)
        assert ga.dtype == wa.dtype
        np.testing.assert_array_equal(ga, wa)
    if pair_cap == 16:
        assert bool(jax.device_get(got[-1]))  # 36 pairs > 16: overflow set


@pytest.mark.parametrize("vals", [
    ["hello", "", None, "a" * 40, "hello", "x"],
    [None, None],
    [""],
    [],
], ids=["mixed", "all-null", "one-empty", "empty"])
def test_string_hash2_parity(vals):
    b = make_batch({"s": (T.STRING, vals)})
    c = b.columns[0]
    v = DevVal(c.dtype, c.data, c.validity, c.offsets)
    got, want = run_both(lambda: S.string_hash2(v))
    for g, w in zip(got, want):
        np.testing.assert_array_equal(jax.device_get(g), jax.device_get(w))


def test_rows_with_match_parity():
    b = make_batch({"s": (T.STRING, ["abc", None, "xabx", "", "ab"])})
    c = b.columns[0]
    v = DevVal(c.dtype, c.data, c.validity, c.offsets)
    got, want = run_both(lambda: S._rows_with_match(v, b"ab"))
    np.testing.assert_array_equal(jax.device_get(got), jax.device_get(want))


def test_cpu_without_interpret_silently_falls_back():
    """Default confs on a non-TPU backend: the XLA formulation runs and
    each engaged-kernel decision is counted as a fallback."""
    if jax.default_backend() == "tpu":
        pytest.skip("backend fallback only observable off-TPU")
    b = make_batch({"s": (T.STRING, ["fallback", "probe"])})
    c = b.columns[0]
    v = DevVal(c.dtype, c.data, c.validity, c.offsets)
    with tier({}):  # defaults: kernels on, interpret off
        before = PT.fallback_count()
        got = jax.block_until_ready(S.string_hash2(v))
        assert PT.fallback_count() > before
    with tier(off_conf()):
        want = jax.block_until_ready(S.string_hash2(v))
    for g, w in zip(got, want):
        np.testing.assert_array_equal(jax.device_get(g), jax.device_get(w))


def test_decide_reasons():
    with tier(off_conf()):
        d = PT.decide("stringHash")
        assert not d.engaged and d.reason == "off"
    with tier(interp_conf()):
        d = PT.decide("stringHash")
        assert d.engaged and d.interpret and d.reason == ""
    with tier({INTERPRET_KEY: True,
               "spark.rapids.sql.tpu.pallas.vmemBudgetBytes": 1024}):
        d = PT.decide("joinProbe", resident_bytes=4096)
        assert not d.engaged and d.reason == "budget"
    if jax.default_backend() != "tpu":
        with tier({}):
            d = PT.decide("strings")
            assert not d.engaged and d.reason == "backend"


def test_registry_names():
    assert [s.name for s in PT.registered()] == [
        "gatherScatter", "joinProbe", "stringHash", "strings"]


def test_deprecated_strings_env_alias(monkeypatch):
    # alias applies only while pallas.strings.enabled is not explicitly set
    monkeypatch.setenv("SPARK_RAPIDS_PALLAS_STRINGS", "0")
    with tier(interp_conf()):
        assert not PT.decide("strings").engaged
        assert PT.decide("stringHash").engaged  # alias is strings-only
    with tier({**interp_conf(),
               "spark.rapids.sql.tpu.pallas.strings.enabled": True}):
        assert PT.decide("strings").engaged  # explicit conf wins
    monkeypatch.setenv("SPARK_RAPIDS_PALLAS_STRINGS", "interp")
    with tier({}):
        d = PT.decide("strings")
        assert d.engaged and d.interpret


def test_session_counts_fallbacks():
    """A default-conf CPU session surfaces the per-query fallback delta
    as last_metrics['pallasFallbackCount'] (unique schema: a cached
    trace would skip the trace-time tier decision entirely)."""
    if jax.default_backend() == "tpu":
        pytest.skip("no fallbacks on the real kernel backend")
    from spark_rapids_tpu.session import TpuSparkSession
    s = TpuSparkSession(RapidsConf({"spark.rapids.sql.enabled": True}))
    df = s.create_dataframe({
        "uniq_pallas_probe_col": ["aa", "abq", None, "b", "xaby"],
        "uniq_pallas_probe_val": [1, 2, 3, 4, 5]})
    out = df.filter(
        df["uniq_pallas_probe_col"].contains("ab")).collect()
    assert len(out) == 2
    assert s.last_metrics["pallasFallbackCount"] >= 1
