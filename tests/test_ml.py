"""ML handoff tests (ColumnarRdd / InternalColumnarRddConverter analogue)."""

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ml import to_device_batches, to_jax

from compare import tpu_session

DATA = {
    "x": (T.DOUBLE, [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
    "y": (T.INT, [0, 1, 0, 1, 0, None]),
    "s": (T.STRING, ["a", "b", "c", "d", "e", "f"]),
}


def test_to_device_batches():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    batches = to_device_batches(df.filter(df["x"] > 1.5))
    assert batches
    total = sum(b.host_num_rows() for b in batches)
    assert total == 5
    # results are device arrays, not host copies
    assert isinstance(batches[0].columns[0].data, jnp.ndarray)


def test_to_jax_feature_matrix():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    feats = to_jax(df.select("x", "y"))
    assert set(feats) == {"x", "x__valid", "y", "y__valid"}
    assert feats["x"].shape[0] == 6
    assert int(feats["y__valid"].sum()) == 5
    # feed straight into a jitted step (no host copy needed)
    import jax

    @jax.jit
    def step(x, v):
        return jnp.sum(jnp.where(v, x, 0.0))

    assert float(step(feats["x"], feats["x__valid"])) == 21.0
