"""Compile/dispatch economics tests: the per-query compile-miss /
dispatch-count / device-time accounting (utils/compile_registry +
utils/tracing), the shared shape-bucket policy, tail-stage fusion, and
session.prewarm()."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

from compare import tpu_session


def _headline_query(s, rows=1000):
    """Mini clone of the bench headline shape: filter -> project ->
    two-key group-by aggregate -> order_by tail."""
    df = s.create_dataframe({
        "k": [i % 7 for i in range(rows)],
        "p": [i % 3 for i in range(rows)],
        "q": [i % 50 for i in range(rows)],
        "v": list(range(rows)),
    })
    return (df
            .filter(df["q"] < 40)
            .with_column("w", df["v"] * df["q"])
            .group_by("k", "p")
            .agg(F.sum("w").alias("sw"), F.count("w").alias("c"),
                 F.min("v").alias("mn"), F.max("v").alias("mx"))
            .order_by("k", "p"))


def test_metrics_present_for_jitted_query():
    s = tpu_session()
    q = _headline_query(s)
    rows = q.collect()
    assert rows
    m = s.last_metrics
    for key in ("compileCount", "compileWallNs", "dispatchCount",
                "compiledShapes", "deviceTimeNs"):
        assert key in m, f"last_metrics missing {key}: {sorted(m)}"
    assert m["compileCount"] > 0  # first run of fresh execs compiles
    assert m["compileWallNs"] > 0
    assert m["dispatchCount"] > 0
    assert m["compiledShapes"] >= m["compileCount"]


def test_repeated_query_reports_zero_new_compiles():
    s = tpu_session()
    q = _headline_query(s)
    first = q.collect()
    second = q.collect()
    assert first == second
    m = s.last_metrics
    assert m["compileCount"] == 0, \
        f"repeat of an identical query recompiled: {m['compileCount']}"
    assert m["compileWallNs"] == 0
    assert m["dispatchCount"] > 0  # still dispatches, just from cache


def test_metrics_detail_toggle_keeps_plan_cache_warm():
    """The metrics-detail conf is excluded from the plan-cache fingerprint:
    flipping it must not recompile anything (bench relies on this for the
    accurate device-time capture run)."""
    s = tpu_session()
    q = _headline_query(s)
    q.collect()
    s.set_conf("spark.rapids.sql.tpu.metrics.detailEnabled", True)
    q.collect()
    m = s.last_metrics
    assert m["compileCount"] == 0
    assert m["deviceTimeNs"] > 0


def _dispatches(fuse: bool):
    conf = RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.sql.shuffle.partitions": 4,
        # force the stage-break shrink so the fused-vs-separate dispatch
        # difference is observable at test scale
        "spark.rapids.sql.tpu.pipeline.shrinkBytes": 0,
        "spark.rapids.sql.tpu.pipeline.fuseTail.enabled": fuse,
    })
    s = TpuSparkSession(conf)
    q = _headline_query(s)
    rows = q.collect()
    assert rows
    return s.last_metrics["dispatchCount"], rows


def test_tail_fusion_reduces_dispatch_count():
    fused_d, fused_rows = _dispatches(fuse=True)
    plain_d, plain_rows = _dispatches(fuse=False)
    assert fused_rows == plain_rows  # fusion is a pure dispatch optimizer
    assert fused_d < plain_d, \
        f"tail fusion did not reduce dispatches: {fused_d} vs {plain_d}"


def test_prewarm_compiles_hot_set_once():
    s = tpu_session()
    q = _headline_query(s)
    warm = s.prewarm(q)
    assert warm["compileCount"] > 0
    q.collect()
    assert s.last_metrics["compileCount"] == 0, \
        "collect after prewarm() must hit every compiled program"
    # a second prewarm is a no-op compile-wise
    warm2 = s.prewarm(q)
    assert warm2["compileCount"] == 0


def test_shared_bucket_policy():
    from spark_rapids_tpu.batch import BUCKETS, round_up_capacity
    assert BUCKETS.rows(1) == 8
    assert BUCKETS.rows(9) == 16
    assert BUCKETS.elems(1) == 16
    assert BUCKETS.elems(17) == 32
    # round_up_capacity routes through the shared policy
    assert round_up_capacity(1000) == BUCKETS.rows(1000) == 1024
    ladder = BUCKETS.hot_buckets(1 << 20)
    assert ladder[0] == 8 and ladder[-1] == 1 << 20
    # pow2 ladder: compiled-shape cardinality is log2-bounded
    assert len(ladder) == 18


def test_pallas_strings_tpu_only(monkeypatch):
    """Pallas lowering is strictly backend == 'tpu' (plus explicit interp
    mode); any other accelerator backend takes the XLA formulation."""
    import jax

    from spark_rapids_tpu.kernels import pallas_strings as PS
    monkeypatch.delenv("SPARK_RAPIDS_PALLAS_STRINGS", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert not PS.use_pallas_strings()
    monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
    assert not PS.use_pallas_strings()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert PS.use_pallas_strings()
    monkeypatch.setenv("SPARK_RAPIDS_PALLAS_STRINGS", "interp")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert PS.use_pallas_strings()
    monkeypatch.setenv("SPARK_RAPIDS_PALLAS_STRINGS", "0")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert not PS.use_pallas_strings()
