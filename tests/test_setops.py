"""INTERSECT / EXCEPT set operations (Spark plans these as null-safe
left-semi/anti joins; the engine rewrites them onto the hash-aggregate
path — group keys already give the set-op NULL-equality)."""

import pytest

from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

A = {"k": (T.INT, [1, 1, 2, 3, None, 5]),
     "s": (T.STRING, ["a", "a", "b", "c", None, "e"])}
B = {"k": (T.INT, [1, 3, None, 7]),
     "s": (T.STRING, ["a", "c", None, "g"])}


def _frames(s):
    return (s.create_dataframe(A, num_partitions=2),
            s.create_dataframe(B, num_partitions=2))


def test_intersect_dataframe():
    def build(s):
        a, b = _frames(s)
        return a.intersect(b).order_by("k", "s")

    assert_tpu_cpu_equal(build, ignore_order=False)
    s = tpu_session()
    a, b = _frames(s)
    rows = a.intersect(b).order_by("k").collect()
    # NULL row matches NULL row (set-op equality), dups collapse;
    # ascending sort puts NULLs first (Spark default)
    assert rows == [(None, None), (1, "a"), (3, "c")]


def test_subtract_dataframe():
    def build(s):
        a, b = _frames(s)
        return a.subtract(b).order_by("k", "s")

    assert_tpu_cpu_equal(build, ignore_order=False)
    s = tpu_session()
    a, b = _frames(s)
    rows = a.subtract(b).order_by("k").collect()
    assert rows == [(2, "b"), (5, "e")]


def test_intersect_except_sql():
    def build(s):
        a, b = _frames(s)
        s.register_view("a", a)
        s.register_view("b", b)
        return s.sql("SELECT k, s FROM a INTERSECT SELECT k, s FROM b "
                     "ORDER BY k, s")

    assert_tpu_cpu_equal(build, ignore_order=False)

    def build2(s):
        a, b = _frames(s)
        s.register_view("a", a)
        s.register_view("b", b)
        return s.sql("SELECT k, s FROM a EXCEPT DISTINCT "
                     "SELECT k, s FROM b ORDER BY k, s")

    assert_tpu_cpu_equal(build2, ignore_order=False)


def test_union_distinct_sql():
    def build(s):
        a, b = _frames(s)
        s.register_view("a", a)
        s.register_view("b", b)
        return s.sql("SELECT k, s FROM a UNION SELECT k, s FROM b "
                     "ORDER BY k, s")

    assert_tpu_cpu_equal(build, ignore_order=False)


def test_set_op_column_count_mismatch():
    s = tpu_session()
    a, _ = _frames(s)
    with pytest.raises(ValueError):
        a.intersect(a.select("k"))


def test_intersect_all_rejected():
    s = tpu_session()
    a, b = _frames(s)
    s.register_view("a", a)
    s.register_view("b", b)
    with pytest.raises(NotImplementedError):
        s.sql("SELECT k FROM a INTERSECT ALL SELECT k FROM b")


def test_intersect_binds_tighter_than_union():
    """a UNION (b INTERSECT c), per SQL precedence — not (a UNION b)
    INTERSECT c."""
    s = tpu_session()
    for name, vals in (("ta", [1]), ("tb", [2]), ("tc", [2])):
        s.register_view(name, s.create_dataframe(
            {"k": (T.INT, vals)}, num_partitions=1))
    rows = s.sql("SELECT k FROM ta UNION SELECT k FROM tb "
                 "INTERSECT SELECT k FROM tc ORDER BY k").collect()
    assert rows == [(1,), (2,)]


def test_union_all_distinct_rejected():
    s = tpu_session()
    s.register_view("ta", s.create_dataframe(
        {"k": (T.INT, [1])}, num_partitions=1))
    with pytest.raises(SyntaxError):
        s.sql("SELECT k FROM ta UNION ALL DISTINCT SELECT k FROM ta")
