"""rapidslint: per-rule firing/non-firing fixtures, suppression and
baseline mechanics, the whole-tree clean gate, and the CLI exit codes.

The fixtures are inline source strings fed straight through the engine —
each rule gets at least one positive (must fire) and one negative (must
stay quiet) so a behavior change in a matcher is caught here before it
lands as a false CI failure (or a silent miss) on the real tree.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from spark_rapids_tpu.analysis.engine import (
    Baseline, Finding, LintEngine, SourceFile,
)
from spark_rapids_tpu.analysis import rules as R
from spark_rapids_tpu.analysis import plan_verify

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "rapidslint.py")


def lint(rule, text, path="spark_rapids_tpu/fixture.py", files=None,
         root=REPO):
    """Run one rule over inline fixture source, return findings."""
    srcs = files if files is not None else [(path, text)]
    sfs = [SourceFile(os.path.join(root, p), p, textwrap.dedent(t))
           for p, t in srcs]
    return LintEngine([rule]).run(sfs, root)


def rule_ids(findings):
    return [f.rule_id for f in findings]


# -- R1: import-time jnp construction -----------------------------------------

def test_r1_fires_on_module_scope_jnp():
    out = lint(R.ImportTimeJnpRule(), """\
        import jax.numpy as jnp
        LOOKUP = jnp.zeros((4,), dtype=jnp.int32)
        """)
    assert rule_ids(out) == ["R1"]
    assert "import time" in out[0].message


def test_r1_fires_inside_class_body_and_conditional():
    out = lint(R.ImportTimeJnpRule(), """\
        import jax.numpy as jnp
        class K:
            TABLE = jnp.arange(8)
        if True:
            OTHER = jax.numpy.ones(3)
        """)
    assert rule_ids(out) == ["R1", "R1"]


def test_r1_quiet_inside_functions_and_lambdas():
    out = lint(R.ImportTimeJnpRule(), """\
        import jax.numpy as jnp
        def build():
            return jnp.zeros((4,))
        make = lambda: jnp.ones(2)
        SHAPE = (4, 4)  # plain tuple at import time is fine
        """)
    assert out == []


# -- R2: semaphore release in finally -----------------------------------------

def test_r2_fires_on_unpaired_acquire():
    out = lint(R.SemaphoreReleaseRule(), """\
        def stage(ctx, hb):
            ctx.semaphore.acquire()
            return push(hb)
        """)
    assert rule_ids(out) == ["R2"]
    assert "finally" in out[0].message


def test_r2_quiet_when_release_in_finally():
    out = lint(R.SemaphoreReleaseRule(), """\
        def stage(ctx, hb):
            ctx.semaphore.acquire()
            try:
                return push(hb)
            finally:
                ctx.semaphore.release()
        """)
    assert out == []


def test_r2_quiet_on_non_semaphore_acquire():
    # plain lock acquire/release pairs are not this rule's business
    out = lint(R.SemaphoreReleaseRule(), """\
        def locked(self):
            self._lock.acquire()
            self._lock.release()
        """)
    assert out == []


# -- R3: unbounded waits ------------------------------------------------------

def test_r3_fires_on_unbounded_primitives():
    out = lint(R.UnboundedWaitRule(), """\
        def run(cond, t, self):
            cond.wait()
            t.join()
            self._q.get()
        """)
    assert rule_ids(out) == ["R3", "R3", "R3"]


def test_r3_quiet_with_timeouts_and_non_queue_get():
    out = lint(R.UnboundedWaitRule(), """\
        def run(cond, t, q, d):
            cond.wait(0.25)
            t.join(timeout=5.0)
            q.get(timeout=1.0)
            d.get()  # receiver is not queue-shaped: dict-style get
        """)
    assert out == []


# -- R4: swallowed KeyboardInterrupt/SystemExit -------------------------------

def test_r4_fires_on_bare_except_and_base_exception():
    out = lint(R.SwallowBaseExceptionRule(), """\
        def f():
            try:
                work()
            except:
                pass
            try:
                work()
            except BaseException as e:
                log(e)
        """)
    assert rule_ids(out) == ["R4", "R4"]


def test_r4_quiet_on_reraise_exit_and_narrow_handler():
    out = lint(R.SwallowBaseExceptionRule(), """\
        import os, sys
        def f():
            try:
                work()
            except BaseException:
                raise
            try:
                work()
            except BaseException:
                os._exit(1)
            try:
                work()
            except Exception:
                pass  # cannot catch KI/SE — fine
        """)
    assert out == []


# -- R5: donation hygiene -----------------------------------------------------

def test_r5_fires_on_raw_jit_and_stray_donation():
    out = lint(R.DonationHygieneRule(), """\
        import jax
        def compile_it(f):
            g = jax.jit(f)
            h = jax.jit(f, donate_argnums=(0,))
            return g, h
        """)
    assert rule_ids(out) == ["R5", "R5"]


def test_r5_quiet_on_instrumented_jit_and_registry_file():
    out = lint(R.DonationHygieneRule(), """\
        from spark_rapids_tpu.utils.compile_registry import instrumented_jit
        def compile_it(f):
            return instrumented_jit(f, donate_argnums=(0,))
        """)
    assert out == []
    # the registry module itself is the one sanctioned jax.jit call site
    out = lint(R.DonationHygieneRule(), """\
        import jax
        def _wrap(f):
            return jax.jit(f)
        """, path=R.DonationHygieneRule.ALLOWED_FILE)
    assert out == []


# -- R6: device sync under DeviceRuntime._lock --------------------------------

def test_r6_fires_on_sync_inside_runtime_lock():
    out = lint(R.SyncUnderRuntimeLockRule(), """\
        import jax, threading
        class DeviceRuntime:
            _lock = threading.Lock()
            @classmethod
            def snap(cls, buf):
                with cls._lock:
                    return jax.device_get(buf)
        """)
    assert rule_ids(out) == ["R6"]
    assert "_lock" in out[0].message


def test_r6_quiet_when_sync_moved_outside_lock():
    out = lint(R.SyncUnderRuntimeLockRule(), """\
        import jax, threading
        class DeviceRuntime:
            _lock = threading.Lock()
            @classmethod
            def snap(cls, buf):
                with cls._lock:
                    ref = buf
                return jax.device_get(ref)
        class Other:
            _lock = threading.Lock()
            def ok(self, buf):
                # not DeviceRuntime's lock: out of scope for R6
                with self._lock:
                    return jax.device_get(buf)
        """)
    assert out == []


# -- R7: conf-registry sync ---------------------------------------------------

def test_r7_fires_on_dead_conf_and_unregistered_literal():
    out = lint(R.ConfRegistrySyncRule(), None, files=[
        ("spark_rapids_tpu/config.py", """\
            DEAD = conf_bool("spark.rapids.test.deadKnob", True, "unused")
            LIVE = conf_int("spark.rapids.test.liveKnob", 4, "used")
            """),
        ("spark_rapids_tpu/user.py", """\
            from spark_rapids_tpu.config import LIVE
            def f(conf):
                conf.set("spark.rapids.test.notRegistered", "1")
                return LIVE.get(conf)
            """),
    ])
    msgs = [f.message for f in out]
    assert len(msgs) == 2
    assert any("dead conf" in m and "deadKnob" in m for m in msgs)
    assert any("not registered" in m and "notRegistered" in m for m in msgs)


def test_r7_quiet_on_registered_and_referenced_keys():
    out = lint(R.ConfRegistrySyncRule(), None, files=[
        ("spark_rapids_tpu/config.py", """\
            LIVE = conf_int("spark.rapids.test.liveKnob", 4, "used")
            '''docstring mentioning spark.rapids.test.proseOnly is fine'''
            """),
        ("spark_rapids_tpu/user.py", """\
            from spark_rapids_tpu.config import LIVE
            def f(conf, name):
                key = f"spark.rapids.sql.exec.{name}"  # dynamic family
                return LIVE.get(conf), conf.lookup(key)
            def g(conf):
                # prefix literal covering a registered key
                return conf.starts("spark.rapids.test.")
            """),
    ])
    assert out == []


# -- R8: metrics-key sync -----------------------------------------------------

_SESSION_FIXTURE = """\
    class S:
        def execute(self):
            self.last_metrics["compileCount"] = 1
            self.last_metrics["dispatchCount"] = 2
    """

_BENCH_FIXTURE = """\
    def record(m):
        return {
            "vs_baseline": 1.0,
            "compile_count": m.get("compileCount"),
        }
    """


def _write_doc(root, keys):
    os.makedirs(os.path.join(root, "docs"), exist_ok=True)
    rows = "\n".join(f"| `{k}` | doc |" for k in keys)
    with open(os.path.join(root, "docs", "metrics.md"), "w") as f:
        f.write("| Key | Meaning |\n|---|---|\n" + rows + "\n")


def test_r8_quiet_when_in_sync(tmp_path):
    root = str(tmp_path)
    _write_doc(root, ["compileCount", "dispatchCount", "vs_baseline",
                      "compile_count"])
    out = lint(R.MetricsKeySyncRule(), None, root=root, files=[
        ("spark_rapids_tpu/session.py", _SESSION_FIXTURE),
        ("bench.py", _BENCH_FIXTURE),
    ])
    assert out == []


def test_r8_fires_on_each_drift_direction(tmp_path):
    root = str(tmp_path)
    # doc omits dispatchCount and documents a phantom key
    _write_doc(root, ["compileCount", "vs_baseline", "compile_count",
                      "phantomKey"])
    bench_bad = _BENCH_FIXTURE.replace('m.get("compileCount")',
                                       'm.get("neverSetKey")')
    out = lint(R.MetricsKeySyncRule(), None, root=root, files=[
        ("spark_rapids_tpu/session.py", _SESSION_FIXTURE),
        ("bench.py", bench_bad),
    ])
    msgs = [f.message for f in out]
    assert any("neverSetKey" in m and "never sets" in m for m in msgs)
    assert any("dispatchCount" in m and "undocumented" in m for m in msgs)
    assert any("phantomKey" in m and "neither" in m for m in msgs)


def test_r8_fires_when_doc_missing(tmp_path):
    out = lint(R.MetricsKeySyncRule(), None, root=str(tmp_path), files=[
        ("spark_rapids_tpu/session.py", _SESSION_FIXTURE),
    ])
    assert len(out) == 1 and "missing" in out[0].message


# -- R9: pallas kernel tier ---------------------------------------------------

def test_r9_fires_outside_tier_entry_points():
    out = lint(R.PallasKernelTierRule(), """\
        from jax.experimental import pallas as pl
        def rogue_kernel(x):
            return pl.pallas_call(lambda r, o: None,
                                  out_shape=x)(x)
        """, path="spark_rapids_tpu/exprs/strings.py")
    assert rule_ids(out) == ["R9"]
    assert "pallas_tier" in out[0].message


def test_r9_quiet_in_tier_entry_points():
    src = """\
        from jax.experimental import pallas as pl
        def kernel(x):
            return pl.pallas_call(lambda r, o: None, out_shape=x)(x)
        """
    for allowed in ("spark_rapids_tpu/kernels/pallas_tier.py",
                    "spark_rapids_tpu/kernels/pallas_strings.py"):
        assert lint(R.PallasKernelTierRule(), src, path=allowed) == []


def test_r9_quiet_on_unrelated_calls():
    out = lint(R.PallasKernelTierRule(), """\
        def fine(x):
            return pallas_callback(x)  # not pallas_call
        """, path="spark_rapids_tpu/kernels/layout.py")
    assert out == []


# -- suppressions and baseline mechanics --------------------------------------

def test_line_suppression_silences_one_rule_only():
    out = lint(R.UnboundedWaitRule(), """\
        def run(cond):
            cond.wait()  # rapidslint: disable=R3
            cond.wait()
        """)
    assert len(out) == 1 and out[0].line == 3


def test_file_suppression_silences_whole_file():
    out = lint(R.UnboundedWaitRule(), """\
        # rapidslint: disable-file=R3
        def run(cond):
            cond.wait()
        """)
    assert out == []


def test_suppression_for_other_rule_does_not_apply():
    out = lint(R.UnboundedWaitRule(), """\
        def run(cond):
            cond.wait()  # rapidslint: disable=R4
        """)
    assert rule_ids(out) == ["R3"]


def test_baseline_matches_by_line_text_not_number():
    f = Finding("R3", "a.py", 42, "msg")
    f.line_text = "    cond.wait()   "
    bl = Baseline([{"rule": "R3", "path": "a.py", "line": "cond.wait()",
                    "reason": "ok"}])
    new, used, stale = bl.partition([f])
    assert new == [] and stale == [] and len(used) == 1


def test_baseline_stale_entry_detected():
    bl = Baseline([{"rule": "R3", "path": "gone.py",
                    "line": "cond.wait()", "reason": "ok"}])
    new, used, stale = bl.partition([])
    assert new == [] and used == [] and len(stale) == 1


def test_baseline_reasons_all_filled_in():
    with open(os.path.join(REPO, "tools", "rapidslint_baseline.json")) as f:
        entries = json.load(f)["findings"]
    assert entries, "baseline unexpectedly empty"
    for e in entries:
        assert e.get("reason") and "TODO" not in e["reason"], \
            f"baseline entry without a justification: {e}"


# -- whole-tree gate and CLI --------------------------------------------------

def _run_cli(*args, cwd=REPO):
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, cwd=cwd)


def test_tree_is_clean_against_baseline():
    p = _run_cli("--check")
    assert p.returncode == 0, f"lint gate failed:\n{p.stdout}{p.stderr}"
    assert "clean" in p.stdout


def test_cli_rules_catalog_lists_all_rules():
    p = _run_cli("--rules")
    assert p.returncode == 0
    for rid in ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9"):
        assert rid in p.stdout


def _make_tree(tmp_path, bad_source):
    root = tmp_path / "fake_repo"
    pkg = root / "spark_rapids_tpu"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(textwrap.dedent(bad_source))
    (root / "tools").mkdir()
    (root / "ci").mkdir()
    bl = root / "baseline.json"
    bl.write_text('{"findings": []}')
    return str(root), str(bl)


@pytest.mark.parametrize("bad", [
    "import jax.numpy as jnp\nX = jnp.zeros(4)\n",                      # R1
    "def f(ctx):\n    ctx.semaphore.acquire()\n",                       # R2
    "def f(t):\n    t.join()\n",                                        # R3
    "def f():\n    try:\n        g()\n    except:\n        pass\n",     # R4
    "import jax\ndef f(g):\n    return jax.jit(g)\n",                   # R5
    ("import jax, threading\n"
     "class DeviceRuntime:\n"
     "    _lock = threading.Lock()\n"
     "    def f(self, b):\n"
     "        with self._lock:\n"
     "            return jax.device_get(b)\n"),                         # R6
    'K = conf_int("spark.rapids.test.dead", 1, "never read")\n',        # R7
    ("from jax.experimental import pallas as pl\n"
     "def f(x):\n"
     "    return pl.pallas_call(g, out_shape=x)(x)\n"),                 # R9
], ids=["R1", "R2", "R3", "R4", "R5", "R6", "R7", "R9"])
def test_cli_rejects_injected_regression(tmp_path, bad):
    root, bl = _make_tree(tmp_path, bad)
    p = _run_cli("--check", "--root", root, "--baseline", bl)
    assert p.returncode == 1, f"injected regression not caught:\n{p.stdout}"


def test_cli_rejects_injected_r8_regression(tmp_path):
    # R8 needs the session fixture: a metrics key with no doc at all
    root, bl = _make_tree(
        tmp_path,
        "class S:\n"
        "    def execute(self):\n"
        "        self.last_metrics[\"compileCount\"] = 1\n")
    os.rename(os.path.join(root, "spark_rapids_tpu", "bad.py"),
              os.path.join(root, "spark_rapids_tpu", "session.py"))
    p = _run_cli("--check", "--root", root, "--baseline", bl)
    assert p.returncode == 1
    assert "metrics" in p.stdout


def test_cli_rejects_stale_baseline(tmp_path):
    root, bl = _make_tree(tmp_path, "X = 1\n")
    with open(bl, "w") as f:
        json.dump({"findings": [{"rule": "R3", "path": "gone.py",
                                 "line": "q.get()", "reason": "old"}]}, f)
    p = _run_cli("--check", "--root", root, "--baseline", bl)
    assert p.returncode == 1
    assert "stale" in p.stdout


def test_cli_flags_syntax_error_file(tmp_path):
    root, bl = _make_tree(tmp_path, "def broken(:\n")
    p = _run_cli("--check", "--root", root, "--baseline", bl)
    assert p.returncode == 1
    assert "does not parse" in p.stdout


def test_lint_gate_is_runtime_free():
    # the CI gate's 15s budget depends on never importing jax; run a
    # whole --check in-process and prove the property instead of
    # trusting comments
    code = ("import sys\n"
            "sys.argv = ['rapidslint', '--check']\n"
            "import runpy\n"
            "try:\n"
            f"    runpy.run_path({CLI!r}, run_name='__main__')\n"
            "except SystemExit:\n"
            "    pass\n"
            "assert 'jax' not in sys.modules, 'lint gate imported jax'\n")
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr


# -- plan_verify fixtures -----------------------------------------------------

class _FakeField:
    def __init__(self, name, dtype="int"):
        self.name = name
        self.dtype = dtype

    def __repr__(self):
        return f"{self.name}:{self.dtype}"


class _FakeSchema:
    def __init__(self, *names, dtype="int"):
        self.fields = tuple(_FakeField(n, dtype) for n in names)


class _FakeOp:
    is_tpu = False

    def __init__(self, *children, schema=None):
        self.children = list(children)
        self.output_schema = schema or _FakeSchema("a")
        self.op_id = f"{type(self).__name__}@fake"


def test_plan_verify_accepts_well_formed_tree():
    plan_verify.verify_plan(_FakeOp(_FakeOp()))


def test_plan_verify_rejects_duplicate_columns():
    bad = _FakeOp(schema=_FakeSchema("a", "a"))
    with pytest.raises(plan_verify.PlanInvariantError,
                       match="duplicate output columns"):
        plan_verify.verify_plan(bad)


def test_plan_verify_rejects_missing_dtype():
    bad = _FakeOp(schema=_FakeSchema("a", dtype=None))
    with pytest.raises(plan_verify.PlanInvariantError, match="no dtype"):
        plan_verify.verify_plan(bad)


def test_plan_verify_rejects_unmediated_boundary():
    child = _FakeOp()
    child.is_tpu = True
    parent = _FakeOp(child)  # CPU parent fed by TPU child, no transition
    with pytest.raises(plan_verify.PlanInvariantError,
                       match="without a HostToDevice/DeviceToHost"):
        plan_verify.verify_plan(parent)


def test_plan_verify_rejects_bad_donation_provenance():
    src = _FakeOp()  # neither stage-break nor HostToDeviceExec
    root = _FakeOp(src)
    root._stage_builds = {"default": ([src], None)}
    root._stage_cache = {("default", None, (True,)): object()}
    with pytest.raises(plan_verify.PlanInvariantError,
                       match="donates source"):
        plan_verify.verify_plan(root)


def test_plan_verify_accepts_stage_break_donation():
    src = _FakeOp()
    src.pipeline_stage_break = True
    root = _FakeOp(src)
    root._stage_builds = {"default": ([src], None)}
    root._stage_cache = {("default", None, (True,)): object()}
    plan_verify.verify_plan(root)


def test_plan_verify_semaphore_balance():
    class _Sem:
        def __init__(self, depth):
            self._d = depth

        def held_depth(self):
            return self._d

    class _Runtime:
        def __init__(self, depth):
            self.semaphore = _Sem(depth)

    plan_verify.verify_plan(_FakeOp(), runtime=_Runtime(0))
    with pytest.raises(plan_verify.PlanInvariantError,
                       match="leaked device admission"):
        plan_verify.verify_plan(_FakeOp(), runtime=_Runtime(2))


def test_plan_verify_on_a_real_executed_plan():
    # end-to-end: run a query, then verify the session's actual plan
    from compare import tpu_session
    from spark_rapids_tpu import types as T
    s = tpu_session()
    df = s.create_dataframe({"a": (T.INT, [1, 2, 3, 4, 5, 6]),
                             "b": (T.LONG, [10, 20, 30, 40, 50, 60])},
                            num_partitions=2)
    df.filter(df["a"] > 2).select("a", "b").collect()
    plan_verify.verify_session(s)
