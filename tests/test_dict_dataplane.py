"""Encoded corridor v2 tests: dictionary codes crossing the shuffle and
join layers (dict-aware shuffle matrix, shared/divergent/duplicate-entry
dictionary joins), gather_segments_kway's encoded merge, the adaptive
read-ahead controller, per-format dict decode (CSV/ORC), the page-level
chunk slabs, the per-thread reader handle cache, and the D2H invariant
that collected results never carry unmaterialized codes."""

import os
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    HostBatch, device_to_host, device_to_host_many, host_to_device,
)
from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch

from compare import tpu_session

DICT_AWARE_OFF = {"spark.rapids.sql.tpu.exchange.dictAware.enabled": False}
JOIN_KEYS_OFF = {"spark.rapids.sql.tpu.join.dictKeys.enabled": False}
NO_COLLAPSE = {"spark.rapids.sql.tpu.exchange.collapseLocal": False}

DATA = {
    "i": (T.INT, [1, 2, None, 4, 5, 6, 7, None] * 30),
    "l": (T.LONG, [10, None, 30, 40, 50, 60, 70, 80] * 30),
    # low-cardinality strings with nulls and empties: the dictionary case
    "s": (T.STRING, ["aa", "bb", None, "bb", "", "cc", "aa", "cc"] * 30),
}


def _v2_session(**confs):
    return tpu_session(**{"spark.rapids.sql.tpu.scan.v2.enabled": True,
                          **NO_COLLAPSE, **confs})


def _cpu_session():
    return tpu_session(**{"spark.rapids.sql.enabled": False})


def _write_dict_parquet(tmp_path, name="pq", data=None, rows_per_group=60):
    import pyarrow.parquet as pq
    s = tpu_session()
    out = str(tmp_path / name)
    s.create_dataframe(data or DATA, num_partitions=2).write_parquet(out)
    files = [f for f in os.listdir(out) if f.endswith(".parquet")]
    big = pa.concat_tables(
        [pq.read_table(os.path.join(out, f)) for f in files])
    for f in files:
        os.remove(os.path.join(out, f))
    pq.write_table(big, os.path.join(out, "part-00000.parquet"),
                   row_group_size=rows_per_group)
    return out


def _rows(session, build):
    return sorted(build(session).collect(),
                  key=lambda r: tuple((v is None, str(v)) for v in r))


# -- dict-aware shuffle matrix ------------------------------------------------


def _shuffle_query(kind):
    def q(s, out):
        df = s.read.parquet(out)
        if kind == "hash":
            return df.group_by("s").agg(F.count("i").alias("c"),
                                        F.sum("l").alias("sl"))
        if kind == "range":
            return df.order_by("s", "i")
        return df.repartition(4)
    return q


@pytest.mark.parametrize("kind", ["hash", "range", "roundrobin"])
def test_dict_shuffle_parity_matrix(tmp_path, kind):
    """Encoded pieces (codes + merged dictionary on the wire) are
    bit-identical to the materialized split and the CPU oracle across all
    three partitionings, over a string column with NULLs and empties —
    with the same sync count either way."""
    out = _write_dict_parquet(tmp_path)
    q = _shuffle_query(kind)
    s_on = _v2_session()
    got_on = _rows(s_on, lambda s: q(s, out))
    m_on = dict(s_on.last_metrics)
    s_off = _v2_session(**DICT_AWARE_OFF)
    got_off = _rows(s_off, lambda s: q(s, out))
    m_off = dict(s_off.last_metrics)
    want = _rows(_cpu_session(), lambda s: q(s, out))
    assert got_on == got_off, (got_on[:5], got_off[:5])
    assert got_on == want, (got_on[:5], want[:5])
    # the encoded wire format must not change the split's sync economics
    assert m_on.get("shuffleSyncs") == m_off.get("shuffleSyncs"), \
        (m_on.get("shuffleSyncs"), m_off.get("shuffleSyncs"))


def test_dict_shuffle_warm_repeat_compiles_nothing(tmp_path):
    """The encoded split's programs are shape-stable: a warm repeat of
    the same shuffle recompiles nothing."""
    out = _write_dict_parquet(tmp_path)
    s = _v2_session()
    q = _shuffle_query("hash")
    first = _rows(s, lambda s2: q(s2, out))
    again = _rows(s, lambda s2: q(s2, out))
    assert first == again
    assert s.last_metrics.get("compileCount", 0) == 0, s.last_metrics


def test_dict_shuffle_empty_pieces_parity(tmp_path):
    """More targets than distinct keys: empty target partitions flow
    through the encoded split identically to the materialized one."""
    data = {
        "i": (T.INT, list(range(40))),
        "s": (T.STRING, (["x", "y", None, "x"] * 10)),
    }
    out = _write_dict_parquet(tmp_path, data=data, rows_per_group=10)

    def q(s):
        df = s.read.parquet(out)
        return df.group_by("s").agg(F.count("i").alias("c"))
    confs = {"spark.sql.shuffle.partitions": 8}
    got_on = _rows(_v2_session(**confs), q)
    got_off = _rows(_v2_session(**confs, **DICT_AWARE_OFF), q)
    assert got_on == got_off
    assert len(got_on) == 3


def test_dict_shuffle_saved_metric_nonnegative(tmp_path):
    out = _write_dict_parquet(tmp_path)
    s = _v2_session()
    _rows(s, lambda s2: _shuffle_query("roundrobin")(s2, out))
    m = s.last_metrics
    assert m.get("shuffleEncodedBytesSaved", 0) >= 0, m


# -- gather_segments_kway encoded merge --------------------------------------


def _encoded_batch(strings, extra=None):
    """Device batch whose string column keeps its arrow dictionary."""
    cols = {"s": pa.array(strings, type=pa.string()).dictionary_encode()}
    if extra:
        cols.update(extra)
    hb = arrow_to_host_batch(pa.table(cols), keep_dictionary=True)
    db = host_to_device(hb)
    assert db.columns[0].codes is not None
    return db


def test_gather_segments_kway_encoded_merges_dictionaries():
    """Two inputs with DIFFERENT dictionaries: the encoded k-way gather
    shifts codes by static entry bases and packs both dictionaries; the
    materialized rows equal the plain path's."""
    from spark_rapids_tpu.kernels.layout import gather_segments_kway_run
    a = _encoded_batch(["aa", "bb", "aa", "cc"],
                       {"v": pa.array([1, 2, 3, 4], type=pa.int64())})
    b = _encoded_batch(["dd", "aa"],
                       {"v": pa.array([5, 6], type=pa.int64())})
    enc = gather_segments_kway_run([a, b], [1, 0], [3, 2],
                                   out_capacity=8, out_byte_caps=[64],
                                   keep_encoded=True)
    assert enc.columns[0].codes is not None  # stayed encoded
    plain = gather_segments_kway_run([a, b], [1, 0], [3, 2],
                                     out_capacity=8, out_byte_caps=[64])
    assert plain.columns[0].codes is None
    got = device_to_host_many([enc])[0].to_pydict()
    want = device_to_host_many([plain])[0].to_pydict()
    assert got == want
    assert got["s"] == ["bb", "aa", "cc", "dd", "aa"]
    assert got["v"] == [2, 3, 4, 5, 6]


def test_gather_segments_kway_mixed_parts_materialize():
    """One encoded + one plain input for the same column: no shared
    dictionary space exists, so the output is materialized — with the
    same rows."""
    from spark_rapids_tpu.kernels.layout import gather_segments_kway_run
    enc = _encoded_batch(["aa", "bb", "aa"])
    plain = host_to_device(HostBatch.from_pydict(
        {"s": (T.STRING, ["zz", "bb"])}))
    out = gather_segments_kway_run([enc, plain], [0, 0], [3, 2],
                                   out_capacity=8, out_byte_caps=[64],
                                   keep_encoded=True)
    assert out.columns[0].codes is None
    got = device_to_host_many([out])[0].to_pydict()
    assert got["s"] == ["aa", "bb", "aa", "zz", "bb"]


# -- encoded join keys --------------------------------------------------------


def _canon_eq(l_codes, r_codes, l_strs, r_strs):
    """Aligned codes must agree with content equality row-by-row."""
    l_codes = np.asarray(l_codes)[: len(l_strs)]
    r_codes = np.asarray(r_codes)[: len(r_strs)]
    for i, a in enumerate(l_strs):
        for j, b in enumerate(r_strs):
            assert (l_codes[i] == r_codes[j]) == (a == b), \
                (i, j, a, b, int(l_codes[i]), int(r_codes[j]))


def test_align_dict_codes_shared_dictionary_with_duplicates():
    """A shuffle-merged dictionary can hold DUPLICATE entries; raw code
    equality would miss matches, canonical alignment must not."""
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.kernels.join import align_dict_codes
    idx = pa.array([0, 1, 2, 3], type=pa.int32())
    # entries 0 and 2 are both "aa"; 1 and 3 differ
    arr = pa.DictionaryArray.from_arrays(
        idx, pa.array(["aa", "bb", "aa", "cc"]))
    hb = arrow_to_host_batch(pa.table({"s": arr}), keep_dictionary=True)
    col = host_to_device(hb).columns[0]
    v = DevVal.from_column_encoded(col)
    pair = align_dict_codes(v, v)
    assert pair is not None
    strs = ["aa", "bb", "aa", "cc"]
    _canon_eq(pair[0], pair[1], strs, strs)


def test_align_dict_codes_divergent_dictionaries():
    """Different dictionaries: the smaller side translates into the
    larger; unmatched entries get sentinel codes that equal nothing."""
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.kernels.join import align_dict_codes
    l_strs = ["aa", "bb", "zz", "aa"]
    r_strs = ["bb", "qq", "aa", "bb", "aa"]
    lv = DevVal.from_column_encoded(_encoded_batch(l_strs).columns[0])
    rv = DevVal.from_column_encoded(_encoded_batch(r_strs).columns[0])
    pair = align_dict_codes(lv, rv)
    assert pair is not None
    _canon_eq(pair[0], pair[1], l_strs, r_strs)


def test_align_dict_codes_falls_back_when_oversized(monkeypatch):
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.kernels.join import align_dict_codes
    lv = DevVal.from_column_encoded(_encoded_batch(["aa", "bb"]).columns[0])
    rv = DevVal.from_column_encoded(_encoded_batch(["bb", "cc"]).columns[0])
    assert align_dict_codes(lv, rv, max_cells=1) is None


def _join_data(tmp_path):
    left = {
        "s": (T.STRING, ["aa", "bb", None, "cc", "", "aa", "dd"] * 20),
        "v": (T.LONG, list(range(140))),
    }
    right = {
        "s": (T.STRING, ["bb", "aa", "", None, "ee"] * 8),
        "w": (T.LONG, [i * 3 for i in range(40)]),
    }
    return (_write_dict_parquet(tmp_path, "left", left),
            _write_dict_parquet(tmp_path, "right", right, rows_per_group=10))


def test_encoded_join_parity_divergent_dictionaries(tmp_path):
    """Scanned-in string join keys ride as codes: each side carries its
    own file's dictionary (divergent), and the encoded hash join must be
    bit-identical to dictKeys-off and the CPU oracle."""
    lp, rp = _join_data(tmp_path)

    def q(s):
        left = s.read.parquet(lp)
        right = s.read.parquet(rp)
        return left.join(right, on="s", how="inner")
    confs = {"spark.sql.autoBroadcastJoinThreshold": -1,
             "spark.sql.shuffle.partitions": 4}
    got_on = _rows(_v2_session(**confs), q)
    got_off = _rows(_v2_session(**confs, **JOIN_KEYS_OFF), q)
    want = _rows(_cpu_session(), q)
    assert got_on == got_off
    assert got_on == want


def test_encoded_join_parity_shared_dictionary(tmp_path):
    """Self-join over the SAME scanned file: both sides' dictionaries
    hold the same entries (the shared/duplicate alignment path at the
    session level)."""
    lp, _ = _join_data(tmp_path)

    def q(s):
        a = s.read.parquet(lp)
        b = s.read.parquet(lp).group_by("s").agg(
            F.count("v").alias("c"))
        return a.join(b, on="s", how="inner")
    confs = {"spark.sql.autoBroadcastJoinThreshold": -1,
             "spark.sql.shuffle.partitions": 4}
    got_on = _rows(_v2_session(**confs), q)
    got_off = _rows(_v2_session(**confs, **JOIN_KEYS_OFF), q)
    want = _rows(_cpu_session(), q)
    assert got_on == got_off
    assert got_on == want


def test_encoded_broadcast_join_parity(tmp_path):
    lp, rp = _join_data(tmp_path)

    def q(s):
        left = s.read.parquet(lp)
        right = s.read.parquet(rp)
        return left.join(right, on="s", how="left")
    got_on = _rows(_v2_session(), q)
    got_off = _rows(_v2_session(**JOIN_KEYS_OFF), q)
    want = _rows(_cpu_session(), q)
    assert got_on == got_off
    assert got_on == want


def test_encoded_join_warm_repeat_compiles_nothing(tmp_path):
    lp, rp = _join_data(tmp_path)
    s = _v2_session(**{"spark.sql.autoBroadcastJoinThreshold": -1,
                       "spark.sql.shuffle.partitions": 4})

    def q(s2):
        return s2.read.parquet(lp).join(s2.read.parquet(rp), on="s")
    first = _rows(s, q)
    again = _rows(s, q)
    assert first == again
    assert s.last_metrics.get("compileCount", 0) == 0, s.last_metrics


# -- D2H invariant: codes never leak into collected results ------------------


def test_collected_host_batches_are_materialized():
    """device_to_host without keep_dictionary always materializes; only
    the spill path may keep dictionaries (and must keep codes sane)."""
    db = _encoded_batch(["aa", None, "bb", "aa"],
                        {"v": pa.array([1, 2, 3, 4], type=pa.int64())})
    hb = device_to_host(db)
    assert all(c.dictionary is None for c in hb.columns)
    assert hb.to_pydict()["s"] == ["aa", None, "bb", "aa"]
    kept = device_to_host(db, keep_dictionary=True)
    dc = kept.columns[0]
    assert dc.dictionary is not None
    codes = np.asarray(dc.values, dtype=np.int64)
    assert codes.min() >= 0 and codes.max() < len(dc.dictionary)
    # round-trip: a spilled encoded batch rehydrates to the same rows
    back = device_to_host(host_to_device(kept))
    assert back.to_pydict() == hb.to_pydict()


def test_plan_verify_reports_encoded_d2h_leak():
    from spark_rapids_tpu.analysis.plan_verify import check_encoded_corridor

    class Ctx:
        encoded_d2h_leaks = 2
    problems = check_encoded_corridor(None, Ctx())
    assert problems and "2" in problems[0]
    assert check_encoded_corridor(None, None) == []


# -- adaptive read-ahead ------------------------------------------------------


def test_explicit_depth_disables_adaptive(tmp_path):
    """scan.readAhead.depth set explicitly pins the window: the adaptive
    controller must never move it."""
    out = _write_dict_parquet(tmp_path, rows_per_group=20)
    s = _v2_session(**{"spark.rapids.sql.tpu.scan.readAhead.depth": 3})
    assert len(s.read.parquet(out).collect()) == 240
    assert s.last_metrics.get("readaheadDepthEffective") == 3, \
        s.last_metrics


def test_adaptive_depth_stays_clamped_and_recorded(tmp_path):
    out = _write_dict_parquet(tmp_path, rows_per_group=20)
    s = _v2_session(**{
        "spark.rapids.sql.tpu.scan.readAhead.adaptive.enabled": True,
        "spark.rapids.sql.tpu.scan.readAhead.maxDepth": 6})
    assert len(s.read.parquet(out).collect()) == 240
    d = s.last_metrics.get("readaheadDepthEffective", 0)
    assert 1 <= d <= 6, s.last_metrics
    assert s.runtime.semaphore.held_depth() == 0


def test_adaptive_off_keeps_static_depth(tmp_path):
    out = _write_dict_parquet(tmp_path, rows_per_group=20)
    s = _v2_session(**{
        "spark.rapids.sql.tpu.scan.readAhead.adaptive.enabled": False})
    assert len(s.read.parquet(out).collect()) == 240
    # static default depth reported unchanged
    assert s.last_metrics.get("readaheadDepthEffective") == 4, \
        s.last_metrics


# -- per-format dict decode (CSV / ORC) --------------------------------------


def test_orc_dict_encoding_v1_v2_parity(tmp_path):
    s = tpu_session()
    out = str(tmp_path / "orc")
    s.create_dataframe(DATA, num_partitions=2).write_orc(out)

    def q(s2):
        df = s2.read.orc(out)
        return df.group_by("s").agg(F.count("i").alias("c"),
                                    F.sum("l").alias("sl"))
    want = _rows(tpu_session(
        **{"spark.rapids.sql.tpu.scan.v2.enabled": False}), q)
    s_on = _v2_session()
    got_on = _rows(s_on, q)
    got_off = _rows(_v2_session(
        **{"spark.rapids.sql.tpu.scan.dictEncoding.enabled": False}), q)
    assert got_on == want
    assert got_off == want
    assert s_on.last_metrics.get("scanDictColumns", 0) > 0, \
        s_on.last_metrics


def test_csv_dict_encoding_v1_v2_parity(tmp_path):
    s = tpu_session()
    data = {
        "i": (T.INT, list(range(80))),
        # no nulls/empties: CSV cannot round-trip '' vs NULL
        "s": (T.STRING, ["red", "green", "blue", "red"] * 20),
    }
    out = str(tmp_path / "csv")
    s.create_dataframe(data, num_partitions=2).write_csv(out)

    def q(s2):
        df = s2.read.csv(out)
        return df.group_by("s").agg(F.count("i").alias("c"))
    want = _rows(tpu_session(
        **{"spark.rapids.sql.tpu.scan.v2.enabled": False}), q)
    s_on = _v2_session()
    got_on = _rows(s_on, q)
    got_off = _rows(_v2_session(
        **{"spark.rapids.sql.tpu.scan.dictEncoding.enabled": False}), q)
    assert got_on == want
    assert got_off == want
    assert s_on.last_metrics.get("scanDictColumns", 0) > 0, \
        s_on.last_metrics


def test_parquet_dictionary_typed_schema_enters_corridor(tmp_path):
    """A parquet file written from dictionary-encoded arrow arrays reads
    back with a dictionary<string> arrow schema (pyarrow round-trips the
    arrow schema through file metadata, so read_dictionary is never
    asked).  The scan must still feed the encoded corridor — and decode
    correctly when the corridor is off."""
    import pyarrow.parquet as pq
    cats = ["aa", "bb", None, "", "cc"]
    tb = pa.table({
        "i": pa.array(list(range(200)), pa.int64()),
        "s": pa.array([cats[i % len(cats)] for i in range(200)])
             .dictionary_encode(),
    })
    out = str(tmp_path / "dictschema")
    os.makedirs(out)
    pq.write_table(tb, os.path.join(out, "part-00000.parquet"),
                   row_group_size=50)

    def q(s2):
        df = s2.read.parquet(out)
        return df.group_by("s").agg(F.count("i").alias("c"))
    want = _rows(tpu_session(
        **{"spark.rapids.sql.tpu.scan.v2.enabled": False}), q)
    s_on = _v2_session()
    got_on = _rows(s_on, q)
    got_off = _rows(_v2_session(
        **{"spark.rapids.sql.tpu.scan.dictEncoding.enabled": False}), q)
    assert got_on == want
    assert got_off == want
    assert s_on.last_metrics.get("scanDictColumns", 0) > 0, \
        s_on.last_metrics


# -- page-level chunk slabs ---------------------------------------------------


def test_page_chunk_slabs_parity_one_big_row_group(tmp_path):
    """A single huge row group split into column slabs decodes to the
    same rows as the whole-row-group path (consumer-side zip merge)."""
    import pyarrow.parquet as pq
    rng = np.random.RandomState(9)
    n = 5000
    out = str(tmp_path / "big_rg")
    os.makedirs(out)
    pq.write_table(pa.table({
        "k": pa.array(rng.randint(0, 100, n).astype(np.int64)),
        "v": pa.array(rng.rand(n).round(4)),
        "s": pa.array(np.array([f"t{i % 13}" for i in range(n)],
                               dtype=object)),
    }), os.path.join(out, "part-00000.parquet"), row_group_size=n)

    def q(s):
        df = s.read.parquet(out)
        return df.group_by("s").agg(F.count("k").alias("c"),
                                    F.sum("v").alias("sv"))
    want = _rows(_v2_session(), q)
    s = _v2_session(
        **{"spark.rapids.sql.tpu.scan.pageChunk.minBytes": 1024})
    got = _rows(s, q)
    assert got == want
    assert s.runtime.semaphore.held_depth() == 0


def test_page_chunk_disabled_by_zero(tmp_path):
    out = _write_dict_parquet(tmp_path)

    def q(s):
        return s.read.parquet(out)
    want = _rows(_v2_session(), q)
    got = _rows(_v2_session(
        **{"spark.rapids.sql.tpu.scan.pageChunk.minBytes": 0}), q)
    assert got == want


# -- per-thread reader handle cache ------------------------------------------


class _Handle:
    def __init__(self):
        self.closed = False

    def close(self):
        self.closed = True


def test_cached_reader_hits_and_staleness(tmp_path):
    from spark_rapids_tpu.io.decode_pool import (
        cached_reader, clear_reader_cache, reader_cache_stats,
    )
    clear_reader_cache()
    p = str(tmp_path / "f.bin")
    with open(p, "wb") as f:
        f.write(b"x" * 64)
    made = []

    def factory():
        h = _Handle()
        made.append(h)
        return h

    a = cached_reader("t", p, factory, 4)
    b = cached_reader("t", p, factory, 4)
    assert a is b and len(made) == 1
    hits, misses = reader_cache_stats()
    assert hits >= 1 and misses >= 1
    # rewritten file (different size -> different key): never stale
    with open(p, "wb") as f:
        f.write(b"y" * 128)
    c = cached_reader("t", p, factory, 4)
    assert c is not a and len(made) == 2
    # a different kind on the same path is a distinct handle
    d = cached_reader("t2", p, factory, 4)
    assert d is not c and len(made) == 3
    clear_reader_cache()


def test_cached_reader_lru_closes_evicted(tmp_path):
    from spark_rapids_tpu.io.decode_pool import (
        cached_reader, clear_reader_cache,
    )
    clear_reader_cache()
    paths = []
    for i in range(3):
        p = str(tmp_path / f"f{i}.bin")
        with open(p, "wb") as f:
            f.write(b"z" * (32 + i))
        paths.append(p)
    made = {}

    def factory_for(p):
        def factory():
            h = _Handle()
            made[p] = h
            return h
        return factory

    for p in paths:
        cached_reader("t", p, factory_for(p), 2)
    assert made[paths[0]].closed      # evicted past cache_size=2
    assert not made[paths[1]].closed
    assert not made[paths[2]].closed
    clear_reader_cache()
    assert made[paths[1]].closed and made[paths[2]].closed


def test_cached_reader_disabled_and_missing_file(tmp_path):
    from spark_rapids_tpu.io.decode_pool import cached_reader
    made = []

    def factory():
        h = _Handle()
        made.append(h)
        return h
    p = str(tmp_path / "g.bin")
    with open(p, "wb") as f:
        f.write(b"q" * 16)
    a = cached_reader("t", p, factory, 0)
    b = cached_reader("t", p, factory, 0)
    assert a is not b and len(made) == 2  # size<=0: cache bypassed
    missing = str(tmp_path / "nope.bin")
    c = cached_reader("t", missing, factory, 4)
    assert c is made[-1]  # stat failure: factory, uncached


def test_scan_reader_cache_hits_in_session(tmp_path):
    """Many row groups in one file: pool threads reopen the same path and
    must hit their thread-local handle cache."""
    from spark_rapids_tpu.io.decode_pool import reader_cache_stats
    out = _write_dict_parquet(tmp_path, rows_per_group=15)
    h0, _ = reader_cache_stats()
    s = _v2_session()
    assert len(s.read.parquet(out).collect()) == 240
    h1, _ = reader_cache_stats()
    assert h1 > h0, (h0, h1)


def test_scan_reader_cache_disabled_still_works(tmp_path):
    out = _write_dict_parquet(tmp_path, rows_per_group=15)
    s = _v2_session(
        **{"spark.rapids.sql.tpu.scan.fileHandleCache.size": 0})
    assert len(s.read.parquet(out).collect()) == 240
