"""Sort-key encoding and LSD argsort tests.

The TPU backend routes every multi-word sort through `_argsort_lsd`
(sortkeys.py) because lax.sort compile time grows ~2x per operand on the
TPU toolchain.  These tests cross-check the LSD chain against the direct
multi-operand sort on CPU, and pin down the grouping-mode string encoding
plus the liveness/null-rank word fold.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, device_to_host, host_to_device
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels.sort import argsort_batch, sort_batch
from spark_rapids_tpu.kernels.sortkeys import (
    _argsort_lsd,
    encode_sort_keys,
    keys_equal_prev,
)


@pytest.mark.parametrize("n_words", [1, 2, 3, 5, 8, 21])
def test_lsd_matches_direct_sort(n_words):
    rng = np.random.default_rng(42 + n_words)
    cap = 512
    # Tiny alphabet => lots of ties, so a stability bug would show.
    words = [jnp.asarray(rng.integers(0, 4, size=cap, dtype=np.uint32))
             for _ in range(n_words)]
    iota = jnp.arange(cap, dtype=jnp.int32)
    direct = jax.lax.sort(tuple(words) + (iota,), num_keys=n_words,
                          is_stable=True)[-1]
    lsd = _argsort_lsd(words, iota)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(lsd))


def test_lsd_under_jit_matches():
    rng = np.random.default_rng(7)
    cap = 256
    words = [jnp.asarray(rng.integers(0, 1 << 32, size=cap, dtype=np.uint32))
             for _ in range(6)]
    iota = jnp.arange(cap, dtype=jnp.int32)

    direct = jax.lax.sort(tuple(words) + (iota,), num_keys=6,
                          is_stable=True)[-1]
    lsd = jax.jit(lambda ws: _argsort_lsd(list(ws), iota))(tuple(words))
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(lsd))


def _str_val(values):
    hb = HostBatch.from_pydict({"s": (T.STRING, values)})
    db = host_to_device(hb)
    return DevVal.from_column(db.columns[0]), db


def _int_val(values, dtype=T.INT):
    hb = HostBatch.from_pydict({"x": (dtype, values)})
    db = host_to_device(hb)
    return DevVal.from_column(db.columns[0]), db


def test_grouping_mode_equal_strings_adjacent():
    vals = ["pear", "apple", "pear", "fig", "apple", "pear", None, "fig",
            None, "apple"] * 7
    v, db = _str_val(vals)
    perm = argsort_batch([v], [True], [True], db.num_rows, groupings=[True])
    # Grouping encoding: every run of equal values must be contiguous.
    g = device_to_host(db).to_pydict()["s"]
    n = int(db.num_rows)
    sorted_vals = [g[int(i)] for i in np.asarray(perm)[:n]]
    seen = set()
    prev = object()
    for s in sorted_vals:
        if s != prev:
            assert s not in seen, f"group {s!r} split across the sort"
            seen.add(s)
            prev = s
    assert seen == {None, "apple", "fig", "pear"}


def test_grouping_vs_full_encode_same_groups():
    vals = ["aa", "ab", "aa", None, "b", "ab", "aa", None]
    v, db = _str_val(vals)
    for groupings in (None, [True]):
        perm = argsort_batch([v], [True], [True], db.num_rows,
                             groupings=groupings)
        n = int(db.num_rows)
        g = device_to_host(db).to_pydict()["s"]
        sorted_vals = [g[int(i)] for i in np.asarray(perm)[:n]]
        from collections import Counter
        assert Counter(map(repr, sorted_vals)) == \
            Counter(map(repr, vals))


def test_liveness_fold_padding_rows_last():
    # Pad capacity beyond num_rows; padding must sort after every live row,
    # including nulls-last live rows.
    hb = HostBatch.from_pydict({"x": (T.INT, [3, None, 1, 2])})
    db = host_to_device(hb, capacity=16)
    v = DevVal.from_column(db.columns[0])
    for nf in (True, False):
        words = encode_sort_keys([v], [True], [nf], db.num_rows)
        perm = np.asarray(_argsort_lsd(words,
                                       jnp.arange(16, dtype=jnp.int32)))
        live_positions = [int(np.where(perm == i)[0][0]) for i in range(4)]
        assert max(live_positions) <= 3, \
            f"padding sorted before live rows (nulls_first={nf})"
        order = [int(i) for i in perm[:4]]
        vals = [3, None, 1, 2]
        got = [vals[i] for i in order]
        assert got == ([None, 1, 2, 3] if nf else [1, 2, 3, None])


def test_fold_collapses_liveness_word():
    v, db = _int_val([5, 1, 4])
    words_folded = encode_sort_keys([v], [True], [True], db.num_rows)
    words_sep = encode_sort_keys([v], [True], [True], db.num_rows,
                                 liveness=False)
    assert len(words_folded) == len(words_sep)  # fold saved one word


def test_string_order_by_still_lexicographic():
    vals = ["banana", "apple", "cherry", "apricot", None, "b"]
    v, db = _str_val(vals)
    out = device_to_host(
        sort_batch(db, [v], [True], [True])).to_pydict()["s"]
    assert out == [None, "apple", "apricot", "b", "banana", "cherry"]


def test_multi_key_mixed_grouping():
    # Grouping string key + full-order int key: within each string group,
    # ints must be exactly ordered.
    ks = ["x", "y", "x", "y", "x", "y", "x"]
    xs = [5, 2, 1, 9, 3, 0, 4]
    hb = HostBatch.from_pydict({"k": (T.STRING, ks), "x": (T.INT, xs)})
    db = host_to_device(hb)
    kv = DevVal.from_column(db.columns[0])
    xv = DevVal.from_column(db.columns[1])
    perm = argsort_batch([kv, xv], [True, True], [True, True], db.num_rows,
                         groupings=[True, False])
    n = int(db.num_rows)
    order = [int(i) for i in np.asarray(perm)[:n]]
    got = [(ks[i], xs[i]) for i in order]
    by_group = {}
    for k, x in got:
        by_group.setdefault(k, []).append(x)
    assert by_group["x"] == sorted(by_group["x"])
    assert by_group["y"] == sorted(by_group["y"])
