"""Query-intelligence tests (history/): persistent statistics store,
history-seeded planning, and the cross-query fragment cache — cold/warm
bit-parity, every invalidation edge (input mtime, conf state, eviction,
device-lost generation), clean semaphore/catalog accounting after warm
serves, the off-switch parity contract, and the rapidshist CLI."""

import os
import subprocess
import sys

import pytest

from compare import tpu_session
from spark_rapids_tpu.history import input_identity, runtime_stats, store
from spark_rapids_tpu.history.fragcache import fragment_cache

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_history_state():
    fragment_cache().clear()
    store.reset_stats()
    store.invalidate_cache()
    yield
    fragment_cache().clear()
    fragment_cache().configure(64, 256 << 20)
    store.reset_stats()
    store.invalidate_cache()


def _hist_session(hist_dir, **confs):
    return tpu_session(**{
        "spark.rapids.sql.tpu.history.dir": str(hist_dir), **confs})


def _df(s, n=2048, mod=7, seed=0):
    return s.create_dataframe(
        {"k": [(seed + i) % mod for i in range(n)],
         "v": [(seed + 3 * i) % 997 for i in range(n)]},
        num_partitions=2)


def _rows(batch):
    cols = batch.to_pydict()
    return sorted(zip(*[cols[name] for name in batch.schema.names]))


# -- fragment cache: cold/warm ------------------------------------------------


def test_warm_repeat_serves_fragment_bit_identical(tmp_path):
    """The second run of the same query serves the whole subtree from
    the fragment cache: zero compiles, zero dispatches, hits > 0, and
    bit-identical rows."""
    s = _hist_session(tmp_path / "h")
    q = _df(s).group_by("k").sum("v")
    cold, m1 = s.execute_with_metrics(q.plan)
    assert m1["fragmentCacheHits"] == 0, m1
    assert m1["statsStoreQueries"] == 1, m1
    warm, m2 = s.execute_with_metrics(q.plan)
    assert m2["fragmentCacheHits"] == 1, m2
    assert m2["fragmentCacheBytes"] > 0, m2
    assert m2["compileCount"] == 0, m2
    assert m2["dispatchCount"] == 0, m2
    assert _rows(warm) == _rows(cold)


def test_store_record_written_at_query_end(tmp_path):
    hist = tmp_path / "h"
    s = _hist_session(hist)
    s.execute(_df(s).group_by("k").sum("v").plan)
    records = store.load(str(hist))
    assert len(records) == 1
    (rec,) = records.values()
    assert rec["v"] == store.STORE_VERSION
    assert rec["conf_sig"] == store.conf_signature(s.conf._settings.items())
    assert rec["out_rows"] == 7
    assert rec["wall_ns"] > 0


def test_disabled_is_history_free_behavior(tmp_path):
    """history.enabled=false (even with a dir set) must be byte-for-byte
    today's engine: no store file, no metrics, no cache entries — and
    the same rows as a session with no history conf at all."""
    hist = tmp_path / "h"
    base = tpu_session()
    want = _rows(base.execute(_df(base).group_by("k").sum("v").plan))

    s = _hist_session(hist, **{
        "spark.rapids.sql.tpu.history.enabled": False})
    q = _df(s).group_by("k").sum("v")
    for _ in range(2):
        got = _rows(s.execute(q.plan))
        assert got == want
        m = s.last_metrics
        assert m["fragmentCacheHits"] == 0, m
        assert m["statsStoreQueries"] == 0, m
        assert m["historySeededDecisions"] == 0, m
    assert not os.path.exists(store.store_path(str(hist)))
    assert len(fragment_cache()) == 0


# -- invalidation edges -------------------------------------------------------


@pytest.fixture
def pq_dir(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(
        {"k": [i % 5 for i in range(512)],
         "v": [(3 * i) % 97 for i in range(512)]}, num_partitions=2)
    out = str(tmp_path / "pq")
    df.write_parquet(out)
    return out


def _pq_query(s, pq_dir):
    return s.read.parquet(pq_dir).group_by("k").sum("v")


def test_input_mtime_change_invalidates_fragment(tmp_path, pq_dir):
    """Touching an input file changes its (mtime_ns, size) identity:
    the repeat run must MISS (recompute from the files), not serve the
    stale fragment."""
    s = _hist_session(tmp_path / "h")
    q = _pq_query(s, pq_dir)
    want = _rows(s.execute(q.plan))
    _, m2 = s.execute_with_metrics(q.plan)
    assert m2["fragmentCacheHits"] == 1, m2

    part = next(f for f in sorted(os.listdir(pq_dir))
                if f.endswith(".parquet"))
    path = os.path.join(pq_dir, part)
    st = os.stat(path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 10 ** 9))

    # the re-read plan sees the new identity -> different key -> miss
    q3 = _pq_query(s, pq_dir)
    got, m3 = s.execute_with_metrics(q3.plan)
    assert m3["fragmentCacheHits"] == 0, m3
    assert _rows(got) == want


def test_conf_state_change_invalidates_fragment(tmp_path, pq_dir):
    """A plan-relevant conf difference signs a different fragment key:
    a session under another configuration never serves the first
    session's fragment."""
    hist = tmp_path / "h"
    s1 = _hist_session(hist)
    q1 = _pq_query(s1, pq_dir)
    want = _rows(s1.execute(q1.plan))
    _, m = s1.execute_with_metrics(q1.plan)
    assert m["fragmentCacheHits"] == 1, m

    s2 = _hist_session(hist, **{"spark.sql.autoBroadcastJoinThreshold": -1})
    q2 = _pq_query(s2, pq_dir)
    got, m2 = s2.execute_with_metrics(q2.plan)
    assert m2["fragmentCacheHits"] == 0, m2
    assert _rows(got) == want


def test_conf_signature_excludes_inert_namespaces():
    base = [("spark.rapids.sql.enabled", True),
            ("spark.sql.shuffle.partitions", 4)]
    sig = store.conf_signature(base)
    # metrics./obs./history. knobs never change plans -> same signature
    assert store.conf_signature(base + [
        ("spark.rapids.sql.tpu.history.dir", "/x"),
        ("spark.rapids.sql.tpu.obs.eventLogDir", "/y"),
        ("spark.rapids.sql.tpu.metrics.detailEnabled", True)]) == sig
    # anything else does
    assert store.conf_signature(base + [
        ("spark.sql.autoBroadcastJoinThreshold", -1)]) != sig


def test_eviction_under_tiny_budget_recomputes(tmp_path):
    """With a fragment budget too small to hold anything, the insert is
    immediately evicted: the repeat run recomputes from lineage with
    correct rows (never a crash, never stale data)."""
    s = _hist_session(tmp_path / "h", **{
        "spark.rapids.sql.tpu.history.fragments.maxBytes": 1})
    q = _df(s).group_by("k").sum("v")
    want = _rows(s.execute(q.plan))
    got, m2 = s.execute_with_metrics(q.plan)
    assert m2["fragmentCacheHits"] == 0, m2
    assert _rows(got) == want
    st = fragment_cache().stats()
    assert st["fragment_cache_evictions"] > 0, st
    assert st["fragment_cache_entries"] == 0, st


def test_device_lost_generation_invalidates(tmp_path):
    """A device-lost recovery bumps the runtime generation; fragments
    built under the old device must not serve — the repeat recomputes on
    the recovered runtime."""
    from spark_rapids_tpu.runtime.device import DeviceRuntime

    DeviceRuntime.reset()
    try:
        s = _hist_session(tmp_path / "h")
        q = _df(s).group_by("k").sum("v")
        want = _rows(s.execute(q.plan))
        assert len(fragment_cache()) == 1

        DeviceRuntime.recover(s.conf)
        got, m2 = s.execute_with_metrics(q.plan)
        assert m2["fragmentCacheHits"] == 0, m2
        assert _rows(got) == want
        # and the stale entry was dropped, replaced by a fresh insert
        assert len(fragment_cache()) == 1
    finally:
        DeviceRuntime.reset()
        fragment_cache().clear()


def test_clean_accounting_after_warm_serves(tmp_path):
    """Warm serves take no device admission and leak nothing: after a
    cold+warm+warm sequence the semaphore is free and the catalog
    accounting verifies clean (with the cached fragments still live)."""
    s = _hist_session(tmp_path / "h")
    q = _df(s).group_by("k").sum("v")
    s.execute(q.plan)
    s.execute(q.plan)
    s.execute(q.plan)
    assert s.last_metrics["fragmentCacheHits"] == 1
    assert s.runtime.semaphore.held_depth() == 0
    assert s.runtime.catalog.verify_accounting() == []


# -- history-seeded planning --------------------------------------------------


def test_seeding_applies_recorded_layout_with_parity(tmp_path):
    """With a warm store, a fresh physical plan of the same fingerprint
    applies the recorded exchange layout at PLAN time (decisions > 0)
    and still returns bit-identical rows."""
    from spark_rapids_tpu.serve import shared_plan_cache

    confs = {
        # collapsed local exchanges never split -> nothing to record/seed
        "spark.rapids.sql.tpu.exchange.collapseLocal": False,
        "spark.sql.shuffle.partitions": 16,
        # isolate seeding from the fragment path
        "spark.rapids.sql.tpu.history.fragments.enabled": False,
    }
    s = _hist_session(tmp_path / "h", **confs)
    q = _df(s, n=4096, mod=13).group_by("k").sum("v")
    want = _rows(s.execute(q.plan))
    assert s.last_metrics["historySeededDecisions"] == 0

    # a fresh phys of the same fingerprint seeds from the store
    shared_plan_cache().clear()
    got, m2 = s.execute_with_metrics(q.plan)
    assert m2["historySeededDecisions"] >= 1, m2
    assert m2["statsStoreQueries"] == 1, m2
    assert _rows(got) == want


def test_seed_disabled_consults_nothing(tmp_path):
    from spark_rapids_tpu.serve import shared_plan_cache

    s = _hist_session(tmp_path / "h", **{
        "spark.rapids.sql.tpu.history.seed.enabled": False,
        "spark.rapids.sql.tpu.exchange.collapseLocal": False,
        "spark.rapids.sql.tpu.history.fragments.enabled": False,
    })
    q = _df(s).group_by("k").sum("v")
    want = _rows(s.execute(q.plan))
    shared_plan_cache().clear()
    got, m2 = s.execute_with_metrics(q.plan)
    assert m2["statsStoreQueries"] == 0, m2
    assert m2["historySeededDecisions"] == 0, m2
    assert _rows(got) == want


# -- store unit behavior ------------------------------------------------------


def test_store_lookup_staleness_and_conf_mismatch(tmp_path):
    d = str(tmp_path / "h")
    store.append(d, {"fp": "aaaa", "conf_sig": "s1", "ts": 1000.0})
    # conf signature must match
    assert store.lookup(d, "aaaa", "s1") is not None
    assert store.lookup(d, "aaaa", "s2") is None
    # age horizon measured from `now`
    assert store.lookup(d, "aaaa", "s1", max_age_sec=50,
                        now=1030.0) is not None
    assert store.lookup(d, "aaaa", "s1", max_age_sec=50, now=1100.0) is None
    # absent fingerprint / absent dir are plain misses
    assert store.lookup(d, "bbbb", "s1") is None
    assert store.lookup(str(tmp_path / "nope"), "aaaa", "s1") is None


def test_store_newest_record_wins_and_tolerates_torn_tail(tmp_path):
    d = str(tmp_path / "h")
    store.append(d, {"fp": "aaaa", "conf_sig": "s1", "wall_ns": 1})
    store.append(d, {"fp": "aaaa", "conf_sig": "s1", "wall_ns": 2})
    with open(store.store_path(d), "a", encoding="utf-8") as f:
        f.write('{"fp": "cccc", "tor')  # torn tail write
    store.invalidate_cache(d)
    records = store.load(d)
    assert set(records) == {"aaaa"}
    assert records["aaaa"]["wall_ns"] == 2


def test_store_prune_bounds_and_keeps_newest(tmp_path):
    d = str(tmp_path / "h")
    for i in range(6):
        store.append(d, {"fp": f"fp{i % 3}", "conf_sig": "s", "n": i})
    before, after = store.prune(d, 2)
    assert before == 6 and after <= 2
    records = store.load(d)
    assert records["fp2"]["n"] == 5  # newest per fingerprint survived


def test_input_identity_kinds(tmp_path, pq_dir):
    s = tpu_session()
    mem = _df(s).plan
    sig = input_identity(mem)
    assert sig is not None and sig.startswith("mem:")
    file_plan = s.read.parquet(pq_dir).plan
    fsig = input_identity(file_plan)
    assert fsig is not None and "file:" in fsig and str(pq_dir) in fsig
    # a vanished input means "do not cache", not a crash
    part = next(f for f in os.listdir(pq_dir) if f.endswith(".parquet"))
    os.rename(os.path.join(pq_dir, part),
              os.path.join(pq_dir, part + ".gone"))
    try:
        assert input_identity(file_plan) is None
    finally:
        os.rename(os.path.join(pq_dir, part + ".gone"),
                  os.path.join(pq_dir, part))


# -- rollups and tooling ------------------------------------------------------


def test_serve_stats_roll_up_history_counters(tmp_path):
    from spark_rapids_tpu.serve import ServeScheduler

    s = _hist_session(tmp_path / "h")
    with ServeScheduler(s, max_concurrency=2) as sched:
        df = _df(s).group_by("k").sum("v")
        sched.submit(df).result(timeout=120)
        sched.submit(df).result(timeout=120)
        st = sched.stats()
    for key in ("history_store_queries", "history_store_appends",
                "fragment_cache_entries", "fragment_cache_hits",
                "fragment_cache_misses"):
        assert key in st, sorted(st)
    assert st["history_store_appends"] >= 2, st
    assert st["fragment_cache_hits"] >= 1, st
    assert runtime_stats()["history_store_appends"] >= 2


def test_rapidshist_cli_inspects_and_prunes(tmp_path):
    hist = str(tmp_path / "h")
    s = _hist_session(hist)
    q = _df(s).group_by("k").sum("v")
    s.execute(q.plan)
    s.execute(q.plan)

    tool = os.path.join(REPO_ROOT, "tools", "rapidshist.py")
    out = subprocess.run([sys.executable, tool, hist],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "fingerprint" in out.stdout
    assert "exchange" in out.stdout or "wall" in out.stdout

    out = subprocess.run([sys.executable, tool, hist, "--prune", "1"],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    store.invalidate_cache(hist)
    assert len(store.load(hist)) == 1

    # empty store exits 2, not 0 (scriptable "nothing here" signal)
    out = subprocess.run([sys.executable, tool, str(tmp_path / "none")],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, (out.stdout, out.stderr)
