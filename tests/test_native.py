"""Native runtime tests: C++ batch serializer + arena (and their python
fallbacks agree on the wire format)."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.native_rt import (
    HostArena, _py_deserialize, _py_serialize, deserialize_host_batch,
    get_lib, serialize_host_batch,
)

from conftest import assert_batches_equal

DATA = {
    "i": (T.INT, [1, 2, None, 4]),
    "l": (T.LONG, [10, None, 30, 40]),
    "d": (T.DOUBLE, [0.5, 1.5, None, float("nan")]),
    "s": (T.STRING, ["alpha", "", None, "delta✓"]),
    "b": (T.BOOLEAN, [True, None, False, True]),
}


def test_native_lib_builds():
    assert get_lib() is not None, "native toolchain present; must build"


def test_serialize_roundtrip_native():
    hb = HostBatch.from_pydict(DATA)
    buf = serialize_host_batch(hb)
    out = deserialize_host_batch(buf, hb.schema)
    assert_batches_equal(hb.to_pydict(), out.to_pydict(), approx=True)


def test_python_fallback_reads_native_frames():
    hb = HostBatch.from_pydict(DATA)
    buf = serialize_host_batch(hb)
    out = _py_deserialize(np.frombuffer(buf, dtype=np.uint8), hb.schema)
    assert_batches_equal(hb.to_pydict(), out.to_pydict(), approx=True)


def test_arena_recycles():
    a = HostArena(1 << 20)
    if get_lib() is None:
        pytest.skip("no native lib")
    b1 = a.alloc(1000)
    b1.array[:] = 7
    a.free(b1)
    stats1 = a.stats()
    assert stats1["pooled"] >= 1024
    b2 = a.alloc(900)  # same size class -> recycled
    stats2 = a.stats()
    assert stats2["pooled"] < stats1["pooled"] or \
        stats2["allocated"] >= 1024
    a.free(b2)
    a.close()


def test_empty_batch_roundtrip():
    hb = HostBatch.from_pydict({"x": (T.INT, []), "s": (T.STRING, [])})
    buf = serialize_host_batch(hb)
    out = deserialize_host_batch(buf, hb.schema)
    assert out.num_rows == 0


class TestNativeLZ:
    def _roundtrip(self, data: bytes):
        from spark_rapids_tpu.mem.codec import get_codec
        c = get_codec("nativelz")
        enc = c.compress(data)
        assert c.decompress(enc, len(data)) == data
        return enc

    def test_lz_roundtrip_shapes(self):
        import os
        import numpy as np
        r = np.random.RandomState(5)
        cases = [
            b"",
            b"a",
            b"abcd" * 3,
            b"x" * 100_000,                        # highly compressible
            bytes(r.randint(0, 256, 64_000, dtype=np.uint8)),  # random
            (b"the quick brown fox " * 5000),
            bytes(r.randint(0, 4, 300_000, dtype=np.uint8)),
            os.urandom(13),
        ]
        for data in cases:
            self._roundtrip(data)

    def test_lz_compresses_repetitive_data(self):
        from spark_rapids_tpu.native_rt import get_lib
        if get_lib() is None:
            import pytest
            pytest.skip("native library unavailable")
        data = b"ABABABAB" * 50_000
        enc = self._roundtrip(data)
        assert len(enc) < len(data) // 10

    def test_lz_rejects_corrupt_stream(self):
        from spark_rapids_tpu.native_rt import get_lib
        if get_lib() is None:
            import pytest
            pytest.skip("native library unavailable")
        from spark_rapids_tpu.mem.codec import get_codec
        import pytest
        c = get_codec("nativelz")
        enc = c.compress(b"hello world, hello world, hello world")
        if enc[0] == 1:  # only the compressed form validates structure
            with pytest.raises((ValueError, RuntimeError)):
                c.decompress(b"\x01" + b"\xff\xff\x00\x10" * 4, 37)

    def test_spill_with_nativelz_codec(self):
        from spark_rapids_tpu.batch import HostBatch, device_to_host, \
            host_to_device
        from spark_rapids_tpu.config import RapidsConf
        from spark_rapids_tpu.mem.catalog import BufferCatalog
        from spark_rapids_tpu import types as T
        from conftest import assert_batches_equal
        conf = RapidsConf({
            "spark.rapids.memory.tpu.spillBudgetBytes": 1,
            "spark.rapids.memory.host.spillStorageSize": 1,
            "spark.rapids.shuffle.compression.codec": "nativelz",
        })
        cat = BufferCatalog(conf)
        data = {"x": (T.INT, list(range(500))),
                "s": (T.STRING, [f"row-{i}" for i in range(500)])}
        h1 = cat.register(host_to_device(HostBatch.from_pydict(data)),
                          priority=1)
        cat.register(host_to_device(HostBatch.from_pydict(data)),
                     priority=2)
        cat.drain_spills()
        assert cat.metrics["spilled_to_disk"] >= 1
        got = device_to_host(h1.get()).to_pydict()
        assert_batches_equal(HostBatch.from_pydict(data).to_pydict(), got)
