"""Native runtime tests: C++ batch serializer + arena (and their python
fallbacks agree on the wire format)."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.native_rt import (
    HostArena, _py_deserialize, _py_serialize, deserialize_host_batch,
    get_lib, serialize_host_batch,
)

from conftest import assert_batches_equal

DATA = {
    "i": (T.INT, [1, 2, None, 4]),
    "l": (T.LONG, [10, None, 30, 40]),
    "d": (T.DOUBLE, [0.5, 1.5, None, float("nan")]),
    "s": (T.STRING, ["alpha", "", None, "delta✓"]),
    "b": (T.BOOLEAN, [True, None, False, True]),
}


def test_native_lib_builds():
    assert get_lib() is not None, "native toolchain present; must build"


def test_serialize_roundtrip_native():
    hb = HostBatch.from_pydict(DATA)
    buf = serialize_host_batch(hb)
    out = deserialize_host_batch(buf, hb.schema)
    assert_batches_equal(hb.to_pydict(), out.to_pydict(), approx=True)


def test_python_fallback_reads_native_frames():
    hb = HostBatch.from_pydict(DATA)
    buf = serialize_host_batch(hb)
    out = _py_deserialize(np.frombuffer(buf, dtype=np.uint8), hb.schema)
    assert_batches_equal(hb.to_pydict(), out.to_pydict(), approx=True)


def test_arena_recycles():
    a = HostArena(1 << 20)
    if get_lib() is None:
        pytest.skip("no native lib")
    b1 = a.alloc(1000)
    b1.array[:] = 7
    a.free(b1)
    stats1 = a.stats()
    assert stats1["pooled"] >= 1024
    b2 = a.alloc(900)  # same size class -> recycled
    stats2 = a.stats()
    assert stats2["pooled"] < stats1["pooled"] or \
        stats2["allocated"] >= 1024
    a.free(b2)
    a.close()


def test_empty_batch_roundtrip():
    hb = HostBatch.from_pydict({"x": (T.INT, []), "s": (T.STRING, [])})
    buf = serialize_host_batch(hb)
    out = deserialize_host_batch(buf, hb.schema)
    assert out.num_rows == 0
