"""GroupedData.pivot: per-value conditional-aggregate rewrite."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {"year": (T.INT, [2023, 2023, 2023, 2024, 2024, 2024]),
        "course": (T.STRING, ["java", "scala", "java", "scala", None,
                              "java"]),
        "earnings": (T.DOUBLE, [100.0, 200.0, 50.0, 300.0, 25.0, None])}


def test_pivot_explicit_values():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return (df.group_by("year")
                .pivot("course", ["java", "scala"])
                .agg(F.sum("earnings").alias("sum"))
                .order_by("year"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.group_by("year").pivot("course", ["java", "scala"])
            .agg(F.sum("earnings").alias("s")).order_by("year").collect())
    assert rows == [(2023, 150.0, 200.0), (2024, None, 300.0)]


def test_pivot_discovers_values_and_null_column():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = (df.group_by("year").pivot("course")
           .agg(F.sum("earnings").alias("s")).order_by("year"))
    # discovered values sort ascending with NULL first (Spark order)
    assert out.columns == ["year", "null", "java", "scala"]
    rows = out.collect()
    assert rows == [(2023, None, 150.0, 200.0),
                    (2024, 25.0, None, 300.0)]


def test_pivot_multiple_aggs_and_count():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=3)
        return (df.group_by("year")
                .pivot("course", ["java", "scala"])
                .agg(F.sum("earnings").alias("sum"),
                     F.count("earnings").alias("cnt"))
                .order_by("year"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)
    out = (df.group_by("year").pivot("course", ["java"])
           .agg(F.sum("earnings").alias("sum"),
                F.count("earnings").alias("cnt")))
    assert out.columns == ["year", "java_sum", "java_cnt"]


def test_pivot_count_star():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return (df.group_by("year")
                .pivot("course", ["java", "scala"])
                .agg(F.count("*").alias("n"))
                .order_by("year"))

    assert_tpu_cpu_equal(build, ignore_order=False)
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.group_by("year").pivot("course", ["java", "scala"])
            .agg(F.count("*").alias("n")).order_by("year").collect())
    # count(*) counts MATCHING rows incl. the null-earnings java row
    assert rows == [(2023, 2, 1), (2024, 1, 1)]


def test_pivot_absent_combo_count_is_null_and_first_picks():
    """pyspark parity: count() of an absent (group, value) combination is
    NULL (not 0), and first() under pivot ignores the gating nulls."""
    s = tpu_session()
    df = s.create_dataframe(
        {"k": (T.INT, [1, 1, 1, 2]),
         "p": (T.STRING, ["a", "b", "a", "a"]),
         "x": (T.INT, [10, 20, 5, 7])}, num_partitions=2)
    rows = (df.group_by("k").pivot("p", ["a", "b"])
            .agg(F.count("x").alias("n")).order_by("k").collect())
    assert rows == [(1, 2, 1), (2, 1, None)]
    rows = (df.group_by("k").pivot("p", ["a", "b"])
            .agg(F.first("x").alias("f")).order_by("k").collect())
    assert rows[0][2] == 20  # k=1's 'b' cell, not clobbered by gating

    def build(s2):
        d = s2.create_dataframe(
            {"k": (T.INT, [1, 1, 1, 2]),
             "p": (T.STRING, ["a", "b", "a", "a"]),
             "x": (T.INT, [10, 20, 5, 7])}, num_partitions=2)
        return (d.group_by("k").pivot("p", ["a", "b"])
                .agg(F.count("x").alias("n")).order_by("k"))

    assert_tpu_cpu_equal(build, ignore_order=False)


def test_pivot_unaliased_multi_agg_names_disambiguate():
    s = tpu_session()
    df = s.create_dataframe(
        {"k": (T.INT, [1]), "p": (T.STRING, ["a"]),
         "x": (T.INT, [1]), "y": (T.INT, [2])}, num_partitions=1)
    out = (df.group_by("k").pivot("p", ["a"])
           .agg(F.sum("x"), F.sum("y")))
    assert out.columns == ["k", "a_sum(x)", "a_sum(y)"]
