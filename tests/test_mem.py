"""Memory subsystem tests (RapidsBufferCatalogSuite / DeviceMemoryStore /
DiskStore suites' pattern): spill tiers, budgets, cache, codecs."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, device_to_host, host_to_device
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.mem.catalog import BufferCatalog, SpillableBatch
from spark_rapids_tpu.mem.codec import get_codec

from compare import tpu_session
from conftest import assert_batches_equal

DATA = {
    "x": (T.INT, [1, 2, 3, None, 5]),
    "s": (T.STRING, ["aa", None, "cc", "dd", ""]),
}


def make_catalog(device_budget, host_budget=1 << 20):
    conf = RapidsConf({
        "spark.rapids.memory.tpu.spillBudgetBytes": device_budget,
        "spark.rapids.memory.host.spillStorageSize": host_budget,
    })
    return BufferCatalog(conf)


def batch():
    return host_to_device(HostBatch.from_pydict(DATA))


def test_register_and_get():
    cat = make_catalog(1 << 30)
    h = cat.register(batch())
    assert h.tier == SpillableBatch.TIER_DEVICE
    got = device_to_host(h.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)
    h.close()
    assert cat.device_bytes_in_use() == 0


def test_spill_to_host_on_budget():
    cat = make_catalog(device_budget=50)  # tiny: forces spill
    h1 = cat.register(batch(), priority=1)
    h2 = cat.register(batch(), priority=2)
    cat.drain_spills()  # register returns with the spill still in flight
    # lowest priority spilled first
    assert h1.tier == SpillableBatch.TIER_HOST
    assert cat.metrics["spilled_to_host"] >= 1
    # unspill transparently
    got = device_to_host(h1.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)


def test_spill_to_disk_when_host_full():
    cat = make_catalog(device_budget=1, host_budget=1)
    h1 = cat.register(batch(), priority=1)
    cat.register(batch(), priority=2)
    cat.drain_spills()
    assert cat.metrics["spilled_to_disk"] >= 1
    got = device_to_host(h1.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)


def test_codecs_roundtrip():
    payload = b"hello world " * 100
    for name in ("copy", "zlib"):
        c = get_codec(name)
        enc = c.compress(payload)
        assert c.decompress(enc, len(payload)) == payload
    with pytest.raises(ValueError):
        get_codec("nope")


def test_dataframe_cache():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2) \
        .filter(s.create_dataframe(DATA)["x"].is_not_null())
    cached = df.cache()
    r1 = sorted(map(str, cached.collect()))
    # second run must hit the materialized cache (same results)
    r2 = sorted(map(str, cached.collect()))
    assert r1 == r2
    assert cached.plan.holder.is_materialized
    cached.unpersist()
    assert not cached.plan.holder.is_materialized


def test_join_spills_under_tiny_budget():
    """A shuffled join whose shuffle outputs exceed the device budget must
    spill shuffle pieces to host mid-query and still produce correct
    results (RapidsShuffleInternalManager.scala:91-154 +
    SpillableColumnarBatch.scala:27 role)."""
    import numpy as np
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    from spark_rapids_tpu.session import TpuSparkSession
    from spark_rapids_tpu.config import RapidsConf

    DeviceRuntime.reset()
    try:
        conf = RapidsConf({
            "spark.rapids.sql.enabled": True,
            "spark.sql.shuffle.partitions": 4,
            "spark.rapids.sql.tpu.exchange.collapseLocal": False,
            "spark.sql.autoBroadcastJoinThreshold": -1,
            # ~64KB device budget: far below the shuffle working set
            "spark.rapids.memory.tpu.spillBudgetBytes": 64 * 1024,
        })
        s = TpuSparkSession(conf)
        n = 20_000
        rng = np.random.RandomState(5)
        left = s.create_dataframe(
            {"k": rng.randint(0, 500, n).tolist(),
             "v": rng.randint(0, 100, n).tolist()}, num_partitions=3)
        right = s.create_dataframe(
            {"k": list(range(500)), "w": list(range(500))},
            num_partitions=2)
        out = left.join(right, on="k", how="inner")
        rows = out.collect()
        assert len(rows) == n  # every left row matches exactly one right row
        mem = s.last_metrics.get("memory", {})
        assert mem.get("spilled_to_host", 0) > 0, mem
        assert mem.get("unspilled", 0) > 0, mem
    finally:
        DeviceRuntime.reset()


def test_exchange_split_memoized_for_retry():
    """A task retry re-reads the already-materialized shuffle pieces
    instead of re-running the split (the role persisted shuffle files play
    for Spark's retry); handles close when the query ends."""
    import numpy as np
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.plan.physical import ExecContext
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    from spark_rapids_tpu.session import TpuSparkSession

    DeviceRuntime.reset()
    try:
        conf = RapidsConf({
            "spark.rapids.sql.enabled": True,
            "spark.sql.shuffle.partitions": 4,
            "spark.rapids.sql.tpu.exchange.collapseLocal": False,
        })
        s = TpuSparkSession(conf)
        df = s.create_dataframe(
            {"k": list(range(100)), "v": list(range(100))},
            num_partitions=3)
        phys = s.plan_physical(df.group_by("k").sum("v").plan)
        # find the exchange in the plan
        def find_ex(op):
            from spark_rapids_tpu.parallel.exchange import (
                TpuShuffleExchangeExec,
            )
            if isinstance(op, TpuShuffleExchangeExec):
                return op
            for c in op.children:
                r = find_ex(c)
                if r is not None:
                    return r
            return None

        ex = find_ex(phys)
        assert ex is not None
        ctx = ExecContext(conf, device=s.runtime.device)
        parts1 = ex.partitions(ctx)
        first = [list(p) for p in parts1]
        cache = ex._split_cache
        parts2 = ex.partitions(ctx)  # the retry path
        assert ex._split_cache is cache  # no recompute
        second = [list(p) for p in parts2]
        assert [len(p) for p in first] == [len(p) for p in second]
        n_open = len(ctx._deferred_handles)
        assert n_open > 0
        ctx.close_deferred()
        assert all(h.closed for h in ctx._deferred_handles) or \
            not ctx._deferred_handles
    finally:
        DeviceRuntime.reset()


class _FakeOom:
    """Raises a RESOURCE_EXHAUSTED error shaped like jax's for the first
    ``failures`` calls, then succeeds — a stand-in for XLA's allocator."""

    def __init__(self, failures=1):
        self.failures = failures
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.failures > 0:
            self.failures -= 1
            err = type("XlaRuntimeError", (Exception,), {})
            raise err("RESOURCE_EXHAUSTED: Out of memory allocating "
                      "1073741824 bytes.")
        return "ok"


def test_oom_retry_spills_and_reruns():
    from spark_rapids_tpu.mem.catalog import run_with_oom_retry
    cat = make_catalog(1 << 30)
    h = cat.register(batch())
    assert h.tier == SpillableBatch.TIER_DEVICE
    thunk = _FakeOom(failures=1)
    assert run_with_oom_retry(cat, thunk) == "ok"
    assert thunk.calls == 2
    # the alloc-failure handler spilled the registered batch to host
    assert h.tier == SpillableBatch.TIER_HOST
    assert cat.metrics.get("oom_spill_bytes", 0) > 0
    # and the handle still rehydrates correctly afterwards
    got = device_to_host(h.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)


def test_oom_retry_gives_up_when_nothing_spillable():
    from spark_rapids_tpu.mem.catalog import run_with_oom_retry
    cat = make_catalog(1 << 30)  # nothing registered
    thunk = _FakeOom(failures=1)
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        run_with_oom_retry(cat, thunk)
    assert thunk.calls == 1  # no pointless retry


def test_oom_retry_passes_other_errors_through():
    from spark_rapids_tpu.mem.catalog import run_with_oom_retry
    cat = make_catalog(1 << 30)
    cat.register(batch())

    def boom():
        raise ValueError("RESOURCE_EXHAUSTED mentioned but wrong type")

    with pytest.raises(ValueError):
        run_with_oom_retry(cat, boom)
