"""Two-process multi-host dryrun: spawns 2 CPU-backend processes that join
a jax.distributed process group via init_multihost and run the
mesh-shuffled aggregation across them (the reference's multi-executor
shuffle as the normal case, UCXShuffleTransport.scala:47-235)."""

import json
import os
import socket
import subprocess
import sys

import pytest

# Backends that cannot run multiprocess computations surface it as an
# UNIMPLEMENTED runtime error — an environment limitation of the virtual
# CPU mesh, not an engine bug, so the dryrun SKIPS instead of failing.
_UNSUPPORTED_MARKERS = (
    # deliberately narrow: the bare status code "UNIMPLEMENTED" would also
    # match genuine engine bugs (an op unsupported only in the
    # multi-process path) and silently skip the sole multihost test
    "Multiprocess computations aren't implemented",
    "multi-process computations aren't implemented",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_multihost_agg():
    port = _free_port()
    env = dict(os.environ)
    # the demo pins its own platform/flags; scrub the test harness's
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-m",
             "spark_rapids_tpu.parallel.multihost_demo",
             "--rank", str(rank), "--world", "2",
             "--coordinator", f"127.0.0.1:{port}", "--devices", "4"],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        if p.returncode != 0 and any(m in out for m in
                                     _UNSUPPORTED_MARKERS):
            pytest.skip("backend cannot run multiprocess computations "
                        "(CPU backend): " +
                        next(line for line in out.splitlines()
                             if any(m in line for m in
                                    _UNSUPPORTED_MARKERS))[:200])
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out[-3000:]}"
    results = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("{"):
                results.append(json.loads(line))
    assert len(results) == 2, outs
    for r in results:
        assert r["ok"] and r["process_count"] == 2
        assert r["local_devices"] == 4 and r["global_devices"] == 8
    assert {r["rank"] for r in results} == {0, 1}
