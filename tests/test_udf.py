"""UDF tests: bytecode compiler (udf-compiler analogue) + pandas/row UDF
fallback path (udf_cudf_test / GpuArrowEvalPythonExec analogues)."""

import math

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.exprs.base import ColumnRef, Literal
from spark_rapids_tpu.udf.compiler import CannotCompile, compile_udf

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {
    "x": (T.INT, [1, -2, 3, None, 5, -6]),
    "y": (T.DOUBLE, [0.5, 1.5, None, 3.5, 4.5, 5.5]),
    "s": (T.STRING, ["Ham", "spam", None, "Eggs", "", "Toast"]),
}


# -- compiler unit tests -----------------------------------------------------


def test_compile_arith():
    e = compile_udf(lambda a, b: a * 2 + b - 1,
                    [ColumnRef("x", T.INT), ColumnRef("y", T.DOUBLE)])
    assert "Add" in type(e).__name__ or e is not None


def test_compile_conditional():
    e = compile_udf(lambda a: a + 1 if a > 0 else a - 1,
                    [ColumnRef("x", T.INT)])
    assert type(e).__name__ == "If"


def test_compile_abs_and_math():
    compile_udf(lambda a: abs(a) + math.sqrt(a), [ColumnRef("y", T.DOUBLE)])


def test_compile_string_methods():
    e = compile_udf(lambda s: s.upper(), [ColumnRef("s", T.STRING)])
    assert type(e).__name__ == "Upper"


def test_compile_rejects_loops():
    def f(a):
        t = 0
        for i in range(3):
            t += a
        return t
    with pytest.raises(CannotCompile):
        compile_udf(f, [ColumnRef("x", T.INT)])


def test_compile_closure_constant():
    k = 10

    def f(a):
        return a + k
    e = compile_udf(f, [ColumnRef("x", T.INT)])
    assert e is not None


# -- end-to-end --------------------------------------------------------------


def test_row_udf_fallback_path():
    def q(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        my = F.udf(lambda a: None if a is None else a * 3 + 1,
                   return_type=T.LONG)
        return df.with_column("t", my(df["x"]))
    assert_tpu_cpu_equal(q)


def test_pandas_udf():
    def q(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        my = F.pandas_udf(lambda a: a * 2.0 + 1.0, return_type=T.DOUBLE)
        return df.with_column("t", my(df["y"]))
    assert_tpu_cpu_equal(q, approx=True)


def test_compiled_udf_runs_on_tpu():
    s = tpu_session(**{"spark.rapids.sql.udfCompiler.enabled": True})
    df = s.create_dataframe(DATA, num_partitions=2)
    my = F.udf(lambda a: a * 2 + 1, return_type=T.INT)
    out = df.with_column("t", my(df["x"]))
    rows = out.collect()
    # compiled projection must be on the TPU: no PythonUDF fallback reason
    assert "PythonUDF" not in s.last_explain
    got = {r[0]: r[3] for r in rows}
    assert got[1] == 3 and got[-2] == -3
    assert got[None] is None


def test_uncompilable_udf_falls_back():
    s = tpu_session(**{"spark.rapids.sql.udfCompiler.enabled": True})
    df = s.create_dataframe(DATA, num_partitions=2)

    def weird(a):
        return hash(str(a)) % 97  # hash() not compilable

    my = F.udf(weird, return_type=T.INT)
    out = df.with_column("t", my(df["x"]))
    rows = out.collect()
    assert "cannot run on TPU" in s.last_explain
    assert len(rows) == 6
