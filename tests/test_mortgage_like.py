"""Mortgage-like ETL benchmark correctness (MortgageSparkSuite pattern):
each pipeline runs on the TPU engine and the CPU engine and must agree."""

import pytest

from spark_rapids_tpu.benchmarks.mortgage_like import (
    aggregates_with_join, register_mortgage, run_mortgage,
    simple_aggregates,
)

from compare import assert_tpu_cpu_equal

SF = 0.05


def _build(pipeline):
    def build(s):
        register_mortgage(s, sf=SF, num_partitions=3)
        return pipeline(s)
    return build


def test_mortgage_etl():
    assert_tpu_cpu_equal(
        _build(run_mortgage),
        approx=True, ignore_order=False)


def test_mortgage_simple_aggregates():
    assert_tpu_cpu_equal(_build(simple_aggregates), approx=True,
                         ignore_order=False)


def test_mortgage_aggregates_with_join():
    assert_tpu_cpu_equal(_build(aggregates_with_join), approx=True,
                         ignore_order=False)


def test_mortgage_csv_roundtrip(tmp_path):
    """The reference's Run.csv entry: the ETL driven from CSV files on
    disk rather than registered in-memory views."""
    from compare import tpu_session
    from spark_rapids_tpu.benchmarks.mortgage_like import (
        gen_acquisition, gen_performance,
    )

    s = tpu_session()
    perf_dir = str(tmp_path / "perf")
    acq_dir = str(tmp_path / "acq")
    s.create_dataframe(gen_performance(SF), num_partitions=2) \
        .write_csv(perf_dir, mode="overwrite")
    s.create_dataframe(gen_acquisition(SF), num_partitions=2) \
        .write_csv(acq_dir, mode="overwrite")

    def build(sess):
        sess.register_view("perf_raw", sess.read.csv(perf_dir))
        sess.register_view("acq_raw", sess.read.csv(acq_dir))
        return run_mortgage(sess)

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
