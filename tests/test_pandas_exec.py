"""Pandas-exec family tests (GpuMapInPandasExec /
GpuFlatMapGroupsInPandasExec / GpuFlatMapCoGroupsInPandasExec /
GpuAggregateInPandasExec analogues)."""

import numpy as np

from spark_rapids_tpu import types as T

from compare import tpu_session

DATA = {
    "k": (T.STRING, ["a", "b", "a", "c", "b", "a", None, "c"]),
    "v": (T.LONG, [1, 2, 3, 4, 5, 6, 7, 8]),
    "x": (T.DOUBLE, [0.5, 1.0, 1.5, 2.0, 2.5, 3.0, 3.5, 4.0]),
}


def test_map_in_pandas():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)

    def fn(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["v2"] = pdf["v"] * 2
            yield pdf[["k", "v2"]]

    out = df.map_in_pandas(fn, [("k", T.STRING), ("v2", T.LONG)])
    rows = sorted(out.collect(), key=lambda r: (r[0] is None, str(r)))
    expect = sorted(
        [(k, v * 2) for k, v in zip(DATA["k"][1], DATA["v"][1])],
        key=lambda r: (r[0] is None, str(r)))
    assert rows == expect


def test_apply_in_pandas_grouped_map():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)

    def center(pdf):
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf[["k", "v"]]

    out = df.group_by("k").apply_in_pandas(
        center, [("k", T.STRING), ("v", T.DOUBLE)])
    rows = out.collect()
    # group a: v = 1,3,6 -> mean 10/3; group b: 2,5 -> 3.5; c: 4,8 -> 6
    by_key = {}
    for k, v in rows:
        by_key.setdefault(k, []).append(round(v, 6))
    assert sorted(by_key["b"]) == [-1.5, 1.5]
    assert sorted(by_key["c"]) == [-2.0, 2.0]
    assert len(by_key["a"]) == 3
    assert abs(sum(by_key["a"])) < 1e-5  # rounded to 6 dp above


def test_agg_in_pandas():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.group_by("k").agg_in_pandas({
        "v_sum": (lambda ser: ser.sum(), T.LONG, "v"),
        "x_max": (lambda ser: ser.max(), T.DOUBLE, "x"),
    })
    rows = {r[0]: (r[1], r[2]) for r in out.collect()}
    assert rows["a"] == (10, 3.0)
    assert rows["b"] == (7, 2.5)
    assert rows["c"] == (12, 4.0)


def test_cogroup_apply_in_pandas():
    s = tpu_session()
    left = s.create_dataframe({
        "k": (T.STRING, ["a", "b", "a"]),
        "v": (T.LONG, [1, 2, 3])})
    right = s.create_dataframe({
        "k": (T.STRING, ["a", "c"]),
        "w": (T.LONG, [10, 30])})

    def fn(lg, rg):
        import pandas as pd
        key = lg["k"].iloc[0] if len(lg) else rg["k"].iloc[0]
        return pd.DataFrame({
            "k": [key],
            "l_sum": [int(lg["v"].sum()) if len(lg) else 0],
            "r_sum": [int(rg["w"].sum()) if len(rg) else 0],
        })

    out = left.group_by("k").cogroup(right.group_by("k")).apply_in_pandas(
        fn, [("k", T.STRING), ("l_sum", T.LONG), ("r_sum", T.LONG)])
    rows = {r[0]: (r[1], r[2]) for r in out.collect()}
    assert rows == {"a": (4, 10), "b": (2, 0), "c": (0, 30)}


def test_pandas_exec_explains_fallback():
    s = tpu_session()
    df = s.create_dataframe(DATA)
    out = df.map_in_pandas(lambda it: it, [("k", T.STRING), ("v", T.LONG),
                                           ("x", T.DOUBLE)])
    out.collect()
    assert "host Arrow path" in s.last_explain


def test_worker_semaphore_bounds_concurrency():
    import threading
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.plan.physical import ExecContext
    from spark_rapids_tpu.runtime import python_worker as pw

    conf = RapidsConf({"spark.rapids.python.concurrentPythonWorkers": 2})
    ctx = ExecContext(conf)
    active = []
    peak = []
    lock = threading.Lock()

    def work():
        with pw.python_worker_slot(ctx):
            with lock:
                active.append(1)
                peak.append(len(active))
            import time
            time.sleep(0.05)
            with lock:
                active.pop()

    threads = [threading.Thread(target=work) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) <= 2


def test_window_in_pandas():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)
    out = df.window_in_pandas(
        ["k"], {"vmean": (lambda ser: float(ser.mean()), T.DOUBLE, "v")})
    rows = out.collect()
    assert len(rows) == 8
    by_key = {}
    for r in rows:
        by_key.setdefault(r[0], set()).add(r[3])
    # group a: v=1,3,6 -> mean 10/3 on every row of the partition
    assert by_key["a"] == {10.0 / 3.0}
    assert by_key["b"] == {3.5}


def test_window_in_pandas_validates_inputs():
    import pytest as _pytest
    s = tpu_session()
    df = s.create_dataframe(DATA)
    with _pytest.raises(TypeError):
        df.window_in_pandas([df["v"]], {"m": (lambda s_: 0.0, T.DOUBLE,
                                              "v")})
    with _pytest.raises(ValueError):
        df.window_in_pandas(["k"], {"v": (lambda s_: 0.0, T.DOUBLE, "v")})


def test_worker_slot_does_not_leak_device_permits():
    """A thread that holds NO device permit must not end up donating one
    (TpuSemaphore.release at depth 0 is a no-op, so blind re-acquire would
    leak admission and deadlock later queries)."""
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.plan.physical import ExecContext
    from spark_rapids_tpu.runtime.device import TpuSemaphore
    from spark_rapids_tpu.runtime.python_worker import python_worker_slot

    sem = TpuSemaphore(1)
    ctx = ExecContext(RapidsConf(), semaphore=sem)
    with python_worker_slot(ctx):
        pass
    assert sem.held_depth() == 0
    # permit still available: a fresh acquire must succeed immediately
    sem.acquire()
    assert sem.held_depth() == 1
    sem.release()
    assert sem.held_depth() == 0
    # and a holder releases + re-acquires cleanly
    sem.acquire()
    with python_worker_slot(ctx):
        assert sem.held_depth() == 0  # released while python runs
    assert sem.held_depth() == 1
    sem.release()


def test_cogroup_null_keys_pair_up():
    s = tpu_session()
    left = s.create_dataframe({"k": ["a", None], "v": [1, 2]})
    right = s.create_dataframe({"k": [None, "b"], "w": [10, 20]})

    def fn(lg, rg):
        import pandas as pd
        key = None
        if len(lg):
            key = lg["k"].iloc[0]
        elif len(rg):
            key = rg["k"].iloc[0]
        if key is not None and key != key:
            key = None
        return pd.DataFrame({
            "k": [key], "ln": [len(lg)], "rn": [len(rg)]})

    out = left.group_by("k").cogroup(right.group_by("k")).apply_in_pandas(
        fn, [("k", T.STRING), ("ln", T.LONG), ("rn", T.LONG)])
    rows = {r[0]: (r[1], r[2]) for r in out.collect()}
    # the NULL key must appear ONCE with both sides
    assert rows[None] == (1, 1), rows


# ---------------------------------------------------------------------------
# Out-of-process worker (GpuArrowPythonRunner / python/rapids/worker.py
# analogue): user python runs in a forked process over framed IPC pipes.
# ---------------------------------------------------------------------------


def test_map_in_pandas_runs_out_of_process():
    import os
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)

    def fn(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["pid"] = os.getpid()
            yield pdf[["k", "pid"]]

    out = df.map_in_pandas(fn, [("k", T.STRING), ("pid", T.LONG)])
    rows = out.collect()
    assert rows, "no rows"
    worker_pids = {r[1] for r in rows}
    assert os.getpid() not in worker_pids, \
        f"python ran in the engine process: {worker_pids}"
    from spark_rapids_tpu.runtime import python_worker
    assert python_worker.last_worker_pid is not None
    assert python_worker.last_worker_pid != os.getpid()


def test_map_in_pandas_in_process_when_disabled():
    import os
    s = tpu_session(**{"spark.rapids.python.outOfProcess.enabled": False})
    df = s.create_dataframe(DATA, num_partitions=2)

    def fn(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["pid"] = os.getpid()
            yield pdf[["k", "pid"]]

    out = df.map_in_pandas(fn, [("k", T.STRING), ("pid", T.LONG)])
    assert {r[1] for r in out.collect()} == {os.getpid()}


def test_worker_crash_raises_and_engine_survives():
    import os

    import pytest

    from spark_rapids_tpu.runtime.python_worker import PythonWorkerError

    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=1)

    def crash(it):
        for _pdf in it:
            os._exit(9)  # hard death: no exception frame reaches the pipe
        yield  # pragma: no cover

    out = df.map_in_pandas(crash, [("k", T.STRING)])
    with pytest.raises(PythonWorkerError, match="died"):
        out.collect()

    # the engine process is intact: a fresh query on the same session works
    def ok(it):
        for pdf in it:
            yield pdf[["k"]]

    rows = s.create_dataframe(DATA, num_partitions=1) \
        .map_in_pandas(ok, [("k", T.STRING)]).collect()
    assert len(rows) == 8


def test_worker_exception_propagates_with_traceback():
    import pytest

    from spark_rapids_tpu.runtime.python_worker import PythonWorkerError

    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=1)

    def boom(it):
        for _pdf in it:
            raise ValueError("user code exploded")
        yield  # pragma: no cover

    out = df.map_in_pandas(boom, [("k", T.STRING)])
    with pytest.raises(PythonWorkerError, match="user code exploded"):
        out.collect()


def test_upstream_error_propagates_through_worker():
    """An upstream iterator failure (scan/expression) must surface on the
    consumer, not read as clean EOF + silently truncated results."""
    import pytest

    from spark_rapids_tpu.batch import HostBatch
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime.python_worker import run_python_task

    class Ctx:
        conf = RapidsConf()
        semaphore = None

    hb = HostBatch.from_pydict({"v": (T.LONG, [1, 2, 3])})

    def inputs():
        yield 0, hb
        raise ValueError("upstream scan failed")

    def task(frames):
        for _i, b in frames:
            yield b

    with pytest.raises(ValueError, match="upstream scan failed"):
        list(run_python_task(Ctx(), task, inputs(), [hb.schema],
                             hb.schema))
