"""Out-of-core ring test: file-backed TPC-H q1/q18 under a spill budget
far below the working set must still be green, with spills asserted
nonzero (CI-scale twin of benchmarks/oocore_run.py; the SF10 artifact is
BENCH_OOCORE.md)."""

import pytest


@pytest.mark.parametrize("qname", [
    "q1",
    # q18 is the ~9-minute three-way-join variant: full out-of-core
    # coverage, but far too heavy for the quick (-m 'not slow') pass —
    # q1 keeps the spill-tier proof in every tier-1 run
    pytest.param("q18", marks=pytest.mark.slow),
])
def test_oocore_query_under_tiny_budget(qname, tmp_path):
    from spark_rapids_tpu.benchmarks import oocore_run

    res = oocore_run.run(
        sf=0.2, budget_mb=2, queries=[qname],
        out_path=str(tmp_path / "oocore.md"))
    r = res[qname]
    assert r["agree"]
    assert r["spilled_to_host"] + r["spilled_to_disk"] > 0
