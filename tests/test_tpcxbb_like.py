"""TPCxBB-like query correctness (tpcxbb_test.py pattern): every query in
the supported set runs on the TPU engine and the CPU engine and must
agree."""

import pytest

from spark_rapids_tpu.benchmarks.tpcxbb_like import QUERIES, register_tpcxbb

from compare import assert_tpu_cpu_equal

SF = 0.05


@pytest.mark.parametrize("qname", sorted(QUERIES.keys()))
def test_tpcxbb_like_query(qname):
    def build(s):
        register_tpcxbb(s, sf=SF, num_partitions=3)
        return s.sql(QUERIES[qname])

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
