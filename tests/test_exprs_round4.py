"""Round-4 expression-parity additions: hyperbolics, cot, log(base, x),
weekday, to_unix_timestamp, time-add, initcap, substring_index, split,
unary plus, AtLeastNNonNulls/dropna (closing the GpuOverrides registry
diff vs GpuOverrides.scala's expr[] list)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

NUM = {"x": (T.DOUBLE, [0.5, -1.25, 2.0, None, 0.0]),
       "b": (T.DOUBLE, [2.0, 10.0, 2.718281828, 3.0, 2.0]),
       "n": (T.INT, [1, 2, None, 4, 5])}

STR = {"s": (T.STRING, ["hello world", "a-b-c-d", "UPPER case",
             None, "  padded  x", "www.apache.org"])}


def test_hyperbolics_and_cot():
    def build(s):
        df = s.create_dataframe(NUM, num_partitions=2)
        return df.select(
            F.sinh("x").alias("sh"), F.cosh("x").alias("ch"),
            F.tanh("x").alias("th"), F.cot("b").alias("ct"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_hyperbolics_sql_and_log_base():
    def build(s):
        s.register_view("t", s.create_dataframe(NUM, num_partitions=2))
        return s.sql(
            "SELECT sinh(x) AS a, asinh(x) AS b, acosh(b) AS c, "
            "atanh(x / 10.0) AS d, log(2.0, b) AS e FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_initcap():
    def build(s):
        s.register_view("t", s.create_dataframe(STR, num_partitions=2))
        return s.sql("SELECT initcap(s) AS c FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_initcap_ground_truth():
    s = tpu_session()
    df = s.create_dataframe(STR, num_partitions=2)
    rows = [r[0] for r in df.select(F.initcap("s").alias("c")).collect()]
    assert rows[0] == "Hello World"
    assert rows[2] == "Upper Case"
    assert rows[3] is None
    assert rows[4] == "  Padded  X"


@pytest.mark.parametrize("count", [1, 2, 3, 10, -1, -2, -10, 0])
def test_substring_index(count):
    def build(s, count=count):
        s.register_view("t", s.create_dataframe(STR, num_partitions=2))
        return s.sql(
            f"SELECT substring_index(s, '-', {count}) AS c FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_substring_index_ground_truth():
    s = tpu_session()
    df = s.create_dataframe(STR, num_partitions=2)
    got = [r[0] for r in df.select(
        F.substring_index("s", ".", 2).alias("c")).collect()]
    assert got[5] == "www.apache"
    got = [r[0] for r in df.select(
        F.substring_index("s", ".", -2).alias("c")).collect()]
    assert got[5] == "apache.org"


def test_split_falls_back_and_matches():
    def build(s):
        s.register_view("t", s.create_dataframe(STR, num_partitions=2))
        return s.sql("SELECT split(s, '-') AS parts FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False,
                         expect_fallback="split")


def test_weekday_and_to_unix_timestamp():
    data = {"d": (T.DATE, [0, 1, 2, 3, 4, 5, 6, None, 11323])}

    def build(s):
        s.register_view("t", s.create_dataframe(data, num_partitions=2))
        return s.sql("SELECT weekday(d) AS w, dayofweek(d) AS dw, "
                     "to_unix_timestamp(d) AS ut FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    s = tpu_session()
    df = s.create_dataframe(data, num_partitions=1)
    rows = df.select(F.weekday("d").alias("w")).collect()
    # 1970-01-01 (day 0) was a Thursday -> weekday 3 (0 = Monday)
    assert rows[0][0] == 3 and rows[3][0] == 6 and rows[4][0] == 0


def test_time_add():
    from spark_rapids_tpu.dataframe import Column
    from spark_rapids_tpu.exprs.datetime import TimeAdd

    def build(s):
        df = s.create_dataframe(
            {"ts": (T.TIMESTAMP, [0, 86_400_000_000, None])},
            num_partitions=1)
        return df.select(Column(TimeAdd(
            df["ts"].expr, 3_600_000_000)).alias("plus1h"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_unary_positive_sql():
    def build(s):
        s.register_view("t", s.create_dataframe(NUM, num_partitions=2))
        return s.sql("SELECT +x AS px, -x AS nx FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_dropna():
    data = {"a": (T.INT, [1, None, 3, None, 5]),
            "f": (T.DOUBLE, [1.0, 2.0, float("nan"), None, 5.0])}

    def build_any(s):
        return s.create_dataframe(data, num_partitions=2).dropna()

    def build_all(s):
        return s.create_dataframe(data, num_partitions=2).dropna("all")

    def build_thresh(s):
        return s.create_dataframe(data, num_partitions=2).dropna(
            thresh=1, subset=["f"])

    assert_tpu_cpu_equal(build_any, ignore_order=False)
    assert_tpu_cpu_equal(build_all, ignore_order=False)
    assert_tpu_cpu_equal(build_thresh, ignore_order=False)

    s = tpu_session()
    rows = s.create_dataframe(data, num_partitions=1).dropna().collect()
    assert rows == [(1, 1.0), (5, 5.0)]


def test_fillna():
    data = {"a": (T.INT, [1, None, 3]),
            "s": (T.STRING, ["x", None, "z"]),
            "f": (T.DOUBLE, [None, 2.0, None])}

    import pytest as _pt
    s0 = tpu_session()
    df0 = s0.create_dataframe(data, num_partitions=1)
    with _pt.raises(TypeError):
        df0.fillna(None)
    with _pt.raises(KeyError):
        df0.fillna(0, subset=["nope"])
    # float fill on an INT column casts to the column type (pyspark)
    rows0 = df0.fillna(2.9).collect()
    assert rows0[1][0] == 2 and isinstance(rows0[1][0], int)
    # NaN in a float column is filled too
    dfn = s0.create_dataframe(
        {"f": (T.DOUBLE, [float("nan"), None, 1.0])}, num_partitions=1)
    assert [r[0] for r in dfn.fillna(7.0).collect()] == [7.0, 7.0, 1.0]

    def build_scalar(s):
        return s.create_dataframe(data, num_partitions=2).fillna(0)

    def build_dict(s):
        return s.create_dataframe(data, num_partitions=2).fillna(
            {"s": "?", "f": -1.0})

    assert_tpu_cpu_equal(build_scalar, ignore_order=False)
    assert_tpu_cpu_equal(build_dict, ignore_order=False)

    s = tpu_session()
    rows = s.create_dataframe(data, num_partitions=1).fillna(0).collect()
    # numeric columns filled, string column untouched by a numeric fill
    assert rows == [(1, "x", 0.0), (0, None, 2.0), (3, "z", 0.0)]
    rows = s.create_dataframe(data, num_partitions=1).fillna(
        {"s": "?", "f": -1.0}).collect()
    assert rows == [(1, "x", -1.0), (None, "?", 2.0), (3, "z", -1.0)]


def test_hex():
    data = {"l": (T.LONG, [0, 1, 255, 4095, -1, -255,
                           9223372036854775807, None]),
            "i": (T.INT, [16, -16, 0, None, 1, 2, 3, 4])}

    def build(s):
        s.register_view("t", s.create_dataframe(data, num_partitions=2))
        return s.sql("SELECT hex(l) AS hl, hex(i) AS hi FROM t")

    assert_tpu_cpu_equal(build, ignore_order=False)
    s = tpu_session()
    df = s.create_dataframe(data, num_partitions=1)
    rows = [r[0] for r in df.select(F.hex("l").alias("h")).collect()]
    assert rows[0] == "0" and rows[1] == "1" and rows[2] == "FF"
    assert rows[3] == "FFF"
    assert rows[4] == "FFFFFFFFFFFFFFFF"      # -1 two's complement
    assert rows[5] == "FFFFFFFFFFFFFF01"      # -255
    assert rows[6] == "7FFFFFFFFFFFFFFF"
    assert rows[7] is None


def test_hex_string_and_double_fallback():
    data = {"s": (T.STRING, ["Spark SQL", "", None]),
            "f": (T.DOUBLE, [1.5, -2.9, float("nan")])}

    def build(s):
        s.register_view("t", s.create_dataframe(data, num_partitions=2))
        return s.sql("SELECT hex(s) AS hs, hex(f) AS hf FROM t")

    assert_tpu_cpu_equal(build, ignore_order=False,
                         expect_fallback="hex")
    s = tpu_session()
    df = s.create_dataframe(data, num_partitions=1)
    rows = df.select(F.hex("s").alias("hs"),
                     F.hex("f").alias("hf")).collect()
    assert rows[0] == ("537061726B2053514C", "1")
    assert rows[1] == ("", "FFFFFFFFFFFFFFFE")  # trunc toward zero: -2
    assert rows[2][0] is None and rows[2][1] == "0"  # NaN -> 0


def test_hex_double_saturation():
    data = {"f": (T.DOUBLE, [float("inf"), float("-inf"), 1e20, -1e20])}
    s = tpu_session()
    df = s.create_dataframe(data, num_partitions=1)
    rows = [r[0] for r in df.select(F.hex("f").alias("h")).collect()]
    assert rows[0] == "7FFFFFFFFFFFFFFF"   # +inf -> Long.MAX
    assert rows[1] == "8000000000000000"   # -inf -> Long.MIN
    assert rows[2] == "7FFFFFFFFFFFFFFF"   # out of range saturates
    assert rows[3] == "8000000000000000"


def test_to_date_and_date_format():
    data = {"s": (T.STRING, ["2001-03-16", "1970-01-01", "2026-12-31",
                             "not a date", "2001-13-01", None,
                             "2001-3-16"])}

    def build(s):
        s.register_view("t", s.create_dataframe(data, num_partitions=2))
        return s.sql("SELECT to_date(s) AS d, "
                     "date_format(to_date(s), 'yyyy-MM-dd') AS f FROM t")

    assert_tpu_cpu_equal(build, ignore_order=False)
    s = tpu_session()
    df = s.create_dataframe(data, num_partitions=1)
    rows = df.select(F.to_date("s").alias("d"),
                     F.date_format(F.to_date("s")).alias("f")).collect()
    import datetime as dt
    assert rows[0][0] == dt.date(2001, 3, 16) or rows[0][0] == 11397
    assert rows[0][1] == "2001-03-16"
    assert rows[1][1] == "1970-01-01"
    assert rows[3] == (None, None)    # unparseable -> NULL
    assert rows[4] == (None, None)    # month 13 -> NULL
    assert rows[5] == (None, None)
    assert rows[6] == (None, None)    # non-padded needs a custom fmt


def test_to_date_custom_format_cpu_fallback():
    data = {"s": (T.STRING, ["03/16/2001", "12/31/1970", "bad"])}

    def build(s):
        s.register_view("t", s.create_dataframe(data, num_partitions=1))
        return s.sql("SELECT to_date(s, 'MM/dd/yyyy') AS d FROM t")

    assert_tpu_cpu_equal(build, ignore_order=False,
                         expect_fallback="to_date")


def test_to_date_invalid_calendar_dates_and_old_years():
    data = {"s": (T.STRING, ["2021-02-30", "2021-04-31", "2020-02-29",
                             "0999-12-31", "2021-02-29"])}

    def build(s):
        s.register_view("t", s.create_dataframe(data, num_partitions=1))
        return s.sql("SELECT to_date(s) AS d FROM t")

    assert_tpu_cpu_equal(build, ignore_order=False)
    s = tpu_session()
    rows = s.create_dataframe(data, num_partitions=1).select(
        F.to_date("s").alias("d")).collect()
    assert rows[0][0] is None          # Feb 30
    assert rows[1][0] is None          # Apr 31
    assert rows[2][0] is not None      # leap day 2020
    assert rows[3][0] is not None      # year < 1000 stays valid
    assert rows[4][0] is None          # 2021 not a leap year


def test_date_format_rejects_unsupported_tokens():
    s = tpu_session()
    df = s.create_dataframe({"d": (T.DATE, [0])}, num_partitions=1)
    with pytest.raises(ValueError):
        df.select(F.date_format("d", "dd-MMM-yyyy").alias("x")).collect()
