"""Expression kernels: TPU (jit) result must match the CPU (numpy) oracle,
which itself encodes Spark CPU semantics — the same CPU-vs-accelerated
compare strategy as the reference's SparkQueryCompareTestSuite."""

import jax
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, device_to_host, host_to_device
from spark_rapids_tpu.exprs import (
    Abs, Add, And, Average, Cast, CaseWhen, Coalesce, ColumnRef, ConcatStrings,
    Count, DateAdd, DateDiff, DayOfMonth, Divide, Equals, GreaterThan, If, In,
    IntegralDivide, IsNan, IsNotNull, IsNull, Length, LessThan, Like, Literal,
    Lower, Max, Min, Month, Multiply, Murmur3Hash, Not, NotEquals, Or, Pmod,
    Remainder, StringContains, StringEndsWith, StringLocate, StringLPad,
    StringReplace, StringRPad, StringStartsWith, StringTrim, Substring,
    Subtract, Sum, Upper, Year, Sqrt, Round,
)
from spark_rapids_tpu.exprs.base import CpuEvalCtx, TpuEvalCtx, resolve

from conftest import assert_cols_equal


def run_both(expr, data, approx=False):
    """Evaluate expr on TPU (via jit) and CPU, compare, return CPU result."""
    batch = HostBatch.from_pydict(data)
    expr = resolve(expr, batch.schema)
    cpu = expr.cpu_eval(CpuEvalCtx(batch))
    dev_batch = host_to_device(batch)

    def stage(b):
        v = expr.tpu_eval(TpuEvalCtx(b))
        from spark_rapids_tpu.batch import ColumnBatch
        out_schema = T.Schema([T.Field("out", v.dtype)])
        return ColumnBatch(out_schema, [v.to_column()], b.num_rows, b.capacity)

    out = jax.jit(stage)(dev_batch)
    host = device_to_host(out)
    expected = cpu.to_column().to_list()
    actual = host.columns[0].to_list()
    assert_cols_equal(expected, actual, approx=approx, msg=repr(expr))
    return expected


INTS = {"a": (T.INT, [1, 2, None, -4, 5, 0, 7]),
        "b": (T.INT, [10, 0, 3, None, -5, 2, 7])}
DOUBLES = {"x": (T.DOUBLE, [1.5, -2.25, None, 0.0, float("nan"), 1e10, -0.5]),
           "y": (T.DOUBLE, [2.0, 4.0, 1.0, 0.0, 1.0, None, 2.0])}
STRINGS = {"s": (T.STRING, ["hello", "", None, "WORLD", "  pad  ", "tail", "hello"]),
           "t": (T.STRING, ["he", "x", "y", "LD", None, "ail", "hello"])}


class TestArithmetic:
    def test_add(self):
        assert run_both(Add(ColumnRef("a"), ColumnRef("b")), INTS) == \
            [11, 2, None, None, 0, 2, 14]

    def test_subtract(self):
        run_both(Subtract(ColumnRef("a"), ColumnRef("b")), INTS)

    def test_multiply(self):
        run_both(Multiply(ColumnRef("a"), ColumnRef("b")), INTS)

    def test_divide_null_on_zero(self):
        out = run_both(Divide(ColumnRef("a"), ColumnRef("b")), INTS, approx=True)
        assert out[1] is None  # 2 / 0 -> NULL

    def test_integral_divide(self):
        out = run_both(IntegralDivide(Literal(-7), Literal(2)), INTS)
        assert out[0] == -3  # truncation toward zero, not floor

    def test_remainder_sign(self):
        out = run_both(Remainder(Literal(-7), Literal(3)), INTS)
        assert out[0] == -1  # java semantics: sign of dividend

    def test_pmod(self):
        out = run_both(Pmod(Literal(-7), Literal(3)), INTS)
        assert out[0] == 2

    def test_pmod_negative_divisor(self):
        # Spark: pmod(-5, -3) = -2 (NOT forced non-negative)
        out = run_both(Pmod(Literal(-5), Literal(-3)), INTS)
        assert out[0] == -2
        out = run_both(Pmod(Literal(5), Literal(-3)), INTS)
        assert out[0] == 2

    def test_abs_mixed(self):
        run_both(Abs(ColumnRef("x")), DOUBLES, approx=True)

    def test_promotion_int_double(self):
        run_both(Add(ColumnRef("a"), Literal(0.5)), INTS, approx=True)


class TestPredicates:
    def test_comparisons(self):
        for cls in (Equals, NotEquals, LessThan, GreaterThan):
            run_both(cls(ColumnRef("a"), ColumnRef("b")), INTS)

    def test_and_kleene(self):
        # NULL AND FALSE = FALSE (not NULL)
        out = run_both(And(Literal(None, T.BOOLEAN), Literal(False)), INTS)
        assert out[0] is False

    def test_or_kleene(self):
        out = run_both(Or(Literal(None, T.BOOLEAN), Literal(True)), INTS)
        assert out[0] is True

    def test_not(self):
        run_both(Not(Equals(ColumnRef("a"), ColumnRef("b"))), INTS)

    def test_in(self):
        run_both(In(ColumnRef("a"), [1, 5, 99]), INTS)

    def test_string_equality(self):
        out = run_both(Equals(ColumnRef("s"), ColumnRef("t")), STRINGS)
        assert out == [False, False, None, False, None, False, True]


class TestNulls:
    def test_is_null(self):
        assert run_both(IsNull(ColumnRef("a")), INTS) == \
            [False, False, True, False, False, False, False]

    def test_is_not_null(self):
        run_both(IsNotNull(ColumnRef("a")), INTS)

    def test_isnan(self):
        out = run_both(IsNan(ColumnRef("x")), DOUBLES)
        assert out[4] is True

    def test_coalesce(self):
        out = run_both(Coalesce(ColumnRef("a"), ColumnRef("b")), INTS)
        assert out == [1, 2, 3, -4, 5, 0, 7]


class TestConditional:
    def test_if(self):
        run_both(If(GreaterThan(ColumnRef("a"), ColumnRef("b")),
                    ColumnRef("a"), ColumnRef("b")), INTS)

    def test_case_when(self):
        expr = CaseWhen(
            [(GreaterThan(ColumnRef("a"), Literal(3)), Literal(100)),
             (GreaterThan(ColumnRef("a"), Literal(1)), Literal(50))],
            Literal(0))
        out = run_both(expr, INTS)
        assert out == [0, 50, 0, 0, 100, 0, 100]

    def test_case_when_no_else(self):
        expr = CaseWhen([(GreaterThan(ColumnRef("a"), Literal(3)), Literal(1))])
        out = run_both(expr, INTS)
        assert out[0] is None


class TestCast:
    def test_int_to_double(self):
        run_both(Cast(ColumnRef("a"), T.DOUBLE), INTS, approx=True)

    def test_double_to_int_truncates(self):
        out = run_both(Cast(Literal(-2.7), T.INT), INTS)
        assert out[0] == -2

    def test_nan_to_int_is_zero(self):
        out = run_both(Cast(ColumnRef("x"), T.INT), DOUBLES)
        assert out[4] == 0

    def test_date_timestamp_roundtrip(self):
        data = {"d": (T.DATE, [0, 18262, None, -365])}
        run_both(Cast(Cast(ColumnRef("d"), T.TIMESTAMP), T.DATE), data)

    def test_int_to_bool(self):
        run_both(Cast(ColumnRef("a"), T.BOOLEAN), INTS)


class TestMath:
    def test_sqrt(self):
        run_both(Sqrt(Cast(ColumnRef("a"), T.DOUBLE)), INTS, approx=True)

    def test_round_half_up(self):
        out = run_both(Round(Literal(2.5)), INTS, approx=True)
        assert out[0] == 3.0
        out = run_both(Round(Literal(-2.5)), INTS, approx=True)
        assert out[0] == -3.0


class TestDatetime:
    DATES = {"d": (T.DATE, [0, 18262, None, -1, 11016, 19789])}

    def test_year_month_day(self):
        assert run_both(Year(ColumnRef("d")), self.DATES) == \
            [1970, 2020, None, 1969, 2000, 2024]
        run_both(Month(ColumnRef("d")), self.DATES)
        run_both(DayOfMonth(ColumnRef("d")), self.DATES)

    def test_date_add_diff(self):
        run_both(DateAdd(ColumnRef("d"), Literal(30)), self.DATES)
        run_both(DateDiff(ColumnRef("d"), Literal(100, T.DATE)), self.DATES)


class TestStrings:
    def test_length(self):
        assert run_both(Length(ColumnRef("s")), STRINGS) == \
            [5, 0, None, 5, 7, 4, 5]

    def test_upper_lower(self):
        run_both(Upper(ColumnRef("s")), STRINGS)
        run_both(Lower(ColumnRef("s")), STRINGS)

    def test_substring(self):
        assert run_both(Substring(ColumnRef("s"), 2, 3), STRINGS) == \
            ["ell", "", None, "ORL", " pa", "ail", "ell"]
        run_both(Substring(ColumnRef("s"), -3), STRINGS)

    def test_substring_negative_beyond_start(self):
        # Spark: substring('abcd', -6, 3) = 'a' (window measured from raw start)
        data = {"s": (T.STRING, ["abcd"])}
        assert run_both(Substring(ColumnRef("s"), -6, 3), data) == ["a"]

    def test_trim(self):
        out = run_both(StringTrim(ColumnRef("s")), STRINGS)
        assert out[4] == "pad"

    def test_concat(self):
        out = run_both(ConcatStrings(ColumnRef("s"), Literal("!")), STRINGS)
        assert out[0] == "hello!"

    def test_needles(self):
        assert run_both(StringStartsWith(ColumnRef("s"), Literal("he")),
                        STRINGS) == [True, False, None, False, False, False, True]
        run_both(StringEndsWith(ColumnRef("s"), Literal("lo")), STRINGS)
        run_both(StringContains(ColumnRef("s"), Literal("l")), STRINGS)

    def test_like(self):
        run_both(Like(ColumnRef("s"), "he%"), STRINGS)
        run_both(Like(ColumnRef("s"), "%l%"), STRINGS)
        run_both(Like(ColumnRef("s"), "h%o"), STRINGS)

    def test_locate(self):
        assert run_both(StringLocate(Literal("l"), ColumnRef("s")), STRINGS) == \
            [3, 0, None, 0, 0, 4, 3]

    def test_replace(self):
        out = run_both(StringReplace(ColumnRef("s"), Literal("l"), Literal("LL")),
                       STRINGS)
        assert out[0] == "heLLLLo"

    def test_pad(self):
        assert run_both(StringLPad(ColumnRef("s"), 7, "*"), STRINGS)[0] == \
            "**hello"
        run_both(StringRPad(ColumnRef("s"), 3, "-"), STRINGS)


class TestHash:
    def test_murmur3_matches_cpu(self):
        run_both(Murmur3Hash(ColumnRef("a"), ColumnRef("b")), INTS)
        run_both(Murmur3Hash(ColumnRef("x")), DOUBLES)

    def test_murmur3_int_spark_value(self):
        # Spark: Murmur3Hash(Literal(1, IntegerType), 42) == -559580957
        data = {"k": (T.INT, [1])}
        out = run_both(Murmur3Hash(ColumnRef("k")), data)
        assert out[0] == -559580957


class TestBitwise:
    def test_and_or_xor(self):
        from spark_rapids_tpu.exprs import BitwiseAnd, BitwiseOr, BitwiseXor
        assert run_both(BitwiseAnd(ColumnRef("a"), ColumnRef("b")), INTS) == \
            [0, 0, None, None, 1, 0, 7]
        run_both(BitwiseOr(ColumnRef("a"), ColumnRef("b")), INTS)
        run_both(BitwiseXor(ColumnRef("a"), ColumnRef("b")), INTS)

    def test_not(self):
        from spark_rapids_tpu.exprs import BitwiseNot
        assert run_both(BitwiseNot(ColumnRef("a")), INTS) == \
            [-2, -3, None, 3, -6, -1, -8]

    def test_shifts(self):
        from spark_rapids_tpu.exprs import (
            ShiftLeft, ShiftRight, ShiftRightUnsigned,
        )
        data = {"v": (T.INT, [1, -8, None, 1 << 30, -1]),
                "s": (T.INT, [3, 1, 2, 2, 1])}
        assert run_both(ShiftLeft(ColumnRef("v"), ColumnRef("s")), data) == \
            [8, -16, None, 0, -2]
        assert run_both(ShiftRight(ColumnRef("v"), ColumnRef("s")), data) == \
            [0, -4, None, 1 << 28, -1]
        assert run_both(
            ShiftRightUnsigned(ColumnRef("v"), ColumnRef("s")), data) == \
            [0, 2147483644, None, 1 << 28, 2147483647]

    def test_shift_amount_masked_java(self):
        from spark_rapids_tpu.exprs import ShiftLeft
        data = {"v": (T.INT, [1, 1]), "s": (T.INT, [33, 32])}
        # java: s & 31 -> 1, 0
        assert run_both(ShiftLeft(ColumnRef("v"), ColumnRef("s")), data) == \
            [2, 1]

    def test_long_shifts(self):
        from spark_rapids_tpu.exprs import ShiftRightUnsigned
        data = {"v": (T.LONG, [-1, 1 << 40]), "s": (T.INT, [1, 8])}
        # java: -1L >>> 1 == Long.MAX_VALUE
        assert run_both(
            ShiftRightUnsigned(ColumnRef("v"), ColumnRef("s")), data) == \
            [(1 << 63) - 1, 1 << 32]

    def test_bitwise_fallback_on_strings(self):
        from tests.compare import assert_tpu_cpu_equal
        from spark_rapids_tpu import functions as F

        def build(s):
            df = s.create_dataframe({"a": [1, 2, 3], "b": [4, 5, 6]})
            return df.select(F.col("a").bitwiseAND(F.col("b")))

        assert_tpu_cpu_equal(build)


class TestRegExpReplace:
    def test_literal_pattern(self):
        from spark_rapids_tpu.exprs import RegExpReplace
        data = {"s": (T.STRING,
                      ["hello", "ell", None, "bell bell", "", "no match"])}
        assert run_both(
            RegExpReplace(ColumnRef("s"), Literal("ell"), Literal("ELL")),
            data) == ["hELLo", "ELL", None, "bELL bELL", "", "no match"]

    def test_escaped_literal(self):
        from spark_rapids_tpu.exprs import RegExpReplace
        data = {"s": (T.STRING, ["a.b", "axb", "xa.b."])}
        assert run_both(
            RegExpReplace(ColumnRef("s"), Literal("a\\.b"), Literal("X")),
            data) == ["X", "axb", "xX."]

    def test_char_class(self):
        from spark_rapids_tpu.exprs import RegExpReplace
        data = {"s": (T.STRING, ["a1b22c333", "no digits", None, "9"])}
        assert run_both(
            RegExpReplace(ColumnRef("s"), Literal("[0-9]"), Literal("#")),
            data) == ["a#b##c###", "no digits", None, "#"]

    def test_char_class_delete(self):
        from spark_rapids_tpu.exprs import RegExpReplace
        data = {"s": (T.STRING, ["a-b_c", "--__"])}
        assert run_both(
            RegExpReplace(ColumnRef("s"), Literal("[-_]"), Literal("")),
            data) == ["abc", ""]

    def test_real_regex_falls_back(self):
        from tests.compare import assert_tpu_cpu_equal
        from spark_rapids_tpu import functions as F

        def build(s):
            df = s.create_dataframe({"s": ["foo12bar", "baz3", "qux"]})
            return df.select(F.regexp_replace("s", r"\d+", "N"))

        assert_tpu_cpu_equal(build, expect_fallback="RegExpReplace")


class TestSplitPart:
    def test_basic(self):
        from spark_rapids_tpu.exprs import SplitPart
        data = {"s": (T.STRING,
                      ["a,b,c", "one", None, ",lead", "trail,", ""])}
        assert run_both(SplitPart(ColumnRef("s"), ",", 1), data) == \
            ["a", "one", None, "", "trail", ""]
        assert run_both(SplitPart(ColumnRef("s"), ",", 2), data) == \
            ["b", "", None, "lead", "", ""]
        assert run_both(SplitPart(ColumnRef("s"), ",", 3), data) == \
            ["c", "", None, "", "", ""]

    def test_multichar_delim(self):
        from spark_rapids_tpu.exprs import SplitPart
        data = {"s": (T.STRING, ["a::b::c", "x::", "::"])}
        assert run_both(SplitPart(ColumnRef("s"), "::", 2), data) == \
            ["b", "", ""]

    def test_negative_part_falls_back(self):
        from tests.compare import assert_tpu_cpu_equal
        from spark_rapids_tpu import functions as F

        def build(s):
            df = s.create_dataframe({"s": ["a,b,c", "x,y"]})
            return df.select(F.split_part("s", ",", -1))

        assert_tpu_cpu_equal(build, expect_fallback="SplitPart")


class TestConcatWs:
    def test_skips_nulls(self):
        from spark_rapids_tpu.exprs import ConcatWs
        data = {"s": (T.STRING, ["a", None, "c", None]),
                "t": (T.STRING, ["x", "y", None, None])}
        assert run_both(
            ConcatWs("-", ColumnRef("s"), ColumnRef("t")), data) == \
            ["a-x", "y", "c", ""]

    def test_three_cols_empty_sep(self):
        from spark_rapids_tpu.exprs import ConcatWs
        data = {"s": (T.STRING, ["a", ""]), "t": (T.STRING, ["b", None]),
                "u": (T.STRING, ["c", "z"])}
        assert run_both(
            ConcatWs("", ColumnRef("s"), ColumnRef("t"), ColumnRef("u")),
            data) == ["abc", "z"]

    def test_multibyte_sep(self):
        from spark_rapids_tpu.exprs import ConcatWs
        data = {"s": (T.STRING, ["a", "hello"]),
                "t": (T.STRING, ["b", "world"])}
        assert run_both(
            ConcatWs(" :: ", ColumnRef("s"), ColumnRef("t")), data) == \
            ["a :: b", "hello :: world"]


class TestUnixTime:
    def test_unix_timestamp_roundtrip(self):
        from spark_rapids_tpu.exprs import FromUnixTime, UnixTimestamp
        secs = [0, 1_600_000_000, None, 86_399, 2_000_000_000]
        data = {"ts": (T.TIMESTAMP,
                       [None if s is None else s * 1_000_000
                        for s in secs])}
        assert run_both(UnixTimestamp(ColumnRef("ts")), data) == secs

    def test_unix_timestamp_date(self):
        from spark_rapids_tpu.exprs import UnixTimestamp
        data = {"d": (T.DATE, [0, 1, 18000, None])}
        assert run_both(UnixTimestamp(ColumnRef("d")), data) == \
            [0, 86400, 18000 * 86400, None]

    def test_from_unixtime_default_format(self):
        from spark_rapids_tpu.exprs import FromUnixTime
        data = {"s": (T.LONG, [0, 1_600_000_000, None, 86_399])}
        assert run_both(FromUnixTime(ColumnRef("s")), data) == \
            ["1970-01-01 00:00:00", "2020-09-13 12:26:40", None,
             "1970-01-01 23:59:59"]

    def test_from_unixtime_custom_format_falls_back(self):
        from tests.compare import assert_tpu_cpu_equal
        from spark_rapids_tpu import functions as F

        def build(s):
            df = s.create_dataframe({"s": [0, 1_600_000_000]})
            return df.select(F.from_unixtime("s", "yyyy/MM/dd"))

        assert_tpu_cpu_equal(build, expect_fallback="FromUnixTime")


class TestRegExpReplaceEdges:
    def test_escaped_range_endpoint_class(self):
        from spark_rapids_tpu.exprs import RegExpReplace
        # [\.-0] is the range '.'..'0' = {., /, 0}
        data = {"s": (T.STRING, ["a/b-c", "x.y0z"])}
        assert run_both(
            RegExpReplace(ColumnRef("s"), Literal(r"[\.-0]"), Literal("")),
            data) == ["ab-c", "xyz"]

    def test_literal_backslash_replacement(self):
        # replacement is literal text (no python-re template expansion
        # crash on \U, no '$1' group references); 'a+' is a real regex so
        # the planner routes to the CPU re path
        from tests.compare import assert_tpu_cpu_equal
        from spark_rapids_tpu import functions as F

        def build(s):
            df = s.create_dataframe({"s": ["aaa b", "nope"]})
            return df.select(
                F.regexp_replace("s", "a+", "C:\\Users").alias("r"))

        assert_tpu_cpu_equal(build, expect_fallback="RegExpReplace")
        from tests.compare import tpu_session
        s = tpu_session()
        assert build(s).collect()[0][0] == "C:\\Users b"

    def test_split_part_zero_raises(self):
        from spark_rapids_tpu.exprs import SplitPart
        with pytest.raises(ValueError):
            SplitPart(ColumnRef("s"), ",", 0)
