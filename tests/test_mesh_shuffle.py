"""Mesh all-to-all shuffle tests over the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.parallel.distributed import run_distributed_agg_demo
from spark_rapids_tpu.parallel.mesh_shuffle import make_exchange_fn, make_mesh

from jax.sharding import NamedSharding, PartitionSpec as P


def test_exchange_roundtrip():
    mesh = make_mesh(4)
    n, cap = 4, 32
    rng = np.random.RandomState(0)
    data = rng.randint(0, 1000, size=(n, cap)).astype(np.int64)
    validity = rng.rand(n, cap) < 0.8
    num_rows = np.array([32, 20, 0, 7], dtype=np.int32)
    pids = rng.randint(0, n, size=(n, cap)).astype(np.int32)

    sh = NamedSharding(mesh, P("data", None))
    s1 = NamedSharding(mesh, P("data"))
    fn = make_exchange_fn(mesh, n_cols=1, cap=cap)
    (out_d,), (out_v,), out_n = fn(
        [jax.device_put(data, sh)], [jax.device_put(validity, sh)],
        jax.device_put(num_rows, s1), jax.device_put(pids, sh))
    out_d = np.asarray(out_d)
    out_v = np.asarray(out_v)
    out_n = np.asarray(out_n)

    # every (value, validity) row must land exactly once on the right device
    sent = {}
    for d in range(n):
        for r in range(num_rows[d]):
            key = (int(pids[d, r]),)
            sent.setdefault(key, []).append(
                (int(data[d, r]), bool(validity[d, r])))
    for dest in range(n):
        got = [(int(out_d[dest, i]), bool(out_v[dest, i]))
               for i in range(int(out_n[dest]))]
        exp = sent.get((dest,), [])
        assert sorted(got) == sorted(exp), f"dest {dest}"


def test_distributed_agg_demo_8dev():
    stats = run_distributed_agg_demo(8, rows_per_device=128)
    assert stats["devices"] == 8
    assert stats["groups"] == 17
