"""Mesh all-to-all shuffle tests over the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.parallel.distributed import run_distributed_agg_demo
from spark_rapids_tpu.parallel.mesh_shuffle import make_exchange_fn, make_mesh

from jax.sharding import NamedSharding, PartitionSpec as P


def test_exchange_roundtrip():
    mesh = make_mesh(4)
    n, cap = 4, 32
    rng = np.random.RandomState(0)
    data = rng.randint(0, 1000, size=(n, cap)).astype(np.int64)
    validity = rng.rand(n, cap) < 0.8
    num_rows = np.array([32, 20, 0, 7], dtype=np.int32)
    pids = rng.randint(0, n, size=(n, cap)).astype(np.int32)

    sh = NamedSharding(mesh, P("data", None))
    s1 = NamedSharding(mesh, P("data"))
    fn = make_exchange_fn(mesh, n_cols=1, cap=cap)
    (out_d,), (out_v,), out_n = fn(
        [jax.device_put(data, sh)], [jax.device_put(validity, sh)],
        jax.device_put(num_rows, s1), jax.device_put(pids, sh))
    out_d = np.asarray(out_d)
    out_v = np.asarray(out_v)
    out_n = np.asarray(out_n)

    # every (value, validity) row must land exactly once on the right device
    sent = {}
    for d in range(n):
        for r in range(num_rows[d]):
            key = (int(pids[d, r]),)
            sent.setdefault(key, []).append(
                (int(data[d, r]), bool(validity[d, r])))
    for dest in range(n):
        got = [(int(out_d[dest, i]), bool(out_v[dest, i]))
               for i in range(int(out_n[dest]))]
        exp = sent.get((dest,), [])
        assert sorted(got) == sorted(exp), f"dest {dest}"


def test_distributed_agg_demo_8dev():
    stats = run_distributed_agg_demo(8, rows_per_device=128)
    assert stats["devices"] == 8
    assert stats["groups"] == 17


# ---------------------------------------------------------------------------
# Engine-level mesh shuffle: planner-built queries whose exchanges run the
# ICI all-to-all collective (spark.rapids.shuffle.ici.enabled).
# ---------------------------------------------------------------------------

from tests.compare import assert_tpu_cpu_equal, tpu_session  # noqa: E402
from spark_rapids_tpu import functions as F  # noqa: E402

MESH_CONFS = {"spark.rapids.shuffle.ici.enabled": True,
              "spark.rapids.sql.variableFloatAgg.enabled": True}


def _people_df(sess, n=500, parts=5):
    cats = ["red", "green", "blue", None, "a-very-long-color-name-x", ""]
    rng = np.random.RandomState(3)
    return sess.create_dataframe({
        "name": [cats[i] for i in rng.randint(0, len(cats), n)],
        "age": rng.randint(0, 90, n).tolist(),
        "score": (rng.rand(n) * 10).round(4).tolist(),
    }, num_partitions=parts)


def _assert_mesh_used(sess):
    # host-driven exchanges count meshExchanges; with mesh SPMD (the
    # default) the exchange instead fuses into a shard_map program and
    # counts meshBoundariesFused — either proves rows moved over the mesh
    ops = [op for op, ms in sess.last_metrics.items()
           if isinstance(ms, dict) and (ms.get("meshExchanges") or
                                        ms.get("meshBoundariesFused"))]
    assert ops, f"no mesh exchange ran: {sess.last_metrics}"


def test_mesh_groupby_string_key():
    assert_tpu_cpu_equal(
        lambda s: _people_df(s).group_by("name").agg(
            F.sum(F.col("age")), F.count(F.col("age")),
            F.avg(F.col("score"))),
        approx=True, confs=MESH_CONFS)
    sess = tpu_session(**MESH_CONFS)
    _people_df(sess).group_by("name").agg(F.sum(F.col("age"))).collect()
    _assert_mesh_used(sess)


def test_mesh_shuffled_join():
    def build(s):
        left = _people_df(s, n=300, parts=4)
        right = s.create_dataframe({
            "name": ["red", "green", "blue", None, "missing"],
            "bonus": [1, 2, 3, 4, 5],
        }, num_partitions=2)
        # big threshold=0 disables broadcast so the shuffled path runs
        return left.join(right, on="name", how="inner")

    assert_tpu_cpu_equal(
        build, confs={**MESH_CONFS,
                      "spark.sql.autoBroadcastJoinThreshold": 0})
    sess = tpu_session(**MESH_CONFS,
                       **{"spark.sql.autoBroadcastJoinThreshold": 0})
    build(sess).collect()
    _assert_mesh_used(sess)


def test_mesh_global_sort_ordering():
    # range partitioning over the mesh must preserve total order across
    # device partitions (partition d's keys < partition d+1's)
    assert_tpu_cpu_equal(
        lambda s: _people_df(s, n=400).sort(
            F.col("age").asc(), F.col("name").asc()),
        approx=True, ignore_order=False, confs=MESH_CONFS)


def test_mesh_repartition_roundrobin():
    assert_tpu_cpu_equal(
        lambda s: _people_df(s, n=200).repartition(6).select("age"),
        confs=MESH_CONFS, ignore_order=True)


def test_mesh_distinct():
    assert_tpu_cpu_equal(
        lambda s: _people_df(s, n=300).select("name").distinct(),
        confs=MESH_CONFS)


def test_mesh_strings_survive_roundtrip():
    # empty strings, NULLs and long strings through the padded-matrix
    # all-to-all layout
    sess = tpu_session(**MESH_CONFS)
    vals = ["", None, "x" * 100, "short", "ünïcødé-ÿ", "tail"] * 20
    df = sess.create_dataframe(
        {"s": vals, "v": list(range(len(vals)))}, num_partitions=4)
    out = df.group_by("s").agg(F.count(F.col("v")))
    rows = sorted(out.collect(), key=lambda r: (r[0] is None, str(r[0])))
    expect = {}
    for s in vals:
        expect[s] = expect.get(s, 0) + 1
    exp = sorted(expect.items(), key=lambda r: (r[0] is None, str(r[0])))
    assert [(a, b) for a, b in rows] == exp
    _assert_mesh_used(sess)


def test_multihost_single_process_noop():
    """World size 1 (every dev/test environment): init is a no-op and the
    process-group info reflects a single process."""
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.parallel.multihost import init_multihost, world_info
    assert init_multihost(RapidsConf()) is False
    info = world_info()
    assert info["process_count"] == 1 and info["process_index"] == 0
    assert info["global_devices"] == info["local_devices"]


def test_mesh_shuffle_payloads_stay_on_device(monkeypatch):
    """The device-resident contract (VERDICT r3 #1): between map-side eval
    and reduce-side consumption, NO payload-sized buffer is device_get —
    only scalar/metadata fetches and the final result materialization
    touch the host."""
    import spark_rapids_tpu.batch as B
    import spark_rapids_tpu.plan.pipeline as PL

    in_materialize = []
    offending = []
    real_get = jax.device_get
    real_d2h_many = B.device_to_host_many

    def patched_d2h_many(batches):
        in_materialize.append(True)
        try:
            return real_d2h_many(batches)
        finally:
            in_materialize.pop()

    def patched_get(x):
        if not in_materialize:
            for leaf in jax.tree_util.tree_leaves(x):
                size = getattr(leaf, "size", None)
                if size is not None and size > 256:
                    offending.append(getattr(leaf, "shape", size))
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", patched_get)
    monkeypatch.setattr(B, "device_to_host_many", patched_d2h_many)
    monkeypatch.setattr(PL, "device_to_host_many", patched_d2h_many)

    sess = tpu_session(**MESH_CONFS,
                       **{"spark.sql.autoBroadcastJoinThreshold": 0})
    left = _people_df(sess, n=600, parts=4)
    right = sess.create_dataframe({
        "name": ["red", "green", "blue", None, "missing"],
        "bonus": [1, 2, 3, 4, 5],
    }, num_partitions=2)
    out = left.join(right, on="name", how="inner") \
              .group_by("name").agg(F.sum(F.col("age")),
                                    F.count(F.col("bonus")))
    rows = out.collect()
    assert rows, "mesh query returned nothing"
    _assert_mesh_used(sess)
    assert not offending, \
        f"payload-sized device_get on the mesh path: {offending[:5]}"
