"""Serving runtime tests (ISSUE PR 11 acceptance list): shared
executable cache across sessions, N-thread concurrent bit-parity with
per-query metric attribution, weighted fair queueing, micro-batch
coalescing + maxDelayMs semantics, per-query deadlines failing fast,
and clean semaphore/catalog accounting after a concurrent storm."""

import time

import pytest

from compare import tpu_session
from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.serve import (
    DeadlineExceeded, QueryTemplate, ServeScheduler, shared_plan_cache,
)


def _df(s, n=200, seed=0):
    return s.create_dataframe({
        "k": [(seed + i) % 5 for i in range(n)],
        "v": [(seed + 3 * i) % 97 for i in range(n)],
    })


def _rows(batch):
    cols = batch.to_pydict()
    return sorted(zip(*[cols[name] for name in batch.schema.names]))


# -- shared executable cache -------------------------------------------------


def test_second_session_compiles_zero_and_identical():
    """The plan/executable cache is process-wide: a second session
    executing the same plan reports compileCount == 0 with bit-identical
    rows."""
    s1 = tpu_session()
    df = _df(s1).group_by("k").sum("v")
    out1, m1 = s1.execute_with_metrics(df.plan)

    s2 = tpu_session()
    out2, m2 = s2.execute_with_metrics(df.plan)
    assert m2["compileCount"] == 0, m2
    assert _rows(out2) == _rows(out1)
    # and the cache recorded the cross-session hit
    assert shared_plan_cache().stats()["plan_cache_hits"] >= 1


def test_plan_cache_keyed_by_conf_state():
    """A plan-relevant conf change must NOT reuse the cached physical
    plan (only metrics./obs. knobs are excluded from the key)."""
    s1 = tpu_session()
    df = _df(s1).filter("v > 10")
    s1.execute(df.plan)
    phys1 = s1.last_physical_plan
    s2 = tpu_session(**{"spark.rapids.sql.enabled": False})
    s2.execute(df.plan)
    assert s2.last_physical_plan is not phys1
    # metrics-detail toggles do reuse it
    s3 = tpu_session(**{"spark.rapids.sql.tpu.metrics.detailEnabled": True})
    s3.execute(df.plan)
    assert s3.last_physical_plan is phys1


# -- concurrent execution ----------------------------------------------------


def test_concurrent_parity_and_clean_accounting():
    """N threads x M distinct queries through one scheduler return the
    same rows as serial execution; afterwards nothing holds the device
    semaphore and the catalog accounting is clean."""
    s = tpu_session()
    dfs = [_df(s, n=150, seed=7 * i).group_by("k").sum("v")
           for i in range(6)]
    serial = [_rows(s.execute(df.plan)) for df in dfs]

    with ServeScheduler(s, max_concurrency=3) as sched:
        futs = [sched.submit(df) for df in dfs]
        got = [_rows(f.result(timeout=120)) for f in futs]
    assert got == serial

    if s.runtime is not None and s.runtime.semaphore is not None:
        assert s.runtime.semaphore.held_depth() == 0
    if s.runtime is not None:
        assert s.runtime.catalog.verify_accounting() == []


def test_concurrent_metric_attribution():
    """Each future's metrics dict describes ITS query: per-query
    dispatch counts under concurrency sum to what the same queries
    report serially, and every query saw at least one dispatch."""
    s = tpu_session()
    dfs = [_df(s, n=120, seed=11 * i).filter("v > 5") for i in range(4)]
    serial_total = 0
    for df in dfs:
        _out, m = s.execute_with_metrics(df.plan)
        serial_total += m["dispatchCount"]

    with ServeScheduler(s, max_concurrency=4) as sched:
        futs = [sched.submit(df) for df in dfs]
        for f in futs:
            f.result(timeout=120)
    per_query = [f.metrics["dispatchCount"] for f in futs]
    assert all(d >= 1 for d in per_query), per_query
    assert sum(per_query) == serial_total, (per_query, serial_total)


# -- weighted fair queueing --------------------------------------------------


def test_weighted_fairness_ratio():
    """With tenant a at weight 2 and b at weight 1 and the whole backlog
    queued before the (single) runner starts, a's queries complete ~2x
    as often in any completion-order prefix."""
    s = tpu_session(**{
        "spark.rapids.sql.tpu.serve.tenant.a.weight": "2",
        "spark.rapids.sql.tpu.serve.tenant.b.weight": "1",
    })
    df = _df(s).filter("v > 3")
    s.execute(df.plan)  # warm compile outside the scheduled phase
    sched = ServeScheduler(s, max_concurrency=1, autostart=False)
    done = []
    for i in range(18):
        tenant = "a" if i < 12 else "b"  # 12 a's, 6 b's, all pre-queued
        fut = sched.submit(df, tenant=tenant)
        done.append((tenant, fut))
    # record completion order via future resolution polling
    sched.start()
    for tenant, fut in done:
        fut.result(timeout=120)
    st = sched.stats()
    sched.close()
    assert st["tenants"]["a"]["completed"] == 12
    assert st["tenants"]["b"]["completed"] == 6
    # vtime law: while both queues are non-empty, a pops twice per b pop.
    # Verify via per-tenant latency: b's median wait is ~>= a's (a drains
    # faster under contention).
    assert st["tenants"]["a"]["p50_ms"] <= st["tenants"]["b"]["p50_ms"] * 2


def test_wfq_pop_order_two_to_one():
    """The scheduler's pop order itself honors the 2:1 weights (checked
    on the internal queues without running queries)."""
    s = tpu_session(**{
        "spark.rapids.sql.tpu.serve.tenant.a.weight": "2",
        "spark.rapids.sql.tpu.serve.tenant.b.weight": "1",
    })
    sched = ServeScheduler(s, max_concurrency=1, autostart=False)
    df = _df(s)
    for _ in range(8):
        sched.submit(df, tenant="a")
    for _ in range(8):
        sched.submit(df, tenant="b")
    pops = []
    with sched._lock:
        for _ in range(9):
            tenant, _item = sched._pop_locked()
            pops.append(tenant.name)
    # first 9 pops at weights 2:1 -> 6 a's, 3 b's
    assert pops.count("a") == 6, pops
    assert pops.count("b") == 3, pops
    sched.close()


# -- micro-query batching ----------------------------------------------------


def _mk_batch(lo, n=40):
    return HostBatch.from_pydict({
        "x": (T.LONG, [(lo + i) % 100 for i in range(n)]),
        "y": (T.DOUBLE, [float((lo + 2 * i) % 9) for i in range(n)]),
    })


def test_micro_batch_parity_and_coalescing():
    """Same-template queries queued together coalesce into fewer
    dispatches and every caller gets exactly its own rows (bit-parity
    with individual serial execution)."""
    s = tpu_session()
    tmpl = QueryTemplate("evens-t1", lambda d: d.filter("x % 2 = 0"))
    batches = [_mk_batch(13 * i) for i in range(8)]

    # serial reference: no coalescing
    ser = ServeScheduler(s, max_concurrency=1)
    ser._batch_enabled = False
    expected = [ser.submit_micro(tmpl, b).result(timeout=120).to_pydict()
                for b in batches]
    ser.close()

    sched = ServeScheduler(s, max_concurrency=1, autostart=False)
    futs = [sched.submit_micro(tmpl, b) for b in batches]
    sched.start()
    got = [f.result(timeout=120).to_pydict() for f in futs]
    st = sched.stats()
    sched.close()
    assert got == expected
    assert st["batched_queries"] >= 2, st
    assert st["micro_dispatches"] < len(batches), st


def test_micro_batch_respects_max_queries():
    """serve.batch.maxQueries caps how many queries one dispatch
    carries."""
    s = tpu_session(**{"spark.rapids.sql.tpu.serve.batch.maxQueries": 3})
    tmpl = QueryTemplate("evens-t2", lambda d: d.filter("x % 2 = 0"))
    batches = [_mk_batch(7 * i) for i in range(9)]
    sched = ServeScheduler(s, max_concurrency=1, autostart=False)
    futs = [sched.submit_micro(tmpl, b) for b in batches]
    sched.start()
    for f in futs:
        f.result(timeout=120)
    st = sched.stats()
    sched.close()
    assert st["micro_dispatches"] >= 3, st


def test_micro_batch_max_delay_window():
    """With batching eligible, a lone micro query lingers at most
    ~maxDelayMs for partners: a straggler submitted within the window
    rides the same dispatch."""
    s = tpu_session(**{
        "spark.rapids.sql.tpu.serve.batch.maxDelayMs": 300.0})
    tmpl = QueryTemplate("evens-t3", lambda d: d.filter("x % 2 = 0"))
    sched = ServeScheduler(s, max_concurrency=1)
    # warm the group binding so the timed window isn't compile-bound
    sched.submit_micro(tmpl, _mk_batch(0)).result(timeout=120)
    f1 = sched.submit_micro(tmpl, _mk_batch(5))
    time.sleep(0.05)  # inside the 300ms window
    f2 = sched.submit_micro(tmpl, _mk_batch(11))
    f1.result(timeout=120)
    f2.result(timeout=120)
    st = sched.stats()
    sched.close()
    # warm dispatch + ONE coalesced dispatch for the pair
    assert st["micro_dispatches"] == 2, st
    assert st["batched_queries"] == 2, st


def test_micro_batch_rejects_non_rowwise_templates():
    """A template containing an aggregation cannot be coalesced (rows
    from different callers would mix) and fails with a clear error."""
    s = tpu_session()
    tmpl = QueryTemplate("bad-agg", lambda d: d.group_by("x").sum("y"))
    sched = ServeScheduler(s, max_concurrency=1)
    fut = sched.submit_micro(tmpl, _mk_batch(0))
    with pytest.raises(ValueError, match="row-wise"):
        fut.result(timeout=120)
    sched.close()


# -- deadlines ---------------------------------------------------------------


def test_deadline_exceeded_fails_fast_neighbors_finish():
    """An already-expired deadline fails fast (never executes) with
    DeadlineExceeded while a neighboring query completes normally."""
    s = tpu_session()
    df = _df(s).group_by("k").sum("v")
    expected = _rows(s.execute(df.plan))
    sched = ServeScheduler(s, max_concurrency=1, autostart=False)
    doomed = sched.submit(df, tenant="a", deadline_sec=1e-9)
    ok = sched.submit(df, tenant="b")
    time.sleep(0.01)  # let the 1ns deadline lapse while queued
    sched.start()
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=120)
    assert _rows(ok.result(timeout=120)) == expected
    st = sched.stats()
    sched.close()
    assert st["deadline_exceeded"] == 1, st
    assert st["tenants"]["a"]["deadline_exceeded"] == 1
    assert st["tenants"]["b"]["completed"] == 1
    # fail-fast: the doomed query is NON_RETRYABLE, no recovery replay
    assert doomed.exception().__class__ is DeadlineExceeded


def test_generous_deadline_completes():
    s = tpu_session()
    df = _df(s).filter("v > 1")
    expected = _rows(s.execute(df.plan))
    with ServeScheduler(s, max_concurrency=2) as sched:
        fut = sched.submit(df, deadline_sec=60.0)
        assert _rows(fut.result(timeout=120)) == expected
    assert fut.metrics is not None


# -- storm: concurrency + batching + sessions -------------------------------


def test_mixed_storm_clean_after():
    """Micro + plain queries from 3 tenants on 3 runners: everything
    completes with correct rows, and the process is clean afterwards
    (no held semaphore permits, catalog accounting passes)."""
    s = tpu_session()
    tmpl = QueryTemplate("storm", lambda d: d.filter("x % 3 = 0"))
    df = _df(s, n=100).filter("v > 2")
    plain_expected = _rows(s.execute(df.plan))
    with ServeScheduler(s, max_concurrency=3) as sched:
        micro = [sched.submit_micro(tmpl, _mk_batch(3 * i),
                                    tenant=f"t{i % 3}") for i in range(9)]
        plain = [sched.submit(df, tenant=f"t{i % 3}") for i in range(6)]
        for f in micro:
            out = f.result(timeout=120)
            got = out.to_pydict()
            assert all(v % 3 == 0 for v in got["x"])
        for f in plain:
            assert _rows(f.result(timeout=120)) == plain_expected
        st = sched.stats()
    assert st["completed"] == 15, st
    assert st["failed"] == 0, st
    if s.runtime is not None and s.runtime.semaphore is not None:
        assert s.runtime.semaphore.held_depth() == 0
    if s.runtime is not None:
        assert s.runtime.catalog.verify_accounting() == []
